"""Unified causal-LM family with KV-cache inference support.

The TPU-native analogue of the reference's kernel-backed model implementations
(``model_implementations/transformers/ds_{gpt,bloom,opt,megatron_gpt}.py`` +
``module_inject/containers/``): instead of 12 per-architecture injection containers, ONE
configurable transformer covers the families — positional scheme (learned/rotary/alibi),
parallel residual, GQA, gated MLP, pre/post-LN — and per-family constructors pin the knobs.

Two execution paths:
- ``forward(params, ids)``: full-sequence logits (training/scoring, flash/xla attention);
- ``prefill(params, ids)`` / ``decode_step(params, cache, tok)``: KV-cache serving path.
  The cache is head-major ``(b, h_kv, T, d)`` feeding ``ops/attention/decode.py``'s fused
  kernel (reference hot loop ``softmax_context``, ``csrc/transformer/inference``).
"""

import dataclasses
from functools import partial
from typing import Any, Dict, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from ..ops.attention.decode import decode_attention, decode_attention_xla
from ..ops.transformer.attention import xla_attention
from ..parallel.overlap import (RowParallelDense, chunked_expert_exchange,
                                get_overlap_config, moe_overlap_chunks,
                                raw_or_param)
from .base import Model
from ..utils.jax_compat import shard_map


@dataclasses.dataclass
class CausalLMConfig:
    vocab_size: int = 50257
    max_seq_len: int = 2048
    n_embd: int = 768
    n_layer: int = 12
    n_head: int = 12
    n_kv_head: Optional[int] = None          # GQA; None → MHA
    d_ff: Optional[int] = None               # None → 4*n_embd
    pos_emb: str = "learned"                 # learned | rotary | alibi | none
    rotary_pct: float = 1.0                  # NeoX partial rotary
    rotary_base: float = 10000.0
    parallel_residual: bool = False          # NeoX/GPT-J
    gated_mlp: bool = False                  # LLaMA SwiGLU
    activation: str = "gelu"                 # gelu | relu | silu
    layernorm: str = "layernorm"             # layernorm | rmsnorm
    ln_eps: float = 1e-5
    embed_layernorm: bool = False            # BLOOM
    tie_word_embeddings: bool = True
    qkv_bias: bool = True
    mlp_bias: bool = True
    lm_head_bias: bool = False               # GPT-J ties nothing and biases the head
    dtype: Any = jnp.bfloat16
    init_std: float = 0.02
    name: str = "causal-lm"
    # MoE serving (reference ``ops/transformer/inference/moe_inference.py``): every
    # ``moe_layer_interval``-th layer's FFN is a gated expert mixture. 0 experts = dense.
    num_experts: int = 0
    moe_layer_interval: int = 2
    moe_top_k: int = 1
    # decode (t==1) routes via selected-expert weight GATHER instead of the all-expert
    # dispatch einsum — e× less FFN HBM traffic per step (reference builds dedicated MoE
    # inference ops for this hot loop, ``moe_inference.py:463``). False = always dispatch
    # (debug / parity testing).
    moe_decode_fastpath: bool = True
    # "pallas" = gather-fused kernel (weights stream HBM→MXU once);
    # "xla" = w[idx] gather + einsum (lets XLA pin small expert stacks in VMEM)
    moe_decode_impl: str = "pallas"

    VALID_MOE_DECODE_IMPLS = ("pallas", "xla")

    def __post_init__(self):
        # case-sensitive on purpose: 'XLA'/'Pallas'/'triton' must not silently
        # select the pallas path through a failed == "xla" comparison
        if self.moe_decode_impl not in self.VALID_MOE_DECODE_IMPLS:
            raise ValueError(
                f"moe_decode_impl={self.moe_decode_impl!r} is not one of "
                f"{self.VALID_MOE_DECODE_IMPLS}")

    def is_moe_layer(self, i: int) -> bool:
        return self.num_experts > 0 and (i + 1) % self.moe_layer_interval == 0

    @property
    def head_dim(self) -> int:
        return self.n_embd // self.n_head

    @property
    def kv_heads(self) -> int:
        return self.n_kv_head or self.n_head

    @property
    def ffn_dim(self) -> int:
        return self.d_ff or 4 * self.n_embd

    def num_params(self) -> int:
        d, L, v = self.n_embd, self.n_layer, self.vocab_size
        f = self.ffn_dim
        mlp = d * f * (3 if self.gated_mlp else 2)
        attn = d * d + 2 * d * self.kv_heads * self.head_dim + d * d
        n_moe = sum(1 for i in range(L) if self.is_moe_layer(i))
        moe_extra = n_moe * (self.num_experts - 1) * 2 * d * f  # experts replace the FFN
        return (v * d + L * (attn + mlp) + moe_extra +
                (0 if self.tie_word_embeddings else v * d))


# ---------------------------------------------------------------- family constructors
def gpt2_cfg(**kw) -> CausalLMConfig:
    return CausalLMConfig(pos_emb="learned", activation="gelu", name="gpt2", **kw)


def bloom_cfg(**kw) -> CausalLMConfig:
    """BLOOM (reference container ``module_inject/containers/bloom.py``): alibi positions,
    embedding layernorm, tied head."""
    kw.setdefault("pos_emb", "alibi")
    kw.setdefault("embed_layernorm", True)
    kw.setdefault("name", "bloom")
    return CausalLMConfig(**kw)


def opt_cfg(**kw) -> CausalLMConfig:
    kw.setdefault("pos_emb", "learned")
    kw.setdefault("activation", "relu")
    kw.setdefault("name", "opt")
    return CausalLMConfig(**kw)


def gptneox_cfg(**kw) -> CausalLMConfig:
    """GPT-NeoX (container ``gptneox.py``): rotary (partial), parallel residual."""
    kw.setdefault("pos_emb", "rotary")
    kw.setdefault("rotary_pct", 0.25)
    kw.setdefault("parallel_residual", True)
    kw.setdefault("tie_word_embeddings", False)
    kw.setdefault("name", "gpt-neox")
    return CausalLMConfig(**kw)


def gptj_cfg(**kw) -> CausalLMConfig:
    kw.setdefault("pos_emb", "rotary")
    kw.setdefault("rotary_pct", 0.25)
    kw.setdefault("parallel_residual", True)
    kw.setdefault("name", "gptj")
    return CausalLMConfig(**kw)


def llama_cfg(**kw) -> CausalLMConfig:
    kw.setdefault("pos_emb", "rotary")
    kw.setdefault("gated_mlp", True)
    kw.setdefault("activation", "silu")
    kw.setdefault("layernorm", "rmsnorm")
    kw.setdefault("qkv_bias", False)
    kw.setdefault("mlp_bias", False)
    kw.setdefault("tie_word_embeddings", False)
    kw.setdefault("ln_eps", 1e-6)
    kw.setdefault("name", "llama")
    return CausalLMConfig(**kw)


FAMILIES = {
    "gpt2": gpt2_cfg, "bloom": bloom_cfg, "opt": opt_cfg,
    "gpt_neox": gptneox_cfg, "gptj": gptj_cfg, "llama": llama_cfg,
}


# ----------------------------------------------------------------------- positional
def alibi_slopes(n_head: int) -> np.ndarray:
    """BLOOM alibi slope schedule (geometric in powers of 2)."""
    closest = 2 ** int(np.floor(np.log2(n_head)))
    base = 2.0 ** (-(2.0 ** -(np.log2(closest) - 3)))
    slopes = base ** np.arange(1, closest + 1)
    if closest < n_head:
        extra_base = 2.0 ** (-(2.0 ** -(np.log2(2 * closest) - 3)))
        extra = extra_base ** np.arange(1, 2 * (n_head - closest) + 1, 2)
        slopes = np.concatenate([slopes, extra])
    return slopes.astype(np.float32)


def rotate_half(x):
    x1, x2 = jnp.split(x, 2, axis=-1)
    return jnp.concatenate([-x2, x1], axis=-1)


def apply_rotary(x, positions, base: float, pct: float):
    """x: (b, t, h, d); positions: (b, t). Reference kernel:
    ``csrc/transformer/inference/csrc/apply_rotary_pos_emb.cu``."""
    d = x.shape[-1]
    rot = int(d * pct) // 2 * 2
    if rot == 0:
        return x
    x_rot, x_pass = x[..., :rot], x[..., rot:]
    inv_freq = 1.0 / (base ** (np.arange(0, rot, 2, dtype=np.float32) / rot))
    freqs = positions[..., None].astype(jnp.float32) * inv_freq[None, None]  # (b,t,rot/2)
    emb = jnp.concatenate([freqs, freqs], axis=-1)[:, :, None, :]            # (b,t,1,rot)
    cos, sin = jnp.cos(emb), jnp.sin(emb)
    x_rot = x_rot.astype(jnp.float32)
    out = x_rot * cos + rotate_half(x_rot) * sin
    return jnp.concatenate([out.astype(x.dtype), x_pass], axis=-1)


def _norm(cfg: CausalLMConfig, name: str):
    if cfg.layernorm == "rmsnorm":
        return nn.RMSNorm(epsilon=cfg.ln_eps, dtype=jnp.float32, name=name)
    return nn.LayerNorm(epsilon=cfg.ln_eps, dtype=jnp.float32, name=name)


def _act(cfg: CausalLMConfig):
    return {"gelu": partial(nn.gelu, approximate=True), "relu": nn.relu,
            "silu": nn.silu}[cfg.activation]


# ----------------------------------------------------------------------- modules
class QuantDense(nn.Module):
    """Drop-in for ``nn.Dense`` at column-parallel quantizable sites
    (qkv / fc_in / gate / up).

    Parameter tree (``kernel``/``bias``, fp32) is identical to ``nn.Dense`` —
    checkpoints and the training path don't change. At serve time the engine
    may swap ``kernel`` for a quant node; the projection then runs through the
    fused dequant-matmul kernel (``ops/quantizer/fused_matmul.py``) so
    int8/int4 bytes are what streams from HBM on the decode hot path."""
    features: int
    use_bias: bool = True
    dtype: Any = jnp.float32
    kernel_init: Any = nn.initializers.lecun_normal()
    bias_init: Any = nn.initializers.zeros
    site: str = "wq.dense"

    @nn.compact
    def __call__(self, x):
        kernel = raw_or_param(self, "kernel", self.kernel_init,
                               (x.shape[-1], self.features))
        bias = (self.param("bias", self.bias_init, (self.features,),
                           jnp.float32) if self.use_bias else None)
        from ..ops.quantizer import is_quant_node, quant_dense_apply
        if is_quant_node(kernel):
            return quant_dense_apply(x, kernel, bias, self.dtype,
                                     parallel="column", site=self.site)
        y = x.astype(self.dtype) @ kernel.astype(self.dtype)
        return y if bias is None else y + bias.astype(self.dtype)


class _ExpertWeights(nn.Module):
    """Param holder producing the same tree as the training ``moe.experts.Experts``
    module (``moe_experts/{w1,b1,w2,b2}``) so trained checkpoints map 1:1; the routing
    math lives in the caller where it can be vmapped over token chunks. ``w1``/``w2``
    may come back as quant nodes at serve time (see :func:`raw_or_param`)."""
    num_experts: int
    d_model: int
    d_ff: int
    init_std: float

    @nn.compact
    def __call__(self):
        e, d, f = self.num_experts, self.d_model, self.d_ff
        init = nn.initializers.normal(self.init_std)
        return (raw_or_param(self, "w1", init, (e, d, f)),
                self.param("b1", nn.initializers.zeros, (e, f), jnp.float32),
                raw_or_param(self, "w2", init, (e, f, d)),
                self.param("b2", nn.initializers.zeros, (e, d), jnp.float32))


class CausalLMLayer(nn.Module):
    config: CausalLMConfig
    is_moe: bool = False

    def _attn_proj(self, x):
        cfg = self.config
        hd, hk = cfg.head_dim, cfg.kv_heads
        q = QuantDense(cfg.n_head * hd, use_bias=cfg.qkv_bias, dtype=cfg.dtype,
                       kernel_init=nn.initializers.normal(cfg.init_std),
                       site="wq.q_proj", name="q_proj")(x)
        k = QuantDense(hk * hd, use_bias=cfg.qkv_bias, dtype=cfg.dtype,
                       kernel_init=nn.initializers.normal(cfg.init_std),
                       site="wq.k_proj", name="k_proj")(x)
        v = QuantDense(hk * hd, use_bias=cfg.qkv_bias, dtype=cfg.dtype,
                       kernel_init=nn.initializers.normal(cfg.init_std),
                       site="wq.v_proj", name="v_proj")(x)
        b, t = x.shape[:2]
        return (q.reshape(b, t, cfg.n_head, hd), k.reshape(b, t, hk, hd),
                v.reshape(b, t, hk, hd))

    def _mlp(self, h):
        cfg = self.config
        act = _act(cfg)
        init = nn.initializers.normal(cfg.init_std)
        proj_init = nn.initializers.normal(cfg.init_std / (2 * cfg.n_layer) ** 0.5)
        if cfg.gated_mlp:
            gate = QuantDense(cfg.ffn_dim, use_bias=cfg.mlp_bias, dtype=cfg.dtype,
                              kernel_init=init, site="wq.gate_proj",
                              name="gate_proj")(h)
            up = QuantDense(cfg.ffn_dim, use_bias=cfg.mlp_bias, dtype=cfg.dtype,
                            kernel_init=init, site="wq.up_proj",
                            name="up_proj")(h)
            h = act(gate) * up
        else:
            h = QuantDense(cfg.ffn_dim, use_bias=cfg.mlp_bias, dtype=cfg.dtype,
                           kernel_init=init, site="wq.fc_in", name="fc_in")(h)
            h = act(h)
        # row-parallel TP site: lowers to the chunked matmul-reduce-scatter
        # ring when comm_overlap is active (plain matmul + GSPMD allreduce
        # otherwise); parameter tree identical to nn.Dense
        return RowParallelDense(cfg.n_embd, use_bias=cfg.mlp_bias,
                                dtype=cfg.dtype, kernel_init=proj_init,
                                span="tp.fc_out", name="fc_out")(h)

    # prefill tokens are routed in chunks of this size: the one-hot dispatch/combine
    # tensors are (C, e, C) per chunk — linear total memory/flops in token count instead
    # of the quadratic (s, e, s) a whole-sequence no-drop dispatch would build
    MOE_CHUNK = 256

    def _moe_mlp(self, h):
        """Gated expert-mixture FFN for serving (reference ``moe_inference.py``: gating +
        einsum dispatch in the decode path). Eval-mode gating: deterministic, no token drop
        (chunked dispatch with capacity = chunk size — routing is per-token, so chunking
        does not change results; the reference's inference MoE has no capacity dropping
        either), experts sharded over the ``expert`` axis."""
        from ..moe.sharded_moe import TopKGate
        from ..parallel.mesh import AXIS_EXPERT, get_global_mesh
        cfg = self.config
        b, t, d = h.shape
        s = b * t
        x = h.reshape(s, d)
        wg = self.param("moe_gate", nn.initializers.normal(cfg.init_std),
                        (d, cfg.num_experts), jnp.float32)
        gate = TopKGate(k=cfg.moe_top_k, drop_tokens=False, use_rts=False,
                        top2_2nd_expert_sampling=False)
        # bind expert weights ONCE at this scope (params: moe_experts/{w1,b1,w2,b2}, same
        # tree as the training Experts module), then route with pure math — safe to vmap
        w1, b1, w2, b2 = _ExpertWeights(cfg.num_experts, d, cfg.ffn_dim, cfg.init_std,
                                        name="moe_experts")()
        act = _act(cfg)
        cdtype = cfg.dtype
        mesh = get_global_mesh()
        expert_sharded = mesh is not None and mesh.size(AXIS_EXPERT) > 1
        from ..ops.quantizer import dequantize_node, is_quant_node
        quant_experts = is_quant_node(w1) or is_quant_node(w2)
        if (quant_experts and t == 1 and cfg.moe_decode_fastpath
                and not expert_sharded and cfg.num_experts > cfg.moe_top_k):
            # quantized decode fast path: gather the SELECTED experts'
            # int8/int4 bytes from HBM (2-4x less weight traffic than a bf16
            # gather), dequantize only the gathered slices. Same dispatch-time
            # impl re-validation as the fp fastpath below; both impl spellings
            # route here (the quant gather IS the xla-style gather, and the
            # pallas kernel's BlockSpec streaming doesn't apply to packed
            # payloads yet)
            if cfg.moe_decode_impl not in CausalLMConfig.VALID_MOE_DECODE_IMPLS:
                raise ValueError(
                    f"moe_decode_impl={cfg.moe_decode_impl!r} is not one of "
                    f"{CausalLMConfig.VALID_MOE_DECODE_IMPLS}")
            from ..moe.sharded_moe import topk_select
            from ..ops.moe import moe_decode_ffn_quant
            k = cfg.moe_top_k
            logits = x.astype(jnp.float32) @ wg.astype(jnp.float32)
            idx, gw = topk_select(logits, k)
            xk = x.astype(cdtype)
            if k > 1:
                xk = jnp.repeat(xk, k, axis=0)
            y = moe_decode_ffn_quant(xk, idx.reshape(-1), w1, b1, w2, b2, act)
            out = jnp.einsum("bk,bkm->bm", gw, y.reshape(b, k, d))
            return out.reshape(b, t, d).astype(h.dtype)
        if quant_experts:
            # dispatch path (prefill / expert-sharded / fastpath off): every
            # expert's FFN runs, so collapse the nodes here — XLA fuses the
            # dequant into the consuming einsum's operand read. On the XLA
            # fallback backend the engine hoists this out of compiled decode
            # bodies (decode_fns); on the FUSED backend the nodes reach this
            # point inside the loop body and the step streams bf16-equivalent
            # expert bytes (XLA LICM makes the dequant a loop constant at
            # best) — t==1 here means that regression is live, so say so
            if t == 1:
                from ..ops.quantizer import fused_backend_active
                if fused_backend_active():
                    from ..utils.logging import log_dist
                    log_dist(
                        "weight_quant[moe_experts]: quantized experts on the "
                        "decode DISPATCH path (expert-sharded or fastpath "
                        "off) — dequantized in the loop body, no weight-"
                        "stream win; consider weight_quant.exclude for "
                        "expert FFNs in this topology", ranks=[0])
            w1 = dequantize_node(w1) if is_quant_node(w1) else w1
            w2 = dequantize_node(w2) if is_quant_node(w2) else w2
        if (t == 1 and cfg.moe_decode_fastpath and not expert_sharded
                and cfg.num_experts > cfg.moe_top_k):
            # decode fast path: a (b, 1, d) step touches at most b*k experts; the
            # gather-fused kernel streams just those experts' weights instead of
            # running every expert's FFN on a mostly-zero dispatch tensor. Routing
            # semantics shared with the dispatch path via topk_select (parity pinned
            # in tests/unit/moe/test_moe_decode.py).
            from ..moe.sharded_moe import topk_select
            from ..ops.moe import moe_decode_ffn, moe_decode_ffn_xla
            k = cfg.moe_top_k
            logits = x.astype(jnp.float32) @ wg.astype(jnp.float32)       # (b, e)
            idx, gw = topk_select(logits, k)                              # (b, k) ×2
            xk = x.astype(cdtype)
            if k > 1:
                xk = jnp.repeat(xk, k, axis=0)                            # (b*k, d)
            # dispatch-time re-validation: configs mutated after construction
            # (engine plumbing) must not silently fall through to pallas
            if cfg.moe_decode_impl not in CausalLMConfig.VALID_MOE_DECODE_IMPLS:
                raise ValueError(
                    f"moe_decode_impl={cfg.moe_decode_impl!r} is not one of "
                    f"{CausalLMConfig.VALID_MOE_DECODE_IMPLS}")
            ffn = (moe_decode_ffn_xla if cfg.moe_decode_impl == "xla"
                   else moe_decode_ffn)
            y = ffn(xk, idx.reshape(-1),
                    w1.astype(cdtype), b1.astype(cdtype),
                    w2.astype(cdtype), b2.astype(cdtype), act)
            out = jnp.einsum("bk,bkm->bm", gw, y.reshape(b, k, d))
            return out.reshape(b, t, d).astype(h.dtype)

        def expert_fn(expert_in):                       # (e, c, m) → (e, c, m)
            hh = jnp.einsum("ecm,emf->ecf", expert_in, w1.astype(cdtype)) + \
                b1[:, None, :].astype(cdtype)
            hh = act(hh)
            return jnp.einsum("ecf,efm->ecm", hh, w2.astype(cdtype)) + \
                b2[:, None, :].astype(cdtype)

        def gating(tokens):                             # pure math, safe under vmap
            _, combine, dispatch, _ = gate(wg, tokens, train=False, rng=None)
            return combine, dispatch

        e = cfg.num_experts
        chunk = min(s, self.MOE_CHUNK)
        pad = (-s) % chunk
        xc = jnp.pad(x, ((0, pad), (0, 0))).reshape(-1, chunk, d)     # (n, C, m)
        n = xc.shape[0]
        combine, dispatch = jax.vmap(gating)(xc)                      # (n, C, e, cap)
        cap = combine.shape[-1]          # == chunk for top-1, 2*chunk for top-2 (no-drop)
        expert_in = jnp.einsum("nsec,nsm->encm", dispatch.astype(jnp.float32),
                               xc.astype(jnp.float32)).astype(cdtype)
        expert_in = expert_in.reshape(e, n * cap, d)
        if expert_sharded:
            # capacity-chunked exchange when comm_overlap is active: each
            # chunk's token-major → expert-major a2a overlaps the previous
            # chunk's expert FFN (bitwise-exact — per-token FFN, whole combine)
            n_chunks = moe_overlap_chunks(get_overlap_config(),
                                          mesh.size(AXIS_EXPERT), n * cap)
            expert_out = chunked_expert_exchange(
                expert_in, expert_fn,
                mesh.sharding(P(AXIS_EXPERT, None, None)), n_chunks,
                site="moe.decode_a2a")
        else:
            expert_out = expert_fn(expert_in)                         # (e, n*cap, m)
        expert_out = expert_out.reshape(e, n, cap, d)
        out = jnp.einsum("nsec,encm->nsm", combine.astype(jnp.float32),
                         expert_out.astype(jnp.float32))
        out = out.reshape(-1, d)[:s]
        return out.reshape(b, t, d).astype(h.dtype)

    @nn.compact
    def __call__(self, x, positions, cache: Optional[Dict] = None,
                 cache_len: Optional[jnp.ndarray] = None,
                 prefix_fill: bool = False, page_table=None,
                 kv_cap: Optional[int] = None):
        """x: (b, t, d). With ``cache`` given (decode): t==1, attention against the cache.
        With ``prefix_fill`` (static): suffix prefill at a nonzero cache offset —
        ``cache`` already holds a restored prompt-prefix KV slab in rows
        ``[0, cache_len)``, the t suffix tokens write their K/V at rows
        ``cache_len + i`` and attend over prefix + suffix (the prefix-cache hit
        path: the prefix's prefill compute is skipped entirely).

        With ``page_table`` (decode only): ``cache`` holds GLOBAL KV pages
        ``{"k": (P, hk, page, d), ...}`` and the ``(b, max_pages)`` table maps
        each row's positions to physical pages — the step appends K/V at the
        page-mapped row and attends through the paged-attention op (XLA dense
        gather sliced to ``kv_cap`` rows = bit-identical to the slot-row
        cache; Pallas gather-by-page-index kernel on TPU).
        Returns (y, new_cache_kv or None)."""
        cfg = self.config
        b, t, _ = x.shape
        h_in = _norm(cfg, "ln_attn")(x).astype(cfg.dtype)
        q, k, v = self._attn_proj(h_in)
        if cfg.pos_emb == "rotary":
            q = apply_rotary(q, positions, cfg.rotary_base, cfg.rotary_pct)
            k = apply_rotary(k, positions, cfg.rotary_base, cfg.rotary_pct)

        slopes = (jnp.asarray(alibi_slopes(cfg.n_head))
                  if cfg.pos_emb == "alibi" else None)

        new_kv = None
        if cache is not None and t == 1 and page_table is not None:
            # paged decode: append at the page-mapped row, attend by page index
            from ..ops.paged_attention import (gather_kv_dense,
                                               paged_attention,
                                               paged_cache_update)
            cap = int(kv_cap if kv_cap is not None
                      else page_table.shape[1] * cache["k"].shape[2])
            k_hm = k.transpose(0, 2, 1, 3)   # (b, hk, 1, d)
            v_hm = v.transpose(0, 2, 1, 3)
            k_pages, v_pages = paged_cache_update(
                cache["k"], cache["v"], k_hm, v_hm, page_table, cache_len)
            new_kv = {"k": k_pages, "v": v_pages}
            lens1 = cache_len + 1
            if slopes is not None:
                kd, vd = gather_kv_dense(k_pages, v_pages, page_table, cap)
                o = decode_attention_xla_alibi(q[:, 0], kd, vd, lens1,
                                               slopes)[:, None]
            else:
                o = paged_attention(q[:, 0], k_pages, v_pages, page_table,
                                    lens1, cap)[:, None]
        elif cache is not None and t == 1:
            # decode: append to cache (head-major), fused decode kernel
            k_hm = k.transpose(0, 2, 1, 3)   # (b, hk, 1, d)
            v_hm = v.transpose(0, 2, 1, 3)
            k_cache = _cache_update(cache["k"], k_hm, cache_len)
            v_cache = _cache_update(cache["v"], v_hm, cache_len)
            new_kv = {"k": k_cache, "v": v_cache}
            o = _sharded_decode(q[:, 0], k_cache, v_cache, cache_len + 1,
                                alibi=slopes)[:, None]
        elif cache is not None and prefix_fill:
            # suffix prefill at offset cache_len: scatter suffix K/V into rows
            # [cache_len, cache_len + t) (OOB pad rows drop), attend each suffix
            # query over every cache row at position <= its own
            k_hm = k.transpose(0, 2, 1, 3)   # (b, hk, t, d)
            v_hm = v.transpose(0, 2, 1, 3)
            idx = cache_len[:, None] + jnp.arange(t)[None]        # (b, t)

            def put(c, n, i):
                return c.at[:, i, :].set(n.astype(c.dtype))

            k_cache = jax.vmap(put)(cache["k"], k_hm, idx)
            v_cache = jax.vmap(put)(cache["v"], v_hm, idx)
            new_kv = {"k": k_cache, "v": v_cache}
            o = _prefix_attention_xla(q, k_cache, v_cache, cache_len, slopes)
        else:
            o = _bias_attention(q, k, v, slopes)
            if cache is not None:
                # prefill: write the prompt's K/V (post-rotary) into the fixed cache
                T = cache["k"].shape[2]
                k_hm = k.transpose(0, 2, 1, 3)
                v_hm = v.transpose(0, 2, 1, 3)
                pad = ((0, 0), (0, 0), (0, T - t), (0, 0))
                new_kv = {"k": jnp.pad(k_hm, pad).astype(cache["k"].dtype),
                          "v": jnp.pad(v_hm, pad).astype(cache["v"].dtype)}
        o = o.reshape(b, t, cfg.n_embd)
        proj_init = nn.initializers.normal(cfg.init_std / (2 * cfg.n_layer) ** 0.5)
        attn_out = RowParallelDense(cfg.n_embd, use_bias=cfg.mlp_bias,
                                    dtype=cfg.dtype, kernel_init=proj_init,
                                    span="tp.o_proj", name="o_proj")(o)

        mlp = self._moe_mlp if self.is_moe else self._mlp
        if cfg.parallel_residual:
            h_mlp = _norm(cfg, "ln_mlp")(x).astype(cfg.dtype)
            y = x + attn_out + mlp(h_mlp)
        else:
            x = x + attn_out
            h_mlp = _norm(cfg, "ln_mlp")(x).astype(cfg.dtype)
            y = x + mlp(h_mlp)
        return y, new_kv


def _bias_attention(q, k, v, slopes):
    """Full-sequence causal attention, optionally with per-head alibi slopes.

    The alibi bias rides INSIDE the Pallas flash kernel (no (h, t, s) bias tensor in
    HBM — the reference fuses the same bias into ``softmax_kernels.cu``); tiny or
    non-128-aligned lengths take the XLA einsum path where block padding would
    dominate the kernel."""
    from ..ops.attention.flash import flash_attention
    from ..ops.transformer.attention import flash_eligible
    if k.shape[2] != q.shape[2]:  # GQA prefill: broadcast kv heads to query heads
        g = q.shape[2] // k.shape[2]
        k = jnp.repeat(k, g, axis=2)
        v = jnp.repeat(v, g, axis=2)
    if slopes is None:
        return flash_attention(q, k, v, causal=True)
    if flash_eligible(q.shape[1]):
        return flash_attention(q, k, v, causal=True, alibi_slopes=slopes)
    return _alibi_attention_xla(q, k, v, slopes)


def _alibi_attention_xla(q, k, v, slopes):
    """XLA reference path for alibi attention (short/unaligned sequences; also the
    numerical reference the flash-alibi kernel is tested against)."""
    d = q.shape[-1]
    scale = 1.0 / float(np.sqrt(d))
    t, s = q.shape[1], k.shape[1]
    rows = jnp.arange(t)[:, None]
    cols = jnp.arange(s)[None, :]
    bias = slopes[:, None, None] * (cols - rows)[None].astype(jnp.float32)
    logits = jnp.einsum("bthd,bshd->bhts", q, k).astype(jnp.float32) * scale
    logits = logits + bias[None]
    causal = jnp.tril(jnp.ones((t, s), dtype=bool))
    logits = jnp.where(causal[None, None], logits, -1e30)
    probs = jax.nn.softmax(logits, axis=-1).astype(v.dtype)
    return jnp.einsum("bhts,bshd->bthd", probs, v)


def _prefix_attention_xla(q, k_cache, v_cache, offset, slopes=None):
    """Suffix-prefill attention: queries at global positions ``offset + i``
    over the full KV cache (restored prefix rows + just-written suffix rows),
    masked ``key_pos <= query_pos`` — the t×T generalisation of
    ``decode_attention_xla_alibi``'s 1×T shape. fp32 softmax like every other
    XLA attention path here; rows beyond ``offset + t - 1`` (stale slab pad /
    unwritten) are masked out by construction.

    q: (b, t, h, d); k_cache/v_cache: (b, hk, T, d); offset: (b,)."""
    b, t, h, d = q.shape
    hk, T = k_cache.shape[1], k_cache.shape[2]
    g = h // hk
    scale = 1.0 / float(np.sqrt(d))
    q5 = q.reshape(b, t, hk, g, d).astype(jnp.float32)
    s = jnp.einsum("btkgd,bkTd->bkgtT", q5,
                   k_cache.astype(jnp.float32)) * scale
    q_pos = offset[:, None] + jnp.arange(t)[None]                  # (b, t)
    k_pos = jnp.arange(T)
    if slopes is not None:
        rel = (k_pos[None, None, :] - q_pos[:, :, None]).astype(jnp.float32)
        s = s + slopes.reshape(1, hk, g, 1, 1) * rel[:, None, None]
    mask = k_pos[None, None, :] <= q_pos[:, :, None]               # (b, t, T)
    s = jnp.where(mask[:, None, None], s, -1e30)
    p = jax.nn.softmax(s, axis=-1)
    o = jnp.einsum("bkgtT,bkTd->btkgd", p, v_cache.astype(jnp.float32))
    return o.reshape(b, t, h, d).astype(q.dtype)


def _cache_update(cache, new, cache_len):
    """cache: (b, hk, T, d); new: (b, hk, 1, d); write at per-sequence position."""
    def one(c, n, p):
        return jax.lax.dynamic_update_slice(c, n.astype(c.dtype), (0, p, 0))
    return jax.vmap(one)(cache, new, cache_len)


def _sharded_decode(q, k_cache, v_cache, lens, alibi=None):
    """Wrap the decode kernel in shard_map over batch/TP axes (pallas is opaque to SPMD).

    Alibi slopes travel as a per-head input sharded over the tensor axis, so each TP shard
    sees exactly its heads' slopes."""
    from ..parallel.mesh import AXIS_TENSOR, BATCH_AXES, get_global_mesh
    b, h, d = q.shape
    mesh = get_global_mesh()

    if mesh is not None:
        batch_axes = tuple(ax for ax in BATCH_AXES if mesh.size(ax) > 1)
        bsz = int(np.prod([mesh.size(ax) for ax in batch_axes])) if batch_axes else 1
        tp = mesh.size(AXIS_TENSOR)
        use_tp = tp > 1 and h % tp == 0 and k_cache.shape[1] % tp == 0
        manual = set(batch_axes) | ({AXIS_TENSOR} if use_tp else set())
        if manual and b % max(bsz, 1) == 0:
            tpax = AXIS_TENSOR if use_tp else None
            qspec = P(batch_axes or None, tpax, None)
            cspec = P(batch_axes or None, tpax, None, None)
            lspec = P(batch_axes or None)
            if alibi is None:
                mapped = shard_map(
                    lambda q_l, k_l, v_l, l_l: decode_attention(q_l, k_l, v_l, l_l),
                    mesh=mesh.mesh, axis_names=manual,
                    in_specs=(qspec, cspec, cspec, lspec), out_specs=qspec,
                    check_vma=False)
                return mapped(q, k_cache, v_cache, lens)
            mapped = shard_map(
                decode_attention_xla_alibi, mesh=mesh.mesh, axis_names=manual,
                in_specs=(qspec, cspec, cspec, lspec, P(tpax)), out_specs=qspec,
                check_vma=False)
            return mapped(q, k_cache, v_cache, lens, jnp.asarray(alibi))

    if alibi is not None:
        return decode_attention_xla_alibi(q, k_cache, v_cache, lens, jnp.asarray(alibi))
    return decode_attention(q, k_cache, v_cache, lens)


def decode_attention_xla_alibi(q, k_cache, v_cache, cache_len, slopes):
    """Decode attention with alibi bias (jnp path; bloom decode)."""
    b, h, d = q.shape
    hk, T = k_cache.shape[1], k_cache.shape[2]
    g = h // hk
    scale = 1.0 / float(np.sqrt(d))
    q4 = q.reshape(b, hk, g, d).astype(jnp.float32)
    s = jnp.einsum("bkgd,bktd->bkgt", q4, k_cache.astype(jnp.float32)) * scale
    pos = jnp.arange(T)[None, None, None, :]
    cur = (cache_len[:, None, None, None] - 1).astype(jnp.float32)
    s = s + slopes.reshape(1, hk, g, 1) * (pos - cur)
    mask = pos < cache_len[:, None, None, None]
    s = jnp.where(mask, s, -1e30)
    p = jax.nn.softmax(s, axis=-1)
    o = jnp.einsum("bkgt,bktd->bkgd", p, v_cache.astype(jnp.float32))
    return o.reshape(b, h, d).astype(q.dtype)


class CausalLM(nn.Module):
    config: CausalLMConfig

    @nn.compact
    def __call__(self, input_ids, positions=None, caches=None, cache_lens=None,
                 logits_positions=None, prefix_fill=False, page_table=None,
                 kv_cap=None):
        """``logits_positions`` (b,): compute the LM head ONLY at these sequence
        positions (serving prefill needs just each prompt's last valid token — for a
        250k vocab at t=512 this removes ~99.8% of the head matmul and the (b, t, V)
        fp32 logits buffer; reference parity: ds_inference reads final-token logits).
        Returns (b, 1, V) logits in that mode.

        ``prefix_fill`` (static): suffix prefill at cache offset ``cache_lens``
        — the caches already hold a restored prompt-prefix KV slab; the caller
        must pass ``positions = cache_lens + arange(t)`` so rotary/learned
        embeddings see global positions."""
        cfg = self.config
        b, t = input_ids.shape
        if positions is None:
            positions = jnp.broadcast_to(jnp.arange(t)[None], (b, t))
        wte = self.param("wte", nn.initializers.normal(cfg.init_std),
                         (cfg.vocab_size, cfg.n_embd), jnp.float32)
        x = wte[input_ids].astype(cfg.dtype)
        if cfg.pos_emb == "learned":
            wpe = self.param("wpe", nn.initializers.normal(cfg.init_std),
                             (cfg.max_seq_len, cfg.n_embd), jnp.float32)
            x = x + jnp.take(wpe, positions, axis=0).astype(cfg.dtype)
        if cfg.embed_layernorm:
            x = _norm(cfg, "ln_embed")(x).astype(cfg.dtype)

        new_caches = []
        for i in range(cfg.n_layer):
            layer_cache = None if caches is None else caches[i]
            x, new_kv = CausalLMLayer(cfg, is_moe=cfg.is_moe_layer(i),
                                      name=f"layers_{i}")(
                x, positions, cache=layer_cache, cache_len=cache_lens,
                prefix_fill=prefix_fill, page_table=page_table,
                kv_cap=kv_cap)
            new_caches.append(new_kv)

        x = _norm(cfg, "ln_f")(x)
        if logits_positions is not None:
            x = x[jnp.arange(b), logits_positions][:, None]    # (b, 1, d)
        if cfg.tie_word_embeddings:
            logits = x.astype(jnp.float32) @ wte.T
        else:
            logits = nn.Dense(cfg.vocab_size, use_bias=cfg.lm_head_bias,
                              dtype=jnp.float32,
                              kernel_init=nn.initializers.normal(cfg.init_std),
                              name="lm_head")(x.astype(jnp.float32))
        if caches is None:
            return logits
        return logits, new_caches


# ----------------------------------------------------------- segmented (offload_param)
def _norm_mod(cfg: CausalLMConfig):
    """Top-level (unnamed) norm module for standalone segment apply."""
    if cfg.layernorm == "rmsnorm":
        return nn.RMSNorm(epsilon=cfg.ln_eps, dtype=jnp.float32)
    return nn.LayerNorm(epsilon=cfg.ln_eps, dtype=jnp.float32)


def causal_lm_segments(cfg: CausalLMConfig, layers_per_group: int = 2):
    """Decompose :class:`CausalLM` into host-streamable :class:`~.base.Segment` slices.

    The segment parameter trees use the SAME top-level keys as the monolithic
    ``CausalLM.init`` tree (``wte``/``wpe``/``ln_embed``/``layers_i``/``ln_f``/``lm_head``)
    so checkpoints interchange between the streamed and the resident engines. Tied
    embeddings put ``wte`` in the last segment's ``param_keys`` (shared, not re-initialised);
    its gradient accumulates contributions from both ends, exactly like the monolithic
    backward.

    Reference: sub_group partitioning of ZeRO-3 params
    (``runtime/zero/stage3.py`` ``sub_group_size``,
    ``partitioned_param_coordinator.py:239`` fetch order).
    """
    from .base import Segment
    from .gpt2 import cross_entropy_loss
    segs = []

    def _positions(ids):
        b, t = ids.shape
        return jnp.broadcast_to(jnp.arange(t)[None], (b, t))

    # ---- embed -----------------------------------------------------------------
    embed_keys = ["wte"]
    if cfg.pos_emb == "learned":
        embed_keys.append("wpe")
    if cfg.embed_layernorm:
        embed_keys.append("ln_embed")

    def embed_init(rng):
        init = nn.initializers.normal(cfg.init_std)
        p = {"wte": init(jax.random.fold_in(rng, 0),
                         (cfg.vocab_size, cfg.n_embd), jnp.float32)}
        if cfg.pos_emb == "learned":
            p["wpe"] = init(jax.random.fold_in(rng, 1),
                            (cfg.max_seq_len, cfg.n_embd), jnp.float32)
        if cfg.embed_layernorm:
            p["ln_embed"] = _norm_mod(cfg).init(
                jax.random.fold_in(rng, 2),
                jnp.zeros((1, 1, cfg.n_embd), jnp.float32))["params"]
        return tuple(p[k] for k in embed_keys)

    def embed_apply(p, batch, rng):
        p = dict(zip(embed_keys, p))
        ids = batch["input_ids"]
        x = p["wte"][ids].astype(cfg.dtype)
        if cfg.pos_emb == "learned":
            x = x + jnp.take(p["wpe"], _positions(ids), axis=0).astype(cfg.dtype)
        if cfg.embed_layernorm:
            x = _norm_mod(cfg).apply({"params": p["ln_embed"]}, x).astype(cfg.dtype)
        return x

    segs.append(Segment(name="embed", kind="first",
                        param_keys=tuple(embed_keys), init_keys=tuple(embed_keys),
                        init_fn=embed_init, apply_fn=embed_apply))

    # ---- layer groups ----------------------------------------------------------
    # One shared apply/init FUNCTION OBJECT per (is_moe flags) signature: segments with
    # the same layer composition then present jax.jit with the same callable AND the
    # same arg structure, so a 48-layer model compiles its interior group once, not 24×.
    _group_fns = {}

    def _fns_for(flags):
        if flags not in _group_fns:
            def group_init(rng, flags=flags):
                x = jnp.zeros((1, 4, cfg.n_embd), cfg.dtype)
                pos = jnp.zeros((1, 4), jnp.int32)
                return tuple(
                    CausalLMLayer(cfg, is_moe=moe).init(
                        {"params": jax.random.fold_in(rng, j)}, x, pos)["params"]
                    for j, moe in enumerate(flags))

            def group_apply(p, x, batch, rng, flags=flags):
                pos = _positions(batch["input_ids"])
                for moe, layer_params in zip(flags, p):
                    layer = CausalLMLayer(cfg, is_moe=moe)
                    x, _ = layer.apply({"params": layer_params}, x, pos)
                return x

            _group_fns[flags] = (group_init, group_apply)
        return _group_fns[flags]

    for lo in range(0, cfg.n_layer, layers_per_group):
        hi = min(lo + layers_per_group, cfg.n_layer)
        keys = tuple(f"layers_{i}" for i in range(lo, hi))
        flags = tuple(cfg.is_moe_layer(i) for i in range(lo, hi))
        group_init, group_apply = _fns_for(flags)
        segs.append(Segment(name=f"layers[{lo}:{hi}]", kind="mid", param_keys=keys,
                            init_keys=keys, init_fn=group_init,
                            apply_fn=group_apply))

    # ---- final norm + head + loss ----------------------------------------------
    final_init_keys = ["ln_f"] if cfg.tie_word_embeddings else ["ln_f", "lm_head"]
    final_param_keys = ["ln_f", "wte"] if cfg.tie_word_embeddings \
        else ["ln_f", "lm_head"]

    def final_init(rng):
        p = {"ln_f": _norm_mod(cfg).init(
            jax.random.fold_in(rng, 0),
            jnp.zeros((1, 1, cfg.n_embd), jnp.float32))["params"]}
        if not cfg.tie_word_embeddings:
            head = {"kernel": nn.initializers.normal(cfg.init_std)(
                jax.random.fold_in(rng, 1),
                (cfg.n_embd, cfg.vocab_size), jnp.float32)}
            if cfg.lm_head_bias:
                head["bias"] = jnp.zeros((cfg.vocab_size,), jnp.float32)
            p["lm_head"] = head
        return tuple(p[k] for k in final_init_keys)

    def final_apply(p, x, batch, rng):
        p = dict(zip(final_param_keys, p))
        x = _norm_mod(cfg).apply({"params": p["ln_f"]}, x)
        if cfg.tie_word_embeddings:
            logits = x.astype(jnp.float32) @ p["wte"].T
        else:
            logits = x.astype(jnp.float32) @ p["lm_head"]["kernel"]
            if cfg.lm_head_bias:
                logits = logits + p["lm_head"]["bias"]
        ids = batch["input_ids"]
        labels = batch.get("labels")
        if labels is None:
            labels = jnp.concatenate(
                [ids[:, 1:], jnp.full((ids.shape[0], 1), -100, dtype=ids.dtype)], axis=1)
        return cross_entropy_loss(logits, labels)

    segs.append(Segment(name="final", kind="last",
                        param_keys=tuple(final_param_keys),
                        init_keys=tuple(final_init_keys),
                        init_fn=final_init, apply_fn=final_apply))
    return segs


# ----------------------------------------------------------------------- bundles
def causal_lm_model(cfg: CausalLMConfig, sample_seq_len: Optional[int] = None,
                    layers_per_group: int = 2) -> Model:
    """Training/scoring bundle (loss over shifted labels). ``layers_per_group`` sets the
    granularity of the offload_param streaming decomposition (see
    :func:`causal_lm_segments`)."""
    from .gpt2 import cross_entropy_loss
    module = CausalLM(cfg)
    t = sample_seq_len or min(cfg.max_seq_len, 1024)

    def init_fn(rng):
        sample = jnp.zeros((1, t), dtype=jnp.int32)
        return module.init({"params": rng}, sample)["params"]

    def loss_fn(params, batch, rng):
        ids = batch["input_ids"]
        logits = module.apply({"params": params}, ids)
        labels = batch.get("labels")
        if labels is None:
            labels = jnp.concatenate(
                [ids[:, 1:], jnp.full((ids.shape[0], 1), -100, dtype=ids.dtype)], axis=1)
        return cross_entropy_loss(logits, labels)

    def apply_fn(params, batch, rng=None):
        ids = batch["input_ids"] if isinstance(batch, dict) else batch
        return module.apply({"params": params}, ids)

    return Model(loss_fn=loss_fn, init_fn=init_fn, apply_fn=apply_fn,
                 param_specs=None, name=cfg.name,
                 flops_per_sample=6.0 * cfg.num_params() * t,
                 segments=causal_lm_segments(cfg, layers_per_group))


def init_cache(cfg: CausalLMConfig, batch_size: int, max_len: Optional[int] = None,
               dtype=None):
    """Fixed-capacity head-major KV caches, one per layer."""
    T = max_len or cfg.max_seq_len
    dtype = dtype or cfg.dtype
    shape = (batch_size, cfg.kv_heads, T, cfg.head_dim)
    return [{"k": jnp.zeros(shape, dtype), "v": jnp.zeros(shape, dtype)}
            for _ in range(cfg.n_layer)]


def causal_lm_param_specs(params, tensor_axis: str = "tensor") -> Any:
    """Megatron TP rules for :class:`CausalLM` params (the sharding the reference's
    ``ReplaceWithTensorSlicing`` performs on qkv/mlp weights, ``module_inject/replace_module.py:25``)."""
    flat, treedef = jax.tree_util.tree_flatten_with_path(params)

    def spec_for(path_str: str, ndim: int):
        col = ("q_proj", "k_proj", "v_proj", "fc_in", "gate_proj", "up_proj")
        row = ("o_proj", "fc_out")
        if "/moe_experts/" in path_str:
            # expert dim over the expert axis (reference EP serving: experts split across
            # ranks at load, ``moe_inference.py``)
            from ..parallel.mesh import AXIS_EXPERT
            return P(AXIS_EXPERT, *([None] * (ndim - 1)))
        if path_str.endswith("moe_gate"):
            return P(*([None] * ndim))
        if any(f"/{n}/" in path_str or path_str.endswith(f"{n}/kernel") for n in col):
            if path_str.endswith("kernel"):
                return P(None, tensor_axis)
            if path_str.endswith("bias"):
                return P(tensor_axis)
        if any(f"/{n}/" in path_str for n in row):
            if path_str.endswith("kernel"):
                return P(tensor_axis, None)
            return P(*([None] * ndim)) if ndim else P()
        if path_str.endswith("wte") or path_str.endswith("lm_head/kernel"):
            return P(tensor_axis, None) if path_str.endswith("wte") else P(None, tensor_axis)
        return P(*([None] * ndim)) if ndim else P()

    specs = []
    for path, leaf in flat:
        path_str = "/".join(str(getattr(k, "key", getattr(k, "idx", k))) for k in path)
        specs.append(spec_for(path_str, getattr(leaf, "ndim", 0)))
    return jax.tree_util.tree_unflatten(treedef, specs)
