"""GPT-2 family — flagship causal-LM model.

TPU-first re-design of the model class the reference optimises (Megatron GPT-2 is DeepSpeed's
canonical workload; see reference ``tests/model/Megatron_GPT2`` and the inference containers
``module_inject/containers/gpt2.py``). Design choices for XLA/TPU:

- ``nn.scan`` over a single Block definition: one compiled layer body regardless of depth,
  which keeps compile time flat and later gives pipeline stages a natural split point.
- optional ``jax.checkpoint`` (remat) per layer — the analogue of the reference's activation
  checkpointing (``runtime/activation_checkpointing/checkpointing.py``).
- bf16 compute / fp32 params via the engine's dtype policy; softmax and layernorm run fp32.
- attention is pluggable (``ops/transformer/attention.py``): xla | flash (Pallas) | ring
  (sequence-parallel Pallas).
- weight-tied LM head (wte used for output projection), GPT-2 initialisation scheme.
"""

import dataclasses
from typing import Any, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from ..ops.transformer.attention import get_attention_impl
from .base import Model


@dataclasses.dataclass
class GPT2Config:
    vocab_size: int = 50257
    n_positions: int = 1024
    n_embd: int = 768
    n_layer: int = 12
    n_head: int = 12
    dropout: float = 0.0
    dtype: Any = jnp.bfloat16          # compute dtype
    remat: bool = False
    remat_policy: str = "full"         # full | dots (save matmul outputs, recompute the rest)
    scan_layers: bool = True
    attention_impl: str = "auto"       # flash kernel on TPU, xla attention elsewhere
    init_std: float = 0.02
    # Separate q/k/v projections instead of the fused c_attn. Required for in-stage
    # tensor parallelism: separate (d, d) kernels shard their last dim into whole head
    # groups, so the SAME global parameter layout is exact at every tp degree (a fused
    # (d, 3d) kernel sharded contiguously would mix q/k/v columns per shard, making the
    # model's meaning depend on tp — a silent checkpoint-portability hazard).
    split_qkv: bool = False
    # >0: compute the training loss with the chunked-vocab CE (online logsumexp
    # over vocab chunks of this size, runtime/zero/tiling.py) instead of
    # materialising (b, t, V) logits — the long-sequence memory knob (a 32k×50k
    # logits buffer alone is 6.6 GB fp32)
    vocab_chunk: int = 0

    @property
    def head_dim(self) -> int:
        return self.n_embd // self.n_head

    def flops_per_token(self) -> float:
        # 6ND training-flops rule + attention quadratic term
        n = self.num_params()
        return 6.0 * n + 12.0 * self.n_layer * self.n_embd * self.n_positions

    def num_params(self) -> int:
        d, L, v, t = self.n_embd, self.n_layer, self.vocab_size, self.n_positions
        return v * d + t * d + L * (12 * d * d + 13 * d) + 2 * d


# Preset sizes used by BASELINE configs (125M..13B follow GPT-3 table).
GPT2_PRESETS = {
    "gpt2-125m": dict(n_embd=768, n_layer=12, n_head=12),
    "gpt2-350m": dict(n_embd=1024, n_layer=24, n_head=16),
    "gpt2-760m": dict(n_embd=1536, n_layer=24, n_head=16),
    "gpt2-1.3b": dict(n_embd=2048, n_layer=24, n_head=16),
    "gpt2-2.7b": dict(n_embd=2560, n_layer=32, n_head=32),
    "gpt2-6.7b": dict(n_embd=4096, n_layer=32, n_head=32),
    "gpt2-13b": dict(n_embd=5120, n_layer=40, n_head=40),
}


def gpt2_config(preset: str, **overrides) -> GPT2Config:
    kw = dict(GPT2_PRESETS[preset])
    kw.update(overrides)
    return GPT2Config(**kw)


class Block(nn.Module):
    config: GPT2Config

    @nn.compact
    def __call__(self, x, deterministic: bool = True):
        cfg = self.config
        attn = get_attention_impl(cfg.attention_impl)
        h = nn.LayerNorm(dtype=jnp.float32, name="ln_1")(x).astype(cfg.dtype)
        if cfg.split_qkv:
            q = nn.Dense(cfg.n_embd, dtype=cfg.dtype, name="q_attn",
                         kernel_init=nn.initializers.normal(cfg.init_std))(h)
            k = nn.Dense(cfg.n_embd, dtype=cfg.dtype, name="k_attn",
                         kernel_init=nn.initializers.normal(cfg.init_std))(h)
            v = nn.Dense(cfg.n_embd, dtype=cfg.dtype, name="v_attn",
                         kernel_init=nn.initializers.normal(cfg.init_std))(h)
        else:
            qkv = nn.Dense(3 * cfg.n_embd, dtype=cfg.dtype, name="c_attn",
                           kernel_init=nn.initializers.normal(cfg.init_std))(h)
            q, k, v = jnp.split(qkv, 3, axis=-1)
        b, t, _ = q.shape
        q = q.reshape(b, t, cfg.n_head, cfg.head_dim)
        k = k.reshape(b, t, cfg.n_head, cfg.head_dim)
        v = v.reshape(b, t, cfg.n_head, cfg.head_dim)
        drop_rng = (None if deterministic or cfg.dropout == 0.0
                    else self.make_rng("dropout"))
        o = attn(q, k, v, causal=True, dropout_rate=0.0 if deterministic else cfg.dropout,
                 dropout_rng=drop_rng)
        o = o.reshape(b, t, cfg.n_embd)
        # scaled init on residual-writing projections (GPT-2 scheme)
        proj_init = nn.initializers.normal(cfg.init_std / (2 * cfg.n_layer) ** 0.5)
        o = nn.Dense(cfg.n_embd, dtype=cfg.dtype, name="c_proj", kernel_init=proj_init)(o)
        o = nn.Dropout(cfg.dropout, deterministic=deterministic)(o)
        x = x + o

        h = nn.LayerNorm(dtype=jnp.float32, name="ln_2")(x).astype(cfg.dtype)
        h = nn.Dense(4 * cfg.n_embd, dtype=cfg.dtype, name="c_fc",
                     kernel_init=nn.initializers.normal(cfg.init_std))(h)
        h = nn.gelu(h, approximate=True)
        h = nn.Dense(cfg.n_embd, dtype=cfg.dtype, name="mlp_c_proj",
                     kernel_init=proj_init)(h)
        h = nn.Dropout(cfg.dropout, deterministic=deterministic)(h)
        return x + h


# ------------------------------------------------------- manual tensor parallelism
def _manual_layer_norm(p, x, eps: float = 1e-6):
    """fp32 layernorm matching ``nn.LayerNorm(dtype=jnp.float32)`` numerics
    (flax ``_compute_stats``: var = E[x²] − E[x]², clamped at 0)."""
    x32 = x.astype(jnp.float32)
    mean = jnp.mean(x32, axis=-1, keepdims=True)
    mean2 = jnp.mean(jnp.square(x32), axis=-1, keepdims=True)
    var = jnp.maximum(0.0, mean2 - jnp.square(mean))
    mul = jax.lax.rsqrt(var + eps) * p["scale"].astype(jnp.float32)
    return (x32 - mean) * mul + p["bias"].astype(jnp.float32)


def _tp_conjugate_ops(axis: str):
    """Megatron's f/g conjugate operators (megatron/mpu ``copy_to_model_parallel`` /
    ``reduce_from_model_parallel``), defined via custom_vjp so the backward
    collectives are EXPLICIT: under ``shard_map(check_vma=False)`` the raw ``psum``
    transposes to another psum, which double-counts replicated cotangents.

    - ``f``: identity forward, psum backward — enters a column-parallel region
      (the replicated input's cotangent sums each shard's contribution);
    - ``g``: psum forward, identity backward — exits a row-parallel region
      (the summed output's cotangent is already replicated).
    """
    @jax.custom_vjp
    def f(x):
        return x

    f.defvjp(lambda x: (x, None), lambda _, ct: (jax.lax.psum(ct, axis),))

    @jax.custom_vjp
    def g(x):
        return jax.lax.psum(x, axis)

    g.defvjp(lambda x: (jax.lax.psum(x, axis), None), lambda _, ct: (ct,))
    return f, g


def block_tp_apply(cfg: GPT2Config, tp: int, axis: str,
                   sp_axis: Optional[str] = None):
    """Megatron-style manual-collective Block forward for use INSIDE a ``shard_map``
    whose manual axes include ``axis`` (reference 3D parallelism: TP inside pipeline
    stages, ``runtime/pipe/topology.py:243``; column/row classification as in
    ``module_inject/replace_module.py:25``).

    The caller passes the LOCAL parameter shard: q/k/v + fc kernels column-sharded
    (last dim, whole head groups), o/mlp projections row-sharded (first dim); the
    f/g conjugate pair brackets each col→row sandwich — the two collectives per
    block that Megatron inserts. Exactly equal to the replicated ``Block``
    (``split_qkv=True``, dropout off) at any tp degree.

    With ``sp_axis`` the activations additionally arrive SEQUENCE-SHARDED
    (pipe×tensor×seq 4D): dense/LN math is per-token so only the attention
    changes — local heads attend over K/V all-gathered along the seq axis
    (grouped collectives; see ``allgather_attention_local``).

    Returns ``fn(params_local, x, rng) -> y``.
    """
    if not (cfg.split_qkv):
        raise AssertionError("tensor-parallel Block needs split_qkv=True (see GPT2Config)")
    if not (cfg.n_head % tp == 0):
        raise AssertionError((cfg.n_head, tp))
    if not (cfg.dropout == 0.0):
        raise AssertionError("TP stage_fn does not implement attention dropout")
    h_local = cfg.n_head // tp
    dt = cfg.dtype
    f_op, g_op = _tp_conjugate_ops(axis)

    def dense(p, x):
        return x @ p["kernel"].astype(dt) + p["bias"].astype(dt)

    # honor cfg.attention_impl like the replicated Block does, with the manual-region
    # constraint that only impls with a local (non-shard_map) form are usable
    impl = cfg.attention_impl
    if callable(impl) or impl in ("ring", "ulysses"):
        raise NotImplementedError(
            f"attention_impl={impl!r} has no manual-TP form inside the 1F1B "
            "shard_map — use 'auto', 'xla', or 'flash' for TP pipeline bodies")

    def attention(q, k, v):
        if sp_axis is not None:
            from ..ops.attention.ring import allgather_attention_local
            return allgather_attention_local(q, k, v, causal=True,
                                             axis_name=sp_axis)
        from ..ops.transformer.attention import FLASH_MIN_SEQ, xla_attention
        t = q.shape[1]
        use_flash = (impl == "flash" or
                     (impl == "auto" and jax.default_backend() == "tpu"
                      and t >= FLASH_MIN_SEQ and t % 128 == 0))
        if use_flash:
            from ..ops.attention.flash import flash_attention_local
            return flash_attention_local(q, k, v, causal=True)
        return xla_attention(q, k, v, causal=True)

    def apply(p, x, rng=None):
        b, t, _ = x.shape
        h = f_op(_manual_layer_norm(p["ln_1"], x).astype(dt))
        q = dense(p["q_attn"], h).reshape(b, t, h_local, cfg.head_dim)
        k = dense(p["k_attn"], h).reshape(b, t, h_local, cfg.head_dim)
        v = dense(p["v_attn"], h).reshape(b, t, h_local, cfg.head_dim)
        o = attention(q, k, v).reshape(b, t, h_local * cfg.head_dim)
        # row-parallel projection: local partial matmul, g = psum-fwd/identity-bwd;
        # bias is added once, after the reduction
        o = g_op(o @ p["c_proj"]["kernel"].astype(dt)) + p["c_proj"]["bias"].astype(dt)
        x = x + o
        h = f_op(_manual_layer_norm(p["ln_2"], x).astype(dt))
        h = nn.gelu(dense(p["c_fc"], h), approximate=True)
        h = g_op(h @ p["mlp_c_proj"]["kernel"].astype(dt)) \
            + p["mlp_c_proj"]["bias"].astype(dt)
        return x + h

    return apply


# TP sharding roles of Block parameters (consumed by PipelineModule.param_specs):
# column-parallel kernels shard their LAST dim (outputs = whole head groups / ffn
# slices) and take their bias with them; row-parallel kernels shard their FIRST
# weight dim (inputs), bias replicated.
BLOCK_TP_COL = ("q_attn", "k_attn", "v_attn", "c_fc")
BLOCK_TP_ROW = ("c_proj", "mlp_c_proj")


def block_sp_apply(cfg: GPT2Config, sp: int, axis: str):
    """Sequence-parallel Block forward for use INSIDE a ``shard_map`` whose manual
    axes include ``axis`` (pipe×seq: context parallelism inside 1F1B pipeline
    stages — beyond the reference, whose SP story is absent).

    Activations arrive SEQUENCE-SHARDED ``(b, t/S, d)``; parameters are the full
    replicated Block tree (dense/LN work is per-token, so local chunks need no
    collectives) and attention all-gathers K/V over the seq axis
    (:func:`~...ops.attention.ring.allgather_attention_local` — grouped
    collectives, NOT the ppermute ring, because pipeline stage activity is
    staggered; see that function's docstring). Exactly equal to the replicated
    ``Block`` (dropout off) at any seq degree.

    Returns ``fn(params, x_local, rng) -> y_local``.
    """
    if not (cfg.split_qkv):
        raise AssertionError("seq-parallel Block needs split_qkv=True (see GPT2Config)")
    if not (cfg.dropout == 0.0):
        raise AssertionError("SP stage_fn does not implement attention dropout")
    dt = cfg.dtype

    def dense(p, x):
        return x @ p["kernel"].astype(dt) + p["bias"].astype(dt)

    def apply(p, x, rng=None):
        from ..ops.attention.ring import allgather_attention_local
        b, tl, _ = x.shape
        h = _manual_layer_norm(p["ln_1"], x).astype(dt)
        q = dense(p["q_attn"], h).reshape(b, tl, cfg.n_head, cfg.head_dim)
        k = dense(p["k_attn"], h).reshape(b, tl, cfg.n_head, cfg.head_dim)
        v = dense(p["v_attn"], h).reshape(b, tl, cfg.n_head, cfg.head_dim)
        o = allgather_attention_local(q, k, v, causal=True, axis_name=axis)
        o = o.reshape(b, tl, cfg.n_embd)
        o = dense(p["c_proj"], o)
        x = x + o
        h = _manual_layer_norm(p["ln_2"], x).astype(dt)
        h = nn.gelu(dense(p["c_fc"], h), approximate=True)
        h = dense(p["mlp_c_proj"], h)
        return x + h

    return apply


def _pin_batch_sharding(x):
    """Pin ``(b, t, d)`` activations to batch sharding over the present batch
    axes. The ZeRO-sharded embedding/layernorm params otherwise hand GSPMD
    conflicting sharding preferences for the layer carry (an fsdp-sharded
    feature dim vs the batch-sharded inputs), and it resolves them with an
    "Involuntary full rematerialization" replicate-reshard (the same failure
    mode — and fix — as ``moe/layer.py``'s token pinning). No-op without an
    installed mesh. Only called from the module-level ``GPT2`` forward, never
    from ``Block`` — the pipe engine wraps ``Block`` in manual shard_map
    regions where these axis names are not GSPMD-visible."""
    from ..parallel.mesh import AXIS_SEQ, BATCH_AXES, get_global_mesh
    mesh = get_global_mesh()
    if mesh is None:
        return x
    axes = tuple(ax for ax in BATCH_AXES if mesh.size(ax) > 1)
    if not axes and mesh.size(AXIS_SEQ) <= 1:
        return x
    # the seq dim keeps its context-parallel sharding (Ulysses) — pinning it
    # to replicated would itself conflict with the attention's a2a layout
    return jax.lax.with_sharding_constraint(
        x, mesh.sharding(mesh.batch_spec(extra_dims=x.ndim - 1,
                                         shard_seq_dim=1)))


def _pin_replicated(w):
    """Pin a parameter to full replication at a USE site. The embedding gather
    reads the whole ``wte`` row-wise; letting GSPMD keep the table's ZeRO/TP
    sharding on the gather operand makes the gather OUTPUT inherit a sharded
    feature dim, which then full-remats against the batch-sharded carry. The
    table is all-gathered for the row gather either way — pinning just makes
    the output sharding unconstrained instead of conflicting."""
    from ..parallel.mesh import get_global_mesh
    mesh = get_global_mesh()
    if mesh is None:
        return w
    return jax.lax.with_sharding_constraint(
        w, mesh.sharding(P(*([None] * w.ndim))))


class GPT2(nn.Module):
    config: GPT2Config

    @nn.compact
    def __call__(self, input_ids, deterministic: bool = True,
                 return_hidden: bool = False):
        """``return_hidden``: return ``(final hidden states, wte)`` instead of
        logits — the chunked-vocab CE path consumes these to avoid the
        ``(b, t, V)`` logits buffer (6.6 GB at seq 32k × vocab 50k)."""
        cfg = self.config
        b, t = input_ids.shape
        wte = self.param("wte", nn.initializers.normal(cfg.init_std),
                         (cfg.vocab_size, cfg.n_embd), jnp.float32)
        wpe = self.param("wpe", nn.initializers.normal(cfg.init_std),
                         (cfg.n_positions, cfg.n_embd), jnp.float32)
        x = _pin_replicated(wte)[input_ids].astype(cfg.dtype) + \
            wpe[:t][None].astype(cfg.dtype)
        x = nn.Dropout(cfg.dropout, deterministic=deterministic)(x)
        x = _pin_batch_sharding(x)

        block = Block
        if cfg.remat:
            policy = (jax.checkpoint_policies.dots_with_no_batch_dims_saveable
                      if cfg.remat_policy == "dots" else None)
            block = nn.remat(Block, prevent_cse=False, static_argnums=(2,), policy=policy)
        if cfg.scan_layers:
            x, _ = nn.scan(
                lambda mdl, carry, _: (
                    _pin_batch_sharding(mdl(carry, deterministic)), None),
                variable_axes={"params": 0},
                split_rngs={"params": True, "dropout": True},
                length=cfg.n_layer,
                metadata_params={nn.PARTITION_NAME: "layers"},
            )(block(cfg, name="h"), x, None)
        else:
            for i in range(cfg.n_layer):
                x = _pin_batch_sharding(block(cfg, name=f"h_{i}")(x, deterministic))

        x = nn.LayerNorm(dtype=jnp.float32, name="ln_f")(x)
        if return_hidden:
            return x, wte
        # Tied LM head. bf16 operands + fp32 MXU accumulation: full-rate matmul (an fp32
        # matmul runs at ~1/4 MXU rate and this is ~25% of model FLOPs), fp32-accurate logits.
        logits = jax.lax.dot_general(
            x.astype(cfg.dtype), wte.astype(cfg.dtype),
            dimension_numbers=(((2,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        return logits


def cross_entropy_loss(logits, labels, ignore_index: int = -100):
    """Next-token CE in fp32 with label masking."""
    vocab = logits.shape[-1]
    mask = labels != ignore_index
    safe_labels = jnp.where(mask, labels, 0)
    logz = jax.nn.logsumexp(logits, axis=-1)
    gold = jnp.take_along_axis(logits, safe_labels[..., None], axis=-1)[..., 0]
    nll = (logz - gold) * mask
    return nll.sum() / jnp.maximum(mask.sum(), 1)


def cross_entropy_loss_sp(logits, labels, axis_name: str,
                          ignore_index: int = -100):
    """Sequence-parallel CE: this shard's (sum, valid-count) contributions are
    psum'd over ``axis_name`` before the ratio, so unequal masked-token counts
    per shard (e.g. the final -100 living on the last shard) stay exact. For use
    INSIDE a shard_map manual over the seq axis (the 1F1B sp tail).

    The sum rides the ``g`` conjugate op (psum forward, IDENTITY backward): under
    ``check_vma=False`` a raw psum transposes to another psum, which would scale
    every upstream cotangent by the seq degree (the same trap the Megatron f/g
    ops exist for)."""
    _, g_op = _tp_conjugate_ops(axis_name)
    mask = labels != ignore_index
    safe_labels = jnp.where(mask, labels, 0)
    logz = jax.nn.logsumexp(logits, axis=-1)
    gold = jnp.take_along_axis(logits, safe_labels[..., None], axis=-1)[..., 0]
    nll = ((logz - gold) * mask).sum()
    total = g_op(nll)
    count = jax.lax.psum(mask.sum(), axis_name)   # integer: no cotangent path
    return total / jnp.maximum(count, 1)


def gpt2_model(config: GPT2Config, sample_seq_len: Optional[int] = None,
               sample_batch_size: int = 1) -> Model:
    """Build a :class:`Model` for the engine: batch = {"input_ids": (B, T)} with optional
    "labels" (defaults to shifted input_ids)."""
    module = GPT2(config)
    t = sample_seq_len or config.n_positions

    def init_fn(rng):
        sample = jnp.zeros((sample_batch_size, t), dtype=jnp.int32)
        return module.init({"params": rng, "dropout": rng}, sample)["params"]

    def _shift_labels(batch):
        ids = batch["input_ids"]
        if "labels" in batch:
            return batch["labels"]
        return jnp.concatenate(
            [ids[:, 1:], jnp.full((ids.shape[0], 1), -100, dtype=ids.dtype)], axis=1)

    def loss_fn(params, batch, rng):
        if config.vocab_chunk:
            from ..runtime.zero.tiling import chunked_vocab_cross_entropy
            hidden, wte = module.apply({"params": params}, batch["input_ids"],
                                       deterministic=False,
                                       rngs={"dropout": rng},
                                       return_hidden=True)
            return chunked_vocab_cross_entropy(hidden, wte, _shift_labels(batch),
                                               chunk=config.vocab_chunk,
                                               compute_dtype=config.dtype)
        logits = module.apply({"params": params}, batch["input_ids"],
                              deterministic=False, rngs={"dropout": rng})
        return cross_entropy_loss(logits, _shift_labels(batch))

    def apply_fn(params, batch, rng=None):
        ids = batch["input_ids"] if isinstance(batch, dict) else batch
        return module.apply({"params": params}, ids, deterministic=True)

    return Model(
        loss_fn=loss_fn,
        init_fn=init_fn,
        apply_fn=apply_fn,
        param_specs=None,  # filled per-mesh by gpt2_param_specs
        flops_per_sample=config.flops_per_token() * t,
        name=f"GPT2(L{config.n_layer},d{config.n_embd})",
    )


def gpt2_param_specs(params, tensor_axis: str = "tensor") -> Any:
    """Megatron-style TP PartitionSpecs by parameter path.

    Column-parallel: qkv and mlp-in kernels shard their output dim; row-parallel: attn/mlp out
    projections shard their input dim; embeddings shard the vocab dim. XLA inserts the
    all-reduces the reference does manually via ``LinearAllreduce`` (``module_inject/layers.py``).
    """
    flat = jax.tree_util.tree_flatten_with_path(params)[0]
    treedef = jax.tree_util.tree_structure(params)

    def spec_for(path_str: str, ndim: int):
        lead = [None] * (ndim - 2)
        if "c_attn" in path_str or "c_fc" in path_str:
            if path_str.endswith("kernel"):
                return P(*lead, None, tensor_axis)
            return P(*([None] * (ndim - 1)), tensor_axis)
        if ("c_proj" in path_str or "mlp_c_proj" in path_str) and path_str.endswith("kernel"):
            return P(*lead, tensor_axis, None)
        if path_str.endswith("wte"):
            return P(tensor_axis, None)
        return P(*([None] * ndim)) if ndim else P()

    specs = []
    for path, leaf in flat:
        path_str = "/".join(str(getattr(k, "key", getattr(k, "idx", k))) for k in path)
        specs.append(spec_for(path_str, getattr(leaf, "ndim", 0)))
    return jax.tree_util.tree_unflatten(treedef, specs)
