"""Stable-Diffusion model family: UNet2DCondition, VAE decoder, CLIP text encoder.

TPU-native re-design of the reference's diffusers serving surface — the reference
injects optimized containers into HF diffusers pipelines
(``module_inject/containers/unet.py:1``, ``vae.py:1``, ``clip.py:1``; model
implementations ``model_implementations/diffusers/unet.py:1``, ``vae.py:1``) and
ships an NHWC bias-add CUDA kernel (``csrc/spatial/csrc/opt_bias_add.cu:1``).
Here the models are flax modules in NHWC layout end-to-end (the TPU conv layout —
XLA fuses bias-add + nonlinearity into the convolutions, which is the whole job of
the reference's spatial kernel), and module/param names MIRROR the diffusers state
dict key paths so weight conversion is one generic transpose walk
(``module_inject.diffusers_policies``).

Shapes follow ``UNet2DConditionModel`` / ``AutoencoderKL`` / ``CLIPTextModel`` of
the SD-1.x family, parameterized so tests run tiny.
"""

import dataclasses
import math
from typing import Any, Optional, Sequence, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp

# --------------------------------------------------------------------------- configs


@dataclasses.dataclass
class UNetConfig:
    sample_size: int = 64                  # latent H=W
    in_channels: int = 4
    out_channels: int = 4
    block_out_channels: Tuple[int, ...] = (320, 640, 1280, 1280)
    layers_per_block: int = 2
    cross_attention_dim: int = 768
    attention_head_dim: int = 8            # heads; head_dim = C // heads
    norm_num_groups: int = 32
    norm_eps: float = 1e-5                 # diffusers UNet2DConditionModel norm_eps
    dtype: Any = jnp.bfloat16

    @property
    def n_down(self) -> int:
        return len(self.block_out_channels)


@dataclasses.dataclass
class VAEConfig:
    latent_channels: int = 4
    out_channels: int = 3
    block_out_channels: Tuple[int, ...] = (128, 256, 512, 512)
    layers_per_block: int = 2              # decoder uses layers_per_block + 1
    norm_num_groups: int = 32
    norm_eps: float = 1e-6                 # diffusers AutoencoderKL norm eps
    scaling_factor: float = 0.18215
    dtype: Any = jnp.bfloat16


@dataclasses.dataclass
class CLIPTextConfig:
    vocab_size: int = 49408
    max_position_embeddings: int = 77
    hidden_size: int = 768
    num_hidden_layers: int = 12
    num_attention_heads: int = 12
    intermediate_size: int = 3072
    ln_eps: float = 1e-5
    act: str = "quick_gelu"                # HF hidden_act: quick_gelu (SD-1.x) | gelu
    dtype: Any = jnp.bfloat16


# ----------------------------------------------------------------------- primitives
def _gn(groups, name, eps):
    # GroupNorms stay fp32 regardless of the compute dtype (same policy as the
    # fp32 LayerNorms in the text/decoder stacks); eps follows the source model
    # (diffusers UNet 1e-5, VAE 1e-6)
    return nn.GroupNorm(num_groups=groups, epsilon=eps, name=name,
                        dtype=jnp.float32)


def _conv(out_ch, k, name, dtype, stride=1, pad=1):
    return nn.Conv(out_ch, (k, k), strides=(stride, stride),
                   padding=[(pad, pad), (pad, pad)], dtype=dtype, name=name)


def timestep_embedding(t, dim: int, max_period: float = 10000.0):
    """Sinusoidal timestep embedding (diffusers ``get_timestep_embedding``)."""
    half = dim // 2
    freqs = jnp.exp(-math.log(max_period) *
                    jnp.arange(half, dtype=jnp.float32) / half)
    args = t.astype(jnp.float32)[:, None] * freqs[None]
    return jnp.concatenate([jnp.cos(args), jnp.sin(args)], axis=-1)


class _Attention(nn.Module):
    """Multi-head attention with diffusers param names to_q/to_k/to_v/to_out.0.

    Spatial self-attention flattens (h, w) into the sequence; cross-attention
    reads keys/values from the text context. Heads shard over the tensor axis
    under TP (column-parallel qkv, row-parallel out — the Megatron layout the
    reference's containers apply to attention, ``containers/unet.py``)."""
    heads: int
    dim: int
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x, context=None):
        # the context Dense infers its input dim, so no context_dim field needed
        ctx = x if context is None else context
        d = self.dim
        q = nn.Dense(d, use_bias=False, dtype=self.dtype, name="to_q")(x)
        k = nn.Dense(d, use_bias=False, dtype=self.dtype, name="to_k")(ctx)
        v = nn.Dense(d, use_bias=False, dtype=self.dtype, name="to_v")(ctx)
        b, t, _ = q.shape
        s = ctx.shape[1]
        hd = d // self.heads
        q = q.reshape(b, t, self.heads, hd).transpose(0, 2, 1, 3)
        k = k.reshape(b, s, self.heads, hd).transpose(0, 2, 1, 3)
        v = v.reshape(b, s, self.heads, hd).transpose(0, 2, 1, 3)
        scores = jnp.einsum("bhtd,bhsd->bhts", q, k) / jnp.sqrt(hd).astype(
            q.dtype)
        probs = jax.nn.softmax(scores.astype(jnp.float32), axis=-1).astype(
            v.dtype)
        o = jnp.einsum("bhts,bhsd->bhtd", probs, v)
        o = o.transpose(0, 2, 1, 3).reshape(b, t, d)
        return nn.Dense(d, dtype=self.dtype, name="to_out_0")(o)


class _FeedForward(nn.Module):
    """GEGLU feed-forward (diffusers ``ff.net.0.proj`` + ``ff.net.2``); gate
    uses EXACT (erf) gelu like torch ``F.gelu`` in diffusers' GEGLU."""
    dim: int
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x):
        h = nn.Dense(8 * self.dim, dtype=self.dtype, name="net_0_proj")(x)
        a, g = jnp.split(h, 2, axis=-1)
        return nn.Dense(self.dim, dtype=self.dtype, name="net_2")(
            a * nn.gelu(g, approximate=False))


class _BasicTransformerBlock(nn.Module):
    """LN → self-attn → LN → cross-attn → LN → GEGLU FF (diffusers
    ``BasicTransformerBlock``; LayerNorm eps 1e-5 = torch default)."""
    heads: int
    dim: int
    context_dim: int
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x, context):
        def ln(name):
            return nn.LayerNorm(epsilon=1e-5, dtype=jnp.float32, name=name)
        h = ln("norm1")(x).astype(self.dtype)
        x = x + _Attention(self.heads, self.dim, dtype=self.dtype,
                           name="attn1")(h)
        h = ln("norm2")(x).astype(self.dtype)
        x = x + _Attention(self.heads, self.dim,
                           dtype=self.dtype, name="attn2")(h, context)
        h = ln("norm3")(x).astype(self.dtype)
        return x + _FeedForward(self.dim, dtype=self.dtype, name="ff")(h)


class _Transformer2D(nn.Module):
    """Spatial transformer (diffusers ``Transformer2DModel``): GN → 1×1 conv in →
    flatten (h, w) → blocks → 1×1 conv out + residual."""
    heads: int
    dim: int
    context_dim: int
    groups: int
    eps: float = 1e-5
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x, context):
        b, hh, ww, c = x.shape
        res = x
        h = _gn(self.groups, "norm", self.eps)(x).astype(self.dtype)
        h = _conv(self.dim, 1, "proj_in", self.dtype, pad=0)(h)
        h = h.reshape(b, hh * ww, self.dim)
        h = _BasicTransformerBlock(self.heads, self.dim, self.context_dim,
                                   dtype=self.dtype,
                                   name="transformer_blocks_0")(h, context)
        h = h.reshape(b, hh, ww, self.dim)
        h = _conv(c, 1, "proj_out", self.dtype, pad=0)(h)
        return h + res


class _ResnetBlock(nn.Module):
    """GN → silu → conv → (+time emb) → GN → silu → conv → +skip (diffusers
    ``ResnetBlock2D``; the 1×1 ``conv_shortcut`` appears when channels change)."""
    out_ch: int
    groups: int
    time_dim: Optional[int] = None
    eps: float = 1e-5
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x, temb=None):
        in_ch = x.shape[-1]
        h = _gn(self.groups, "norm1", self.eps)(x).astype(self.dtype)
        h = _conv(self.out_ch, 3, "conv1", self.dtype)(nn.silu(h))
        if temb is not None:
            t = nn.Dense(self.out_ch, dtype=self.dtype,
                         name="time_emb_proj")(nn.silu(temb))
            h = h + t[:, None, None, :]
        h = _gn(self.groups, "norm2", self.eps)(h).astype(self.dtype)
        h = _conv(self.out_ch, 3, "conv2", self.dtype)(nn.silu(h))
        if in_ch != self.out_ch:
            x = _conv(self.out_ch, 1, "conv_shortcut", self.dtype, pad=0)(x)
        return x + h


# ------------------------------------------------------------------------- the UNet
class UNet2DCondition(nn.Module):
    """Conditional denoising UNet (diffusers ``UNet2DConditionModel``, SD-1.x
    topology): cross-attention down blocks, mid, up blocks with skip concats.
    NHWC throughout; all names mirror the diffusers state dict."""
    config: UNetConfig

    @nn.compact
    def __call__(self, sample, timesteps, encoder_hidden_states):
        cfg = self.config
        dt = cfg.dtype
        chs = cfg.block_out_channels
        groups = cfg.norm_num_groups
        heads = cfg.attention_head_dim
        tdim = 4 * chs[0]

        temb = timestep_embedding(timesteps, chs[0])
        temb = nn.Dense(tdim, dtype=dt, name="time_embedding_linear_1")(
            temb.astype(dt))
        temb = nn.Dense(tdim, dtype=dt, name="time_embedding_linear_2")(
            nn.silu(temb))
        ctx = encoder_hidden_states.astype(dt)

        h = _conv(chs[0], 3, "conv_in", dt)(sample.astype(dt))
        skips = [h]
        # down: CrossAttn blocks for all but the last, plain Down for the last
        for bi, ch in enumerate(chs):
            attn = bi < len(chs) - 1
            for li in range(cfg.layers_per_block):
                h = _ResnetBlock(ch, groups, tdim, eps=cfg.norm_eps, dtype=dt,
                                 name=f"down_blocks_{bi}_resnets_{li}")(h, temb)
                if attn:
                    h = _Transformer2D(heads, ch, cfg.cross_attention_dim,
                                       groups, eps=cfg.norm_eps, dtype=dt,
                                       name=f"down_blocks_{bi}_attentions_{li}"
                                       )(h, ctx)
                skips.append(h)
            if bi < len(chs) - 1:
                h = _conv(ch, 3, f"down_blocks_{bi}_downsamplers_0_conv", dt,
                          stride=2)(h)
                skips.append(h)

        h = _ResnetBlock(chs[-1], groups, tdim, eps=cfg.norm_eps, dtype=dt,
                         name="mid_block_resnets_0")(h, temb)
        h = _Transformer2D(heads, chs[-1], cfg.cross_attention_dim, groups,
                           eps=cfg.norm_eps, dtype=dt, name="mid_block_attentions_0")(h, ctx)
        h = _ResnetBlock(chs[-1], groups, tdim, eps=cfg.norm_eps, dtype=dt,
                         name="mid_block_resnets_1")(h, temb)

        # up: reversed channels; each block consumes layers_per_block+1 skips
        for bi, ch in enumerate(reversed(chs)):
            attn = bi > 0
            for li in range(cfg.layers_per_block + 1):
                h = jnp.concatenate([h, skips.pop()], axis=-1)
                h = _ResnetBlock(ch, groups, tdim, eps=cfg.norm_eps, dtype=dt,
                                 name=f"up_blocks_{bi}_resnets_{li}")(h, temb)
                if attn:
                    h = _Transformer2D(heads, ch, cfg.cross_attention_dim,
                                       groups, eps=cfg.norm_eps, dtype=dt,
                                       name=f"up_blocks_{bi}_attentions_{li}"
                                       )(h, ctx)
            if bi < len(chs) - 1:
                b, hh, ww, c = h.shape
                h = jax.image.resize(h, (b, 2 * hh, 2 * ww, c), "nearest")
                h = _conv(c, 3, f"up_blocks_{bi}_upsamplers_0_conv", dt)(h)

        h = _gn(groups, "conv_norm_out", cfg.norm_eps)(h).astype(dt)
        return _conv(self.config.out_channels, 3, "conv_out", dt)(
            nn.silu(h)).astype(jnp.float32)


# ------------------------------------------------------------------------- the VAE
class VAEDecoder(nn.Module):
    """Latents → image (diffusers ``AutoencoderKL`` decode half +
    ``post_quant_conv``). Caller divides latents by ``scaling_factor``."""
    config: VAEConfig

    @nn.compact
    def __call__(self, z):
        cfg = self.config
        dt = cfg.dtype
        chs = cfg.block_out_channels
        groups = cfg.norm_num_groups
        z = _conv(cfg.latent_channels, 1, "post_quant_conv", dt, pad=0)(
            z.astype(dt))
        h = _conv(chs[-1], 3, "decoder_conv_in", dt)(z)
        h = _ResnetBlock(chs[-1], groups, eps=cfg.norm_eps, dtype=dt,
                         name="decoder_mid_block_resnets_0")(h)
        # single-head spatial attention mid-block (diffusers ``Attention`` with
        # heads=1 inside the VAE)
        b, hh, ww, c = h.shape
        hn = _gn(groups, "decoder_mid_block_attentions_0_group_norm",
                 cfg.norm_eps)(h)
        o = _Attention(1, c, dtype=dt,
                       name="decoder_mid_block_attentions_0")(
                           hn.astype(dt).reshape(b, hh * ww, c))
        h = h + o.reshape(b, hh, ww, c)
        h = _ResnetBlock(chs[-1], groups, eps=cfg.norm_eps, dtype=dt,
                         name="decoder_mid_block_resnets_1")(h)
        for bi, ch in enumerate(reversed(chs)):
            for li in range(cfg.layers_per_block + 1):
                h = _ResnetBlock(ch, groups, eps=cfg.norm_eps, dtype=dt,
                                 name=f"decoder_up_blocks_{bi}_resnets_{li}")(h)
            if bi < len(chs) - 1:
                b, hh, ww, c = h.shape
                h = jax.image.resize(h, (b, 2 * hh, 2 * ww, c), "nearest")
                h = _conv(c, 3, f"decoder_up_blocks_{bi}_upsamplers_0_conv",
                          dt)(h)
        h = _gn(groups, "decoder_conv_norm_out", cfg.norm_eps)(h).astype(dt)
        return _conv(cfg.out_channels, 3, "decoder_conv_out", dt)(
            nn.silu(h)).astype(jnp.float32)


# ------------------------------------------------------------------- CLIP text
class CLIPTextEncoder(nn.Module):
    """CLIP text transformer (HF ``CLIPTextModel``): token+position embeddings,
    pre-LN causal blocks with quick-gelu MLPs, final LN. Parity vs the torch
    module is pinned in tests (``test_diffusion.py::test_clip_matches_hf``)."""
    config: CLIPTextConfig

    @nn.compact
    def __call__(self, input_ids):
        cfg = self.config
        dt = cfg.dtype
        b, t = input_ids.shape
        tok = self.param("token_embedding",
                         nn.initializers.normal(0.02),
                         (cfg.vocab_size, cfg.hidden_size), jnp.float32)
        pos = self.param("position_embedding",
                         nn.initializers.normal(0.02),
                         (cfg.max_position_embeddings, cfg.hidden_size),
                         jnp.float32)
        x = (tok[input_ids] + pos[None, :t]).astype(dt)
        mask = jnp.where(
            jnp.arange(t)[None, :] <= jnp.arange(t)[:, None], 0.0, -jnp.inf
        ).astype(jnp.float32)
        hd = cfg.hidden_size // cfg.num_attention_heads
        for i in range(cfg.num_hidden_layers):
            pfx = f"layers_{i}"
            h = nn.LayerNorm(epsilon=cfg.ln_eps, dtype=jnp.float32,
                             name=f"{pfx}_layer_norm1")(x).astype(dt)
            q = nn.Dense(cfg.hidden_size, dtype=dt, name=f"{pfx}_q_proj")(h)
            k = nn.Dense(cfg.hidden_size, dtype=dt, name=f"{pfx}_k_proj")(h)
            v = nn.Dense(cfg.hidden_size, dtype=dt, name=f"{pfx}_v_proj")(h)
            q = q.reshape(b, t, -1, hd).transpose(0, 2, 1, 3)
            k = k.reshape(b, t, -1, hd).transpose(0, 2, 1, 3)
            v = v.reshape(b, t, -1, hd).transpose(0, 2, 1, 3)
            s = jnp.einsum("bhtd,bhsd->bhts", q, k) / jnp.sqrt(hd).astype(
                q.dtype)
            p = jax.nn.softmax(s.astype(jnp.float32) + mask, axis=-1).astype(
                v.dtype)
            o = jnp.einsum("bhts,bhsd->bhtd", p, v).transpose(
                0, 2, 1, 3).reshape(b, t, cfg.hidden_size)
            x = x + nn.Dense(cfg.hidden_size, dtype=dt,
                             name=f"{pfx}_out_proj")(o)
            h = nn.LayerNorm(epsilon=cfg.ln_eps, dtype=jnp.float32,
                             name=f"{pfx}_layer_norm2")(x).astype(dt)
            h = nn.Dense(cfg.intermediate_size, dtype=dt,
                         name=f"{pfx}_fc1")(h)
            if cfg.act == "quick_gelu":
                h = h * jax.nn.sigmoid(1.702 * h)      # CLIP quick-gelu
            else:
                h = nn.gelu(h, approximate=False)      # SD-2.x text encoders
            x = x + nn.Dense(cfg.hidden_size, dtype=dt, name=f"{pfx}_fc2")(h)
        return nn.LayerNorm(epsilon=cfg.ln_eps, dtype=jnp.float32,
                            name="final_layer_norm")(x)
