"""Pipelined GPT-2: the LayerSpec decomposition of ``models/gpt2.py``.

Reference analogue: Megatron-style ``GPT2ModelPipe`` built from ``LayerSpec``s (the usage
pattern ``deepspeed/runtime/pipe/module.py`` is designed for; see reference
``tests/unit/simple_model.py:LinearStackPipe``). The embedding is tied with the LM head via
``TiedLayerSpec`` (reference ``module.py:74``).
"""

from typing import Optional

import flax.linen as nn
import jax.numpy as jnp

from ..runtime.pipe.module import FlaxPipeLayer, LayerSpec, PipelineModule, TiedLayerSpec
from .gpt2 import (BLOCK_TP_COL, BLOCK_TP_ROW, Block, GPT2Config, block_sp_apply,
                   block_tp_apply, cross_entropy_loss, cross_entropy_loss_sp)


class GPT2EmbedPipe(nn.Module):
    """wte + wpe + dropout; owns the tied embedding table."""
    config: GPT2Config

    @nn.compact
    def __call__(self, input_ids, deterministic: bool = True):
        cfg = self.config
        wte = self.param("wte", nn.initializers.normal(cfg.init_std),
                         (cfg.vocab_size, cfg.n_embd), jnp.float32)
        wpe = self.param("wpe", nn.initializers.normal(cfg.init_std),
                         (cfg.n_positions, cfg.n_embd), jnp.float32)
        t = input_ids.shape[-1]
        x = wte[input_ids].astype(cfg.dtype) + wpe[:t][None].astype(cfg.dtype)
        return nn.Dropout(cfg.dropout, deterministic=deterministic)(x)


class GPT2FinalNorm(nn.Module):
    config: GPT2Config

    @nn.compact
    def __call__(self, x, deterministic: bool = True):
        return nn.LayerNorm(dtype=jnp.float32, name="ln_f")(x)


def _embed_layer(cfg):
    return FlaxPipeLayer(GPT2EmbedPipe(cfg), deterministic_kwarg=True)


def _block_layer(cfg):
    tp_factory = sp_factory = None
    if cfg.split_qkv:
        tp_factory = lambda tp, axis, **kw: block_tp_apply(cfg, tp, axis, **kw)
        sp_factory = lambda sp, axis: block_sp_apply(cfg, sp, axis)
    return FlaxPipeLayer(Block(cfg), deterministic_kwarg=True,
                         tp_apply_factory=tp_factory,
                         tp_col=BLOCK_TP_COL, tp_row=BLOCK_TP_ROW,
                         sp_apply_factory=sp_factory)


def _norm_layer(cfg):
    return FlaxPipeLayer(GPT2FinalNorm(cfg), deterministic_kwarg=True)


def _tied_head_forward(base_layer, params, x):
    """LM head reusing the tied wte (reference TiedLayerSpec forward_fn pattern)."""
    return x.astype(jnp.float32) @ params["wte"].T


def _tied_hidden_forward(base_layer, params, x):
    """Chunked-vocab head: pass (hidden, wte) through so the LOSS computes the
    online-logsumexp CE without a (b, t, V) logits buffer (the pipelined analogue
    of ``GPT2Config(vocab_chunk=N)``)."""
    return (x, params["wte"])


def gpt2_pipeline_module(config: GPT2Config, num_stages: int,
                         sample_seq_len: Optional[int] = None,
                         sample_batch_size: int = 1,
                         activation_checkpoint_interval: int = 1,
                         partition_method: str = "uniform") -> PipelineModule:
    t = sample_seq_len or config.n_positions
    sample = jnp.zeros((sample_batch_size, t), dtype=jnp.int32)
    chunk = int(getattr(config, "vocab_chunk", 0) or 0)
    if chunk:
        # chunked-vocab tail: head layer passes (hidden, wte) through; the loss
        # computes the online-logsumexp CE — no (b, t, V) logits on the last stage
        from ..runtime.zero.tiling import chunked_vocab_cross_entropy
        head_fn = _tied_hidden_forward
        loss_fn = lambda out, lab: chunked_vocab_cross_entropy(
            out[0], out[1], lab, chunk=chunk, compute_dtype=config.dtype)

        def sp_loss_fn(out, lab, axis):
            raise NotImplementedError(
                "GPT2Config.vocab_chunk does not compose with a seq-sharded "
                "pipeline tail yet — drop the seq mesh axis or unset vocab_chunk")
    else:
        head_fn = _tied_head_forward
        loss_fn = cross_entropy_loss
        sp_loss_fn = cross_entropy_loss_sp
    layers = [
        TiedLayerSpec("embed", _embed_layer, config),
        *[LayerSpec(_block_layer, config) for _ in range(config.n_layer)],
        LayerSpec(_norm_layer, config),
        TiedLayerSpec("embed", _embed_layer, config, forward_fn=head_fn),
    ]
    mod = PipelineModule(
        layers=layers,
        num_stages=num_stages,
        loss_fn=loss_fn,
        sp_loss_fn=sp_loss_fn,
        sample_input=sample,
        partition_method=partition_method,
        activation_checkpoint_interval=activation_checkpoint_interval,
    )
    if chunk:
        # apply_fn keeps the (b, t, V) logits contract even though the head
        # layer emits (hidden, wte) for the chunked loss
        mod.apply_transform = lambda out: \
            out[0].astype(jnp.float32) @ out[1].T
    return mod
