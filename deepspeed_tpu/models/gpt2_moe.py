"""GPT-2 + MoE — the DeepSpeed-MoE NLG architecture.

Reference pattern: alternating dense/MoE transformer layers with top-1 gating and a scaled
load-balancing loss (``docs/_posts/2021-12-09-deepspeed-moe-nlg.md``; layer wiring via
``deepspeed.moe.layer.MoE``). Expert parallelism rides the ``expert`` mesh axis.
"""

import dataclasses
from typing import Any, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from ..moe.layer import MoE
from ..ops.transformer.attention import get_attention_impl
from .base import Model
from .gpt2 import GPT2Config, cross_entropy_loss


@dataclasses.dataclass
class GPT2MoEConfig(GPT2Config):
    num_experts: int = 8
    moe_layer_interval: int = 2      # every k-th layer is MoE (reference alternates)
    top_k: int = 1
    capacity_factor: float = 1.25
    eval_capacity_factor: float = 2.0
    min_capacity: int = 4
    noisy_gate_policy: Optional[str] = "RSample"
    moe_loss_coef: float = 0.01
    use_residual: bool = False
    # token-dim sharding axes threaded to moe.layer.MoE.token_axes; the pipeline module
    # overrides to () because inside its manual shard_map these axes are not GSPMD-visible
    moe_token_axes: tuple = ("data", "fsdp", "seq")


class MoEBlock(nn.Module):
    """Transformer block with an MoE FFN (attention identical to the dense Block)."""
    config: GPT2MoEConfig

    @nn.compact
    def __call__(self, x, deterministic: bool = True):
        cfg = self.config
        attn = get_attention_impl(cfg.attention_impl)
        h = nn.LayerNorm(dtype=jnp.float32, name="ln_1")(x).astype(cfg.dtype)
        qkv = nn.Dense(3 * cfg.n_embd, dtype=cfg.dtype, name="c_attn",
                       kernel_init=nn.initializers.normal(cfg.init_std))(h)
        q, k, v = jnp.split(qkv, 3, axis=-1)
        b, t, _ = q.shape
        q = q.reshape(b, t, cfg.n_head, cfg.head_dim)
        k = k.reshape(b, t, cfg.n_head, cfg.head_dim)
        v = v.reshape(b, t, cfg.n_head, cfg.head_dim)
        drop_rng = (None if deterministic or cfg.dropout == 0.0
                    else self.make_rng("dropout"))
        o = attn(q, k, v, causal=True,
                 dropout_rate=0.0 if deterministic else cfg.dropout,
                 dropout_rng=drop_rng)
        o = o.reshape(b, t, cfg.n_embd)
        proj_init = nn.initializers.normal(cfg.init_std / (2 * cfg.n_layer) ** 0.5)
        o = nn.Dense(cfg.n_embd, dtype=cfg.dtype, name="c_proj", kernel_init=proj_init)(o)
        o = nn.Dropout(cfg.dropout, deterministic=deterministic)(o)
        x = x + o

        h = nn.LayerNorm(dtype=jnp.float32, name="ln_2")(x).astype(cfg.dtype)
        y, l_aux, exp_counts = MoE(
            hidden_size=cfg.n_embd,
            ffn_hidden_size=4 * cfg.n_embd,
            num_experts=cfg.num_experts,
            k=cfg.top_k,
            capacity_factor=cfg.capacity_factor,
            eval_capacity_factor=cfg.eval_capacity_factor,
            min_capacity=cfg.min_capacity,
            noisy_gate_policy=cfg.noisy_gate_policy,
            use_residual=cfg.use_residual,
            dtype=cfg.dtype,
            init_std=cfg.init_std,
            token_axes=tuple(cfg.moe_token_axes),
            name="moe")(h, deterministic=deterministic)
        self.sow("losses", "moe_l_aux", l_aux)
        y = nn.Dropout(cfg.dropout, deterministic=deterministic)(y)
        return x + y


class GPT2MoE(nn.Module):
    config: GPT2MoEConfig

    @nn.compact
    def __call__(self, input_ids, deterministic: bool = True):
        cfg = self.config
        b, t = input_ids.shape
        wte = self.param("wte", nn.initializers.normal(cfg.init_std),
                         (cfg.vocab_size, cfg.n_embd), jnp.float32)
        wpe = self.param("wpe", nn.initializers.normal(cfg.init_std),
                         (cfg.n_positions, cfg.n_embd), jnp.float32)
        from .gpt2 import Block, _pin_batch_sharding, _pin_replicated
        x = _pin_replicated(wte)[input_ids].astype(cfg.dtype) + \
            wpe[:t][None].astype(cfg.dtype)
        x = nn.Dropout(cfg.dropout, deterministic=deterministic)(x)
        x = _pin_batch_sharding(x)

        for i in range(cfg.n_layer):
            if (i + 1) % cfg.moe_layer_interval == 0:
                x = MoEBlock(cfg, name=f"h_moe_{i}")(x, deterministic)
            else:
                x = Block(cfg, name=f"h_{i}")(x, deterministic)
            x = _pin_batch_sharding(x)

        x = nn.LayerNorm(dtype=jnp.float32, name="ln_f")(x)
        return x.astype(jnp.float32) @ wte.T


def gpt2_moe_model(config: GPT2MoEConfig, sample_seq_len: Optional[int] = None,
                   sample_batch_size: int = 1) -> Model:
    module = GPT2MoE(config)
    t = sample_seq_len or config.n_positions

    def init_fn(rng):
        sample = jnp.zeros((sample_batch_size, t), dtype=jnp.int32)
        return module.init({"params": rng, "gating": rng}, sample)["params"]

    def loss_fn(params, batch, rng):
        ids = batch["input_ids"]
        logits, mutables = module.apply(
            {"params": params}, ids, deterministic=False,
            rngs={"gating": rng, "dropout": jax.random.fold_in(rng, 1)},
            mutable=["losses"])
        labels = batch.get("labels")
        if labels is None:
            labels = jnp.concatenate(
                [ids[:, 1:], jnp.full((ids.shape[0], 1), -100, dtype=ids.dtype)], axis=1)
        lm_loss = cross_entropy_loss(logits, labels)
        aux = jax.tree_util.tree_leaves(mutables.get("losses", {}))
        moe_loss = (jnp.sum(jnp.stack([jnp.sum(a) for a in aux]))
                    if aux else jnp.float32(0.0))
        return lm_loss + config.moe_loss_coef * moe_loss

    def apply_fn(params, batch, rng=None):
        ids = batch["input_ids"] if isinstance(batch, dict) else batch
        return module.apply({"params": params}, ids, deterministic=True)

    return Model(loss_fn=loss_fn, init_fn=init_fn, apply_fn=apply_fn,
                 param_specs=None,
                 name=f"GPT2MoE(L{config.n_layer},d{config.n_embd},E{config.num_experts})")


def gpt2_moe_param_specs(params, expert_axis: str = "expert",
                         tensor_axis: Optional[str] = None) -> Any:
    """Expert params shard over ``expert`` (reference expert-parallel groups); the gate stays
    replicated; dense params follow the Megatron TP rules of ``gpt2_param_specs`` when
    ``tensor_axis`` is given, else replicate. Classification reuses
    ``moe.utils.is_moe_param_path`` so spec building and optimizer grouping agree."""
    from ..moe.utils import _path_str, is_moe_param_path
    from .gpt2 import gpt2_param_specs
    flat, treedef = jax.tree_util.tree_flatten_with_path(params)
    dense_spec_tree = (gpt2_param_specs(params, tensor_axis=tensor_axis)
                       if tensor_axis is not None else None)
    dense_specs = (jax.tree_util.tree_leaves(
        dense_spec_tree, is_leaf=lambda x: isinstance(x, P))
        if dense_spec_tree is not None else None)

    specs = []
    for idx, (path, leaf) in enumerate(flat):
        path_str = _path_str(path)
        ndim = getattr(leaf, "ndim", 0)
        if is_moe_param_path(path_str):
            if "experts" in path_str:
                specs.append(P(expert_axis, *([None] * (ndim - 1))))
            else:  # gate_wg: replicated (tiny)
                specs.append(P(*([None] * ndim)) if ndim else P())
        elif dense_specs is not None:
            specs.append(dense_specs[idx])
        else:
            specs.append(P(*([None] * ndim)) if ndim else P())
    return jax.tree_util.tree_unflatten(treedef, specs)
