"""Pipelined GPT-2-MoE: MoE blocks as first-class 1F1B pipeline body layers.

Pipe×expert composition (reference treats expert groups and pipeline topology as
composable: ``deepspeed/utils/groups.py:109``, ``runtime/pipe/topology.py:243`` —
MoE-at-scale trains with experts sharded inside pipeline stages). TPU realisation:
the 1F1B shard_map goes manual over ``pipe`` only; the ``expert`` axis stays under
GSPMD, so the MoE layer's sharding-constraint dispatch inserts the expert
all-to-all INSIDE each stage's forward/backward, and the per-layer load-balancing
aux losses ride the stage scan → pipe psum → microbatch accumulator into the total
loss (``PipeLayer.has_aux`` protocol).

The body unit is a dense+MoE PAIR (the reference's alternating NLG architecture,
``moe_layer_interval=2``) so the stage scan sees a homogeneous parameter stack.
"""

import dataclasses
from typing import Optional

import flax.linen as nn
import jax
import jax.numpy as jnp

from ..runtime.pipe.module import (FlaxPipeLayer, LayerSpec, PipelineModule,
                                   TiedLayerSpec)
from .gpt2 import Block, cross_entropy_loss
from .gpt2_moe import GPT2MoEConfig, MoEBlock
from .gpt2_pipe import GPT2EmbedPipe, GPT2FinalNorm, _tied_head_forward


class MoEPairBlock(nn.Module):
    """One pipeline body unit: dense transformer block followed by an MoE block."""
    config: GPT2MoEConfig

    @nn.compact
    def __call__(self, x, deterministic: bool = True):
        x = Block(self.config, name="dense")(x, deterministic)
        return MoEBlock(self.config, name="moe")(x, deterministic)


class MoEPipeLayer(FlaxPipeLayer):
    """FlaxPipeLayer + the aux protocol: surfaces the MoE blocks' sown
    load-balancing losses as the scalar the 1F1B executor aggregates."""

    has_aux = True
    # expert-weight path components → P(pipe, expert, ...) in param_specs
    ep_paths = ("experts",)

    def _rngs(self, rng):
        if rng is None:
            return {}
        return {"dropout": rng, "gating": jax.random.fold_in(rng, 7)}

    def init(self, rng, x):
        rngs = {"params": rng, "dropout": rng, "gating": rng}
        return self.module.init(rngs, x, **self._kwargs(rng))["params"]

    def apply(self, params, x, rng=None):
        y, _ = self.module.apply({"params": params}, x, rngs=self._rngs(rng),
                                 mutable=["losses"], **self._kwargs(rng))
        return y

    def apply_with_aux(self, params, x, rng=None):
        y, mut = self.module.apply({"params": params}, x, rngs=self._rngs(rng),
                                   mutable=["losses"], **self._kwargs(rng))
        leaves = jax.tree_util.tree_leaves(mut.get("losses", {}))
        aux = (jnp.sum(jnp.stack([jnp.sum(a) for a in leaves]))
               if leaves else jnp.float32(0.0))
        return y, aux.astype(jnp.float32)


def _pair_layer(cfg):
    return MoEPipeLayer(MoEPairBlock(cfg), deterministic_kwarg=True)


def _embed_layer(cfg):
    return FlaxPipeLayer(GPT2EmbedPipe(cfg), deterministic_kwarg=True)


def _norm_layer(cfg):
    return FlaxPipeLayer(GPT2FinalNorm(cfg), deterministic_kwarg=True)


def gpt2_moe_pipeline_module(config: GPT2MoEConfig, num_stages: int,
                             sample_seq_len: Optional[int] = None,
                             sample_batch_size: int = 1,
                             activation_checkpoint_interval: int = 1,
                             partition_method: str = "uniform") -> PipelineModule:
    """Alternating dense/MoE GPT-2 as a pipeline (``n_layer`` transformer layers =
    ``n_layer/2`` dense+MoE pair units; requires ``moe_layer_interval == 2`` and
    even ``n_layer``)."""
    if not (config.moe_layer_interval == 2):
        raise AssertionError("the pipelined MoE body pairs one dense with one MoE block " \
        f"(moe_layer_interval=2); got interval {config.moe_layer_interval}")
    if not (config.n_layer % 2 == 0):
        raise AssertionError("n_layer must be even (dense+MoE pairs)")
    if config.moe_token_axes:
        # body layers run inside the pipe's manual shard_map where data/fsdp/seq are
        # manual axes — a GSPMD sharding constraint naming them would be an error
        config = dataclasses.replace(config, moe_token_axes=())
    t = sample_seq_len or config.n_positions
    sample = jnp.zeros((sample_batch_size, t), dtype=jnp.int32)
    layers = [
        TiedLayerSpec("embed", _embed_layer, config),
        *[LayerSpec(_pair_layer, config) for _ in range(config.n_layer // 2)],
        LayerSpec(_norm_layer, config),
        TiedLayerSpec("embed", _embed_layer, config, forward_fn=_tied_head_forward),
    ]
    return PipelineModule(
        layers=layers,
        num_stages=num_stages,
        loss_fn=cross_entropy_loss,
        sample_input=sample,
        partition_method=partition_method,
        activation_checkpoint_interval=activation_checkpoint_interval,
        aux_loss_coef=config.moe_loss_coef,
    )
