"""Model contract for the training engine.

The reference engine wraps a ``torch.nn.Module`` whose ``forward`` returns the loss
(``runtime/engine.py:1781``). The JAX engine needs the functional equivalent: a pure
``loss_fn(params, batch, rng) -> loss`` plus a parameter initialiser. :class:`Model` bundles
those, with optional metadata the engine exploits:

- ``param_specs``: pytree of PartitionSpec declaring tensor/pipeline sharding of parameters
  (merged with ZeRO's fsdp sharding by ``runtime/zero/partition.py``).
- ``apply_fn``: inference forward (logits), used by the inference engine.
- ``flops_per_sample``: fed to the throughput timer / flops profiler.
"""

import dataclasses
from typing import Any, Callable, List, Optional, Tuple


@dataclasses.dataclass
class Segment:
    """One host-streamable slice of a model (ZeRO-3 parameter offload unit).

    The reference fetches parameters per-submodule under autograd hooks
    (``zero/partitioned_param_coordinator.py:239``); the JAX equivalent is an explicit
    sequential decomposition: the engine streams one segment's parameters HBM-ward at a
    time, runs its forward (and later its VJP with segment-granular rematerialisation),
    and lets the previous segment's buffers die. ``kind`` fixes the apply signature:

    - ``first``: ``apply_fn(params, batch, rng) -> carry``
    - ``mid``:   ``apply_fn(params, carry, batch, rng) -> carry``
    - ``last``:  ``apply_fn(params, carry, batch, rng) -> scalar loss``

    ``params`` arrives as a TUPLE of subtrees ordered like ``param_keys`` (not a dict
    keyed by name): equally-shaped segments then present identical pytree structures to
    ``jax.jit``, so e.g. every interior layer group of a uniform transformer shares ONE
    compiled forward and ONE compiled VJP regardless of depth.

    ``param_keys`` are the top-level parameter-tree keys the segment consumes;
    ``init_keys`` the (sub)set it materialises in ``init_fn`` — keys shared with an
    earlier segment (tied embeddings) appear in ``param_keys`` only.
    """
    name: str
    kind: str                      # "first" | "mid" | "last"
    param_keys: Tuple[str, ...]
    init_keys: Tuple[str, ...]
    init_fn: Callable              # (rng) -> {key: subtree} for init_keys
    apply_fn: Callable


@dataclasses.dataclass
class Model:
    loss_fn: Callable  # (params, batch, rng) -> loss  or  (loss, aux)
    init_fn: Callable  # (rng) -> params
    apply_fn: Optional[Callable] = None  # (params, batch, rng) -> outputs
    param_specs: Any = None
    flops_per_sample: Optional[float] = None
    name: str = "model"
    # ZeRO-3 param-offload decomposition (None: model does not support offload_param)
    segments: Optional[List[Segment]] = None

    def init(self, rng):
        return self.init_fn(rng)


def from_flax(module, sample_batch, loss_fn: Optional[Callable] = None,
              rng_collections=("dropout",), name: Optional[str] = None,
              param_specs: Any = None, flops_per_sample: Optional[float] = None) -> Model:
    """Adapt a ``flax.linen`` module to :class:`Model`.

    By default the module's ``__call__(batch, ...)`` must return the scalar loss (mirroring the
    reference's nn.Module contract); pass ``loss_fn(logits_or_outputs, batch)`` to compute loss
    from outputs instead.
    """
    import jax

    def init_fn(rng):
        init_rngs = {"params": rng}
        for c in rng_collections:
            rng, sub = jax.random.split(rng)
            init_rngs[c] = sub
        return module.init(init_rngs, sample_batch)["params"]

    def full_loss(params, batch, rng):
        rngs = {}
        for i, c in enumerate(rng_collections):
            rngs[c] = jax.random.fold_in(rng, i)
        out = module.apply({"params": params}, batch, rngs=rngs)
        if loss_fn is not None:
            return loss_fn(out, batch)
        return out

    def apply_fn(params, batch, rng=None):
        rngs = {}
        if rng is not None:
            for i, c in enumerate(rng_collections):
                rngs[c] = jax.random.fold_in(rng, i)
        return module.apply({"params": params}, batch, rngs=rngs)

    return Model(loss_fn=full_loss, init_fn=init_fn, apply_fn=apply_fn,
                 param_specs=param_specs, flops_per_sample=flops_per_sample,
                 name=name or type(module).__name__)
