"""Bidirectional encoder family (BERT / DistilBERT) for injected inference.

Reference: the encoder injection containers
(``deepspeed/module_inject/containers/bert.py:1``, ``distil_bert.py:1``) — the
non-generative half of the reference's inference-kernel surface. TPU-native
redesign mirrors :mod:`causal_lm`: ONE configurable post-LN encoder covers the
family; per-family constructors pin the knobs; per-family policies in
``module_inject`` map HF weights onto it with output parity.

Encoders serve whole sequences in one forward (no KV cache / generation), so the
serving path is a TP-sharded jitted ``forward`` — flash attention is available
but full-sequence bidirectional attention on short encoder inputs is already
MXU-friendly under plain XLA.
"""

import dataclasses
from functools import partial
from typing import Any, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P


@dataclasses.dataclass
class EncoderConfig:
    vocab_size: int = 30522
    max_seq_len: int = 512
    type_vocab_size: int = 2          # 0 → no token-type embeddings (DistilBERT)
    n_embd: int = 768
    n_layer: int = 12
    n_head: int = 12
    d_ff: Optional[int] = None        # None → 4*n_embd
    activation: str = "gelu"
    ln_eps: float = 1e-12
    pooler: bool = True               # BERT pooler head; DistilBERT has none
    dtype: Any = jnp.float32
    init_std: float = 0.02
    name: str = "encoder"

    @property
    def head_dim(self) -> int:
        return self.n_embd // self.n_head

    @property
    def ffn_dim(self) -> int:
        return self.d_ff or 4 * self.n_embd

    def num_params(self) -> int:
        d, L, f = self.n_embd, self.n_layer, self.ffn_dim
        emb = (self.vocab_size + self.max_seq_len + self.type_vocab_size) * d
        layer = 4 * d * d + 2 * d * f
        return emb + L * layer + (d * d if self.pooler else 0)


def bert_cfg(**kw) -> EncoderConfig:
    kw.setdefault("name", "bert")
    return EncoderConfig(**kw)


def distilbert_cfg(**kw) -> EncoderConfig:
    kw.setdefault("type_vocab_size", 0)
    kw.setdefault("pooler", False)
    kw.setdefault("name", "distilbert")
    return EncoderConfig(**kw)


def _act(cfg: EncoderConfig):
    return {"gelu": partial(nn.gelu, approximate=False), "relu": nn.relu}[
        cfg.activation]


class EncoderLayer(nn.Module):
    """Post-LN transformer encoder layer (BERT layout: residual then LayerNorm)."""
    config: EncoderConfig

    @nn.compact
    def __call__(self, x, mask_bias):
        cfg = self.config
        b, t, d = x.shape
        h = cfg.n_head
        hd = cfg.head_dim
        init = nn.initializers.normal(cfg.init_std)
        q = nn.Dense(d, dtype=cfg.dtype, kernel_init=init, name="q_proj")(x)
        k = nn.Dense(d, dtype=cfg.dtype, kernel_init=init, name="k_proj")(x)
        v = nn.Dense(d, dtype=cfg.dtype, kernel_init=init, name="v_proj")(x)
        q = q.reshape(b, t, h, hd)
        k = k.reshape(b, t, h, hd)
        v = v.reshape(b, t, h, hd)
        logits = jnp.einsum("bthd,bshd->bhts", q, k).astype(jnp.float32)
        logits = logits / np.sqrt(hd) + mask_bias
        probs = jax.nn.softmax(logits, axis=-1).astype(v.dtype)
        o = jnp.einsum("bhts,bshd->bthd", probs, v).reshape(b, t, d)
        o = nn.Dense(d, dtype=cfg.dtype, kernel_init=init, name="o_proj")(o)
        x = nn.LayerNorm(epsilon=cfg.ln_eps, dtype=jnp.float32,
                         name="ln_attn")(x + o).astype(cfg.dtype)

        hmid = nn.Dense(cfg.ffn_dim, dtype=cfg.dtype, kernel_init=init,
                        name="fc_in")(x)
        hmid = _act(cfg)(hmid)
        y = nn.Dense(d, dtype=cfg.dtype, kernel_init=init, name="fc_out")(hmid)
        return nn.LayerNorm(epsilon=cfg.ln_eps, dtype=jnp.float32,
                            name="ln_mlp")(x + y).astype(cfg.dtype)


class EncoderLM(nn.Module):
    config: EncoderConfig

    @nn.compact
    def __call__(self, input_ids, attention_mask=None, token_type_ids=None):
        """Returns ``(last_hidden_state, pooler_output or None)``.

        ``attention_mask``: HF-style (b, t) 1/0 — 0 keys are masked out for every
        query (additive -inf bias, the HF ``get_extended_attention_mask``)."""
        cfg = self.config
        b, t = input_ids.shape
        init = nn.initializers.normal(cfg.init_std)
        wte = self.param("wte", init, (cfg.vocab_size, cfg.n_embd), jnp.float32)
        wpe = self.param("wpe", init, (cfg.max_seq_len, cfg.n_embd), jnp.float32)
        x = wte[input_ids] + wpe[:t][None]
        if cfg.type_vocab_size > 0:
            tte = self.param("tte", init, (cfg.type_vocab_size, cfg.n_embd),
                             jnp.float32)
            if token_type_ids is None:
                token_type_ids = jnp.zeros_like(input_ids)
            x = x + tte[token_type_ids]
        x = nn.LayerNorm(epsilon=cfg.ln_eps, dtype=jnp.float32,
                         name="ln_embed")(x).astype(cfg.dtype)

        if attention_mask is None:
            mask_bias = jnp.zeros((b, 1, 1, t), jnp.float32)
        else:
            mask_bias = jnp.where(attention_mask[:, None, None, :].astype(bool),
                                  0.0, -1e30).astype(jnp.float32)
        for i in range(cfg.n_layer):
            x = EncoderLayer(cfg, name=f"layers_{i}")(x, mask_bias)

        pooled = None
        if cfg.pooler:
            pooled = jnp.tanh(nn.Dense(cfg.n_embd, dtype=cfg.dtype,
                                       kernel_init=init,
                                       name="pooler")(x[:, 0]))
        return x, pooled


def encoder_param_specs(params, tensor_axis: str = "tensor") -> Any:
    """Megatron TP rules for :class:`EncoderLM` (same classification the CausalLM
    serving path uses: q/k/v/fc_in column-parallel, o/fc_out row-parallel)."""
    col = ("q_proj", "k_proj", "v_proj", "fc_in")
    row = ("o_proj", "fc_out")

    def spec_for(path_str: str, ndim: int):
        if any(f"/{n}/" in path_str for n in col):
            if path_str.endswith("kernel"):
                return P(None, tensor_axis)
            return P(tensor_axis)
        if any(f"/{n}/" in path_str for n in row):
            if path_str.endswith("kernel"):
                return P(tensor_axis, None)
            return P(*([None] * ndim)) if ndim else P()
        return P(*([None] * ndim)) if ndim else P()

    flat, treedef = jax.tree_util.tree_flatten_with_path(params)
    specs = []
    for path, leaf in flat:
        path_str = "/".join(str(getattr(kk, "key", getattr(kk, "idx", kk)))
                            for kk in path)
        specs.append(spec_for(path_str, getattr(leaf, "ndim", 0)))
    return jax.tree_util.tree_unflatten(treedef, specs)
