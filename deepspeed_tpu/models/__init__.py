from .base import Model, from_flax
from .causal_lm import (FAMILIES, CausalLM, CausalLMConfig, bloom_cfg, causal_lm_model,
                        causal_lm_param_specs, gpt2_cfg, gptj_cfg, gptneox_cfg,
                        init_cache, llama_cfg, opt_cfg)
from .gpt2 import (GPT2, GPT2Config, GPT2_PRESETS, cross_entropy_loss, gpt2_config,
                   gpt2_model, gpt2_param_specs)
from .gpt2_moe import GPT2MoE, GPT2MoEConfig, gpt2_moe_model, gpt2_moe_param_specs
from .gpt2_pipe import gpt2_pipeline_module
from .diffusion import (CLIPTextConfig, CLIPTextEncoder, UNet2DCondition,
                        UNetConfig, VAEConfig, VAEDecoder)
