"""Real experiment runner for the subprocess autotuner lane.

The reference tuner launches actual training jobs per experiment
(``deepspeed/autotuning/autotuner.py:39`` via ``launcher/runner.py:351``); this is
the TPU equivalent: a fresh process (own XLA backend, own HBM — a config that
OOMs kills only this experiment) that builds a REAL engine from the merged
config, times train steps on synthetic data, and writes the scheduler-protocol
result JSON.

The base config carries a ``model`` block telling the runner what to build::

    "model": {
        "factory": "deepspeed_tpu.models:gpt2_model",      # module:callable
        "config_class": "deepspeed_tpu.models:GPT2Config",
        "config": {"vocab_size": 50304, "n_layer": 12, ...},  # class kwargs
        "sample_seq_len": 1024,
        "measure_steps": 20,                                # timed steps
        "warmup_steps": 3,
    }

Override keys are dotted paths into the merged config; ``model.config.*`` keys
therefore tune MODEL knobs (remat policy, attention impl, flash block sizes)
alongside engine knobs (micro batch, zero stage) in one space. Invoke as
``python -m deepspeed_tpu.autotuning.runner --config f --overrides f --out f``
(set ``autotuning.experiment_runner: "deepspeed_tpu.autotuning.runner"``).
"""

import argparse
import importlib
import json
import time


def _resolve(spec: str):
    mod, _, attr = spec.partition(":")
    return getattr(importlib.import_module(mod), attr)


def run_experiment(config: dict, overrides: dict) -> dict:
    from .autotuner import apply_overrides

    merged = apply_overrides(config, overrides)
    merged.pop("autotuning", None)
    model_spec = merged.pop("model", None)
    if not model_spec:
        raise ValueError(
            "runner config needs a 'model' block "
            "({factory, config_class, config, sample_seq_len})")

    import jax
    import numpy as np

    import deepspeed_tpu as ds

    factory = _resolve(model_spec["factory"])
    cfg_cls = _resolve(model_spec["config_class"])
    model_cfg = cfg_cls(**model_spec.get("config", {}))
    seq = int(model_spec.get("sample_seq_len",
                             getattr(model_cfg, "n_positions", 1024)))
    model = factory(model_cfg, sample_seq_len=seq)

    engine, _, _, _ = ds.initialize(model=model, config=merged)
    batch_size = engine.train_batch_size()
    vocab = int(getattr(model_cfg, "vocab_size", 32000))
    rng = np.random.default_rng(0)
    batch = {"input_ids": rng.integers(0, vocab, size=(batch_size, seq),
                                       dtype=np.int32)}

    # ≥1 warmup binds `loss` for the sync below; ≥1 measured step for dt/steps
    warmup = max(1, int(model_spec.get("warmup_steps", 3)))
    steps = max(1, int(model_spec.get("measure_steps", 20)))
    for _ in range(warmup):
        loss = engine.train_batch(batch)
    float(loss)                                   # sync: exclude compile/warmup
    t0 = time.perf_counter()
    for _ in range(steps):
        loss = engine.train_batch(batch)
    final_loss = float(loss)                      # sync: all steps retired
    dt = time.perf_counter() - t0

    tokens_per_s = batch_size * seq * steps / dt
    n_params = model_cfg.num_params() if hasattr(model_cfg, "num_params") else 0
    return {"status": "ok",
            "latency_s": dt / steps,
            "throughput": tokens_per_s,
            "flops": 6.0 * n_params * tokens_per_s,   # fwd+bwd estimate
            "loss": final_loss,
            "batch_size": batch_size,
            "devices": jax.device_count()}


def main():
    import os

    # honor the caller's platform choice even under site hooks that pin another
    # platform regardless of JAX_PLATFORMS (config.update after import is the
    # only reliable override — same recipe as tests/conftest.py)
    plat = os.environ.get("JAX_PLATFORMS")
    if plat and "," not in plat:
        import jax
        jax.config.update("jax_platforms", plat)

    p = argparse.ArgumentParser()
    p.add_argument("--config", required=True)
    p.add_argument("--overrides", required=True)
    p.add_argument("--out", required=True)
    args = p.parse_args()
    with open(args.config) as f:
        config = json.load(f)
    with open(args.overrides) as f:
        overrides = json.load(f)
    result = run_experiment(config, overrides)
    with open(args.out, "w") as f:
        json.dump(result, f)


if __name__ == "__main__":
    main()
