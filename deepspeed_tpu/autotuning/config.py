"""Autotuning configuration.

Behavioural equivalent of reference ``deepspeed/autotuning/config.py``
(``DeepSpeedAutotuningConfig``): same JSON keys under ``"autotuning"``.
"""

from typing import Any, Dict, List, Optional

from pydantic import Field, field_validator, model_validator

from ..config.config_utils import ConfigModel

METRIC_LATENCY = "latency"
METRIC_THROUGHPUT = "throughput"
METRIC_FLOPS = "flops"

TUNER_GRIDSEARCH = "gridsearch"
TUNER_RANDOM = "random"
TUNER_MODELBASED = "model_based"


class AutotuningConfig(ConfigModel):
    enabled: bool = False
    fast: bool = True                     # micro-batch-only sweep first
    results_dir: str = "autotuning_results"
    exps_dir: str = "autotuning_exps"
    overwrite: bool = True
    start_profile_step: int = Field(3, ge=0)
    end_profile_step: int = Field(5, gt=0)
    metric: str = METRIC_THROUGHPUT       # latency | throughput | flops
    tuner_type: str = TUNER_GRIDSEARCH
    tuner_early_stopping: int = Field(5, gt=0)
    tuner_num_trials: int = Field(50, gt=0)
    max_train_batch_size: Optional[int] = None
    min_train_batch_size: int = Field(1, gt=0)
    max_train_micro_batch_size_per_gpu: Optional[int] = None
    min_train_micro_batch_size_per_gpu: int = Field(1, gt=0)
    num_tuning_micro_batch_sizes: int = Field(3, gt=0)
    mp_size: int = Field(1, gt=0)
    # tuning-space overrides with DOTTED flat keys mapping to candidate value lists,
    # e.g. {"zero_optimization.stage": [0, 1, 3]}
    tuning_space: Dict[str, Any] = Field(default_factory=dict)
    # subprocess experiment scheduler (reference autotuning/scheduler.py
    # ResourceManager): a runner MODULE name switches trials from in-process to
    # crash-isolated subprocesses run max_parallel at a time (see scheduler.py)
    experiment_runner: Optional[str] = None
    experiment_timeout_s: float = Field(600.0, gt=0)
    max_parallel_experiments: int = Field(1, gt=0)
    # reference "model_info" block: {"num_params": N} enables memory pruning in
    # subprocess mode without an in-process profile engine build
    model_info: Dict[str, Any] = Field(default_factory=dict)

    @field_validator("metric")
    @classmethod
    def _valid_metric(cls, v):
        if v not in (METRIC_LATENCY, METRIC_THROUGHPUT, METRIC_FLOPS):
            raise ValueError(f"autotuning metric {v!r} must be one of "
                             f"{METRIC_LATENCY}/{METRIC_THROUGHPUT}/{METRIC_FLOPS}")
        return v

    @model_validator(mode="after")
    def _profile_window(self):
        if self.end_profile_step <= self.start_profile_step:
            raise ValueError(
                f"end_profile_step ({self.end_profile_step}) must be > "
                f"start_profile_step ({self.start_profile_step})")
        return self
