"""Autotuning (reference ``deepspeed/autotuning``): search micro-batch/ZeRO/remat
configs by short in-process measured trials."""
from .autotuner import Autotuner, apply_overrides
from .config import AutotuningConfig
from .tuner import GridSearchTuner, ModelBasedTuner, RandomTuner, make_tuner
