"""Experiment scheduler: subprocess-isolated, parallel measured trials.

Behavioural equivalent of reference ``deepspeed/autotuning/scheduler.py``
(``ResourceManager:1``): the reference launches every experiment as a separate
multi-GPU job so a hard failure (OOM-kill, kernel abort) marks ONE experiment
failed instead of killing the tuner, and runs experiments in parallel on disjoint
resources. The in-process ``Autotuner._measure`` path keeps trials cheap on a
single chip but cannot survive hard crashes; this scheduler restores the
reference's isolation/parallelism for multi-host or crash-prone tuning spaces.

Protocol: each experiment runs ``python -m <runner_module> --config <json-file>
--overrides <json-file> --out <json-file>`` in a fresh process (own XLA backend,
own HBM). The runner builds the engine with the overrides merged in, measures a
few steps, and writes ``{"status": "ok", "latency_s": ..., "throughput": ...,
"flops": ...}`` to ``--out``. Missing/partial output, a non-zero exit, or a
timeout mark the experiment failed/timeout. ``slot_envs`` gives each parallel
slot its own environment overlay (e.g. disjoint device sets on a pod).
"""

import json
import os
import subprocess
import sys
import tempfile
import time
from typing import Any, Dict, List, Optional

from ..utils.logging import log_dist, logger


class ExperimentScheduler:
    def __init__(self, runner_module: str, base_config: Dict,
                 results_dir: str = "autotuning_results",
                 timeout_s: float = 600.0, max_parallel: int = 1,
                 slot_envs: Optional[List[Dict[str, str]]] = None,
                 python: Optional[str] = None):
        self.runner_module = runner_module
        self.base_config = dict(base_config)
        self.results_dir = results_dir
        self.timeout_s = float(timeout_s)
        self.max_parallel = max(1, int(max_parallel))
        self.slot_envs = slot_envs or [{}] * self.max_parallel
        if not (len(self.slot_envs) >= self.max_parallel):
            raise AssertionError("need one env overlay per parallel slot")
        self.python = python or sys.executable

    def _launch(self, exp_id: int, overrides: Dict, workdir: str, slot: int):
        cfg_f = os.path.join(workdir, f"exp{exp_id}_config.json")
        ovr_f = os.path.join(workdir, f"exp{exp_id}_overrides.json")
        out_f = os.path.join(workdir, f"exp{exp_id}_result.json")
        # per-experiment log file, NOT a pipe: an undrained pipe fills its buffer
        # and deadlocks a verbose (engine-building) runner into a false timeout
        log_f = os.path.join(self.results_dir, f"exp{exp_id}.log")
        with open(cfg_f, "w") as f:
            json.dump(self.base_config, f)
        with open(ovr_f, "w") as f:
            json.dump(overrides, f)
        env = dict(os.environ)
        env.update(self.slot_envs[slot])
        log_fh = open(log_f, "w")
        proc = subprocess.Popen(
            [self.python, "-m", self.runner_module, "--config", cfg_f,
             "--overrides", ovr_f, "--out", out_f],
            env=env, stdout=log_fh, stderr=subprocess.STDOUT)
        return {"id": exp_id, "overrides": overrides, "proc": proc,
                "out_f": out_f, "log_f": log_f, "log_fh": log_fh,
                "slot": slot, "t0": time.time()}

    def _finish(self, job, timed_out: bool) -> Dict[str, Any]:
        rec: Dict[str, Any] = {"exp": job["overrides"], "exp_id": job["id"],
                               "slot": job["slot"], "log": job["log_f"],
                               "wall_s": round(time.time() - job["t0"], 2)}
        if timed_out:
            job["proc"].kill()
            job["proc"].wait()
        job["log_fh"].close()
        if timed_out:
            rec["status"] = "timeout"
            return rec
        rc = job["proc"].returncode
        if rc == 0 and os.path.isfile(job["out_f"]):
            try:
                with open(job["out_f"]) as f:
                    result = json.load(f)
                rec.update(result)
                rec.setdefault("status", "ok")
                return rec
            except (json.JSONDecodeError, OSError) as e:
                rec["status"] = "failed"
                rec["error"] = f"unreadable result file: {e}"
                return rec
        rec["status"] = "failed"
        rec["returncode"] = rc
        try:
            with open(job["log_f"]) as f:
                rec["error"] = f.read()[-2000:]
        except OSError:
            rec["error"] = ""
        return rec

    def run(self, experiments: List[Dict]) -> List[Dict[str, Any]]:
        """Run every experiment; returns one record per experiment, input order.
        A crashed or timed-out experiment yields a failed/timeout record and the
        scheduler continues — the reference resource manager's contract."""
        os.makedirs(self.results_dir, exist_ok=True)
        records: List[Optional[Dict]] = [None] * len(experiments)
        with tempfile.TemporaryDirectory(dir=self.results_dir) as workdir:
            pending = list(enumerate(experiments))
            running: List[Dict] = []
            free_slots = list(range(self.max_parallel))
            while pending or running:
                while pending and free_slots:
                    exp_id, ovr = pending.pop(0)
                    slot = free_slots.pop(0)
                    running.append(self._launch(exp_id, ovr, workdir, slot))
                    log_dist(f"[scheduler] exp {exp_id} {ovr} -> slot {slot}",
                             ranks=[0])
                time.sleep(0.05)
                still = []
                for job in running:
                    rc = job["proc"].poll()
                    timed_out = (rc is None and
                                 time.time() - job["t0"] > self.timeout_s)
                    if rc is None and not timed_out:
                        still.append(job)
                        continue
                    rec = self._finish(job, timed_out)
                    records[job["id"]] = rec
                    free_slots.append(job["slot"])
                    if rec["status"] != "ok":
                        logger.warning(f"[scheduler] exp {job['id']} "
                                       f"{rec['status']}: "
                                       f"{rec.get('error', '')[:200]}")
                running = still
        return [r for r in records if r is not None]
