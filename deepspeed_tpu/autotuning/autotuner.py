"""Autotuner: find the fastest feasible (micro-batch, ZeRO stage, remat) config.

Behavioural equivalent of reference ``deepspeed/autotuning/autotuner.py``
(``Autotuner:39``, 2.8k LoC with a subprocess resource manager): profile the model,
prune the tuning space against device memory, run short measured trials for the
surviving candidates, and report the best config + records.

TPU-native redesign: the reference must launch every experiment as a fresh multi-GPU
job through the launcher; under single-controller JAX an experiment is just
"build engine → run a few steps → read the throughput timer", all in-process. OOMs
surface as XLA ``RESOURCE_EXHAUSTED`` errors and mark the config infeasible, exactly
like the reference's failed experiments. Memory pruning uses the same arithmetic the
reference's ``model_info`` path uses: params × (2 bytes weights+grads compute copies +
12 bytes fp32 master+moments / ZeRO shards) + activation footprint ∝ micro batch.
"""

import itertools
import json
import os
import time
from typing import Any, Callable, Dict, List, Optional

import jax

from ..utils.logging import log_dist, logger
from .config import AutotuningConfig
from .tuner import make_tuner

DEFAULT_TUNING_SPACE = {
    "zero_optimization.stage": [0, 1, 2, 3],
}

# metric name → record key; one definition shared by the in-process measure path
# and subprocess best-selection (config.py's validator lists the same names)
METRIC_KEYS = {"latency": "latency_s", "throughput": "throughput",
               "flops": "flops"}


def metric_value(metric: str, record: Dict) -> float:
    """Signed metric for maximisation (latency negated)."""
    v = float(record[METRIC_KEYS[metric]])
    return -v if metric == "latency" else v


class Autotuner:
    """``engine_factory(overrides: dict) -> engine`` builds a fresh engine with the
    candidate config merged in; ``batch_factory(global_batch_size) -> batch`` supplies
    one full train batch of that size (``engine.train_batch`` splits it into gas
    microbatches itself). The separation keeps the tuner model-agnostic (reference
    passes user script args instead)."""

    def __init__(self, base_config: Dict, engine_factory: Callable[[Dict], Any],
                 batch_factory: Callable[[int], Any],
                 autotuning_config: Optional[AutotuningConfig] = None,
                 hbm_bytes: Optional[int] = None):
        self.base_config = dict(base_config)
        self.cfg = autotuning_config or AutotuningConfig(
            **base_config.get("autotuning", {}))
        self.engine_factory = engine_factory
        self.batch_factory = batch_factory
        # subprocess mode must NOT touch jax in the tuner process (acquiring the
        # TPU here would make every isolated runner fail device init) — pruning
        # then needs hbm_bytes passed explicitly
        self.hbm_bytes = hbm_bytes or (
            None if self.cfg.experiment_runner else self._detect_hbm())
        self.records: List[Dict] = []
        self.model_info: Dict[str, Any] = {}

    # ------------------------------------------------------------------ resources
    @staticmethod
    def _detect_hbm() -> Optional[int]:
        try:
            stats = jax.local_devices()[0].memory_stats() or {}
            return stats.get("bytes_limit")
        except Exception:
            return None

    # ------------------------------------------------------------------ profiling
    def _profile_model(self):
        """Param count from one abstract engine build (reference ``model_info``
        profile run)."""
        engine = self.engine_factory({})
        n_params = sum(int(l.size) for l in
                       jax.tree_util.tree_leaves(engine.state.params))
        self.model_info = {"num_params": n_params}
        del engine
        return n_params

    def _n_devices(self) -> int:
        if self.cfg.experiment_runner:
            # stay off jax in the tuner process (see __init__); shard estimates
            # fall back to 1 (conservative: over-estimates per-device bytes)
            return 1
        return jax.device_count()

    def _estimate_bytes(self, overrides: Dict, n_params: int) -> float:
        """Reference ``memory_estimation`` arithmetic: 16 bytes/param (bf16 weight+grad
        + fp32 master+m+v) with the optimizer/master tier divided by ZeRO shards."""
        stage = overrides.get("zero_optimization.stage",
                              self.base_config.get("zero_optimization", {})
                              .get("stage", 0))
        n_dev = self._n_devices()
        shards = n_dev if stage >= 1 else 1
        param_shards = n_dev if stage >= 3 else 1
        fixed = n_params * (4.0 / param_shards + 12.0 / shards)
        micro = overrides.get("train_micro_batch_size_per_gpu", 1)
        act = self.model_info.get("activation_bytes_per_sample", 0) * micro
        return fixed + act

    # ------------------------------------------------------------------ space
    def _micro_batch_candidates(self) -> List[int]:
        lo = self.cfg.min_train_micro_batch_size_per_gpu
        hi = self.cfg.max_train_micro_batch_size_per_gpu or max(lo, 64)
        out = []
        m = max(1, lo)
        while m <= hi:
            out.append(m)
            m *= 2
        return out[-self.cfg.num_tuning_micro_batch_sizes:] if self.cfg.fast \
            else out

    def tuning_space(self) -> List[Dict]:
        """Cartesian product of micro-batch × configured dimension values
        (reference ``_generate_experiments``)."""
        space: Dict[str, List] = {
            "train_micro_batch_size_per_gpu": self._micro_batch_candidates(),
        }
        extra = self.cfg.tuning_space or DEFAULT_TUNING_SPACE
        for key, values in extra.items():
            space[key] = list(values) if isinstance(values, (list, tuple)) \
                else [values]
        keys = sorted(space)
        exps = [dict(zip(keys, combo))
                for combo in itertools.product(*(space[k] for k in keys))]
        return exps

    # ------------------------------------------------------------------ measuring
    def _measure(self, overrides: Dict) -> Optional[float]:
        n_params = self.model_info.get("num_params")
        if n_params and self.hbm_bytes:
            est = self._estimate_bytes(overrides, n_params)
            if est > self.hbm_bytes:
                logger.info(f"[autotuner] prune {overrides}: est "
                            f"{est/1e9:.2f}GB > HBM {self.hbm_bytes/1e9:.2f}GB")
                self.records.append({"exp": overrides, "status": "pruned"})
                return None
        try:
            engine = self.engine_factory(overrides)
            batch = self.batch_factory(engine.train_batch_size())
            warmup = self.cfg.start_profile_step
            steps = self.cfg.end_profile_step
            for _ in range(warmup):
                engine.train_batch(batch)
            jax.block_until_ready(engine.state.params)
            t0 = time.perf_counter()
            for _ in range(warmup, steps):
                engine.train_batch(batch)
            jax.block_until_ready(engine.state.params)
            dt = (time.perf_counter() - t0) / max(1, steps - warmup)
            samples_per_sec = engine.train_batch_size() / dt
            flops = getattr(engine.module, "flops_per_sample", 0) or 0
            record = {"exp": overrides, "status": "ok",
                      "latency_s": dt, "throughput": samples_per_sec,
                      "flops": samples_per_sec * flops}
            self.records.append(record)
            log_dist(f"[autotuner] {overrides} -> {samples_per_sec:.1f} samples/s "
                     f"({dt*1e3:.1f} ms/step)", ranks=[0])
            del engine
            return metric_value(self.cfg.metric, record)
        except Exception as e:  # XLA RESOURCE_EXHAUSTED and friends
            logger.warning(f"[autotuner] {overrides} failed: {e}")
            self.records.append({"exp": overrides, "status": "failed",
                                 "error": str(e)})
            return None

    # ------------------------------------------------------------------ entry
    def _tune_subprocess(self, exps: List[Dict]) -> Optional[Dict]:
        """Crash-isolated parallel trials through the ExperimentScheduler
        (reference ResourceManager). Memory pruning still happens in-process;
        surviving experiments all launch (grid semantics — the scheduler's
        parallelism replaces the sequential tuner strategies)."""
        from .scheduler import ExperimentScheduler
        n_params = self.model_info.get("num_params")
        survivors = []
        for ovr in exps:
            if n_params and self.hbm_bytes and \
                    self._estimate_bytes(ovr, n_params) > self.hbm_bytes:
                self.records.append({"exp": ovr, "status": "pruned"})
                continue
            survivors.append(ovr)
        sched = ExperimentScheduler(
            self.cfg.experiment_runner, self.base_config,
            results_dir=self.cfg.results_dir,
            timeout_s=self.cfg.experiment_timeout_s,
            max_parallel=self.cfg.max_parallel_experiments)
        recs = sched.run(survivors)
        self.records.extend(recs)
        ok = [r for r in recs
              if r.get("status") == "ok" and METRIC_KEYS[self.cfg.metric] in r]
        if not ok:
            return None
        return max(ok, key=lambda r: metric_value(self.cfg.metric, r))["exp"]

    def tune(self) -> Optional[Dict]:
        """Run the search; returns the best overrides dict (reference
        ``Autotuner.tune``) and writes ``results_dir/autotuning_results.json``."""
        if self.cfg.experiment_runner:
            # subprocess mode exists because in-process engine builds may hard-
            # crash — do NOT build the profile engine here either; take the
            # param count from config (reference model_info block) when present,
            # else skip memory pruning and let infeasible configs fail isolated
            self.model_info = dict(self.cfg.model_info or {})
        else:
            self._profile_model()
        exps = self.tuning_space()
        log_dist(f"[autotuner] exploring {len(exps)} configurations "
                 f"({self.cfg.tuner_type})", ranks=[0])
        if self.cfg.experiment_runner:
            if self.cfg.tuner_type != "gridsearch" or \
                    min(self.cfg.tuner_num_trials,
                        self.cfg.tuner_early_stopping) < len(exps):
                log_dist(
                    f"[autotuner] experiment_runner set: tuner_type="
                    f"{self.cfg.tuner_type!r}/tuner_num_trials/"
                    f"tuner_early_stopping are ignored — all "
                    f"{len(exps)} surviving configs launch as a full grid "
                    f"under the subprocess scheduler", ranks=[0])
            best = self._tune_subprocess(exps)
        else:
            tuner = make_tuner(self.cfg.tuner_type, exps, self.cfg.metric)
            best = tuner.tune(self._measure, sample_size=1,
                              n_trials=self.cfg.tuner_num_trials,
                              early_stopping=self.cfg.tuner_early_stopping)
        os.makedirs(self.cfg.results_dir, exist_ok=True)
        out_path = os.path.join(self.cfg.results_dir, "autotuning_results.json")
        with open(out_path, "w") as f:
            json.dump({"best": best, "model_info": self.model_info,
                       "records": self.records}, f, indent=2, default=str)
        log_dist(f"[autotuner] best config: {best} (results at {out_path})",
                 ranks=[0])
        return best


def apply_overrides(config: Dict, overrides: Dict) -> Dict:
    """Merge dotted-key overrides into a nested ds_config copy."""
    import copy
    out = copy.deepcopy(config)
    for key, value in overrides.items():
        node = out
        parts = key.split(".")
        for p in parts[:-1]:
            node = node.setdefault(p, {})
        node[parts[-1]] = value
    # micro batch changes re-derive gas from train_batch_size
    if "train_micro_batch_size_per_gpu" in overrides:
        out.pop("gradient_accumulation_steps", None)
    return out
