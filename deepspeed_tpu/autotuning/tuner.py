"""Experiment tuners: the order in which candidate configs are tried.

Behavioural equivalent of reference ``deepspeed/autotuning/tuner/``
(``GridSearchTuner``, ``RandomTuner``, ``ModelBasedTuner`` — ``base_tuner.py:15``):
each consumes a list of candidate experiments and yields them in its own order;
``tune()`` supports early stopping after ``early_stopping`` non-improving trials.

The model-based tuner replaces the reference's XGBoost cost model with an on-line
nearest-neighbour score over the numeric features of already-measured configs —
dependency-free and adequate for the small spaces the autotuner explores.
"""

import random
from typing import Any, Callable, Dict, List, Optional, Tuple


def _features(exp: Dict) -> List[float]:
    out = []
    for key in sorted(exp):
        v = exp[key]
        if isinstance(v, bool):
            out.append(float(v))
        elif isinstance(v, (int, float)):
            out.append(float(v))
    return out


class BaseTuner:
    """Iterate experiments, measure, keep the best (reference ``base_tuner.py``)."""

    def __init__(self, exps: List[Dict], metric: str = "throughput"):
        self.all_exps = list(exps)
        self.metric = metric
        self.best_exp: Optional[Dict] = None
        self.best_metric_val: float = float("-inf")
        self.records: List[Tuple[Dict, float]] = []

    def next_batch(self, sample_size: int) -> List[Dict]:
        raise NotImplementedError

    def has_next(self) -> bool:
        return bool(self.all_exps)

    def update(self):
        """Hook after each measured batch (model refit etc.)."""

    def tune(self, measure: Callable[[Dict], Optional[float]],
             sample_size: int = 1, n_trials: int = 1000,
             early_stopping: Optional[int] = None) -> Optional[Dict]:
        """Run up to ``n_trials`` experiments; ``measure`` returns the metric value
        (higher is better) or None for an infeasible config."""
        tried = 0
        since_best = 0
        while self.has_next() and tried < n_trials:
            for exp in self.next_batch(sample_size):
                val = measure(exp)
                tried += 1
                if val is not None:
                    self.records.append((exp, val))
                    if val > self.best_metric_val:
                        self.best_metric_val = val
                        self.best_exp = exp
                        since_best = 0
                        continue
                since_best += 1
                if early_stopping and since_best >= early_stopping:
                    return self.best_exp
            self.update()
        return self.best_exp


class GridSearchTuner(BaseTuner):
    def next_batch(self, sample_size: int) -> List[Dict]:
        batch = self.all_exps[:sample_size]
        self.all_exps = self.all_exps[sample_size:]
        return batch


class RandomTuner(BaseTuner):
    def __init__(self, exps, metric="throughput", seed: int = 0):
        super().__init__(exps, metric)
        self._rng = random.Random(seed)

    def next_batch(self, sample_size: int) -> List[Dict]:
        n = min(sample_size, len(self.all_exps))
        picks = self._rng.sample(range(len(self.all_exps)), n)
        batch = [self.all_exps[i] for i in picks]
        for i in sorted(picks, reverse=True):
            self.all_exps.pop(i)
        return batch


class ModelBasedTuner(BaseTuner):
    """Explore-then-exploit: after a few random probes, prefer the unmeasured config
    whose features are closest to the best measured ones (reference
    ``model_based_tuner.py`` capability with a KNN score instead of XGBoost)."""

    def __init__(self, exps, metric="throughput", warmup: int = 2, seed: int = 0):
        super().__init__(exps, metric)
        self.warmup = warmup
        self._rng = random.Random(seed)

    def _score(self, exp: Dict) -> float:
        if not self.records:
            return 0.0
        f = _features(exp)
        num = den = 0.0
        for rec_exp, val in self.records:
            rf = _features(rec_exp)
            d = sum((a - b) ** 2 for a, b in zip(f, rf)) ** 0.5
            w = 1.0 / (1.0 + d)
            num += w * val
            den += w
        return num / den

    def next_batch(self, sample_size: int) -> List[Dict]:
        batch = []
        for _ in range(min(sample_size, len(self.all_exps))):
            if len(self.records) < self.warmup:
                idx = self._rng.randrange(len(self.all_exps))
            else:
                idx = max(range(len(self.all_exps)),
                          key=lambda i: self._score(self.all_exps[i]))
            batch.append(self.all_exps.pop(idx))
        return batch


def make_tuner(tuner_type: str, exps: List[Dict], metric: str) -> BaseTuner:
    if tuner_type == "gridsearch":
        return GridSearchTuner(exps, metric)
    if tuner_type == "random":
        return RandomTuner(exps, metric)
    if tuner_type == "model_based":
        return ModelBasedTuner(exps, metric)
    raise ValueError(f"unknown tuner_type {tuner_type!r}")
