"""deepspeed_tpu — a TPU-native training & inference framework.

Brand-new design with the capability surface of DeepSpeed (reference:
``deepspeed/__init__.py``): ``initialize()`` wraps a model + JSON config into a training engine
with ZeRO-style sharding over a named device mesh; ``init_inference()`` builds a TP-sharded
serving engine. Compute is JAX/XLA/Pallas over `jax.sharding.Mesh`; collectives are
sharding-induced and compiled onto ICI, not process-group calls.
"""

__version__ = "0.1.0"
version = __version__

from . import comm  # noqa: F401
from .comm import init_distributed  # noqa: F401
from .config import DeepSpeedConfig  # noqa: F401
from .parallel import MeshSpec  # noqa: F401
from .utils.logging import logger  # noqa: F401


def initialize(args=None,
               model=None,
               optimizer=None,
               model_parameters=None,
               training_data=None,
               lr_scheduler=None,
               mpu=None,
               dist_init_required=None,
               collate_fn=None,
               config=None,
               config_params=None):
    """Create a training engine. Reference: ``deepspeed/__init__.py:initialize:52``.

    Returns ``(engine, optimizer_handle, dataloader, lr_scheduler_handle)`` like the reference.
    ``model`` is a :class:`deepspeed_tpu.models.Model` (an apply-fn + param pytree pair) or a
    flax module wrapper; see ``runtime/engine.py``.
    """
    from .runtime.engine import DeepSpeedEngine
    from .runtime.pipe.module import PipelineModule

    config = config if config is not None else config_params
    if config is None and args is not None and hasattr(args, "deepspeed_config") \
            and args.deepspeed_config is not None:
        config = args.deepspeed_config
    if not (config is not None):
        raise AssertionError("DeepSpeed requires --deepspeed_config or config=")

    if isinstance(model, PipelineModule):
        from .runtime.pipe.engine import PipelineEngine
        engine = PipelineEngine(args=args, model=model, optimizer=optimizer,
                                model_parameters=model_parameters,
                                training_data=training_data, lr_scheduler=lr_scheduler,
                                mpu=mpu, collate_fn=collate_fn, config=config)
    else:
        engine = DeepSpeedEngine(args=args, model=model, optimizer=optimizer,
                                 model_parameters=model_parameters,
                                 training_data=training_data, lr_scheduler=lr_scheduler,
                                 mpu=mpu, collate_fn=collate_fn, config=config)
    return engine, engine.optimizer, engine.training_dataloader, engine.lr_scheduler


def init_inference(model, config=None, **kwargs):
    """Create an inference engine. Reference: ``deepspeed/__init__.py:init_inference:233``.

    Decoder (CausalLM) models serve through :class:`InferenceEngine` (KV-cache
    generation); encoder models (BERT/DistilBERT configs or HF modules) through
    :class:`EncoderInferenceEngine` (whole-sequence forward) — the reference's
    bert/distil_bert injection containers."""
    from .inference.engine import InferenceEngine
    from .inference.config import DeepSpeedInferenceConfig

    if config is None:
        config = {}
    if isinstance(config, dict):
        config.update({k: v for k, v in kwargs.items() if v is not None})
        config = DeepSpeedInferenceConfig(**config)

    from .models.encoder import EncoderConfig
    is_encoder = isinstance(model, EncoderConfig)
    if not is_encoder:
        try:
            from .module_inject.encoder_policies import is_hf_encoder
            is_encoder = is_hf_encoder(model)
        except ImportError:
            pass
    if is_encoder:
        from .inference.encoder_engine import EncoderInferenceEngine
        return EncoderInferenceEngine(model, config)
    return InferenceEngine(model, config)


def add_config_arguments(parser):
    """Reference ``deepspeed/__init__.py:add_config_arguments`` (``_add_core_arguments:159``)."""
    group = parser.add_argument_group("DeepSpeed", "DeepSpeed configurations")
    group.add_argument("--deepspeed", default=False, action="store_true",
                       help="Enable DeepSpeed (helper flag, parsed for compatibility)")
    group.add_argument("--deepspeed_config", default=None, type=str,
                       help="Path to DeepSpeed json configuration")
    group.add_argument("--deepscale", default=False, action="store_true",
                       help=argparse_suppress())
    group.add_argument("--deepscale_config", default=None, type=str,
                       help=argparse_suppress())
    return parser


def argparse_suppress():
    import argparse
    return argparse.SUPPRESS

from . import inference  # noqa: F401,E402  (init_inference config surface)
from . import moe  # noqa: F401,E402
from .runtime.activation_checkpointing import checkpointing  # noqa: F401,E402
from .profiling.flops_profiler import get_model_profile  # noqa: F401,E402
