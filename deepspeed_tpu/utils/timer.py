"""Wall-clock and throughput timers.

TPU-native analogue of the reference's ``deepspeed/utils/timer.py``
(``SynchronizedWallClockTimer`` / ``ThroughputTimer``). Synchronisation is
``jax.block_until_ready`` on a sentinel instead of ``cuda.synchronize``; everything else is
framework-neutral timing logic.
"""

import time
from collections import OrderedDict
from typing import Dict, List, Optional

from .logging import logger

FORWARD_MICRO_TIMER = "fwd_microstep"
FORWARD_GLOBAL_TIMER = "fwd"
BACKWARD_MICRO_TIMER = "bwd_microstep"
BACKWARD_GLOBAL_TIMER = "bwd"
STEP_MICRO_TIMER = "step_microstep"
STEP_GLOBAL_TIMER = "step"
TRAIN_BATCH_TIMER = "train_batch"


def _sync():
    """Block until all dispatched device work completes (analogue of cuda.synchronize)."""
    try:
        import jax
        # effects_barrier waits for all outstanding async dispatches.
        jax.effects_barrier()
    except Exception:
        pass


class _Timer:
    def __init__(self, name: str):
        self.name = name
        self.started = False
        self._elapsed = 0.0
        self._start_time = 0.0
        self._record = []

    def start(self, sync: bool = False):
        if self.started:
            return
        if sync:
            _sync()
        self._start_time = time.perf_counter()
        self.started = True

    def stop(self, sync: bool = True, record: bool = False):
        if not self.started:
            return
        if sync:
            _sync()
        dt = time.perf_counter() - self._start_time
        self._elapsed += dt
        if record:
            self._record.append(dt)
        self.started = False

    def reset(self):
        self.started = False
        self._elapsed = 0.0

    def elapsed(self, reset: bool = True) -> float:
        """Elapsed time in seconds."""
        was_started = self.started
        if was_started:
            self.stop()
        out = self._elapsed
        if reset:
            self.reset()
        if was_started:
            self.start()
        return out

    def mean(self) -> float:
        return sum(self._record) / len(self._record) if self._record else 0.0


class SynchronizedWallClockTimer:
    """Named-timer registry. Reference: ``utils/timer.py:SynchronizedWallClockTimer``."""

    def __init__(self):
        self.timers: Dict[str, _Timer] = OrderedDict()

    def __call__(self, name: str) -> _Timer:
        if name not in self.timers:
            self.timers[name] = _Timer(name)
        return self.timers[name]

    def has_timer(self, name: str) -> bool:
        return name in self.timers

    @staticmethod
    def memory_usage() -> str:
        try:
            import jax
            stats = jax.local_devices()[0].memory_stats() or {}
            in_use = stats.get("bytes_in_use", 0) / (1024**3)
            peak = stats.get("peak_bytes_in_use", 0) / (1024**3)
            return f"mem in_use={in_use:.2f}GB peak={peak:.2f}GB"
        except Exception:
            return "mem n/a"

    def log(self, names: List[str], normalizer: float = 1.0, reset: bool = True,
            memory_breakdown: bool = False, ranks: Optional[List[int]] = None):
        if not (normalizer > 0.0):
            raise AssertionError('normalizer > 0.0')
        parts = []
        for name in names:
            if name in self.timers:
                ms = self.timers[name].elapsed(reset=reset) * 1000.0 / normalizer
                parts.append(f"{name}: {ms:.2f}ms")
        msg = "time (ms) | " + " | ".join(parts)
        if memory_breakdown:
            msg += " | " + self.memory_usage()
        logger.info(msg)

    def get_mean(self, names: List[str], normalizer: float = 1.0) -> Dict[str, float]:
        return {
            name: self.timers[name].mean() * 1000.0 / normalizer
            for name in names if name in self.timers
        }


class ThroughputTimer:
    """Samples/sec + TFLOPS estimation across steps.

    Reference: ``utils/timer.py:ThroughputTimer``. ``batch_size`` here is the global train batch.
    """

    def __init__(self, batch_size: int, start_step: int = 2, steps_per_output: int = 50,
                 monitor_memory: bool = False, logging_fn=None):
        self.start_time = 0.0
        self.end_time = 0.0
        self.started = False
        self.batch_size = max(1, batch_size)
        self.start_step = start_step
        self.epoch_count = 0
        self.micro_step_count = 0
        self.global_step_count = 0
        self.total_elapsed_time = 0.0
        self.step_elapsed_time = 0.0
        self.steps_per_output = steps_per_output
        self._window_start_step = 0
        self._timed_steps = 0
        self.monitor_memory = monitor_memory
        self.logging = logging_fn or logger.info
        # optional: flops per sample for TFLOPS reporting
        self.flops_per_sample: Optional[float] = None

    def update_epoch_count(self):
        self.epoch_count += 1
        self.micro_step_count = 0

    def start(self):
        """Window-based timing: the per-step ``cuda.synchronize`` the reference does
        (``utils/timer.py``) would stall XLA's async dispatch queue — instead we sync only at
        ``steps_per_output`` window boundaries; the window wall-time divided by window steps is
        the honest per-step time (device work in between stays fully pipelined)."""
        self.started = True
        if self.global_step_count >= self.start_step and self.start_time == 0.0:
            _sync()
            self.start_time = time.perf_counter()
            self._window_start_step = self.global_step_count

    def stop(self, global_step: bool = False, report_speed: bool = True):
        if not self.started:
            return
        self.started = False
        self.micro_step_count += 1
        if global_step:
            self.global_step_count += 1
        if self.start_time > 0 and global_step and \
                self.global_step_count % self.steps_per_output == 0:
            self._close_window()
            if report_speed:
                msg = (f"epoch={self.epoch_count}/micro_step={self.micro_step_count}/"
                       f"global_step={self.global_step_count}, "
                       f"RunningAvgSamplesPerSec={self.avg_samples_per_sec():.2f}, "
                       f"CurrSamplesPerSec={self.batch_size / self.step_elapsed_time:.2f}")
                if self.flops_per_sample:
                    tflops = (self.flops_per_sample * self.batch_size /
                              self.step_elapsed_time) / 1e12
                    msg += f", TFLOPS={tflops:.2f}"
                if self.monitor_memory:
                    msg += ", " + SynchronizedWallClockTimer.memory_usage()
                self.logging(msg)

    def _close_window(self):
        """Sync the device and fold the open timing window into the running totals."""
        _sync()
        self.end_time = time.perf_counter()
        duration = self.end_time - self.start_time
        window_steps = max(1, self.global_step_count - self._window_start_step)
        self.total_elapsed_time += duration
        self._timed_steps += window_steps
        self.step_elapsed_time = duration / window_steps
        self.start_time = 0.0

    def avg_samples_per_sec(self) -> float:
        # Runs shorter than steps_per_output have an open window — close it so short jobs
        # still report a valid average instead of 0.
        if self.start_time > 0 and self.global_step_count > self._window_start_step:
            self._close_window()
        if self._timed_steps > 0 and self.total_elapsed_time > 0:
            return self.batch_size * self._timed_steps / self.total_elapsed_time
        return 0.0
