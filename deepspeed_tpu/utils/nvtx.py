"""Profiler range annotation.

Behavioural equivalent of reference ``deepspeed/utils/nvtx.py`` (``instrument_w_nvtx``)
and the accelerator ``range_push/range_pop`` surface: on TPU the profiler is XLA's —
ranges become ``jax.profiler.TraceAnnotation`` named scopes, visible in TensorBoard's
trace viewer / Perfetto exactly where NVTX ranges land in Nsight.

Two flavours, wired at the PR-10 observability call sites:

- :func:`annotate` — HOST-side ``TraceAnnotation`` around a dispatch (prefill,
  decode chunk, train step): shows as a named range on the host lane of an
  XLA-profiler capture, aligning the device timeline with the wall-clock spans
  ``observability.trace`` records for the same region;
- :func:`named_scope` — IN-GRAPH ``jax.named_scope`` around traced collectives
  (``parallel/overlap.py`` rings, quantized allreduce): the name lands in XLA
  op metadata, so the device ops themselves carry the call-site label.

Both are no-ops cheap enough for hot paths when no profiler is capturing
(``TraceMe`` checks an atomic; ``named_scope`` only exists at trace time).
"""

import functools
import threading
from typing import Callable

import jax


def annotate(name: str):
    """Host-side profiler range (context manager)."""
    return jax.profiler.TraceAnnotation(name)


def named_scope(name: str):
    """Trace-time op-metadata scope for in-graph regions (collectives)."""
    return jax.named_scope(name)


def instrument_w_nvtx(func: Callable) -> Callable:
    """Decorate ``func`` so its execution appears as a named range in profiler traces
    (name kept for reference source compatibility)."""

    @functools.wraps(func)
    def wrapped(*args, **kwargs):
        with jax.profiler.TraceAnnotation(func.__qualname__):
            return func(*args, **kwargs)

    return wrapped


class _RangeStack(threading.local):
    """Thread-local: TraceAnnotation scopes are thread-bound, and the reference's
    range_push/range_pop contract is per-thread."""

    def __init__(self):
        self._stack = []

    def push(self, name: str):
        ann = jax.profiler.TraceAnnotation(name)
        ann.__enter__()
        self._stack.append(ann)

    def pop(self):
        if self._stack:
            self._stack.pop().__exit__(None, None, None)


_ranges = _RangeStack()


def range_push(name: str):
    """Accelerator ``range_push`` (reference ``abstract_accelerator.py:161``)."""
    _ranges.push(name)


def range_pop():
    _ranges.pop()
