"""Deterministic fault injection + retry — the substrate of the fault-tolerance ring.

The reference framework is tested against rank death, preemption, and flaky storage
by running real clusters; this repo's CPU-lane equivalent is a *seedable, in-process
fault registry*: production I/O paths declare named fault points
(``fault_point("ckpt.save")``) that are free when no fault is registered, and tests
arm them with :class:`FaultSpec` to prove recovery behaviour deterministically:

    with inject("ckpt.save", FaultSpec(kind="io_error", max_faults=2)):
        engine.save_checkpoint(...)     # first two shard writes raise OSError

Fault kinds:

- ``io_error`` — raise ``spec.exc_type(spec.message)`` (default OSError): a flaky
  filesystem / object store;
- ``kill`` — ``os.kill(os.getpid(), SIGKILL)``: a preemption landing mid-operation
  (only meaningful in subprocess-driven tests — the process dies for real);
- ``delay`` — sleep ``spec.delay_s``: a slow device, for timeout-path testing.

Probabilistic faults (``prob < 1``) draw from a dedicated seeded RNG so a failing
test replays exactly. All registry state is process-local and reset by
:func:`reset_faults` (tests) — production code never registers faults, so the
hot-path cost is one dict lookup against an empty dict.

Two extensions for multi-process chaos testing:

- **env propagation** — a seeded fault schedule serializes into the
  ``DS_TPU_FAULT_SPEC`` environment variable (:func:`fault_env`) and a spawned
  replica arms it at startup with :func:`apply_fault_env` — so a chaos test can
  inject deterministically into subprocess-hosted replicas whose registry is
  otherwise unreachable;
- **introspection** — :func:`list_fault_points` lists every fault point the
  process has declared (sites seen by ``fault_point``), plus armed/fired counts,
  so harnesses can discover injectable sites instead of hard-coding them.

:func:`retry_with_backoff` is the shared retry policy for every I/O path that can
see transient errors (checkpoint shard writes, manifest reads, NVMe copies):
bounded attempts, exponential backoff, retry only on ``retryable`` exception types.
"""

import builtins
import json
import os
import random
import signal
import threading
import time
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Tuple, Type

from .logging import logger


@dataclass
class FaultSpec:
    """One armed fault at a named site.

    ``after_n`` passes through the first N hits unharmed; ``max_faults`` bounds how
    many times the fault fires (None = every eligible hit); ``prob`` gates each
    eligible hit through the registry's seeded RNG.
    """
    kind: str = "io_error"              # io_error | kill | delay
    prob: float = 1.0
    after_n: int = 0
    max_faults: Optional[int] = None
    exc_type: Type[BaseException] = OSError
    message: str = "injected fault"
    delay_s: float = 0.05

    def __post_init__(self):
        if self.kind not in ("io_error", "kill", "delay"):
            raise ValueError(f"unknown fault kind {self.kind!r} "
                             "(expected io_error | kill | delay)")


@dataclass
class _ArmedFault:
    spec: FaultSpec
    hits: int = 0
    fired: int = 0


class FaultRegistry:
    """Process-local registry of armed faults, keyed by site name."""

    def __init__(self, seed: int = 0):
        self._lock = threading.Lock()
        self._faults: Dict[str, List[_ArmedFault]] = {}
        self._rng = random.Random(seed)
        self._fired: Dict[str, int] = {}
        self._sites: set = set()        # every site ever declared via check()

    def reseed(self, seed: int):
        with self._lock:
            self._rng = random.Random(seed)

    def arm(self, site: str, spec: FaultSpec) -> _ArmedFault:
        armed = _ArmedFault(spec)
        with self._lock:
            self._faults.setdefault(site, []).append(armed)
        return armed

    def disarm(self, site: str, armed: _ArmedFault):
        with self._lock:
            lst = self._faults.get(site, [])
            if armed in lst:
                lst.remove(armed)
            if not lst:
                self._faults.pop(site, None)

    def reset(self):
        with self._lock:
            self._faults.clear()
            self._fired.clear()
            self._rng = random.Random(0)
            # _sites is deliberately kept: declared fault points are a property
            # of the code that ran, not of what a test armed

    def sites(self) -> Dict[str, Dict[str, int]]:
        """Introspection: every known fault point (declared by a ``fault_point``
        call, armed, or fired) → ``{"armed": n_specs, "fired": n}``."""
        with self._lock:
            known = self._sites | set(self._faults) | set(self._fired)
            return {s: {"armed": len(self._faults.get(s, ())),
                        "fired": self._fired.get(s, 0)}
                    for s in sorted(known)}

    def fired(self, site: Optional[str] = None) -> int:
        with self._lock:
            if site is not None:
                return self._fired.get(site, 0)
            return sum(self._fired.values())

    def check(self, site: str):
        """The fault point: decide (under the lock) whether an armed fault fires,
        then act outside the lock. No-op when nothing is armed at ``site``."""
        self._sites.add(site)       # introspection (set.add: GIL-atomic, cheap)
        if not self._faults:        # fast path: injection entirely disabled
            return
        to_fire: Optional[FaultSpec] = None
        with self._lock:
            for armed in self._faults.get(site, ()):
                spec = armed.spec
                armed.hits += 1
                if armed.hits <= spec.after_n:
                    continue
                if spec.max_faults is not None and armed.fired >= spec.max_faults:
                    continue
                if spec.prob < 1.0 and self._rng.random() >= spec.prob:
                    continue
                armed.fired += 1
                self._fired[site] = self._fired.get(site, 0) + 1
                to_fire = spec
                break
        if to_fire is None:
            return
        if to_fire.kind == "delay":
            time.sleep(to_fire.delay_s)
            return
        if to_fire.kind == "kill":
            logger.error(f"[fault] kill injected at {site!r}")
            os.kill(os.getpid(), signal.SIGKILL)
        logger.warning(f"[fault] {to_fire.kind} injected at {site!r}: "
                       f"{to_fire.message}")
        raise to_fire.exc_type(f"{to_fire.message} [site={site}]")


_REGISTRY = FaultRegistry()


def get_registry() -> FaultRegistry:
    return _REGISTRY


def fault_point(site: str):
    """Named fault point — call from production I/O paths. Free when no fault is
    armed (one falsy-dict check)."""
    _REGISTRY.check(site)


class inject:
    """Arm ``spec`` at ``site`` for the scope of the context manager (re-entrant
    and usable as a plain object with ``.arm()/.disarm()`` for subprocess drivers
    that never exit the scope)."""

    def __init__(self, site: str, spec: FaultSpec):
        self.site = site
        self.spec = spec
        self._armed: Optional[_ArmedFault] = None

    def arm(self) -> "inject":
        self._armed = _REGISTRY.arm(self.site, self.spec)
        return self

    def disarm(self):
        if self._armed is not None:
            _REGISTRY.disarm(self.site, self._armed)
            self._armed = None

    @property
    def fired(self) -> int:
        return self._armed.fired if self._armed is not None else 0

    def __enter__(self) -> "inject":
        return self.arm()

    def __exit__(self, *exc):
        self.disarm()
        return False


def faults_fired(site: Optional[str] = None) -> int:
    """How many faults have fired (at ``site``, or in total)."""
    return _REGISTRY.fired(site)


def reset_faults():
    _REGISTRY.reset()


def list_fault_points() -> Dict[str, Dict[str, int]]:
    """Every fault point this process knows about (declared / armed / fired) →
    ``{"armed": n, "fired": n}``. Harness discovery API: chaos specs can target
    real sites instead of hard-coded strings."""
    return _REGISTRY.sites()


# --------------------------------------------------------------- env propagation
#
# The registry is process-local; chaos tests on subprocess-hosted replicas need
# the parent's seeded fault schedule to survive the exec boundary. The contract:
# the parent serializes (site, FaultSpec) pairs + a registry seed into
# DS_TPU_FAULT_SPEC; every spawned entrypoint that wants deterministic injection
# calls apply_fault_env() at startup (deepspeed-serve and the loadgen do).

FAULT_SPEC_ENV = "DS_TPU_FAULT_SPEC"


def _spec_to_dict(spec: FaultSpec) -> Dict:
    return {"kind": spec.kind, "prob": spec.prob, "after_n": spec.after_n,
            "max_faults": spec.max_faults, "exc_type": spec.exc_type.__name__,
            "message": spec.message, "delay_s": spec.delay_s}


def _spec_from_dict(d: Dict) -> FaultSpec:
    exc = getattr(builtins, str(d.get("exc_type", "OSError")), None)
    if not (isinstance(exc, type) and issubclass(exc, BaseException)):
        exc = OSError        # only builtin exception types cross the boundary
    return FaultSpec(kind=d.get("kind", "io_error"),
                     prob=float(d.get("prob", 1.0)),
                     after_n=int(d.get("after_n", 0)),
                     max_faults=(None if d.get("max_faults") is None
                                 else int(d["max_faults"])),
                     exc_type=exc,
                     message=str(d.get("message", "injected fault")),
                     delay_s=float(d.get("delay_s", 0.05)))


def serialize_faults(entries: List[Tuple[str, FaultSpec]], seed: int = 0) -> str:
    """JSON form of a seeded fault schedule, suitable for ``DS_TPU_FAULT_SPEC``."""
    return json.dumps({"seed": int(seed),
                       "faults": [{"site": site, **_spec_to_dict(spec)}
                                  for site, spec in entries]})


def fault_env(entries: List[Tuple[str, FaultSpec]], seed: int = 0
              ) -> Dict[str, str]:
    """``{DS_TPU_FAULT_SPEC: <json>}`` — merge into a child's ``env``."""
    return {FAULT_SPEC_ENV: serialize_faults(entries, seed)}


def apply_fault_env(environ=None) -> int:
    """Arm the fault schedule carried by ``DS_TPU_FAULT_SPEC`` (if any) into this
    process's registry, reseeding its RNG with the schedule's seed. Returns the
    number of faults armed (0 when the variable is unset). Malformed payloads
    raise ``ValueError`` — a chaos run must never silently degrade to fault-free."""
    payload = (environ if environ is not None else os.environ).get(FAULT_SPEC_ENV)
    if not payload:
        return 0
    try:
        data = json.loads(payload)
        entries = [(str(f["site"]), _spec_from_dict(f)) for f in data["faults"]]
    except (KeyError, TypeError, ValueError, json.JSONDecodeError) as e:
        raise ValueError(f"malformed {FAULT_SPEC_ENV}: {e}") from e
    _REGISTRY.reseed(int(data.get("seed", 0)))
    for site, spec in entries:
        _REGISTRY.arm(site, spec)
    logger.info(f"[fault] armed {len(entries)} fault(s) from {FAULT_SPEC_ENV}: "
                f"{[s for s, _ in entries]}")
    return len(entries)


def retry_with_backoff(fn: Callable, retries: int = 3, base_delay: float = 0.05,
                       max_delay: float = 2.0,
                       retryable: Tuple[Type[BaseException], ...] = (OSError,),
                       on_retry: Optional[Callable[[int, BaseException], None]]
                       = None,
                       sleep: Callable[[float], None] = time.sleep):
    """Call ``fn()`` with up to ``retries`` retries on ``retryable`` exceptions,
    exponential backoff between attempts (``base_delay * 2**attempt``, capped at
    ``max_delay``). Non-retryable exceptions propagate immediately; the last
    retryable exception propagates when the budget is exhausted.

    ``on_retry(attempt_index, exc)`` observes each failure before the backoff
    sleep — loggers and test probes hook here.
    """
    attempt = 0
    while True:
        try:
            return fn()
        except retryable as e:
            if attempt >= retries:
                raise
            if on_retry is not None:
                on_retry(attempt, e)
            delay = min(base_delay * (2 ** attempt), max_delay)
            logger.warning(f"[retry] attempt {attempt + 1}/{retries} failed "
                           f"({type(e).__name__}: {e}); retrying in {delay:.2f}s")
            sleep(delay)
            attempt += 1
