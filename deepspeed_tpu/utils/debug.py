"""Safe-mode / race-detection equivalents.

The reference's closest tools (SURVEY §5.2): ZeRO-3 trace-validation (raises when the
forward order diverges between iterations), ``safe_mode`` recomputation checks, and
``CheckOverflow``. On TPU the compiled program cannot race internally — XLA emits one
deterministic schedule — so the analogous hazards are HOST-side: accidental implicit
device↔host transfers breaking the async pipeline, and nondeterminism sneaking in via
unseeded host RNG or donated-buffer reuse. These helpers surface both:

- :func:`set_transfer_guard` arms JAX's transfer guard so implicit transfers raise
  (the transfer analogue of a race detector);
- :func:`validate_determinism` runs a jitted step twice from identical inputs and
  asserts bitwise-equal results — the ``safe_mode`` recomputation check.
"""

from typing import Any, Callable

import jax
import numpy as np

from .logging import logger


def set_transfer_guard(level: str = "disallow"):
    """Arm JAX's transfer guard: ``"allow" | "log" | "disallow"`` (reference safe-mode
    spirit: make silent hazards loud). Affects implicit host↔device transfers only —
    explicit ``device_put``/``device_get`` stay legal."""
    jax.config.update("jax_transfer_guard", level)


class DeterminismError(AssertionError):
    pass


def validate_determinism(step_fn: Callable, *args, n_runs: int = 2,
                         rtol: float = 0.0, atol: float = 0.0) -> Any:
    """Run ``step_fn(*args)`` ``n_runs`` times and assert identical outputs.

    With default tolerances the check is BITWISE (XLA compiles one deterministic
    schedule; divergence means host-side nondeterminism — unseeded rng, donated-buffer
    aliasing, data races in input assembly). Returns the first run's output.

    Note: donated-argument functions cannot be validated this way — pass a non-donating
    wrapper or fresh pytrees per run.
    """
    outs = []
    for i in range(n_runs):
        out = step_fn(*args)
        outs.append(jax.tree_util.tree_map(lambda l: np.asarray(l), out))
    first = outs[0]
    for i, other in enumerate(outs[1:], start=2):
        leaves_a = jax.tree_util.tree_leaves(first)
        leaves_b = jax.tree_util.tree_leaves(other)
        for a, b in zip(leaves_a, leaves_b):
            if rtol == 0.0 and atol == 0.0:
                if not np.array_equal(a, b, equal_nan=True):
                    try:  # bool/int leaves can't subtract; the diff is advisory only
                        detail = f"(max abs diff {np.max(np.abs(a.astype(np.float64) - b.astype(np.float64)))})"
                    except (TypeError, ValueError):
                        detail = f"({np.sum(a != b)} differing elements)"
                    raise DeterminismError(
                        f"run 1 vs run {i}: outputs differ bitwise {detail} — "
                        "host-side nondeterminism (unseeded rng? donated buffer "
                        "reuse?)")
            else:
                np.testing.assert_allclose(a, b, rtol=rtol, atol=atol)
    logger.info(f"determinism validated over {n_runs} runs")
    return outs[0]
