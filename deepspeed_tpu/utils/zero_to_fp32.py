"""Consolidate a (possibly ZeRO-sharded) checkpoint into one fp32 state dict.

Behavioural equivalent of reference ``deepspeed/utils/zero_to_fp32.py`` (the script users
run to turn per-rank ZeRO shards into a plain ``pytorch_model.bin``). Orbax checkpoints
are re-shardable by construction, so "consolidation" is a restore with replicated
(host) sharding followed by a flat fp32 dump — no shard-merging arithmetic needed.

CLI: ``python -m deepspeed_tpu.utils.zero_to_fp32 <checkpoint_dir> <output_file>``
(``checkpoint_dir`` is the engine save dir or a specific ``global_stepN`` inside it).
Output format by extension: ``.npz`` (numpy), ``.pt`` (torch state dict), default npz.
"""

import argparse
import os
import sys
from typing import Any, Dict

import numpy as np

from .logging import logger


def _flatten_params(tree: Any) -> Dict[str, np.ndarray]:
    import jax
    out: Dict[str, np.ndarray] = {}
    for path, leaf in jax.tree_util.tree_flatten_with_path(tree)[0]:
        name = ".".join(str(getattr(p, "key", getattr(p, "idx", p))) for p in path)
        out[name] = np.asarray(leaf, dtype=np.float32)
    return out


def get_fp32_state_dict_from_zero_checkpoint(checkpoint_dir: str,
                                             tag: str = None) -> Dict[str, np.ndarray]:
    """Reference ``get_fp32_state_dict_from_zero_checkpoint``: returns a flat
    name → fp32 numpy array dict of the model parameters."""
    path = checkpoint_dir
    latest = os.path.join(checkpoint_dir, "latest")
    if tag is not None:
        path = os.path.join(checkpoint_dir, tag)
    elif os.path.isfile(latest):
        with open(latest) as f:
            path = os.path.join(checkpoint_dir, f.read().strip())
    state_path = os.path.join(path, "state")
    if not os.path.isdir(state_path):
        raise FileNotFoundError(f"no engine state at {state_path}")
    # Restore with explicit single-device shardings built from checkpoint METADATA —
    # the consolidator typically runs on a different (often 1-device) topology than
    # the training mesh that wrote the checkpoint, so the saved shardings must not
    # be replayed (this is the whole point of consolidation).
    import jax
    import orbax.checkpoint as ocp
    ckptr = ocp.StandardCheckpointer()
    meta = ckptr.metadata(os.path.abspath(state_path))
    host = jax.local_devices()[0]
    sharding = jax.sharding.SingleDeviceSharding(host)

    def abstract(m):
        return jax.ShapeDtypeStruct(m.shape, m.dtype, sharding=sharding)

    is_meta_leaf = lambda x: hasattr(x, "shape") and hasattr(x, "dtype")
    # orbax >= 0.9 wraps the metadata tree in a result object (.item_metadata);
    # older releases return the tree directly
    meta_tree = getattr(meta, "item_metadata", meta)
    params_meta = dict(meta_tree)["params"]
    abstract_params = jax.tree_util.tree_map(abstract, params_meta,
                                             is_leaf=is_meta_leaf)
    restore_args = jax.tree_util.tree_map(
        lambda _: ocp.ArrayRestoreArgs(sharding=sharding), params_meta,
        is_leaf=is_meta_leaf)
    try:
        restore = ocp.args.PyTreeRestore(
            item={"params": abstract_params},
            restore_args={"params": restore_args},
            partial_restore=True)
    except TypeError:
        # orbax < 0.9 has no partial_restore: restore the FULL tree (optimizer
        # state included — transiently costs its host RAM) and select params
        full_abstract = jax.tree_util.tree_map(abstract, dict(meta_tree),
                                               is_leaf=is_meta_leaf)
        full_restore_args = jax.tree_util.tree_map(
            lambda _: ocp.ArrayRestoreArgs(sharding=sharding), dict(meta_tree),
            is_leaf=is_meta_leaf)
        restore = ocp.args.PyTreeRestore(item=full_abstract,
                                         restore_args=full_restore_args)
    with ocp.PyTreeCheckpointer() as tree_ckptr:
        restored = tree_ckptr.restore(os.path.abspath(state_path), args=restore)
    return _flatten_params(restored["params"])


def convert_zero_checkpoint_to_fp32_state_dict(checkpoint_dir: str,
                                               output_file: str,
                                               tag: str = None):
    """Reference ``convert_zero_checkpoint_to_fp32_state_dict``."""
    sd = get_fp32_state_dict_from_zero_checkpoint(checkpoint_dir, tag)
    n_params = sum(int(v.size) for v in sd.values())
    if output_file.endswith(".pt") or output_file.endswith(".bin"):
        import torch
        torch.save({k: torch.from_numpy(np.ascontiguousarray(v))
                    for k, v in sd.items()}, output_file)
    else:
        np.savez(output_file if output_file.endswith(".npz")
                 else output_file + ".npz", **sd)
    logger.info(f"consolidated {len(sd)} tensors / {n_params:,} fp32 params "
                f"-> {output_file}")
    return sd


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        description="Consolidate a deepspeed_tpu checkpoint into one fp32 state dict")
    p.add_argument("checkpoint_dir")
    p.add_argument("output_file", help=".npz (numpy) or .pt/.bin (torch)")
    p.add_argument("--tag", default=None, help="checkpoint tag (default: latest)")
    args = p.parse_args(argv)
    convert_zero_checkpoint_to_fp32_state_dict(args.checkpoint_dir,
                                               args.output_file, args.tag)
    return 0


if __name__ == "__main__":
    sys.exit(main())
