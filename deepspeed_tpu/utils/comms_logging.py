"""Communication op logger with algorithmic/bus bandwidth math.

Behavioural equivalent of reference ``deepspeed/utils/comms_logging.py`` (``CommsLogger:58``,
``calc_bw_log:25``). On TPU, collectives inside jit are scheduled by XLA and invisible to
Python; this logger covers the eager comm facade (checkpoint resharding, host syncs) and is also
fed estimated volumes by the engine for in-graph collectives.
"""

import math
from typing import Dict

from .logging import logger


def get_caller_func(frame_depth: int = 3) -> str:
    import sys
    return sys._getframe(frame_depth).f_code.co_name


def calc_bw_log(comm_op: str, size_bytes: int, duration_s: float, n_ranks: int):
    """Returns (msg_size_bytes, algbw_Gbps, busbw_Gbps).

    Bus-bandwidth correction factors follow the standard ring-collective accounting the
    reference uses: allreduce busbw = algbw * 2(n-1)/n; all_gather/reduce_scatter = (n-1)/n.
    """
    duration_s = max(duration_s, 1e-12)
    n = max(n_ranks, 1)
    if comm_op in ("all_reduce", "allreduce", "all_to_all_single", "all_to_all"):
        tput = size_bytes / duration_s
        busbw = tput * (2 * (n - 1) / n)
    elif comm_op in ("all_gather", "allgather", "all_gather_into_tensor",
                     "reduce_scatter", "reduce_scatter_tensor"):
        size_bytes = size_bytes * n
        tput = size_bytes / duration_s
        busbw = tput * ((n - 1) / n)
    else:  # send/recv/broadcast/reduce/barrier
        tput = size_bytes / duration_s
        busbw = tput
    return size_bytes, tput * 8 / 1e9, busbw * 8 / 1e9


class CommsLogger:
    """Per-op record of counts/volumes/latencies; ``log_all`` prints a summary table."""

    def __init__(self, config=None):
        if config is not None:
            self.enabled = config.enabled
            self.verbose = config.verbose
            self.prof_all = config.prof_all
            self.prof_ops = list(config.prof_ops)
            self.debug = config.debug
        else:
            self.enabled = False
            self.verbose = False
            self.prof_all = True
            self.prof_ops = []
            self.debug = False
        self.comms_dict: Dict[str, Dict[int, list]] = {}

    def configure(self, config):
        self.enabled = config.enabled
        self.verbose = config.verbose
        self.prof_all = config.prof_all
        self.prof_ops = list(config.prof_ops)
        self.debug = config.debug

    def should_profile(self, op_name: str) -> bool:
        if not self.enabled:
            return False
        return self.prof_all or op_name in self.prof_ops

    def append(self, raw_name: str, record_name: str, latency_s: float, msg_size: int,
               n_ranks: int = 1):
        msg_size, algbw, busbw = calc_bw_log(raw_name, msg_size, latency_s, n_ranks)
        rec = self.comms_dict.setdefault(record_name, {})
        if msg_size in rec:
            rec[msg_size][0] += 1
            rec[msg_size][1].append(latency_s)
            rec[msg_size][2].append(algbw)
            rec[msg_size][3].append(busbw)
        else:
            rec[msg_size] = [1, [latency_s], [algbw], [busbw]]
        if self.verbose:
            logger.info(f"comm op: {record_name} | time(ms): {latency_s*1000:.2f} | "
                        f"msg size: {_fmt_size(msg_size)} | algbw(Gbps): {algbw:.2f} | "
                        f"busbw(Gbps): {busbw:.2f}")

    def log_all(self, print_log: bool = True, show_straggler: bool = False):
        lines = [f"{'Comm. Op':<20}{'Message Size':<20}{'Count':<10}"
                 f"{'Total Latency(ms)':<20}{'Avg Latency(ms)':<20}"
                 f"{'tput_avg (Gbps)':<20}{'busbw_avg (Gbps)':<20}"]
        for record_name, sizes in sorted(self.comms_dict.items()):
            lines.append(record_name)
            for size, (count, lats, algs, buss) in sorted(sizes.items()):
                total_lat = sum(lats) * 1000
                avg_lat = total_lat / count
                lines.append(f"{'':<20}{_fmt_size(size):<20}{count:<10}"
                             f"{total_lat:<20.2f}{avg_lat:<20.2f}"
                             f"{sum(algs)/count:<20.2f}{sum(buss)/count:<20.2f}")
        out = "\n".join(lines)
        if print_log:
            logger.info("\n" + out)
        return out


class CollectiveSpans:
    """Trace-time bytes-on-wire accounting for IN-GRAPH collectives by call site.

    XLA-scheduled collectives are invisible to Python timers, but their wire
    volume is a static function of shapes — each decomposed/monolithic call
    site (``parallel/overlap.py``, engine grad sync) records its per-dispatch
    payload when the enclosing computation TRACES. ``summary()`` therefore
    reports per-trace estimates (one record per compiled call site, not per
    step); ``overlap_ratio`` is the fraction of recorded bytes moved by
    overlap-scheduled (chunked ring / pipelined a2a) collectives. Consumed by
    MonitorMaster events and ``bench.py --overlap``.
    """

    def __init__(self):
        self._spans: Dict[str, Dict] = {}

    def reset(self):
        self._spans.clear()

    def record(self, site: str, comm_op: str, size_bytes: int, n_ranks: int,
               overlapped: bool):
        rec = self._spans.setdefault(
            site, {"op": comm_op, "traces": 0, "bytes_per_call": 0,
                   "bytes_total": 0, "n_ranks": n_ranks,
                   "overlapped": bool(overlapped)})
        rec["traces"] += 1
        rec["bytes_per_call"] = int(size_bytes)
        # ACCUMULATE: n_layer traced calls at one site (e.g. every layer's
        # o_proj) must sum, not overwrite, or totals underreport by ~n_layer
        rec["bytes_total"] += int(size_bytes)
        rec["n_ranks"] = int(n_ranks)
        rec["overlapped"] = bool(overlapped)

    def summary(self) -> Dict[str, Dict]:
        return {k: dict(v) for k, v in self._spans.items()}

    def total_bytes(self) -> int:
        return spans_total_bytes(self._spans)

    def overlapped_bytes(self) -> int:
        return spans_overlapped_bytes(self._spans)

    def overlap_ratio(self) -> float:
        return spans_overlap_ratio(self._spans)


def spans_total_bytes(spans: Dict[str, Dict]) -> int:
    return sum(v["bytes_total"] for v in spans.values())


def spans_overlapped_bytes(spans: Dict[str, Dict]) -> int:
    return sum(v["bytes_total"] for v in spans.values() if v["overlapped"])


def spans_overlap_ratio(spans: Dict[str, Dict]) -> float:
    total = spans_total_bytes(spans)
    return (spans_overlapped_bytes(spans) / total) if total else 0.0


collective_spans = CollectiveSpans()


def record_collective(site: str, comm_op: str, size_bytes: int, n_ranks: int,
                      overlapped: bool = False):
    collective_spans.record(site, comm_op, size_bytes, n_ranks, overlapped)


def _fmt_size(num_bytes: float) -> str:
    if num_bytes == 0:
        return "0 B"
    units = ["B", "KB", "MB", "GB", "TB"]
    k = int(math.floor(math.log(max(num_bytes, 1), 1024)))
    k = min(k, len(units) - 1)
    return f"{num_bytes / (1024 ** k):.2f} {units[k]}"
