"""Communication op logger with algorithmic/bus bandwidth math.

Behavioural equivalent of reference ``deepspeed/utils/comms_logging.py`` (``CommsLogger:58``,
``calc_bw_log:25``). On TPU, collectives inside jit are scheduled by XLA and invisible to
Python; this logger covers the eager comm facade (checkpoint resharding, host syncs) and is also
fed estimated volumes by the engine for in-graph collectives.
"""

import math
from typing import Dict

from .logging import logger


def get_caller_func(frame_depth: int = 3) -> str:
    import sys
    return sys._getframe(frame_depth).f_code.co_name


def calc_bw_log(comm_op: str, size_bytes: int, duration_s: float, n_ranks: int):
    """Returns (msg_size_bytes, algbw_Gbps, busbw_Gbps).

    Bus-bandwidth correction factors follow the standard ring-collective accounting the
    reference uses: allreduce busbw = algbw * 2(n-1)/n; all_gather/reduce_scatter = (n-1)/n.
    """
    duration_s = max(duration_s, 1e-12)
    n = max(n_ranks, 1)
    if comm_op in ("all_reduce", "allreduce", "all_to_all_single", "all_to_all"):
        tput = size_bytes / duration_s
        busbw = tput * (2 * (n - 1) / n)
    elif comm_op in ("all_gather", "allgather", "all_gather_into_tensor",
                     "reduce_scatter", "reduce_scatter_tensor"):
        size_bytes = size_bytes * n
        tput = size_bytes / duration_s
        busbw = tput * ((n - 1) / n)
    else:  # send/recv/broadcast/reduce/barrier
        tput = size_bytes / duration_s
        busbw = tput
    return size_bytes, tput * 8 / 1e9, busbw * 8 / 1e9


class CommsLogger:
    """Per-op record of counts/volumes/latencies; ``log_all`` prints a summary table."""

    def __init__(self, config=None):
        if config is not None:
            self.enabled = config.enabled
            self.verbose = config.verbose
            self.prof_all = config.prof_all
            self.prof_ops = list(config.prof_ops)
            self.debug = config.debug
        else:
            self.enabled = False
            self.verbose = False
            self.prof_all = True
            self.prof_ops = []
            self.debug = False
        self.comms_dict: Dict[str, Dict[int, list]] = {}

    def configure(self, config):
        self.enabled = config.enabled
        self.verbose = config.verbose
        self.prof_all = config.prof_all
        self.prof_ops = list(config.prof_ops)
        self.debug = config.debug

    def should_profile(self, op_name: str) -> bool:
        if not self.enabled:
            return False
        return self.prof_all or op_name in self.prof_ops

    def append(self, raw_name: str, record_name: str, latency_s: float, msg_size: int,
               n_ranks: int = 1):
        msg_size, algbw, busbw = calc_bw_log(raw_name, msg_size, latency_s, n_ranks)
        rec = self.comms_dict.setdefault(record_name, {})
        if msg_size in rec:
            rec[msg_size][0] += 1
            rec[msg_size][1].append(latency_s)
            rec[msg_size][2].append(algbw)
            rec[msg_size][3].append(busbw)
        else:
            rec[msg_size] = [1, [latency_s], [algbw], [busbw]]
        if self.verbose:
            logger.info(f"comm op: {record_name} | time(ms): {latency_s*1000:.2f} | "
                        f"msg size: {_fmt_size(msg_size)} | algbw(Gbps): {algbw:.2f} | "
                        f"busbw(Gbps): {busbw:.2f}")

    def log_all(self, print_log: bool = True, show_straggler: bool = False):
        lines = [f"{'Comm. Op':<20}{'Message Size':<20}{'Count':<10}"
                 f"{'Total Latency(ms)':<20}{'Avg Latency(ms)':<20}"
                 f"{'tput_avg (Gbps)':<20}{'busbw_avg (Gbps)':<20}"]
        for record_name, sizes in sorted(self.comms_dict.items()):
            lines.append(record_name)
            for size, (count, lats, algs, buss) in sorted(sizes.items()):
                total_lat = sum(lats) * 1000
                avg_lat = total_lat / count
                lines.append(f"{'':<20}{_fmt_size(size):<20}{count:<10}"
                             f"{total_lat:<20.2f}{avg_lat:<20.2f}"
                             f"{sum(algs)/count:<20.2f}{sum(buss)/count:<20.2f}")
        out = "\n".join(lines)
        if print_log:
            logger.info("\n" + out)
        return out


def _fmt_size(num_bytes: float) -> str:
    if num_bytes == 0:
        return "0 B"
    units = ["B", "KB", "MB", "GB", "TB"]
    k = int(math.floor(math.log(max(num_bytes, 1), 1024)))
    k = min(k, len(units) - 1)
    return f"{num_bytes / (1024 ** k):.2f} {units[k]}"
