from .logging import logger, log_dist, print_rank_0
from .timer import SynchronizedWallClockTimer, ThroughputTimer
from .comms_logging import CommsLogger, calc_bw_log
from .fault_injection import (FaultSpec, fault_point, faults_fired, inject,
                              reset_faults, retry_with_backoff)
