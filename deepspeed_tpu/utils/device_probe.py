"""Hang-proof jax device probe.

``jax.devices()`` on a dead TPU tunnel does not raise — it blocks forever
inside the PJRT client, wedging whatever process asked. Every entry point
that must decide "are real chips reachable?" before touching the backend
(``__graft_entry__.dryrun_multichip``, ``env_report``, ``bench.py
--overlap``) goes through this one probe instead of rolling its own.
"""

import json
import os
import re
import subprocess
import sys

# Child source for the subprocess probe: ONE backend init yields the whole
# inventory, so callers that want detail (env_report) don't pay a second init.
# The child calls back into _inventory_inprocess so both paths share one
# source of truth for the inventory shape.
_INVENTORY_SRC = (
    "import json\n"
    "from deepspeed_tpu.utils.device_probe import _inventory_inprocess\n"
    "print(json.dumps(_inventory_inprocess()))\n")


def _backend_already_initialized() -> bool:
    """True iff jax's backend is live IN THIS PROCESS — checked without
    triggering initialisation (which is the thing that can hang). The
    ``sys.modules`` fast path keeps the probe import-free when the caller
    never touched jax (the shim module itself imports jax)."""
    if "jax" not in sys.modules:
        return False
    from .jax_compat import backend_initialized
    return backend_initialized()


def _inventory_inprocess() -> dict:
    import jax
    devs = jax.devices()
    per = []
    for d in devs[:8]:
        try:
            stats = d.memory_stats() or {}
        except Exception:
            stats = {}
        per.append({"id": d.id, "kind": d.device_kind,
                    "bytes_limit": stats.get("bytes_limit")})
    return {"platform": devs[0].platform, "device_count": len(devs),
            "process_index": jax.process_index(),
            "process_count": jax.process_count(), "devices": per}


def probe_device_inventory(timeout_s: float = 60.0):
    """Device inventory WITHOUT risking a parent-process hang. Decision order:

    1. env says CPU (``JAX_PLATFORMS=cpu``): the in-process probe is safe and
       cheap — use it (backend init here is fine, the caller wants CPU anyway);
    2. backend already initialised in this process: ``jax.devices()`` returns
       the cached client list and cannot hang — use it (a subprocess probe
       here would FAIL on real TPUs, the parent holds the exclusive libtpu
       lock, and misreport a healthy host as dead);
    3. otherwise probe in a THROWAWAY subprocess with a timeout: a hang or
       crash kills the child, never the caller.

    Returns the inventory dict (see ``_INVENTORY_SRC``) or ``None`` when the
    probe timed out/failed — callers treat ``None`` as "no real devices".
    """
    if (os.environ.get("JAX_PLATFORMS", "").strip().lower() == "cpu"
            or _backend_already_initialized()):
        try:
            return _inventory_inprocess()
        except Exception:
            return None
    try:
        # the parent may have deepspeed_tpu importable only via its own
        # sys.path — pin the package root so the child's import cannot miss
        pkg_root = os.path.dirname(os.path.dirname(
            os.path.dirname(os.path.abspath(__file__))))
        env = dict(os.environ)
        env["PYTHONPATH"] = pkg_root + os.pathsep + env.get("PYTHONPATH", "")
        proc = subprocess.run(
            [sys.executable, "-c", _INVENTORY_SRC],
            capture_output=True, text=True, timeout=timeout_s, env=env)
        if proc.returncode == 0:
            return json.loads(proc.stdout.strip().splitlines()[-1])
    except (subprocess.TimeoutExpired, ValueError, IndexError, OSError):
        pass
    return None


def probe_device_count(timeout_s: float = 60.0) -> int:
    """Device count via :func:`probe_device_inventory`; 0 on probe failure,
    which callers treat as "spawn the virtual CPU mesh"."""
    inv = probe_device_inventory(timeout_s)
    return 0 if inv is None else inv["device_count"]


def virtual_cpu_mesh_env(n_devices: int, base_env=None) -> dict:
    """Child-process env pinned to an ``n_devices`` virtual CPU mesh: the
    re-exec recipe shared by ``__graft_entry__.dryrun_multichip`` and
    ``bench.py --overlap`` (strip any existing host-platform flag, pin CPU)."""
    env = dict(os.environ if base_env is None else base_env)
    env["JAX_PLATFORMS"] = "cpu"
    xla = re.sub(r"--xla_force_host_platform_device_count=\d+", "",
                 env.get("XLA_FLAGS", ""))
    env["XLA_FLAGS"] = (
        xla + f" --xla_force_host_platform_device_count={n_devices}").strip()
    return env
