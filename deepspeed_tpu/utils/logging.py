"""Rank-aware logging utilities.

TPU-native analogue of the reference's ``deepspeed/utils/logging.py`` (logger + ``log_dist``):
on TPU pods each host is a JAX process; ``log_dist`` filters by ``jax.process_index()`` instead
of torch.distributed rank.
"""

import logging
import os
import sys
from typing import Iterable, Optional

LOG_LEVELS = {
    "debug": logging.DEBUG,
    "info": logging.INFO,
    "warning": logging.WARNING,
    "error": logging.ERROR,
    "critical": logging.CRITICAL,
}


def _create_logger(name: str = "DeepSpeedTPU", level: int = logging.INFO) -> logging.Logger:
    lg = logging.getLogger(name)
    lg.setLevel(level)
    lg.propagate = False
    if not lg.handlers:
        handler = logging.StreamHandler(stream=sys.stdout)
        handler.setFormatter(
            logging.Formatter("[%(asctime)s] [%(levelname)s] [%(name)s] %(message)s"))
        lg.addHandler(handler)
    return lg


logger = _create_logger(
    level=LOG_LEVELS.get(os.environ.get("DSTPU_LOG_LEVEL", "info").lower(), logging.INFO))


def _process_index() -> int:
    try:
        import jax
        return jax.process_index()
    except Exception:  # jax not initialised yet
        return 0


def log_dist(message: str, ranks: Optional[Iterable[int]] = None, level: int = logging.INFO):
    """Log ``message`` only on the listed process indices (``None`` / ``[-1]`` = all).

    Mirrors reference ``deepspeed/utils/logging.py:log_dist``.
    """
    my_rank = _process_index()
    if ranks is None or -1 in ranks or my_rank in ranks:
        logger.log(level, f"[Rank {my_rank}] {message}")


def print_rank_0(message: str):
    if _process_index() == 0:
        logger.info(message)


def warning_once(message: str, _seen=set()):
    if message not in _seen:
        _seen.add(message)
        logger.warning(message)
