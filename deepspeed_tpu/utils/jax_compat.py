"""JAX API compatibility shims.

The framework targets current JAX surface names; older installed versions spell
some of them differently. Centralising the translation here keeps kernel and
model code on ONE spelling:

- ``shard_map``: ``jax.shard_map(f, mesh=, axis_names=, in_specs=, out_specs=,
  check_vma=)`` (new) vs ``jax.experimental.shard_map.shard_map(f, mesh,
  in_specs, out_specs, check_rep=, auto=)`` (old). ``axis_names`` lists the
  MANUAL axes; the old API takes the complement (``auto``) instead, and calls
  its replication check ``check_rep``.
- ``backend_initialized``: is a jax backend live in THIS process, checked
  without triggering initialisation (which can hang on a dead TPU tunnel).
"""

from typing import Any, Optional, Set

import jax

_NEW_SHARD_MAP = getattr(jax, "shard_map", None)


def shard_map(f, *, mesh, in_specs, out_specs,
              axis_names: Optional[Set[Any]] = None, check_vma: bool = False):
    """New-style ``jax.shard_map`` surface, usable on old JAX too.

    On old JAX the region always runs FULLY manual: partial-auto (non-manual
    axes left auto) lowers through a PartitionId path the SPMD partitioner
    rejects — and on some shapes hard-aborts the process — so spec-unmentioned
    axes are instead treated as replicated through the region (values
    identical; redundant compute on those axes). Bodies that genuinely need an
    auto axis inside the region (sharding constraints over ``expert`` in the
    MoE pipeline body) are unsupported on old JAX and fail loudly at trace.
    """
    if _NEW_SHARD_MAP is not None:
        kwargs = dict(mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                      check_vma=check_vma)
        if axis_names is not None:
            kwargs["axis_names"] = axis_names
        return _NEW_SHARD_MAP(f, **kwargs)
    from jax.experimental.shard_map import shard_map as _old
    return _old(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                check_rep=bool(check_vma))


def backend_initialized() -> bool:
    """True iff a jax backend is already live in this process.

    Reads the memoisation cache that ``xla_bridge.backends()`` populates —
    there is no public "initialised?" predicate (every public surface would
    trigger the initialisation we must avoid). Getting ``False`` wrong is
    HARMFUL (device probes would misreport a live TPU host as dead because a
    subprocess can't take the parent's libtpu lock), so cache-attribute drift
    on a jax upgrade raises instead of guessing.
    """
    try:
        from jax._src import xla_bridge
        cache = xla_bridge._backends
    except (ImportError, AttributeError) as e:
        raise RuntimeError(
            "jax_compat.backend_initialized: jax's backend cache moved "
            f"(installed jax {jax.__version__}) — update this shim") from e
    return bool(cache)
