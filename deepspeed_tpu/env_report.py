"""Environment report — the ``ds_report`` equivalent.

Reference ``deepspeed/env_report.py`` (``main:147``, op-compatibility table): prints
framework/toolchain versions, the device inventory as JAX sees it, and the build status of
the host-side native ops (the TPU analogue of the reference's CUDA op table — device kernels
need no prebuild here, XLA/Pallas compile in-process).
"""

import importlib
import shutil
import subprocess
import sys

GREEN_OK = "\033[92m[OKAY]\033[0m"
RED_NO = "\033[93m[NO]\033[0m"


def _version(mod_name: str) -> str:
    try:
        mod = importlib.import_module(mod_name)
        return getattr(mod, "__version__", "unknown")
    except Exception:
        return "not installed"


def _gxx_version() -> str:
    gxx = shutil.which("g++")
    if not gxx:
        return "not found"
    try:
        out = subprocess.run([gxx, "--version"], capture_output=True, text=True,
                             timeout=10).stdout.splitlines()
        return out[0] if out else "unknown"
    except Exception:
        return "unknown"


def main() -> int:
    print("-" * 70)
    print("deepspeed_tpu environment report (ds_report)")
    print("-" * 70)
    print("versions:")
    for mod in ("jax", "jaxlib", "flax", "optax", "orbax.checkpoint", "numpy",
                "ml_dtypes"):
        print(f"  {mod:<18} {_version(mod)}")
    print(f"  {'python':<18} {sys.version.split()[0]}")
    print(f"  {'g++':<18} {_gxx_version()}")

    print("devices:")
    # a dead TPU tunnel makes jax.devices() BLOCK (not raise) — the probe's
    # throwaway child does the ONE backend init and hands back the whole
    # inventory (in-process short-circuit when env pins CPU or the backend is
    # already live here)
    from .utils.device_probe import probe_device_inventory
    inv = probe_device_inventory()
    if inv is None:
        print("  jax backend unavailable (device probe timed out or "
              "failed — tunnel down?)")
        _print_ops_table()
        return 0
    print(f"  platform={inv['platform']} device_count={inv['device_count']} "
          f"process={inv['process_index']}/{inv['process_count']}")
    for d in inv["devices"]:
        lim = d.get("bytes_limit")
        mem = f" hbm={lim / 1024**3:.1f}GB" if lim else ""
        print(f"  {d['id']}: {d['kind']}{mem}")
    if inv["device_count"] > len(inv["devices"]):
        print(f"  ... and {inv['device_count'] - len(inv['devices'])} more")

    _print_ops_table()
    return 0


def _print_ops_table():
    print("host-side native ops (op_builder):")
    from .ops.adam.cpu_adam import native_available
    print(f"  cpu_adam/cpu_adagrad (SIMD offload step) "
          f"{GREEN_OK if native_available() else RED_NO}")
    try:
        from .runtime.swap_tensor.aio import aio_available
        print(f"  async_io (NVMe swap) {GREEN_OK if aio_available() else RED_NO}")
    except ImportError:
        print(f"  async_io (NVMe swap) {RED_NO}")
    print("-" * 70)


if __name__ == "__main__":
    sys.exit(main())
