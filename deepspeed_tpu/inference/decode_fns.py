"""Shared compiled-step builders for generation: prefill, whole-batch decode loop,
and fixed-shape chunked decode.

This is the factored-out core of ``InferenceEngine._loop_fns``: the single-call
``generate`` path keeps its one-``lax.while_loop``-per-call shape (the XLA analogue
of CUDA-graph replay), while the serving executor composes the same prefill with
:func:`build_decode_chunk` — K fixed steps over a fixed slot-batch, returning to the
host between chunks so the continuous-batching scheduler can admit/retire requests
mid-stream. Both paths share the token-selection closures here, so sampling
semantics cannot drift between them.

Key-stream contract: the batched :func:`make_select_fn` draws ONE key per step for
the whole batch (cheap, but a row's sample depends on its batch position);
:func:`make_slot_select_fn` folds a per-slot ``(seed, step)`` into the base key, so
a request's sampled tokens are a pure function of its own seed and token index —
independent of which KV slot it lands in and of who shares the slot-batch. Serving
needs the latter: continuous batching re-binds requests to slots arbitrarily.
"""

from typing import Any, Callable

import jax
import jax.numpy as jnp

from ..parallel.overlap import overlap_scope


def logits_transform(do_sample: bool, temperature: float, top_k: int,
                     top_p: float) -> Callable[[Any], Any]:
    """Temperature/top-k/top-p masking over ``(b, V)`` logits (sampling only)."""

    def transform(x):
        x = x / jnp.maximum(temperature, 1e-6)
        if top_k and top_k > 0:
            kth = jnp.sort(x, axis=-1)[:, -top_k][:, None]
            x = jnp.where(x < kth, -jnp.inf, x)
        if top_p < 1.0:
            sorted_logits = jnp.sort(x, axis=-1)[:, ::-1]
            probs = jax.nn.softmax(sorted_logits, axis=-1)
            cum = jnp.cumsum(probs, axis=-1)
            cutoff_idx = jnp.sum(cum < top_p, axis=-1, keepdims=True)
            cutoff = jnp.take_along_axis(sorted_logits, cutoff_idx, axis=-1)
            x = jnp.where(x < cutoff, -jnp.inf, x)
        return x

    return transform


def make_select_fn(do_sample: bool, temperature: float, top_k: int, top_p: float):
    """``(b, V)`` logits + one shared key → ``(b, 1)`` tokens (generate path)."""
    transform = logits_transform(do_sample, temperature, top_k, top_p)

    def select(logits, rng):
        if not do_sample:
            return jnp.argmax(logits, axis=-1)[:, None].astype(jnp.int32)
        return jax.random.categorical(rng, transform(logits),
                                      axis=-1)[:, None].astype(jnp.int32)

    return select


def make_slot_select_fn(do_sample: bool, temperature: float, top_k: int,
                        top_p: float):
    """``(S, V)`` logits + per-slot ``(seed, step)`` → ``(S, 1)`` tokens.

    Greedy is slot-independent by construction; sampling folds each slot's seed and
    per-request step counter into the base key so co-batched requests never share a
    key stream.
    """
    transform = logits_transform(do_sample, temperature, top_k, top_p)

    def select(logits, base_key, seeds, steps):
        if not do_sample:
            return jnp.argmax(logits, axis=-1)[:, None].astype(jnp.int32)
        x = transform(logits)

        def one(row, seed, step):
            key = jax.random.fold_in(jax.random.fold_in(base_key, seed), step)
            return jax.random.categorical(key, row)

        return jax.vmap(one)(x, seeds, steps)[:, None].astype(jnp.int32)

    return select


def build_prefill(module, dequant, overlap=None):
    """Prefill: one forward over the (right-padded) prompt, logits read only at each
    sequence's last valid position (``logits_positions`` skips the rest of the head
    matmul), KV written into the fixed cache buffers.

    ``overlap``: the owning engine's ``OverlapConfig`` — installed for the
    duration of the TRACE (``overlap_scope``) so the compiled body bakes in
    that engine's comm-overlap lowering regardless of ambient global state.
    This is ALSO how the fused quantized ring reaches serving: with
    weight-quant row-parallel params AND an active scope, ``quant_dense_apply``
    routes through ``parallel/qring.py`` (intN wire, ``chunk_bits``/
    ``quant_block`` read from this config) instead of the monolithic psum —
    no builder below carries ring-specific code.
    """

    def prefill(params, ids, caches, lens0):
        with overlap_scope(overlap):
            logits, new_caches = module.apply(
                {"params": dequant(params)}, ids, caches=caches,
                cache_lens=jnp.zeros_like(lens0),
                logits_positions=jnp.maximum(lens0 - 1, 0))
        return logits[:, 0], new_caches

    return prefill


def build_prefix_prefill(module, dequant, overlap=None):
    """Suffix prefill at a nonzero cache offset — the prefix-cache hit path.

    ``caches`` arrive with a restored prompt-prefix KV slab in rows
    ``[0, prefix_len)``; the forward runs over the (right-padded) suffix only,
    writes suffix K/V at rows ``prefix_len + i``, attends each suffix token over
    prefix + suffix, and reads logits at the suffix's last valid position. The
    prefix's prefill compute is skipped entirely — a cache hit costs one
    suffix-bucket forward instead of a full-prompt one.
    """

    def prefix_prefill(params, ids, caches, prefix_len, suffix_len):
        b, t = ids.shape
        positions = prefix_len[:, None] + jnp.arange(t)[None]
        with overlap_scope(overlap):
            logits, new_caches = module.apply(
                {"params": dequant(params)}, ids, positions=positions,
                caches=caches, cache_lens=prefix_len,
                logits_positions=jnp.maximum(suffix_len - 1, 0),
                prefix_fill=True)
        return logits[:, 0], new_caches

    return prefix_prefill


def build_spec_verify(module, dequant, overlap=None):
    """Speculative one-pass verify over a slot-batch (dense slot-row caches).

    ``ids (S, t)`` is each slot's verify window ``[cur_tok, draft_0 ..
    draft_{t-2}]``; the forward runs in ``prefix_fill`` mode at cache offset
    ``lens`` — the window's K/V scatter into rows ``lens + j`` and every
    window position attends over committed rows + the in-window prefix
    (``key_pos <= query_pos``), exactly the PR 9 suffix-prefill math. Unlike
    :func:`build_prefix_prefill` the LM head runs at EVERY window position
    (``logits_positions=None``): the accept rule needs the target's
    distribution after each draft prefix.

    Rollback is the caller's job and is free: rows written past the accepted
    prefix stay stale-but-masked (attention masks ``>= cache_len``) and are
    overwritten by later appends — committing is a ``cache_len`` advance,
    rejecting is not advancing. Returns ``(logits (S, t, V), new_caches)``.
    """

    def spec_verify(params, ids, caches, lens):
        b, t = ids.shape
        positions = lens[:, None] + jnp.arange(t)[None]
        with overlap_scope(overlap):
            logits, new_caches = module.apply(
                {"params": dequant(params)}, ids, positions=positions,
                caches=caches, cache_lens=lens,
                logits_positions=None, prefix_fill=True)
        return logits, new_caches

    return spec_verify


def build_paged_spec_verify(module, dequant, kv_cap: int, overlap=None):
    """Paged sibling of :func:`build_spec_verify`: gather each slot's pages to
    the dense view once, run the same ``prefix_fill`` verify forward, then
    mirror ONLY the valid window rows ``[lens, lens + valid)`` of live slots
    back through the page table (the paged chunk's end-of-chunk writeback
    idiom). ``valid (S,)`` is ``spec_len + 1`` — the cur-token row plus the
    real (un-padded) draft rows; pad rows, inactive slots, and rows at/past
    ``kv_cap`` route to the out-of-range page index and the scatter drops
    them, so released or shared pages are never written.

    The mirror is a ``fori_loop`` over the window rows — the loop the
    analysis sweep's dequant pin targets: ``dequant`` collapses the quantized
    params ONCE above it, so int8 payloads must never appear as loop-body
    inputs (the same loop-invariance contract as both decode-chunk bodies).
    """
    from ..ops.paged_attention import gather_kv_dense

    def spec_verify(params, ids, caches, page_table, lens, valid, active):
        # hoisted: dequant once per verify dispatch, never inside the mirror
        params = dequant(params)
        b, t = ids.shape
        ps = caches[0]["k"].shape[2]
        mp = page_table.shape[1]
        P_total = caches[0]["k"].shape[0]
        dense = [dict(zip(("k", "v"),
                          gather_kv_dense(c["k"], c["v"], page_table, kv_cap)))
                 for c in caches]
        positions = lens[:, None] + jnp.arange(t)[None]
        with overlap_scope(overlap):
            logits, dense = module.apply(
                {"params": params}, ids, positions=positions,
                caches=dense, cache_lens=lens,
                logits_positions=None, prefix_fill=True)

        def mirror(j, pages):
            rows = lens + j
            page_pos = jnp.clip(rows // ps, 0, mp - 1)
            pidx = jnp.where(active & (j < valid) & (rows < kv_cap),
                             jnp.take_along_axis(
                                 page_table, page_pos[:, None], axis=1)[:, 0],
                             P_total)
            off = rows % ps
            idx = jnp.minimum(rows, kv_cap - 1)[:, None, None, None]
            out = []
            for c, dn in zip(pages, dense):
                k_new = jnp.take_along_axis(dn["k"], idx, axis=2)[:, :, 0, :]
                v_new = jnp.take_along_axis(dn["v"], idx, axis=2)[:, :, 0, :]
                out.append(
                    {"k": c["k"].at[pidx, :, off, :].set(
                        k_new.astype(c["k"].dtype)),
                     "v": c["v"].at[pidx, :, off, :].set(
                        v_new.astype(c["v"].dtype))})
            return out

        new_caches = jax.lax.fori_loop(0, t, mirror, list(caches))
        return logits, new_caches

    return spec_verify


def build_decode_loop(module, dequant, select, gen_cap: int, overlap=None):
    """Whole-batch run-to-completion decode: ONE ``lax.while_loop`` for all remaining
    tokens, EOS termination as an on-device reduction in the loop condition
    (``InferenceEngine.generate``'s decode shape)."""

    def decode_loop_inner(params, tok0, caches, lens, n_new, eos, rng):
        # HOISTED param prep: on the XLA fallback path ``dequant`` collapses
        # quant nodes here, OUTSIDE the while_loop — the dequantized weights
        # become loop constants, computed once per dispatch instead of per
        # decode step (HLO-pinned: no int8 operands inside the loop body).
        # On the fused path it is the identity and quantized bytes stream
        # from HBM inside each step's projection kernels.
        params = dequant(params)
        b = tok0.shape[0]
        buf = jnp.zeros((b, gen_cap), jnp.int32).at[:, 0].set(tok0[:, 0])
        finished0 = tok0[:, 0] == eos          # eos = -1 when unused: never matches

        def cond(s):
            i, _, _, _, finished, _ = s
            return jnp.logical_and(i < n_new, jnp.logical_not(jnp.all(finished)))

        def body(s):
            i, tok, caches, lens, finished, buf = s
            positions = lens[:, None]
            logits, caches = module.apply(
                {"params": params}, tok, positions=positions,
                caches=caches, cache_lens=lens)
            tok = select(logits[:, -1], jax.random.fold_in(rng, i))
            # finished sequences keep emitting eos (HF pad-with-eos behaviour)
            tok = jnp.where(finished[:, None], jnp.maximum(eos, 0), tok)
            finished = jnp.logical_or(finished, tok[:, 0] == eos)
            buf = buf.at[:, i].set(tok[:, 0])
            return i + 1, tok, caches, lens + 1, finished, buf

        # lens is each sequence's append position: the prompt's true length (generated
        # tokens overwrite right-pad slots in the cache; decode masks by cache_len)
        state = (jnp.int32(1), tok0, caches, lens, finished0, buf)
        n, _, _, _, _, buf = jax.lax.while_loop(cond, body, state)
        return buf, n

    def decode_loop(*args):
        # overlap_scope is a trace-time effect: the while_loop body traces
        # inside it, baking the owning engine's comm-overlap lowering in
        with overlap_scope(overlap):
            return decode_loop_inner(*args)

    return decode_loop


def build_decode_chunk(module, dequant, slot_select, chunk_size: int,
                       overlap=None):
    """Fixed-shape chunked decode over a slot-batch: exactly ``chunk_size`` steps,
    every shape static, one compile per (slots, cap, chunk, sampling) key.

    Per-slot state (all ``(S,)`` unless noted):

    - ``toks (S, 1)``: each slot's last emitted token (the next step's input);
    - ``lens``: the slot's KV append position — advances only while the slot is
      active, so a retired slot's cache rows below ``lens`` stay intact until the
      pool zero-fills it;
    - ``active``: slot holds a live, unfinished request. Inactive slots still flow
      through the batch (fixed shapes) but emit ``max(eos, 0)`` and freeze;
    - ``remaining``: decode-token budget (prefill's first token already spent);
    - ``eos_ids``: per-request EOS (−1 = none, never matches);
    - ``seeds`` / ``steps``: per-request sampling stream coordinates.

    A slot's real tokens in the returned ``buf (S, chunk_size)`` are the prefix of
    length ``steps_out[s] - steps_in[s]`` — active→inactive is one-way inside a
    chunk, so no gaps. The scheduler harvests on the host between chunks.
    """

    def decode_chunk(params, toks, caches, lens, active, remaining, eos_ids,
                     seeds, steps, base_key):
        # hoisted out of the fori_loop body — same loop-invariance contract as
        # build_decode_loop (dequant once per chunk dispatch, not per step)
        params = dequant(params)
        S = toks.shape[0]
        buf = jnp.zeros((S, chunk_size), jnp.int32)

        def body(i, s):
            toks, caches, lens, active, remaining, steps, buf = s
            logits, caches = module.apply(
                {"params": params}, toks, positions=lens[:, None],
                caches=caches, cache_lens=lens)
            nxt = slot_select(logits[:, -1], base_key, seeds, steps)
            tok = jnp.where(active[:, None], nxt,
                            jnp.maximum(eos_ids, 0)[:, None]).astype(jnp.int32)
            buf = buf.at[:, i].set(tok[:, 0])
            remaining = remaining - active.astype(jnp.int32)
            finished = jnp.logical_or(tok[:, 0] == eos_ids, remaining <= 0)
            lens = lens + active.astype(jnp.int32)
            steps = steps + active.astype(jnp.int32)
            active = jnp.logical_and(active, jnp.logical_not(finished))
            return tok, caches, lens, active, remaining, steps, buf

        with overlap_scope(overlap):     # trace-time: fori body traces inside
            toks, caches, lens, active, remaining, steps, buf = jax.lax.fori_loop(
                0, chunk_size, body,
                (toks, caches, lens, active, remaining, steps, buf))
        return buf, toks, caches, lens, active, remaining, steps

    return decode_chunk


def build_paged_decode_chunk(module, dequant, slot_select, chunk_size: int,
                             kv_cap: int, overlap=None, fused: bool = False):
    """Paged sibling of :func:`build_decode_chunk`: the caches are GLOBAL KV
    pages (``{"k": (P, hk, page, d), ...}`` per layer) and each step writes at
    the page-mapped row of the slot's static-shape ``page_table`` row — the
    table itself never changes inside a chunk (pages are bound at admission),
    so it rides as a loop constant. Every shape is static in (slots,
    total-pages, page, chunk): a slot's page COUNT is runtime data in the
    table, so page growth across requests never mints a compile key (pinned by
    the analysis sweep's paged lane).

    ``fused=True`` (TPU / ``DS_TPU_PAGED_FORCE_FUSED=1``): each step attends
    straight against the pages through the Pallas gather-by-page-index kernel
    — the dense view never materialises.

    ``fused=False`` (the XLA fallback): the dense per-slot view is gathered
    ONCE per chunk — hoisted out of the ``fori_loop``, same loop-invariance
    idea as the dequant hoist — and carried through the steps; each step runs
    the EXACT slot-row decode math on the carry (greedy bit-identity with the
    slot pool is then structural, not analytical) and mirrors its appended
    K/V row into the pages so they stay the source of truth across chunks. A
    per-step gather cost S·cap bytes every step and measurably ate the paged
    occupancy win on CPU hosts; per-chunk it is 1/K of that. ``kv_cap``
    bounds the dense view at exactly the slot-row pool's ``cap``."""
    from ..ops.paged_attention import gather_kv_dense

    def decode_chunk(params, toks, caches, page_table, lens, active, remaining,
                     eos_ids, seeds, steps, base_key):
        # same dequant loop-invariance contract as build_decode_chunk
        params = dequant(params)
        S = toks.shape[0]
        buf = jnp.zeros((S, chunk_size), jnp.int32)

        if fused:
            def body(i, s):
                toks, caches, lens, active, remaining, steps, buf = s
                logits, caches = module.apply(
                    {"params": params}, toks, positions=lens[:, None],
                    caches=caches, cache_lens=lens, page_table=page_table,
                    kv_cap=kv_cap)
                nxt = slot_select(logits[:, -1], base_key, seeds, steps)
                tok = jnp.where(active[:, None], nxt,
                                jnp.maximum(eos_ids, 0)[:, None]
                                ).astype(jnp.int32)
                buf = buf.at[:, i].set(tok[:, 0])
                remaining = remaining - active.astype(jnp.int32)
                finished = jnp.logical_or(tok[:, 0] == eos_ids, remaining <= 0)
                lens = lens + active.astype(jnp.int32)
                steps = steps + active.astype(jnp.int32)
                active = jnp.logical_and(active, jnp.logical_not(finished))
                return tok, caches, lens, active, remaining, steps, buf

            with overlap_scope(overlap):
                toks, caches, lens, active, remaining, steps, buf = \
                    jax.lax.fori_loop(0, chunk_size, body,
                                      (toks, caches, lens, active, remaining,
                                       steps, buf))
            return buf, toks, caches, lens, active, remaining, steps

        # XLA fallback: hoisted per-chunk gather, pure slot-row steps over the
        # dense carry, ONE end-of-chunk mirror of the appended rows back into
        # the pages — the pages leave/enter the loop nowhere, so the loop body
        # is byte-for-byte the slot pool's
        ps = caches[0]["k"].shape[2]
        mp = page_table.shape[1]
        P_total = caches[0]["k"].shape[0]
        lens_in = lens
        dense = [dict(zip(("k", "v"),
                          gather_kv_dense(c["k"], c["v"], page_table, kv_cap)))
                 for c in caches]

        def body(i, s):
            toks, dense, lens, active, remaining, steps, buf = s
            logits, dense = module.apply(
                {"params": params}, toks, positions=lens[:, None],
                caches=dense, cache_lens=lens)
            nxt = slot_select(logits[:, -1], base_key, seeds, steps)
            tok = jnp.where(active[:, None], nxt,
                            jnp.maximum(eos_ids, 0)[:, None]).astype(jnp.int32)
            buf = buf.at[:, i].set(tok[:, 0])
            remaining = remaining - active.astype(jnp.int32)
            finished = jnp.logical_or(tok[:, 0] == eos_ids, remaining <= 0)
            lens = lens + active.astype(jnp.int32)
            steps = steps + active.astype(jnp.int32)
            active = jnp.logical_and(active, jnp.logical_not(finished))
            return tok, dense, lens, active, remaining, steps, buf

        with overlap_scope(overlap):     # trace-time: fori body traces inside
            toks, dense, lens, active, remaining, steps, buf = \
                jax.lax.fori_loop(0, chunk_size, body,
                                  (toks, dense, lens, active, remaining,
                                   steps, buf))
        # mirror rows [lens_in, lens) (this chunk's appends) into the pages;
        # rows a slot never advanced past, or beyond cap, route to an
        # out-of-range page index and the scatter drops them
        done = lens - lens_in
        new_caches = []
        for c, dn in zip(caches, dense):
            k_p, v_p = c["k"], c["v"]
            for j in range(chunk_size):
                rows = lens_in + j
                page_pos = jnp.clip(rows // ps, 0, mp - 1)
                pidx = jnp.where((j < done) & (rows < kv_cap),
                                 jnp.take_along_axis(
                                     page_table, page_pos[:, None],
                                     axis=1)[:, 0],
                                 P_total)
                off = rows % ps
                idx = jnp.minimum(rows, kv_cap - 1)[:, None, None, None]
                k_new = jnp.take_along_axis(dn["k"], idx, axis=2)[:, :, 0, :]
                v_new = jnp.take_along_axis(dn["v"], idx, axis=2)[:, :, 0, :]
                k_p = k_p.at[pidx, :, off, :].set(k_new.astype(k_p.dtype))
                v_p = v_p.at[pidx, :, off, :].set(v_new.astype(v_p.dtype))
            new_caches.append({"k": k_p, "v": v_p})
        return buf, toks, new_caches, lens, active, remaining, steps

    return decode_chunk
