"""Inference engine: TP-sharded serving with AOT-compiled prefill/decode.

Reference: ``deepspeed/inference/engine.py`` (``InferenceEngine:35``,
``_create_model_parallel_group:201``, ``_create_cuda_graph:479``, ``forward:541``,
``_generate:571``). TPU-native redesign:

- TP groups → a mesh with a ``tensor`` axis; params land sharded via Megatron-rule
  PartitionSpecs (the compile-time equivalent of ``ReplaceWithTensorSlicing``,
  ``module_inject/replace_module.py:25``);
- CUDA-graph capture → ``jax.jit`` AOT compilation of the prefill and decode steps with
  donated KV caches (fixed shapes, zero host round-trips between decode iterations);
- kernel injection → the fused Pallas decode-attention path inside ``models/causal_lm.py``
  (selected per family by the policy registry in ``module_inject``).
"""

import os
import time
from typing import Any, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from ..models.causal_lm import (CausalLM, CausalLMConfig, causal_lm_param_specs,
                                init_cache)
from ..parallel.mesh import AXIS_DATA, AXIS_TENSOR, MeshSpec, set_global_mesh
from ..utils.logging import log_dist, logger
from .config import DeepSpeedInferenceConfig


class InferenceEngine:
    """Serve a :class:`CausalLM` (or anything converted to one by ``module_inject``)."""

    def __init__(self, model, config: Optional[DeepSpeedInferenceConfig] = None,
                 params: Optional[Any] = None, mesh_spec: Optional[MeshSpec] = None,
                 seed: int = 0):
        self._config = config or DeepSpeedInferenceConfig()
        tp = self._config.resolved_tp()
        dp = max(1, int(self._config.data_parallel))
        self.mesh_spec = mesh_spec or MeshSpec(
            {AXIS_TENSOR: tp, AXIS_DATA: dp}, devices=jax.devices()[:tp * dp])
        # activate our mesh BEFORE any model tracing — a previously-active engine's mesh
        # must not leak into this engine's init/forward traces
        set_global_mesh(self.mesh_spec)

        self.model_config, self.params = self._resolve_model(model, params, seed)
        self.dtype = self._config.jax_dtype()
        # serve dtype wins over the model's training dtype (reference _convert_to_dtype:462)
        self.model_config.dtype = self.dtype
        self.module = CausalLM(self.model_config)

        self._shard_params()
        self._fns: Dict[str, Any] = {}
        self.ttft: Optional[float] = None
        log_dist(f"inference engine ready: {self.model_config.name} "
                 f"params≈{self.model_config.num_params():,} tp={tp} dp={dp} "
                 f"dtype={self.dtype.__name__}", ranks=[0])

    # ------------------------------------------------------------------ setup
    def _resolve_model(self, model, params, seed):
        if isinstance(model, CausalLMConfig):
            cfg = model
            if params is None:
                module = CausalLM(cfg)
                params = module.init(
                    {"params": jax.random.PRNGKey(seed)},
                    jnp.zeros((1, 8), jnp.int32))["params"]
            return cfg, params
        if isinstance(model, tuple) and len(model) == 2:
            return model  # (config, params)
        # HF torch module → policy conversion (module_inject analogue)
        from ..module_inject.replace_module import convert_hf_model
        return convert_hf_model(model)

    def _spec_fits(self, shape, spec) -> bool:
        mesh = self.mesh_spec
        for i, entry in enumerate(tuple(spec)):
            if entry is None:
                continue
            axes = entry if isinstance(entry, tuple) else (entry,)
            for ax in axes:
                if shape[i] % mesh.size(ax) != 0:
                    return False
        return True

    def _shard_params(self):
        specs = causal_lm_param_specs(self.params, tensor_axis=AXIS_TENSOR)
        mesh = self.mesh_spec

        def place(leaf, spec):
            arr = jnp.asarray(leaf)
            if arr.ndim >= 2 and arr.dtype in (jnp.float32, jnp.float16, jnp.bfloat16):
                arr = arr.astype(self.dtype)  # matmul weights in serve dtype; norms fp32
            if not self._spec_fits(arr.shape, spec):
                spec = P(*([None] * arr.ndim))
            return jax.device_put(arr, NamedSharding(mesh.mesh, spec))

        self.params = jax.tree_util.tree_map(place, self.params, specs)
        self._param_specs = specs

    # ------------------------------------------------------------------ compiled steps
    def _build_fns(self):
        self._fns["forward"] = jax.jit(
            lambda params, ids: self.module.apply({"params": params}, ids))

    def _sampled_fns(self, do_sample, temperature, top_k, top_p):
        """Prefill/decode steps with token selection fused in — one dispatch per decode
        step, no eager ops in the loop (the XLA analogue of CUDA-graph replay)."""
        key = ("gen", do_sample, float(temperature), int(top_k), float(top_p))
        if key in self._fns:
            return self._fns[key]
        module = self.module

        def select(logits, rng):
            if not do_sample:
                return jnp.argmax(logits, axis=-1)[:, None]
            x = logits / jnp.maximum(temperature, 1e-6)
            if top_k and top_k > 0:
                kth = jnp.sort(x, axis=-1)[:, -top_k][:, None]
                x = jnp.where(x < kth, -jnp.inf, x)
            if top_p < 1.0:
                sorted_logits = jnp.sort(x, axis=-1)[:, ::-1]
                probs = jax.nn.softmax(sorted_logits, axis=-1)
                cum = jnp.cumsum(probs, axis=-1)
                cutoff_idx = jnp.sum(cum < top_p, axis=-1, keepdims=True)
                cutoff = jnp.take_along_axis(sorted_logits, cutoff_idx, axis=-1)
                x = jnp.where(x < cutoff, -jnp.inf, x)
            return jax.random.categorical(rng, x, axis=-1)[:, None]

        def prefill(params, ids, caches, lens0, rng):
            logits, new_caches = module.apply(
                {"params": params}, ids, caches=caches, cache_lens=lens0)
            lens = lens0 + ids.shape[1]
            return select(logits[:, -1], rng), new_caches, lens

        def decode(params, tok, caches, lens, rng):
            positions = lens[:, None]
            logits, new_caches = module.apply(
                {"params": params}, tok, positions=positions,
                caches=caches, cache_lens=lens)
            return select(logits[:, -1], rng), new_caches, lens + 1

        fns = (jax.jit(prefill, donate_argnums=(2,)),
               jax.jit(decode, donate_argnums=(2,)))
        self._fns[key] = fns
        return fns

    # ------------------------------------------------------------------ API
    def _activate(self):
        # engines may coexist (e.g. tp=1 and tp=4); tracing consults the global mesh, so
        # re-assert ours before any compiled-fn call
        set_global_mesh(self.mesh_spec)

    def forward(self, input_ids, *args, **kwargs):
        """Full forward logits (reference ``InferenceEngine.forward:541``)."""
        self._activate()
        ids = jnp.asarray(input_ids)
        if "forward" not in self._fns:
            self._build_fns()
        return self._fns["forward"](self.params, ids)

    __call__ = forward

    def generate(self, input_ids, max_new_tokens: int = 32, do_sample: bool = False,
                 temperature: float = 1.0, top_k: int = 0, top_p: float = 1.0,
                 eos_token_id: Optional[int] = None, seed: int = 0, **kwargs):
        """Greedy/sampled generation with the AOT decode loop
        (reference ``_generate:571`` guard + HF-style knobs). Returns (b, t+new) tokens."""
        if kwargs.get("num_beams", 1) != 1:
            raise NotImplementedError("beam search is not supported (reference parity: "
                                      "DeepSpeed inference rejects num_beams > 1)")
        self._activate()
        ids = np.asarray(input_ids)
        b, t = ids.shape
        cap = max(self._config.max_out_tokens, t + max_new_tokens)
        prefill, decode = self._sampled_fns(do_sample, temperature, top_k, top_p)

        caches = init_cache(self.model_config, b, cap, dtype=self.dtype)
        lens0 = jnp.zeros((b,), jnp.int32)
        rng = jax.random.PRNGKey(seed)
        t0 = time.perf_counter()
        tok, caches, lens = prefill(self.params, jnp.asarray(ids), caches, lens0,
                                    jax.random.fold_in(rng, 0))
        jax.block_until_ready(tok)
        self.ttft = time.perf_counter() - t0

        out = [ids]
        finished = np.zeros((b,), dtype=bool)
        for step in range(max_new_tokens):
            tok_np = np.asarray(tok)
            if eos_token_id is not None:
                tok_np = np.where(finished[:, None], eos_token_id, tok_np)
                finished |= tok_np[:, 0] == eos_token_id
            out.append(tok_np)
            if step == max_new_tokens - 1 or (eos_token_id is not None
                                              and finished.all()):
                break
            tok, caches, lens = decode(self.params, jnp.asarray(tok_np), caches, lens,
                                       jax.random.fold_in(rng, step + 1))
        return np.concatenate(out, axis=1)

    # ------------------------------------------------------------------ checkpoints
    def load_checkpoint(self, ckpt_dir: str, tag: Optional[str] = None):
        """Load params saved by the training engine (orbax; re-sharded onto this mesh) —
        the reference's ``_load_checkpoint:392`` sharded-load path."""
        from ..config.config import CheckpointConfig
        from ..runtime.checkpoint_engine.checkpoint_engine import make_checkpoint_engine
        eng = make_checkpoint_engine(CheckpointConfig())
        if tag is None:
            latest = os.path.join(ckpt_dir, "latest")
            tag = open(latest).read().strip() if os.path.isfile(latest) else None
        path = os.path.join(ckpt_dir, tag) if tag else ckpt_dir
        shardings = jax.tree_util.tree_map(
            lambda s: NamedSharding(self.mesh_spec.mesh, s), self._param_specs,
            is_leaf=lambda x: isinstance(x, P))
        self.params = eng.load_subtree(os.path.join(path, "state"), "params",
                                       template=self.params, shardings=shardings)
        logger.info(f"inference params loaded from {path}")
