"""Inference engine: TP-sharded serving with AOT-compiled prefill/decode.

Reference: ``deepspeed/inference/engine.py`` (``InferenceEngine:35``,
``_create_model_parallel_group:201``, ``_create_cuda_graph:479``, ``forward:541``,
``_generate:571``). TPU-native redesign:

- TP groups → a mesh with a ``tensor`` axis; params land sharded via Megatron-rule
  PartitionSpecs (the compile-time equivalent of ``ReplaceWithTensorSlicing``,
  ``module_inject/replace_module.py:25``);
- CUDA-graph capture → ``jax.jit`` AOT compilation of the prefill and decode steps with
  donated KV caches (fixed shapes, zero host round-trips between decode iterations);
- kernel injection → the fused Pallas decode-attention path inside ``models/causal_lm.py``
  (selected per family by the policy registry in ``module_inject``).
"""

import os
import time
from typing import Any, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from ..observability.metrics import record_events as obs_record_events
from ..models.causal_lm import (CausalLM, CausalLMConfig, causal_lm_param_specs,
                                init_cache)
from ..parallel.mesh import AXIS_DATA, AXIS_TENSOR, MeshSpec, set_global_mesh
from ..parallel.overlap import resolve_overlap_config, set_overlap_config
from ..utils.logging import log_dist, logger
from .config import DeepSpeedInferenceConfig
from .decode_fns import build_decode_loop, build_prefill, make_select_fn


def spec_fits(mesh_spec, shape, spec) -> bool:
    """Every named axis (incl. tuple entries) divides its dimension — the shared
    placement guard of the decoder and encoder serving engines (non-divisible
    leaves fall back to replication instead of crashing device_put)."""
    for i, entry in enumerate(tuple(spec)):
        if entry is None:
            continue
        axes = entry if isinstance(entry, tuple) else (entry,)
        for ax in axes:
            if shape[i] % mesh_spec.size(ax) != 0:
                return False
    return True


class InferenceEngine:
    """Serve a :class:`CausalLM` (or anything converted to one by ``module_inject``)."""

    def __init__(self, model, config: Optional[DeepSpeedInferenceConfig] = None,
                 params: Optional[Any] = None, mesh_spec: Optional[MeshSpec] = None,
                 seed: int = 0):
        self._config = config or DeepSpeedInferenceConfig()
        tp = self._config.resolved_tp()
        dp = max(1, int(self._config.data_parallel))
        self.mesh_spec = mesh_spec or MeshSpec(
            {AXIS_TENSOR: tp, AXIS_DATA: dp}, devices=jax.devices()[:tp * dp])
        # activate our mesh BEFORE any model tracing — a previously-active engine's mesh
        # must not leak into this engine's init/forward traces
        set_global_mesh(self.mesh_spec)
        # comm-compute overlap (chunked collective matmuls on the TP decode
        # path); installed like the mesh so every trace this engine initiates
        # sees ITS setting, and threaded into the compiled-step builders
        self.comm_overlap = resolve_overlap_config(self._config.comm_overlap)
        set_overlap_config(self.comm_overlap)

        # validate the impl override BEFORE any model resolution/tracing so a
        # bad value ('triton', 'XLA') fails fast at construction
        if self._config.moe_decode_impl is not None and \
                self._config.moe_decode_impl not in \
                CausalLMConfig.VALID_MOE_DECODE_IMPLS:
            raise ValueError(
                f"moe_decode_impl={self._config.moe_decode_impl!r} is not "
                f"one of {CausalLMConfig.VALID_MOE_DECODE_IMPLS}")
        self.model_config, self.params = self._resolve_model(model, params, seed)
        self.dtype = self._config.jax_dtype()
        # serve dtype wins over the model's training dtype (reference _convert_to_dtype:462)
        self.model_config.dtype = self.dtype
        if self._config.moe_decode_impl is not None:
            # applied before the module exists so every compiled fn sees it
            self.model_config.moe_decode_impl = self._config.moe_decode_impl
        self.module = CausalLM(self.model_config)

        self._shard_params()
        self._fns: Dict[str, Any] = {}
        self.ttft: Optional[float] = None
        self.tpot: Optional[float] = None          # seconds per decode token (per seq)
        self.decode_tps: Optional[float] = None    # decode tokens/sec across the batch
        self._monitor = None                       # optional MonitorMaster
        self._gen_count = 0
        log_dist(f"inference engine ready: {self.model_config.name} "
                 f"params≈{self.model_config.num_params():,} tp={tp} dp={dp} "
                 f"dtype={self.dtype.__name__}", ranks=[0])

    # ------------------------------------------------------------------ setup
    def _resolve_model(self, model, params, seed):
        if isinstance(model, CausalLMConfig):
            cfg = model
            if params is None:
                params = self._init_params_segmented(cfg, seed)
            return cfg, params
        if isinstance(model, tuple) and len(model) == 2:
            cfg, params = model
            if isinstance(cfg, CausalLMConfig):
                return cfg, params
            # our training models' (config, params): GPT2Config / GPT2MoEConfig
            from ..models.gpt2 import GPT2Config
            if isinstance(cfg, GPT2Config):
                from ..module_inject.replace_module import convert_training_model
                return convert_training_model(cfg, params)
            return cfg, params
        # HF torch module → policy conversion (module_inject analogue)
        from ..module_inject.replace_module import convert_hf_model
        return convert_hf_model(model)

    def _init_params_segmented(self, cfg, seed):
        """Random weights in the SERVE dtype, initialised one model segment at a time
        (reuses the offload_param decomposition): a 7B bf16 model inits in ~14 GB of
        HBM instead of the ~28 GB a monolithic fp32 ``module.init`` would need —
        transient fp32 peaks one segment, not the whole model."""
        from ..models.causal_lm import causal_lm_segments
        serve_dtype = self._config.jax_dtype()
        segs = causal_lm_segments(cfg, layers_per_group=1)
        rng = jax.random.PRNGKey(seed)
        init_jits = {}
        params = {}
        for si, seg in enumerate(segs):
            if not seg.init_keys:
                continue
            if seg.init_fn not in init_jits:
                def casted(r, fn=seg.init_fn):
                    return jax.tree_util.tree_map(
                        lambda x: x.astype(serve_dtype)
                        if x.dtype == jnp.float32 else x, fn(r))
                init_jits[seg.init_fn] = jax.jit(casted)
            sub = init_jits[seg.init_fn](jax.random.fold_in(rng, si))
            for key, tree in zip(seg.init_keys, sub):
                params[key] = tree
        return params

    def _spec_fits(self, shape, spec) -> bool:
        return spec_fits(self.mesh_spec, shape, spec)

    # weight-path names eligible for quantization (matmul kernels; embeddings,
    # norms and the lm_head stay in fp — the head shares the huge-vocab logits
    # matmul with tied ``wte``, and the reference GroupQuantizer likewise skips
    # embeddings)
    _QUANT_NAMES = ("q_proj", "k_proj", "v_proj", "o_proj", "fc_in", "fc_out",
                    "gate_proj", "up_proj")

    def _shard_params(self):
        self.params = self._place_params(self.params)

    def _place_params(self, raw):
        """Cast to serve dtype, optionally grouped-quantize matmul weights
        (``weight_quant`` config block; the legacy ``quant``/``dtype="int8"``
        spellings resolve to its 8-bit defaults), and device_put with Megatron
        TP specs.

        Quantized leaves become ``{"__int8_q__"|"__int4_q__", *_scale__}``
        nodes that stay quantized through the decode hot path: the model's
        projection sites (``QuantDense``/``RowParallelDense``) feed them to the
        fused dequant-matmul kernels so int8/int4 bytes are what streams from
        HBM. On non-TPU backends :meth:`_dequant` collapses the tree once per
        dispatch instead.

        Every candidate matrix passes a quantize-time relative-error audit
        (``quantize_with_audit``): outlier-heavy matrices (relative Frobenius
        error above ``weight_quant.outlier_threshold``) and ``exclude``-listed
        paths stay in the serve dtype. Decisions — including the EFFECTIVE
        group size when the requested group does not divide k — land in
        ``self.quant_audit`` and are logged via ``log_dist`` /
        :meth:`set_monitor`."""
        specs = causal_lm_param_specs(raw, tensor_axis=AXIS_TENSOR)
        mesh = self.mesh_spec
        if self._config.quant.enabled or self._config.is_int8():
            from ..ops.quantizer import validate_quant_config
            validate_quant_config(self._config.quant)
        wq = self._config.resolved_weight_quant()
        if wq.enabled and wq.bits not in (8, 4):
            raise ValueError(f"weight_quant.bits={wq.bits} not in (8, 4)")
        if wq.enabled and wq.group < 1:
            raise ValueError(f"weight_quant.group={wq.group} must be >= 1")
        self._wq = wq
        threshold = wq.resolved_threshold()
        audit = []
        self._raw_template = jax.tree_util.tree_map(
            lambda x: jax.ShapeDtypeStruct(np.shape(x), getattr(x, "dtype", np.float32)),
            raw)

        def put(arr, spec):
            if not self._spec_fits(arr.shape, spec):
                spec = P(*([None] * arr.ndim))
            return jax.device_put(arr, NamedSharding(mesh.mesh, spec))

        def quantizable(path_tuple, arr):
            if arr.ndim < 2:
                return False
            names = set(path_tuple)
            if names & set(self._QUANT_NAMES) and path_tuple[-1] == "kernel":
                return True
            return "moe_experts" in names and path_tuple[-1] in ("w1", "w2")

        def walk(node, spec_node, path):
            if isinstance(node, dict):
                return {k: walk(v, spec_node[k], path + (k,)) for k, v in node.items()}
            arr = jnp.asarray(node)
            if arr.ndim >= 2 and arr.dtype in (jnp.float32, jnp.float16, jnp.bfloat16):
                arr = arr.astype(self.dtype)
            if wq.enabled and quantizable(path, arr):
                pstr = "/".join(path)
                if any(sub in pstr for sub in wq.exclude):
                    audit.append({"name": pstr, "decision": "excluded",
                                  "reason": "weight_quant.exclude match",
                                  "bits": wq.bits, "group_requested": wq.group,
                                  "group_effective": None, "rel_err": None})
                else:
                    from ..ops.quantizer import quantize_with_audit
                    qnode, info = quantize_with_audit(
                        arr, bits=wq.bits, group_size=wq.group,
                        threshold=threshold, name=pstr)
                    audit.append(info)
                    if qnode is not None:
                        spec_t = tuple(spec_node) + \
                            (None,) * (arr.ndim - len(tuple(spec_node)))
                        return {k: put(v, P(*spec_t)) for k, v in qnode.items()}
            return put(arr, spec_node)

        placed = walk(raw, specs, ())
        self._param_specs = specs
        self.quant_audit = audit
        n_q = sum(1 for e in audit if e["decision"] == "quantized")
        self._quantized = wq.enabled and n_q > 0
        if wq.enabled:
            for e in audit:
                if e["decision"] != "quantized":
                    log_dist(f"weight_quant: {e['name']} kept fp — {e['reason']}",
                             ranks=[0])
                elif e["group_effective"] != e["group_requested"]:
                    log_dist(f"weight_quant: {e['name']} effective group "
                             f"{e['group_effective']} (requested {wq.group})",
                             ranks=[0])
            log_dist(
                f"weight_quant: int{wq.bits} group={wq.group} — {n_q} matrices "
                f"quantized, {len(audit) - n_q} kept fp "
                f"(outlier_threshold={threshold})", ranks=[0])
        return placed

    def weight_stream_report(self) -> Dict[str, float]:
        """Modeled HBM weight-stream bytes for one full pass over the params
        (≈ one decode step: every matmul weight read once). Quant nodes use
        the fused kernel's own block accounting (``node_weight_bytes`` —
        payload + scales, each block read exactly once). Everything fp — the
        kept-fp matrices AND the bf16-equivalent of quantized ones — is
        billed at 2 bytes/elem, so the model describes a bf16 TPU deployment
        with one consistent denominator regardless of the dtype a CPU test
        engine happens to serve in. ``reduction_quantized_nodes`` is the
        kernel-accounting reduction over the quantized set (the bench's
        modeled bytes-per-step figure); ``reduction_total`` includes the
        fp-kept matrices (embeddings/lm_head/excluded)."""
        from ..ops.quantizer import (dense_weight_bytes, is_quant_node,
                                     node_logical_shape, node_weight_bytes)
        acc = {"quantized_bytes": 0, "quantized_bf16_equiv": 0, "fp_bytes": 0}

        def walk(node):
            if is_quant_node(node):
                acc["quantized_bytes"] += node_weight_bytes(node)
                acc["quantized_bf16_equiv"] += dense_weight_bytes(
                    node_logical_shape(node), jnp.bfloat16)
            elif isinstance(node, dict):
                for v in node.values():
                    walk(v)
            elif getattr(node, "ndim", 0) >= 2:
                acc["fp_bytes"] += dense_weight_bytes(node.shape, jnp.bfloat16)

        walk(self.params)
        step = acc["quantized_bytes"] + acc["fp_bytes"]
        bf16_equiv = acc["quantized_bf16_equiv"] + acc["fp_bytes"]
        return {
            **acc,
            "modeled_step_bytes": step,
            "bf16_equiv_step_bytes": bf16_equiv,
            "reduction_total": bf16_equiv / step if step else 1.0,
            "reduction_quantized_nodes": (
                acc["quantized_bf16_equiv"] / acc["quantized_bytes"]
                if acc["quantized_bytes"] else 1.0),
        }

    def _dequant(self, params):
        """Per-dispatch parameter prep for the compiled-step builders.

        Unquantized: identity. Quantized on the fused backend (TPU, or forced
        via ``DS_TPU_WQ_FORCE_FUSED=1`` in tests): quant nodes pass through to
        the per-site fused dequant-matmul kernels — int8/int4 bytes stream
        from HBM inside the decode loop. Quantized on the XLA fallback backend
        (CPU hosts, excluded matrices): collapse the tree ONCE here — the
        builders call this OUTSIDE the compiled loop bodies, so the dequant is
        loop-invariant (HLO-pinned by ``test_weight_quant.py``) instead of
        re-derived every while_loop step."""
        if not getattr(self, "_quantized", False):
            return params
        from ..ops.quantizer import dequantize_tree, fused_backend_active
        if fused_backend_active():
            return params
        return dequantize_tree(params, self.dtype)

    # ------------------------------------------------------------------ compiled steps
    def _build_fns(self):
        self._fns["forward"] = jax.jit(
            lambda params, ids: self.module.apply(
                {"params": self._dequant(params)}, ids))

    def _loop_fns(self, do_sample, temperature, top_k, top_p, gen_cap):
        """Device-resident generation: prefill (first token, synced for TTFT) + ONE compiled
        ``lax.while_loop`` for all remaining tokens — the XLA analogue of CUDA-graph replay
        (reference ``_create_cuda_graph:479``) with zero host round-trips in the decode loop;
        EOS termination is an on-device all-reduce in the loop condition.

        The step bodies live in ``decode_fns`` (``build_prefill``/``build_decode_loop``),
        shared with the serving executor's chunked variant (``build_decode_chunk``) so the
        two decode paths cannot drift."""
        key = ("loop", do_sample, float(temperature), int(top_k), float(top_p), gen_cap)
        if key in self._fns:
            return self._fns[key]
        select = self._select_fn(do_sample, temperature, top_k, top_p)
        prefill_logits = build_prefill(self.module, self._dequant,
                                       overlap=self.comm_overlap)

        def prefill(params, ids, caches, lens0, rng):
            # ids may be right-padded: next-token logits are computed ONLY at each
            # sequence's last *valid* position (logits_positions skips the other
            # t-1 rows of the huge head matmul — a 250k-vocab 7B prompt's TTFT is
            # dominated by it otherwise)
            logits, new_caches = prefill_logits(params, ids, caches, lens0)
            return select(logits, rng), new_caches, lens0

        decode_loop = build_decode_loop(self.module, self._dequant, select, gen_cap,
                                        overlap=self.comm_overlap)

        # No donation on either fn: prefill rebuilds cache buffers (pad-write) and the loop
        # reuses its carry buffers internally — donating caches cannot alias any output
        # (they are not returned) and only produces "donated buffer not usable" warnings.
        fns = (jax.jit(prefill), jax.jit(decode_loop))
        self._fns[key] = fns
        return fns

    def _select_fn(self, do_sample, temperature, top_k, top_p):
        """Token-selection closure shared by the generation paths."""
        return make_select_fn(do_sample, temperature, top_k, top_p)

    # ------------------------------------------------------------------ API
    def set_monitor(self, monitor):
        """Attach a :class:`~deepspeed_tpu.monitor.MonitorMaster`; every ``generate``
        then emits ``inference/ttft_ms``, ``inference/tpot_ms`` and
        ``inference/decode_tokens_per_sec`` events (step = generate-call index).
        A weight-quantized engine also emits its quantization audit once on
        attach: matrix decisions and the modeled weight-stream reduction."""
        self._monitor = monitor
        audit = getattr(self, "quant_audit", None)
        if audit:
            rep = self.weight_stream_report()
            n_q = sum(1 for e in audit if e["decision"] == "quantized")
            events = [
                ("inference/weight_quant/bits", float(self._wq.bits), 0),
                ("inference/weight_quant/matrices_quantized", float(n_q), 0),
                ("inference/weight_quant/matrices_kept_fp",
                 float(len(audit) - n_q), 0),
                ("inference/weight_quant/modeled_step_bytes",
                 float(rep["modeled_step_bytes"]), 0),
                ("inference/weight_quant/reduction_vs_bf16",
                 float(rep["reduction_total"]), 0),
            ]
            obs_record_events(events)    # registry: independent of monitor
            if monitor is not None and getattr(monitor, "enabled", False):
                monitor.write_events(events)
        return self

    def _activate(self):
        # engines may coexist (e.g. tp=1 and tp=4); tracing consults the global mesh, so
        # re-assert ours before any compiled-fn call
        set_global_mesh(self.mesh_spec)
        set_overlap_config(self.comm_overlap)

    def forward(self, input_ids, *args, **kwargs):
        """Full forward logits (reference ``InferenceEngine.forward:541``)."""
        self._activate()
        ids = jnp.asarray(input_ids)
        if "forward" not in self._fns:
            self._build_fns()
        return self._fns["forward"](self.params, ids)

    __call__ = forward

    def generate(self, input_ids, max_new_tokens: int = 32, do_sample: bool = False,
                 temperature: float = 1.0, top_k: int = 0, top_p: float = 1.0,
                 eos_token_id: Optional[int] = None, seed: int = 0,
                 attention_mask=None, prompt_lengths=None, **kwargs):
        """Greedy/sampled generation, fully device-resident (reference ``_generate:571``
        guard + HF-style knobs). Returns (b, t+generated) tokens.

        The decode loop is ONE compiled ``lax.while_loop`` dispatch — no per-token host
        round-trips; EOS termination happens on device. TTFT (``self.ttft``) is measured by
        host-syncing the prefill's first token.

        Unequal-length prompts: pass ``attention_mask`` (HF-style 0/1, must be
        right-padded) or ``prompt_lengths``; positions, the prefill's next-token read and
        the KV append point are then per-sequence (generated tokens overwrite pad slots).
        """
        if kwargs.get("num_beams", 1) != 1:
            raise NotImplementedError("beam search is not supported (reference parity: "
                                      "DeepSpeed inference rejects num_beams > 1)")
        self._activate()
        ids = np.asarray(input_ids)
        b, t = ids.shape
        if max_new_tokens <= 0:
            return ids

        if attention_mask is not None:
            am = np.asarray(attention_mask).astype(bool)
            lens_np = am.sum(axis=1).astype(np.int32)
            expect = np.arange(t)[None, :] < lens_np[:, None]
            if not np.array_equal(am, expect):
                raise ValueError("attention_mask must be right-padded (1s then 0s); "
                                 "left-padded prompts are not supported")
            if (lens_np < 1).any():
                raise ValueError("attention_mask rows must contain at least one valid token")
        elif prompt_lengths is not None:
            lens_np = np.asarray(prompt_lengths, dtype=np.int32)
            if lens_np.shape != (b,) or (lens_np < 1).any() or (lens_np > t).any():
                raise ValueError(f"prompt_lengths must be (b,) in [1, {t}]")
        else:
            lens_np = np.full((b,), t, dtype=np.int32)

        cap = max(self._config.max_out_tokens, t + max_new_tokens)
        # buffer sized by the prompt-independent cap so the decode loop compiles ONCE per
        # (cap, sampling config, batch) — varying prompt lengths only recompile prefill
        gen_cap = cap
        prefill, decode_loop = self._loop_fns(do_sample, temperature, top_k, top_p,
                                              gen_cap)

        caches = init_cache(self.model_config, b, cap, dtype=self.dtype)
        lens0 = jnp.asarray(lens_np)
        rng = jax.random.PRNGKey(seed)
        ids_dev = jnp.asarray(ids)
        prefill_key = jax.random.fold_in(rng, 0)
        # Force the argument prep (H2D transfer of ids, cache zero-fill, key folds)
        # to COMPLETE before the TTFT clock starts: one tiny fetch depending on all
        # of them. Otherwise those async dispatches execute inside the timed region
        # and TTFT books host→device transfer latency as prefill time (on a
        # tunneled dev chip that is several ~100 ms round-trips; on production
        # hardware this sync costs microseconds).
        if "touch" not in self._fns:
            self._fns["touch"] = jax.jit(
                lambda i, k, c: i[0, 0] + k[0].astype(i.dtype)
                + sum(leaf[0, 0, 0, 0] for leaf in jax.tree_util.tree_leaves(c)
                      ).astype(i.dtype))
        np.asarray(self._fns["touch"](ids_dev, prefill_key, caches))
        t0 = time.perf_counter()
        tok0, caches, lens = prefill(self.params, ids_dev, caches, lens0,
                                     prefill_key)
        tok0_np = np.asarray(tok0)                      # host sync: honest TTFT
        self.ttft = time.perf_counter() - t0

        eos = np.int32(-1 if eos_token_id is None else eos_token_id)
        # cache room is guaranteed: cap >= t + max_new_tokens, and the last appended KV
        # lands at position t + max_new_tokens - 2 < cap
        t1 = time.perf_counter()
        buf, n = decode_loop(self.params, tok0, caches, lens,
                             np.int32(max_new_tokens), eos, rng)
        n = int(n)
        gen = np.asarray(buf)[:, :n]                    # host sync ends the decode clock
        decode_time = time.perf_counter() - t1
        # TPOT counts only loop-produced tokens (the first token is TTFT's);
        # decode_tps is batch-aggregate throughput of the same window
        if n > 1 and decode_time > 0:
            self.tpot = decode_time / (n - 1)
            self.decode_tps = b * (n - 1) / decode_time
        else:
            self.tpot = None
            self.decode_tps = None
        self._gen_count += 1
        events = [("inference/ttft_ms", self.ttft * 1e3, self._gen_count)]
        if self.tpot is not None:
            events += [("inference/tpot_ms", self.tpot * 1e3, self._gen_count),
                       ("inference/decode_tokens_per_sec", self.decode_tps,
                        self._gen_count)]
        obs_record_events(events)        # registry: independent of monitor
        if self._monitor is not None and getattr(self._monitor, "enabled", False):
            self._monitor.write_events(events)
        return np.concatenate([ids, gen], axis=1)

    # ------------------------------------------------------------------ checkpoints
    def load_checkpoint(self, ckpt_dir: str, tag: Optional[str] = None):
        """Load params saved by the training engine (orbax; re-sharded onto this mesh) —
        the reference's ``_load_checkpoint:392`` sharded-load path."""
        from ..config.config import CheckpointConfig
        from ..runtime.checkpoint_engine.checkpoint_engine import make_checkpoint_engine
        eng = make_checkpoint_engine(CheckpointConfig())
        if tag is None:
            latest = os.path.join(ckpt_dir, "latest")
            tag = open(latest).read().strip() if os.path.isfile(latest) else None
        path = os.path.join(ckpt_dir, tag) if tag else ckpt_dir
        shardings = jax.tree_util.tree_map(
            lambda s: NamedSharding(self.mesh_spec.mesh, s), self._param_specs,
            is_leaf=lambda x: isinstance(x, P))
        # checkpoints hold fp params: restore against the pre-quantization template, then
        # re-run placement (cast + optional int8 quantization + sharding)
        restored = eng.load_subtree(os.path.join(path, "state"), "params",
                                    template=self._raw_template, shardings=shardings)
        self.params = self._place_params(restored)
        self._fns.clear()                       # param tree structure may have changed
        logger.info(f"inference params loaded from {path}")
