"""Continuous-batching scheduler: bounded queue → slot-recycled decode chunks.

The serving loop above ``InferenceEngine``'s single-call ``generate``: requests
arrive at any time, wait in a bounded FIFO queue, are prefilled into a free KV
slot between decode chunks, and decode alongside whatever else is in flight. A
finished sequence releases its slot at the next chunk boundary and a pending
prompt is prefilled into it while the other slots keep decoding — continuous
batching in the sense of Orca/vLLM, built from two compiled shapes (bucketed
prefill + K-step chunk) instead of a token-level iteration.

Semantics:

- **admission control** — ``submit`` validates prompt/budget against the pool cap
  up front (fail fast, never poison the queue);
- **backpressure** — a full queue raises :class:`QueueFullError` carrying a
  ``retry_after`` hint: the request is *rejected*, never silently dropped;
- **deadlines / cancellation** — checked at every chunk boundary, for queued and
  in-flight requests alike; an expired/cancelled in-flight request keeps its
  partial tokens and frees its slot;
- **transient faults** — prefill and chunk dispatch run under
  ``retry_with_backoff`` with ``fault_point`` sites ``serving.prefill`` /
  ``serving.decode_chunk``, the same injection substrate as the checkpoint ring.

Token parity: greedy decode through the scheduler is bit-identical to per-request
``InferenceEngine.generate`` (same prefill math, same per-step decode math —
shared via ``decode_fns``). Sampled decode is deterministic per request ``seed``
and independent of slot placement/co-batching (per-slot key streams), but is not
bit-identical to ``generate``'s batched key stream.

Threading: the scheduler is single-threaded by design — drive it with ``step()``
/ ``run()`` from one thread (the loadgen and ``deepspeed-serve`` do exactly
that). ``RequestHandle.cancel`` only sets a flag and is safe to call from
anywhere.
"""

import itertools
import time
from collections import deque
from dataclasses import dataclass, field
from enum import Enum
from typing import Deque, List, Optional

import numpy as np

from ...observability.trace import CAT_SERVING, get_tracer
from ...utils.fault_injection import fault_point, retry_with_backoff
from ...utils.logging import logger
from ..speculative import SpeculativeConfig, make_proposer
from .executor import ChunkedDecodeExecutor
from .prefix_cache import PrefixCache, PrefixCacheConfig
from .telemetry import ServingTelemetry, adaptive_retry_after


class RequestState(Enum):
    QUEUED = "queued"
    RUNNING = "running"
    FINISHED = "finished"
    CANCELLED = "cancelled"
    EXPIRED = "expired"
    EVICTED = "evicted"     # replica death/drain: partial tokens kept for retry


class QueueFullError(RuntimeError):
    """Backpressure: the admission queue is at capacity. ``retry_after`` is the
    scheduler's hint (seconds) for when to resubmit."""

    def __init__(self, retry_after: float):
        super().__init__(f"serving queue full; retry after {retry_after:.3f}s")
        self.retry_after = float(retry_after)


@dataclass
class ServingConfig:
    slots: int = 2                      # concurrent sequences in the slot-batch
    chunk_size: int = 8                 # decode steps per compiled chunk
    max_queue: int = 16                 # admission queue bound (backpressure)
    max_seq_len: Optional[int] = None   # KV cap; default engine max_out_tokens
    max_prompt_len: Optional[int] = None
    default_max_new_tokens: int = 32
    do_sample: bool = False
    temperature: float = 1.0
    top_k: int = 0
    top_p: float = 1.0
    retry_after_s: float = 0.25         # backpressure hint FLOOR (the emitted
    #   hint is load-adaptive: queue depth / observed drain rate)
    retry_after_max_s: float = 8.0
    transient_retries: int = 2          # retry_with_backoff budget per dispatch
    retry_base_delay: float = 0.02
    base_seed: int = 0
    chunk_deadline_s: Optional[float] = None   # per-chunk watchdog (None = off)
    prefix_cache: Optional[PrefixCacheConfig] = None   # None = cache off
    # KV memory shape: "paged" (default) = global fixed-size pages behind
    # per-slot page tables, page-count admission, zero-copy refcounted prefix
    # sharing; "slots" = the legacy slot-row pool (one cap-row reservation
    # per slot). Greedy output is bit-identical either way.
    kv_pool: str = "paged"
    kv_page_size: int = 16
    kv_total_pages: Optional[int] = None   # HBM budget in pages (None = match
    #   the slot-row pool's bytes: slots * ceil(cap/page) + the null page)
    # speculative decoding: every decode chunk becomes ONE draft-propose /
    # one-pass-verify round (greedy output stays bit-identical; sampled keeps
    # the per-slot key-stream distribution exactly — see inference.speculative)
    speculate: bool = False
    spec_k: int = 4                     # draft tokens per verify window
    spec_proposer: str = "ngram"        # "ngram" | "draft_model"
    spec_ngram_max: int = 4
    spec_ngram_min: int = 1
    spec_draft_engine: object = None    # tiny engine for "draft_model"


def validate_admission(prompt, max_new_tokens: Optional[int],
                       default_max_new: int, max_prompt_len: int, cap: int):
    """Shared admission contract (scheduler + router): normalize the prompt and
    budget, raise ``ValueError`` for anything that could never fit. One owner —
    the router's pre-check must never drift from what a replica will accept."""
    prompt = np.asarray(prompt, dtype=np.int32).reshape(-1)
    max_new = int(default_max_new if max_new_tokens is None else max_new_tokens)
    if prompt.size < 1:
        raise ValueError("prompt must contain at least one token")
    if max_new < 1:
        raise ValueError(f"max_new_tokens must be >= 1, got {max_new}")
    if prompt.size > max_prompt_len:
        raise ValueError(f"prompt length {prompt.size} exceeds "
                         f"max_prompt_len={max_prompt_len}")
    if prompt.size + max_new > cap:
        raise ValueError(f"prompt ({prompt.size}) + max_new_tokens "
                         f"({max_new}) exceeds KV capacity {cap}")
    return prompt, max_new


@dataclass
class RequestHandle:
    """Caller's view of a submitted request (filled in by the scheduler)."""
    id: int
    prompt: np.ndarray
    max_new_tokens: int
    eos_token_id: Optional[int]
    deadline_s: Optional[float]
    seed: int
    arrival: float
    state: RequestState = RequestState.QUEUED
    tokens: List[int] = field(default_factory=list)
    ttft: Optional[float] = None        # queue wait + prefill, seconds
    tpot: Optional[float] = None        # seconds per decode token
    finish_reason: Optional[str] = None  # eos | length | cancelled | deadline
    slot: Optional[int] = None
    first_token_at: Optional[float] = None
    finished_at: Optional[float] = None
    prefix_hit_tokens: int = 0          # prefill tokens skipped via the
    #   prefix cache (0 = cold miss); loadgen splits TTFT on this
    _cancel: bool = False
    _span: Optional[object] = None      # request-scoped trace root (OpenSpan)

    @property
    def trace_id(self) -> Optional[str]:
        return self._span.trace_id if self._span is not None else None

    def cancel(self) -> None:
        self._cancel = True

    @property
    def done(self) -> bool:
        return self.state in (RequestState.FINISHED, RequestState.CANCELLED,
                              RequestState.EXPIRED, RequestState.EVICTED)

    def result(self) -> np.ndarray:
        """Generated tokens (EOS included when emitted; partial if cancelled)."""
        return np.asarray(self.tokens, dtype=np.int32)

    def output_ids(self) -> np.ndarray:
        return np.concatenate([self.prompt.astype(np.int32), self.result()])


class ContinuousBatchingScheduler:
    """Admission queue + slot tables driving a :class:`ChunkedDecodeExecutor`."""

    def __init__(self, engine, config: Optional[ServingConfig] = None,
                 monitor=None):
        self.config = cfg = config or ServingConfig()
        cap = int(cfg.max_seq_len or engine._config.max_out_tokens)
        self.executor = ChunkedDecodeExecutor(
            engine, slots=cfg.slots, cap=cap, chunk_size=cfg.chunk_size,
            do_sample=cfg.do_sample, temperature=cfg.temperature,
            top_k=cfg.top_k, top_p=cfg.top_p,
            max_prompt_len=cfg.max_prompt_len, base_seed=cfg.base_seed,
            chunk_deadline_s=cfg.chunk_deadline_s, kv_pool=cfg.kv_pool,
            kv_page_size=cfg.kv_page_size, kv_total_pages=cfg.kv_total_pages)
        self.cap = cap
        self.proposer = None
        self._spec_cfg: Optional[SpeculativeConfig] = None
        if cfg.speculate:
            self._spec_cfg = SpeculativeConfig(
                k=cfg.spec_k, proposer=cfg.spec_proposer,
                ngram_max=cfg.spec_ngram_max, ngram_min=cfg.spec_ngram_min,
                draft_engine=cfg.spec_draft_engine)
            self.proposer = make_proposer(self._spec_cfg)
        self.telemetry = ServingTelemetry(monitor)
        self._tracer = get_tracer()
        self.prefix_cache: Optional[PrefixCache] = None
        if cfg.prefix_cache is not None and cfg.prefix_cache.enabled:
            self.prefix_cache = PrefixCache(cfg.prefix_cache)
            if self.executor.paged:
                # LRU eviction of a page entry decrefs against the CURRENT
                # pool (any pool swap clears the cache first, so an entry's
                # pages always belong to the pool this resolves to)
                self.prefix_cache.page_release = \
                    lambda pages: self.executor.pool.release_shared(pages)
                # spill path: gather an evicted entry's pages as a dense host
                # slab (the gather_prefix wire format) before the refs drop
                self.prefix_cache.page_gather = \
                    lambda pages, rows: self.executor.pool.gather_pages(
                        pages, rows)
        self.queue: Deque[RequestHandle] = deque()
        self._ids = itertools.count()
        S = cfg.slots
        self._slot_req: List[Optional[RequestHandle]] = [None] * S
        self._toks = np.zeros(S, np.int32)
        self._lens = np.zeros(S, np.int32)
        self._active = np.zeros(S, bool)
        self._remaining = np.zeros(S, np.int32)
        self._eos = np.full(S, -1, np.int32)
        self._seeds = np.zeros(S, np.int32)
        self._steps = np.zeros(S, np.int32)

    # ---------------------------------------------------------------- frontend
    def submit(self, prompt, max_new_tokens: Optional[int] = None,
               eos_token_id: Optional[int] = None,
               deadline_s: Optional[float] = None, seed: int = 0,
               trace_ctx=None) -> RequestHandle:
        """Enqueue a request. Raises ``ValueError`` on inadmissible shapes and
        :class:`QueueFullError` (with ``retry_after``) under backpressure.

        ``trace_ctx`` (an ``observability.trace.SpanContext`` or ``None``)
        joins this request's spans to a propagated parent trace — the router
        passes its dispatch-attempt context here, and the subprocess replica
        deserializes one off the JSONL pipe, so replica-side spans land on the
        same trace id as the frontend's."""
        prompt, max_new = validate_admission(
            prompt, max_new_tokens, self.config.default_max_new_tokens,
            self.executor.max_prompt_len, self.cap)
        if len(self.queue) >= self.config.max_queue:
            self.telemetry.on_rejected()
            raise QueueFullError(self.retry_after_hint())
        handle = RequestHandle(
            id=next(self._ids), prompt=prompt, max_new_tokens=max_new,
            eos_token_id=eos_token_id, deadline_s=deadline_s, seed=int(seed),
            arrival=time.monotonic())
        handle._span = self._tracer.begin(
            "replica_request", cat=CAT_SERVING, ctx=trace_ctx,
            t0=handle.arrival,
            attrs={"request_id": handle.id, "prompt_tokens": int(prompt.size),
                   "max_new_tokens": max_new})
        self.queue.append(handle)
        return handle

    @property
    def queue_depth(self) -> int:
        return len(self.queue)

    def retry_after_hint(self, now: Optional[float] = None) -> float:
        """Load-adaptive backpressure hint (see
        :func:`~.telemetry.adaptive_retry_after`)."""
        cfg = self.config
        return adaptive_retry_after(cfg.retry_after_s, cfg.retry_after_max_s,
                                    len(self.queue), cfg.max_queue,
                                    self.telemetry.drain_rate(now))

    @property
    def active_requests(self) -> List[RequestHandle]:
        return [h for h in self._slot_req if h is not None]

    @property
    def busy(self) -> bool:
        return bool(self.queue) or any(h is not None for h in self._slot_req)

    # ------------------------------------------------------------------- loop
    def step(self) -> bool:
        """One scheduler iteration: sweep deadlines/cancellations, admit pending
        prompts into free slots, run one decode chunk, retire finished slots.
        Returns True when any request made progress."""
        now = time.monotonic()
        self._sweep_queue(now)
        self._sweep_running(now)
        admitted = self._admit()
        decoded = self._decode_chunk()
        pool = self.executor.pool
        self.telemetry.on_step(
            len(self.queue), pool.occupancy,
            prefix_stats=(None if self.prefix_cache is None
                          else self.prefix_cache.stats()),
            paged_stats=(pool.stats() if pool.paged else None))
        return admitted or decoded

    def run(self, max_steps: int = 100000) -> dict:
        """Drive ``step()`` until queue and slots drain; returns the telemetry
        snapshot."""
        steps = 0
        while self.busy and steps < max_steps:
            self.step()
            steps += 1
        return self.telemetry.snapshot()

    # ------------------------------------------------------------ prefix cache
    def _insert_prefix(self, handle: RequestHandle, slot: int) -> None:
        """Index the slot's prompt KV in the trie under the full prompt token
        path. Paged pool: SHARE the slot's prompt-covering pages (refcount
        bump — zero-copy, no device gather at all). Slot-row pool: gather a
        slab copy (padded to the prompt bucket) as before."""
        if self.prefix_cache is None:
            return
        P = int(handle.prompt.size)
        if P < self.prefix_cache.config.min_insert_tokens:
            self.prefix_cache.insert_skipped += 1
            return                   # skip the device gather, not just the insert
        if self.prefix_cache.contains(handle.prompt):
            return                   # resident (LRU refreshed): same tokens ⇒
            #   bit-identical slab, don't pay the gather to drop it
        pool = self.executor.pool
        if pool.paged:
            nbytes = pool.pages_for(P) * pool.page_nbytes
            if nbytes > self.prefix_cache.config.max_bytes:
                self.prefix_cache.insert_skipped += 1
                return
            pages = pool.share_prefix(slot, P)
            if not self.prefix_cache.insert_pages(handle.prompt, pages,
                                                  nbytes):
                pool.release_shared(pages)   # resident/refused: drop our refs
            return
        rows = self.executor.bucket_for(P)
        if pool.slab_nbytes(rows) > self.prefix_cache.config.max_bytes:
            self.prefix_cache.insert_skipped += 1
            return                   # could never fit: skip the gather too
        slab = pool.gather_prefix(slot, rows)
        self.prefix_cache.insert(handle.prompt, slab)

    def _retire_prefix(self, handle: RequestHandle, slot: int) -> None:
        """Completion-path insert hook: runs for every request leaving a slot
        through a healthy retirement (finished / cancelled / expired — the
        prefill was paid, so its prompt KV is worth keeping). Eviction paths
        (``evict_all``) deliberately skip it: the pool may be poisoned there.
        """
        if (self.prefix_cache is not None
                and self.prefix_cache.config.insert_on == "completion"):
            self._insert_prefix(handle, slot)

    @property
    def prefix_hit_rate(self) -> float:
        """ADMISSION-level hit rate (successful prefills) — everything named
        ``prefix_hit_rate`` (this, the monitor tags, the snapshot) derives
        from the same counters; the trie's lookup-level rate (which also
        counts failed/retried admissions) is only in
        :meth:`prefix_cache_report`."""
        if self.prefix_cache is None:
            return 0.0
        t = self.telemetry
        n = t.prefix_hits + t.prefix_misses
        return t.prefix_hits / n if n else 0.0

    def prefix_cache_report(self) -> dict:
        """``weight_stream_report()``-style summary of the prefix cache: hit
        accounting, resident slab bytes against budget, and the modeled
        prefill-compute saving (skipped prefill tokens / total prompt tokens
        seen). The ``hits``/``misses``/``hit_rate`` here are the trie's
        LOOKUP-level counters (they also tick on admissions that later fail
        and retry) — everything published as ``prefix_hit_rate`` elsewhere is
        admission-level."""
        if self.prefix_cache is None:
            return {"enabled": False}
        s = self.prefix_cache.stats()
        seen = max(1, s["lookup_tokens"])
        return {
            "enabled": True,
            **s,
            "budget_fill": s["cached_bytes"] / max(1, s["max_bytes"]),
            "prefill_tokens_skipped_frac": s["hit_tokens"] / seen,
        }

    def _rebuild_pool(self) -> None:
        """Discard + rebuild the KV pool after a failure that may have
        consumed donated buffers. On the paged pool the prefix cache's shared
        pages live INSIDE the discarded buffers, so its device rung is
        dropped with it (without spilling — gathering from a poisoned pool is
        not trustworthy) — the honest cost of zero-copy sharing. Host-rung
        entries are independent numpy slabs and survive to serve promote hits
        against the rebuilt pool, exactly like slot-mode's independent
        gathered slabs always have."""
        if self.executor.paged and self.prefix_cache is not None:
            self.prefix_cache.drop_device()
        self.executor.reset_pool()

    # --------------------------------------------------------------- eviction
    def evict_all(self, reason: str = "evicted") -> List[RequestHandle]:
        """Evict every queued and in-flight request with its generated-so-far
        prefix: each handle finalizes as ``EVICTED`` (tokens kept), the slot
        tables are cleared and the KV pool rebuilt.

        This is the checkpointless-retry hook the router relies on: an evicted
        handle re-enqueues elsewhere as ``prompt + tokens`` with the remaining
        budget, and greedy decode continues prefix-consistently — the request,
        not a checkpoint, is the unit of recovery on the inference path.
        """
        now = time.monotonic()
        out: List[RequestHandle] = []
        for h in self.queue:
            self._finalize(h, RequestState.EVICTED, reason, now)
            out.append(h)
        self.queue.clear()
        for slot, h in enumerate(self._slot_req):
            if h is None:
                continue
            self._finalize(h, RequestState.EVICTED, reason, now)
            out.append(h)
            self._slot_req[slot] = None
        self._active[:] = False
        self._remaining[:] = 0
        self._steps[:] = 0
        self._eos[:] = -1
        # rebuild rather than per-slot zero-fill: on the death path the old
        # buffers may be inside a failed/wedged dispatch and cannot be trusted
        self._rebuild_pool()
        return out

    # ----------------------------------------------------------------- sweeps
    def _expired(self, handle: RequestHandle, now: float) -> bool:
        return (handle.deadline_s is not None
                and now - handle.arrival > handle.deadline_s)

    def _sweep_queue(self, now: float) -> None:
        kept = deque()
        for h in self.queue:
            if h._cancel:
                self._finalize(h, RequestState.CANCELLED, "cancelled", now)
            elif self._expired(h, now):
                self._finalize(h, RequestState.EXPIRED, "deadline", now)
            else:
                kept.append(h)
        self.queue = kept

    def _sweep_running(self, now: float) -> None:
        for slot, h in enumerate(self._slot_req):
            if h is None:
                continue
            if h._cancel:
                self._retire_prefix(h, slot)   # prefill was paid: keep its KV
                self._finalize(h, RequestState.CANCELLED, "cancelled", now)
                self._release(slot)
            elif self._expired(h, now):
                self._retire_prefix(h, slot)
                self._finalize(h, RequestState.EXPIRED, "deadline", now)
                self._release(slot)

    # -------------------------------------------------------------- admission
    def _admit(self) -> bool:
        admitted = False
        cfg = self.config
        tracer = self._tracer
        while self.queue:
            pool = self.executor.pool    # re-read: a failed hit-prefill below
            head = self.queue[0]         # rebuilds the pool mid-loop
            # page-count admission: the paged pool admits when the request's
            # OWN reservation (prompt + budget, page-granular) fits — not when
            # a whole cap-row slot frees up. Conservative (all-fresh) check:
            # a prefix hit can only need fewer pages. The slot pool reduces
            # to its free-slot check. FIFO: a head that doesn't fit waits.
            need_tokens = int(head.prompt.size) + int(head.max_new_tokens)
            if self.proposer is not None:
                # speculation headroom: a verify window writes up to spec_k
                # draft rows past the committed length before the accept rule
                # trims them — admit only when those rows fit too, so a
                # mid-stream round never lands on an unreserved page. Clamped
                # to the cap: the per-slot proposal limit already shrinks the
                # window near the cap edge.
                need_tokens = min(need_tokens + self._spec_cfg.k, self.cap)
            if not pool.can_admit(need_tokens):
                # admission-pressure eviction (paged): cached prefixes pin
                # real pool pages, so a full free list trades the coldest
                # cached prefixes for admission capacity before giving up —
                # a waiting request always outranks a cold cached prefix.
                # Only entries holding a refcount-1 page are worth dropping:
                # evicting one whose pages live slots still bind frees
                # nothing, and would just empty the cache for no capacity.
                # Peek the head's own prefix first (stats/LRU-free): its
                # matching entry must survive the sweep — evicting it would
                # trade the head's zero-copy hit for a full prefill — and a
                # hit shrinks the fresh-page need to the unshared suffix.
                # ... but ONLY when pages are the shortage: evicting cached
                # prefixes frees pages, never slots, so a queue blocked on a
                # full slot set must not drain the cache for zero gain.
                matched_hint = 0
                if pool.paged and self.prefix_cache is not None \
                        and pool.free_slots > 0:
                    matched_hint, keep = self.prefix_cache.peek(head.prompt)
                    if keep is not None and keep.pages is None:
                        # host-rung match: the promote path acquires all-fresh
                        # pages, so the hint must not shrink the page need
                        matched_hint = 0
                    frees = lambda e: e is not keep and \
                        e.pages is not None and any(  # noqa: E731
                            pool.page_ref(p) == 1 for p in e.pages)
                    while not pool.can_admit(need_tokens,
                                             matched=matched_hint) and \
                            self.prefix_cache.evict_lru(frees):
                        pass
                if not pool.can_admit(need_tokens, matched=matched_hint):
                    break
            handle = self.queue.popleft()
            admit_t = time.monotonic()
            tracer.record_span("queue_wait", handle._span,
                               handle.arrival, admit_t)
            matched, entry = 0, None
            if self.prefix_cache is not None:
                t_lk = time.monotonic()
                matched, entry = self.prefix_cache.lookup(handle.prompt)
                tracer.record_span("prefix_lookup", handle._span, t_lk,
                                   time.monotonic(),
                                   attrs={"hit": entry is not None,
                                          "matched_tokens": int(matched)})
            if pool.paged and entry is not None and entry.pages is not None:
                # zero-copy hit: bind the shared prefix pages into the fresh
                # slot's table (refcount bump + one COW boundary page) — the
                # paged replacement for the slab restore scatter
                slot = pool.acquire(need_tokens, prefix_pages=entry.pages,
                                    matched=matched)
            else:
                # miss, slot-pool hit, or host-rung PROMOTE hit (entry with a
                # spilled numpy slab): all-fresh pages; the promote restores
                # the slab into them inside prefill_into_slot
                slot = pool.acquire(need_tokens)
            if slot is None:   # can_admit is conservative, so only a racing
                self.queue.appendleft(handle)          # caller could land here
                break

            def attempt(h=handle, s=slot, m=matched, e=entry):
                fault_point("serving.prefill")
                if e is not None:
                    return self.executor.prefill_into_slot(
                        s, h.prompt, h.seed, prefix_len=m,
                        prefix_slab=e.slab, trace_ctx=h._span)
                return self.executor.prefill_into_slot(s, h.prompt, h.seed,
                                                       trace_ctx=h._span)

            prefill_span = tracer.start_span(
                "prefill", parent=handle._span,
                attrs={"slot": slot, "prefix_len": int(matched)
                       if entry is not None else 0})
            try:
                tok0, _ = retry_with_backoff(attempt,
                                             retries=cfg.transient_retries,
                                             base_delay=cfg.retry_base_delay)
            except Exception as e:
                tracer.end_span(prefill_span,
                                attrs={"outcome": "error",
                                       "error": type(e).__name__})
                # retry budget exhausted: fail THIS request, keep serving — the
                # slot must not leak and the loop must not die with the queue
                # still holding live requests
                logger.error(f"[serving] prefill failed for request "
                             f"{handle.id}: {type(e).__name__}: {e}")
                now = time.monotonic()
                self._finalize(handle, RequestState.CANCELLED, "error", now)
                if entry is not None:
                    # cache-hit path: the suffix-prefill dispatch DONATES the
                    # pool caches (unlike the miss path's batch-1 prefill), so
                    # a failure here may have consumed them — zero-filling the
                    # slot or restoring into the old binding would crash the
                    # loop on deleted buffers. Same recovery as a failed
                    # decode chunk: fail the in-flight requests, rebuild the
                    # pool, keep serving (a router retries them elsewhere).
                    logger.error("[serving] failed prefill was a prefix-cache "
                                 "hit (donated pool dispatch); failing "
                                 f"{sum(h is not None for h in self._slot_req)}"
                                 " in-flight request(s) and rebuilding the "
                                 "KV pool")
                    for s2, h2 in enumerate(self._slot_req):
                        if h2 is not None:
                            self._finalize(h2, RequestState.CANCELLED,
                                           "error", now)
                            self._slot_req[s2] = None
                    self._active[:] = False
                    self._remaining[:] = 0
                    self._steps[:] = 0
                    self._eos[:] = -1
                    self._rebuild_pool()
                else:
                    self._release(slot)
                continue
            now = time.monotonic()
            tracer.end_span(prefill_span, t1=now,
                            attrs={"outcome": "ok",
                                   "prefix_hit": entry is not None})
            handle.state = RequestState.RUNNING
            handle.slot = slot
            handle.tokens.append(int(tok0))
            handle.first_token_at = now
            handle.ttft = now - handle.arrival
            handle.prefix_hit_tokens = matched if entry is not None else 0
            self.telemetry.on_prefix(entry is not None,
                                     handle.prefix_hit_tokens,
                                     enabled=self.prefix_cache is not None)
            if (self.prefix_cache is not None
                    and self.prefix_cache.config.insert_on == "prefill"):
                self._insert_prefix(handle, slot)
            eos = -1 if handle.eos_token_id is None else int(handle.eos_token_id)
            if tok0 == eos or handle.max_new_tokens == 1:
                self._retire_prefix(handle, slot)
                self._finalize(handle, RequestState.FINISHED,
                               "eos" if tok0 == eos else "length", now)
                self._release(slot)
            else:
                self._slot_req[slot] = handle
                self._toks[slot] = tok0
                self._lens[slot] = handle.prompt.size
                self._active[slot] = True
                self._remaining[slot] = handle.max_new_tokens - 1
                self._eos[slot] = eos
                self._seeds[slot] = handle.seed
                self._steps[slot] = 1       # token 0 came from prefill
            admitted = True
        return admitted

    # ----------------------------------------------------------------- decode
    def _decode_chunk(self) -> bool:
        if not self._active.any():
            return False
        cfg = self.config
        steps_before = self._steps.copy()

        def attempt():
            fault_point("serving.decode_chunk")
            if self.proposer is not None:
                return self._spec_round()
            return self.executor.run_chunk(
                self._toks, self._lens, self._active, self._remaining,
                self._eos, self._seeds, self._steps)

        try:
            res = retry_with_backoff(attempt, retries=cfg.transient_retries,
                                     base_delay=cfg.retry_base_delay)
        except Exception as e:
            # retry budget exhausted mid-decode: the pool buffers may have been
            # donated into a dispatch that died, so they cannot be trusted —
            # fail every in-flight request, rebuild the pool, keep serving the
            # queue (same never-kill-the-loop contract as admission)
            logger.error(f"[serving] decode chunk failed: "
                         f"{type(e).__name__}: {e}; failing "
                         f"{sum(h is not None for h in self._slot_req)} "
                         "in-flight request(s) and rebuilding the KV pool")
            now = time.monotonic()
            for slot, h in enumerate(self._slot_req):
                if h is not None:
                    self._finalize(h, RequestState.CANCELLED, "error", now)
                    self._slot_req[slot] = None
            self._active[:] = False
            self._remaining[:] = 0
            self._steps[:] = 0
            self._eos[:] = -1
            self._rebuild_pool()
            return False
        now = time.monotonic()
        counts = res.steps - steps_before
        total = 0
        chunk_t0 = now - res.elapsed
        chunk_idx = self.telemetry._chunk_idx + 1
        for slot, h in enumerate(self._slot_req):
            if h is None or counts[slot] <= 0:
                continue
            h.tokens.extend(res.buf[slot, :counts[slot]].tolist())
            total += int(counts[slot])
            # one span per participating request: the chunk is a batch-level
            # dispatch, but "where did THIS request's time go" needs it on the
            # request's own trace. Guarded: tracing-off must not build attrs
            # dicts on the hottest loop.
            if h._span is not None:
                self._tracer.record_span(
                    "decode_chunk", h._span, chunk_t0, now,
                    attrs={"chunk": chunk_idx, "slot": slot,
                           "tokens": int(counts[slot])})
        was_active = self._active.copy()
        self._toks = res.toks[:, 0].copy()
        self._lens = res.lens.copy()
        self._remaining = res.remaining.copy()
        self._steps = res.steps.copy()
        self._active = res.active.copy()
        for slot in np.nonzero(was_active & ~res.active)[0]:
            h = self._slot_req[int(slot)]
            if h is None:
                continue
            reason = ("eos" if h.eos_token_id is not None
                      and h.tokens and h.tokens[-1] == h.eos_token_id
                      else "length")
            self._retire_prefix(h, int(slot))
            self._finalize(h, RequestState.FINISHED, reason, now)
            self._release(int(slot))
        self.telemetry.on_chunk(total, res.elapsed)
        if self.proposer is not None:
            self.telemetry.on_spec(res.proposed, res.accepted, total,
                                   res.draft_s, res.elapsed)
        return True

    def _spec_round(self):
        """Build each active slot's draft window on the host (the proposer
        sees the request's full prompt+generated stream — pure host state, so
        a checkpointless retry re-derives the same drafts anywhere) and run
        one fixed-shape verify round through the executor."""
        k = self._spec_cfg.k
        S = self.config.slots
        proposals = np.zeros((S, k), np.int32)
        spec_lens = np.zeros(S, np.int32)
        t0 = time.perf_counter()
        for slot, h in enumerate(self._slot_req):
            if h is None or not self._active[slot]:
                continue
            # window rows [lens, lens+L] must fit the cap, and an L-draft
            # round can emit L+1 tokens — cap-edge and budget-edge slots get
            # a truncated (possibly empty) window, degenerating to the plain
            # single-token step through the same compiled shape
            limit = min(k, self.cap - 1 - int(self._lens[slot]),
                        int(self._remaining[slot]) - 1)
            if limit <= 0:
                continue
            ctx = np.concatenate([h.prompt.astype(np.int32),
                                  np.asarray(h.tokens, np.int32)])
            draft = np.asarray(self.proposer.propose(ctx, limit), np.int32)
            L = min(int(draft.size), limit)
            if L > 0:
                proposals[slot, :L] = draft[:L]
                spec_lens[slot] = L
        draft_s = time.perf_counter() - t0
        res = self.executor.run_spec_round(
            self._toks, self._lens, self._active, self._remaining,
            self._eos, self._seeds, self._steps, proposals, spec_lens)
        res.draft_s = draft_s
        return res

    # --------------------------------------------------------------- lifecycle
    def _finalize(self, handle: RequestHandle, state: RequestState,
                  reason: str, now: float) -> None:
        handle.state = state
        handle.finish_reason = reason
        handle.finished_at = now
        if (handle.first_token_at is not None and len(handle.tokens) > 1
                and now > handle.first_token_at):
            handle.tpot = (now - handle.first_token_at) / (len(handle.tokens) - 1)
        if handle._span is not None:
            self._tracer.instant("retire", handle._span,
                                 attrs={"state": state.value,
                                        "reason": reason})
            self._tracer.end_span(
                handle._span, t1=now,
                attrs={"state": state.value, "reason": reason,
                       "tokens": len(handle.tokens)})
            handle._span = None
        self.telemetry.on_finished(handle)

    def _release(self, slot: int) -> None:
        self._slot_req[slot] = None
        self._active[slot] = False
        self._remaining[slot] = 0
        self._steps[slot] = 0
        self._eos[slot] = -1
        self.executor.pool.release(slot)
