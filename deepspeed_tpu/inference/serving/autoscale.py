"""Elastic serving control plane: metrics-driven autoscaling + SLO admission.

DeepSpeed's elasticity pillar (elastic agent, bounded restarts, launcher-level
scale changes) re-imagined for the serving path: the loop from **live metrics**
(router queue depth, recent TTFT p95, slot occupancy) to **replica count and
admission decisions** is closed here, so a load swing changes capacity instead
of latency, and a doomed request is turned away at the front door instead of
expiring after burning decode steps.

Three pieces:

- :class:`ServiceTimeEstimator` — a small online model of what serving one
  request costs *right now*: EWMA first-token latency, EWMA seconds-per-token,
  the observed EOS fraction (how much of the requested budget is actually
  generated before EOS), and a windowed completion drain rate. It powers both
  the SLO admission check (``Router.submit`` sheds requests whose estimated
  completion misses their deadline — cheap, before prefill) and the
  load-adaptive ``retry_after`` hint on every backpressure rejection.
  The estimator refuses to guess blind: until ``min_observations``
  completions it reports ``None`` and admission never sheds.
- :class:`Autoscaler` — evaluated each pump step against hysteresis +
  cooldown: ``breach_evals`` consecutive breaching evaluations (queue depth
  per live replica above ``queue_high_per_replica``, or recent TTFT p95 above
  ``ttft_p95_slo_ms``) add a replica (spawned from ``engine_factory``, warmed
  through the router's RECOVERING half-open probe path — it serves one probe
  request before taking real load); ``idle_evals`` consecutive idle
  evaluations (empty queue, mean occupancy below ``occupancy_low``) retire the
  least-loaded replica through :meth:`~.router.Router.begin_retire`, whose
  drain/hand-off machinery migrates in-flight requests bit-identically
  (``lost == 0`` is the asserted contract). ``cooldown_s`` after any action
  keeps the scaler from fighting itself — or the circuit breaker.
- **replica-seconds accounting** — attached replicas integrated over wall
  time: the provisioned-capacity cost an autoscaled run is judged against a
  static-N deployment on (``BENCH_AUTOSCALE`` gates static-N at >= 2x).

Decisions are observable end to end: ``autoscale/scale_up_total`` /
``autoscale/scale_down_total`` / ``autoscale/replica_seconds`` counters and
the ``router/target_replicas`` gauge in the metrics registry, plus one
``autoscale/scale_up|scale_down`` tracer span per decision (cat
``autoscale``) carrying the triggering signals — the Perfetto view shows
*why* capacity changed next to the request lanes that caused it.

Threading: like the router, single-threaded — call :meth:`Autoscaler.step`
from the same loop that drives ``router.step()`` (the loadgen and
``deepspeed-serve --autoscale`` do exactly that).
"""

import time
from collections import deque
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional

import numpy as np

from ...observability import flight as obs_flight
from ...observability.metrics import RegistryFeed
from ...observability.trace import CAT_AUTOSCALE, get_tracer
from ...utils.logging import logger
from .router import ReplicaState, Router
from .telemetry import window_rate


@dataclass
class EstimatorConfig:
    alpha: float = 0.25            # EWMA weight for new observations
    min_observations: int = 3      # never shed before this many completions
    drain_window: int = 64         # completion timestamps for the drain rate
    drain_horizon_s: float = 10.0  # ignore completions older than this
    # cold-start priors are deliberately absent: an estimator with no
    # evidence returns None and the admission layer admits (never shed blind)


class ServiceTimeEstimator:
    """Online service-time model fed by completed requests.

    ``observe`` on every completion; ``estimate_s`` answers "if this request
    were admitted now, when would it finish?" as::

        wait_s  = queue_depth / drain_rate          (queue ahead of it)
        serve_s = ttft_ewma + expected_tokens * tpot_ewma

    ``ttft_ewma`` is measured arrival→first-token under recent load, so it
    already folds in historical queue wait — the explicit ``wait_s`` term
    makes the estimate respond to a queue that is *growing faster than the
    EWMA adapts*. The overlap biases the estimate conservative (sheds a
    borderline request rather than admitting a doomed one), which is the
    right direction for an admission gate.
    """

    def __init__(self, config: Optional[EstimatorConfig] = None):
        self.config = config or EstimatorConfig()
        self.ttft_s: Optional[float] = None    # EWMA arrival -> first token
        self.tpot_s: Optional[float] = None    # EWMA seconds per decode token
        self.eos_frac: Optional[float] = None  # EWMA generated / budget
        self.observations = 0
        self._finishes: deque = deque(maxlen=self.config.drain_window)

    def _ewma(self, old: Optional[float], new: float) -> float:
        a = self.config.alpha
        return new if old is None else (1 - a) * old + a * new

    def observe(self, ttft_s: Optional[float], tpot_s: Optional[float],
                generated: int, budget: int,
                now: Optional[float] = None) -> None:
        """One completed request: latency stats + how much of its budget it
        actually used (EOS statistics)."""
        now = time.monotonic() if now is None else now
        if ttft_s is not None:
            self.ttft_s = self._ewma(self.ttft_s, float(ttft_s))
        if tpot_s is not None:
            self.tpot_s = self._ewma(self.tpot_s, float(tpot_s))
        if budget > 0:
            self.eos_frac = self._ewma(
                self.eos_frac, min(1.0, float(generated) / float(budget)))
        self.observations += 1
        self._finishes.append(now)

    @property
    def ready(self) -> bool:
        return (self.observations >= self.config.min_observations
                and self.ttft_s is not None)

    def drain_rate(self, now: Optional[float] = None) -> Optional[float]:
        """Recent completions per second (None until two finishes land inside
        the horizon — a stale window must not report an ancient rate)."""
        now = time.monotonic() if now is None else now
        return window_rate(self._finishes, now, self.config.drain_horizon_s)

    def expected_tokens(self, max_new_tokens: int) -> float:
        """Requested budget discounted by the observed EOS fraction."""
        frac = 1.0 if self.eos_frac is None else self.eos_frac
        return max(1.0, float(max_new_tokens) * frac)

    def estimate_s(self, max_new_tokens: int, queue_depth: int = 0,
                   now: Optional[float] = None) -> Optional[float]:
        """Estimated admission→completion seconds, or None when not ready."""
        if not self.ready:
            return None
        serve = self.ttft_s + self.expected_tokens(max_new_tokens) \
            * (self.tpot_s or 0.0)
        rate = self.drain_rate(now)
        wait = queue_depth / rate if rate else 0.0
        return wait + serve

    def snapshot(self) -> Dict:
        return {"ready": self.ready, "observations": self.observations,
                "ttft_s": self.ttft_s, "tpot_s": self.tpot_s,
                "eos_frac": self.eos_frac,
                "drain_rate": self.drain_rate()}


@dataclass
class AutoscaleConfig:
    min_replicas: int = 1
    max_replicas: int = 4
    eval_interval_s: float = 0.05      # signal sampling period
    queue_high_per_replica: float = 3.0  # queued reqs per live replica -> up
    ttft_p95_slo_ms: Optional[float] = None  # recent-TTFT breach -> up
    ttft_window_min: int = 5           # recent TTFTs needed before the p95
    #   signal is trusted (a 1-sample "p95" is noise)
    signal_horizon_s: float = 10.0     # TTFT-p95 freshness: with no completion
    #   inside this window the p95 signal reads None — a surge's frozen tail
    #   must not pin breach=True forever after traffic stops (the scale-down
    #   half of the loop would deadlock at max_replicas)
    occupancy_low: float = 0.35        # smoothed live occupancy below -> down
    occupancy_alpha: float = 0.3       # EWMA weight for the occupancy signal
    #   (instantaneous occupancy of a small slot pool is nearly 0-or-1; the
    #   raw sample would reset the idle streak on every busy blink)
    breach_evals: int = 2              # consecutive breaches before scale-up
    idle_evals: int = 8                # consecutive idles before scale-down
    cooldown_s: float = 1.0            # quiet period before a SCALE-DOWN
    up_cooldown_s: Optional[float] = None  # quiet period before a SCALE-UP;
    #   None = cooldown_s / 4 — scale out fast (latency is bleeding), scale
    #   in slow (tearing capacity down too eagerly re-breaches immediately)
    retire_grace_s: float = 2.0        # in-flight drain window on scale-down

    @property
    def effective_up_cooldown_s(self) -> float:
        return (self.cooldown_s / 4.0 if self.up_cooldown_s is None
                else self.up_cooldown_s)

    def __post_init__(self):
        if self.min_replicas < 1:
            raise ValueError(f"min_replicas must be >= 1, "
                             f"got {self.min_replicas}")
        if self.max_replicas < self.min_replicas:
            raise ValueError(f"max_replicas ({self.max_replicas}) < "
                             f"min_replicas ({self.min_replicas})")


class Autoscaler:
    """Closes the metrics→capacity loop over a :class:`~.router.Router`.

    ``engine_factory`` is called once per scale-up and must return an engine
    whose weights are bit-identical to the existing replicas' (share replica
    0's params — the same contract ``_build_engines`` uses; the retry/drain
    parity guarantees assume it). Replicas it adds enter through the
    RECOVERING half-open probe path, so a cold replica proves itself on one
    request before taking real load.
    """

    def __init__(self, router: Router, engine_factory: Callable[[], object],
                 config: Optional[AutoscaleConfig] = None):
        self.router = router
        self.engine_factory = engine_factory
        self.config = cfg = config or AutoscaleConfig()
        if len(router.replicas) < cfg.min_replicas:
            raise ValueError(
                f"router starts with {len(router.replicas)} replica(s), "
                f"below min_replicas={cfg.min_replicas}")
        self.target_replicas = len(router.replicas)
        self.scale_ups = 0
        self.scale_downs = 0
        self.replica_seconds = 0.0
        self.decisions: deque = deque(maxlen=256)   # bounded decision log
        self._breach = 0
        self._idle = 0
        self._occ_ewma: Optional[float] = None
        self._evals = 0
        self._last_eval: Optional[float] = None
        self._last_tick: Optional[float] = None
        self._last_action: Optional[float] = None
        self._feed = RegistryFeed()
        self._tracer = get_tracer()

    # ----------------------------------------------------------------- signals
    def _active(self) -> List:
        """Replicas counted toward capacity: attached, not DEAD, not retiring
        (a retiring replica still drains but takes no new work)."""
        out = []
        for r in self.router.replicas:
            h = self.router.health[r.id]
            if h.state != ReplicaState.DEAD and not h.retiring:
                out.append(r)
        return out

    def signals(self, now: Optional[float] = None) -> Dict:
        now = time.monotonic() if now is None else now
        active = self._active()
        tel = self.router.telemetry
        recent = list(tel.recent_ttft_ms)
        finishes = self.router.estimator._finishes
        fresh = bool(finishes) and \
            now - finishes[-1] <= self.config.signal_horizon_s
        ttft_p95 = (float(np.percentile(recent, 95))
                    if fresh and len(recent) >= self.config.ttft_window_min
                    else None)
        occ = (float(np.mean([r.scheduler.executor.pool.occupancy
                              for r in active])) if active else 1.0)
        return {"queue_depth": self.router.queue_depth,
                "active_replicas": len(active),
                "attached_replicas": len(self.router.replicas),
                "ttft_p95_ms": ttft_p95, "occupancy": occ,
                "occupancy_ewma": self._occ_ewma}

    # ------------------------------------------------------------------- loop
    def step(self, now: Optional[float] = None) -> Optional[str]:
        """Accumulate replica-seconds every call; evaluate the policy at
        ``eval_interval_s``. Returns the action taken ("up"/"down") or None."""
        now = time.monotonic() if now is None else now
        if getattr(self.router, "draining", False):
            # SIGTERM drain owns the replica set from here: a scale-up racing
            # the drain flag would raise RouterDrainingError out of the
            # serving loop and skip the hand-off block entirely
            return None
        if self._last_tick is not None and now > self._last_tick:
            # retiring replicas still hold HBM until detached: they count
            self.replica_seconds += \
                (now - self._last_tick) * len(self.router.replicas)
        self._last_tick = now
        if (self._last_eval is not None
                and now - self._last_eval < self.config.eval_interval_s):
            return None
        self._last_eval = now
        return self._evaluate(now)

    def _evaluate(self, now: float) -> Optional[str]:
        cfg = self.config
        sig = self.signals(now)
        self._evals += 1
        self._emit(sig)
        n = sig["active_replicas"]
        a = cfg.occupancy_alpha
        self._occ_ewma = (sig["occupancy"] if self._occ_ewma is None
                          else (1 - a) * self._occ_ewma
                          + a * sig["occupancy"])
        sig["occupancy_ewma"] = self._occ_ewma
        breach = (sig["queue_depth"] > cfg.queue_high_per_replica * max(1, n)
                  or (cfg.ttft_p95_slo_ms is not None
                      and sig["ttft_p95_ms"] is not None
                      and sig["ttft_p95_ms"] > cfg.ttft_p95_slo_ms))
        idle = (not breach and sig["queue_depth"] == 0
                and self._occ_ewma < cfg.occupancy_low)
        # hysteresis: consecutive-evaluation counters, each reset by the other
        self._breach = self._breach + 1 if breach else 0
        self._idle = self._idle + 1 if idle else 0
        since_action = (None if self._last_action is None
                        else now - self._last_action)
        # the ceiling bounds ATTACHED capacity too: a DEAD replica may later
        # recover through the breaker, and active-only accounting would let
        # the set grow past max_replicas in the meantime
        n_attached = len([r for r in self.router.replicas
                          if not self.router.health[r.id].retiring])
        if (self._breach >= cfg.breach_evals
                and (since_action is None
                     or since_action >= cfg.effective_up_cooldown_s)
                and n < cfg.max_replicas and n_attached < cfg.max_replicas):
            return self._scale_up(now, sig)
        if (self._idle >= cfg.idle_evals
                and (since_action is None or since_action >= cfg.cooldown_s)
                and n > cfg.min_replicas):
            return self._scale_down(now, sig)
        return None

    # ---------------------------------------------------------------- actions
    def _scale_up(self, now: float, sig: Dict) -> str:
        span = self._tracer.begin("autoscale/scale_up", cat=CAT_AUTOSCALE,
                                  tid="autoscale", attrs=dict(sig))
        engine = self.engine_factory()
        replica = self.router.add_replica(engine, warm=True)
        self.scale_ups += 1
        self.target_replicas = sig["active_replicas"] + 1
        self._last_action = now
        self._breach = self._idle = 0
        self.decisions.append({"t": now, "action": "up",
                               "replica": replica.id, **sig})
        self._tracer.end_span(span, attrs={"replica": replica.id,
                                           "target": self.target_replicas})
        obs_flight.journal("scale_up", replica=replica.id,
                           target=self.target_replicas, **sig)
        logger.info(f"[autoscale] scale UP -> replica {replica.id} "
                    f"(queue={sig['queue_depth']}, "
                    f"ttft_p95={sig['ttft_p95_ms']}, "
                    f"active={sig['active_replicas']})")
        self._emit(sig)
        return "up"

    def _scale_down(self, now: float, sig: Dict) -> Optional[str]:
        # least-loaded LIVE victim; never the last min_replicas
        cands = [r for r in self._active()
                 if self.router.health[r.id].state == ReplicaState.LIVE]
        if len(cands) <= self.config.min_replicas:
            return None
        victim = min(cands, key=lambda r: (r.outstanding, -r.id))
        span = self._tracer.begin("autoscale/scale_down", cat=CAT_AUTOSCALE,
                                  tid="autoscale",
                                  attrs={**sig, "replica": victim.id})
        # deliberately NOT forwarding this evaluation's (possibly injected)
        # `now`: the retire grace deadline is checked by Router.step's clock,
        # and a synthetic scaler clock against the router's real one would
        # expire the grace window instantly (or never)
        self.router.begin_retire(victim.id,
                                 grace_s=self.config.retire_grace_s)
        self.scale_downs += 1
        self.target_replicas = max(self.config.min_replicas,
                                   sig["active_replicas"] - 1)
        self._last_action = now
        self._breach = self._idle = 0
        self.decisions.append({"t": now, "action": "down",
                               "replica": victim.id, **sig})
        self._tracer.end_span(span, attrs={"target": self.target_replicas})
        obs_flight.journal("scale_down", replica=victim.id,
                           target=self.target_replicas, **sig)
        logger.info(f"[autoscale] scale DOWN -> retiring replica {victim.id} "
                    f"(occupancy={sig['occupancy']:.2f}, "
                    f"active={sig['active_replicas']})")
        self._emit(sig)
        return "down"

    # -------------------------------------------------------------- telemetry
    def _emit(self, sig: Dict) -> None:
        self._feed.record_events([
            ("router/target_replicas", float(self.target_replicas),
             self._evals),
            ("autoscale/scale_up_total", float(self.scale_ups), self._evals),
            ("autoscale/scale_down_total", float(self.scale_downs),
             self._evals),
            ("autoscale/replica_seconds", float(self.replica_seconds),
             self._evals),
        ])

    @property
    def transient_s(self) -> float:
        """The control loop's documented reaction window: how long a breach
        can legitimately go unanswered (detection + up-cooldown) plus the
        retire grace on the way down. Benches use it as the latency allowance
        an autoscaled lane gets over an always-provisioned one."""
        cfg = self.config
        return (cfg.breach_evals * cfg.eval_interval_s
                + cfg.effective_up_cooldown_s + cfg.retire_grace_s)

    def report(self) -> Dict:
        """BENCH-JSON-shaped summary of what the control loop did."""
        return {"target_replicas": self.target_replicas,
                "transient_s": self.transient_s,
                "attached_replicas": len(self.router.replicas),
                "scale_ups": self.scale_ups,
                "scale_downs": self.scale_downs,
                "replica_seconds": self.replica_seconds,
                "evaluations": self._evals,
                "decisions": list(self.decisions),
                "estimator": (self.router.estimator.snapshot()
                              if self.router.estimator is not None else None)}
