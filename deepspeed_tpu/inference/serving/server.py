"""``deepspeed-serve``: the serving-subsystem entrypoint.

Two modes over the same frontend (a single scheduler, or — with
``--replicas N`` — the health-supervised multi-replica :class:`Router`):

- **stdin mode** (default): read one JSON request per line
  (``{"prompt": [ids...], "max_new_tokens": 16, "eos_token_id": null,
  "deadline_s": null, "seed": 0, "session": null}``), stream one JSON result per
  completed request to stdout (tokens + TTFT/TPOT + finish reason), then a final
  summary line. Backpressured submissions are retried after the scheduler's hint.
- **--selftest**: synthesize a small random-weight model and a burst of random
  requests; exit 0 iff every request completes. With ``--replicas >= 2`` the
  selftest is a kill-and-retry round trip: a replica is killed mid-decode and
  the run passes only if every request still completes with greedy outputs
  bit-identical to an unkilled run (checkpointless retry proven end-to-end).

``--prefix-cache`` enables the radix prompt-prefix KV cache (per replica:
shared system prompts skip prefill, greedy outputs bit-identical to cache-off;
``--prefix-cache-mb`` bounds the slab HBM budget).
``--autoscale --min-replicas N --max-replicas M`` attaches the elastic control
plane (``serving.autoscale``): replica count follows queue depth / recent TTFT
p95 with hysteresis + cooldown, scale-up warms through the RECOVERING probe,
scale-down retires gracefully (in-flight requests migrate bit-identically).
``--slo-admission`` sheds requests whose estimated completion misses their
``deadline_s`` at admission (an ``{"error": ...}`` line with the retry-after
hint) instead of letting them expire after burning decode steps.
``--host-replicas`` hosts each replica in its OWN supervised child process
(``serving.host``): replicas pump concurrently instead of sharing one serial
loop, chaos ``kill``/``stall`` deliver real SIGKILL/SIGSTOP, and a
``ReplicaSupervisor`` respawns dead children with exponential backoff under
``--max-restarts`` (exhausted budget pins the replica DEAD; survivors keep
serving). ``/statusz`` then carries child PIDs and restart counts.
``--chaos "<spec>"`` schedules replica kills/stalls (see ``serving.chaos``), and
a ``DS_TPU_FAULT_SPEC`` env (``utils.fault_injection.fault_env``) is armed at
startup — the hook chaos tests use to inject deterministically into
subprocess-hosted serve processes. Metrics go to the jsonl monitor backend when
``--jsonl-metrics DIR`` is given.

Observability (PR 10, ``docs/OBSERVABILITY.md``):

- ``--metrics-port P`` serves Prometheus text exposition at
  ``http://127.0.0.1:P/metrics`` from the process metrics registry (the same
  counters the BENCH JSON reports);
- ``--trace-out FILE`` enables the request-scoped span tracer and writes a
  Perfetto-loadable Chrome trace on exit (``FILE.jsonl`` alongside it when the
  path ends in ``.json``... pass a ``.jsonl`` path to stream spans instead);
- ``--profile-dir DIR [--profile-steps N]`` arms on-demand XLA profiler
  capture: ``kill -USR2 <pid>`` captures the next N decode chunks/prefills to
  DIR (TensorBoard/Perfetto-loadable device trace).
"""

import argparse
import json
import sys
import time
from typing import Optional

import numpy as np


def _build_engine(args, params=None):
    import jax.numpy as jnp

    from ...models.causal_lm import gpt2_cfg, llama_cfg
    from ..config import DeepSpeedInferenceConfig
    from ..engine import InferenceEngine
    family = {"gpt2": gpt2_cfg, "llama": llama_cfg}[args.family]
    cfg = family(vocab_size=args.vocab_size, max_seq_len=args.max_seq_len,
                 n_embd=args.n_embd, n_layer=args.n_layer, n_head=args.n_head,
                 dtype={"float32": jnp.float32, "bfloat16": jnp.bfloat16}
                 [args.dtype])
    engine = InferenceEngine(cfg, DeepSpeedInferenceConfig(
        dtype=args.dtype, max_out_tokens=args.max_seq_len,
        tensor_parallel={"tp_size": args.tp}), params=params)
    if args.checkpoint:
        engine.load_checkpoint(args.checkpoint)
    return engine


def _build_engines(args, n: int):
    """N replica engines with SHARED weights (replica 0's params are reused —
    bit-identical replicas, init cost paid once; params are never donated, so
    sharing the buffers is safe)."""
    first = _build_engine(args)
    return [first] + [_build_engine(args, params=first.params)
                      for _ in range(n - 1)]


def _close_hosts(front) -> None:
    """Stop every hosted replica's child through the escalation ladder (a
    no-op for in-process replicas / the single-scheduler front)."""
    for r in getattr(front, "replicas", []):
        if getattr(r, "is_hosted", False):
            r.close()


def _make_monitor(args) -> Optional[object]:
    if not args.jsonl_metrics:
        return None
    from ...config.config import MonitorConfig
    from ...monitor import MonitorMaster
    return MonitorMaster(MonitorConfig(jsonl_monitor={
        "enabled": True, "output_path": args.jsonl_metrics,
        "job_name": "deepspeed-serve"}))


def make_status_provider(front, autoscaler=None, recorder=None,
                         detector=None, supervisor=None):
    """``/statusz`` JSON assembler over a serving frontend (scheduler or
    router): replica health + outstanding work (hosted replicas add child
    PID + restart count), queue depth, degradation rung, paged-KV pressure,
    prefix hit rate, recent anomaly trips, the last autoscale decisions with
    their triggering signals, the replica supervisor's restart/pinned
    accounting, and the flight recorder's retention stats."""
    is_router = hasattr(front, "replicas")

    def status():
        doc = {"t": time.time(),
               "kind": "router" if is_router else "scheduler"}
        if is_router:
            tel = front.telemetry
            doc.update({
                "queue_depth": front.queue_depth,
                "draining": front.draining,
                "degradation_rung": front.degradation_rung.value,
                "degradation_rung_name": front.degradation_rung.name,
                "replicas": [
                    {"id": r.id,
                     "health": front.health[r.id].state.value,
                     "outstanding": r.outstanding,
                     "running": r.running,
                     "queued": r.queued,
                     "retiring": front.health[r.id].retiring,
                     **({"pid": r.child_pid, "restarts": r.restarts,
                         "prefix_hit_rate": (
                             r.scheduler.prefix_hit_rate
                             if r.scheduler.prefix_cache_report().get(
                                 "child") else None)}
                        if getattr(r, "is_hosted", False) else {}),
                     **({"severed": r.severed,
                         "reconnects": r.reconnects,
                         "rtt_ms": r.rtt_ms()}
                        if getattr(r, "is_socket", False) else {})}
                    for r in front.replicas],
                "retired_replicas": list(front.retired),
                "counters": {
                    "submitted": tel.submitted, "completed": tel.completed,
                    "retried": tel.retried, "evicted": tel.evicted,
                    "rejected": tel.rejected, "shed": tel.shed,
                    "deferred": tel.deferred, "expired": tel.expired,
                    "handed_off": tel.handed_off},
            })
            pools = [r.scheduler.executor.pool for r in front.replicas]
            paged = [p.stats() for p in pools if p.paged]
            if paged:
                doc["pages"] = {
                    "pages_in_use": sum(p["pages_in_use"] for p in paged),
                    "total_pages": sum(p["total_pages"] for p in paged),
                    "page_fragmentation": (
                        float(np.mean([p["page_fragmentation"]
                                       for p in paged]))),
                    "prefix_shared_pages": sum(p["prefix_shared_pages"]
                                               for p in paged)}
            if any(r.scheduler.prefix_cache is not None
                   for r in front.replicas):
                rep = front.prefix_cache_report()
                doc["prefix_hit_rate"] = rep.get("hit_rate")
            # fleet KV economy (PR 19): admission-level hit rate + tiered
            # byte/movement counters across in-process AND hosted replicas
            # (hosted numbers come from heartbeat gossip)
            if front._kv_economy_enabled():
                kv = front.kv_economy_report()
                doc["kv_economy"] = {
                    "fleet_hit_rate": kv["fleet_hit_rate"],
                    "prefill_tokens_skipped": kv["prefill_tokens_skipped"],
                    "cached_bytes": kv["cached_bytes"],
                    "spilled_bytes": kv["spilled_bytes"],
                    "spills_total": kv["spills_total"],
                    "promotions_total": kv["promotions_total"],
                    "prefix_routed": kv["prefix_routed"],
                    "prefix_saved_tokens": kv["prefix_saved_tokens"]}
            specs = [r.scheduler.telemetry.spec for r in front.replicas
                     if getattr(r.scheduler.telemetry, "spec_enabled", False)]
            if specs:
                proposed = sum(s.proposed for s in specs)
                doc["speculative"] = {
                    "proposed": proposed,
                    "accepted": sum(s.accepted for s in specs),
                    "acceptance_rate": (sum(s.accepted for s in specs)
                                        / proposed if proposed else 0.0),
                    "passes_per_token": (
                        sum(s.rounds for s in specs)
                        / max(1, sum(s.tokens for s in specs)))}
        else:
            tel = front.telemetry
            pool = front.executor.pool
            doc.update({
                "queue_depth": front.queue_depth,
                "slot_occupancy": pool.occupancy,
                "counters": {"completed": tel.completed,
                             "rejected": tel.rejected,
                             "cancelled": tel.cancelled,
                             "expired": tel.expired,
                             "evicted": tel.evicted,
                             "tokens_total": tel.tokens_total},
            })
            if pool.paged:
                doc["pages"] = pool.stats()
            if front.prefix_cache is not None:
                doc["prefix_hit_rate"] = front.prefix_hit_rate
            if getattr(tel, "spec_enabled", False):
                s = tel.spec
                doc["speculative"] = {
                    "proposed": s.proposed, "accepted": s.accepted,
                    "acceptance_rate": s.acceptance_rate,
                    "passes_per_token": s.passes_per_token}
        if autoscaler is not None:
            doc["autoscale"] = {
                "target_replicas": autoscaler.target_replicas,
                "scale_ups": autoscaler.scale_ups,
                "scale_downs": autoscaler.scale_downs,
                "last_decisions": list(autoscaler.decisions)[-5:]}
        if supervisor is not None:
            doc["hosts"] = supervisor.report()
        if detector is not None:
            doc["anomalies"] = {"trips": detector.trips,
                                "recent": list(detector.recent)[-8:]}
        if recorder is not None:
            doc["flight"] = recorder.stats()
        return doc

    return status


def make_health_provider(front):
    """``/healthz`` liveness/readiness: the process answering IS liveness;
    readiness = at least one LIVE replica AND the degradation ladder below
    ADMISSION_CLOSED (a router that rejects every submission is alive but not
    ready). The single-scheduler path is ready whenever it answers."""
    is_router = hasattr(front, "replicas")

    def health():
        if not is_router:
            return True, {"live": True, "ready": True, "kind": "scheduler"}
        from .router import DegradationRung, ReplicaState
        live = sum(1 for r in front.replicas
                   if front.health[r.id].state == ReplicaState.LIVE)
        rung = front.degradation_rung
        ready = (live >= 1
                 and rung.value < DegradationRung.ADMISSION_CLOSED.value
                 and not front.draining)
        return ready, {"live": True, "ready": ready, "kind": "router",
                       "live_replicas": live,
                       "degradation_rung": rung.value,
                       "draining": front.draining}

    return health


def _result_line(h) -> str:
    return json.dumps({
        "id": h.id, "state": h.state.value, "finish_reason": h.finish_reason,
        "tokens": [int(t) for t in h.tokens],
        "ttft_ms": None if h.ttft is None else h.ttft * 1e3,
        "tpot_ms": None if h.tpot is None else h.tpot * 1e3,
    })


def _serve_stdin(sched, out=sys.stdout, inp=None, chaos=None,
                 autoscaler=None, supervisor=None):
    """Streaming serve loop: requests are admitted as their lines arrive (a
    reader thread feeds a queue, so a client may keep the pipe open and read
    results before sending more) and each result is emitted the moment its
    request completes. A malformed or inadmissible line fails alone — an
    ``{"error": ...}`` line is emitted and serving continues.

    ``sched`` is any frontend with the scheduler protocol (``submit`` /
    ``step`` / ``busy`` / ``telemetry``) — a single
    :class:`ContinuousBatchingScheduler` or a multi-replica :class:`Router`
    (router-only fields like ``session`` are forwarded when present).
    ``chaos`` is an optional :class:`~.chaos.ChaosSchedule` polled every loop.
    """
    import queue as _queue
    import threading

    from .router import AdmissionShedError
    from .scheduler import QueueFullError
    inp = inp if inp is not None else sys.stdin
    is_router = hasattr(sched, "replicas")
    lines: "_queue.Queue" = _queue.Queue()
    _EOF = object()

    def _reader():
        for line in inp:
            lines.put(line)
        lines.put(_EOF)

    threading.Thread(target=_reader, daemon=True).start()
    handles, pending, eof = [], [], False
    not_before = 0.0
    while not eof or pending or sched.busy:
        if is_router and sched.draining:
            break                            # SIGTERM: graceful drain below
        if chaos is not None:
            chaos.poll(sched)
        if autoscaler is not None:
            autoscaler.step()
        if supervisor is not None:
            supervisor.step()       # respawn dead hosted replicas (backoff)
        while True:                          # drain whatever the reader has
            try:
                line = lines.get_nowait()
            except _queue.Empty:
                break
            if line is _EOF:
                eof = True
                break
            if line.strip():
                pending.append(line.strip())
        while pending and time.monotonic() >= not_before:
            try:
                req = json.loads(pending[0])
                kwargs = dict(max_new_tokens=req.get("max_new_tokens"),
                              eos_token_id=req.get("eos_token_id"),
                              deadline_s=req.get("deadline_s"),
                              seed=req.get("seed", 0))
                if is_router:
                    kwargs["session"] = req.get("session")
                    kwargs["priority"] = req.get("priority", 0)
                handles.append(sched.submit(
                    np.asarray(req["prompt"], np.int32), **kwargs))
                pending.pop(0)
            except AdmissionShedError as e:  # SLO shed is TERMINAL for this
                # line: its deadline re-anchors at every resubmission, so a
                # deadline below bare service time would re-shed forever and
                # head-of-line-block every later request — fail it with the
                # hint and keep serving (checked before its QueueFullError
                # parent, which IS worth resubmitting)
                out.write(json.dumps({"error": f"shed: {e}",
                                      "retry_after": e.retry_after,
                                      "line": pending.pop(0)[:200]}) + "\n")
            except QueueFullError as e:      # backpressure: drain, then resubmit
                not_before = time.monotonic() + e.retry_after
                break
            except Exception as e:           # bad line: fail it, keep serving
                out.write(json.dumps({"error": f"{type(e).__name__}: {e}",
                                      "line": pending.pop(0)[:200]}) + "\n")
        if sched.busy or (is_router and getattr(sched, "retiring_pending",
                                                False)):
            # an idle scale-down still needs steps: only the router's retire
            # sweep detaches a RETIRING replica, and idle is exactly when
            # scale-downs happen
            sched.step()
        elif not eof or pending:
            time.sleep(0.01)                 # idle: await input, don't spin
        for h in [h for h in handles if h.done]:
            out.write(_result_line(h) + "\n")
            handles.remove(h)
    if is_router and sched.draining:
        # graceful drain: finish in-flight chunks, then emit a hand-off spec
        # per unfinished request (re-submittable on another router) and an
        # error line per never-admitted client line — nothing silently dropped
        for spec in sched.drain():
            out.write(json.dumps({"handoff": spec}) + "\n")
        for line in pending:
            out.write(json.dumps({"error": "draining", "line": line[:200]})
                      + "\n")
        for h in handles:
            out.write(_result_line(h) + "\n")
    return (sched.snapshot() if is_router else sched.telemetry.snapshot())


def _selftest(sched, n_requests: int, vocab: int, seed: int = 0):
    rng = np.random.default_rng(seed)
    handles = []
    from .scheduler import QueueFullError
    reqs = [(rng.integers(0, vocab, size=int(rng.integers(3, 12))).astype(np.int32),
             int(rng.integers(2, 10))) for _ in range(n_requests)]
    while reqs or sched.busy:
        while reqs:
            prompt, max_new = reqs[0]
            try:
                handles.append(sched.submit(prompt, max_new_tokens=max_new))
                reqs.pop(0)
            except QueueFullError:
                break
        sched.step()
    ok = all(h.state.value == "finished" for h in handles)
    return ok, sched.telemetry.snapshot()


def _selftest_router(router, engines, n_requests: int, vocab: int,
                     seed: int = 0):
    """Kill-and-retry round trip: submit a burst of greedy requests, kill one
    replica the moment it is mid-decode, and require (1) every request
    completes, (2) at least one was evicted+retried, (3) every output is
    bit-identical to the unkilled per-request ``generate`` reference."""
    from .chaos import ChaosEvent, ChaosSchedule
    from .scheduler import QueueFullError
    rng = np.random.default_rng(seed)
    reqs = [(rng.integers(0, vocab, size=int(rng.integers(4, 10))
                          ).astype(np.int32),
             int(rng.integers(8, 16))) for _ in range(n_requests)]
    victim = len(router.replicas) - 1
    chaos = ChaosSchedule([ChaosEvent(kind="kill", replica=victim,
                                      when="busy")])
    pending = list(reqs)
    handles = []
    while pending or router.busy:
        chaos.poll(router)
        while pending:
            prompt, max_new = pending[0]
            try:
                handles.append(router.submit(prompt, max_new_tokens=max_new))
                pending.pop(0)
            except QueueFullError:
                break
        router.step()
    snap = router.snapshot()
    ok = all(h.state.value == "finished" for h in handles)
    retried = sum(h.retried for h in handles)
    parity = True
    for h, (prompt, max_new) in zip(handles, reqs):
        ref = engines[0].generate(prompt[None, :], max_new_tokens=max_new)
        if not np.array_equal(h.result(), np.asarray(ref)[0, prompt.size:]):
            parity = False
    snap["kill_fired"] = chaos.exhausted
    snap["retried_requests"] = retried
    snap["parity_ok"] = parity
    ok = ok and parity and snap["lost"] == 0 and chaos.exhausted and retried > 0
    return ok, snap


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="deepspeed-serve", description=__doc__)
    ap.add_argument("--family", default="gpt2", choices=("gpt2", "llama"))
    ap.add_argument("--vocab-size", type=int, default=256)
    ap.add_argument("--max-seq-len", type=int, default=128)
    ap.add_argument("--n-embd", type=int, default=64)
    ap.add_argument("--n-layer", type=int, default=2)
    ap.add_argument("--n-head", type=int, default=4)
    ap.add_argument("--dtype", default="float32", choices=("float32", "bfloat16"))
    ap.add_argument("--tp", type=int, default=1)
    ap.add_argument("--checkpoint", default=None,
                    help="training checkpoint dir to serve")
    ap.add_argument("--slots", type=int, default=4)
    ap.add_argument("--chunk-size", type=int, default=8)
    ap.add_argument("--kv-pool", default="paged", choices=("paged", "slots"),
                    help="KV memory shape: 'paged' (default) = fixed-size "
                         "pages behind per-slot page tables (page-count "
                         "admission, zero-copy refcounted prefix sharing); "
                         "'slots' = legacy cap-row-per-slot pool")
    ap.add_argument("--kv-page-size", type=int, default=16,
                    help="KV page size in tokens (paged pool; default 16). "
                         "Must be a positive multiple of --chunk-size so "
                         "page boundaries stay chunk-aligned")
    ap.add_argument("--speculate", action="store_true",
                    help="speculative decoding: every decode chunk becomes "
                         "one draft-propose / one-pass-verify round (n-gram "
                         "self-speculation — greedy output is bit-identical, "
                         "sampled stays exactly target-distributed)")
    ap.add_argument("--spec-k", type=int, default=4,
                    help="draft tokens per verify window (default 4)")
    ap.add_argument("--spec-ngram-max", type=int, default=4,
                    help="longest suffix n-gram the proposer matches "
                         "(default 4; tried down to 1)")
    ap.add_argument("--max-queue", type=int, default=32)
    ap.add_argument("--replicas", type=int, default=1,
                    help=">=2 serves through the multi-replica router")
    ap.add_argument("--host-replicas", action="store_true",
                    help="host each replica in its OWN supervised child "
                         "process (serving.host): replicas pump concurrently "
                         "instead of sharing one serial loop, chaos kills/"
                         "stalls deliver real SIGKILL/SIGSTOP, and a "
                         "ReplicaSupervisor respawns dead children with "
                         "exponential backoff under --max-restarts")
    ap.add_argument("--host-transport", default="stdio",
                    choices=("stdio", "socket"),
                    help="hosted-replica transport: 'stdio' (default) = "
                         "JSONL over the child's stdin/stdout pipe; "
                         "'socket' = the same protocol v1 carried in "
                         "length-prefixed CRC-framed TCP (serving.net) with "
                         "session-token redial, so a severed connection "
                         "evicts-and-retries instead of killing the child")
    ap.add_argument("--replica-endpoint", action="append", default=None,
                    metavar="HOST:PORT",
                    help="adopt an ALREADY-RUNNING socket replica child "
                         "(started with --serve-socket --listen) at this "
                         "address instead of spawning one; repeatable — each "
                         "endpoint becomes one router member. Implies the "
                         "hosted-router path; geometry flags must match the "
                         "remote child's")
    ap.add_argument("--max-restarts", type=int, default=3,
                    help="per-replica child respawn budget (hosted replicas; "
                         "exhausted -> pinned DEAD, survivors keep serving)")
    ap.add_argument("--restart-backoff", type=float, default=0.5,
                    help="base seconds of the exponential respawn backoff")
    ap.add_argument("--autoscale", action="store_true",
                    help="metrics-driven autoscaling: start at --min-replicas "
                         "and let the control plane scale within "
                         "[--min-replicas, --max-replicas] from queue depth "
                         "and recent TTFT p95")
    ap.add_argument("--min-replicas", type=int, default=1)
    ap.add_argument("--max-replicas", type=int, default=4)
    ap.add_argument("--slo-admission", action="store_true",
                    help="SLO-aware admission: requests whose estimated "
                         "completion misses their deadline_s are shed at "
                         "admission with a load-adaptive retry_after")
    ap.add_argument("--chaos", default=None,
                    help="chaos spec, e.g. 'kill:replica=1,at=0.5;"
                         "stall:replica=0,when=busy,s=0.6' (see serving.chaos)")
    ap.add_argument("--chunk-deadline", type=float, default=None,
                    help="per-chunk watchdog deadline in seconds")
    ap.add_argument("--prefix-cache", action="store_true",
                    help="enable the radix prompt-prefix KV cache (shared "
                         "system prompts skip prefill; greedy outputs stay "
                         "bit-identical to cache-off)")
    ap.add_argument("--prefix-cache-mb", type=float, default=256.0,
                    help="prefix-cache HBM byte budget (MiB)")
    ap.add_argument("--prefix-tier-mb", type=float, default=0.0,
                    help="host-RAM rung under the HBM budget (MiB, 0 = off): "
                         "LRU-evicted prefix entries spill here as dense "
                         "slabs and promote back on a later hit (a slab "
                         "copy instead of a re-prefill)")
    ap.add_argument("--prefix-min-hit", type=int, default=8,
                    help="minimum matched tokens for a cache hit")
    ap.add_argument("--prefix-aware-routing", action="store_true",
                    help="score dispatch by expected prefill-tokens-saved "
                         "(in-process trie probe / hosted heartbeat digest "
                         "gossip) against outstanding load; session affinity "
                         "demotes to a tiebreaker")
    ap.add_argument("--jsonl-metrics", default=None,
                    help="directory for the jsonl monitor backend")
    ap.add_argument("--metrics-port", type=int, default=None,
                    help="serve Prometheus /metrics exposition on this port")
    ap.add_argument("--trace-out", default=None,
                    help="enable request-scoped tracing; write a "
                         "Perfetto-loadable Chrome trace here on exit")
    ap.add_argument("--flight-out", default=None,
                    help="enable the tail-latency flight recorder + anomaly "
                         "detector (implies tracing); write the Perfetto-"
                         "loadable flight bundle here on exit — SIGUSR1, "
                         "router drain, and anomaly trips write numbered "
                         "siblings (SIGUSR2 stays the XLA profiler)")
    ap.add_argument("--profile-dir", default=None,
                    help="arm on-demand XLA profiler capture to this logdir "
                         "(trigger with SIGUSR2)")
    ap.add_argument("--profile-steps", type=int, default=4,
                    help="decode chunks/prefills per profiler capture")
    ap.add_argument("--selftest", action="store_true")
    ap.add_argument("--requests", type=int, default=8,
                    help="selftest request count")
    args = ap.parse_args(argv)

    # a seeded fault schedule may have been serialized into our environment by
    # a parent chaos harness (utils.fault_injection.fault_env)
    from ...utils.fault_injection import apply_fault_env
    apply_fault_env()

    # observability spine: tracer / flight recorder / Prometheus exposition /
    # status plane / profiler capture
    from ...observability import (AnomalyDetector, FlightRecorder,
                                  configure_capture, get_registry, get_tracer,
                                  start_metrics_server)
    from ...observability.anomaly import install_detector
    tracer = None
    if args.trace_out or args.flight_out:
        tracer = get_tracer().enable(pid_label="deepspeed-serve")
        if args.trace_out and args.trace_out.endswith(".jsonl"):
            tracer.stream_to(args.trace_out)
    recorder = detector = None
    if args.flight_out:
        recorder = FlightRecorder(dump_path=args.flight_out).attach(tracer)
        recorder.install_sigusr1()          # SIGUSR2 stays the XLA profiler
        detector = AnomalyDetector(recorder=recorder)
        install_detector(detector)
        get_registry().attach_monitor(detector)
    metrics_server = None
    # the front doesn't exist yet when the port opens: the providers read a
    # late-bound slot, and /healthz honestly reports not-ready until it lands
    _providers = {"status": None, "health": None}

    def _statusz():
        fn = _providers["status"]
        return fn() if fn is not None else {"starting": True}

    def _healthz():
        fn = _providers["health"]
        if fn is None:
            return False, {"live": True, "ready": False, "starting": True}
        return fn()

    if args.metrics_port is not None:
        metrics_server = start_metrics_server(args.metrics_port,
                                              status_provider=_statusz,
                                              health_provider=_healthz)
        print(json.dumps({"metrics_port": metrics_server.server_port}),
              file=sys.stderr)
    if args.profile_dir:
        configure_capture(args.profile_dir, num_ticks=args.profile_steps)

    def _obs_epilogue():
        # every exit path (selftest included) must land the trace/bundle the
        # user asked for and release the exposition port
        if recorder is not None:
            path = recorder.dump(args.flight_out, reason="exit")
            print(json.dumps({"flight_out": path, **recorder.stats()}),
                  file=sys.stderr)
            get_registry().detach_monitor(detector)
            install_detector(None)
            recorder.detach()
        if tracer is not None:
            if args.trace_out and not args.trace_out.endswith(".jsonl"):
                n = tracer.export_chrome(args.trace_out)
                print(json.dumps({"trace_out": args.trace_out, "spans": n}),
                      file=sys.stderr)
            tracer.close_stream()
        if metrics_server is not None:
            metrics_server.shutdown()

    from .prefix_cache import PrefixCacheConfig
    from .scheduler import ContinuousBatchingScheduler, ServingConfig
    prefix_cfg = None
    if args.prefix_cache:
        prefix_cfg = PrefixCacheConfig(
            max_bytes=int(args.prefix_cache_mb * 1024 * 1024),
            host_tier_bytes=int(args.prefix_tier_mb * 1024 * 1024),
            min_hit_tokens=args.prefix_min_hit,
            min_insert_tokens=args.prefix_min_hit)
    if args.kv_pool == "paged" and (
            args.kv_page_size < 1
            or args.kv_page_size % args.chunk_size != 0):
        raise SystemExit(
            f"--kv-page-size {args.kv_page_size} must be a positive multiple "
            f"of --chunk-size {args.chunk_size} (page boundaries stay "
            "chunk-aligned)")
    serving_cfg = ServingConfig(slots=args.slots, chunk_size=args.chunk_size,
                                max_queue=args.max_queue,
                                max_seq_len=args.max_seq_len,
                                chunk_deadline_s=args.chunk_deadline,
                                prefix_cache=prefix_cfg,
                                kv_pool=args.kv_pool,
                                kv_page_size=args.kv_page_size,
                                speculate=args.speculate, spec_k=args.spec_k,
                                spec_ngram_max=args.spec_ngram_max)
    monitor = _make_monitor(args)
    if recorder is not None:
        # mirror per-request attribution events into the monitor backend
        # (telemetry already feeds both monitor and registry directly)
        recorder.monitor = monitor
    chaos = None
    autoscaler = None
    supervisor = None
    # SLO admission lives on the Router: a bare --slo-admission must not
    # silently degrade to the admission-blind single-scheduler path
    if args.replicas > 1 or args.autoscale or args.slo_admission \
            or args.host_replicas or args.replica_endpoint:
        from .autoscale import Autoscaler, AutoscaleConfig
        from .chaos import ChaosSchedule, parse_chaos
        from .router import Router, RouterConfig
        if args.autoscale and args.replicas > args.max_replicas:
            raise SystemExit(f"--replicas {args.replicas} exceeds "
                             f"--max-replicas {args.max_replicas}")
        # with --autoscale an explicit --replicas sets the STARTING size
        # (bounded below by --min-replicas), it is not silently discarded
        n0 = (max(args.min_replicas, args.replicas) if args.autoscale
              else args.replicas)
        rcfg = RouterConfig(serving=serving_cfg, max_queue=args.max_queue,
                            slo_admission=args.slo_admission,
                            prefix_aware_routing=args.prefix_aware_routing)
        if args.host_replicas or args.replica_endpoint:
            from .host import (HostConfig, HostedReplica, ReplicaSupervisor,
                               SocketHostedReplica, SupervisorConfig)
            if args.checkpoint:
                raise SystemExit("--host-replicas serves the deterministic-"
                                 "init model; --checkpoint does not cross "
                                 "the pipe")
            if args.dtype != "float32" or args.tp != 1:
                raise SystemExit("--host-replicas children build float32 "
                                 "tp=1 engines (the determinism contract "
                                 "behind bit-exact retry parity)")
            # serving knobs cross the pipe as child argv (HostConfig.dims):
            # each child builds its own prefix cache / paged pool / watchdog
            hcfg = HostConfig(
                family=args.family, vocab_size=args.vocab_size,
                max_seq_len=args.max_seq_len, n_embd=args.n_embd,
                n_layer=args.n_layer, n_head=args.n_head, slots=args.slots,
                chunk_size=args.chunk_size,
                prefix_cache=args.prefix_cache,
                prefix_cache_mb=(args.prefix_cache_mb
                                 if args.prefix_cache else None),
                prefix_tier_mb=(args.prefix_tier_mb
                                if args.prefix_cache and args.prefix_tier_mb
                                else None),
                prefix_min_hit=(args.prefix_min_hit
                                if args.prefix_cache else None),
                kv_pool=args.kv_pool, kv_page_size=args.kv_page_size,
                chunk_deadline_s=args.chunk_deadline)
            if args.replica_endpoint:
                # adopt running children: the endpoint list IS the fleet
                members = [SocketHostedReplica(hcfg, endpoint=ep)
                           for ep in args.replica_endpoint]
            elif args.host_transport == "socket":
                members = [SocketHostedReplica(hcfg) for _ in range(n0)]
            else:
                members = [HostedReplica(hcfg) for _ in range(n0)]
            for m in members:
                m.wait_ready()
            engines = None
            # autoscale grow-by-spawn always spawns locally — even an
            # endpoint fleet grows with a local socket child, not a dial
            # to an address nobody is listening on
            if args.replica_endpoint or args.host_transport == "socket":
                engine_factory = lambda: SocketHostedReplica(hcfg)  # noqa: E731
            else:
                engine_factory = lambda: HostedReplica(hcfg)   # noqa: E731
            if args.selftest:
                # looser than the in-process selftest: heartbeats ride a
                # 50ms child stream, and a 0.15s flatline bound would
                # false-kill a briefly descheduled healthy child
                rcfg.suspect_after_s, rcfg.dead_after_s = 0.5, 1.5
                rcfg.recover_after_s, rcfg.max_attempts = 30.0, 4
        else:
            engines = _build_engines(args, n0)
            members = engines
            engine_factory = lambda: _build_engine(   # noqa: E731
                args, params=engines[0].params)
            if args.selftest:
                # tight health thresholds: the kill-and-retry round trip
                # should prove itself in ~a second, not wait out production
                # timeouts
                rcfg.suspect_after_s, rcfg.dead_after_s = 0.05, 0.15
                rcfg.recover_after_s, rcfg.max_attempts = 30.0, 4
        front = Router(members, rcfg, monitor=monitor)
        front.install_sigterm_drain()      # SIGTERM = graceful drain
        if args.host_replicas or args.replica_endpoint:
            supervisor = ReplicaSupervisor(front, SupervisorConfig(
                max_restarts=args.max_restarts,
                backoff_base_s=args.restart_backoff))
        if args.autoscale:
            autoscaler = Autoscaler(
                front, engine_factory,
                AutoscaleConfig(min_replicas=args.min_replicas,
                                max_replicas=args.max_replicas))
        if args.chaos:
            chaos = ChaosSchedule(parse_chaos(args.chaos))
        _providers["status"] = make_status_provider(
            front, autoscaler=autoscaler, recorder=recorder,
            detector=detector, supervisor=supervisor)
        _providers["health"] = make_health_provider(front)
        if args.selftest:
            ref_engines = (engines if engines is not None
                           else [members[0].engine])
            ok, snap = _selftest_router(front, ref_engines, args.requests,
                                        args.vocab_size)
            _close_hosts(front)
            print(json.dumps({"selftest_ok": ok, **snap}))
            _obs_epilogue()
            return 0 if ok else 1
    else:
        if args.chaos:
            raise SystemExit("--chaos needs --replicas >= 2")
        if args.host_replicas:
            raise SystemExit("--host-replicas serves through the router")
        engine = _build_engine(args)
        front = ContinuousBatchingScheduler(engine, serving_cfg,
                                            monitor=monitor)
        _providers["status"] = make_status_provider(front, recorder=recorder,
                                                    detector=detector)
        _providers["health"] = make_health_provider(front)
        if args.selftest:
            ok, snap = _selftest(front, args.requests, args.vocab_size)
            print(json.dumps({"selftest_ok": ok, **snap}))
            _obs_epilogue()
            return 0 if ok else 1
    snap = _serve_stdin(front, chaos=chaos, autoscaler=autoscaler,
                        supervisor=supervisor)
    _close_hosts(front)
    print(json.dumps(snap), file=sys.stderr)
    _obs_epilogue()
    return 0


if __name__ == "__main__":
    sys.exit(main())
