"""``deepspeed-serve``: the serving-subsystem entrypoint.

Two modes over the same scheduler:

- **stdin mode** (default): read one JSON request per line
  (``{"prompt": [ids...], "max_new_tokens": 16, "eos_token_id": null,
  "deadline_s": null, "seed": 0}``), stream one JSON result per completed
  request to stdout (tokens + TTFT/TPOT + finish reason), then a final summary
  line. Backpressured submissions are retried after the scheduler's hint.
- **--selftest**: synthesize a small random-weight model and a burst of random
  requests; exit 0 iff every request completes. The zero-infrastructure way to
  prove the serving ring works on this host.

Metrics go to the jsonl monitor backend when ``--jsonl-metrics DIR`` is given.
"""

import argparse
import json
import sys
import time
from typing import Optional

import numpy as np


def _build_engine(args):
    import jax.numpy as jnp

    from ...models.causal_lm import gpt2_cfg, llama_cfg
    from ..config import DeepSpeedInferenceConfig
    from ..engine import InferenceEngine
    family = {"gpt2": gpt2_cfg, "llama": llama_cfg}[args.family]
    cfg = family(vocab_size=args.vocab_size, max_seq_len=args.max_seq_len,
                 n_embd=args.n_embd, n_layer=args.n_layer, n_head=args.n_head,
                 dtype={"float32": jnp.float32, "bfloat16": jnp.bfloat16}
                 [args.dtype])
    engine = InferenceEngine(cfg, DeepSpeedInferenceConfig(
        dtype=args.dtype, max_out_tokens=args.max_seq_len,
        tensor_parallel={"tp_size": args.tp}))
    if args.checkpoint:
        engine.load_checkpoint(args.checkpoint)
    return engine


def _make_monitor(args) -> Optional[object]:
    if not args.jsonl_metrics:
        return None
    from ...config.config import MonitorConfig
    from ...monitor import MonitorMaster
    return MonitorMaster(MonitorConfig(jsonl_monitor={
        "enabled": True, "output_path": args.jsonl_metrics,
        "job_name": "deepspeed-serve"}))


def _result_line(h) -> str:
    return json.dumps({
        "id": h.id, "state": h.state.value, "finish_reason": h.finish_reason,
        "tokens": [int(t) for t in h.tokens],
        "ttft_ms": None if h.ttft is None else h.ttft * 1e3,
        "tpot_ms": None if h.tpot is None else h.tpot * 1e3,
    })


def _serve_stdin(sched, out=sys.stdout, inp=None):
    """Streaming serve loop: requests are admitted as their lines arrive (a
    reader thread feeds a queue, so a client may keep the pipe open and read
    results before sending more) and each result is emitted the moment its
    request completes. A malformed or inadmissible line fails alone — an
    ``{"error": ...}`` line is emitted and serving continues."""
    import queue as _queue
    import threading

    from .scheduler import QueueFullError
    inp = inp if inp is not None else sys.stdin
    lines: "_queue.Queue" = _queue.Queue()
    _EOF = object()

    def _reader():
        for line in inp:
            lines.put(line)
        lines.put(_EOF)

    threading.Thread(target=_reader, daemon=True).start()
    handles, pending, eof = [], [], False
    not_before = 0.0
    while not eof or pending or sched.busy:
        while True:                          # drain whatever the reader has
            try:
                line = lines.get_nowait()
            except _queue.Empty:
                break
            if line is _EOF:
                eof = True
                break
            if line.strip():
                pending.append(line.strip())
        while pending and time.monotonic() >= not_before:
            try:
                req = json.loads(pending[0])
                handles.append(sched.submit(
                    np.asarray(req["prompt"], np.int32),
                    max_new_tokens=req.get("max_new_tokens"),
                    eos_token_id=req.get("eos_token_id"),
                    deadline_s=req.get("deadline_s"),
                    seed=req.get("seed", 0)))
                pending.pop(0)
            except QueueFullError as e:      # backpressure: drain, then resubmit
                not_before = time.monotonic() + e.retry_after
                break
            except Exception as e:           # bad line: fail it, keep serving
                out.write(json.dumps({"error": f"{type(e).__name__}: {e}",
                                      "line": pending.pop(0)[:200]}) + "\n")
        if sched.busy:
            sched.step()
        elif not eof or pending:
            time.sleep(0.01)                 # idle: await input, don't spin
        for h in [h for h in handles if h.done]:
            out.write(_result_line(h) + "\n")
            handles.remove(h)
    return sched.telemetry.snapshot()


def _selftest(sched, n_requests: int, vocab: int, seed: int = 0):
    rng = np.random.default_rng(seed)
    handles = []
    from .scheduler import QueueFullError
    reqs = [(rng.integers(0, vocab, size=int(rng.integers(3, 12))).astype(np.int32),
             int(rng.integers(2, 10))) for _ in range(n_requests)]
    while reqs or sched.busy:
        while reqs:
            prompt, max_new = reqs[0]
            try:
                handles.append(sched.submit(prompt, max_new_tokens=max_new))
                reqs.pop(0)
            except QueueFullError:
                break
        sched.step()
    ok = all(h.state.value == "finished" for h in handles)
    return ok, sched.telemetry.snapshot()


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="deepspeed-serve", description=__doc__)
    ap.add_argument("--family", default="gpt2", choices=("gpt2", "llama"))
    ap.add_argument("--vocab-size", type=int, default=256)
    ap.add_argument("--max-seq-len", type=int, default=128)
    ap.add_argument("--n-embd", type=int, default=64)
    ap.add_argument("--n-layer", type=int, default=2)
    ap.add_argument("--n-head", type=int, default=4)
    ap.add_argument("--dtype", default="float32", choices=("float32", "bfloat16"))
    ap.add_argument("--tp", type=int, default=1)
    ap.add_argument("--checkpoint", default=None,
                    help="training checkpoint dir to serve")
    ap.add_argument("--slots", type=int, default=4)
    ap.add_argument("--chunk-size", type=int, default=8)
    ap.add_argument("--max-queue", type=int, default=32)
    ap.add_argument("--jsonl-metrics", default=None,
                    help="directory for the jsonl monitor backend")
    ap.add_argument("--selftest", action="store_true")
    ap.add_argument("--requests", type=int, default=8,
                    help="selftest request count")
    args = ap.parse_args(argv)

    from .scheduler import ContinuousBatchingScheduler, ServingConfig
    engine = _build_engine(args)
    sched = ContinuousBatchingScheduler(
        engine, ServingConfig(slots=args.slots, chunk_size=args.chunk_size,
                              max_queue=args.max_queue,
                              max_seq_len=args.max_seq_len),
        monitor=_make_monitor(args))
    if args.selftest:
        ok, snap = _selftest(sched, args.requests, args.vocab_size)
        print(json.dumps({"selftest_ok": ok, **snap}))
        return 0 if ok else 1
    snap = _serve_stdin(sched)
    print(json.dumps(snap), file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
