"""Serving telemetry: per-request TTFT/TPOT, queue depth, slot occupancy,
tokens/sec — recorded into the process-wide observability registry
(``observability.metrics``: bounded instruments, Prometheus exposition),
emitted as ``MonitorMaster`` events (any enabled backend: csv, tensorboard,
wandb, jsonl) and aggregated for the load-generator's BENCH JSON.

Event tags are declared once in ``observability.schema`` (step semantics in
parentheses):

- ``serving/ttft_ms``, ``serving/tpot_ms`` — per finished request (completion idx);
- ``serving/tokens_per_sec`` — per decode chunk (chunk idx);
- ``serving/queue_depth``, ``serving/slot_occupancy`` — per scheduler step (tick);
- ``serving/completed_total``, ``serving/rejected_total`` — per scheduler step;
- ``serving/prefix_hit_rate``, ``serving/prefix_cached_bytes``,
  ``serving/prefix_evicted_total`` — per scheduler step, prefix cache enabled
  only (hit/miss/inserted/evicted counters + cached-token bytes ride the
  aggregate snapshot);
- ``serving/prefix_spilled_bytes``, ``serving/prefix_spills_total``,
  ``serving/prefix_promotions_total`` — per scheduler step, tiered prefix
  cache (host-RAM rung) enabled only;
- ``serving/spec_*`` — per verify round, speculation enabled only; the
  emission site lives in ``inference.speculative.emit_spec_events`` (the
  subsystem that owns the semantics), this class only keeps the counters.

Latency distributions are **fixed-log-bucket histograms**, not lists: memory
stays O(1) over a week-long soak (the pre-PR-10 ``ttfts``/``tpots`` Python
lists grew one float per request forever) while ``snapshot()`` keeps the same
percentile keys, now bucket-derived.
"""

import time
from collections import deque
from typing import Dict, Iterable, Optional

from ...observability.metrics import Histogram, RegistryFeed
from ..speculative import SpecStats, emit_spec_events


def window_rate(times: Iterable[float], now: float,
                horizon_s: float = 10.0) -> Optional[float]:
    """Events per second over the trailing ``horizon_s`` window, or None
    without fresh evidence (fewer than two events inside the horizon — a
    stale window must never report an ancient rate). THE drain-rate helper:
    scheduler/router backpressure hints and the autoscale estimator all rate
    their completion streams through this one function."""
    recent = [t for t in times if t >= now - horizon_s]
    if len(recent) < 2 or now <= recent[0]:
        return None
    return (len(recent) - 1) / max(now - recent[0], 1e-6)


def adaptive_retry_after(floor_s: float, cap_s: float, queue_depth: int,
                         max_queue: int,
                         drain_rate: Optional[float]) -> float:
    """Load-adaptive backpressure hint: estimated seconds until one queue
    slot drains (``(depth + 1) / drain_rate``), a fill-scaled multiple of
    the floor before any drain evidence exists; bounded to
    ``[floor_s, cap_s]`` so one bad estimate cannot park every client for
    minutes. A static hint convoys rejected clients back in lockstep at
    exactly the wrong moment — this one stretches with the backlog. Shared
    by the scheduler and the router (the two QueueFullError emitters)."""
    if drain_rate is None or drain_rate <= 0:
        hint = floor_s * (1.0 + queue_depth / max(1, max_queue))
    else:
        hint = (queue_depth + 1) / drain_rate
    return float(min(max(hint, floor_s), cap_s))


class ServingTelemetry:
    """Aggregator + event emitter; ``monitor`` is an optional MonitorMaster."""

    def __init__(self, monitor=None):
        self.monitor = monitor
        self._tick = 0
        self._chunk_idx = 0
        self._finished_idx = 0
        # per-telemetry bounded histograms (ms): the snapshot's percentile
        # source. The process registry keeps its own global instruments via
        # record_events — per-replica snapshots must not blend across replicas.
        self.ttft_ms = Histogram()
        self.tpot_ms = Histogram()
        # per-emitter registry feed: this telemetry's cumulative counters
        # contribute DELTAS, so N replicas (and successive runs) sum in
        # /metrics instead of max-merging
        self._feed = RegistryFeed()
        self.tokens_total = 0
        self.completed = 0
        self.rejected = 0
        self.cancelled = 0
        self.expired = 0
        self.evicted = 0
        self.decode_seconds = 0.0
        # prefix-cache counters (only advanced when the cache is enabled)
        self.prefix_enabled = False
        self.prefix_hits = 0
        self.prefix_misses = 0
        self.prefix_hit_tokens = 0
        self._prefix_stats = None    # latest PrefixCache.stats() gauge set
        self._paged_stats = None     # latest PagedKVPool.stats() gauge set
        # speculative-decoding counters (only advanced when speculation is on);
        # the spec_* event emission itself lives in inference.speculative
        self.spec = SpecStats()
        self.spec_enabled = False
        # completion timestamps (bounded): the observed drain rate behind the
        # load-adaptive QueueFullError.retry_after hint
        self._finish_times = deque(maxlen=64)
        self._t_start = time.perf_counter()

    # ------------------------------------------------------------------- emits
    def _write(self, events):
        self._feed.record_events(events)   # process registry (/metrics)
        if self.monitor is not None and getattr(self.monitor, "enabled", False):
            self.monitor.write_events(events)

    def on_step(self, queue_depth: int, occupancy: float,
                prefix_stats=None, paged_stats=None) -> None:
        self._tick += 1
        ev = [("serving/queue_depth", float(queue_depth), self._tick),
              ("serving/slot_occupancy", float(occupancy), self._tick),
              ("serving/completed_total", float(self.completed), self._tick),
              ("serving/rejected_total", float(self.rejected), self._tick)]
        if paged_stats is not None:
            # paged-pool gauges/counters (PagedKVPool.stats()): page-granular
            # occupancy, allocation-granularity waste, zero-copy sharing
            self._paged_stats = paged_stats
            ev += [("serving/pages_in_use",
                    float(paged_stats["pages_in_use"]), self._tick),
                   ("serving/page_fragmentation",
                    float(paged_stats["page_fragmentation"]), self._tick),
                   ("serving/prefix_shared_pages",
                    float(paged_stats["prefix_shared_pages"]), self._tick),
                   ("serving/cow_copies_total",
                    float(paged_stats["cow_copies_total"]), self._tick)]
        if prefix_stats is not None:
            self._prefix_stats = prefix_stats
            # hit_rate here is ADMISSION-level (successful prefills), the same
            # quantity the snapshot publishes under the same name — the trie's
            # own lookup-level counters (which also tick on failed/retried
            # admissions) live in prefix_cache_report() only
            n = self.prefix_hits + self.prefix_misses
            ev += [("serving/prefix_hit_rate",
                    self.prefix_hits / n if n else 0.0, self._tick),
                   ("serving/prefix_cached_bytes",
                    float(prefix_stats["cached_bytes"]), self._tick),
                   ("serving/prefix_evicted_total",
                    float(prefix_stats["evicted"]), self._tick)]
            if "spilled_bytes" in prefix_stats:
                # tiered-cache rung (PR 19): host-RAM residency + the two
                # movement counters (device→host spill, host→device promote)
                ev += [("serving/prefix_spilled_bytes",
                        float(prefix_stats["spilled_bytes"]), self._tick),
                       ("serving/prefix_spills_total",
                        float(prefix_stats["spills"]), self._tick),
                       ("serving/prefix_promotions_total",
                        float(prefix_stats["promotions"]), self._tick)]
        self._write(ev)

    def on_prefix(self, hit: bool, tokens: int, enabled: bool = True) -> None:
        """Per-admission hit/miss accounting (``tokens`` = prefill tokens
        skipped via the restored prefix; 0 on a miss)."""
        if not enabled:
            return
        self.prefix_enabled = True
        if hit:
            self.prefix_hits += 1
            self.prefix_hit_tokens += int(tokens)
        else:
            self.prefix_misses += 1

    def on_chunk(self, tokens: int, elapsed: float) -> None:
        self._chunk_idx += 1
        self.tokens_total += int(tokens)
        self.decode_seconds += float(elapsed)
        if elapsed > 0:
            self._write([("serving/tokens_per_sec", tokens / elapsed,
                          self._chunk_idx)])

    def on_spec(self, proposed: int, accepted: int, tokens: int,
                draft_s: float, verify_s: float) -> None:
        """Per-verify-round speculative accounting (one round == one target
        forward pass over the whole slot-batch)."""
        self.spec_enabled = True
        s = self.spec
        s.rounds += 1
        s.proposed += int(proposed)
        s.accepted += int(accepted)
        s.tokens += int(tokens)
        s.draft_s += float(draft_s)
        s.verify_s += float(verify_s)
        emit_spec_events(self, s, draft_s, s.rounds)

    def on_rejected(self) -> None:
        self.rejected += 1

    def on_finished(self, handle) -> None:
        from .scheduler import RequestState
        if handle.state == RequestState.CANCELLED:
            self.cancelled += 1
            return
        if handle.state == RequestState.EXPIRED:
            self.expired += 1
            return
        if handle.state == RequestState.EVICTED:
            self.evicted += 1
            return
        self.completed += 1
        self._finished_idx += 1
        self._finish_times.append(time.monotonic())
        events = []
        if handle.ttft is not None:
            self.ttft_ms.observe(handle.ttft * 1e3)
            events.append(("serving/ttft_ms", handle.ttft * 1e3,
                           self._finished_idx))
        if handle.tpot is not None:
            self.tpot_ms.observe(handle.tpot * 1e3)
            events.append(("serving/tpot_ms", handle.tpot * 1e3,
                           self._finished_idx))
        self._write(events)

    def drain_rate(self, now: Optional[float] = None,
                   horizon_s: float = 10.0) -> Optional[float]:
        """Recent completions per second, or None without fresh evidence."""
        now = time.monotonic() if now is None else now
        return window_rate(self._finish_times, now, horizon_s)

    # --------------------------------------------------------------- aggregate
    def snapshot(self) -> Dict:
        elapsed = time.perf_counter() - self._t_start
        prefix = {}
        if self.prefix_enabled or self._prefix_stats is not None:
            n = self.prefix_hits + self.prefix_misses
            prefix = {
                "prefix_hits": self.prefix_hits,
                "prefix_misses": self.prefix_misses,
                "prefix_hit_rate": self.prefix_hits / n if n else 0.0,
                "prefix_hit_tokens": self.prefix_hit_tokens,
            }
            if self._prefix_stats is not None:
                prefix["prefix_inserted"] = self._prefix_stats["inserted"]
                prefix["prefix_evicted"] = self._prefix_stats["evicted"]
                prefix["prefix_cached_bytes"] = \
                    self._prefix_stats["cached_bytes"]
                if "spilled_bytes" in self._prefix_stats:
                    prefix["prefix_spilled_bytes"] = \
                        self._prefix_stats["spilled_bytes"]
                    prefix["prefix_spills"] = self._prefix_stats["spills"]
                    prefix["prefix_promotions"] = \
                        self._prefix_stats["promotions"]
        paged = ({f"paged_{k}": v for k, v in self._paged_stats.items()}
                 if self._paged_stats is not None else {})
        spec = self.spec.snapshot() if self.spec_enabled else {}
        return {
            **prefix,
            **paged,
            **spec,
            "elapsed_s": elapsed,
            "completed": self.completed,
            "rejected": self.rejected,
            "cancelled": self.cancelled,
            "expired": self.expired,
            "evicted": self.evicted,
            "tokens_total": self.tokens_total,
            "tokens_per_sec": (self.tokens_total / self.decode_seconds
                               if self.decode_seconds > 0 else 0.0),
            "ttft_ms_p50": self.ttft_ms.percentile(50),
            "ttft_ms_p95": self.ttft_ms.percentile(95),
            "tpot_ms_p50": self.tpot_ms.percentile(50),
            "tpot_ms_p95": self.tpot_ms.percentile(95),
        }
