"""Chunked decode executor: compiled fixed-shape chunks over a slot-batch.

The refactored form of ``InferenceEngine._loop_fns``: instead of one
run-to-completion ``lax.while_loop`` per user call, decode runs in chunks of K
steps over a fixed slot-batch and returns to the host between chunks — the host
window in which the continuous-batching scheduler retires finished requests,
recycles their KV slots and prefills pending prompts, while the other slots keep
decoding. Compile-key discipline:

- ONE decode-chunk compile per (slots, cap, chunk, sampling) key, cached on the
  owning engine's ``_fns`` so coexisting executors share it;
- ONE prefill compile per (prompt-bucket, cap, sampling) key — prompts are
  right-padded to power-of-two buckets so arbitrary lengths hit a handful of
  compiles.

KV buffers are donated unconditionally (chunk in-place-updates the pool rows;
jax 0.4.37 honours ``donate_argnums`` on CPU too — no backend guards).

Watchdog: with ``chunk_deadline_s`` set, each chunk (dispatch + host fetch — the
two places a hung compile or collective wedges) runs on a watchdog thread and a
deadline overrun raises :class:`ChunkTimeoutError` instead of blocking the
scheduler loop forever. The timed region declares the ``serving.chunk_compute``
fault point, so a ``delay`` fault (or the :meth:`stall_next` chaos hook) models
the hang deterministically. A timed-out chunk's pool buffers are unrecoverable —
they were donated into the wedged dispatch — so the caller must ``reset_pool``
(the scheduler's decode-failure path already does).
"""

import threading
import time
from dataclasses import dataclass
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ...models.causal_lm import init_cache
from ...observability import profiler as obs_profiler
from ...observability.trace import get_tracer
from ...utils.fault_injection import fault_point
from ...utils.nvtx import annotate
from ..decode_fns import (build_decode_chunk, build_paged_decode_chunk,
                          build_paged_spec_verify, build_prefill,
                          build_prefix_prefill, build_spec_verify,
                          make_slot_select_fn)
from ..speculative import accept_tokens
from .kv_pool import PagedKVPool, SlotKVPool


class ChunkTimeoutError(RuntimeError):
    """A decode chunk exceeded its wall-clock deadline (hung compile/collective).

    Deliberately NOT a retryable transient: the chunk's donated KV buffers are
    lost inside the wedged dispatch, so the only safe recovery is evict + pool
    rebuild (+ requeue on another replica, when a router is above)."""

    def __init__(self, deadline_s: float):
        super().__init__(f"decode chunk exceeded its {deadline_s:.3f}s deadline")
        self.deadline_s = float(deadline_s)


def prompt_buckets(max_prompt_len: int, smallest: int = 8) -> Tuple[int, ...]:
    """Power-of-two right-pad buckets covering ``[1, max_prompt_len]``."""
    buckets = []
    b = smallest
    while b < max_prompt_len:
        buckets.append(b)
        b *= 2
    buckets.append(max_prompt_len)
    return tuple(buckets)


@dataclass
class ChunkResult:
    """Host view of one decode chunk (everything already fetched)."""
    buf: np.ndarray          # (S, K) emitted tokens; per-slot real prefix only
    toks: np.ndarray         # (S, 1) each slot's last token
    lens: np.ndarray         # (S,) KV append positions
    active: np.ndarray       # (S,) bool
    remaining: np.ndarray    # (S,) decode budget left
    steps: np.ndarray        # (S,) per-request tokens emitted so far
    elapsed: float           # wall seconds for dispatch + fetch


@dataclass
class SpecResult(ChunkResult):
    """One speculative verify round, harvest-compatible with a chunk: ``buf``
    is (S, k+1) wide and a slot's real tokens are still the prefix of length
    ``steps_out - steps_in``, so the scheduler's chunk harvest works
    unchanged. ``proposed``/``accepted`` feed the ``serving/spec_*``
    telemetry; ``draft_s`` is filled by the scheduler (the proposer runs on
    the host before the dispatch)."""
    proposed: int = 0        # real draft tokens offered this round
    accepted: int = 0        # draft tokens that survived accept/reject
    draft_s: float = 0.0     # host proposer wall seconds (set by caller)


class ChunkedDecodeExecutor:
    """Drives prefill-into-slot + K-step decode chunks for a scheduler."""

    def __init__(self, engine, slots: int, cap: int, chunk_size: int,
                 do_sample: bool = False, temperature: float = 1.0,
                 top_k: int = 0, top_p: float = 1.0, max_prompt_len: Optional[int]
                 = None, base_seed: int = 0,
                 chunk_deadline_s: Optional[float] = None,
                 cold_chunk_grace_s: float = 120.0,
                 kv_pool: str = "paged", kv_page_size: int = 16,
                 kv_total_pages: Optional[int] = None):
        if chunk_size < 1:
            raise ValueError(f"chunk_size must be >= 1, got {chunk_size}")
        if chunk_deadline_s is not None and chunk_deadline_s <= 0:
            raise ValueError("chunk_deadline_s must be positive when set, got "
                             f"{chunk_deadline_s}")
        if kv_pool not in ("paged", "slots"):
            raise ValueError(f"kv_pool must be 'paged' or 'slots', "
                             f"got {kv_pool!r}")
        self.engine = engine
        self.slots = int(slots)
        self.cap = int(cap)
        self.chunk_size = int(chunk_size)
        self.max_prompt_len = int(max_prompt_len or cap - 1)
        if self.max_prompt_len >= self.cap:
            raise ValueError("max_prompt_len must leave room for at least one "
                             f"generated token (cap={self.cap})")
        self.sampling = (bool(do_sample), float(temperature), int(top_k),
                         float(top_p))
        self.buckets = prompt_buckets(self.max_prompt_len)
        self.kv_pool_kind = kv_pool
        self.kv_page_size = int(kv_page_size)
        self.kv_total_pages = kv_total_pages
        self.pool = self._build_pool()
        self._slot_select = make_slot_select_fn(*self.sampling)
        self._base_key = jax.random.PRNGKey(base_seed)
        self.chunk_deadline_s = chunk_deadline_s
        self.cold_chunk_grace_s = float(cold_chunk_grace_s)
        self._warm_chunk = False        # first successful chunk marks warm
        self._stall_next = 0.0
        self._restore_kill = None       # chaos hook: fires between prefix
        #   restore and suffix prefill (see arm_restore_kill)

    @property
    def chunk_warm(self) -> bool:
        """True once the chunk fn has completed at least once — the point from
        which ``chunk_deadline_s`` is enforced at face value (the first chunk is
        granted ``cold_chunk_grace_s`` to cover its XLA compile)."""
        return self._warm_chunk

    def arm_restore_kill(self, callback) -> None:
        """Chaos hook: invoke ``callback`` exactly once, in the window between
        the prefix-slab restore and the suffix prefill of the next cache-hit
        admission, then abort that admission attempt — the deterministic
        stand-in for a replica dying with a restored-but-unprefilled slot. The
        scheduler's prefill retry re-runs the whole restore (donation-safe:
        ``restore_prefix`` rebinds the pool before this hook can fire)."""
        self._restore_kill = callback

    @property
    def restore_kill_pending(self) -> bool:
        return self._restore_kill is not None

    def stall_next(self, seconds: float) -> None:
        """Chaos hook: make the next chunk stall ``seconds`` inside the timed
        region — a deterministic stand-in for a hung compile/collective. With a
        ``chunk_deadline_s`` armed the watchdog converts it into a
        :class:`ChunkTimeoutError`; without one it wedges, which is the failure
        mode the watchdog exists to remove."""
        self._stall_next = float(seconds)

    def _build_pool(self):
        if self.kv_pool_kind == "paged":
            return PagedKVPool(self.engine.model_config, self.slots, self.cap,
                               page_size=self.kv_page_size,
                               dtype=self.engine.dtype,
                               total_pages=self.kv_total_pages)
        return SlotKVPool(self.engine.model_config, self.slots, self.cap,
                          dtype=self.engine.dtype)

    @property
    def paged(self) -> bool:
        return self.kv_pool_kind == "paged"

    def reset_pool(self) -> None:
        """Discard the pool (e.g. after a failed dispatch that may have consumed
        donated buffers) and rebuild it fresh, every slot free. On the paged
        pool this also voids every page the prefix cache holds references to —
        the scheduler clears its cache alongside (``_rebuild_pool``)."""
        self.pool = self._build_pool()

    # ------------------------------------------------------------- compiled fns
    def _chunk_fn(self):
        if self.paged:
            from ...ops.paged_attention import fused_paged_for
            from ...parallel.mesh import AXIS_TENSOR, get_global_mesh
            mesh = get_global_mesh()
            cfg = self.engine.model_config
            # the fused kernel has no alibi bias (the layer would re-gather
            # the dense view EVERY step inside the loop — the fallback hoists
            # it once per chunk), no shard_map TP path (the fallback's dense
            # steps route through _sharded_decode), and its dispatcher needs
            # a lane-aligned head dim on-chip (fused_paged_for mirrors it);
            # every excluded regime decodes strictly faster on the fallback
            fused = fused_paged_for(cfg.head_dim) \
                and getattr(cfg, "pos_emb", None) != "alibi" \
                and (mesh is None or mesh.size(AXIS_TENSOR) <= 1)
            # one compile per (slots, pages, page, cap, chunk, sampling) key:
            # per-request page COUNTS are runtime table data, so mixed-length
            # traffic and page growth never mint a new key (sweep-pinned).
            # The fused flag is part of the key — tests toggle the env var.
            key = ("serve_chunk_paged", self.slots, self.pool.total_pages,
                   self.pool.page_size, self.cap, self.chunk_size,
                   self.sampling, fused)
        else:
            key = ("serve_chunk", self.slots, self.cap, self.chunk_size,
                   self.sampling)
        fns = self.engine._fns
        if key not in fns:
            overlap = getattr(self.engine, "comm_overlap", None)
            if self.paged:
                chunk = build_paged_decode_chunk(
                    self.engine.module, self.engine._dequant,
                    self._slot_select, self.chunk_size, kv_cap=self.cap,
                    overlap=overlap, fused=fused)
            else:
                chunk = build_decode_chunk(self.engine.module,
                                           self.engine._dequant,
                                           self._slot_select, self.chunk_size,
                                           overlap=overlap)
            fns[key] = jax.jit(chunk, donate_argnums=(2,))   # caches/pages
        return fns[key]

    def _prefill_fn(self, bucket: int):
        key = ("serve_prefill", bucket, self.cap, self.sampling)
        fns = self.engine._fns
        if key not in fns:
            engine = self.engine
            prefill_logits = build_prefill(engine.module, engine._dequant,
                                           overlap=getattr(engine,
                                                           "comm_overlap", None))
            select = self._slot_select
            cfg = engine.model_config
            cap, dtype = self.cap, engine.dtype

            def prefill(params, ids, len0, seed, base_key):
                caches = init_cache(cfg, 1, cap, dtype=dtype)
                logits, new_caches = prefill_logits(params, ids, caches, len0)
                tok0 = select(logits, base_key, seed, jnp.zeros_like(seed))
                return tok0, new_caches

            fns[key] = jax.jit(prefill)
        return fns[key]

    def _suffix_prefill_fn(self, bucket: int):
        """Cache-hit prefill: gather the slot's batch-1 cache view (holding the
        restored prefix slab), run the suffix forward at the prefix offset,
        scatter the row back. The POOL caches flow through and are donated —
        same compile-key discipline as the chunk fn, one compile per
        (slots, cap, suffix-bucket, sampling) key."""
        key = ("serve_suffix_prefill", self.slots, self.cap, bucket,
               self.sampling)
        fns = self.engine._fns
        if key not in fns:
            engine = self.engine
            prefix_prefill = build_prefix_prefill(
                engine.module, engine._dequant,
                overlap=getattr(engine, "comm_overlap", None))
            select = self._slot_select

            def prefill(params, caches, slot, ids, prefix_len, suffix_len,
                        seed, base_key):
                one = [{"k": jax.lax.dynamic_slice_in_dim(c["k"], slot, 1, 0),
                        "v": jax.lax.dynamic_slice_in_dim(c["v"], slot, 1, 0)}
                       for c in caches]
                logits, new_one = prefix_prefill(params, ids, one, prefix_len,
                                                 suffix_len)
                tok0 = select(logits, base_key, seed, jnp.zeros_like(seed))
                caches = [
                    {"k": jax.lax.dynamic_update_slice_in_dim(
                        c["k"], n["k"].astype(c["k"].dtype), slot, 0),
                     "v": jax.lax.dynamic_update_slice_in_dim(
                        c["v"], n["v"].astype(c["v"].dtype), slot, 0)}
                    for c, n in zip(caches, new_one)]
                return tok0, caches

            fns[key] = jax.jit(prefill, donate_argnums=(1,))
        return fns[key]

    def _suffix_prefill_fn_paged(self, bucket: int):
        """Paged cache-hit prefill: the slot's pages (shared prefix pages
        bound zero-copy at admission + its COW/fresh pages) are gathered into
        the dense batch-1 view INSIDE the dispatch, the suffix forward runs at
        the prefix offset, and ONLY the suffix rows scatter back to their
        page-mapped positions — shared pages are read, never written. The
        POOL pages flow through and are donated; one compile per
        (pages, page, cap, suffix-bucket, sampling) key."""
        key = ("serve_suffix_prefill_paged", self.pool.total_pages,
               self.pool.page_size, self.cap, bucket, self.sampling)
        fns = self.engine._fns
        if key not in fns:
            engine = self.engine
            prefix_prefill = build_prefix_prefill(
                engine.module, engine._dequant,
                overlap=getattr(engine, "comm_overlap", None))
            select = self._slot_select
            cap = self.cap
            ps, mp = self.pool.page_size, self.pool.max_pages
            P_total = self.pool.total_pages

            def prefill(params, caches, tbl, ids, prefix_len, suffix_len,
                        seed, base_key):
                one = []
                for c in caches:
                    _, hk, _, d = c["k"].shape
                    k = c["k"][tbl].transpose(1, 0, 2, 3).reshape(hk, -1, d)
                    v = c["v"][tbl].transpose(1, 0, 2, 3).reshape(hk, -1, d)
                    one.append({"k": k[None, :, :cap, :],
                                "v": v[None, :, :cap, :]})
                logits, new_one = prefix_prefill(params, ids, one, prefix_len,
                                                 suffix_len)
                tok0 = select(logits, base_key, seed, jnp.zeros_like(seed))
                # scatter ONLY the suffix rows [prefix, prefix + bucket) back;
                # rows beyond cap route to an out-of-range page index and the
                # scatter drops them (the dense path's OOB-pad-drop contract)
                t = ids.shape[1]
                rows = prefix_len[0] + jnp.arange(t)
                page_pos = jnp.clip(rows // ps, 0, mp - 1)
                pidx = jnp.where(rows < cap, tbl[page_pos], P_total)
                off = rows % ps
                out = []
                for c, n in zip(caches, new_one):
                    kv = {}
                    for key_ in ("k", "v"):
                        vals = jnp.take(n[key_][0], rows, axis=1,
                                        mode="clip").transpose(1, 0, 2)
                        kv[key_] = c[key_].at[pidx, :, off, :].set(
                            vals.astype(c[key_].dtype))
                    out.append(kv)
                return tok0, out

            fns[key] = jax.jit(prefill, donate_argnums=(1,))
        return fns[key]

    def _spec_verify_fn(self, k: int):
        """Speculative one-pass verify: ONE compile per (slots, cap, k,
        sampling) key (paged adds the pool geometry, mirroring the chunk
        key). ``k`` is the static window width minus the cur-token row —
        per-slot draft LENGTHS are runtime data (``valid``), so shrunken
        proposals at the cap edge or a dry proposer never mint a new key.
        The pool caches/pages are donated like every other decode dispatch."""
        if self.paged:
            key = ("serve_spec_verify_paged", self.slots,
                   self.pool.total_pages, self.pool.page_size, self.cap, k,
                   self.sampling)
        else:
            key = ("serve_spec_verify", self.slots, self.cap, k,
                   self.sampling)
        fns = self.engine._fns
        if key not in fns:
            overlap = getattr(self.engine, "comm_overlap", None)
            if self.paged:
                fn = build_paged_spec_verify(self.engine.module,
                                             self.engine._dequant,
                                             kv_cap=self.cap, overlap=overlap)
            else:
                fn = build_spec_verify(self.engine.module,
                                       self.engine._dequant, overlap=overlap)
            fns[key] = jax.jit(fn, donate_argnums=(2,))   # caches/pages
        return fns[key]

    def bucket_for(self, prompt_len: int) -> int:
        for b in self.buckets:
            if prompt_len <= b:
                return b
        raise ValueError(f"prompt length {prompt_len} exceeds max_prompt_len="
                         f"{self.max_prompt_len}")

    def _dispatch_watched(self, timed):
        """Run ``timed`` under the chunk watchdog (when armed): dispatch +
        host fetch on a worker thread, :class:`ChunkTimeoutError` on overrun.
        The first dispatch per executor pays its XLA compile inside the timed
        region — it is granted ``cold_chunk_grace_s`` so a routine compile
        doesn't read as a wedged replica (a genuinely hung compile still
        trips)."""
        if self.chunk_deadline_s is None:
            return timed()
        deadline = (self.chunk_deadline_s if self._warm_chunk
                    else max(self.chunk_deadline_s, self.cold_chunk_grace_s))
        box = {}

        def runner():
            try:
                box["out"] = timed()
            except BaseException as e:          # surfaced on the caller thread
                box["exc"] = e

        th = threading.Thread(target=runner, daemon=True,
                              name="ds-serve-chunk-watchdog")
        th.start()
        th.join(deadline)
        if th.is_alive():
            raise ChunkTimeoutError(deadline)
        if "exc" in box:
            raise box["exc"]
        return box["out"]

    # -------------------------------------------------------------------- steps
    def prefill_into_slot(self, slot: int, prompt: np.ndarray, seed: int = 0,
                          prefix_len: int = 0, prefix_slab=None,
                          trace_ctx=None) -> Tuple[int, float]:
        """Prefill ``prompt`` (1-D int tokens) and scatter its KV into ``slot``.

        With ``prefix_len > 0`` (prefix-cache hit): restore ``prefix_slab``
        into the slot via the pool's donated scatter, then prefill ONLY the
        suffix ``prompt[prefix_len:]`` at cache offset ``prefix_len`` — the
        prompt bucket is chosen by **suffix** length, so a 128-token cached
        system prompt with an 8-token user turn pays an 8-bucket forward, not a
        256-bucket one. The ``serving.prefix_restore`` fault point (and the
        chaos ``when=restore`` hook) sits exactly between restore and suffix
        prefill — the boundary whose donation discipline the soak guards.

        Returns ``(first_token, prefill_seconds)`` — the first token is
        host-synced before the clock stops, so the scheduler's TTFT is honest.
        """
        # lint: host-sync-ok (host prompt tokens, never a device value)
        prompt = np.asarray(prompt, dtype=np.int32).reshape(-1)
        t = prompt.shape[0]
        tracer = get_tracer()
        self.engine._activate()
        if prefix_len:
            if not 0 < prefix_len < t:
                raise ValueError(f"prefix_len must be in (0, prompt_len={t}), "
                                 f"got {prefix_len}")
            suffix = prompt[prefix_len:]
            bucket = self.bucket_for(suffix.size)
            ids = np.zeros((1, bucket), np.int32)
            ids[0, :suffix.size] = suffix
            t0 = time.perf_counter()
            tr0 = time.monotonic()
            if self.paged:
                fn = self._suffix_prefill_fn_paged(bucket)
                if prefix_slab is not None:
                    # host-tier PROMOTE hit: the match lives as a spilled
                    # dense slab, not as live pages — restore it into the
                    # slot's (all-fresh, unshared) pages, paying one
                    # host→device copy instead of a re-prefill
                    with annotate("serving.restore_prefix"):
                        self.pool.promote_prefix(slot, prefix_slab, prefix_len)
                    tracer.record_span("restore_prefix", trace_ctx, tr0,
                                       time.monotonic(),
                                       attrs={"slot": slot,
                                              "prefix_len": int(prefix_len),
                                              "promoted": True})
                else:
                    # zero-copy hit: the prefix pages were BOUND into the
                    # slot's table at admission (refcount bump + one COW
                    # page) — there is no slab restore to pay; the span
                    # records the bind seam
                    tracer.record_span("bind_prefix", trace_ctx, tr0,
                                       time.monotonic(),
                                       attrs={"slot": slot,
                                              "prefix_len": int(prefix_len)})
            else:
                fn = self._suffix_prefill_fn(bucket)
                with annotate("serving.restore_prefix"):
                    self.pool.restore_prefix(slot, prefix_slab)
                tracer.record_span("restore_prefix", trace_ctx, tr0,
                                   time.monotonic(),
                                   attrs={"slot": slot,
                                          "prefix_len": int(prefix_len)})
            # the restore->prefill (paged: bind->prefill) seam: the chaos
            # when=restore hook and fault point fire exactly here, after the
            # pool/table was touched and before the suffix forward
            fault_point("serving.prefix_restore")
            if self._restore_kill is not None:
                cb, self._restore_kill = self._restore_kill, None
                cb()
                raise RuntimeError("chaos: replica killed between prefix "
                                   "restore/bind and suffix prefill")
            ts0 = time.monotonic()
            with annotate("serving.suffix_prefill"):
                if self.paged:
                    tok0, caches = fn(self.engine.params, self.pool.caches,
                                      jnp.asarray(self.pool.page_table[slot]),
                                      jnp.asarray(ids),
                                      jnp.asarray([prefix_len], jnp.int32),
                                      jnp.asarray([suffix.size], jnp.int32),
                                      jnp.asarray([seed], jnp.int32),
                                      self._base_key)
                else:
                    tok0, caches = fn(self.engine.params, self.pool.caches,
                                      np.int32(slot), jnp.asarray(ids),
                                      jnp.asarray([prefix_len], jnp.int32),
                                      jnp.asarray([suffix.size], jnp.int32),
                                      jnp.asarray([seed], jnp.int32),
                                      self._base_key)
                self.pool.caches = caches
                # lint: host-sync-ok (honest TTFT: first token synced on purpose)
                tok0 = int(np.asarray(tok0)[0, 0])
            tracer.record_span("suffix_prefill", trace_ctx, ts0,
                               time.monotonic(),
                               attrs={"bucket": bucket,
                                      "suffix_tokens": int(suffix.size)})
            obs_profiler.tick("prefill")
            return tok0, time.perf_counter() - t0
        bucket = self.bucket_for(t)
        ids = np.zeros((1, bucket), np.int32)
        ids[0, :t] = prompt
        fn = self._prefill_fn(bucket)
        t0 = time.perf_counter()
        tb0 = time.monotonic()
        with annotate("serving.prefill"):
            tok0, one_caches = fn(self.engine.params, jnp.asarray(ids),
                                  jnp.asarray([t], jnp.int32),
                                  jnp.asarray([seed], jnp.int32),
                                  self._base_key)
            # lint: host-sync-ok (honest TTFT: first token synced on purpose)
            tok0 = int(np.asarray(tok0)[0, 0])
        tracer.record_span("bucket_prefill", trace_ctx, tb0, time.monotonic(),
                           attrs={"bucket": bucket, "prompt_tokens": int(t)})
        dt = time.perf_counter() - t0
        self.pool.scatter_prefill(slot, one_caches)
        obs_profiler.tick("prefill")
        return tok0, dt

    def run_chunk(self, toks: np.ndarray, lens: np.ndarray, active: np.ndarray,
                  remaining: np.ndarray, eos_ids: np.ndarray, seeds: np.ndarray,
                  steps: np.ndarray) -> ChunkResult:
        """One K-step compiled chunk over the slot-batch; pool caches are donated
        in and rebound from the output. All other state is host numpy.

        With ``chunk_deadline_s`` set, dispatch + host fetch run on a watchdog
        thread; an overrun raises :class:`ChunkTimeoutError` and the pool is left
        unusable (its buffers are inside the wedged dispatch) — callers recover
        via ``reset_pool``.
        """
        self.engine._activate()
        fn = self._chunk_fn()
        # snapshot the cache binding on THIS thread: if the watchdog abandons a
        # wedged chunk and the caller rebuilds the pool, the late-finishing
        # thread must keep donating the OLD buffers, never the fresh pool's
        caches_in = self.pool.caches
        state = (jnp.asarray(lens, jnp.int32), jnp.asarray(active, bool),
                 jnp.asarray(remaining, jnp.int32),
                 jnp.asarray(eos_ids, jnp.int32),
                 jnp.asarray(seeds, jnp.int32), jnp.asarray(steps, jnp.int32),
                 self._base_key)
        if self.paged:
            # the table is host state bound at admission; it never changes
            # inside a chunk, so it rides as a (tiny) per-dispatch operand
            args = (self.engine.params,
                    jnp.asarray(toks, jnp.int32).reshape(-1, 1), caches_in,
                    jnp.asarray(self.pool.page_table)) + state
        else:
            args = (self.engine.params,
                    jnp.asarray(toks, jnp.int32).reshape(-1, 1),
                    caches_in) + state
        t0 = time.perf_counter()

        def timed():
            # the region a deadline must cover: injected stalls, compile +
            # dispatch (hung compile), and host fetch (hung collective)
            fault_point("serving.chunk_compute")
            if self._stall_next > 0:
                stall, self._stall_next = self._stall_next, 0.0
                time.sleep(stall)
            with annotate("serving.decode_chunk"):
                buf, toks_d, caches, lens_d, active_d, remaining_d, steps_d = \
                    fn(*args)
                # lint: host-sync-ok (chunk-boundary harvest: the scheduler
                # retires/admits between chunks; this fetch IS the boundary)
                host = (np.asarray(buf), np.asarray(toks_d),
                        np.asarray(lens_d), np.asarray(active_d),
                        np.asarray(remaining_d), np.asarray(steps_d))
            return host, caches

        host, caches = self._dispatch_watched(timed)
        self._warm_chunk = True
        obs_profiler.tick("decode_chunk")
        self.pool.caches = caches
        buf, toks_d, lens_d, active_d, remaining_d, steps_d = host
        return ChunkResult(buf=buf, toks=toks_d, lens=lens_d, active=active_d,
                           remaining=remaining_d, steps=steps_d,
                           elapsed=time.perf_counter() - t0)

    def run_spec_round(self, toks: np.ndarray, lens: np.ndarray,
                       active: np.ndarray, remaining: np.ndarray,
                       eos_ids: np.ndarray, seeds: np.ndarray,
                       steps: np.ndarray, proposals: np.ndarray,
                       spec_lens: np.ndarray) -> SpecResult:
        """One draft-verify round over the slot-batch: a single target forward
        scores every slot's ``[cur_tok, draft...]`` window, the accept rule
        runs on the host, and commitment is a per-slot ``lens`` advance.

        ``proposals (S, k)`` holds each slot's draft tokens (pad beyond
        ``spec_lens[s]`` is arbitrary — pad rows are neither attended as
        committed state nor mirrored to pages, and their logits are never
        read). A slot with ``spec_lens == 0`` degenerates to a plain
        single-token decode step through the same compiled shape, which is
        how the cap-edge truncation and a dry proposer are handled — no
        separate fallback path exists to drift from.

        Same donation/watchdog/fault-surface as :meth:`run_chunk` (the
        ``serving.spec_verify`` fault point sits where ``chunk_compute``
        does); a failed dispatch leaves the pool unrecoverable and callers
        recover via ``reset_pool``."""
        self.engine._activate()
        S = int(toks.shape[0])
        proposals = np.asarray(proposals, np.int32).reshape(S, -1)
        k = int(proposals.shape[1])
        fn = self._spec_verify_fn(k)
        caches_in = self.pool.caches
        ids = np.concatenate(
            [np.asarray(toks, np.int32).reshape(-1, 1), proposals], axis=1)
        spec_lens = np.asarray(spec_lens, np.int32)
        valid = spec_lens + 1
        if self.paged:
            args = (self.engine.params, jnp.asarray(ids), caches_in,
                    jnp.asarray(self.pool.page_table),
                    jnp.asarray(lens, jnp.int32), jnp.asarray(valid, jnp.int32),
                    jnp.asarray(active, bool))
        else:
            args = (self.engine.params, jnp.asarray(ids), caches_in,
                    jnp.asarray(lens, jnp.int32))
        t0 = time.perf_counter()

        def timed():
            # the mid-verify chaos/injection seam: after the proposer built
            # the window, before/through the verify dispatch + logits fetch
            fault_point("serving.spec_verify")
            if self._stall_next > 0:
                stall, self._stall_next = self._stall_next, 0.0
                time.sleep(stall)
            with annotate("serving.spec_verify"):
                logits, caches = fn(*args)
                # lint: host-sync-ok (round-boundary harvest: accept/reject
                # needs the window logits on the host; this fetch IS the
                # boundary, the spec analogue of the chunk harvest)
                return np.asarray(logits), caches

        logits, caches = self._dispatch_watched(timed)
        self._warm_chunk = True
        obs_profiler.tick("spec_verify")
        self.pool.caches = caches

        buf = np.zeros((S, k + 1), np.int32)
        toks_out = np.asarray(toks, np.int32).copy()
        lens_out = np.asarray(lens, np.int32).copy()
        active_out = np.asarray(active, bool).copy()
        remaining_out = np.asarray(remaining, np.int32).copy()
        steps_out = np.asarray(steps, np.int32).copy()
        proposed = accepted = 0
        for s in range(S):
            if not active_out[s]:
                continue
            L = int(spec_lens[s])
            proposed += L
            emitted, acc = accept_tokens(
                proposals[s, :L], logits[s, :L + 1], sampling=self.sampling,
                base_key=self._base_key, seed=int(seeds[s]),
                step0=int(steps[s]))
            accepted += acc
            # chunk semantics on the emitted stream: clamp to the decode
            # budget, truncate at the first EOS (inclusive), then commit
            r = int(remaining_out[s])
            if len(emitted) > r:
                emitted = emitted[:r]
            eos = int(eos_ids[s])
            if eos >= 0 and eos in emitted:
                emitted = emitted[:emitted.index(eos) + 1]
            e = len(emitted)
            buf[s, :e] = emitted
            toks_out[s] = emitted[-1]
            lens_out[s] += e
            steps_out[s] += e
            remaining_out[s] = r - e
            if remaining_out[s] <= 0 or (eos >= 0 and emitted[-1] == eos):
                active_out[s] = False
        return SpecResult(buf=buf, toks=toks_out.reshape(-1, 1),
                          lens=lens_out, active=active_out,
                          remaining=remaining_out, steps=steps_out,
                          elapsed=time.perf_counter() - t0,
                          proposed=proposed, accepted=accepted)
