"""Radix prompt-prefix KV cache: shared system prompts skip prefill.

A path-compressed trie over token-ID prefixes whose entries hold **KV slabs** —
per-layer ``{"k": (hk, R, d), "v": (hk, R, d)}`` device arrays gathered from a
:class:`~.kv_pool.SlotKVPool` slot after that prompt's prefill (rows padded to
the prompt's power-of-two bucket ``R``; the real covered length is the entry's
trie depth). On admission the scheduler walks the trie, splits the prompt into
``cached_prefix + suffix``, restores the slab into the slot and prefills only
the suffix — a hit costs one suffix-bucket forward instead of a full-prompt
prefill (the serving-side analogue of SGLang's RadixAttention, specialized to
this codebase's fixed-shape compiled-chunk world).

Two residency rungs share the one trie. The **device rung** (``_lru``) holds
hot entries under ``max_bytes`` of HBM — gathered slabs on the slot pool,
refcounted page indices on the paged pool. When ``host_tier_bytes > 0``, an
LRU eviction from the device rung **spills**: the entry's KV is gathered into
a dense host-numpy slab (the ``gather_prefix`` wire format) and the entry
moves to the **host rung** (``_host``) under its own byte budget. A lookup
that misses HBM but lands on a host entry is a **promote** hit: the caller
restores the slab into the new slot (one host→device copy) instead of
re-prefilling. KV rows are verbatim either way, so greedy output stays
bit-identical across hit / promote / miss.

Contracts:

- **exact match by token** — a lookup only ever reuses KV rows whose token path
  is identical, token for token, to the prompt's own prefix. There are no
  approximate/fuzzy hits; a single differing token ends the match. Matches may
  end mid-edge (a stored longer prompt's first ``m`` rows are a valid slab for
  any prompt sharing those ``m`` tokens — K/V at row ``i`` depend only on
  tokens ``0..i``);
- **bit-exactness is a caller property** — slab rows are the *verbatim* device
  buffers a full prefill wrote (a spill round-trips them through host numpy
  unchanged), so greedy decode after a restore continues the identical token
  stream (asserted end-to-end in the serving tests and the chaos soak);
- **a hit never covers the whole prompt** — at least one suffix token is always
  left to prefill, because the first generated token comes from the suffix
  forward's logits;
- **LRU under a byte budget, per rung** — every insert/hit front-moves the
  entry in its rung; device inserts evict (spill) least-recently-used slabs
  until ``max_bytes`` holds, spills evict host LRU until ``host_tier_bytes``
  holds. An entry is resident in exactly one rung. Host slabs are independent
  numpy buffers, so pool rebuilds after replica faults never invalidate them;
  only real process death does (the router's ``revive`` clears the cache for
  exactly that reason).

Thread-safety: none needed — the cache lives inside a single-threaded
scheduler, like every other serving structure here.
"""

import hashlib
from collections import OrderedDict
from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple

import numpy as np


@dataclass
class PrefixCacheConfig:
    """``ServingConfig.prefix_cache``; ``None`` disables the cache entirely."""
    enabled: bool = True
    max_bytes: int = 256 * 1024 * 1024   # HBM budget for cached slabs
    min_hit_tokens: int = 8              # shorter matches re-prefill in full
    min_insert_tokens: int = 8           # don't cache trivially short prompts
    insert_on: str = "completion"        # "completion" | "prefill" (watermark:
    #   insert the moment prefill lands, so concurrent same-prefix requests hit)
    host_tier_bytes: int = 0             # host-RAM spill rung; 0 disables

    def __post_init__(self):
        if self.insert_on not in ("completion", "prefill"):
            raise ValueError(f"insert_on must be 'completion' or 'prefill', "
                             f"got {self.insert_on!r}")
        if self.max_bytes < 1:
            raise ValueError(f"max_bytes must be >= 1, got {self.max_bytes}")
        if self.host_tier_bytes < 0:
            raise ValueError(f"host_tier_bytes must be >= 0, "
                             f"got {self.host_tier_bytes}")


def slab_bytes(slab: List[Dict]) -> int:
    """Device bytes held by one per-layer KV slab."""
    return sum(int(s["k"].nbytes) + int(s["v"].nbytes) for s in slab)


# Prefix-digest gossip: hosted replicas cannot be peek-probed in-process, so
# they advertise what they could match as a small set of prefix digests in
# every heartbeat. Digests are taken at a fixed ladder of prefix lengths —
# the router hashes an incoming prompt at the same ladder points and the
# deepest digest both sides share lower-bounds the replica's real trie match.
DIGEST_LADDER = (16, 32, 64, 128, 256, 512)


def prefix_digest(tokens, k: int) -> str:
    """Stable digest of ``tokens[:k]`` (the ladder point is part of the key,
    so digests at different depths can never collide with each other)."""
    t = np.asarray(tokens, dtype=np.int32).reshape(-1)[:k]
    return f"{k}:{hashlib.blake2b(t.tobytes(), digest_size=8).hexdigest()}"


def match_from_digests(prompt, digests) -> int:
    """Deepest ladder point of ``prompt`` present in a replica's advertised
    digest set — a conservative lower bound on that replica's trie match
    length (0 when nothing matches or the gossip is absent/stale-empty)."""
    if not digests:
        return 0
    prompt = np.asarray(prompt, dtype=np.int32).reshape(-1)
    usable = int(prompt.size) - 1          # a hit never covers the whole prompt
    dset = set(digests)
    for k in reversed(DIGEST_LADDER):
        if k <= usable and prefix_digest(prompt, k) in dset:
            return k
    return 0


class _Entry:
    """A cached prefix anchored at a trie node (depth == covered tokens).

    Three storage forms: ``slab`` as device arrays — an independent gathered
    per-layer KV copy (slot-row pool, device rung); ``pages`` — REFCOUNTED
    physical page indices into the paged pool (zero-copy: a hit binds them
    into the new slot's table, an eviction is a refcount drop via the owner's
    ``page_release`` hook); ``slab`` as host numpy — a spilled dense copy on
    the host rung, restored device-side on a promote hit."""
    __slots__ = ("slab", "tokens", "bytes", "node", "pages")

    def __init__(self, slab: Optional[List[Dict]], tokens: int, node: "_Node",
                 pages=None, nbytes: Optional[int] = None):
        self.slab = slab            # per-layer {"k": (hk, R, d), "v": ...}
        self.pages = pages          # np (n,) physical page indices, or None
        self.tokens = int(tokens)   # real covered rows (== node depth)
        self.bytes = int(nbytes) if nbytes is not None else slab_bytes(slab)
        self.node = node


class _Node:
    """Path-compressed trie node; ``edge`` is the token run from the parent."""
    __slots__ = ("edge", "children", "parent", "entry", "depth")

    def __init__(self, edge: np.ndarray, parent: Optional["_Node"],
                 depth: int):
        self.edge = edge                      # (len,) int32 tokens from parent
        self.children: Dict[int, "_Node"] = {}
        self.parent = parent
        self.entry: Optional[_Entry] = None
        self.depth = int(depth)               # tokens root -> this node


def _common_len(a: np.ndarray, b: np.ndarray) -> int:
    n = min(a.size, b.size)
    if n == 0:
        return 0
    neq = np.nonzero(a[:n] != b[:n])[0]
    return int(neq[0]) if neq.size else n


class PrefixCache:
    """Radix trie over token-ID prefixes; leaves hold KV slabs; LRU by bytes
    over two residency rungs (device HBM, spilled host RAM)."""

    def __init__(self, config: Optional[PrefixCacheConfig] = None):
        self.config = config or PrefixCacheConfig()
        # paged mode: the pool's release_shared, set by the owning scheduler —
        # LRU eviction of a page entry decrefs through it, and so does
        # clear(): against a still-live pool (idle-replica revive) the pages
        # must return to the free list or they leak forever; against a pool
        # about to be discarded (_rebuild_pool) the decref is harmless.
        self.page_release = None
        # paged-mode spill hook: gather_pages(pages, rows) -> dense slab, set
        # by the owning scheduler. Without it a paged eviction cannot spill
        # (there is no dense copy to keep) and falls back to a plain drop.
        self.page_gather = None
        self.root = _Node(np.zeros(0, np.int32), None, 0)
        self._lru: "OrderedDict[int, _Entry]" = OrderedDict()  # id(entry) keyed
        self._host: "OrderedDict[int, _Entry]" = OrderedDict()  # spilled rung
        self.total_bytes = 0
        self.host_bytes = 0
        # counters (telemetry reads these through stats())
        self.hits = 0
        self.misses = 0
        self.hit_tokens = 0          # prefill tokens skipped via restores
        self.lookup_tokens = 0       # prompt tokens seen by lookup
        self.inserted = 0
        self.evicted = 0
        self.insert_skipped = 0      # too short / over-budget single slab
        self.spills = 0              # device evictions that kept a host copy
        self.spill_skipped = 0       # evictions that could not spill
        self.promotions = 0          # host-rung hits handed to the restore path
        self.host_evicted = 0        # host-rung LRU drops

    # ------------------------------------------------------------------ lookup
    def lookup(self, prompt) -> Tuple[int, Optional[_Entry]]:
        """Longest exact token match usable as a restored prefix.

        Returns ``(matched_tokens, entry)``; ``(0, None)`` is a miss. The
        returned entry's slab covers *at least* ``matched_tokens`` valid rows
        (restore writes the whole padded slab; rows beyond the match are
        overwritten by the suffix prefill or masked by ``cache_len``).
        ``matched_tokens`` is capped at ``len(prompt) - 1`` so the suffix is
        never empty, and matches below ``min_hit_tokens`` report as misses.
        A host-rung entry (``entry.pages is None`` with a numpy slab) is a
        **promote** hit: the caller restores the slab into the slot instead
        of binding pages, paying one copy instead of a re-prefill.
        """
        prompt = np.asarray(prompt, dtype=np.int32).reshape(-1)
        self.lookup_tokens += int(prompt.size)
        usable, entry = self._match(prompt)
        if entry is None:
            self.misses += 1
            return 0, None
        self.hits += 1
        self.hit_tokens += usable
        if id(entry) in self._host:
            self.promotions += 1
        self._touch(entry)
        return usable, entry

    def peek(self, prompt) -> Tuple[int, Optional[_Entry]]:
        """What ``lookup`` would return, with no side effects: no hit/miss
        counters, no LRU touch. Admission-pressure eviction peeks the head
        request's prefix to know which entry it must NOT evict (and how many
        fresh pages the head actually needs) without double-counting the
        real lookup that follows on admission; the router's prefix-aware
        dispatch peeks in-process replicas for the same reason."""
        return self._match(np.asarray(prompt, dtype=np.int32).reshape(-1))

    def _match(self, prompt: np.ndarray) -> Tuple[int, Optional[_Entry]]:
        node, i = self.root, 0
        best_anchor: Optional[_Entry] = None     # deepest full-node entry
        best_anchor_len = 0
        stopped: Optional[_Node] = None          # subtree a mid-edge match hit
        while i < prompt.size:
            child = node.children.get(int(prompt[i]))
            if child is None:
                break
            m = _common_len(prompt[i:], child.edge)
            i += m
            if m < child.edge.size:
                # diverged (or prompt ended) mid-edge: every entry below
                # `child` still shares the first `i` tokens with the prompt
                stopped = child
                break
            node = child
            if node.entry is not None:
                best_anchor, best_anchor_len = node.entry, node.depth
        matched, entry = best_anchor_len, best_anchor
        # deeper option: any entry in the subtree we stopped in covers `i`
        sub = stopped if stopped is not None else node
        if i > matched:
            deeper = self._first_entry(sub)
            if deeper is not None:
                matched, entry = i, deeper
        usable = min(matched, int(prompt.size) - 1)
        if entry is None or usable < max(1, self.config.min_hit_tokens):
            return 0, None
        return usable, entry

    def contains(self, prompt) -> bool:
        """Exact-path probe: is this full prompt already DEVICE-resident?
        (Read-only walk — lets callers skip the device gather whose slab
        ``insert`` would only drop; refreshes the resident entry's LRU
        position on True, since the caller's intent was an insert-or-touch.)
        A host-rung entry at the exact path reports False on purpose: the
        caller just finished a full device prefill of this prompt, and the
        re-insert upgrades the spilled copy back to the device rung — without
        that, a spilled prefix would pay the promote copy on every repeat
        forever."""
        prompt = np.asarray(prompt, dtype=np.int32).reshape(-1)
        node, i = self.root, 0
        while i < prompt.size:
            child = node.children.get(int(prompt[i]))
            if child is None:
                return False
            m = _common_len(prompt[i:], child.edge)
            i += m
            if m < child.edge.size:
                return False
            node = child
        if (node.depth == prompt.size and node.entry is not None
                and id(node.entry) in self._lru):
            self._touch(node.entry)
            return True
        return False

    def _first_entry(self, node: _Node) -> Optional[_Entry]:
        stack = [node]
        while stack:
            n = stack.pop()
            if n.entry is not None:
                return n.entry
            stack.extend(n.children.values())
        return None

    # ------------------------------------------------------------------ insert
    def insert(self, prompt, slab: List[Dict]) -> bool:
        """Index ``slab`` (rows padded; rows ``[0, len(prompt))`` are the
        prompt's KV) under the full prompt token path. Re-inserting an already
        device-resident path just refreshes its LRU position (same tokens ⇒
        bit-identical KV, so the resident slab is kept and the new one
        dropped); re-inserting over a host-rung entry upgrades the path back
        to the device rung. Returns True when the slab is (now) resident."""
        prompt = np.asarray(prompt, dtype=np.int32).reshape(-1)
        if prompt.size < max(1, self.config.min_insert_tokens):
            self.insert_skipped += 1
            return False
        nbytes = slab_bytes(slab)
        if nbytes > self.config.max_bytes:
            self.insert_skipped += 1
            return False
        node = self._descend(prompt)
        if node.entry is not None:
            if id(node.entry) in self._lru:
                self._touch(node.entry)
                return True
            self._drop_host(node.entry, prune=False)   # upgrade host -> device
        entry = _Entry(slab, prompt.size, node)
        node.entry = entry
        self._lru[id(entry)] = entry
        self.total_bytes += entry.bytes
        self.inserted += 1
        self._evict_to_budget(keep=entry)
        return True

    def insert_pages(self, prompt, pages, nbytes: int) -> bool:
        """Paged-pool insert: index refcounted page indices under the prompt
        path. Returns True when the cache TOOK OWNERSHIP of the caller's page
        references; False (too short / over budget / already device-resident)
        means the caller must release them. A host-rung entry at the path is
        upgraded: the spilled slab is dropped and the path becomes page-backed
        again. ``nbytes`` counts whole pages and may double-count physically
        shared pages across entries — the budget is an upper bound on
        distinct bytes, never an undercount."""
        prompt = np.asarray(prompt, dtype=np.int32).reshape(-1)
        if prompt.size < max(1, self.config.min_insert_tokens):
            self.insert_skipped += 1
            return False
        if nbytes > self.config.max_bytes:
            self.insert_skipped += 1
            return False
        node = self._descend(prompt)
        if node.entry is not None:
            if id(node.entry) in self._lru:
                self._touch(node.entry)  # resident: keep its refs, drop yours
                return False
            self._drop_host(node.entry, prune=False)   # upgrade host -> device
        entry = _Entry(None, prompt.size, node, pages=np.asarray(pages),
                       nbytes=nbytes)
        node.entry = entry
        self._lru[id(entry)] = entry
        self.total_bytes += entry.bytes
        self.inserted += 1
        self._evict_to_budget(keep=entry)
        return True

    def _descend(self, tokens: np.ndarray) -> _Node:
        """Walk/extend/split the trie so a node exists exactly at ``tokens``."""
        node, i = self.root, 0
        while i < tokens.size:
            child = node.children.get(int(tokens[i]))
            if child is None:
                new = _Node(tokens[i:].copy(), node, tokens.size)
                node.children[int(tokens[i])] = new
                return new
            m = _common_len(tokens[i:], child.edge)
            if m == child.edge.size:
                node, i = child, i + m
                continue
            # split child's edge at m
            mid = _Node(child.edge[:m].copy(), node,
                        child.depth - (child.edge.size - m))
            node.children[int(tokens[i])] = mid
            child.edge = child.edge[m:]
            child.parent = mid
            mid.children[int(child.edge[0])] = child
            node, i = mid, i + m
        return node

    # ---------------------------------------------------------------- eviction
    def _touch(self, entry: _Entry) -> None:
        if id(entry) in self._lru:
            self._lru.move_to_end(id(entry))
        else:
            self._host.move_to_end(id(entry))

    def _evict_to_budget(self, keep: Optional[_Entry] = None) -> int:
        evicted = 0
        while self.total_bytes > self.config.max_bytes and self._lru:
            victim = next(iter(self._lru.values()))
            if victim is keep:
                break        # never evict the slab being inserted
            self._remove(victim)
            evicted += 1
        return evicted

    def evict_lru(self, predicate=None) -> bool:
        """Evict the least-recently-used device entry matching ``predicate``
        (admission-pressure eviction: on the paged pool, cached prefixes pin
        real pool pages, so when admission runs out of free pages the
        scheduler trades cold cached prefixes for admission capacity). The
        predicate lets the caller skip entries whose eviction would free
        nothing — an entry all of whose pages are still bound by live slots
        is pure loss to drop, since the pages stay allocated either way.
        Returns False when nothing eligible remains."""
        for entry in self._lru.values():
            if predicate is None or predicate(entry):
                self._remove(entry)
                return True
        return False

    def _remove(self, entry: _Entry, spill: bool = True) -> None:
        del self._lru[id(entry)]
        self.total_bytes -= entry.bytes
        self.evicted += 1
        spilled = spill and self._spill(entry)
        if entry.pages is not None and self.page_release is not None:
            # paged eviction IS a refcount drop: pages still bound by live
            # slots survive in the pool until those slots release too (the
            # spill gathered its dense host copy before this drop)
            self.page_release(entry.pages)
        if spilled:
            entry.pages = None
            return                  # node keeps the entry, now host-resident
        node = entry.node
        node.entry = None
        self._prune(node)

    def _spill(self, entry: _Entry) -> bool:
        """Demote a device entry to the host rung: gather its KV as a dense
        host-numpy slab under the host byte budget. Returns False (plain
        drop) when the tier is off, a paged entry has no gather hook, or the
        slab alone exceeds the host budget."""
        if self.config.host_tier_bytes <= 0:
            return False
        if entry.pages is not None:
            if self.page_gather is None:
                self.spill_skipped += 1
                return False
            slab = self.page_gather(entry.pages, entry.tokens)
        else:
            slab = entry.slab
        host = [{"k": np.asarray(s["k"]), "v": np.asarray(s["v"])}
                for s in slab]
        nbytes = slab_bytes(host)
        if nbytes > self.config.host_tier_bytes:
            self.spill_skipped += 1
            return False
        while (self.host_bytes + nbytes > self.config.host_tier_bytes
               and self._host):
            self._drop_host(next(iter(self._host.values())), prune=True)
        entry.slab = host
        entry.bytes = nbytes
        self._host[id(entry)] = entry
        self.host_bytes += nbytes
        self.spills += 1
        return True

    def _drop_host(self, entry: _Entry, prune: bool) -> None:
        """Remove a host-rung entry; ``prune=False`` is the upgrade path
        (the caller immediately re-occupies the node with a device entry)."""
        del self._host[id(entry)]
        self.host_bytes -= entry.bytes
        if prune:
            self.host_evicted += 1
            node = entry.node
            node.entry = None
            self._prune(node)

    def _prune(self, node: _Node) -> None:
        # prune entry-less leaf chains so the trie doesn't accrete dead paths
        while (node.parent is not None and node.entry is None
               and not node.children):
            parent = node.parent
            del parent.children[int(node.edge[0])]
            node = parent

    def drop_device(self) -> None:
        """Drop the device rung WITHOUT spilling (``_rebuild_pool``: the pool
        the slabs/pages live in was poisoned by a donation-consumed failure,
        so gathering from it is not trustworthy). Host-rung entries are
        independent numpy buffers and survive to serve promote hits against
        the rebuilt pool — the tiered analogue of the slot pool's
        "independent slabs survive rebuilds" property."""
        for entry in list(self._lru.values()):
            self._remove(entry, spill=False)

    def clear(self) -> None:
        """Drop everything, both rungs (models total state loss on replica
        process death). Paged entries decref through ``page_release`` first —
        without it an idle replica's revive would strand every cached
        prefix's refcounts in the still-live pool (see ``__init__``)."""
        if self.page_release is not None:
            for entry in self._lru.values():
                if entry.pages is not None:
                    self.page_release(entry.pages)
        self.root = _Node(np.zeros(0, np.int32), None, 0)
        self._lru.clear()
        self._host.clear()
        self.total_bytes = 0
        self.host_bytes = 0

    # ----------------------------------------------------------------- metrics
    @property
    def entries(self) -> int:
        return len(self._lru)

    @property
    def host_entries(self) -> int:
        return len(self._host)

    @property
    def hit_rate(self) -> float:
        n = self.hits + self.misses
        return self.hits / n if n else 0.0

    def _entry_tokens(self, entry: _Entry) -> np.ndarray:
        parts = []
        node = entry.node
        while node is not None:
            parts.append(node.edge)
            node = node.parent
        parts.reverse()
        return np.concatenate(parts) if parts else np.zeros(0, np.int32)

    def digest_report(self, limit: int = 16) -> List[str]:
        """Prefix digests (see :data:`DIGEST_LADDER`) for the most recently
        used entries across both rungs — the match-capability gossip a hosted
        replica ships in its heartbeat. Bounded: at most ``limit`` entries
        are walked, each contributing one digest per ladder point it covers."""
        out: List[str] = []
        seen = set()
        scanned = 0
        for rung in (self._lru, self._host):
            for entry in reversed(rung.values()):      # most recent first
                if scanned >= limit:
                    break
                scanned += 1
                tokens = self._entry_tokens(entry)
                for k in DIGEST_LADDER:
                    if k > entry.tokens:
                        break
                    d = prefix_digest(tokens, k)
                    if d not in seen:
                        seen.add(d)
                        out.append(d)
        return out

    def stats(self) -> Dict:
        return {
            "hits": self.hits,
            "misses": self.misses,
            "hit_rate": self.hit_rate,
            "hit_tokens": self.hit_tokens,
            "lookup_tokens": self.lookup_tokens,
            "inserted": self.inserted,
            "evicted": self.evicted,
            "insert_skipped": self.insert_skipped,
            "entries": self.entries,
            "cached_bytes": self.total_bytes,
            "max_bytes": self.config.max_bytes,
            "spills": self.spills,
            "spill_skipped": self.spill_skipped,
            "promotions": self.promotions,
            "host_evicted": self.host_evicted,
            "host_entries": self.host_entries,
            "spilled_bytes": self.host_bytes,
            "host_max_bytes": self.config.host_tier_bytes,
        }
