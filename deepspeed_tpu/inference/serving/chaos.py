"""Chaos schedule: scripted replica kills/stalls/revives against a Router.

Spec grammar (the ``--chaos`` flag of ``deepspeed-serve`` and the loadgen):
semicolon-separated events, each ``kind:key=val,...``:

    kill:replica=1,at=0.5          # kill replica 1 at t=0.5s
    kill:replica=1,when=busy       # kill replica 1 the moment it has in-flight
                                   # work with >=1 generated token (guarantees a
                                   # real mid-decode eviction, deterministically)
    kill:replica=1,sig=KILL        # hosted replicas: the REAL signal delivered
                                   # to the child process — sig=KILL (default,
                                   # the preempted-host model) or sig=TERM
                                   # (child drains in-flight work, then exits);
                                   # in-process replicas keep flag semantics
                                   # (sig= is accepted and ignored there)
    kill:replica=1,when=restore    # kill replica 1 in the window BETWEEN its
                                   # next prefix-slab restore and the suffix
                                   # prefill (prefix-cache soak lane: guards the
                                   # restore path's donation discipline)
    kill:replica=1,when=draining   # kill replica 1 the moment it is RETIRING
                                   # (mid-scale-down: the autoscale soak lane —
                                   # the drain/hand-off parity contract must
                                   # hold even when the drained replica dies)
    stall:replica=0,when=busy,s=0.6   # wedge replica 0's next chunk for 0.6s
                                      # (the chunk watchdog turns this into a
                                      # ChunkTimeoutError); against a HOSTED
                                      # replica the wedge is a real
                                      # SIGSTOP/SIGCONT on the child process —
                                      # its heartbeat stream goes silent and
                                      # the pipe-silence watchdog ages it
    revive:replica=1,at=2.0        # bring a killed replica back (RECOVERING
                                   # probe follows per the router state machine)
    surge:mult=4,at=1.0,s=2.0      # LOAD hook: multiply the offered arrival
                                   # rate by 4x for 2s starting at t=1.0 (the
                                   # loadgen consults load_multiplier(); no
                                   # replica action)
    net:replica=1,mode=partition,at=1.0,s=2.0
                                   # NETWORK faults (socket-hosted replicas
                                   # only — the transport seam must exist, or
                                   # the event raises rather than letting the
                                   # soak pass vacuously):
                                   #   mode=partition — silence both ways for
                                   #     s seconds (heartbeats freeze -> the
                                   #     replica ages SUSPECT->DEAD -> the
                                   #     supervisor respawns or the link
                                   #     redials);
                                   #   mode=delay=<ms> — every read delayed
                                   #     by <ms> (heartbeat jitter: below the
                                   #     SUSPECT threshold it must NOT
                                   #     false-kill);
                                   #   mode=drop=<p> — each read corrupted
                                   #     with probability p (frame CRC
                                   #     quarantine + resync under load)

Events fire at most once. ``at`` is seconds since :class:`ChaosSchedule` start;
``when=busy`` fires on the first poll where the target replica has a running
request; ``when=draining`` fires on the first poll where the target replica is
``RETIRING`` (a scale-down drain in progress — pair it with an autoscaler or an
explicit ``begin_retire``, or the event never fires and ``exhausted`` stays
False, which the soak asserts). ``when=restore`` (kill only) arms the
executor's restore-kill hook on the first poll and counts as fired once a
cache-hit admission actually trips it — it lands *inside* a scheduler step, a
boundary ``poll()`` alone can never hit. ``surge`` marks itself fired when its
window opens; :meth:`ChaosSchedule.load_multiplier` is the product of every
currently-open surge window (1.0 when none). ``poll()`` is called from the
driving loop (loadgen / serve).

With an elastic replica set (PR 12) events address replicas **by id** (ids are
monotonic and never reused). A ``when=``-triggered event whose target is not
currently attached simply waits — the autoscaler may attach/retire it later;
an ``at=``-triggered event DUE against a detached/unknown replica raises (a
mistyped id must fail the run, never silently leave the soak fault-free). The
waiting form's backstop is run-level: the loadgen records ``chaos_exhausted``
/ ``chaos_unfired`` in the BENCH JSON and FAILS the run when any event never
fired — a mistyped ``when=`` id cannot pass quietly either.
"""

import time
from dataclasses import dataclass, field
from typing import List, Optional

from ...utils.logging import logger

KINDS = ("kill", "stall", "revive", "surge", "net")

#: net fault modes and whether each carries an embedded value (mode=delay=80)
NET_MODES = {"partition": False, "delay": True, "drop": True}


@dataclass
class ChaosEvent:
    kind: str                       # kill | stall | revive | surge | net
    replica: int = 0
    at: Optional[float] = None      # seconds after schedule start
    when: Optional[str] = None      # "busy" | "restore" | "draining"
    duration: float = 0.5           # stall seconds / surge+net window seconds
    mult: float = 2.0               # surge rate multiplier
    sig: Optional[str] = None       # kill only: TERM | KILL — the real signal
    #   a HOSTED replica's child receives (in-process kills stay flag-only)
    mode: Optional[str] = None      # net only: partition | delay | drop
    value: float = 0.0              # net only: delay ms / drop probability
    fired: bool = False
    armed: bool = False             # when=restore: hook installed, not yet hit

    def __post_init__(self):
        if self.kind not in KINDS:
            raise ValueError(f"unknown chaos kind {self.kind!r} "
                             f"(expected one of {KINDS})")
        if self.sig is not None:
            if self.kind != "kill":
                raise ValueError("sig= is a kill-only field "
                                 f"(got it on {self.kind!r})")
            self.sig = self.sig.upper()
            if self.sig not in ("TERM", "KILL"):
                raise ValueError(f"unknown kill signal sig={self.sig!r} "
                                 "(expected TERM or KILL)")
        if self.mode is not None and self.kind != "net":
            raise ValueError("mode= is a net-only field "
                             f"(got it on {self.kind!r})")
        if self.kind == "net":
            if self.mode is None:
                raise ValueError("chaos net needs mode=partition|"
                                 "delay=<ms>|drop=<p>")
            if self.mode not in NET_MODES:
                raise ValueError(f"unknown net fault mode {self.mode!r} "
                                 f"(expected one of {tuple(NET_MODES)})")
            if self.mode == "delay" and self.value <= 0:
                raise ValueError("chaos net mode=delay=<ms> needs a positive "
                                 "millisecond value")
            if self.mode == "drop" and not (0.0 < self.value <= 1.0):
                raise ValueError("chaos net mode=drop=<p> needs a "
                                 "probability in (0, 1]")
        if self.kind == "surge":
            if self.at is None:
                raise ValueError("chaos surge needs at=<s>")
            if self.when is not None:
                raise ValueError("chaos surge is time-triggered only "
                                 "(at=<s>,s=<dur>,mult=<x>)")
            if self.mult <= 0:
                raise ValueError(f"surge mult must be > 0, got {self.mult}")
            return
        if self.at is None and self.when is None:
            raise ValueError(f"chaos event {self.kind!r} needs at=<s> or "
                             "when=busy|restore|draining")
        if self.when is not None and self.when not in ("busy", "restore",
                                                       "draining"):
            raise ValueError(f"unknown chaos trigger when={self.when!r}")
        if self.when == "restore" and self.kind != "kill":
            raise ValueError("when=restore is a kill-only trigger (it models "
                             "death inside the restore->prefill window)")
        if self.when == "draining" and self.kind != "kill":
            raise ValueError("when=draining is a kill-only trigger (it models "
                             "death mid-scale-down)")


def parse_chaos(spec: str) -> List[ChaosEvent]:
    """Parse a ``--chaos`` spec string into events (see module docstring)."""
    events = []
    for part in filter(None, (p.strip() for p in spec.split(";"))):
        if ":" not in part:
            raise ValueError(f"malformed chaos event {part!r} "
                             "(expected kind:key=val,...)")
        kind, _, rest = part.partition(":")
        kv = {}
        for item in filter(None, (i.strip() for i in rest.split(","))):
            if "=" not in item:
                raise ValueError(f"malformed chaos field {item!r} in {part!r}")
            k, _, v = item.partition("=")
            kv[k.strip()] = v.strip()
        # net mode may embed its value: the field split partitions on the
        # FIRST '=', so "mode=delay=80" parses to kv["mode"] == "delay=80"
        mode, value = kv.get("mode"), 0.0
        if mode is not None and "=" in mode:
            mode, _, raw_value = mode.partition("=")
            try:
                value = float(raw_value)
            except ValueError:
                raise ValueError(f"malformed net fault value in "
                                 f"mode={kv['mode']!r}")
        events.append(ChaosEvent(
            kind=kind.strip(),
            replica=int(kv.get("replica", 0)),
            at=float(kv["at"]) if "at" in kv else None,
            when=kv.get("when"),
            sig=kv.get("sig"),
            mode=mode, value=value,
            mult=float(kv.get("mult", 2.0)),
            duration=float(kv.get("s", kv.get("duration", 0.5)))))
    return events


@dataclass
class ChaosSchedule:
    """Applies parsed events against a router as their triggers come due."""

    events: List[ChaosEvent]
    t0: float = field(default_factory=time.monotonic)

    def load_multiplier(self, now: Optional[float] = None) -> float:
        """Product of every open surge window's ``mult`` (1.0 when none) —
        the loadgen's offered-rate hook, independent of ``fired``."""
        now = time.monotonic() if now is None else now
        t = now - self.t0
        mult = 1.0
        for ev in self.events:
            if ev.kind == "surge" and ev.at <= t < ev.at + ev.duration:
                mult *= ev.mult
        return mult

    def _due(self, ev: ChaosEvent, router, replica, now: float) -> bool:
        if ev.when == "busy":
            # require a WARM replica (first chunk compiled and completed): the
            # point of when=busy is a deterministic mid-decode hit, and a kill/
            # stall landing inside the first compile is a cold-start test, not
            # a mid-decode one
            return replica.running > 0 and getattr(replica.scheduler.executor,
                                                   "chunk_warm", True)
        if ev.when == "draining":
            from .router import ReplicaState
            return router.replica_state(ev.replica) == ReplicaState.RETIRING
        return True                        # at=: due-ness checked before
        #   target resolution in poll()

    def _target(self, ev: ChaosEvent, router):
        """The attached replica an event addresses, by id. ``when=`` events
        wait for an unattached target (the autoscaler may mint it later);
        ``at=`` events raise once DUE — a mistyped id must fail the run, not
        silently leave the soak fault-free, but an autoscaler may still mint
        the id before the due time."""
        replica = (router.replica_by_id(ev.replica)
                   if hasattr(router, "replica_by_id")
                   else (router.replicas[ev.replica]
                         if ev.replica < len(router.replicas) else None))
        if replica is None and ev.when is None:
            raise ValueError(f"chaos event {ev.kind!r} targets replica "
                             f"{ev.replica} but it is not attached "
                             f"(attached ids: "
                             f"{[r.id for r in router.replicas]})")
        return replica

    def poll(self, router, now: Optional[float] = None) -> List[ChaosEvent]:
        """Fire every due event once; returns the events applied this poll."""
        now = time.monotonic() if now is None else now
        applied = []
        for ev in self.events:
            if ev.fired:
                continue
            if ev.kind == "surge":
                if now - self.t0 >= ev.at:
                    ev.fired = True     # multiplier runs off the window, not
                    applied.append(ev)  # this flag — fired = "window opened"
                    logger.warning(f"[chaos] surge x{ev.mult} for "
                                   f"{ev.duration}s")
                continue
            if ev.when is None and now - self.t0 < ev.at:
                continue                # at=: not due yet — don't resolve the
                #   target early, an autoscaler may mint the id before then
            replica = self._target(ev, router)
            if replica is None:
                continue                # when=-triggered: target not yet born
            if ev.when == "restore":
                # two-phase: arm the executor hook once; it fires inside the
                # next cache-hit admission (between restore and suffix
                # prefill), a window in-between-steps polling cannot reach
                if replica.scheduler.prefix_cache is None:
                    # without a prefix cache the hook is unreachable and the
                    # soak would pass vacuously ("a chaos run must never
                    # degrade to nothing")
                    raise ValueError(
                        f"chaos when=restore targets replica {ev.replica} "
                        "but its prefix cache is disabled — enable "
                        "ServingConfig.prefix_cache (--prefix-cache)")
                ex = replica.scheduler.executor
                if not ev.armed:
                    ex.arm_restore_kill(replica.kill)
                    ev.armed = True
                    logger.warning(f"[chaos] armed restore-kill on replica "
                                   f"{ev.replica}")
                elif not ex.restore_kill_pending:
                    ev.fired = True           # the hook was consumed
                    applied.append(ev)
                continue
            if not self._due(ev, router, replica, now):
                continue
            if ev.kind == "net" and not hasattr(replica, "net_fault"):
                # the transport seam must exist (socket-hosted replicas): a
                # net fault silently skipped would let the soak pass
                # vacuously — "a chaos run must never degrade to nothing"
                raise ValueError(
                    f"chaos net targets replica {ev.replica} but it has no "
                    "network transport seam — net: faults require a "
                    "socket-hosted replica (SocketHostedReplica / "
                    "--replica-endpoint)")
            ev.fired = True
            if ev.kind == "kill":
                if getattr(replica, "is_hosted", False):
                    # real-signal delivery to the child process; in-process
                    # replicas keep the flag semantics below
                    replica.kill(sig=ev.sig or "KILL")
                else:
                    replica.kill()
            elif ev.kind == "revive":
                replica.revive()
            elif ev.kind == "stall":
                # hosted replicas route this to a real SIGSTOP/SIGCONT via
                # their executor view; in-process wedge the next chunk
                replica.scheduler.executor.stall_next(ev.duration)
            elif ev.kind == "net":
                replica.net_fault(ev.mode, ev.value, ev.duration)
            logger.warning(f"[chaos] {ev.kind} replica {ev.replica}"
                           + (f" sig={ev.sig}" if ev.sig else "")
                           + (f" mode={ev.mode}" if ev.mode else "")
                           + (f" ({ev.duration}s)"
                              if ev.kind in ("stall", "net") else "")
                           + (" (mid-retire)" if ev.when == "draining"
                              else ""))
            applied.append(ev)
        return applied

    @property
    def exhausted(self) -> bool:
        return all(ev.fired for ev in self.events)
