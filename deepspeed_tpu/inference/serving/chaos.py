"""Chaos schedule: scripted replica kills/stalls/revives against a Router.

Spec grammar (the ``--chaos`` flag of ``deepspeed-serve`` and the loadgen):
semicolon-separated events, each ``kind:key=val,...``:

    kill:replica=1,at=0.5          # kill replica 1 at t=0.5s
    kill:replica=1,when=busy       # kill replica 1 the moment it has in-flight
                                   # work with >=1 generated token (guarantees a
                                   # real mid-decode eviction, deterministically)
    kill:replica=1,when=restore    # kill replica 1 in the window BETWEEN its
                                   # next prefix-slab restore and the suffix
                                   # prefill (prefix-cache soak lane: guards the
                                   # restore path's donation discipline)
    stall:replica=0,when=busy,s=0.6   # wedge replica 0's next chunk for 0.6s
                                      # (the chunk watchdog turns this into a
                                      # ChunkTimeoutError)
    revive:replica=1,at=2.0        # bring a killed replica back (RECOVERING
                                   # probe follows per the router state machine)

Events fire at most once. ``at`` is seconds since :class:`ChaosSchedule` start;
``when=busy`` fires on the first poll where the target replica has a running
request. ``when=restore`` (kill only) arms the executor's restore-kill hook on
the first poll and counts as fired once a cache-hit admission actually trips it
— it lands *inside* a scheduler step, a boundary ``poll()`` alone can never
hit. ``poll()`` is called from the driving loop (loadgen / serve).
"""

import time
from dataclasses import dataclass, field
from typing import List, Optional

from ...utils.logging import logger

KINDS = ("kill", "stall", "revive")


@dataclass
class ChaosEvent:
    kind: str                       # kill | stall | revive
    replica: int
    at: Optional[float] = None      # seconds after schedule start
    when: Optional[str] = None      # "busy" | "restore"
    duration: float = 0.5           # stall seconds
    fired: bool = False
    armed: bool = False             # when=restore: hook installed, not yet hit

    def __post_init__(self):
        if self.kind not in KINDS:
            raise ValueError(f"unknown chaos kind {self.kind!r} "
                             f"(expected one of {KINDS})")
        if self.at is None and self.when is None:
            raise ValueError(f"chaos event {self.kind!r} needs at=<s> or "
                             "when=busy")
        if self.when is not None and self.when not in ("busy", "restore"):
            raise ValueError(f"unknown chaos trigger when={self.when!r}")
        if self.when == "restore" and self.kind != "kill":
            raise ValueError("when=restore is a kill-only trigger (it models "
                             "death inside the restore->prefill window)")


def parse_chaos(spec: str) -> List[ChaosEvent]:
    """Parse a ``--chaos`` spec string into events (see module docstring)."""
    events = []
    for part in filter(None, (p.strip() for p in spec.split(";"))):
        if ":" not in part:
            raise ValueError(f"malformed chaos event {part!r} "
                             "(expected kind:key=val,...)")
        kind, _, rest = part.partition(":")
        kv = {}
        for item in filter(None, (i.strip() for i in rest.split(","))):
            if "=" not in item:
                raise ValueError(f"malformed chaos field {item!r} in {part!r}")
            k, _, v = item.partition("=")
            kv[k.strip()] = v.strip()
        events.append(ChaosEvent(
            kind=kind.strip(),
            replica=int(kv.get("replica", 0)),
            at=float(kv["at"]) if "at" in kv else None,
            when=kv.get("when"),
            duration=float(kv.get("s", kv.get("duration", 0.5)))))
    return events


@dataclass
class ChaosSchedule:
    """Applies parsed events against a router as their triggers come due."""

    events: List[ChaosEvent]
    t0: float = field(default_factory=time.monotonic)

    def _due(self, ev: ChaosEvent, router, now: float) -> bool:
        if ev.when == "busy":
            # require a WARM replica (first chunk compiled and completed): the
            # point of when=busy is a deterministic mid-decode hit, and a kill/
            # stall landing inside the first compile is a cold-start test, not
            # a mid-decode one
            r = router.replicas[ev.replica]
            return r.running > 0 and getattr(r.scheduler.executor,
                                             "chunk_warm", True)
        return now - self.t0 >= ev.at

    def poll(self, router, now: Optional[float] = None) -> List[ChaosEvent]:
        """Fire every due event once; returns the events applied this poll."""
        now = time.monotonic() if now is None else now
        applied = []
        for ev in self.events:
            if ev.replica >= len(router.replicas):
                # a mistyped index must fail the run, not silently leave the
                # soak fault-free ("a chaos run must never degrade to nothing")
                raise ValueError(f"chaos event {ev.kind!r} targets replica "
                                 f"{ev.replica} but the router has only "
                                 f"{len(router.replicas)}")
            if ev.fired:
                continue
            if ev.when == "restore":
                # two-phase: arm the executor hook once; it fires inside the
                # next cache-hit admission (between restore and suffix
                # prefill), a window in-between-steps polling cannot reach
                replica = router.replicas[ev.replica]
                if replica.scheduler.prefix_cache is None:
                    # without a prefix cache the hook is unreachable and the
                    # soak would pass vacuously ("a chaos run must never
                    # degrade to nothing")
                    raise ValueError(
                        f"chaos when=restore targets replica {ev.replica} "
                        "but its prefix cache is disabled — enable "
                        "ServingConfig.prefix_cache (--prefix-cache)")
                ex = replica.scheduler.executor
                if not ev.armed:
                    ex.arm_restore_kill(replica.kill)
                    ev.armed = True
                    logger.warning(f"[chaos] armed restore-kill on replica "
                                   f"{ev.replica}")
                elif not ex.restore_kill_pending:
                    ev.fired = True           # the hook was consumed
                    applied.append(ev)
                continue
            if not self._due(ev, router, now):
                continue
            ev.fired = True
            replica = router.replicas[ev.replica]
            if ev.kind == "kill":
                replica.kill()
            elif ev.kind == "revive":
                replica.revive()
            elif ev.kind == "stall":
                replica.scheduler.executor.stall_next(ev.duration)
            logger.warning(f"[chaos] {ev.kind} replica {ev.replica}"
                           + (f" ({ev.duration}s)" if ev.kind == "stall"
                              else ""))
            applied.append(ev)
        return applied

    @property
    def exhausted(self) -> bool:
        return all(ev.fired for ev in self.events)
