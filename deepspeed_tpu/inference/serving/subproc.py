"""Subprocess-hosted serving replica: a real process boundary for router tests.

The router's in-process :class:`~.router.EngineReplica` simulates death with a
flag; this module hosts the same engine + scheduler stack in a CHILD process so
tests can deliver a real ``SIGKILL`` and prove the recovery model end-to-end.
It exists because the router's whole recovery design is **prefix-only**: the
parent's view of a replica is nothing but the token prefixes streamed back so
far, so after a kill the request continues bit-identically by re-prefilling
``prompt + streamed_prefix`` anywhere else — no shared memory, no checkpoint,
no device state crosses the process boundary.

Protocol (JSONL over stdin/stdout, every line flushed — the stream must be
truthful at the instant a SIGKILL lands):

- child → ``{"ready": true, "faults_armed": N}`` once the engine is built
  (``N`` from :func:`~...utils.fault_injection.apply_fault_env` — the
  ``DS_TPU_FAULT_SPEC`` env contract arms seeded fault schedules in the child,
  same as ``deepspeed-serve``);
- parent → ``{"id": i, "prompt": [...], "max_new_tokens": n, "seed": s,
  "eos_token_id": e|null, "trace_id": t|absent, "parent_span": p|absent}``
  submits a request (``trace_id``/``parent_span`` propagate the parent's
  span context: the child's tracer joins its spans to that trace, so a
  subprocess-hosted replica's restore/prefill/decode-chunk spans land on the
  SAME trace id as the frontend's — the cross-process half of the
  observability spine);
- child → ``{"id": i, "tokens": [...], "done": bool, "state": "..."}`` after
  every scheduler step in which request ``i`` gained tokens (cumulative
  prefix, not a delta — idempotent under lost/duplicated reads);
- child → ``{"spans": [...]}`` whenever traced spans finished since the last
  step (each span dict is ``observability.trace`` wire format; the parent
  ingests them into its own tracer under a ``subproc<pid>`` lane);
- parent → ``{"cmd": "stop"}`` (or EOF) drains and exits 0.

Determinism contract: the child builds its engine with the same fixed init
seed as an in-parent engine of identical dims, so the parent can compute
bit-exact references with its OWN engine — weights never cross the pipe.

Run as ``python -m deepspeed_tpu.inference.serving.subproc --vocab-size ...``
(the parent-side :class:`SubprocessReplica` wraps spawn/stream/kill).
"""

import argparse
import json
import os
import signal
import subprocess
import sys
import threading
import time
from collections import deque
from typing import Dict, List, Optional


def child_main(argv=None) -> int:
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    ap = argparse.ArgumentParser(prog="serving.subproc")
    ap.add_argument("--vocab-size", type=int, default=96)
    ap.add_argument("--max-seq-len", type=int, default=64)
    ap.add_argument("--n-embd", type=int, default=32)
    ap.add_argument("--n-layer", type=int, default=2)
    ap.add_argument("--n-head", type=int, default=4)
    ap.add_argument("--slots", type=int, default=2)
    ap.add_argument("--chunk-size", type=int, default=2)
    ap.add_argument("--prefix-cache", action="store_true")
    args = ap.parse_args(argv)

    import jax.numpy as jnp

    from ...observability.trace import SpanContext, get_tracer
    from ...utils.fault_injection import apply_fault_env
    from ..config import DeepSpeedInferenceConfig
    from ..engine import InferenceEngine
    from ...models.causal_lm import gpt2_cfg
    from .prefix_cache import PrefixCacheConfig
    from .scheduler import ContinuousBatchingScheduler, ServingConfig

    armed = apply_fault_env()       # DS_TPU_FAULT_SPEC: seeded child schedule
    engine = InferenceEngine(
        gpt2_cfg(vocab_size=args.vocab_size, max_seq_len=args.max_seq_len,
                 n_embd=args.n_embd, n_layer=args.n_layer, n_head=args.n_head,
                 dtype=jnp.float32),
        DeepSpeedInferenceConfig(dtype="float32",
                                 max_out_tokens=args.max_seq_len))
    prefix = PrefixCacheConfig(min_hit_tokens=4, min_insert_tokens=4,
                               insert_on="prefill") if args.prefix_cache \
        else None
    sched = ContinuousBatchingScheduler(engine, ServingConfig(
        slots=args.slots, chunk_size=args.chunk_size,
        max_seq_len=args.max_seq_len, prefix_cache=prefix))

    out = sys.stdout

    def emit(obj):
        out.write(json.dumps(obj) + "\n")
        out.flush()                 # every line visible before any SIGKILL

    emit({"ready": True, "pid": os.getpid(), "faults_armed": armed})

    lines: List[str] = []
    eof = threading.Event()

    def reader():
        for line in sys.stdin:
            if line.strip():
                lines.append(line.strip())
        eof.set()

    threading.Thread(target=reader, daemon=True).start()
    tracer = get_tracer()
    handles: Dict[int, object] = {}
    reported: Dict[int, int] = {}
    stop = False
    while not stop or sched.busy:
        while lines:
            req = json.loads(lines.pop(0))
            if req.get("cmd") == "stop":
                stop = True
                continue
            ctx = None
            if req.get("trace_id"):
                # parent propagated a span context: join its trace (enabling
                # lazily keeps the un-traced soak at zero cost)
                if not tracer.enabled:
                    tracer.enable(pid_label=f"subproc{os.getpid()}")
                ctx = SpanContext(str(req["trace_id"]),
                                  str(req.get("parent_span") or ""))
            h = sched.submit(req["prompt"],
                             max_new_tokens=req.get("max_new_tokens"),
                             eos_token_id=req.get("eos_token_id"),
                             seed=req.get("seed", 0), trace_ctx=ctx)
            handles[int(req["id"])] = h
        if eof.is_set():
            stop = True
        if sched.busy:
            sched.step()
        elif not stop:
            time.sleep(0.005)
        for rid, h in list(handles.items()):
            n = len(h.tokens)
            if n != reported.get(rid) or h.done:
                reported[rid] = n
                emit({"id": rid, "tokens": [int(t) for t in h.tokens],
                      "done": bool(h.done), "state": h.state.value,
                      "prefix_hit_tokens": h.prefix_hit_tokens})
                if h.done:
                    del handles[rid]
        if tracer.enabled:
            finished = tracer.drain()
            if finished:
                # every line flushed: spans streamed BEFORE any SIGKILL lands
                emit({"spans": finished})
    emit({"summary": sched.telemetry.snapshot()})
    return 0


class SubprocessReplica:
    """Parent-side handle on a subprocess-hosted replica.

    Spawns the child, streams its JSONL progress on a reader thread, and keeps
    the per-request **token prefixes** — the only state the recovery model is
    allowed to use. ``sigkill()`` is a real ``SIGKILL``: no atexit, no flush,
    no goodbye; whatever was streamed is all the parent has, exactly like a
    preempted TPU host."""

    def __init__(self, repo_root: str, env: Optional[Dict[str, str]] = None,
                 prefix_cache: bool = False, **dims):
        cmd = [sys.executable, "-m", "deepspeed_tpu.inference.serving.subproc"]
        for k, v in dims.items():
            cmd += [f"--{k.replace('_', '-')}", str(v)]
        if prefix_cache:
            cmd += ["--prefix-cache"]
        full_env = dict(os.environ)
        full_env.setdefault("JAX_PLATFORMS", "cpu")
        if env:
            full_env.update(env)
        self.proc = subprocess.Popen(
            cmd, cwd=repo_root, env=full_env, text=True,
            stdin=subprocess.PIPE, stdout=subprocess.PIPE,
            stderr=subprocess.DEVNULL)
        self.ready: Optional[Dict] = None
        self.progress: Dict[int, Dict] = {}      # id -> last streamed line
        # traced submissions: id -> (trace_id, parent_span, t_submit) — what
        # abandon_open_lanes needs to force-close a killed child's lanes
        self._trace_ctx: Dict[int, tuple] = {}
        # child-side finished spans: bounded drop-oldest, same contract as
        # the tracer's own ring — a traced soak must not grow a Python list
        # forever on the parent
        self.spans: "deque" = deque(maxlen=200_000)
        self.spans_dropped = 0
        self.summary: Optional[Dict] = None
        self._lock = threading.Lock()
        self._reader = threading.Thread(target=self._pump, daemon=True)
        self._reader.start()

    def _pump(self):
        for line in self.proc.stdout:
            try:
                obj = json.loads(line)
            except json.JSONDecodeError:
                continue
            with self._lock:
                if "ready" in obj:
                    self.ready = obj
                elif "summary" in obj:
                    self.summary = obj["summary"]
                elif "spans" in obj:
                    overflow = (len(self.spans) + len(obj["spans"])
                                - self.spans.maxlen)
                    if overflow > 0:
                        self.spans_dropped += overflow
                    self.spans.extend(obj["spans"])
                elif "id" in obj:
                    rid = int(obj["id"])
                    self.progress[rid] = obj
                    if obj.get("done"):
                        # completed lanes need no abandon context: without
                        # this, _trace_ctx grows one entry per traced
                        # request for the life of the replica
                        self._trace_ctx.pop(rid, None)

    def wait_ready(self, timeout: float = 120.0) -> Dict:
        t0 = time.monotonic()
        while time.monotonic() - t0 < timeout:
            with self._lock:
                if self.ready is not None:
                    return self.ready
            if self.proc.poll() is not None:
                raise RuntimeError("subprocess replica died during startup")
            time.sleep(0.02)
        raise TimeoutError("subprocess replica never became ready")

    def submit(self, rid: int, prompt, max_new_tokens: int, seed: int = 0,
               eos_token_id: Optional[int] = None,
               trace_id: Optional[str] = None,
               parent_span: Optional[str] = None) -> None:
        req = {"id": int(rid), "prompt": [int(t) for t in prompt],
               "max_new_tokens": int(max_new_tokens), "seed": int(seed),
               "eos_token_id": eos_token_id}
        if trace_id:
            req["trace_id"] = trace_id
            req["parent_span"] = parent_span
            self._trace_ctx[int(rid)] = (trace_id, parent_span,
                                         time.monotonic())
        self.proc.stdin.write(json.dumps(req) + "\n")
        self.proc.stdin.flush()

    def abandon_open_lanes(self, tracer) -> List[int]:
        """Force-close a killed child's in-flight request lanes.

        The child's ``replica_request`` spans were still OPEN when the SIGKILL
        landed — they never committed, so the dead lane would be a hole in the
        trace. The parent knows the span context it handed each request, so it
        commits one ``state=abandoned`` ``replica_request`` span per undone
        traced request — the same force-close the in-process router performs
        at absorb time — and the flight recorder's retention/attribution see
        the abandoned lane joined to the retry attempt by trace id. Returns
        the request ids closed."""
        from ...observability.trace import SpanContext
        now = time.monotonic()
        closed = []
        for rid, (tid, pspan, t0) in list(self._trace_ctx.items()):
            # every entry is consumed: done lanes need no closing span, and a
            # second abandon call must not re-emit spans for lanes this one
            # already force-closed (pop, not del: the reader thread prunes
            # done lanes concurrently)
            self._trace_ctx.pop(rid, None)
            if self.done(rid):
                continue
            ctx = SpanContext(str(tid), str(pspan or ""))
            tracer.record_span(
                "replica_request", ctx, t0, now,
                attrs={"state": "abandoned", "reason": "sigkill",
                       "request_id": rid,
                       "tokens_streamed": len(self.tokens(rid))},
                tid="subproc-abandoned")
            closed.append(rid)
        return closed

    def take_spans(self) -> List[Dict]:
        """Child-side spans streamed so far (drained); ingest into the parent
        tracer to join the cross-process trace."""
        with self._lock:
            out = list(self.spans)
            self.spans.clear()
        return out

    def tokens(self, rid: int) -> List[int]:
        """The streamed prefix — all the parent may know about a request."""
        with self._lock:
            obj = self.progress.get(int(rid))
            return list(obj["tokens"]) if obj else []

    def done(self, rid: int) -> bool:
        with self._lock:
            obj = self.progress.get(int(rid))
            return bool(obj and obj["done"])

    def wait_tokens(self, rid: int, n: int, timeout: float = 180.0
                    ) -> List[int]:
        """Block until request ``rid`` has streamed >= n tokens (or finished)."""
        t0 = time.monotonic()
        while time.monotonic() - t0 < timeout:
            toks = self.tokens(rid)
            if len(toks) >= n or self.done(rid):
                return toks
            if self.proc.poll() is not None:
                return toks          # died: the streamed prefix is the answer
            time.sleep(0.02)
        raise TimeoutError(f"request {rid}: {len(self.tokens(rid))}/{n} "
                           "tokens before timeout")

    def sigkill(self) -> None:
        self.proc.send_signal(signal.SIGKILL)
        self.proc.wait(timeout=30)

    def stop(self) -> int:
        if self.proc.poll() is None:
            try:
                self.proc.stdin.write(json.dumps({"cmd": "stop"}) + "\n")
                self.proc.stdin.flush()
                self.proc.stdin.close()
            except (BrokenPipeError, OSError):
                pass
            self.proc.wait(timeout=60)
        return self.proc.returncode

    @property
    def alive(self) -> bool:
        return self.proc.poll() is None


if __name__ == "__main__":
    sys.exit(child_main())
