"""Subprocess-hosted serving replica: a real process boundary for the router.

The router's in-process :class:`~.router.EngineReplica` simulates death with a
flag; this module hosts the same engine + scheduler stack in a CHILD process so
a replica can die by real ``SIGKILL`` and the recovery model is proven
end-to-end. It exists because the router's whole recovery design is
**prefix-only**: the parent's view of a replica is nothing but the token
prefixes streamed back so far, so after a kill the request continues
bit-identically by re-prefilling ``prompt + streamed_prefix`` anywhere else —
no shared memory, no checkpoint, no device state crosses the process boundary.
:mod:`.host` builds full Router membership (async submit/harvest, heartbeat
watchdog, supervised respawn) on top of this pipe.

Protocol v1 (JSONL over stdin/stdout, every line flushed — the stream must be
truthful at the instant a SIGKILL lands):

- child → ``{"ready": true, "proto": 1, "pid": p, "faults_armed": N,
  "cap": c, "max_prompt_len": m, "slots": s}`` once the engine is built
  (the **versioned hello**: the parent refuses a proto it does not speak —
  :class:`HostProtocolError` — instead of mis-parsing a drifted stream;
  ``faults_armed`` from :func:`~...utils.fault_injection.apply_fault_env`,
  the ``DS_TPU_FAULT_SPEC`` env contract, same as ``deepspeed-serve``);
- child → ``{"hb": n, "t": wall, "busy": b, "running": r, "queued": q,
  "free_slots": f, "occupancy": o, "rss_bytes": m}`` — a heartbeat every
  ``--hb-interval`` from a dedicated child thread (a scheduler step
  legitimately blocks for seconds inside a first-shape XLA compile; a
  main-loop heartbeat would read as a flatline). The parent stamps replica
  liveness from these CHILD messages, not from its own pump: pipe silence IS
  the death signal — SIGSTOP/SIGKILL silence it, while a wedged dispatch
  stays covered by the scheduler's own chunk watchdog, whose failures stream
  as per-request error states;
- parent → ``{"id": i, "prompt": [...], "max_new_tokens": n, "seed": s,
  "eos_token_id": e|null, "deadline_s": d|absent, "trace_id": t|absent,
  "parent_span": p|absent}`` submits a request (``trace_id``/``parent_span``
  propagate the parent's span context: the child's tracer joins its spans to
  that trace, so a subprocess-hosted replica's prefill/decode-chunk spans land
  on the SAME trace id as the frontend's);
- parent → ``{"cmd": "cancel", "id": i}`` cancels an in-flight request;
- child → ``{"id": i, "tokens": [...], "done": bool, "state": "...",
  "finish_reason": "..."}`` after every scheduler step in which request ``i``
  gained tokens (cumulative prefix, not a delta — idempotent under
  lost/duplicated reads);
- child → ``{"spans": [...]}`` whenever traced spans finished since the last
  step (each span dict is ``observability.trace`` wire format; the parent
  ingests them into its own tracer under a per-host lane);
- parent → ``{"cmd": "stop"}`` (or EOF, or SIGTERM) drains and exits 0.

**Malformed-line quarantine**: a garbled line in either direction is counted
and reported (child answers ``{"badline": ..., "error": ...}``; the parent
keeps a bounded sample in ``quarantined``/``quarantined_sample``) — it never
crashes the peer. One bad line loses one message, not the replica.

**Stop escalation ladder** (:meth:`SubprocessReplica.stop`): drain (stop cmd,
``drain_s`` deadline) → ``SIGTERM`` grace (``term_s``; the child handles
SIGTERM as a graceful drain too) → ``SIGKILL``. A wedged child can no longer
hang the caller — the ladder always terminates.

Determinism contract: the child builds its engine with the same fixed init
seed as an in-parent engine of identical dims, so the parent can compute
bit-exact references with its OWN engine — weights never cross the pipe.

Run as ``python -m deepspeed_tpu.inference.serving.subproc --vocab-size ...``
(the parent-side :class:`SubprocessReplica` wraps spawn/stream/kill).
"""

import argparse
import json
import os
import signal
import subprocess
import sys
import threading
import time
from collections import deque
from typing import Dict, List, Optional

#: wire-protocol version carried in the hello line; the parent refuses any
#: other value (HostProtocolError) rather than mis-parse a drifted stream
PROTO_VERSION = 1


class HostProtocolError(RuntimeError):
    """The child spoke a pipe protocol the parent does not (hello missing a
    ``proto`` field, or carrying an unsupported version)."""


def _rss_bytes() -> int:
    try:
        import resource
        return resource.getrusage(resource.RUSAGE_SELF).ru_maxrss * 1024
    except Exception:
        return 0


def _cache_gossip(sched) -> dict:
    """Additive heartbeat payload: the child cache's KV economy + the digest
    ladder of its resident prefixes, so the parent's prefix-aware dispatch can
    score this replica without a round trip. ``{}`` when the cache is off
    (field absent keeps pre-PR-19 heartbeats byte-compatible)."""
    pc = sched.prefix_cache
    if pc is None:
        return {}
    try:
        t = sched.telemetry
        s = pc.stats()
        return {"cache": {
            "hits": int(t.prefix_hits), "misses": int(t.prefix_misses),
            "hit_tokens": int(t.prefix_hit_tokens),
            "cached_bytes": int(s["cached_bytes"]),
            "spilled_bytes": int(s["spilled_bytes"]),
            "spills": int(s["spills"]),
            "promotions": int(s["promotions"]),
            "entries": int(s["entries"]),
            "host_entries": int(s["host_entries"]),
            "digests": pc.digest_report(),
        }}
    except Exception:
        return {}                   # gossip is best-effort; hb must not die


def child_main(argv=None) -> int:
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    ap = argparse.ArgumentParser(prog="serving.subproc")
    ap.add_argument("--family", default="gpt2", choices=("gpt2", "llama"))
    ap.add_argument("--vocab-size", type=int, default=96)
    ap.add_argument("--max-seq-len", type=int, default=64)
    ap.add_argument("--n-embd", type=int, default=32)
    ap.add_argument("--n-layer", type=int, default=2)
    ap.add_argument("--n-head", type=int, default=4)
    ap.add_argument("--slots", type=int, default=2)
    ap.add_argument("--chunk-size", type=int, default=2)
    ap.add_argument("--hb-interval", type=float, default=0.05)
    ap.add_argument("--prefix-cache", action="store_true")
    # per-child serving knobs (HostConfig ships these across the spawn —
    # PR 16: parent flags now DO cross the pipe instead of being refused)
    ap.add_argument("--prefix-cache-mb", type=float, default=None)
    ap.add_argument("--prefix-tier-mb", type=float, default=None)
    ap.add_argument("--prefix-min-hit", type=int, default=4)
    ap.add_argument("--kv-pool", default="paged", choices=("paged", "slots"))
    ap.add_argument("--kv-page-size", type=int, default=None)
    ap.add_argument("--chunk-deadline", type=float, default=None)
    # socket transport (net.py): serve protocol v1 over framed TCP instead of
    # the stdio pipe — --listen "host:port"/"port" (0 = ephemeral, announced
    # as a {"listening": port} bootstrap line on stdout) or --connect
    # "host:port" (the child runs the dial/backoff loop)
    ap.add_argument("--serve-socket", action="store_true")
    ap.add_argument("--listen", default=None)
    ap.add_argument("--connect", default=None)
    args = ap.parse_args(argv)

    # protocol v1 state shared with the transport: the socket IO (when
    # enabled) must exist BEFORE the heavy jax import so the bootstrap line
    # lands fast and early frames buffer while the engine builds
    lines: List[str] = []
    eof = threading.Event()
    term = threading.Event()        # SIGTERM = graceful drain (ladder rung)
    signal.signal(signal.SIGTERM, lambda signum, frame: term.set())
    sock_io = None
    if args.serve_socket:
        from .net import ChildSocketIO
        sock_io = ChildSocketIO(lines=lines, term=term, listen=args.listen,
                                connect=args.connect)

    import jax.numpy as jnp

    from ...observability.trace import SpanContext, get_tracer
    from ...utils.fault_injection import apply_fault_env
    from ..config import DeepSpeedInferenceConfig
    from ..engine import InferenceEngine
    from ...models.causal_lm import gpt2_cfg, llama_cfg
    from .prefix_cache import PrefixCacheConfig
    from .scheduler import ContinuousBatchingScheduler, ServingConfig

    armed = apply_fault_env()       # DS_TPU_FAULT_SPEC: seeded child schedule
    family = {"gpt2": gpt2_cfg, "llama": llama_cfg}[args.family]
    engine = InferenceEngine(
        family(vocab_size=args.vocab_size, max_seq_len=args.max_seq_len,
               n_embd=args.n_embd, n_layer=args.n_layer, n_head=args.n_head,
               dtype=jnp.float32),
        DeepSpeedInferenceConfig(dtype="float32",
                                 max_out_tokens=args.max_seq_len))
    prefix = None
    if args.prefix_cache:
        prefix = PrefixCacheConfig(
            min_hit_tokens=args.prefix_min_hit,
            min_insert_tokens=args.prefix_min_hit, insert_on="prefill")
        if args.prefix_cache_mb is not None:
            prefix.max_bytes = int(args.prefix_cache_mb * 1024 * 1024)
        if args.prefix_tier_mb is not None:
            prefix.host_tier_bytes = int(args.prefix_tier_mb * 1024 * 1024)
    page_kw = ({"kv_page_size": args.kv_page_size}
               if args.kv_page_size is not None else {})
    sched = ContinuousBatchingScheduler(engine, ServingConfig(
        slots=args.slots, chunk_size=args.chunk_size,
        max_seq_len=args.max_seq_len, prefix_cache=prefix,
        kv_pool=args.kv_pool, chunk_deadline_s=args.chunk_deadline,
        **page_kw))

    out = sys.stdout
    emit_lock = threading.Lock()

    def emit(obj):
        if sock_io is not None:     # framed TCP transport (net.py)
            with emit_lock:
                sock_io.emit(obj)
            return
        with emit_lock:             # hb thread + main loop share the pipe
            out.write(json.dumps(obj) + "\n")
            out.flush()             # every line visible before any SIGKILL

    emit({"ready": True, "proto": PROTO_VERSION, "pid": os.getpid(),
          "faults_armed": armed, "cap": sched.cap,
          "max_prompt_len": sched.executor.max_prompt_len,
          "slots": args.slots})

    # heartbeat THREAD, not a main-loop tick: a scheduler step legitimately
    # blocks for seconds inside a first-shape XLA compile or a long chunk, and
    # main-loop heartbeats would read as a flatline to the parent's
    # pipe-silence watchdog (the in-process router's post-step re-stamp has no
    # equivalent across a pipe). The thread proves PROCESS liveness — SIGSTOP/
    # SIGKILL silence it — while a wedged dispatch stays covered by the
    # scheduler's own chunk watchdog, whose failures stream as request errors.
    hb_stop = threading.Event()

    def hb_loop():
        seq = 0
        while not hb_stop.is_set():
            seq += 1
            try:
                pool = sched.executor.pool
                emit({"hb": seq, "t": time.time(), "busy": bool(sched.busy),
                      "running": len(sched.active_requests),
                      "queued": sched.queue_depth,
                      "free_slots": int(pool.free_slots),
                      "occupancy": float(pool.occupancy),
                      "rss_bytes": _rss_bytes(),
                      # per-child cache economics for the parent's /statusz
                      # (None = cache disabled in this child)
                      "prefix_hit_rate": (float(sched.prefix_hit_rate)
                                          if sched.prefix_cache is not None
                                          else None),
                      # additive v1 field (PR 19): cache gossip for
                      # prefix-aware routing + the fleet KV-economy rollup.
                      # Old parents ignore unknown hb fields; absent on
                      # cache-less children
                      **_cache_gossip(sched)})
            except (BrokenPipeError, ValueError, OSError):
                return              # parent went away: nothing to report to
            hb_stop.wait(args.hb_interval)

    threading.Thread(target=hb_loop, daemon=True).start()

    if sock_io is None:
        def reader():
            for line in sys.stdin:
                if line.strip():
                    lines.append(line.strip())
            eof.set()

        threading.Thread(target=reader, daemon=True).start()
    tracer = get_tracer()
    handles: Dict[int, object] = {}
    reported: Dict[int, int] = {}
    stop = False
    while not stop or sched.busy:
        while lines:
            raw = lines.pop(0)
            try:
                req = json.loads(raw)
            except (ValueError, TypeError) as e:
                # malformed-line quarantine: one bad line loses one message,
                # never the replica — report it and keep serving
                emit({"badline": raw[:200], "error": type(e).__name__})
                continue
            if req.get("cmd") == "stop":
                stop = True
                continue
            if req.get("cmd") == "cancel":
                h = handles.get(int(req.get("id", -1)))
                if h is not None:
                    h.cancel()
                continue
            if req.get("cmd") == "cancel_all":
                # a fresh socket connection superseded a severed one: the
                # parent evicted the in-flight work with prefixes, so free
                # its slots here instead of leaking them to orphans
                for h in list(handles.values()):
                    h.cancel()
                continue
            ctx = None
            if req.get("trace_id"):
                # parent propagated a span context: join its trace (enabling
                # lazily keeps the un-traced soak at zero cost)
                if not tracer.enabled:
                    tracer.enable(pid_label=f"subproc{os.getpid()}")
                ctx = SpanContext(str(req["trace_id"]),
                                  str(req.get("parent_span") or ""))
            try:
                h = sched.submit(req["prompt"],
                                 max_new_tokens=req.get("max_new_tokens"),
                                 eos_token_id=req.get("eos_token_id"),
                                 deadline_s=req.get("deadline_s"),
                                 seed=req.get("seed", 0), trace_ctx=ctx)
            except Exception as e:
                # an inadmissible request fails alone (the parent pre-checks
                # admission, so this is belt-and-braces, not a normal path)
                # — and it fails TERMINALLY: a quarantine report alone would
                # leave the parent's handle open forever (no timeout, no
                # retry); a per-id error state routes it through the router's
                # standard replica-failure retry instead
                emit({"badline": raw[:200], "error": f"{type(e).__name__}: "
                                                     f"{e}"[:200]})
                if "id" in req:
                    emit({"id": int(req["id"]), "tokens": [], "done": True,
                          "state": "cancelled", "finish_reason": "error",
                          "prefix_hit_tokens": 0})
                continue
            handles[int(req["id"])] = h
        if eof.is_set() or term.is_set():
            stop = True
        if sched.busy:
            sched.step()
        elif not stop:
            time.sleep(0.005)
        for rid, h in list(handles.items()):
            n = len(h.tokens)
            if n != reported.get(rid) or h.done:
                reported[rid] = n
                emit({"id": rid, "tokens": [int(t) for t in h.tokens],
                      "done": bool(h.done), "state": h.state.value,
                      "finish_reason": h.finish_reason,
                      "prefix_hit_tokens": h.prefix_hit_tokens})
                if h.done:
                    del handles[rid]
        if tracer.enabled:
            finished = tracer.drain()
            if finished:
                # every line flushed: spans streamed BEFORE any SIGKILL lands
                emit({"spans": finished})
    hb_stop.set()
    emit({"summary": sched.telemetry.snapshot()})
    if sock_io is not None:
        sock_io.close()
    return 0


class SubprocessReplica:
    """Parent-side handle on a subprocess-hosted replica.

    Spawns the child, streams its JSONL progress on a reader thread, and keeps
    the per-request **token prefixes** — the only state the recovery model is
    allowed to use. ``sigkill()`` is a real ``SIGKILL``: no atexit, no flush,
    no goodbye; whatever was streamed is all the parent has, exactly like a
    preempted TPU host. ``stop()`` is the escalation ladder: drain deadline →
    SIGTERM grace → SIGKILL (a wedged child cannot hang the caller)."""

    def __init__(self, repo_root: str, env: Optional[Dict[str, str]] = None,
                 prefix_cache: bool = False, cmd: Optional[List[str]] = None,
                 **dims):
        if cmd is None:
            cmd = [sys.executable, "-m",
                   "deepspeed_tpu.inference.serving.subproc"]
            for k, v in dims.items():
                cmd += [f"--{k.replace('_', '-')}", str(v)]
            if prefix_cache:
                cmd += ["--prefix-cache"]
        full_env = dict(os.environ)
        full_env.setdefault("JAX_PLATFORMS", "cpu")
        try:
            # the determinism contract is self-enforcing: the child must draw
            # the same init bits as the parent's reference engine, and
            # jax_threefry_partitionable changes them — propagate the
            # parent's setting (programmatic config does not inherit)
            import jax
            full_env.setdefault(
                "JAX_THREEFRY_PARTITIONABLE",
                "1" if jax.config.jax_threefry_partitionable else "0")
        except Exception:
            pass                    # parent never imported jax: child default
        if env:
            full_env.update(env)
        self.proc = subprocess.Popen(
            cmd, cwd=repo_root, env=full_env, text=True,
            stdin=subprocess.PIPE, stdout=subprocess.PIPE,
            stderr=subprocess.DEVNULL)
        self.ready: Optional[Dict] = None
        self.hb: Optional[Dict] = None           # last heartbeat line
        self.last_line_at: Optional[float] = None  # monotonic stamp of ANY
        #   well-formed child line — the host's pipe-liveness signal
        self.progress: Dict[int, Dict] = {}      # id -> last streamed line
        # malformed-line quarantine (both directions): counted + sampled,
        # never fatal — one bad line loses one message, not the replica
        self.quarantined = 0                     # child → parent garbage
        self.quarantined_sample: Optional[str] = None
        self.child_quarantined = 0               # child-reported bad input
        self.escalations = 0                     # stop-ladder rungs climbed
        # traced submissions: id -> (trace_id, parent_span, t_submit) — what
        # abandon_open_lanes needs to force-close a killed child's lanes
        self._trace_ctx: Dict[int, tuple] = {}
        # child-side finished spans: bounded drop-oldest, same contract as
        # the tracer's own ring — a traced soak must not grow a Python list
        # forever on the parent
        self.spans: "deque" = deque(maxlen=200_000)
        self.spans_dropped = 0
        self.summary: Optional[Dict] = None
        self._lock = threading.Lock()
        self._reader = threading.Thread(target=self._pump, daemon=True)
        self._reader.start()

    def _pump(self):
        for line in self.proc.stdout:
            try:
                obj = json.loads(line)
            except json.JSONDecodeError:
                with self._lock:
                    self.quarantined += 1
                    self.quarantined_sample = line[:200]
                continue
            with self._lock:
                self.last_line_at = time.monotonic()
                if "ready" in obj:
                    self.ready = obj
                elif "hb" in obj:
                    obj["_rx_t"] = time.time()   # pipe-lag measurement anchor
                    self.hb = obj
                elif "badline" in obj:
                    self.child_quarantined += 1
                elif "summary" in obj:
                    self.summary = obj["summary"]
                elif "spans" in obj:
                    overflow = (len(self.spans) + len(obj["spans"])
                                - self.spans.maxlen)
                    if overflow > 0:
                        self.spans_dropped += overflow
                    self.spans.extend(obj["spans"])
                elif "id" in obj:
                    rid = int(obj["id"])
                    self.progress[rid] = obj
                    if obj.get("done"):
                        # completed lanes need no abandon context: without
                        # this, _trace_ctx grows one entry per traced
                        # request for the life of the replica
                        self._trace_ctx.pop(rid, None)

    def wait_ready(self, timeout: float = 120.0) -> Dict:
        t0 = time.monotonic()
        while time.monotonic() - t0 < timeout:
            with self._lock:
                if self.ready is not None:
                    if self.ready.get("proto") != PROTO_VERSION:
                        raise HostProtocolError(
                            f"child hello carries proto="
                            f"{self.ready.get('proto')!r}; this parent "
                            f"speaks proto={PROTO_VERSION}")
                    return self.ready
            if self.proc.poll() is not None:
                raise RuntimeError("subprocess replica died during startup")
            time.sleep(0.02)
        raise TimeoutError("subprocess replica never became ready")

    def submit(self, rid: int, prompt, max_new_tokens: int, seed: int = 0,
               eos_token_id: Optional[int] = None,
               deadline_s: Optional[float] = None,
               trace_id: Optional[str] = None,
               parent_span: Optional[str] = None) -> None:
        req = {"id": int(rid), "prompt": [int(t) for t in prompt],
               "max_new_tokens": int(max_new_tokens), "seed": int(seed),
               "eos_token_id": eos_token_id}
        if deadline_s is not None:
            req["deadline_s"] = float(deadline_s)
        if trace_id:
            req["trace_id"] = trace_id
            req["parent_span"] = parent_span
            self._trace_ctx[int(rid)] = (trace_id, parent_span,
                                         time.monotonic())
        self.proc.stdin.write(json.dumps(req) + "\n")
        self.proc.stdin.flush()

    def cancel(self, rid: int) -> None:
        """Ask the child to cancel request ``rid`` (best-effort: a dead pipe
        is already the stronger cancellation)."""
        try:
            self.proc.stdin.write(json.dumps({"cmd": "cancel",
                                              "id": int(rid)}) + "\n")
            self.proc.stdin.flush()
        except (BrokenPipeError, OSError):
            pass

    def abandon_open_lanes(self, tracer) -> List[int]:
        """Force-close a killed child's in-flight request lanes.

        The child's ``replica_request`` spans were still OPEN when the SIGKILL
        landed — they never committed, so the dead lane would be a hole in the
        trace. The parent knows the span context it handed each request, so it
        commits one ``state=abandoned`` ``replica_request`` span per undone
        traced request — the same force-close the in-process router performs
        at absorb time — and the flight recorder's retention/attribution see
        the abandoned lane joined to the retry attempt by trace id. Returns
        the request ids closed."""
        from ...observability.trace import SpanContext
        now = time.monotonic()
        closed = []
        for rid, (tid, pspan, t0) in list(self._trace_ctx.items()):
            # every entry is consumed: done lanes need no closing span, and a
            # second abandon call must not re-emit spans for lanes this one
            # already force-closed (pop, not del: the reader thread prunes
            # done lanes concurrently)
            self._trace_ctx.pop(rid, None)
            if self.done(rid):
                continue
            ctx = SpanContext(str(tid), str(pspan or ""))
            tracer.record_span(
                "replica_request", ctx, t0, now,
                attrs={"state": "abandoned", "reason": "sigkill",
                       "request_id": rid,
                       "tokens_streamed": len(self.tokens(rid))},
                tid="subproc-abandoned")
            closed.append(rid)
        return closed

    def take_spans(self) -> List[Dict]:
        """Child-side spans streamed so far (drained); ingest into the parent
        tracer to join the cross-process trace."""
        with self._lock:
            out = list(self.spans)
            self.spans.clear()
        return out

    def tokens(self, rid: int) -> List[int]:
        """The streamed prefix — all the parent may know about a request."""
        with self._lock:
            obj = self.progress.get(int(rid))
            return list(obj["tokens"]) if obj else []

    def done(self, rid: int) -> bool:
        with self._lock:
            obj = self.progress.get(int(rid))
            return bool(obj and obj["done"])

    def wait_tokens(self, rid: int, n: int, timeout: float = 180.0
                    ) -> List[int]:
        """Block until request ``rid`` has streamed >= n tokens (or finished)."""
        t0 = time.monotonic()
        while time.monotonic() - t0 < timeout:
            toks = self.tokens(rid)
            if len(toks) >= n or self.done(rid):
                return toks
            if self.proc.poll() is not None:
                return toks          # died: the streamed prefix is the answer
            time.sleep(0.02)
        raise TimeoutError(f"request {rid}: {len(self.tokens(rid))}/{n} "
                           "tokens before timeout")

    def sigkill(self) -> None:
        self.proc.send_signal(signal.SIGKILL)
        self.proc.wait(timeout=30)

    def stop(self, drain_s: float = 10.0, term_s: float = 5.0) -> int:
        """Stop escalation ladder: drain (stop cmd, ``drain_s`` deadline) →
        SIGTERM grace (``term_s``) → SIGKILL. Always returns — a wedged child
        (stalled, stopped, or ignoring its stdin) can no longer hang the
        caller on an unbounded ``wait``. ``escalations`` counts the rungs
        climbed past the graceful drain."""
        if self.proc.poll() is None:
            try:
                self.proc.stdin.write(json.dumps({"cmd": "stop"}) + "\n")
                self.proc.stdin.flush()
                self.proc.stdin.close()
            except (BrokenPipeError, OSError):
                pass
            try:
                self.proc.wait(timeout=drain_s)
            except subprocess.TimeoutExpired:
                self.escalations += 1
                try:
                    self.proc.send_signal(signal.SIGTERM)
                except ProcessLookupError:
                    pass
                try:
                    self.proc.wait(timeout=term_s)
                except subprocess.TimeoutExpired:
                    # SIGKILL works even on a SIGSTOPped child (SIGTERM does
                    # not deliver until SIGCONT) — the ladder's backstop
                    self.escalations += 1
                    try:
                        self.proc.send_signal(signal.SIGKILL)
                    except ProcessLookupError:
                        pass
                    self.proc.wait(timeout=30)
        return self.proc.returncode

    @property
    def alive(self) -> bool:
        return self.proc.poll() is None


if __name__ == "__main__":
    sys.exit(child_main())
