"""Slot-indexed KV cache pool.

One fixed allocation of ``init_cache(cfg, slots, cap)`` per pool; requests borrow a
slot (row) for their lifetime. Every pool mutation — scatter-in of a prefill's
batch-1 cache, prefix-slab restore on a cache hit, zero-fill on release — runs as
a donated jitted update, so the pool's HBM footprint is constant: jax 0.4.37
honours ``donate_argnums`` on CPU too, so there are no backend guards (guarding
donation behind backend checks cost 1500x on pool scatters in an earlier revision
of this codebase). ``gather_prefix`` is the one non-donating copy-out: it hands
the prefix cache (and, next, disaggregated prefill) slabs whose lifetime is
independent of the pool's.

Per-slot sequence lengths are scheduler state (host numpy, passed into each decode
chunk); the pool owns only the device buffers and the free list.
"""

from typing import Any, Dict, List, Optional

import jax
import numpy as np

from ...models.causal_lm import init_cache


class SlotKVPool:
    """Fixed ``slots × cap`` KV buffers with acquire/release slot recycling."""

    def __init__(self, model_config, slots: int, cap: int, dtype=None):
        if slots < 1 or cap < 2:
            raise ValueError(f"need slots >= 1 and cap >= 2, got {slots}, {cap}")
        self.slots = int(slots)
        self.cap = int(cap)
        self.caches = init_cache(model_config, self.slots, self.cap, dtype=dtype)
        self._free: List[int] = list(range(self.slots))
        # prefix-cache slab movers, one compile per padded row count R (row
        # counts are power-of-two prompt buckets, so the key set is tiny)
        self._gather_fns: Dict[int, Any] = {}
        self._restore_fns: Dict[int, Any] = {}

        def scatter(caches, one, slot):
            return [{"k": c["k"].at[slot].set(o["k"][0]),
                     "v": c["v"].at[slot].set(o["v"][0])}
                    for c, o in zip(caches, one)]

        def zero_fill(caches, slot):
            return [{"k": c["k"].at[slot].set(0.0),
                     "v": c["v"].at[slot].set(0.0)} for c in caches]

        # pool buffers donated unconditionally: the old ones are always dead after
        # the update (the prefill's batch-1 cache is NOT donatable — its (1, ...)
        # buffers cannot alias any (slots, ...) output)
        self._scatter_fn = jax.jit(scatter, donate_argnums=(0,))
        self._zero_fn = jax.jit(zero_fill, donate_argnums=(0,))

    # ------------------------------------------------------------ slot lifecycle
    def acquire(self) -> Optional[int]:
        """Borrow a free slot index, or ``None`` when the pool is full."""
        return self._free.pop(0) if self._free else None

    def release(self, slot: int) -> None:
        """Zero-fill ``slot`` and return it to the free list — a recycled slot must
        never leak the previous request's KV into a new prefill/decode."""
        if slot in self._free:
            raise ValueError(f"slot {slot} is already free")
        self.caches = self._zero_fn(self.caches, np.int32(slot))
        self._free.append(slot)

    def scatter_prefill(self, slot: int, one_caches: List[Dict[str, Any]]) -> None:
        """Write a prefill's batch-1 per-layer cache into row ``slot``."""
        self.caches = self._scatter_fn(self.caches, one_caches, np.int32(slot))

    # --------------------------------------------------------- prefix-cache I/O
    def gather_prefix(self, slot: int, rows: int) -> List[Dict[str, Any]]:
        """Copy rows ``[0, rows)`` of ``slot`` out as an independent KV slab
        (per-layer ``{"k": (hk, rows, d), "v": ...}``) — the prefix-cache
        insert path, and the slab disaggregated prefill will ship to decode
        replicas. NOT donated: the pool keeps serving; the slab's lifetime is
        the trie's, so pool rebuilds after faults never invalidate it."""
        R = int(rows)
        if not 0 < R <= self.cap:
            raise ValueError(f"rows must be in [1, cap={self.cap}], got {R}")
        fn = self._gather_fns.get(R)
        if fn is None:
            def gather(caches, slot):
                out = []
                for c in caches:
                    _, hk, _, d = c["k"].shape
                    out.append({
                        "k": jax.lax.dynamic_slice(
                            c["k"], (slot, 0, 0, 0), (1, hk, R, d))[0],
                        "v": jax.lax.dynamic_slice(
                            c["v"], (slot, 0, 0, 0), (1, hk, R, d))[0]})
                return out
            fn = self._gather_fns[R] = jax.jit(gather)
        return fn(self.caches, np.int32(slot))

    def slab_nbytes(self, rows: int) -> int:
        """Host-side size of a ``rows``-row slab — lets callers apply byte
        budgets BEFORE paying the device gather."""
        total = 0
        for c in self.caches:
            _, hk, _, d = c["k"].shape
            total += 2 * hk * int(rows) * d * c["k"].dtype.itemsize
        return total

    def restore_prefix(self, slot: int, slab: List[Dict[str, Any]]) -> None:
        """Write a gathered KV slab into rows ``[0, slab_rows)`` of ``slot`` —
        the donated scatter on the cache-hit path (``scatter_prefill``'s
        prefix-restore sibling). The pool buffers are donated (the old ones are
        dead after the update); the slab is NOT (it stays resident in the
        trie for the next hit)."""
        R = int(slab[0]["k"].shape[1])
        if R > self.cap:
            raise ValueError(f"slab rows {R} exceed pool cap {self.cap}")
        fn = self._restore_fns.get(R)
        if fn is None:
            def restore(caches, slab, slot):
                out = []
                for c, s in zip(caches, slab):
                    out.append({
                        "k": jax.lax.dynamic_update_slice(
                            c["k"], s["k"][None].astype(c["k"].dtype),
                            (slot, 0, 0, 0)),
                        "v": jax.lax.dynamic_update_slice(
                            c["v"], s["v"][None].astype(c["v"].dtype),
                            (slot, 0, 0, 0))})
                return out
            fn = self._restore_fns[R] = jax.jit(restore, donate_argnums=(0,))
        self.caches = fn(self.caches, slab, np.int32(slot))

    # ------------------------------------------------------------------ metrics
    @property
    def free_slots(self) -> int:
        return len(self._free)

    @property
    def occupancy(self) -> float:
        return 1.0 - len(self._free) / self.slots
