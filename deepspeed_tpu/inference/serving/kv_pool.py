"""Slot-indexed KV cache pool.

One fixed allocation of ``init_cache(cfg, slots, cap)`` per pool; requests borrow a
slot (row) for their lifetime. All three mutations — scatter-in of a prefill's
batch-1 cache, zero-fill on release — run as donated jitted updates, so the pool's
HBM footprint is constant: jax 0.4.37 honours ``donate_argnums`` on CPU too, so
there are no backend guards (guarding donation behind backend checks cost 1500x on
pool scatters in an earlier revision of this codebase).

Per-slot sequence lengths are scheduler state (host numpy, passed into each decode
chunk); the pool owns only the device buffers and the free list.
"""

from typing import Any, Dict, List, Optional

import jax
import numpy as np

from ...models.causal_lm import init_cache


class SlotKVPool:
    """Fixed ``slots × cap`` KV buffers with acquire/release slot recycling."""

    def __init__(self, model_config, slots: int, cap: int, dtype=None):
        if slots < 1 or cap < 2:
            raise ValueError(f"need slots >= 1 and cap >= 2, got {slots}, {cap}")
        self.slots = int(slots)
        self.cap = int(cap)
        self.caches = init_cache(model_config, self.slots, self.cap, dtype=dtype)
        self._free: List[int] = list(range(self.slots))

        def scatter(caches, one, slot):
            return [{"k": c["k"].at[slot].set(o["k"][0]),
                     "v": c["v"].at[slot].set(o["v"][0])}
                    for c, o in zip(caches, one)]

        def zero_fill(caches, slot):
            return [{"k": c["k"].at[slot].set(0.0),
                     "v": c["v"].at[slot].set(0.0)} for c in caches]

        # pool buffers donated unconditionally: the old ones are always dead after
        # the update (the prefill's batch-1 cache is NOT donatable — its (1, ...)
        # buffers cannot alias any (slots, ...) output)
        self._scatter_fn = jax.jit(scatter, donate_argnums=(0,))
        self._zero_fn = jax.jit(zero_fill, donate_argnums=(0,))

    # ------------------------------------------------------------ slot lifecycle
    def acquire(self) -> Optional[int]:
        """Borrow a free slot index, or ``None`` when the pool is full."""
        return self._free.pop(0) if self._free else None

    def release(self, slot: int) -> None:
        """Zero-fill ``slot`` and return it to the free list — a recycled slot must
        never leak the previous request's KV into a new prefill/decode."""
        if slot in self._free:
            raise ValueError(f"slot {slot} is already free")
        self.caches = self._zero_fn(self.caches, np.int32(slot))
        self._free.append(slot)

    def scatter_prefill(self, slot: int, one_caches: List[Dict[str, Any]]) -> None:
        """Write a prefill's batch-1 per-layer cache into row ``slot``."""
        self.caches = self._scatter_fn(self.caches, one_caches, np.int32(slot))

    # ------------------------------------------------------------------ metrics
    @property
    def free_slots(self) -> int:
        return len(self._free)

    @property
    def occupancy(self) -> float:
        return 1.0 - len(self._free) / self.slots
