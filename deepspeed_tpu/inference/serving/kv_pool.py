"""KV cache pools: slot-rows (legacy) and the paged page-table pool.

:class:`SlotKVPool` — one fixed allocation of ``init_cache(cfg, slots, cap)``
per pool; requests borrow a slot (row) for their lifetime, so every slot
reserves its worst-case ``cap`` KV up front. Every pool mutation — scatter-in
of a prefill's batch-1 cache, prefix-slab restore on a cache hit, zero-fill on
release — runs as a donated jitted update, so the pool's HBM footprint is
constant: jax 0.4.37 honours ``donate_argnums`` on CPU too, so there are no
backend guards (guarding donation behind backend checks cost 1500x on pool
scatters in an earlier revision of this codebase).

:class:`PagedKVPool` — the default since PR 13: one global pool of fixed-size
KV **pages** per layer (``{"k": (P, hk, page, d), ...}``) behind a static-shape
per-slot page table. A slot allocates only the pages its ``prompt + max_new``
needs (page-granular admission: occupancy tracks requested tokens, not the
pow2-bucketed worst case), pages are refcounted so the prefix cache can
**share** a prompt's pages zero-copy (a hit binds page indices into the new
slot's table — no slab gather, no restore scatter; the first partially-covered
page is copy-on-write), and a page is the shipment unit disaggregated prefill
will serialize. Released pages are NOT zero-filled: every row below a slot's
``cache_len`` is freshly written (prefill/suffix/decode) or a verbatim shared
prefix row, and attention masks everything at or beyond ``cache_len`` — the
leak-safety argument the slot pool bought with a zero scatter is structural
here, and release becomes O(pages) host bookkeeping.

``gather_prefix``/``restore_prefix`` survive on BOTH pools as the dense-slab
serialization API (page-granular underneath on the paged pool) — the wire
format disaggregated prefill ships between replicas.

Per-slot sequence lengths are scheduler state (host numpy, passed into each
decode chunk); the pool owns the device buffers, the free lists and (paged)
the page table + refcounts.
"""

import functools
import math
from typing import Any, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ...models.causal_lm import init_cache


# Slot-pool movers at MODULE level (shape-keyed jit singletons), same reason
# as the paged movers below: a pool is rebuilt on every reset_pool (failure
# recovery) and per serving lane, and per-instance jitted closures re-paid
# their XLA compile each time.
@functools.lru_cache(maxsize=None)
def _slot_scatter_jit():
    def scatter(caches, one, slot):
        return [{"k": c["k"].at[slot].set(o["k"][0]),
                 "v": c["v"].at[slot].set(o["v"][0])}
                for c, o in zip(caches, one)]

    return jax.jit(scatter, donate_argnums=(0,))


@functools.lru_cache(maxsize=None)
def _slot_zero_jit():
    def zero_fill(caches, slot):
        return [{"k": c["k"].at[slot].set(0.0),
                 "v": c["v"].at[slot].set(0.0)} for c in caches]

    return jax.jit(zero_fill, donate_argnums=(0,))


@functools.lru_cache(maxsize=None)
def _slot_gather_jit(R: int):
    def gather(caches, slot):
        out = []
        for c in caches:
            _, hk, _, d = c["k"].shape
            out.append({
                "k": jax.lax.dynamic_slice(
                    c["k"], (slot, 0, 0, 0), (1, hk, R, d))[0],
                "v": jax.lax.dynamic_slice(
                    c["v"], (slot, 0, 0, 0), (1, hk, R, d))[0]})
        return out

    return jax.jit(gather)


@functools.lru_cache(maxsize=None)
def _slot_restore_jit():
    def restore(caches, slab, slot):
        out = []
        for c, s in zip(caches, slab):
            out.append({
                "k": jax.lax.dynamic_update_slice(
                    c["k"], s["k"][None].astype(c["k"].dtype),
                    (slot, 0, 0, 0)),
                "v": jax.lax.dynamic_update_slice(
                    c["v"], s["v"][None].astype(c["v"].dtype),
                    (slot, 0, 0, 0))})
        return out

    return jax.jit(restore, donate_argnums=(0,))


class SlotKVPool:
    """Fixed ``slots × cap`` KV buffers with acquire/release slot recycling."""

    def __init__(self, model_config, slots: int, cap: int, dtype=None):
        if slots < 1 or cap < 2:
            raise ValueError(f"need slots >= 1 and cap >= 2, got {slots}, {cap}")
        self.slots = int(slots)
        self.cap = int(cap)
        self.caches = init_cache(model_config, self.slots, self.cap, dtype=dtype)
        self._free: List[int] = list(range(self.slots))
        # pool buffers donated unconditionally: the old ones are always dead after
        # the update (the prefill's batch-1 cache is NOT donatable — its (1, ...)
        # buffers cannot alias any (slots, ...) output)
        self._scatter_fn = _slot_scatter_jit()
        self._zero_fn = _slot_zero_jit()

    # ------------------------------------------------------------ slot lifecycle
    def can_admit(self, tokens: Optional[int] = None, matched: int = 0) -> bool:
        """Shared admission protocol with :class:`PagedKVPool` — here a slot
        IS the reservation, so only slot availability matters."""
        return bool(self._free)

    def acquire(self, tokens: Optional[int] = None, prefix_pages=None,
                matched: int = 0) -> Optional[int]:
        """Borrow a free slot index, or ``None`` when the pool is full.
        ``tokens``/``prefix_pages``/``matched`` are accepted for protocol
        parity with :class:`PagedKVPool` and ignored (a slot reserves ``cap``
        regardless)."""
        return self._free.pop(0) if self._free else None

    def release(self, slot: int) -> None:
        """Zero-fill ``slot`` and return it to the free list — a recycled slot must
        never leak the previous request's KV into a new prefill/decode."""
        if slot in self._free:
            raise ValueError(f"slot {slot} is already free")
        self.caches = self._zero_fn(self.caches, np.int32(slot))
        self._free.append(slot)

    def scatter_prefill(self, slot: int, one_caches: List[Dict[str, Any]]) -> None:
        """Write a prefill's batch-1 per-layer cache into row ``slot``."""
        self.caches = self._scatter_fn(self.caches, one_caches, np.int32(slot))

    # --------------------------------------------------------- prefix-cache I/O
    def gather_prefix(self, slot: int, rows: int) -> List[Dict[str, Any]]:
        """Copy rows ``[0, rows)`` of ``slot`` out as an independent KV slab
        (per-layer ``{"k": (hk, rows, d), "v": ...}``) — the prefix-cache
        insert path, and the slab disaggregated prefill will ship to decode
        replicas. NOT donated: the pool keeps serving; the slab's lifetime is
        the trie's, so pool rebuilds after faults never invalidate it."""
        R = int(rows)
        if not 0 < R <= self.cap:
            raise ValueError(f"rows must be in [1, cap={self.cap}], got {R}")
        return _slot_gather_jit(R)(self.caches, np.int32(slot))

    def slab_nbytes(self, rows: int) -> int:
        """Host-side size of a ``rows``-row slab — lets callers apply byte
        budgets BEFORE paying the device gather."""
        total = 0
        for c in self.caches:
            _, hk, _, d = c["k"].shape
            total += 2 * hk * int(rows) * d * c["k"].dtype.itemsize
        return total

    def restore_prefix(self, slot: int, slab: List[Dict[str, Any]]) -> None:
        """Write a gathered KV slab into rows ``[0, slab_rows)`` of ``slot`` —
        the donated scatter on the cache-hit path (``scatter_prefill``'s
        prefix-restore sibling). The pool buffers are donated (the old ones are
        dead after the update); the slab is NOT (it stays resident in the
        trie for the next hit)."""
        R = int(slab[0]["k"].shape[1])
        if R > self.cap:
            raise ValueError(f"slab rows {R} exceed pool cap {self.cap}")
        self.caches = _slot_restore_jit()(self.caches, slab, np.int32(slot))

    # ------------------------------------------------------------------ metrics
    @property
    def free_slots(self) -> int:
        return len(self._free)

    @property
    def occupancy(self) -> float:
        return 1.0 - len(self._free) / self.slots

    @property
    def paged(self) -> bool:
        return False


NULL_PAGE = 0      # reserved sentinel: pads every table row; rows it could
#   contribute are always masked by cache_len, writes to it are dead stores


# Paged movers live at MODULE level (lru_cache + jit-by-shape), not on the
# pool instance: a pool is rebuilt on every reset_pool (failure recovery) and
# per serving lane, and per-instance jitted closures re-paid their XLA compile
# each time — measured at ~0.15 s per pool, which dominated short serving
# runs. Geometry (page size, table width, layer count) is recovered from the
# argument shapes, so one compiled mover serves every same-shaped pool.
@functools.lru_cache(maxsize=None)
def _paged_scatter_jit():
    def scatter(caches, one, tbl):
        # write a prefill's dense batch-1 cache into the slot's pages; rows
        # beyond cap pad with zeros into the (dead) null page
        mp = tbl.shape[0]
        out = []
        for c, o in zip(caches, one):
            _, hk, cap_r, d = o["k"].shape
            ps = c["k"].shape[2]
            pad = ((0, 0), (0, mp * ps - cap_r), (0, 0))
            k = jnp.pad(o["k"][0], pad).reshape(hk, mp, ps, d)
            v = jnp.pad(o["v"][0], pad).reshape(hk, mp, ps, d)
            out.append({
                "k": c["k"].at[tbl].set(
                    k.transpose(1, 0, 2, 3).astype(c["k"].dtype)),
                "v": c["v"].at[tbl].set(
                    v.transpose(1, 0, 2, 3).astype(c["v"].dtype))})
        return out

    return jax.jit(scatter, donate_argnums=(0,))


@functools.lru_cache(maxsize=None)
def _paged_cow_jit():
    def cow(caches, src, dst):
        return [{"k": c["k"].at[dst].set(c["k"][src]),
                 "v": c["v"].at[dst].set(c["v"][src])} for c in caches]

    return jax.jit(cow, donate_argnums=(0,))


@functools.lru_cache(maxsize=None)
def _paged_gather_jit(R: int):
    def gather(caches, tbl):
        out = []
        for c in caches:
            _, hk, ps, d = c["k"].shape
            k = c["k"][tbl].transpose(1, 0, 2, 3).reshape(hk, -1, d)
            v = c["v"][tbl].transpose(1, 0, 2, 3).reshape(hk, -1, d)
            out.append({"k": k[:, :R, :], "v": v[:, :R, :]})
        return out

    return jax.jit(gather)


@functools.lru_cache(maxsize=None)
def _paged_restore_jit(R: int):
    def restore(caches, slab, tbl):
        n = tbl.shape[0]
        out = []
        for c, s in zip(caches, slab):
            hk, _, d = s["k"].shape
            ps = c["k"].shape[2]
            pad = ((0, 0), (0, n * ps - R), (0, 0))
            k = jnp.pad(s["k"], pad).reshape(hk, n, ps, d)
            v = jnp.pad(s["v"], pad).reshape(hk, n, ps, d)
            out.append({
                "k": c["k"].at[tbl].set(
                    k.transpose(1, 0, 2, 3).astype(c["k"].dtype)),
                "v": c["v"].at[tbl].set(
                    v.transpose(1, 0, 2, 3).astype(c["v"].dtype))})
        return out

    return jax.jit(restore, donate_argnums=(0,))


class PagedKVPool:
    """Global fixed-size KV pages behind per-slot page tables (see module
    docstring). ``cap`` is the per-slot row capacity the compiled fns see —
    pages round it UP internally (``max_pages = ceil(cap / page)``) but every
    dense view the model computes over is sliced back to exactly ``cap`` rows,
    so attention math (reduction shapes included) is bit-identical to the
    slot-row pool's."""

    def __init__(self, model_config, slots: int, cap: int, page_size: int = 16,
                 dtype=None, total_pages: Optional[int] = None):
        if slots < 1 or cap < 2:
            raise ValueError(f"need slots >= 1 and cap >= 2, got {slots}, {cap}")
        if page_size < 1:
            raise ValueError(f"page_size must be >= 1, got {page_size}")
        self.slots = int(slots)
        self.cap = int(cap)
        self.page_size = ps = int(page_size)
        self.max_pages = mp = math.ceil(self.cap / ps)   # table width per slot
        if total_pages is None:
            # default budget matches the slot-row pool's HBM exactly (plus the
            # one null page): same bytes, page-granular occupancy
            total_pages = self.slots * mp + 1
        self.total_pages = P = int(total_pages)
        if P < mp + 1:
            raise ValueError(
                f"total_pages={P} cannot hold even one max-size request "
                f"({mp} pages) plus the null page")
        cfg = model_config
        self.n_layer = cfg.n_layer
        dtype = dtype or cfg.dtype
        shape = (P, cfg.kv_heads, ps, cfg.head_dim)
        self.caches = [{"k": jnp.zeros(shape, dtype),
                        "v": jnp.zeros(shape, dtype)}
                       for _ in range(cfg.n_layer)]
        self.page_nbytes = 2 * cfg.n_layer * cfg.kv_heads * ps * \
            cfg.head_dim * jnp.dtype(dtype).itemsize
        # host allocator state
        self.page_table = np.full((self.slots, mp), NULL_PAGE, np.int32)
        self._free_slots: List[int] = list(range(self.slots))
        self._free_pages: List[int] = list(range(1, P))     # 0 = null page
        self._ref = np.zeros(P, np.int64)
        self._slot_npages = np.zeros(self.slots, np.int32)
        self._slot_tokens = np.zeros(self.slots, np.int64)  # reserved tokens
        self.cow_copies_total = 0
        # pool pages donated unconditionally (same contract as SlotKVPool:
        # the old buffers are always dead after the update); the jitted
        # movers are module-level shape-keyed singletons — rebuilding a pool
        # after a failure (or per serving lane) must not re-pay XLA compiles
        self._scatter_fn = _paged_scatter_jit()
        self._cow_fn = _paged_cow_jit()

    # --------------------------------------------------------------- allocator
    def pages_for(self, tokens: int) -> int:
        return math.ceil(max(1, int(tokens)) / self.page_size)

    def _fresh_needed(self, tokens: int, matched: int = 0) -> int:
        """Pages a new request must ALLOCATE (shared full pages bind for free;
        a partially-covered boundary page costs one copy-on-write page)."""
        need = self.pages_for(tokens)
        shared_full = int(matched) // self.page_size
        return need - shared_full

    def can_admit(self, tokens: Optional[int] = None, matched: int = 0) -> bool:
        tokens = self.cap if tokens is None else int(tokens)
        return bool(self._free_slots) and \
            len(self._free_pages) >= self._fresh_needed(tokens, matched)

    def acquire(self, tokens: Optional[int] = None, prefix_pages=None,
                matched: int = 0) -> Optional[int]:
        """Borrow a slot and allocate its pages, or ``None`` when slot or page
        capacity is exhausted (the caller leaves the request queued).

        ``tokens`` is the reservation (``prompt + max_new``; defaults to
        ``cap``). With ``prefix_pages``/``matched`` (a prefix-cache hit), the
        first ``matched // page`` table entries BIND the shared pages
        (refcount bump, zero-copy) and a partially-covered boundary page is
        copied into a fresh private page (copy-on-write) so the new slot's
        suffix writes never touch shared rows."""
        tokens = self.cap if tokens is None else int(tokens)
        if tokens > self.cap:
            raise ValueError(f"reservation {tokens} exceeds cap {self.cap}")
        matched = int(matched)
        if prefix_pages is None:
            matched = 0
        need = self.pages_for(tokens)
        shared_full = matched // self.page_size
        cow = 1 if matched % self.page_size else 0
        fresh = need - shared_full
        if not self._free_slots or len(self._free_pages) < fresh:
            return None
        if prefix_pages is not None and shared_full + cow > len(prefix_pages):
            raise ValueError(
                f"matched={matched} needs {shared_full + cow} prefix pages, "
                f"entry holds {len(prefix_pages)}")
        slot = self._free_slots.pop(0)
        row = self.page_table[slot]
        n = 0
        for j in range(shared_full):                   # zero-copy shared bind
            p = int(prefix_pages[j])
            self._ref[p] += 1
            row[n] = p
            n += 1
        if cow:                                        # boundary page: COW
            src = int(prefix_pages[shared_full])
            dst = self._free_pages.pop(0)
            self.caches = self._cow_fn(self.caches, np.int32(src),
                                       np.int32(dst))
            self.cow_copies_total += 1
            self._ref[dst] = 1
            row[n] = dst
            n += 1
        for _ in range(need - n):                      # private fresh pages
            p = self._free_pages.pop(0)
            self._ref[p] = 1
            row[n] = p
            n += 1
        self._slot_npages[slot] = need
        self._slot_tokens[slot] = tokens
        return slot

    def release(self, slot: int) -> None:
        """Return the slot and decref its pages; pages at refcount 0 go back
        to the free list (a page the prefix cache still references survives —
        eviction there is just another refcount drop). No zero-fill: see the
        module docstring's leak-safety argument."""
        if slot in self._free_slots:
            raise ValueError(f"slot {slot} is already free")
        row = self.page_table[slot]
        for j in range(int(self._slot_npages[slot])):
            self._decref(int(row[j]))
        row[:] = NULL_PAGE
        self._slot_npages[slot] = 0
        self._slot_tokens[slot] = 0
        self._free_slots.append(slot)

    def _decref(self, page: int) -> None:
        if page == NULL_PAGE:
            return
        if self._ref[page] <= 0:
            raise AssertionError(f"refcount underflow on page {page}")
        self._ref[page] -= 1
        if self._ref[page] == 0:
            self._free_pages.append(page)

    # ----------------------------------------------------- prefix page sharing
    def share_prefix(self, slot: int, tokens: int) -> np.ndarray:
        """Refcount-bump the slot's pages covering rows ``[0, tokens)`` and
        return their indices — the prefix cache's zero-copy insert (the paged
        replacement for the slab gather). The boundary page is shared too: a
        later hit only trusts its rows below the matched length and
        copy-on-writes before writing."""
        n = self.pages_for(tokens)
        if n > int(self._slot_npages[slot]):
            raise ValueError(f"slot {slot} holds {self._slot_npages[slot]} "
                             f"pages, cannot share {n}")
        pages = self.page_table[slot, :n].copy()
        for p in pages:
            self._ref[int(p)] += 1
        return pages

    def release_shared(self, pages) -> None:
        """Drop a prefix-cache entry's page references (LRU eviction path)."""
        for p in pages:
            self._decref(int(p))

    def page_ref(self, page: int) -> int:
        """Current refcount of a page (admission-pressure eviction asks which
        cache entries would actually free pages: exactly those holding a
        page at refcount 1)."""
        return int(self._ref[int(page)])

    def table_row(self, slot: int) -> np.ndarray:
        return self.page_table[slot]

    # ------------------------------------------------------ prefill scatter-in
    def scatter_prefill(self, slot: int, one_caches: List[Dict[str, Any]]) \
            -> None:
        """Write a prefill's dense batch-1 per-layer cache into the slot's
        pages (the miss-path sibling of the slot pool's row scatter)."""
        self.caches = self._scatter_fn(self.caches, one_caches,
                                       jnp.asarray(self.page_table[slot]))

    # --------------------------------------------------------- slab I/O (wire)
    def slab_nbytes(self, rows: int) -> int:
        """Host-side size of a dense ``rows``-row slab (serialization API)."""
        total = 0
        for c in self.caches:
            _, hk, _, d = c["k"].shape
            total += 2 * hk * int(rows) * d * c["k"].dtype.itemsize
        return total

    def gather_prefix(self, slot: int, rows: int) -> List[Dict[str, Any]]:
        """Copy rows ``[0, rows)`` of ``slot`` out as an independent dense KV
        slab — the page-granular serialization API disaggregated prefill
        ships (NOT donated; the slab's lifetime is the caller's). Underneath
        it is a page gather sliced to ``rows``."""
        R = int(rows)
        if not 0 < R <= self.cap:
            raise ValueError(f"rows must be in [1, cap={self.cap}], got {R}")
        return _paged_gather_jit(R)(self.caches,
                                    jnp.asarray(self.page_table[slot]))

    def gather_pages(self, pages, rows: int) -> List[Dict[str, Any]]:
        """Dense slab from an ARBITRARY page-index vector — ``gather_prefix``
        without a slot, for pages a live table no longer (or never) maps:
        the prefix cache's spill path gathers an evicted entry's pages into
        a host-tier slab right before dropping its refcounts. Same compiled
        mover as ``gather_prefix`` (the jit is keyed only on ``rows``), NOT
        donated — the pool keeps serving."""
        R = int(rows)
        if not 0 < R <= self.cap:
            raise ValueError(f"rows must be in [1, cap={self.cap}], got {R}")
        n = self.pages_for(R)
        if n > len(pages):
            raise ValueError(f"{R} rows span {n} pages, got {len(pages)}")
        tbl = jnp.asarray(np.asarray(pages, np.int32)[:n])
        return _paged_gather_jit(R)(self.caches, tbl)

    def restore_prefix(self, slot: int, slab: List[Dict[str, Any]]) -> None:
        """Write a dense gathered slab into rows ``[0, slab_rows)`` of the
        slot's pages (donated pool update). Assumes a freshly acquired slot:
        boundary-page rows beyond the slab are zero-padded, which is exactly
        the unwritten state they are in."""
        R = int(slab[0]["k"].shape[1])
        if R > self.cap:
            raise ValueError(f"slab rows {R} exceed pool cap {self.cap}")
        n = self.pages_for(R)
        if n > int(self._slot_npages[slot]):
            raise ValueError(f"slot {slot} holds {self._slot_npages[slot]} "
                             f"pages, slab needs {n}")
        self.caches = _paged_restore_jit(R)(
            self.caches, slab, jnp.asarray(self.page_table[slot, :n]))

    def promote_prefix(self, slot: int, slab: List[Dict[str, Any]],
                       matched: int) -> None:
        """Restore a host-tier slab's first ``matched`` rows into a freshly
        acquired slot — the promote path of the tiered prefix cache. The
        restore width is normalized HOST-SIDE to the page multiple covering
        ``matched`` (slice or zero-pad the numpy slab), so the compiled
        restore is keyed on page multiples only — geometry-bounded compile
        keys instead of one per distinct spilled-prompt length. Rows in
        ``[matched, page-multiple)`` land in the slot's own private pages and
        are overwritten by the suffix prefill or masked by ``cache_len`` —
        the same argument ``restore_prefix`` already makes for its padding.
        Requires a slot acquired WITHOUT shared prefix pages (the donated
        write would otherwise clobber rows other slots still trust)."""
        m = int(matched)
        rows = int(slab[0]["k"].shape[1])
        if not 0 < m <= min(rows, self.cap):
            raise ValueError(f"matched must be in [1, min(slab rows {rows}, "
                             f"cap {self.cap})], got {m}")
        n = self.pages_for(m)
        if n > int(self._slot_npages[slot]):
            raise ValueError(f"slot {slot} holds {self._slot_npages[slot]} "
                             f"pages, promote needs {n}")
        R = n * self.page_size
        if rows != R:
            fixed = []
            for s in slab:
                k = np.asarray(s["k"])[:, :R, :]
                v = np.asarray(s["v"])[:, :R, :]
                if k.shape[1] < R:
                    pad = ((0, 0), (0, R - k.shape[1]), (0, 0))
                    k, v = np.pad(k, pad), np.pad(v, pad)
                fixed.append({"k": k, "v": v})
            slab = fixed
        self.caches = _paged_restore_jit(R)(
            self.caches, slab, jnp.asarray(self.page_table[slot, :n]))

    # ------------------------------------------------------------------ metrics
    @property
    def paged(self) -> bool:
        return True

    @property
    def free_slots(self) -> int:
        return len(self._free_slots)

    @property
    def free_pages(self) -> int:
        return len(self._free_pages)

    @property
    def pages_in_use(self) -> int:
        return self.total_pages - 1 - len(self._free_pages)

    @property
    def occupancy(self) -> float:
        """SLOT occupancy — same quantity (and autoscaler signal semantics)
        as the slot-row pool; page-level utilisation is in :meth:`stats`."""
        return 1.0 - len(self._free_slots) / self.slots

    @property
    def shared_pages(self) -> int:
        """Pages referenced more than once (slot+cache or multi-slot bind)."""
        return int(np.sum(self._ref > 1))

    @property
    def page_fragmentation(self) -> float:
        """Internal fragmentation of slot-held pages: the fraction of
        allocated page rows beyond the slots' token reservations (allocation
        granularity waste — the quantity the page-size knob trades against
        table width)."""
        pages = int(np.sum(self._slot_npages))
        if pages == 0:
            return 0.0
        reserved = int(np.sum(self._slot_tokens))
        return 1.0 - reserved / (pages * self.page_size)

    def stats(self) -> Dict[str, float]:
        return {
            "pages_in_use": float(self.pages_in_use),
            "page_fragmentation": float(self.page_fragmentation),
            "prefix_shared_pages": float(self.shared_pages),
            "cow_copies_total": float(self.cow_copies_total),
            "total_pages": float(self.total_pages - 1),
            "page_size": float(self.page_size),
        }
