"""Process-parallel replica hosts: supervised subprocess replicas as
first-class :class:`~.router.Router` members.

The in-process :class:`~.router.EngineReplica` pump is SERIAL — N replicas on
one host add zero machine parallelism, which left half of the PR 12 autoscale
claim unmeasurable ("static-min breaches the latency gate the autoscaled
router holds"). This module closes that gap: a :class:`HostedReplica` hosts
the engine + scheduler stack in its OWN child process (the
:mod:`.subproc` JSONL pipe), pumps itself concurrently with every other
replica, and presents the exact replica surface the router, autoscaler, chaos
harness, and telemetry already speak:

- **async submit/harvest** — ``submit`` writes one JSONL line and returns a
  :class:`HostedHandle` immediately; the child decodes on its own clock and
  streams cumulative token prefixes back; ``step()`` (the router's pump slot)
  only *harvests* — it never blocks on child compute;
- **child-stamped heartbeats** — replica liveness is the child's own
  heartbeat/progress stream, not the parent's serial pump: ``step()`` copies
  the pipe's last-line stamp into ``last_heartbeat``, so pipe silence ages the
  replica through the existing ``LIVE→SUSPECT→DEAD`` machine exactly like a
  flatlined in-process replica (the **pipe-silence watchdog**). A vanished
  process fast-fails the heartbeat instead of waiting out ``dead_after_s``;
- **real-signal chaos** — ``kill(sig="KILL"|"TERM")`` delivers the actual
  signal; ``stall(s)`` is ``SIGSTOP``/``SIGCONT`` (the chaos harness routes
  in-process flag semantics here automatically);
- **supervision** — :class:`ReplicaSupervisor` respawns dead children with
  exponential backoff under a bounded restart budget (mirroring the
  launcher's ``--max_restarts`` semantics); a respawned replica re-enters
  service through the router's existing ``DEAD→RECOVERING`` half-open
  warm-probe path, and an exhausted budget pins the replica DEAD while the
  router keeps serving on the survivors. Restart/backoff/RSS/pipe-lag
  telemetry is declared in ``observability.schema`` (``host/*``);
- **prefix-only recovery** — unchanged: the parent's view of a replica is the
  streamed token prefixes, so retry after any of the above is bit-identical
  to an unkilled run (the determinism contract lets :attr:`HostedReplica.engine`
  lazily build a parent-side reference engine with identical weights —
  weights never cross the pipe).

The per-child prefix cache is internal to the child — the parent never holds
a trie handle (``scheduler.prefix_cache`` reads ``None``), so chaos
``when=restore`` remains an in-process-replica trigger. What DOES cross the
pipe (PR 19, additive heartbeat field ``cache``) is the cache's gossip: hit
economics, tiered-cache byte/movement counters, and the digest ladder of
resident prefixes, which the router's prefix-aware dispatch scores with
:func:`~.prefix_cache.match_from_digests` instead of a probe round trip.

Threading: like the router — drive :meth:`ReplicaSupervisor.step` from the
same loop as ``router.step()`` (``deepspeed-serve --host-replicas`` and the
loadgen do exactly that). The pipe reader threads only fill buffers.
"""

import itertools
import os
import signal
import threading
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional

import numpy as np

from ...observability import flight as obs_flight
from ...observability.metrics import RegistryFeed
from ...observability.trace import get_tracer
from ...utils.logging import logger
from .router import ReplicaDeadError, ReplicaState
from .scheduler import QueueFullError, RequestState, validate_admission
from .subproc import SubprocessReplica


def _default_repo_root() -> str:
    import deepspeed_tpu
    return os.path.dirname(os.path.dirname(
        os.path.abspath(deepspeed_tpu.__file__)))


@dataclass
class HostConfig:
    """Child-process dims + parent-side supervision knobs for one host."""
    family: str = "gpt2"               # gpt2 | llama (child builds fp32 —
    #   the determinism contract behind parent-side parity references)
    vocab_size: int = 96
    max_seq_len: int = 64
    n_embd: int = 32
    n_layer: int = 2
    n_head: int = 4
    slots: int = 2
    chunk_size: int = 2
    hb_interval_s: float = 0.05        # child heartbeat period
    startup_grace_s: float = 120.0     # boot window (jax import + engine
    #   build + XLA warm inside the child): the parent keeps the heartbeat
    #   alive while the process exists and the hello has not landed — the
    #   pipe-silence watchdog arms only once the child is ready
    stop_drain_s: float = 10.0         # stop ladder rung 1: graceful drain
    stop_term_s: float = 5.0           # stop ladder rung 2: SIGTERM grace
    default_max_new_tokens: int = 32
    retry_after_s: float = 0.25        # backpressure hint on a full host
    repo_root: Optional[str] = None
    env: Optional[Dict[str, str]] = None
    cmd_override: Optional[List[str]] = None   # tests: replace the child argv
    #   (protocol/supervision lanes run against stub children, no jax import)
    # ---------------------------------------- per-child serving knobs (PR 16)
    # these cross the spawn as child argv — the parent-side refusal to
    # combine --prefix-cache with --host-replicas is lifted: each child owns
    # its cache/pool and reports hit-rate economics in its heartbeat
    prefix_cache: bool = False
    prefix_cache_mb: Optional[float] = None
    prefix_tier_mb: Optional[float] = None   # host-RAM rung under the HBM
    #   budget (PR 19): evicted device entries spill here and promote back
    prefix_min_hit: Optional[int] = None
    kv_pool: Optional[str] = None      # paged | slots (child default: paged)
    kv_page_size: Optional[int] = None
    chunk_deadline_s: Optional[float] = None
    # ----------------------------------------------- socket transport (PR 16)
    socket_mode: str = "listen"        # SocketHostedReplica spawn wiring:
    #   "listen" = child binds an ephemeral port, parent dials it;
    #   "connect" = parent listens, child dials (--connect)

    def dims(self) -> Dict:
        d = {"family": self.family, "vocab_size": self.vocab_size,
             "max_seq_len": self.max_seq_len, "n_embd": self.n_embd,
             "n_layer": self.n_layer, "n_head": self.n_head,
             "slots": self.slots, "chunk_size": self.chunk_size,
             "hb_interval": self.hb_interval_s}
        for key, val in (("prefix_cache_mb", self.prefix_cache_mb),
                         ("prefix_tier_mb", self.prefix_tier_mb),
                         ("prefix_min_hit", self.prefix_min_hit),
                         ("kv_pool", self.kv_pool),
                         ("kv_page_size", self.kv_page_size),
                         ("chunk_deadline", self.chunk_deadline_s)):
            if val is not None:
                d[key] = val
        return d


def reference_engine(config: HostConfig):
    """Parent-side engine bit-identical to the child's (same family/dims,
    fp32, same fixed init seed) — the parity checks and drain-handoff
    references compute against it; weights never cross the pipe."""
    import jax.numpy as jnp

    from ...models.causal_lm import gpt2_cfg, llama_cfg
    from ..config import DeepSpeedInferenceConfig
    from ..engine import InferenceEngine
    family = {"gpt2": gpt2_cfg, "llama": llama_cfg}[config.family]
    return InferenceEngine(
        family(vocab_size=config.vocab_size, max_seq_len=config.max_seq_len,
               n_embd=config.n_embd, n_layer=config.n_layer,
               n_head=config.n_head, dtype=jnp.float32),
        DeepSpeedInferenceConfig(dtype="float32",
                                 max_out_tokens=config.max_seq_len))


class HostedHandle:
    """Parent-side view of one request on a hosted replica: the
    ``RequestHandle`` surface the router touches, filled from the child's
    streamed JSONL progress lines (cumulative prefixes — the only state the
    recovery model may use)."""

    def __init__(self, host, rid: int, prompt, max_new_tokens: int,
                 eos_token_id, deadline_s, seed: int):
        self._host = host
        self.id = int(rid)
        self.prompt = prompt
        self.max_new_tokens = int(max_new_tokens)
        self.eos_token_id = eos_token_id
        self.deadline_s = deadline_s
        self.seed = int(seed)
        self.arrival = time.monotonic()
        self.state = RequestState.QUEUED
        self.tokens: List[int] = []
        self.finish_reason: Optional[str] = None
        self.ttft: Optional[float] = None
        self.tpot: Optional[float] = None
        self.slot: Optional[int] = None
        self.first_token_at: Optional[float] = None
        self.finished_at: Optional[float] = None
        self.prefix_hit_tokens = 0
        self._cancel = False
        self._span = None        # replica-side spans live in the child; the
        #   router's absorb path tolerates None here

    def cancel(self) -> None:
        self._cancel = True
        self._host._cancel_request(self.id)

    @property
    def done(self) -> bool:
        return self.state in (RequestState.FINISHED, RequestState.CANCELLED,
                              RequestState.EXPIRED, RequestState.EVICTED)

    def result(self) -> np.ndarray:
        return np.asarray(self.tokens, dtype=np.int32)

    def output_ids(self) -> np.ndarray:
        return np.concatenate([self.prompt.astype(np.int32), self.result()])


class _HostPoolView:
    """The KV-pool slice of the replica surface (occupancy/slot accounting)
    from the child's heartbeat stream."""

    paged = False

    def __init__(self, host):
        self._host = host

    @property
    def free_slots(self) -> int:
        return self._host.free_slots

    @property
    def occupancy(self) -> float:
        hb = self._host.hb
        if hb is not None and "occupancy" in hb:
            return float(hb["occupancy"])
        slots = max(1, self._host.config.slots)
        return min(1.0, self._host.outstanding / slots)


class _HostExecutorView:
    def __init__(self, host):
        self._host = host
        self.pool = _HostPoolView(host)

    @property
    def max_prompt_len(self) -> int:
        ready = self._host._rep.ready if self._host._rep else None
        if ready and "max_prompt_len" in ready:
            return int(ready["max_prompt_len"])
        return self._host.config.max_seq_len - 1   # executor default

    @property
    def chunk_warm(self) -> bool:
        """True once THIS child process streamed a token (chaos ``when=busy``
        requires a warm replica so kills land mid-decode, not mid-compile)."""
        return self._host._warm

    def stall_next(self, seconds: float) -> None:
        # the chaos harness's stall hook: a hosted replica wedges by real
        # SIGSTOP (SIGCONT after the window), not by an in-process sleep
        self._host.stall(seconds)


class _HostTelemetryView:
    def __init__(self, host):
        self._host = host

    @property
    def tokens_total(self) -> int:
        return self._host._tokens_total


class _HostSchedulerView:
    """The scheduler-shaped surface the router/autoscaler/chaos/status plane
    read off a replica. Parent-side accounting only — the child's scheduler
    is the truth, mirrored through hello/heartbeat/progress lines."""

    def __init__(self, host):
        self._host = host
        self.executor = _HostExecutorView(host)
        self.telemetry = _HostTelemetryView(host)
        self.prefix_cache = None       # per-child caches are child-internal

    @property
    def cap(self) -> int:
        ready = self._host._rep.ready if self._host._rep else None
        if ready and "cap" in ready:
            return int(ready["cap"])
        return self._host.config.max_seq_len

    @property
    def queue_depth(self) -> int:
        return self._host.queued

    @property
    def busy(self) -> bool:
        return self._host.outstanding > 0

    @property
    def active_requests(self) -> List[HostedHandle]:
        return list(self._host._handles.values())

    def evict_all(self, reason: str = "evicted") -> List[HostedHandle]:
        """Whole-replica eviction (breaker death / drain / retire-grace) —
        delegated to the host, whose transport knows whether the process or
        merely the connection is the casualty."""
        return self._host.evict_all(reason)

    @property
    def prefix_hit_rate(self) -> float:
        """The child's admission-level hit rate, mirrored off its heartbeat
        (0.0 while the child's cache is disabled or before the first hb)."""
        hb = self._host.hb
        if hb is not None and hb.get("prefix_hit_rate") is not None:
            return float(hb["prefix_hit_rate"])
        return 0.0

    def prefix_cache_report(self) -> Dict:
        hb = self._host.hb
        if hb is not None and hb.get("prefix_hit_rate") is not None:
            rep = {"enabled": True, "child": True,
                   "hit_rate": float(hb["prefix_hit_rate"])}
            # PR 19 gossip: the child's KV economy rides the heartbeat so the
            # router's fleet aggregation covers hosted replicas too (stale hb
            # → stale numbers, never an error)
            cache = hb.get("cache")
            if isinstance(cache, dict):
                for key in ("hits", "misses", "hit_tokens", "cached_bytes",
                            "spilled_bytes", "spills", "promotions",
                            "entries", "host_entries"):
                    if key in cache:
                        rep[key] = cache[key]
            return rep
        return {"enabled": False}


class HostedReplica:
    """A subprocess-hosted replica as a first-class Router member (the
    ``EngineReplica`` contract over the :mod:`.subproc` pipe)."""

    #: Router duck-type marker: objects carrying this join the replica set
    #: as themselves instead of being wrapped in an in-process EngineReplica
    replica_protocol = True
    is_hosted = True

    def __init__(self, config: Optional[HostConfig] = None,
                 replica_id: int = -1, wait_ready: bool = False):
        self.config = config or HostConfig()
        self.id = int(replica_id)
        self.scheduler = _HostSchedulerView(self)
        self._ids = itertools.count()
        self._handles: Dict[int, HostedHandle] = {}
        self._rep: Optional[SubprocessReplica] = None
        self._engine = None
        self._killed = False
        self._stopped = False
        self._warm = False
        self._tokens_total = 0
        self.restarts = 0              # stamped by the supervisor
        self.last_heartbeat = time.monotonic()
        self.last_pump_attempt = self.last_heartbeat
        self._spawned_at = self.last_heartbeat
        self._last_step_at = 0.0
        self._stall_timer: Optional[threading.Timer] = None
        self._tracer = get_tracer()
        self._spawn()
        if wait_ready:
            self.wait_ready()

    def bind(self, replica_id: int) -> None:
        """Router attach point: ids are router-assigned, monotonic, never
        reused."""
        self.id = int(replica_id)

    # -------------------------------------------------------------- lifecycle
    def _spawn(self) -> None:
        cfg = self.config
        self._rep = SubprocessReplica(
            cfg.repo_root or _default_repo_root(), env=cfg.env,
            prefix_cache=cfg.prefix_cache,
            cmd=list(cfg.cmd_override) if cfg.cmd_override else None,
            **(cfg.dims() if cfg.cmd_override is None else {}))
        self._killed = False
        self._warm = False
        self._spawned_at = time.monotonic()
        self.last_heartbeat = self._spawned_at

    def wait_ready(self, timeout: float = 180.0) -> Dict:
        return self._rep.wait_ready(timeout)

    @property
    def ready(self) -> bool:
        return self._rep is not None and self._rep.ready is not None

    @property
    def hb(self) -> Optional[Dict]:
        return self._rep.hb if self._rep is not None else None

    @property
    def child_pid(self) -> Optional[int]:
        return self._rep.proc.pid if self._rep is not None else None

    @property
    def quarantined(self) -> int:
        r = self._rep
        return (r.quarantined + r.child_quarantined) if r is not None else 0

    @property
    def alive(self) -> bool:
        return (not self._killed and self._rep is not None
                and self._rep.proc.poll() is None)

    # ------------------------------------------------------------------ chaos
    def kill(self, sig: str = "KILL") -> None:
        """Real-signal death: ``KILL`` is the preempted-host model (no flush,
        no goodbye), ``TERM`` lets the child drain in-flight work before
        exiting (the stream stays truthful either way)."""
        self._cancel_stall()
        rep = self._rep
        if rep is None or rep.proc.poll() is not None:
            self._killed = True
            return
        signum = {"KILL": signal.SIGKILL,
                  "TERM": signal.SIGTERM}[str(sig).upper()]
        try:
            rep.proc.send_signal(signum)
        except ProcessLookupError:
            pass
        if signum == signal.SIGKILL:
            try:
                rep.proc.wait(timeout=30)
            except Exception:
                pass
        self._killed = True

    def evict_all(self, reason: str = "evicted") -> List["HostedHandle"]:
        """Whole-replica eviction (breaker death / drain / retire-grace). The
        child's device state is unrecoverable from the parent (prefix-only
        recovery), so eviction of a live child = kill; the supervisor owns
        any respawn. Open handles finalize EVICTED with their streamed
        prefixes — exactly what the router's requeue absorbs."""
        self.kill(sig="KILL")
        return self._fail_open_handles(reason)

    def stall(self, seconds: float) -> None:
        """Wedge the child with SIGSTOP for ``seconds`` (SIGCONT after): its
        heartbeat stream goes silent and the pipe-silence watchdog ages the
        replica exactly like a wedged TPU host."""
        rep = self._rep
        if rep is None or rep.proc.poll() is not None:
            return
        try:
            os.kill(rep.proc.pid, signal.SIGSTOP)
        except ProcessLookupError:
            return
        self._cancel_stall()

        def _cont(pid=rep.proc.pid):
            try:
                os.kill(pid, signal.SIGCONT)
            except ProcessLookupError:
                pass

        self._stall_timer = threading.Timer(float(seconds), _cont)
        self._stall_timer.daemon = True
        self._stall_timer.start()

    def _cancel_stall(self) -> None:
        if self._stall_timer is not None:
            self._stall_timer.cancel()
            self._stall_timer = None
            rep = self._rep
            if rep is not None and rep.proc.poll() is None:
                try:                   # never leave a child stopped forever
                    os.kill(rep.proc.pid, signal.SIGCONT)
                except ProcessLookupError:
                    pass

    def revive(self) -> None:
        """Chaos/supervisor respawn: a FRESH process (the old one's HBM state
        is gone with it — same contract as ``EngineReplica.revive``)."""
        self.respawn()

    def respawn(self) -> None:
        """Replace the child with a fresh process. The dead child's in-flight
        trace lanes are force-closed (``state=abandoned``) so the flight
        recorder sees the complete dead lane joined to the retry attempt."""
        self._cancel_stall()
        rep = self._rep
        if rep is not None:
            if self._tracer.enabled:
                try:
                    rep.abandon_open_lanes(self._tracer)
                except Exception:
                    pass
            self._ingest_spans()
            if rep.proc.poll() is None:
                # can't trust a replica being respawned to drain: hard-kill
                try:
                    rep.proc.send_signal(signal.SIGKILL)
                    rep.proc.wait(timeout=30)
                except (ProcessLookupError, Exception):
                    pass
        self._fail_open_handles("respawn")
        self._spawn()

    def close(self) -> int:
        """Graceful shutdown through the stop escalation ladder (detach /
        drain path). Returns the child's exit code."""
        self._stopped = True
        self._cancel_stall()
        if self._rep is None:
            return 0
        self._ingest_spans()
        return self._rep.stop(drain_s=self.config.stop_drain_s,
                              term_s=self.config.stop_term_s)

    # ------------------------------------------------------------------- work
    def submit(self, prompt, max_new_tokens: Optional[int] = None,
               eos_token_id: Optional[int] = None,
               deadline_s: Optional[float] = None, seed: int = 0,
               trace_ctx=None) -> HostedHandle:
        if not self.alive:
            raise ReplicaDeadError(f"hosted replica {self.id} is dead")
        prompt, max_new = validate_admission(
            prompt, max_new_tokens, self.config.default_max_new_tokens,
            self.scheduler.executor.max_prompt_len, self.scheduler.cap)
        if self.available <= 0:
            raise QueueFullError(self.config.retry_after_s)
        rid = next(self._ids)
        h = HostedHandle(self, rid, prompt, max_new, eos_token_id, deadline_s,
                         seed)
        self._handles[rid] = h
        try:
            self._rep.submit(
                rid, prompt, max_new_tokens=max_new, seed=seed,
                eos_token_id=eos_token_id, deadline_s=deadline_s,
                trace_id=trace_ctx.trace_id if trace_ctx is not None else None,
                parent_span=trace_ctx.span_id if trace_ctx is not None
                else None)
        except QueueFullError:
            # write-side backpressure (socket link's bounded out-buffer): the
            # request never left the parent — drop the handle, let the
            # router's admission backpressure absorb it
            del self._handles[rid]
            raise
        return h

    def step(self, now: Optional[float] = None) -> bool:
        """The router's pump slot — HARVEST ONLY, never blocks on child
        compute: sync streamed progress into handles, ingest child spans,
        and maintain the pipe-silence watchdog. Always returns False: the
        parent's serial pump must never stamp this replica's heartbeat —
        liveness is the child's own stream."""
        now = time.monotonic() if now is None else now
        rep = self._rep
        if rep is None:
            return False
        # harvest FIRST: the child flushes every line before a SIGKILL can
        # land, so progress (even a final done) already sitting in the reader
        # buffer must reach the handles before the death path evicts them —
        # failing first would re-decode tokens the pipe truthfully delivered
        self._sync_handles(now)
        pipe_dead = rep.proc.poll() is not None
        if pipe_dead and not self._stopped:
            if self._handles:
                self._fail_open_handles("killed")
            if not self._killed:
                self._killed = True
            # fast-fail: the process is GONE — flatline the heartbeat now
            # instead of waiting out dead_after_s of silence
            self.last_heartbeat = min(self.last_heartbeat, now - 3600.0)
        elif not self._killed:
            t = rep.last_line_at
            if t is not None and t > self.last_heartbeat:
                self.last_heartbeat = t      # child-stamped liveness
            elif (rep.ready is None
                  and now - self._spawned_at < self.config.startup_grace_s):
                # boot window (jax import + engine build): keep the heartbeat
                # alive while the process exists; the watchdog arms at ready
                self.last_heartbeat = now
            elif now - self._last_step_at < 0.001:
                # the router loop is SPINNING (back-to-back steps with
                # nothing new on the pipe): yield the core to the children.
                # A loop doing real work elsewhere (another replica's
                # dispatch/harvest) shows an inter-step gap and pays nothing.
                time.sleep(0.002)
        self._last_step_at = time.monotonic()
        self._ingest_spans()
        return False

    def _sync_handles(self, now: float) -> None:
        rep = self._rep
        for rid, h in list(self._handles.items()):
            line = rep.progress.get(rid)
            if not line:
                continue
            toks = line.get("tokens") or []
            if len(toks) > len(h.tokens):
                if h.first_token_at is None:
                    h.first_token_at = now
                    h.ttft = now - h.arrival
                    h.prefix_hit_tokens = int(line.get("prefix_hit_tokens")
                                              or 0)
                self._tokens_total += len(toks) - len(h.tokens)
                h.tokens = [int(t) for t in toks]
                h.state = RequestState.RUNNING
                self._warm = True
            if line.get("done") and not h.done:
                try:
                    h.state = RequestState(line.get("state", "finished"))
                except ValueError:
                    h.state = RequestState.FINISHED
                h.finish_reason = line.get("finish_reason") or h.state.value
                h.finished_at = now
                if (h.first_token_at is not None and len(h.tokens) > 1
                        and now > h.first_token_at):
                    h.tpot = (now - h.first_token_at) / (len(h.tokens) - 1)
                del self._handles[rid]

    def _fail_open_handles(self, reason: str,
                           only: Optional[List[int]] = None
                           ) -> List[HostedHandle]:
        """Finalize every open handle EVICTED with its streamed prefix (the
        router's requeue path absorbs exactly these tokens). ``only`` limits
        the sweep to specific request ids (the socket link's per-sever-epoch
        eviction)."""
        now = time.monotonic()
        out = []
        for rid, h in list(self._handles.items()):
            if only is not None and rid not in only:
                continue
            if not h.done:
                h.state = RequestState.EVICTED
                h.finish_reason = reason
                h.finished_at = now
            out.append(h)
            del self._handles[rid]
        return out

    def _cancel_request(self, rid: int) -> None:
        if self._rep is not None and self.alive:
            self._rep.cancel(rid)

    def _ingest_spans(self) -> None:
        rep = self._rep
        if rep is None or not rep.spans:
            return
        # child lanes join the parent trace under one host label per replica
        self._tracer.ingest(rep.take_spans(), pid_label=f"host{self.id}")

    # ---------------------------------------------------------------- metrics
    @property
    def engine(self):
        """Lazily-built parent-side reference engine, bit-identical to the
        child's (determinism contract) — what parity checks generate against.
        """
        if self._engine is None:
            self._engine = reference_engine(self.config)
        return self._engine

    @property
    def outstanding(self) -> int:
        return len(self._handles)

    @property
    def running(self) -> int:
        """Open handles actively decoding (>= 1 token streamed) — parent-side
        truth, fresher than the heartbeat's lagged count; chaos ``when=busy``
        keys off this so a kill lands mid-decode deterministically."""
        return sum(1 for h in self._handles.values() if h.tokens)

    @property
    def queued(self) -> int:
        return max(0, self.outstanding - self.running)

    @property
    def free_slots(self) -> int:
        return max(0, self.config.slots - self.outstanding)

    @property
    def available(self) -> int:
        """Dispatch headroom: at most ``slots`` concurrent requests per host
        (queueing stays central, in the router) — and nothing before the
        child's versioned hello lands."""
        if not self.ready or not self.alive:
            return 0
        return self.free_slots

    def pipe_lag_ms(self) -> Optional[float]:
        """Last heartbeat's wall-clock transit+age: how stale the parent's
        view of this child is."""
        hb = self.hb
        if hb is None or "_rx_t" not in hb:
            return None
        return max(0.0, (hb["_rx_t"] - float(hb["t"])) * 1e3)


class SocketHostedReplica(HostedReplica):
    """A :class:`HostedReplica` whose protocol v1 rides the framed-TCP
    transport (:mod:`.net`) instead of the stdio pipe — the same recovery
    semantics across a MACHINE boundary.

    Three wirings (``HostConfig.socket_mode`` + ``endpoint``):

    - ``socket_mode="listen"`` (default): spawn the child with
      ``--serve-socket --listen 127.0.0.1:0`` and dial the bootstrap port;
    - ``socket_mode="connect"``: parent listens, child dials
      (``--connect``) — the wiring for children behind NAT;
    - ``endpoint="host:port"``: dial an externally started child
      (``deepspeed-serve --replica-endpoint``); there is no local process,
      so "kill" means sever + redial and supervision respawns the LINK.

    On a severed connection ``step()`` immediately evicts in-flight requests
    WITH their streamed prefixes (the checkpointless-retry path — recovery
    stays bit-exact) while the link's reconnect machine redials with bounded
    exponential backoff; the frozen heartbeat ages the replica through
    LIVE→SUSPECT→DEAD exactly like pipe silence. A dead CHILD respawns via
    the supervisor; a dead CONNECTION redials via the link — the
    respawn-or-redial split. ``net_fault`` exposes the chaos transport seam
    (``net:replica=i,mode=partition|delay=<ms>|drop=<p>``)."""

    is_socket = True

    def __init__(self, config: Optional[HostConfig] = None,
                 replica_id: int = -1, wait_ready: bool = False,
                 endpoint: Optional[str] = None, net=None):
        self._endpoint = endpoint
        self._net = net                # Optional[net.NetConfig]
        super().__init__(config, replica_id, wait_ready)

    def _spawn(self) -> None:
        from .net import SocketReplicaLink
        cfg = self.config
        if self._rep is not None:
            self._rep.close()          # release the old link's IO + sockets
        spawn_args = (cfg.dims()
                      if cfg.cmd_override is None and self._endpoint is None
                      else {})
        self._rep = SocketReplicaLink(
            cfg.repo_root or _default_repo_root(), env=cfg.env,
            prefix_cache=cfg.prefix_cache,
            cmd=list(cfg.cmd_override) if cfg.cmd_override else None,
            endpoint=self._endpoint,
            child_dials=(cfg.socket_mode == "connect"),
            net=self._net, **spawn_args)
        self._killed = False
        self._warm = False
        self._spawned_at = time.monotonic()
        self.last_heartbeat = self._spawned_at

    # ------------------------------------------------------------------ chaos
    def kill(self, sig: str = "KILL") -> None:
        if self._endpoint is not None:
            # no local process to signal: the connection is the only lever —
            # sever now (step() evicts with prefixes), let the reconnect
            # machine redial; the router re-admits through RECOVERING
            self._cancel_stall()
            self._rep.force_sever("chaos-kill")
            return
        super().kill(sig)

    def stall(self, seconds: float) -> None:
        if self._endpoint is not None:
            # SIGSTOP cannot cross the network: a partition window is the
            # transport-native wedge (silence both ways, then recovery)
            self._rep.net_fault("partition", 0.0, seconds)
            return
        super().stall(seconds)

    def net_fault(self, mode: str, value: float, duration_s: float) -> None:
        """Chaos transport seam (``net:`` grammar): partition | delay | drop
        injected at the parent side of the link."""
        self._rep.net_fault(mode, value, duration_s)

    def force_sever(self, why: str = "forced") -> None:
        """Cut the connection NOW (the live process keeps running): in-flight
        work evicts with prefixes on the next step and the reconnect machine
        redials with the session token — the sever-resume probe the net
        bench and tests drive directly."""
        if self._rep is not None:
            self._rep.force_sever(why)

    def evict_all(self, reason: str = "evicted") -> List["HostedHandle"]:
        """The respawn-vs-redial split at the breaker: when the CONNECTION is
        the known casualty (severed, or a net fault in force) and the child
        process is alive, eviction must not kill the process — open handles
        finalize EVICTED with prefixes, the link severs so the reconnect
        machine redials with the session token, and the re-hello's
        ``cancel_all`` frees the child's orphaned slots. Anything else (true
        heartbeat wedge, drain, retire-grace) keeps the kill semantics: a
        child the parent cannot trust is replaced, not reasoned with."""
        rep = self._rep
        if rep is not None and self.alive \
                and (rep.severed or rep.fault_active):
            if not rep.severed:
                rep.force_sever(f"breaker-evict ({reason})")
            return self._fail_open_handles(reason)
        return super().evict_all(reason)

    # ------------------------------------------------------------------- pump
    def submit(self, prompt, max_new_tokens: Optional[int] = None,
               eos_token_id: Optional[int] = None,
               deadline_s: Optional[float] = None, seed: int = 0,
               trace_ctx=None) -> HostedHandle:
        # stamp the link's sever epoch BEFORE the wire enqueue: a sever that
        # races the enqueue leaves the handle in the old epoch and step()
        # evicts it (the frame may never have left this side), while a handle
        # minted after a quick redial is never swept by the stale sever
        epoch = self._rep.sever_count if self._rep is not None else 0
        h = super().submit(prompt, max_new_tokens=max_new_tokens,
                           eos_token_id=eos_token_id, deadline_s=deadline_s,
                           seed=seed, trace_ctx=trace_ctx)
        h.sever_epoch = epoch
        return h

    def step(self, now: Optional[float] = None) -> bool:
        rep = self._rep
        if rep is not None and not self._stopped and self._handles:
            # sever eviction: whatever was in flight on a severed connection
            # finalizes EVICTED with its streamed prefix — the router's
            # checkpointless retry re-prefills prompt+prefix elsewhere,
            # bit-exact, while the link redials in the background. Keyed on
            # the per-handle sever EPOCH, not the live ``severed`` flag: the
            # IO thread can win the redial race between two parent steps, and
            # the resumed hello's cancel_all would then turn the guaranteed
            # eviction into a child-side cancel. Runs BEFORE the harvest so a
            # post-resume cancelled terminal never beats the eviction; the
            # streamed prefix is folded in here from the same progress lines
            # the harvest would have read.
            count = rep.sever_count
            tnow = time.monotonic()
            stale = []
            for rid, h in list(self._handles.items()):
                if getattr(h, "sever_epoch", 0) >= count or h.done:
                    continue
                line = rep.progress.get(rid) or {}
                toks = line.get("tokens") or []
                if len(toks) > len(h.tokens):
                    if h.first_token_at is None:
                        h.first_token_at = tnow
                        h.ttft = tnow - h.arrival
                        h.prefix_hit_tokens = int(
                            line.get("prefix_hit_tokens") or 0)
                    self._tokens_total += len(toks) - len(h.tokens)
                    h.tokens = [int(t) for t in toks]
                    self._warm = True
                if h._cancel or (line.get("done")
                                 and line.get("state") != "cancelled"):
                    # a real terminal (finished/expired, flushed before the
                    # sever) or a parent-initiated cancel: the harvest applies
                    # it — only in-flight casualties evict
                    continue
                stale.append(rid)
            if stale:
                self._fail_open_handles("severed", only=stale)
        return super().step(now)

    @property
    def available(self) -> int:
        rep = self._rep
        if rep is not None and rep.severed:
            return 0                   # no dispatch into a severed link
        return super().available

    # ---------------------------------------------------------------- surface
    @property
    def severed(self) -> bool:
        return bool(self._rep is not None and self._rep.severed)

    @property
    def reconnects(self) -> int:
        return self._rep.reconnects if self._rep is not None else 0

    @property
    def session(self) -> Optional[str]:
        return self._rep.session if self._rep is not None else None

    @property
    def resumed_last(self) -> Optional[bool]:
        """Whether the link's most recent hello resumed the child's prior
        session (vs a fresh one after a child restart); ``None`` while
        severed — the verdict belongs to the NEXT hello."""
        return self._rep.resumed_last if self._rep is not None else None

    def rtt_ms(self) -> Optional[float]:
        return self._rep.rtt_last_ms if self._rep is not None else None


@dataclass
class SupervisorConfig:
    max_restarts: int = 3          # per-replica respawn budget (the launcher's
    #   --max_restarts semantics: bounded, then pinned DEAD)
    backoff_base_s: float = 0.5    # exponential: base * 2^restarts, capped
    backoff_max_s: float = 30.0
    emit_interval_s: float = 0.25  # telemetry cadence (step() is called from
    #   the hot serving loop)


@dataclass
class _SupervisedState:
    restarts: int = 0
    due: Optional[float] = None    # scheduled respawn time (backoff running)
    backoff_s: float = 0.0
    pinned: bool = False
    backoffs: List[float] = field(default_factory=list)


class ReplicaSupervisor:
    """The supervision tree over a router's hosted replicas: respawn dead
    children with exponential backoff under a bounded restart budget;
    re-admission flows through the router's existing ``DEAD→RECOVERING``
    half-open warm probe (one probe request before real traffic). An
    exhausted budget pins the replica DEAD — the router keeps serving on the
    survivors, and every decision lands in the flight recorder's journal."""

    def __init__(self, router, config: Optional[SupervisorConfig] = None):
        self.router = router
        self.config = config or SupervisorConfig()
        self.state: Dict[int, _SupervisedState] = {}
        self.restarts_total = 0
        self.pinned: List[int] = []
        self._feed = RegistryFeed()
        self._ticks = 0
        self._last_emit: Optional[float] = None

    def step(self, now: Optional[float] = None) -> List[int]:
        """One supervision sweep; returns the replica ids respawned."""
        now = time.monotonic() if now is None else now
        cfg = self.config
        respawned: List[int] = []
        backoff_now = 0.0
        rss_max = 0.0
        lag_max = 0.0
        for r in list(self.router.replicas):
            if not getattr(r, "is_hosted", False):
                continue
            st = self.state.setdefault(r.id, _SupervisedState())
            hb = r.hb
            if hb is not None:
                rss_max = max(rss_max, float(hb.get("rss_bytes") or 0))
                lag = r.pipe_lag_ms()
                if lag is not None:
                    lag_max = max(lag_max, lag)
            h = self.router.health.get(r.id)
            if h is None or st.pinned or h.retiring \
                    or getattr(self.router, "draining", False):
                continue
            if h.state != ReplicaState.DEAD or r.alive:
                st.due = None          # healthy (or already respawned and
                continue               # recovering): no backoff pending
            if st.restarts >= cfg.max_restarts:
                st.pinned = True
                self.pinned.append(r.id)
                logger.error(f"[supervisor] replica {r.id}: restart budget "
                             f"exhausted after {st.restarts} respawn(s); "
                             "pinned DEAD")
                obs_flight.journal("host_pinned", replica=r.id,
                                   restarts=st.restarts)
                continue
            if st.due is None:
                st.backoff_s = min(cfg.backoff_max_s,
                                   cfg.backoff_base_s * (2 ** st.restarts))
                st.backoffs.append(st.backoff_s)
                st.due = now + st.backoff_s
                logger.warning(f"[supervisor] replica {r.id} dead; respawn "
                               f"#{st.restarts + 1} in {st.backoff_s:.2f}s")
                obs_flight.journal("host_backoff", replica=r.id,
                                   backoff_s=round(st.backoff_s, 3),
                                   restarts=st.restarts)
            if now >= st.due:
                st.due = None
                st.restarts += 1
                self.restarts_total += 1
                r.respawn()
                r.restarts = st.restarts
                respawned.append(r.id)
                logger.warning(f"[supervisor] replica {r.id} respawned "
                               f"(child pid {r.child_pid}, restart "
                               f"{st.restarts}/{cfg.max_restarts})")
                obs_flight.journal("host_restart", replica=r.id,
                                   restarts=st.restarts,
                                   child_pid=r.child_pid)
            else:
                backoff_now = max(backoff_now, st.due - now)
        self._ticks += 1
        if (self._last_emit is None
                or now - self._last_emit >= cfg.emit_interval_s):
            self._last_emit = now
            self._feed.record_events([
                ("host/restarts_total", float(self.restarts_total),
                 self._ticks),
                ("host/backoff_s", float(backoff_now), self._ticks),
                ("host/child_rss_bytes", float(rss_max), self._ticks),
                ("host/pipe_lag_ms", float(lag_max), self._ticks),
            ])
        return respawned

    def report(self) -> Dict:
        """``/statusz``-shaped summary: per-replica restart counts, pending
        backoffs, and the pinned set."""
        return {"restarts_total": self.restarts_total,
                "pinned": list(self.pinned),
                "replicas": {rid: {"restarts": st.restarts,
                                   "pinned": st.pinned,
                                   "backoff_s": st.backoff_s if st.due
                                   else 0.0}
                             for rid, st in self.state.items()}}
