"""Multi-replica serving front door: health-supervised routing over N engines.

The layer above :class:`ContinuousBatchingScheduler`: "millions of users" needs
N engine replicas behind ONE bounded admission queue, and it needs replica death
to be an eviction-and-retry event, not a request-loss event. Following the
fail-fast discipline of large-scale continuous-batching serving systems (and the
elasticity pillar on the training side), **requests — not checkpoints — are the
unit of recovery on the inference path**:

- **admission** — one bounded router queue; a full queue raises
  :class:`~.scheduler.QueueFullError` with a ``retry_after`` hint (rejected,
  never dropped); a draining router raises :class:`RouterDrainingError`;
- **dispatch** — least-outstanding-slots across healthy replicas, with session
  affinity (requests carrying the same ``session`` stick to one replica — the
  hook prefix-cache locality hangs off) that yields the moment the pinned
  replica leaves ``LIVE``;
- **health** — each replica runs a state machine
  ``LIVE → SUSPECT → DEAD → RECOVERING (→ LIVE)`` driven by three signals:
  heartbeats (every successful pump step), per-chunk watchdog deadlines
  (:class:`~.executor.ChunkTimeoutError` surfacing as request errors), and a
  consecutive-failure circuit breaker. ``DEAD`` → half-open probe after
  ``recover_after_s`` (one request; success closes the breaker);
- **checkpointless retry** — a dead replica's in-flight requests are evicted
  *with their generated-so-far prefixes* and re-enqueued as
  ``prompt + prefix`` / remaining budget (bounded attempts, per-request replica
  exclusion lists). Greedy retry is prefix-consistent: the final token stream is
  bit-identical to an unkilled run;
- **drain** — SIGTERM (``install_sigterm_drain``) stops admission, lets in-flight
  chunks finish (steps are chunk-granular, so no chunk is ever abandoned
  half-way), evicts what remains with prefixes and hands the queue off as
  re-submittable specs.

Replicas here are in-process (:class:`EngineReplica`: one engine + one
scheduler each — separate meshes in multi-chip deployments), with death/stall
simulated through ``kill()``/``stall_next`` and the fault registry; the
``DS_TPU_FAULT_SPEC`` env contract (``utils.fault_injection``) carries the same
seeded schedules into subprocess-hosted replicas, whose router-side view would
be the streamed token prefixes this module already treats as the only
recoverable state.

Threading: like the scheduler, the router is single-threaded — drive ``step()``
/ ``run()`` from one thread. ``RouterRequest.cancel`` and ``begin_drain`` only
set flags and are safe from signal handlers / other threads.
"""

import itertools
import signal
import time
from collections import deque
from dataclasses import dataclass, field
from enum import Enum
from typing import Deque, Dict, List, Optional, Set

import numpy as np

from ...observability.metrics import Histogram, RegistryFeed
from ...observability.trace import CAT_ROUTER, get_tracer
from ...utils.fault_injection import fault_point, retry_with_backoff
from ...utils.logging import logger
from .scheduler import (ContinuousBatchingScheduler, QueueFullError,
                        RequestState, ServingConfig, validate_admission)


class ReplicaState(Enum):
    LIVE = "live"
    SUSPECT = "suspect"          # missed heartbeats; no new dispatches
    DEAD = "dead"                # evicted; circuit open
    RECOVERING = "recovering"    # half-open: one probe request at a time

    @property
    def code(self) -> int:
        """Stable numeric code for monitor streams."""
        return {"live": 0, "suspect": 1, "dead": 2, "recovering": 3}[self.value]


class RouterRequestState(Enum):
    QUEUED = "queued"
    DISPATCHED = "dispatched"
    FINISHED = "finished"
    CANCELLED = "cancelled"
    EXPIRED = "expired"
    FAILED = "failed"            # retry budget exhausted
    HANDED_OFF = "handed_off"    # drained: returned to the caller as a spec


class ReplicaDeadError(RuntimeError):
    """Dispatch attempted against a replica that is no longer serving."""


class RouterDrainingError(RuntimeError):
    """The router is draining (SIGTERM): admission is closed."""

    def __init__(self):
        super().__init__("router is draining; admission closed")


@dataclass
class RouterConfig:
    max_queue: int = 256                 # router admission bound
    suspect_after_s: float = 2.0         # missed-heartbeat → SUSPECT
    dead_after_s: float = 6.0            # missed-heartbeat → DEAD (evict)
    recover_after_s: float = 10.0        # DEAD → RECOVERING probe window
    breaker_threshold: int = 3           # consecutive failures → DEAD
    max_attempts: int = 3                # dispatches per request (1 + retries)
    dispatch_retries: int = 1            # retry_with_backoff budget per dispatch
    retry_base_delay: float = 0.01
    retry_after_s: float = 0.25          # backpressure hint
    serving: ServingConfig = field(default_factory=ServingConfig)  # per replica


@dataclass
class RouterRequest:
    """Caller's view of a routed request. ``tokens`` accumulates across retry
    attempts; ``prompt`` stays the ORIGINAL prompt (retries re-prefill
    ``prompt + tokens`` internally)."""
    id: int
    prompt: np.ndarray
    max_new_tokens: int
    eos_token_id: Optional[int]
    deadline_s: Optional[float]
    seed: int
    session: Optional[str]
    arrival: float
    state: RouterRequestState = RouterRequestState.QUEUED
    tokens: List[int] = field(default_factory=list)
    finish_reason: Optional[str] = None
    attempts: int = 0                 # dispatches so far
    retried: int = 0                  # re-enqueues after eviction/failure
    evictions: int = 0
    prefix_hit_tokens: int = 0        # from the attempt that produced the
    #   first token (the one TTFT measures) — loadgen splits TTFT on this
    excluded: Set[int] = field(default_factory=set)   # replica exclusion list
    replica_id: Optional[int] = None
    inner: Optional[object] = None    # current attempt's RequestHandle
    ttft: Optional[float] = None
    tpot: Optional[float] = None
    first_token_at: Optional[float] = None
    finished_at: Optional[float] = None
    _cancel: bool = False
    _root_span: Optional[object] = None       # request-scoped trace root
    _attempt_span: Optional[object] = None    # current dispatch attempt
    _prev_attempt_id: Optional[str] = None    # link target for retry spans

    def cancel(self) -> None:
        self._cancel = True
        if self.inner is not None:
            self.inner.cancel()

    @property
    def done(self) -> bool:
        return self.state in (RouterRequestState.FINISHED,
                              RouterRequestState.CANCELLED,
                              RouterRequestState.EXPIRED,
                              RouterRequestState.FAILED,
                              RouterRequestState.HANDED_OFF)

    def result(self) -> np.ndarray:
        """All generated tokens across attempts — including the in-flight
        attempt's live progress (partial if cancelled/evicted)."""
        cur = list(self.tokens)
        if self.inner is not None:
            cur.extend(int(t) for t in self.inner.tokens)
        return np.asarray(cur, dtype=np.int32)

    def output_ids(self) -> np.ndarray:
        return np.concatenate([self.prompt.astype(np.int32), self.result()])

    @property
    def remaining_budget(self) -> int:
        return self.max_new_tokens - len(self.tokens)

    def handoff_spec(self) -> Dict:
        """Re-submittable form for drain hand-off: the generated prefix is
        folded into the prompt so any router can continue the request."""
        return {"id": self.id,
                "prompt": [int(t) for t in self.prompt] + list(self.tokens),
                "prefix_len": len(self.tokens),
                "max_new_tokens": self.remaining_budget,
                "eos_token_id": self.eos_token_id,
                "deadline_s": self.deadline_s, "seed": self.seed,
                "session": self.session}


@dataclass
class ReplicaHealth:
    state: ReplicaState = ReplicaState.LIVE
    consecutive_failures: int = 0
    died_at: Optional[float] = None
    probe_request: Optional[int] = None   # RouterRequest.id of half-open probe


class EngineReplica:
    """In-process replica: one engine + one continuous-batching scheduler.

    Health signals the router reads: ``last_heartbeat`` (advanced by every
    successful :meth:`step`), slot/queue occupancy, and per-request outcomes.
    ``kill()`` simulates abrupt replica death — heartbeats stop, dispatches
    raise — and ``revive()`` brings the process back for the RECOVERING probe.
    """

    def __init__(self, replica_id: int, engine,
                 serving_config: Optional[ServingConfig] = None):
        self.id = int(replica_id)
        self.engine = engine
        self.scheduler = ContinuousBatchingScheduler(engine, serving_config)
        self.last_heartbeat = time.monotonic()
        # last time the router TRIED to pump this replica: heartbeat age is
        # measured against this, not wall time — an idle router that slept
        # between requests has no evidence of death, only a replica that fails
        # to respond while being pumped does
        self.last_pump_attempt = self.last_heartbeat
        self._killed = False

    # ------------------------------------------------------------------ chaos
    def kill(self) -> None:
        """Simulate abrupt death: no more heartbeats, no more work."""
        self._killed = True

    def revive(self) -> None:
        """Bring the replica back, modeling a FRESH process: any scheduler
        state from before the kill is discarded (the router already evicted
        and requeued those requests — leaving them would resume zombie decode
        of work now owned by other replicas), and the prefix cache is cleared
        — a real process death loses its HBM slabs, so the in-process
        simulation must not resurrect them."""
        self._killed = False
        if self.scheduler.busy:
            self.scheduler.evict_all(reason="revive")
        if self.scheduler.prefix_cache is not None:
            self.scheduler.prefix_cache.clear()
        self.last_heartbeat = time.monotonic()
        self.last_pump_attempt = self.last_heartbeat

    @property
    def alive(self) -> bool:
        return not self._killed

    # ------------------------------------------------------------------- work
    def step(self) -> bool:
        """One scheduler step + heartbeat; returns True when the replica
        responded (i.e. it is not killed).

        The heartbeat is stamped AFTER the step completes, with the real clock:
        a step that spends seconds inside a first-dispatch XLA compile must not
        read as a flatline (the router sweeps health at step start, so a
        start-of-step stamp would age by the whole compile)."""
        if self._killed:
            return False
        self.scheduler.step()
        self.last_heartbeat = time.monotonic()
        return True

    def submit(self, *args, **kwargs):
        if self._killed:
            raise ReplicaDeadError(f"replica {self.id} is dead")
        return self.scheduler.submit(*args, **kwargs)

    # ---------------------------------------------------------------- metrics
    @property
    def free_slots(self) -> int:
        return self.scheduler.executor.pool.free_slots

    @property
    def queued(self) -> int:
        return self.scheduler.queue_depth

    @property
    def running(self) -> int:
        return len(self.scheduler.active_requests)

    @property
    def outstanding(self) -> int:
        return self.running + self.queued

    @property
    def available(self) -> int:
        """Slots this replica could start on right now (free minus already
        queued-at-replica) — the router's least-outstanding-slots currency."""
        return self.free_slots - self.queued


class RouterTelemetry:
    """Router-level metrics through MonitorMaster + aggregate snapshot.

    Monitor tags: ``router/queue_depth``, ``router/retried_total``,
    ``router/evicted_total``, ``router/completed_total``,
    ``router/rejected_total``, ``router/replica{i}/health`` (state code),
    ``router/replica{i}/outstanding``, ``router/replica{i}/prefix_hit_rate``
    (prefix cache enabled only — caches are strictly per-replica, so hit rate
    is a per-replica property that session affinity concentrates),
    ``router/drain_ms``, per-request ``router/ttft_ms`` / ``router/tpot_ms``.
    """

    def __init__(self, monitor=None, n_replicas: int = 1):
        self.monitor = monitor
        self.n_replicas = n_replicas
        self._tick = 0
        self._finished_idx = 0
        self.submitted = 0
        self.completed = 0
        self.failed = 0
        self.rejected = 0
        self.cancelled = 0
        self.expired = 0
        self.handed_off = 0
        self.retried = 0
        self.evicted = 0
        self.dispatched: Dict[int, int] = {i: 0 for i in range(n_replicas)}
        self.transitions: List = []       # (tick, replica, old, new)
        # bounded distributions (same O(1)-memory contract as ServingTelemetry)
        self.ttft_ms = Histogram()
        self.tpot_ms = Histogram()
        # per-emitter feed: cumulative *_total counters contribute deltas so
        # successive routers in one process sum in /metrics
        self._feed = RegistryFeed()
        self.drain_s: Optional[float] = None

    def _write(self, events):
        self._feed.record_events(events)   # process registry (/metrics)
        if self.monitor is not None and getattr(self.monitor, "enabled", False):
            self.monitor.write_events(events)

    def on_step(self, queue_depth: int, replicas, health) -> None:
        self._tick += 1
        ev = [("router/queue_depth", float(queue_depth), self._tick),
              ("router/retried_total", float(self.retried), self._tick),
              ("router/evicted_total", float(self.evicted), self._tick),
              ("router/completed_total", float(self.completed), self._tick),
              ("router/rejected_total", float(self.rejected), self._tick)]
        for r in replicas:
            ev.append((f"router/replica{r.id}/health",
                       float(health[r.id].state.code), self._tick))
            ev.append((f"router/replica{r.id}/outstanding",
                       float(r.outstanding), self._tick))
            if r.scheduler.prefix_cache is not None:
                ev.append((f"router/replica{r.id}/prefix_hit_rate",
                           float(r.scheduler.prefix_hit_rate), self._tick))
        self._write(ev)

    def on_transition(self, replica_id: int, old: ReplicaState,
                      new: ReplicaState) -> None:
        self.transitions.append((self._tick, replica_id, old, new))
        self._write([(f"router/replica{replica_id}/health", float(new.code),
                      self._tick)])

    def on_dispatch(self, replica_id: int) -> None:
        self.dispatched[replica_id] = self.dispatched.get(replica_id, 0) + 1

    def on_rejected(self) -> None:
        self.rejected += 1

    def on_evicted(self, n: int = 1) -> None:
        self.evicted += n

    def on_retried(self) -> None:
        self.retried += 1

    def on_drain(self, seconds: float, handed_off: int) -> None:
        self.drain_s = seconds
        self.handed_off += handed_off
        self._write([("router/drain_ms", seconds * 1e3, self._tick),
                     ("router/handed_off_total", float(self.handed_off),
                      self._tick)])

    def on_finished(self, rr: RouterRequest) -> None:
        st = rr.state
        if st == RouterRequestState.CANCELLED:
            self.cancelled += 1
            return
        if st == RouterRequestState.EXPIRED:
            self.expired += 1
            return
        if st == RouterRequestState.FAILED:
            self.failed += 1
            return
        self.completed += 1
        self._finished_idx += 1
        ev = []
        if rr.ttft is not None:
            self.ttft_ms.observe(rr.ttft * 1e3)
            ev.append(("router/ttft_ms", rr.ttft * 1e3, self._finished_idx))
        if rr.tpot is not None:
            self.tpot_ms.observe(rr.tpot * 1e3)
            ev.append(("router/tpot_ms", rr.tpot * 1e3, self._finished_idx))
        self._write(ev)

    def snapshot(self) -> Dict:
        # "lost" is the no-silent-loss invariant: every admitted request must
        # end completed, caller-cancelled, expired, or explicitly handed off.
        # FAILED (retry budget exhausted) counts as lost — it was admitted and
        # not served.
        lost = self.submitted - self.completed - self.cancelled \
            - self.expired - self.handed_off
        return {
            "submitted": self.submitted,
            "completed": self.completed,
            "failed": self.failed,
            "rejected": self.rejected,
            "cancelled": self.cancelled,
            "expired": self.expired,
            "handed_off": self.handed_off,
            "retried": self.retried,
            "evicted": self.evicted,
            "lost": lost,
            "dispatched": dict(self.dispatched),
            "drain_ms": None if self.drain_s is None else self.drain_s * 1e3,
            "ttft_ms_p50": self.ttft_ms.percentile(50),
            "ttft_ms_p95": self.ttft_ms.percentile(95),
            "ttft_ms_p99": self.ttft_ms.percentile(99),
            "tpot_ms_p50": self.tpot_ms.percentile(50),
            "tokens_total": 0,  # filled by Router.snapshot with replica sums
        }


class Router:
    """N :class:`EngineReplica`\\ s behind one bounded admission queue."""

    def __init__(self, engines: List, config: Optional[RouterConfig] = None,
                 monitor=None):
        if not engines:
            raise ValueError("router needs at least one engine replica")
        self.config = cfg = config or RouterConfig()
        self.replicas = [EngineReplica(i, e, cfg.serving)
                         for i, e in enumerate(engines)]
        self.cap = self.replicas[0].scheduler.cap
        self.max_prompt_len = self.replicas[0].scheduler.executor.max_prompt_len
        self.telemetry = RouterTelemetry(monitor, len(self.replicas))
        self.health: Dict[int, ReplicaHealth] = {
            r.id: ReplicaHealth() for r in self.replicas}
        self.queue: Deque[RouterRequest] = deque()
        self.requests: List[RouterRequest] = []       # every admitted request
        self._dispatched: Dict[int, List[RouterRequest]] = {
            r.id: [] for r in self.replicas}
        self._affinity: Dict[str, int] = {}
        self._ids = itertools.count()
        self._draining = False
        self._drain_started: Optional[float] = None
        self._prev_sigterm = None
        self._tracer = get_tracer()

    # ---------------------------------------------------------------- frontend
    def submit(self, prompt, max_new_tokens: Optional[int] = None,
               eos_token_id: Optional[int] = None,
               deadline_s: Optional[float] = None, seed: int = 0,
               session: Optional[str] = None) -> RouterRequest:
        """Admit a request into the router queue. Raises ``ValueError`` on
        inadmissible shapes, :class:`QueueFullError` under backpressure, and
        :class:`RouterDrainingError` once draining has begun."""
        if self._draining:
            raise RouterDrainingError()
        prompt, max_new = validate_admission(
            prompt, max_new_tokens, self.config.serving.default_max_new_tokens,
            self.max_prompt_len, self.cap)
        if len(self.queue) >= self.config.max_queue:
            self.telemetry.on_rejected()
            raise QueueFullError(self.config.retry_after_s)
        rr = RouterRequest(id=next(self._ids), prompt=prompt,
                           max_new_tokens=max_new, eos_token_id=eos_token_id,
                           deadline_s=deadline_s, seed=int(seed),
                           session=session, arrival=time.monotonic())
        rr._root_span = self._tracer.begin(
            "request", cat=CAT_ROUTER, t0=rr.arrival, tid="router",
            attrs={"request_id": rr.id, "prompt_tokens": int(prompt.size),
                   **({"session": session} if session is not None else {})})
        self.queue.append(rr)
        self.requests.append(rr)
        self.telemetry.submitted += 1
        return rr

    @property
    def queue_depth(self) -> int:
        return len(self.queue)

    @property
    def draining(self) -> bool:
        return self._draining

    @property
    def busy(self) -> bool:
        return bool(self.queue) or any(self._dispatched[r.id]
                                       for r in self.replicas)

    def replica_state(self, replica_id: int) -> ReplicaState:
        return self.health[replica_id].state

    # -------------------------------------------------------------------- loop
    def step(self, now: Optional[float] = None) -> None:
        """One router iteration: sweep local queue, run the health state
        machine (evicting newly-DEAD replicas), dispatch, pump every non-DEAD
        replica one scheduler step, then harvest finished/errored attempts.

        ``now`` is injectable for deterministic state-machine tests (it drives
        deadline expiry and health-age checks; heartbeats themselves are always
        stamped with the real clock when a replica's step completes — rewind
        ``replica.last_heartbeat`` to simulate a flatline)."""
        now = time.monotonic() if now is None else now
        self._sweep_queue(now)
        self._health_sweep(now)
        if not self._draining:
            self._dispatch(now)
        self._pump(now)
        self._harvest(now)
        self.telemetry.on_step(len(self.queue), self.replicas, self.health)

    def run(self, max_steps: int = 100000) -> Dict:
        """Drive ``step()`` until every admitted request reaches a terminal
        state (or ``max_steps``); returns the telemetry snapshot."""
        steps = 0
        while self.busy and steps < max_steps:
            self.step()
            steps += 1
        return self.snapshot()

    def snapshot(self) -> Dict:
        snap = self.telemetry.snapshot()
        snap["tokens_total"] = sum(
            r.scheduler.telemetry.tokens_total for r in self.replicas)
        snap["replica_health"] = {r.id: self.health[r.id].state.value
                                  for r in self.replicas}
        if any(r.scheduler.prefix_cache is not None for r in self.replicas):
            snap["prefix_cache"] = self.prefix_cache_report()
        return snap

    def prefix_cache_report(self) -> Dict:
        """Per-replica prefix-cache reports + the aggregate hit accounting
        (caches are per-replica by design; no cross-replica coherence)."""
        per = {f"replica{r.id}": r.scheduler.prefix_cache_report()
               for r in self.replicas}
        hits = sum(p.get("hits", 0) for p in per.values())
        misses = sum(p.get("misses", 0) for p in per.values())
        return {
            "enabled": any(p.get("enabled") for p in per.values()),
            "hits": hits, "misses": misses,
            "hit_rate": hits / (hits + misses) if hits + misses else 0.0,
            "hit_tokens": sum(p.get("hit_tokens", 0) for p in per.values()),
            "cached_bytes": sum(p.get("cached_bytes", 0)
                                for p in per.values()),
            **per,
        }

    # ------------------------------------------------------------------- drain
    def begin_drain(self) -> None:
        """Close admission (signal-handler safe: flag only)."""
        if not self._draining:
            self._draining = True
            self._drain_started = time.monotonic()
            logger.info("[router] drain started: admission closed")

    def install_sigterm_drain(self):
        """Route SIGTERM to :meth:`begin_drain`; returns the previous handler
        (re-install it with ``signal.signal`` to uninstall)."""
        def _handler(signum, frame):
            self.begin_drain()
        self._prev_sigterm = signal.signal(signal.SIGTERM, _handler)
        return self._prev_sigterm

    def drain(self, mode: str = "handoff", max_steps: int = 100000
              ) -> List[Dict]:
        """Graceful drain: stop admitting, finish in-flight chunks, hand off.

        - ``mode="handoff"`` (SIGTERM default): one more step so current chunks
          complete (steps are chunk-granular — nothing is abandoned mid-chunk),
          then every in-flight request is evicted with its prefix and returned,
          together with the undispatched queue, as re-submittable specs
          (:meth:`RouterRequest.handoff_spec`) for the next router.
        - ``mode="complete"``: run dispatched requests to completion; hand off
          only the undispatched queue.
        """
        if mode not in ("handoff", "complete"):
            raise ValueError(f"unknown drain mode {mode!r}")
        self.begin_drain()
        t0 = time.monotonic()
        if mode == "complete":
            steps = 0
            while any(self._dispatched[r.id] for r in self.replicas) \
                    and steps < max_steps:
                self.step()
                steps += 1
        else:
            self.step()                      # finish the in-flight chunks
        handed: List[RouterRequest] = []
        for r in self.replicas:
            if not self._dispatched[r.id]:
                continue
            if self.health[r.id].state != ReplicaState.DEAD:
                r.scheduler.evict_all(reason="drain")
            for rr in self._dispatched[r.id]:
                self._absorb_prefix(rr)
                handed.append(rr)
            self._dispatched[r.id].clear()
        while self.queue:
            handed.append(self.queue.popleft())
        now = time.monotonic()
        specs = []
        for rr in handed:
            rr.state = RouterRequestState.HANDED_OFF
            rr.finish_reason = "drain"
            rr.finished_at = now
            # hand-off bypasses _finalize: commit the open spans here or the
            # drained requests' root/attempt lanes vanish from the trace
            if rr._attempt_span is not None:
                self._tracer.end_span(rr._attempt_span, t1=now,
                                      attrs={"outcome": "handed_off"})
                rr._attempt_span = None
            if rr._root_span is not None:
                self._tracer.end_span(
                    rr._root_span, t1=now,
                    attrs={"state": "handed_off", "reason": "drain",
                           "tokens": len(rr.tokens)})
                rr._root_span = None
            specs.append(rr.handoff_spec())
        self.telemetry.on_drain(now - t0, len(specs))
        # a drained router is about to exit: the monitor backends' tail events
        # (csv/jsonl buffers) must be durable before the process goes away
        m = self.telemetry.monitor
        if m is not None and hasattr(m, "flush"):
            m.flush()
        logger.info(f"[router] drain complete in {(now - t0) * 1e3:.1f} ms: "
                    f"{len(specs)} request(s) handed off")
        return specs

    # ------------------------------------------------------------------ sweeps
    def _expired(self, rr: RouterRequest, now: float) -> bool:
        return (rr.deadline_s is not None
                and now - rr.arrival > rr.deadline_s)

    def _sweep_queue(self, now: float) -> None:
        kept: Deque[RouterRequest] = deque()
        for rr in self.queue:
            if rr._cancel:
                self._finalize(rr, RouterRequestState.CANCELLED, "cancelled",
                               now)
            elif self._expired(rr, now):
                self._finalize(rr, RouterRequestState.EXPIRED, "deadline", now)
            else:
                kept.append(rr)
        self.queue = kept

    # ------------------------------------------------------------------ health
    def _health_sweep(self, now: float) -> None:
        cfg = self.config
        for r in self.replicas:
            h = self.health[r.id]
            if h.state in (ReplicaState.LIVE, ReplicaState.SUSPECT,
                           ReplicaState.RECOVERING):
                # RECOVERING replicas age too: a replica killed mid-probe must
                # flatline back to DEAD (and release its probe request), not
                # hold the probe hostage forever. Age is pump-relative: a
                # router that idled (no pumps) learned nothing — only failing
                # to respond WHILE pumped counts as a missed heartbeat.
                age = max(0.0, r.last_pump_attempt - r.last_heartbeat)
                if age > cfg.dead_after_s:
                    self._mark_dead(r, now, f"missed heartbeats for {age:.2f}s")
                elif age > cfg.suspect_after_s:
                    if h.state == ReplicaState.LIVE:
                        self._transition(r.id, ReplicaState.SUSPECT)
                elif h.state == ReplicaState.SUSPECT:
                    self._transition(r.id, ReplicaState.LIVE)   # recovered
            elif h.state == ReplicaState.DEAD:
                if r.alive and h.died_at is not None \
                        and now - h.died_at >= cfg.recover_after_s:
                    h.probe_request = None
                    self._transition(r.id, ReplicaState.RECOVERING)

    def _transition(self, replica_id: int, new: ReplicaState) -> None:
        h = self.health[replica_id]
        old, h.state = h.state, new
        if old != new:
            logger.info(f"[router] replica {replica_id}: {old.value} -> "
                        f"{new.value}")
            self.telemetry.on_transition(replica_id, old, new)

    def _mark_dead(self, replica, now: float, why: str) -> None:
        h = self.health[replica.id]
        if h.state == ReplicaState.DEAD:
            return
        logger.warning(f"[router] replica {replica.id} declared DEAD ({why}); "
                       f"evicting {len(self._dispatched[replica.id])} "
                       "in-flight request(s)")
        self._transition(replica.id, ReplicaState.DEAD)
        h.died_at = now
        h.probe_request = None
        h.consecutive_failures = 0
        # affinity must not keep steering sessions at a corpse
        for sess in [s for s, rid in self._affinity.items()
                     if rid == replica.id]:
            del self._affinity[sess]
        if replica.alive:
            # circuit-breaker death: the process is responsive, release its
            # slots/pool properly. (A killed replica's device state is gone
            # with the process; the host-side prefixes below are all we need.)
            replica.scheduler.evict_all(reason="replica-dead")
        for rr in self._dispatched[replica.id]:
            self._requeue(rr, replica.id, now, breaker=False)
        self._dispatched[replica.id].clear()

    def _health_failure(self, replica_id: int, now: float) -> None:
        h = self.health[replica_id]
        h.consecutive_failures += 1
        if h.state == ReplicaState.RECOVERING:
            # half-open probe failed: back to DEAD, restart the recovery clock
            self._mark_dead(self._replica(replica_id), now, "probe failed")
        elif h.consecutive_failures >= self.config.breaker_threshold:
            self._mark_dead(self._replica(replica_id), now,
                            f"circuit breaker: {h.consecutive_failures} "
                            "consecutive failures")

    def _health_success(self, replica_id: int) -> None:
        h = self.health[replica_id]
        h.consecutive_failures = 0
        if h.state == ReplicaState.RECOVERING:
            h.probe_request = None
            self._transition(replica_id, ReplicaState.LIVE)  # breaker closes

    def _replica(self, replica_id: int) -> EngineReplica:
        return self.replicas[replica_id]

    # ---------------------------------------------------------------- dispatch
    def _usable(self, replica: EngineReplica, rr: RouterRequest) -> bool:
        h = self.health[replica.id]
        if h.state == ReplicaState.LIVE:
            return replica.available > 0
        if h.state == ReplicaState.RECOVERING:
            return h.probe_request is None and replica.available > 0
        return False

    def _pick(self, rr: RouterRequest) -> Optional[EngineReplica]:
        cands = [r for r in self.replicas if self._usable(r, rr)]
        if not cands:
            return None
        non_excluded = [r for r in cands if r.id not in rr.excluded]
        pool = non_excluded or cands       # all excluded → retry anywhere sane
        if rr.session is not None:
            pinned = self._affinity.get(rr.session)
            for r in pool:
                if r.id == pinned:
                    return r
        return min(pool, key=lambda r: (r.outstanding, r.id))

    def _dispatch(self, now: float) -> None:
        cfg = self.config
        for rr in list(self.queue):
            target = self._pick(rr)
            if target is None:
                continue                   # exclusions differ per request
            deadline = None
            if rr.deadline_s is not None:
                deadline = rr.deadline_s - (now - rr.arrival)
                if deadline <= 0:
                    self.queue.remove(rr)
                    self._finalize(rr, RouterRequestState.EXPIRED, "deadline",
                                   now)
                    continue
            prompt = np.concatenate(
                [rr.prompt, np.asarray(rr.tokens, np.int32)]) \
                if rr.tokens else rr.prompt
            # dispatch-attempt span: retries show as LINKED spans on the same
            # trace id — the retry replica id + the evicted attempt's span id
            # ride the attrs, so a killed request's original and retry lanes
            # join in one Perfetto query
            att = self._tracer.start_span(
                "attempt", parent=rr._root_span, cat=CAT_ROUTER,
                attrs={"replica": target.id, "attempt": rr.attempts + 1,
                       "prefix_tokens": len(rr.tokens),
                       **({"retry": True, "retry_replica_id": target.id,
                           "retry_of": rr._prev_attempt_id}
                          if rr.retried else {})})
            att_ctx = att.ctx if att is not None else None

            def attempt(t=target, p=prompt, r=rr, d=deadline, c=att_ctx):
                fault_point("serving.router.dispatch")
                return t.submit(p, max_new_tokens=r.remaining_budget,
                                eos_token_id=r.eos_token_id, deadline_s=d,
                                seed=r.seed, trace_ctx=c)

            try:
                inner = retry_with_backoff(attempt,
                                           retries=cfg.dispatch_retries,
                                           base_delay=cfg.retry_base_delay)
            except QueueFullError:
                self._tracer.end_span(att, attrs={"outcome": "queue_full"})
                continue                   # replica raced full; try next tick
            except Exception as e:
                logger.warning(f"[router] dispatch of request {rr.id} to "
                               f"replica {target.id} failed: "
                               f"{type(e).__name__}: {e}")
                self._tracer.end_span(att, attrs={"outcome": "dispatch_error",
                                                  "error": type(e).__name__})
                rr.excluded.add(target.id)
                self._health_failure(target.id, now)
                continue
            self.queue.remove(rr)
            rr.state = RouterRequestState.DISPATCHED
            rr.attempts += 1
            rr.replica_id = target.id
            rr.inner = inner
            rr._attempt_span = att
            if rr._cancel:                 # cancel landed between ticks
                inner.cancel()
            self._dispatched[target.id].append(rr)
            if rr.session is not None:
                self._affinity[rr.session] = target.id
            h = self.health[target.id]
            if h.state == ReplicaState.RECOVERING:
                h.probe_request = rr.id
            self.telemetry.on_dispatch(target.id)

    # -------------------------------------------------------------------- pump
    def _pump(self, now: float) -> None:
        attempted = [r for r in self.replicas
                     if self.health[r.id].state != ReplicaState.DEAD]
        pumped = [r for r in attempted if r.step()]
        # one shared post-pump stamp: the pump is serial, so a co-replica's slow
        # step (first-dispatch compile, long chunk) must not age the heartbeats
        # of replicas that already responded this round
        t = time.monotonic()
        for r in attempted:
            r.last_pump_attempt = t
        for r in pumped:
            r.last_heartbeat = t

    # ----------------------------------------------------------------- harvest
    def _absorb_prefix(self, rr: RouterRequest) -> None:
        """Fold the current attempt's tokens into the cross-attempt stream."""
        if rr.inner is not None:
            rr.tokens.extend(int(t) for t in rr.inner.tokens)
            if rr.first_token_at is None and rr.inner.first_token_at is not None:
                rr.first_token_at = rr.inner.first_token_at
                rr.ttft = rr.first_token_at - rr.arrival
                rr.prefix_hit_tokens = getattr(rr.inner, "prefix_hit_tokens",
                                               0)
            inner_span = getattr(rr.inner, "_span", None)
            if inner_span is not None:
                # a killed replica never finalizes its handle: the scheduler-
                # side request span would stay open forever — close it here so
                # the original replica's lane is complete in the trace
                self._tracer.end_span(
                    inner_span, attrs={"state": "abandoned",
                                       "reason": "absorbed-by-router"})
                rr.inner._span = None
            rr.inner = None

    def _harvest(self, now: float) -> None:
        for r in self.replicas:
            if self.health[r.id].state == ReplicaState.DEAD:
                continue                   # handled by _mark_dead eviction
            still: List[RouterRequest] = []
            failures = 0
            h = self.health[r.id]
            for rr in self._dispatched[r.id]:
                inner = rr.inner
                if inner is None or not inner.done:
                    still.append(rr)
                    continue
                if inner.state == RequestState.FINISHED:
                    self._finalize(rr, RouterRequestState.FINISHED,
                                   inner.finish_reason, now)
                    self._health_success(r.id)
                elif inner.state == RequestState.EXPIRED:
                    self._finalize(rr, RouterRequestState.EXPIRED,
                                   "deadline", now)
                    if h.probe_request == rr.id:
                        h.probe_request = None
                elif inner.state == RequestState.CANCELLED \
                        and inner.finish_reason == "cancelled":
                    self._finalize(rr, RouterRequestState.CANCELLED,
                                   "cancelled", now)
                    if h.probe_request == rr.id:
                        h.probe_request = None
                else:
                    # replica-side failure (finish_reason "error") or eviction:
                    # checkpointless retry with the generated-so-far prefix.
                    # Breaker accounting is DEFERRED below — _mark_dead mutates
                    # the very list this loop walks.
                    failures += 1
                    self._requeue(rr, r.id, now, breaker=False)
            self._dispatched[r.id] = still
            for _ in range(failures):
                self._health_failure(r.id, now)

    def _requeue(self, rr: RouterRequest, replica_id: int, now: float,
                 breaker: bool) -> None:
        self._absorb_prefix(rr)
        if rr._attempt_span is not None:
            rr._prev_attempt_id = rr._attempt_span.span_id
            self._tracer.end_span(rr._attempt_span,
                                  attrs={"outcome": "evicted",
                                         "evicted_from_replica": replica_id})
            rr._attempt_span = None
        rr.evictions += 1
        rr.excluded.add(replica_id)
        self.telemetry.on_evicted()
        if breaker:
            self._health_failure(replica_id, now)
        if rr._cancel:
            self._finalize(rr, RouterRequestState.CANCELLED, "cancelled", now)
            return
        if self._expired(rr, now):
            self._finalize(rr, RouterRequestState.EXPIRED, "deadline", now)
            return
        if rr.remaining_budget < 1:
            # the dying replica delivered the full budget but never finalized;
            # everything generated is in hand — this is a completion
            self._finalize(rr, RouterRequestState.FINISHED, "length", now)
            return
        if rr.attempts >= self.config.max_attempts:
            logger.error(f"[router] request {rr.id}: retry budget exhausted "
                         f"after {rr.attempts} attempt(s)")
            self._finalize(rr, RouterRequestState.FAILED, "error", now)
            return
        if rr.prompt.size + len(rr.tokens) > self.max_prompt_len:
            # retry needs prompt+prefix to re-prefill; with the default
            # max_prompt_len (cap-1) this cannot trip, but a tighter configured
            # bound can — fail loudly rather than mis-serve
            logger.error(f"[router] request {rr.id}: prefix "
                         f"{len(rr.tokens)} tokens no longer fits "
                         f"max_prompt_len={self.max_prompt_len}; cannot retry")
            self._finalize(rr, RouterRequestState.FAILED, "error", now)
            return
        rr.state = RouterRequestState.QUEUED
        rr.replica_id = None
        rr.retried += 1
        self.telemetry.on_retried()
        self.queue.appendleft(rr)          # retries go to the head: oldest first

    # --------------------------------------------------------------- lifecycle
    def _finalize(self, rr: RouterRequest, state: RouterRequestState,
                  reason: Optional[str], now: float) -> None:
        self._absorb_prefix(rr)
        rr.state = state
        rr.finish_reason = reason
        rr.finished_at = now
        if (rr.first_token_at is not None and len(rr.tokens) > 1
                and now > rr.first_token_at):
            rr.tpot = (now - rr.first_token_at) / (len(rr.tokens) - 1)
        if rr._attempt_span is not None:
            self._tracer.end_span(rr._attempt_span, t1=now,
                                  attrs={"outcome": state.value})
            rr._attempt_span = None
        if rr._root_span is not None:
            self._tracer.end_span(
                rr._root_span, t1=now,
                attrs={"state": state.value, "reason": reason,
                       "tokens": len(rr.tokens), "attempts": rr.attempts,
                       "retried": rr.retried})
            rr._root_span = None
        self.telemetry.on_finished(rr)
