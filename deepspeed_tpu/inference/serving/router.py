"""Multi-replica serving front door: health-supervised routing over N engines.

The layer above :class:`ContinuousBatchingScheduler`: "millions of users" needs
N engine replicas behind ONE bounded admission queue, and it needs replica death
to be an eviction-and-retry event, not a request-loss event. Following the
fail-fast discipline of large-scale continuous-batching serving systems (and the
elasticity pillar on the training side), **requests — not checkpoints — are the
unit of recovery on the inference path**:

- **admission** — one bounded router queue; a full queue raises
  :class:`~.scheduler.QueueFullError` with a ``retry_after`` hint (rejected,
  never dropped); a draining router raises :class:`RouterDrainingError`;
- **dispatch** — least-outstanding-slots across healthy replicas, with session
  affinity (requests carrying the same ``session`` stick to one replica — the
  hook prefix-cache locality hangs off) that yields the moment the pinned
  replica leaves ``LIVE``;
- **health** — each replica runs a state machine
  ``LIVE → SUSPECT → DEAD → RECOVERING (→ LIVE)`` driven by three signals:
  heartbeats (every successful pump step), per-chunk watchdog deadlines
  (:class:`~.executor.ChunkTimeoutError` surfacing as request errors), and a
  consecutive-failure circuit breaker. ``DEAD`` → half-open probe after
  ``recover_after_s`` (one request; success closes the breaker);
- **checkpointless retry** — a dead replica's in-flight requests are evicted
  *with their generated-so-far prefixes* and re-enqueued as
  ``prompt + prefix`` / remaining budget (bounded attempts, per-request replica
  exclusion lists). Greedy retry is prefix-consistent: the final token stream is
  bit-identical to an unkilled run;
- **drain** — SIGTERM (``install_sigterm_drain``) stops admission, lets in-flight
  chunks finish (steps are chunk-granular, so no chunk is ever abandoned
  half-way), evicts what remains with prefixes and hands the queue off as
  re-submittable specs;
- **elasticity** (PR 12, ``autoscale.py``) — the replica set is dynamic:
  ``add_replica`` attaches a new replica through the RECOVERING warm-probe
  path, ``begin_retire`` drains one out gracefully (in-flight work migrates
  with prefixes at the grace bound — the same bit-exact continuation as death
  retry), and admission is SLO-aware: a load-adaptive ``retry_after`` rides
  every rejection, low-priority requests defer on a degraded router, and
  requests whose estimated completion misses their deadline are shed *at
  admission* (``AdmissionShedError``) instead of expiring after burning
  decode steps. The ladder is
  healthy → defer-low → shed-infeasible → admission-closed
  (:class:`DegradationRung`), observable as ``router/degradation_rung``.

Replicas come in two forms behind one protocol: in-process
(:class:`EngineReplica`: one engine + one scheduler, death/stall simulated
through ``kill()``/``stall_next`` and the fault registry — but the pump is
SERIAL, so replica count adds no machine parallelism) and **process-parallel
hosts** (:class:`~.host.HostedReplica`: the same stack in a supervised child
process over the ``subproc.py`` JSONL pipe — async submit/harvest, heartbeats
stamped from child step messages, real SIGKILL/SIGSTOP chaos, bounded-backoff
respawn via :class:`~.host.ReplicaSupervisor`). A router may mix both; either
way the router-side view of a replica is the streamed token prefixes this
module treats as the only recoverable state. The ``DS_TPU_FAULT_SPEC`` env
contract (``utils.fault_injection``) carries seeded fault schedules into the
child processes.

Threading: like the scheduler, the router is single-threaded — drive ``step()``
/ ``run()`` from one thread. ``RouterRequest.cancel`` and ``begin_drain`` only
set flags and are safe from signal handlers / other threads.
"""

import itertools
import signal
import threading
import time
from collections import deque
from dataclasses import dataclass, field
from enum import Enum
from typing import Deque, Dict, List, Optional, Set

import numpy as np

from ...observability import flight as obs_flight
from ...observability.metrics import Histogram, RegistryFeed
from ...observability.trace import CAT_ROUTER, get_tracer
from ...utils.fault_injection import fault_point, retry_with_backoff
from ...utils.logging import logger
from .prefix_cache import match_from_digests
from .scheduler import (ContinuousBatchingScheduler, QueueFullError,
                        RequestState, ServingConfig, validate_admission)
from .telemetry import adaptive_retry_after


class ReplicaState(Enum):
    LIVE = "live"
    SUSPECT = "suspect"          # missed heartbeats; no new dispatches
    DEAD = "dead"                # evicted; circuit open
    RECOVERING = "recovering"    # half-open: one probe request at a time
    RETIRING = "retiring"        # scale-down drain: no new dispatches, in-
    #   flight work finishes (or migrates with prefixes at the grace bound)

    @property
    def code(self) -> int:
        """Stable numeric code for monitor streams."""
        return {"live": 0, "suspect": 1, "dead": 2, "recovering": 3,
                "retiring": 4}[self.value]


class DegradationRung(Enum):
    """The load-shedding ladder, healthy first. Each rung keeps everything the
    rungs above it do and adds one cheaper-than-serving refusal:

    - ``HEALTHY`` — admit everything admissible;
    - ``DEFER_LOW`` — low-priority requests (``priority < 0``) are deferred
      with a retry-after hint (they come back when load drops);
    - ``SHED_INFEASIBLE`` — the SLO admission check tightens to
      ``shed_margin`` of the deadline (shed earlier, before the queue makes
      every estimate a miss);
    - ``ADMISSION_CLOSED`` — every submission is rejected with a retry-after
      hint (the queue is at/over ``close_fill``, or the router is draining).
    """
    HEALTHY = 0
    DEFER_LOW = 1
    SHED_INFEASIBLE = 2
    ADMISSION_CLOSED = 3


class RouterRequestState(Enum):
    QUEUED = "queued"
    DISPATCHED = "dispatched"
    FINISHED = "finished"
    CANCELLED = "cancelled"
    EXPIRED = "expired"
    FAILED = "failed"            # retry budget exhausted
    HANDED_OFF = "handed_off"    # drained: returned to the caller as a spec


class ReplicaDeadError(RuntimeError):
    """Dispatch attempted against a replica that is no longer serving."""


class RouterDrainingError(RuntimeError):
    """The router is draining (SIGTERM): admission is closed."""

    def __init__(self):
        super().__init__("router is draining; admission closed")


class AdmissionShedError(QueueFullError):
    """SLO-aware admission shed: the request's estimated completion misses its
    deadline, so it is refused *before prefill* instead of expiring after
    burning decode steps. Subclasses :class:`QueueFullError` so existing
    backpressure clients keep working; ``retry_after`` is the load-adaptive
    hint (the estimate may be feasible once the queue drains)."""

    def __init__(self, retry_after: float, estimate_s: Optional[float] = None,
                 deadline_s: Optional[float] = None):
        super().__init__(retry_after)
        self.estimate_s = estimate_s
        self.deadline_s = deadline_s
        self.args = (f"shed at admission: estimated completion "
                     f"{estimate_s if estimate_s is None else round(estimate_s, 3)}s "
                     f"exceeds deadline {deadline_s}s; "
                     f"retry after {retry_after:.3f}s",)


class AdmissionDeferredError(QueueFullError):
    """Degradation-ladder defer: a low-priority request turned away while the
    router is at ``DEFER_LOW`` or worse. Come back after ``retry_after``."""

    def __init__(self, retry_after: float):
        super().__init__(retry_after)
        self.args = ("low-priority request deferred under load; "
                     f"retry after {retry_after:.3f}s",)


@dataclass
class RouterConfig:
    max_queue: int = 256                 # router admission bound
    suspect_after_s: float = 2.0         # missed-heartbeat → SUSPECT
    dead_after_s: float = 6.0            # missed-heartbeat → DEAD (evict)
    recover_after_s: float = 10.0        # DEAD → RECOVERING probe window
    breaker_threshold: int = 3           # consecutive failures → DEAD
    max_attempts: int = 3                # dispatches per request (1 + retries)
    dispatch_retries: int = 1            # retry_with_backoff budget per dispatch
    retry_base_delay: float = 0.01
    retry_after_s: float = 0.25          # backpressure hint FLOOR; the emitted
    #   hint is load-adaptive (queue depth / observed drain rate), capped below
    retry_after_max_s: float = 8.0
    # --- SLO-aware admission + degradation ladder (see DegradationRung) ---
    slo_admission: bool = False          # shed infeasible-deadline requests
    #   at admission (needs a warmed-up estimator; never sheds blind)
    defer_fill: float = 0.75             # queue fill → DEFER_LOW rung
    shed_fill: float = 0.9               # queue fill → SHED_INFEASIBLE rung
    close_fill: float = 1.0              # queue fill → ADMISSION_CLOSED rung
    shed_margin: float = 0.8             # at SHED_INFEASIBLE the estimate must
    #   fit inside shed_margin * deadline (shed earlier under pressure)
    retire_grace_s: float = 5.0          # scale-down: in-flight drain window
    #   before the remainder migrates with prefixes (begin_retire default)
    # --- prefix-aware dispatch (fleet KV economy) ---
    prefix_aware_routing: bool = False   # score replicas by expected
    #   prefill-tokens-saved (in-process peek / hosted heartbeat gossip)
    #   against outstanding load; session affinity demotes from the only
    #   locality signal to a score tiebreaker. Off = legacy affinity-pin +
    #   least-outstanding.
    prefix_route_load_weight: float = 32.0   # dispatch score is
    #   saved_tokens - weight * outstanding: one queued/running request
    #   outweighs this many reusable prefix tokens
    serving: ServingConfig = field(default_factory=ServingConfig)  # per replica


@dataclass
class RouterRequest:
    """Caller's view of a routed request. ``tokens`` accumulates across retry
    attempts; ``prompt`` stays the ORIGINAL prompt (retries re-prefill
    ``prompt + tokens`` internally)."""
    id: int
    prompt: np.ndarray
    max_new_tokens: int
    eos_token_id: Optional[int]
    deadline_s: Optional[float]
    seed: int
    session: Optional[str]
    arrival: float
    priority: int = 0                 # < 0 = deferrable under the ladder
    state: RouterRequestState = RouterRequestState.QUEUED
    tokens: List[int] = field(default_factory=list)
    finish_reason: Optional[str] = None
    attempts: int = 0                 # dispatches so far
    retried: int = 0                  # re-enqueues after eviction/failure
    evictions: int = 0
    prefix_hit_tokens: int = 0        # from the attempt that produced the
    #   first token (the one TTFT measures) — loadgen splits TTFT on this
    expected_saved_tokens: int = 0    # prefix-aware dispatch: the winner's
    #   predicted prefill-tokens-saved at pick time (telemetry only)
    excluded: Set[int] = field(default_factory=set)   # replica exclusion list
    replica_id: Optional[int] = None
    inner: Optional[object] = None    # current attempt's RequestHandle
    ttft: Optional[float] = None
    tpot: Optional[float] = None
    first_token_at: Optional[float] = None
    finished_at: Optional[float] = None
    _cancel: bool = False
    _root_span: Optional[object] = None       # request-scoped trace root
    _attempt_span: Optional[object] = None    # current dispatch attempt
    _prev_attempt_id: Optional[str] = None    # link target for retry spans

    def cancel(self) -> None:
        self._cancel = True
        if self.inner is not None:
            self.inner.cancel()

    @property
    def done(self) -> bool:
        return self.state in (RouterRequestState.FINISHED,
                              RouterRequestState.CANCELLED,
                              RouterRequestState.EXPIRED,
                              RouterRequestState.FAILED,
                              RouterRequestState.HANDED_OFF)

    def result(self) -> np.ndarray:
        """All generated tokens across attempts — including the in-flight
        attempt's live progress (partial if cancelled/evicted)."""
        cur = list(self.tokens)
        if self.inner is not None:
            cur.extend(int(t) for t in self.inner.tokens)
        return np.asarray(cur, dtype=np.int32)

    def output_ids(self) -> np.ndarray:
        return np.concatenate([self.prompt.astype(np.int32), self.result()])

    @property
    def remaining_budget(self) -> int:
        return self.max_new_tokens - len(self.tokens)

    def handoff_spec(self) -> Dict:
        """Re-submittable form for drain hand-off: the generated prefix is
        folded into the prompt so any router can continue the request."""
        return {"id": self.id,
                "prompt": [int(t) for t in self.prompt] + list(self.tokens),
                "prefix_len": len(self.tokens),
                "max_new_tokens": self.remaining_budget,
                "eos_token_id": self.eos_token_id,
                "deadline_s": self.deadline_s, "seed": self.seed,
                "session": self.session, "priority": self.priority}


@dataclass
class ReplicaHealth:
    state: ReplicaState = ReplicaState.LIVE
    consecutive_failures: int = 0
    died_at: Optional[float] = None
    probe_request: Optional[int] = None   # RouterRequest.id of half-open probe
    # scale-down lifecycle: `retiring` survives a mid-drain death (state DEAD)
    # so the retire sweep still detaches the corpse after its eviction
    retiring: bool = False
    retiring_since: Optional[float] = None
    retire_grace_s: float = 5.0


class EngineReplica:
    """In-process replica: one engine + one continuous-batching scheduler.

    Health signals the router reads: ``last_heartbeat`` (advanced by every
    successful :meth:`step`), slot/queue occupancy, and per-request outcomes.
    ``kill()`` simulates abrupt replica death — heartbeats stop, dispatches
    raise — and ``revive()`` brings the process back for the RECOVERING probe.
    """

    def __init__(self, replica_id: int, engine,
                 serving_config: Optional[ServingConfig] = None):
        self.id = int(replica_id)
        self.engine = engine
        self.scheduler = ContinuousBatchingScheduler(engine, serving_config)
        self.last_heartbeat = time.monotonic()
        # last time the router TRIED to pump this replica: heartbeat age is
        # measured against this, not wall time — an idle router that slept
        # between requests has no evidence of death, only a replica that fails
        # to respond while being pumped does
        self.last_pump_attempt = self.last_heartbeat
        self._killed = False

    # ------------------------------------------------------------------ chaos
    def kill(self) -> None:
        """Simulate abrupt death: no more heartbeats, no more work."""
        self._killed = True

    def revive(self) -> None:
        """Bring the replica back, modeling a FRESH process: any scheduler
        state from before the kill is discarded (the router already evicted
        and requeued those requests — leaving them would resume zombie decode
        of work now owned by other replicas), and the prefix cache is cleared
        — a real process death loses its HBM slabs, so the in-process
        simulation must not resurrect them."""
        self._killed = False
        if self.scheduler.busy:
            self.scheduler.evict_all(reason="revive")
        if self.scheduler.prefix_cache is not None:
            self.scheduler.prefix_cache.clear()
        self.last_heartbeat = time.monotonic()
        self.last_pump_attempt = self.last_heartbeat

    @property
    def alive(self) -> bool:
        return not self._killed

    # ------------------------------------------------------------------- work
    def step(self) -> bool:
        """One scheduler step + heartbeat; returns True when the replica
        responded (i.e. it is not killed).

        The heartbeat is stamped AFTER the step completes, with the real clock:
        a step that spends seconds inside a first-dispatch XLA compile must not
        read as a flatline (the router sweeps health at step start, so a
        start-of-step stamp would age by the whole compile)."""
        if self._killed:
            return False
        self.scheduler.step()
        self.last_heartbeat = time.monotonic()
        return True

    def submit(self, *args, **kwargs):
        if self._killed:
            raise ReplicaDeadError(f"replica {self.id} is dead")
        return self.scheduler.submit(*args, **kwargs)

    # ---------------------------------------------------------------- metrics
    @property
    def free_slots(self) -> int:
        return self.scheduler.executor.pool.free_slots

    @property
    def queued(self) -> int:
        return self.scheduler.queue_depth

    @property
    def running(self) -> int:
        return len(self.scheduler.active_requests)

    @property
    def outstanding(self) -> int:
        return self.running + self.queued

    @property
    def available(self) -> int:
        """Slots this replica could start on right now (free minus already
        queued-at-replica) — the router's least-outstanding-slots currency."""
        return self.free_slots - self.queued


class RouterTelemetry:
    """Router-level metrics through MonitorMaster + aggregate snapshot.

    Monitor tags: ``router/queue_depth``, ``router/retried_total``,
    ``router/evicted_total``, ``router/completed_total``,
    ``router/rejected_total``, ``router/replica{i}/health`` (state code),
    ``router/replica{i}/outstanding``, ``router/replica{i}/prefix_hit_rate``
    (prefix cache enabled only — caches are strictly per-replica, so hit rate
    is a per-replica property that session affinity concentrates),
    ``router/drain_ms``, per-request ``router/ttft_ms`` / ``router/tpot_ms``.
    """

    def __init__(self, monitor=None, n_replicas: int = 1):
        self.monitor = monitor
        self.n_replicas = n_replicas
        self._tick = 0
        self._finished_idx = 0
        self.submitted = 0
        self.completed = 0
        self.failed = 0
        self.rejected = 0
        self.cancelled = 0
        self.expired = 0
        self.handed_off = 0
        self.retried = 0
        self.evicted = 0
        self.shed = 0                     # refused at admission: infeasible SLO
        self.deferred = 0                 # refused at admission: low priority
        self.dispatched: Dict[int, int] = {i: 0 for i in range(n_replicas)}
        # prefix-aware dispatch accounting: dispatches won on a non-zero
        # expected-saved score, and the cumulative predicted tokens saved
        self.prefix_routed = 0
        self.prefix_saved_tokens = 0
        self.transitions: List = []       # (tick, replica, old, new)
        # bounded distributions (same O(1)-memory contract as ServingTelemetry)
        self.ttft_ms = Histogram()
        self.tpot_ms = Histogram()
        # recent-TTFT window: the autoscaler's responsive p95 signal (the
        # cumulative histogram above never forgets a cold start)
        self.recent_ttft_ms: Deque[float] = deque(maxlen=64)
        # per-emitter feed: cumulative *_total counters contribute deltas so
        # successive routers in one process sum in /metrics
        self._feed = RegistryFeed()
        self.drain_s: Optional[float] = None

    def _write(self, events):
        self._feed.record_events(events)   # process registry (/metrics)
        if self.monitor is not None and getattr(self.monitor, "enabled", False):
            self.monitor.write_events(events)

    def on_step(self, queue_depth: int, replicas, health,
                rung: int = 0, kv_economy=None) -> None:
        self._tick += 1
        live = sum(1 for r in replicas
                   if health[r.id].state != ReplicaState.DEAD)
        ev = [("router/queue_depth", float(queue_depth), self._tick),
              ("router/retried_total", float(self.retried), self._tick),
              ("router/evicted_total", float(self.evicted), self._tick),
              ("router/completed_total", float(self.completed), self._tick),
              ("router/rejected_total", float(self.rejected), self._tick),
              ("router/shed_total", float(self.shed), self._tick),
              ("router/deferred_total", float(self.deferred), self._tick),
              ("router/deadline_miss_total", float(self.expired), self._tick),
              ("router/degradation_rung", float(rung), self._tick),
              ("router/live_replicas", float(live), self._tick)]
        for r in replicas:
            ev.append((f"router/replica{r.id}/health",
                       float(health[r.id].state.code), self._tick))
            ev.append((f"router/replica{r.id}/outstanding",
                       float(r.outstanding), self._tick))
            if r.scheduler.prefix_cache is not None:
                ev.append((f"router/replica{r.id}/prefix_hit_rate",
                           float(r.scheduler.prefix_hit_rate), self._tick))
        if kv_economy is not None:
            ev += [("router/fleet_prefix_hit_rate",
                    float(kv_economy["fleet_hit_rate"]), self._tick),
                   ("router/prefix_routed_total",
                    float(self.prefix_routed), self._tick),
                   ("router/prefix_saved_tokens_total",
                    float(self.prefix_saved_tokens), self._tick)]
        self._write(ev)

    def on_transition(self, replica_id: int, old: ReplicaState,
                      new: ReplicaState) -> None:
        self.transitions.append((self._tick, replica_id, old, new))
        self._write([(f"router/replica{replica_id}/health", float(new.code),
                      self._tick)])

    def on_dispatch(self, replica_id: int) -> None:
        self.dispatched[replica_id] = self.dispatched.get(replica_id, 0) + 1

    def on_prefix_route(self, saved_tokens: int) -> None:
        """A dispatch won on a non-zero expected-prefix-saved score."""
        self.prefix_routed += 1
        self.prefix_saved_tokens += int(saved_tokens)

    def on_rejected(self) -> None:
        self.rejected += 1

    def on_shed(self) -> None:
        self.shed += 1

    def on_deferred(self) -> None:
        self.deferred += 1

    def on_evicted(self, n: int = 1) -> None:
        self.evicted += n

    def on_retried(self) -> None:
        self.retried += 1

    def on_drain(self, seconds: float, handed_off: int) -> None:
        self.drain_s = seconds
        self.handed_off += handed_off
        self._write([("router/drain_ms", seconds * 1e3, self._tick),
                     ("router/handed_off_total", float(self.handed_off),
                      self._tick)])

    def on_finished(self, rr: RouterRequest) -> None:
        st = rr.state
        if st == RouterRequestState.CANCELLED:
            self.cancelled += 1
            return
        if st == RouterRequestState.EXPIRED:
            self.expired += 1
            return
        if st == RouterRequestState.FAILED:
            self.failed += 1
            return
        self.completed += 1
        self._finished_idx += 1
        ev = []
        if rr.ttft is not None:
            self.ttft_ms.observe(rr.ttft * 1e3)
            self.recent_ttft_ms.append(rr.ttft * 1e3)
            ev.append(("router/ttft_ms", rr.ttft * 1e3, self._finished_idx))
        if rr.tpot is not None:
            self.tpot_ms.observe(rr.tpot * 1e3)
            ev.append(("router/tpot_ms", rr.tpot * 1e3, self._finished_idx))
        self._write(ev)

    def snapshot(self) -> Dict:
        # "lost" is the no-silent-loss invariant: every admitted request must
        # end completed, caller-cancelled, expired, or explicitly handed off.
        # FAILED (retry budget exhausted) counts as lost — it was admitted and
        # not served.
        lost = self.submitted - self.completed - self.cancelled \
            - self.expired - self.handed_off
        return {
            "submitted": self.submitted,
            "completed": self.completed,
            "failed": self.failed,
            "rejected": self.rejected,
            "cancelled": self.cancelled,
            "expired": self.expired,
            "handed_off": self.handed_off,
            "retried": self.retried,
            "evicted": self.evicted,
            "shed": self.shed,
            "deferred": self.deferred,
            "deadline_missed": self.expired,
            "lost": lost,
            "dispatched": dict(self.dispatched),
            "drain_ms": None if self.drain_s is None else self.drain_s * 1e3,
            "ttft_ms_p50": self.ttft_ms.percentile(50),
            "ttft_ms_p95": self.ttft_ms.percentile(95),
            "ttft_ms_p99": self.ttft_ms.percentile(99),
            "tpot_ms_p50": self.tpot_ms.percentile(50),
            "tokens_total": 0,  # filled by Router.snapshot with replica sums
        }


class Router:
    """N :class:`EngineReplica`\\ s behind one bounded admission queue."""

    def __init__(self, engines: List, config: Optional[RouterConfig] = None,
                 monitor=None):
        if not engines:
            raise ValueError("router needs at least one engine replica")
        self.config = cfg = config or RouterConfig()
        self.replicas = [self._as_replica(e, i)
                         for i, e in enumerate(engines)]
        self.cap = self.replicas[0].scheduler.cap
        self.max_prompt_len = self.replicas[0].scheduler.executor.max_prompt_len
        self.telemetry = RouterTelemetry(monitor, len(self.replicas))
        self.health: Dict[int, ReplicaHealth] = {
            r.id: ReplicaHealth() for r in self.replicas}
        self.queue: Deque[RouterRequest] = deque()
        self.requests: List[RouterRequest] = []       # every admitted request
        self._dispatched: Dict[int, List[RouterRequest]] = {
            r.id: [] for r in self.replicas}
        self._affinity: Dict[str, int] = {}
        self._ids = itertools.count()
        self._next_replica_id = len(self.replicas)
        self.retired: List[int] = []          # replica ids detached by retire
        self._detached_tokens = 0             # tokens served by detached
        #   replicas: snapshot()'s tokens_total must survive a scale-down
        self._draining = False
        self._drain_started: Optional[float] = None
        self._prev_sigterm = None
        self._tracer = get_tracer()
        self._rung = DegradationRung.HEALTHY
        # online service-time model: feeds the SLO admission check and the
        # load-adaptive retry_after hint (local import: autoscale.py imports
        # this module at top level)
        from .autoscale import ServiceTimeEstimator
        self.estimator = ServiceTimeEstimator()

    # ---------------------------------------------------------------- frontend
    def submit(self, prompt, max_new_tokens: Optional[int] = None,
               eos_token_id: Optional[int] = None,
               deadline_s: Optional[float] = None, seed: int = 0,
               session: Optional[str] = None,
               priority: int = 0) -> RouterRequest:
        """Admit a request into the router queue. Raises ``ValueError`` on
        inadmissible shapes, :class:`QueueFullError` under backpressure (its
        ``retry_after`` hint is load-adaptive: queue depth over observed drain
        rate), :class:`AdmissionDeferredError` for low-priority requests on a
        degraded router, :class:`AdmissionShedError` for deadlines the online
        estimator says cannot be met (``slo_admission`` only — shed at
        admission, never expired late), and :class:`RouterDrainingError` once
        draining has begun."""
        if self._draining:
            raise RouterDrainingError()
        prompt, max_new = validate_admission(
            prompt, max_new_tokens, self.config.serving.default_max_new_tokens,
            self.max_prompt_len, self.cap)
        now = time.monotonic()
        rung = self._update_rung()
        if len(self.queue) >= self.config.max_queue \
                or rung == DegradationRung.ADMISSION_CLOSED:
            self.telemetry.on_rejected()
            raise QueueFullError(self.retry_after_hint(now))
        if rung.value >= DegradationRung.DEFER_LOW.value and priority < 0:
            self.telemetry.on_deferred()
            raise AdmissionDeferredError(self.retry_after_hint(now))
        if self.config.slo_admission and deadline_s is not None:
            est = self.estimator.estimate_s(max_new, len(self.queue), now)
            margin = (self.config.shed_margin
                      if rung.value >= DegradationRung.SHED_INFEASIBLE.value
                      else 1.0)
            if est is not None and est > float(deadline_s) * margin:
                self.telemetry.on_shed()
                hint = self.retry_after_hint(now)
                # the shed decision leaves evidence: an instant request-root
                # span (the flight recorder retains it with the estimate that
                # refused the request) and a decision-journal entry
                span = self._tracer.begin(
                    "request", cat=CAT_ROUTER, t0=now, tid="router",
                    attrs={"prompt_tokens": int(prompt.size), "state": "shed",
                           "estimate_s": round(est, 4),
                           "deadline_s": float(deadline_s),
                           "retry_after": round(hint, 4),
                           **({"session": session} if session is not None
                              else {})})
                self._tracer.end_span(span, t1=now)
                obs_flight.journal("shed", estimate_s=round(est, 4),
                                   deadline_s=float(deadline_s),
                                   queue_depth=len(self.queue),
                                   retry_after=round(hint, 4))
                raise AdmissionShedError(hint, estimate_s=est,
                                         deadline_s=float(deadline_s))
        rr = RouterRequest(id=next(self._ids), prompt=prompt,
                           max_new_tokens=max_new, eos_token_id=eos_token_id,
                           deadline_s=deadline_s, seed=int(seed),
                           session=session, priority=int(priority),
                           arrival=now)
        rr._root_span = self._tracer.begin(
            "request", cat=CAT_ROUTER, t0=rr.arrival, tid="router",
            attrs={"request_id": rr.id, "prompt_tokens": int(prompt.size),
                   **({"session": session} if session is not None else {})})
        self.queue.append(rr)
        self.requests.append(rr)
        self.telemetry.submitted += 1
        return rr

    @property
    def queue_depth(self) -> int:
        return len(self.queue)

    @property
    def draining(self) -> bool:
        return self._draining

    @property
    def busy(self) -> bool:
        return bool(self.queue) or any(self._dispatched[r.id]
                                       for r in self.replicas)

    @property
    def retiring_pending(self) -> bool:
        """Any attached replica mid-scale-down: the driving loop must keep
        stepping (only :meth:`step`'s retire sweep detaches it), even though
        ``busy`` is False — idle is exactly when scale-downs happen."""
        return any(self.health[r.id].retiring for r in self.replicas)

    def replica_state(self, replica_id: int) -> ReplicaState:
        return self.health[replica_id].state

    def replica_by_id(self, replica_id: int) -> Optional[EngineReplica]:
        """The ATTACHED replica with this id, or None (retired replicas are
        detached from the set; ids are never reused)."""
        for r in self.replicas:
            if r.id == replica_id:
                return r
        return None

    # -------------------------------------------------- degradation ladder
    @property
    def degradation_rung(self) -> DegradationRung:
        return self._rung

    def _update_rung(self) -> DegradationRung:
        """Ladder position from queue fill (deterministic, admission-cheap);
        draining pins ADMISSION_CLOSED. Transitions are logged and traced."""
        cfg = self.config
        fill = len(self.queue) / max(1, cfg.max_queue)
        if self._draining or fill >= cfg.close_fill:
            rung = DegradationRung.ADMISSION_CLOSED
        elif fill >= cfg.shed_fill:
            rung = DegradationRung.SHED_INFEASIBLE
        elif fill >= cfg.defer_fill:
            rung = DegradationRung.DEFER_LOW
        else:
            rung = DegradationRung.HEALTHY
        if rung != self._rung:
            logger.info(f"[router] degradation rung: {self._rung.name} -> "
                        f"{rung.name} (queue fill {fill:.2f})")
            span = self._tracer.begin("degradation_rung", cat=CAT_ROUTER,
                                      tid="router",
                                      attrs={"from": self._rung.name,
                                             "to": rung.name,
                                             "queue_fill": round(fill, 3)})
            self._tracer.end_span(span)
            obs_flight.journal("degradation_rung", src=self._rung.name,
                               dst=rung.name, queue_fill=round(fill, 3))
            self._rung = rung
        return rung

    def retry_after_hint(self, now: Optional[float] = None) -> float:
        """Load-adaptive backpressure hint (see
        :func:`~.telemetry.adaptive_retry_after`), rated off the router-level
        completion stream the estimator observes."""
        cfg = self.config
        return adaptive_retry_after(cfg.retry_after_s, cfg.retry_after_max_s,
                                    len(self.queue), cfg.max_queue,
                                    self.estimator.drain_rate(now))

    # -------------------------------------------------------------------- loop
    def step(self, now: Optional[float] = None) -> None:
        """One router iteration: sweep local queue, run the health state
        machine (evicting newly-DEAD replicas), dispatch, pump every non-DEAD
        replica one scheduler step, then harvest finished/errored attempts.

        ``now`` is injectable for deterministic state-machine tests (it drives
        deadline expiry and health-age checks; heartbeats themselves are always
        stamped with the real clock when a replica's step completes — rewind
        ``replica.last_heartbeat`` to simulate a flatline)."""
        now = time.monotonic() if now is None else now
        self._sweep_queue(now)
        self._health_sweep(now)
        if not self._draining:
            self._dispatch(now)
        self._pump(now)
        self._harvest(now)
        self._retire_sweep(now)
        self._update_rung()
        kv = self.kv_economy_report() if self._kv_economy_enabled() else None
        self.telemetry.on_step(len(self.queue), self.replicas, self.health,
                               rung=self._rung.value, kv_economy=kv)

    def run(self, max_steps: int = 100000) -> Dict:
        """Drive ``step()`` until every admitted request reaches a terminal
        state (or ``max_steps``); returns the telemetry snapshot."""
        steps = 0
        while self.busy and steps < max_steps:
            self.step()
            steps += 1
        return self.snapshot()

    def snapshot(self) -> Dict:
        snap = self.telemetry.snapshot()
        snap["tokens_total"] = self._detached_tokens + sum(
            r.scheduler.telemetry.tokens_total for r in self.replicas)
        snap["replica_health"] = {r.id: self.health[r.id].state.value
                                  for r in self.replicas}
        snap["replicas"] = len(self.replicas)
        snap["retired_replicas"] = list(self.retired)
        snap["degradation_rung"] = self._rung.value
        if self._kv_economy_enabled():
            snap["prefix_cache"] = self.prefix_cache_report()
            snap["kv_economy"] = self.kv_economy_report()
        return snap

    def prefix_cache_report(self) -> Dict:
        """Per-replica prefix-cache reports + the aggregate hit accounting
        (caches are per-replica by design; no cross-replica coherence)."""
        per = {f"replica{r.id}": r.scheduler.prefix_cache_report()
               for r in self.replicas}
        hits = sum(p.get("hits", 0) for p in per.values())
        misses = sum(p.get("misses", 0) for p in per.values())
        return {
            "enabled": any(p.get("enabled") for p in per.values()),
            "hits": hits, "misses": misses,
            "hit_rate": hits / (hits + misses) if hits + misses else 0.0,
            "hit_tokens": sum(p.get("hit_tokens", 0) for p in per.values()),
            "cached_bytes": sum(p.get("cached_bytes", 0)
                                for p in per.values()),
            "spilled_bytes": sum(p.get("spilled_bytes", 0)
                                 for p in per.values()),
            "spills": sum(p.get("spills", 0) for p in per.values()),
            "promotions": sum(p.get("promotions", 0) for p in per.values()),
            **per,
        }

    def _kv_economy_enabled(self) -> bool:
        """Any replica with a prefix cache — in-process (direct trie access)
        or hosted (child-side cache gossiped over the heartbeat)."""
        for r in self.replicas:
            if getattr(r.scheduler, "prefix_cache", None) is not None:
                return True
            hb = getattr(r, "hb", None)
            if isinstance(hb, dict) and isinstance(hb.get("cache"), dict):
                return True
        return False

    def kv_economy_report(self) -> Dict:
        """Fleet-wide KV economy: admission-level hit accounting plus the
        tiered-cache byte/movement counters, aggregated across in-process
        replicas (scheduler telemetry + trie stats) and hosted replicas
        (heartbeat-gossiped ``cache`` dict — stale gossip degrades the
        numbers, never correctness)."""
        hits = misses = hit_tokens = 0
        cached = spilled = spills = promotions = 0
        per = {}
        for r in self.replicas:
            pc = getattr(r.scheduler, "prefix_cache", None)
            if pc is not None:
                t = r.scheduler.telemetry
                s = pc.stats()
                row = {"hits": t.prefix_hits, "misses": t.prefix_misses,
                       "hit_tokens": t.prefix_hit_tokens,
                       "cached_bytes": s["cached_bytes"],
                       "spilled_bytes": s["spilled_bytes"],
                       "spills": s["spills"],
                       "promotions": s["promotions"]}
            else:
                hb = getattr(r, "hb", None)
                cache = hb.get("cache") if isinstance(hb, dict) else None
                if not isinstance(cache, dict):
                    continue
                row = {"hits": int(cache.get("hits", 0)),
                       "misses": int(cache.get("misses", 0)),
                       "hit_tokens": int(cache.get("hit_tokens", 0)),
                       "cached_bytes": int(cache.get("cached_bytes", 0)),
                       "spilled_bytes": int(cache.get("spilled_bytes", 0)),
                       "spills": int(cache.get("spills", 0)),
                       "promotions": int(cache.get("promotions", 0))}
            hits += row["hits"]
            misses += row["misses"]
            hit_tokens += row["hit_tokens"]
            cached += row["cached_bytes"]
            spilled += row["spilled_bytes"]
            spills += row["spills"]
            promotions += row["promotions"]
            per[f"replica{r.id}"] = row
        n = hits + misses
        return {
            "enabled": bool(per),
            "fleet_hit_rate": hits / n if n else 0.0,
            "hits": hits, "misses": misses,
            "prefill_tokens_skipped": hit_tokens,
            "cached_bytes": cached,
            "spilled_bytes": spilled,
            "spills_total": spills,
            "promotions_total": promotions,
            "prefix_routed": self.telemetry.prefix_routed,
            "prefix_saved_tokens": self.telemetry.prefix_saved_tokens,
            "per_replica": per,
        }

    # ------------------------------------------------------------------- drain
    def begin_drain(self) -> None:
        """Close admission (signal-handler safe: flag only)."""
        if not self._draining:
            self._draining = True
            self._drain_started = time.monotonic()
            logger.info("[router] drain started: admission closed")

    def install_sigterm_drain(self):
        """Route SIGTERM to :meth:`begin_drain`; returns the previous handler
        (re-install it with ``signal.signal`` to uninstall)."""
        def _handler(signum, frame):
            self.begin_drain()
        self._prev_sigterm = signal.signal(signal.SIGTERM, _handler)
        return self._prev_sigterm

    def drain(self, mode: str = "handoff", max_steps: int = 100000
              ) -> List[Dict]:
        """Graceful drain: stop admitting, finish in-flight chunks, hand off.

        - ``mode="handoff"`` (SIGTERM default): one more step so current chunks
          complete (steps are chunk-granular — nothing is abandoned mid-chunk),
          then every in-flight request is evicted with its prefix and returned,
          together with the undispatched queue, as re-submittable specs
          (:meth:`RouterRequest.handoff_spec`) for the next router.
        - ``mode="complete"``: run dispatched requests to completion; hand off
          only the undispatched queue.
        """
        if mode not in ("handoff", "complete"):
            raise ValueError(f"unknown drain mode {mode!r}")
        self.begin_drain()
        t0 = time.monotonic()
        if mode == "complete":
            steps = 0
            while any(self._dispatched[r.id] for r in self.replicas) \
                    and steps < max_steps:
                self.step()
                steps += 1
        else:
            self.step()                      # finish the in-flight chunks
        handed: List[RouterRequest] = []
        for r in self.replicas:
            if not self._dispatched[r.id]:
                continue
            if self.health[r.id].state != ReplicaState.DEAD:
                r.scheduler.evict_all(reason="drain")
            for rr in self._dispatched[r.id]:
                self._absorb_prefix(rr)
                handed.append(rr)
            self._dispatched[r.id].clear()
        while self.queue:
            handed.append(self.queue.popleft())
        now = time.monotonic()
        specs = []
        for rr in handed:
            rr.state = RouterRequestState.HANDED_OFF
            rr.finish_reason = "drain"
            rr.finished_at = now
            # hand-off bypasses _finalize: commit the open spans here or the
            # drained requests' root/attempt lanes vanish from the trace
            if rr._attempt_span is not None:
                self._tracer.end_span(rr._attempt_span, t1=now,
                                      attrs={"outcome": "handed_off"})
                rr._attempt_span = None
            if rr._root_span is not None:
                self._tracer.end_span(
                    rr._root_span, t1=now,
                    attrs={"state": "handed_off", "reason": "drain",
                           "tokens": len(rr.tokens)})
                rr._root_span = None
            specs.append(rr.handoff_spec())
        self.telemetry.on_drain(now - t0, len(specs))
        # a drained router is about to exit: the monitor backends' tail events
        # (csv/jsonl buffers) must be durable before the process goes away
        m = self.telemetry.monitor
        if m is not None and hasattr(m, "flush"):
            m.flush()
        # ... and so must the flight evidence: every handed-off request's
        # retained tree + the decision journal land in a drain bundle
        obs_flight.journal("drain", handed_off=len(specs),
                           drain_ms=round((now - t0) * 1e3, 2))
        obs_flight.drain_dump()
        logger.info(f"[router] drain complete in {(now - t0) * 1e3:.1f} ms: "
                    f"{len(specs)} request(s) handed off")
        return specs

    # ------------------------------------------------------------------ sweeps
    def _expired(self, rr: RouterRequest, now: float) -> bool:
        return (rr.deadline_s is not None
                and now - rr.arrival > rr.deadline_s)

    def _sweep_queue(self, now: float) -> None:
        kept: Deque[RouterRequest] = deque()
        for rr in self.queue:
            if rr._cancel:
                self._finalize(rr, RouterRequestState.CANCELLED, "cancelled",
                               now)
            elif self._expired(rr, now):
                self._finalize(rr, RouterRequestState.EXPIRED, "deadline", now)
            else:
                kept.append(rr)
        self.queue = kept

    # ------------------------------------------------------------------ health
    def _health_sweep(self, now: float) -> None:
        cfg = self.config
        for r in self.replicas:
            h = self.health[r.id]
            if h.state in (ReplicaState.LIVE, ReplicaState.SUSPECT,
                           ReplicaState.RECOVERING, ReplicaState.RETIRING):
                # RECOVERING replicas age too: a replica killed mid-probe must
                # flatline back to DEAD (and release its probe request), not
                # hold the probe hostage forever — and RETIRING replicas age
                # so a replica killed mid-scale-down still migrates its
                # in-flight requests via the DEAD eviction path. Age is
                # pump-relative: a router that idled (no pumps) learned
                # nothing — only failing to respond WHILE pumped counts as a
                # missed heartbeat.
                age = max(0.0, r.last_pump_attempt - r.last_heartbeat)
                if age > cfg.dead_after_s:
                    self._mark_dead(r, now, f"missed heartbeats for {age:.2f}s")
                elif age > cfg.suspect_after_s:
                    if h.state == ReplicaState.LIVE:
                        self._transition(r.id, ReplicaState.SUSPECT)
                elif h.state == ReplicaState.SUSPECT:
                    self._transition(r.id, ReplicaState.LIVE)   # recovered
            elif h.state == ReplicaState.DEAD:
                if r.alive and h.died_at is not None \
                        and now - h.died_at >= cfg.recover_after_s:
                    h.probe_request = None
                    self._transition(r.id, ReplicaState.RECOVERING)

    def _transition(self, replica_id: int, new: ReplicaState) -> None:
        h = self.health[replica_id]
        old, h.state = h.state, new
        if old != new:
            logger.info(f"[router] replica {replica_id}: {old.value} -> "
                        f"{new.value}")
            self.telemetry.on_transition(replica_id, old, new)
            obs_flight.journal("replica_health", replica=replica_id,
                               src=old.value, dst=new.value)

    def _mark_dead(self, replica, now: float, why: str) -> None:
        h = self.health[replica.id]
        if h.state == ReplicaState.DEAD:
            return
        logger.warning(f"[router] replica {replica.id} declared DEAD ({why}); "
                       f"evicting {len(self._dispatched[replica.id])} "
                       "in-flight request(s)")
        self._transition(replica.id, ReplicaState.DEAD)
        h.died_at = now
        h.probe_request = None
        h.consecutive_failures = 0
        # affinity must not keep steering sessions at a corpse
        for sess in [s for s, rid in self._affinity.items()
                     if rid == replica.id]:
            del self._affinity[sess]
        if replica.alive:
            # circuit-breaker death: the process is responsive, release its
            # slots/pool properly. (A killed replica's device state is gone
            # with the process; the host-side prefixes below are all we need.)
            replica.scheduler.evict_all(reason="replica-dead")
        for rr in self._dispatched[replica.id]:
            self._requeue(rr, replica.id, now, breaker=False)
        self._dispatched[replica.id].clear()

    def _health_failure(self, replica_id: int, now: float) -> None:
        h = self.health[replica_id]
        h.consecutive_failures += 1
        if h.state == ReplicaState.RECOVERING:
            # half-open probe failed: back to DEAD, restart the recovery clock
            self._mark_dead(self._replica(replica_id), now, "probe failed")
        elif h.consecutive_failures >= self.config.breaker_threshold:
            self._mark_dead(self._replica(replica_id), now,
                            f"circuit breaker: {h.consecutive_failures} "
                            "consecutive failures")

    def _health_success(self, replica_id: int) -> None:
        h = self.health[replica_id]
        h.consecutive_failures = 0
        if h.state == ReplicaState.RECOVERING:
            h.probe_request = None
            self._transition(replica_id, ReplicaState.LIVE)  # breaker closes

    def _replica(self, replica_id: int) -> EngineReplica:
        r = self.replica_by_id(replica_id)
        if r is None:
            raise KeyError(f"replica {replica_id} is not attached")
        return r

    def _as_replica(self, item, replica_id: int):
        """Engine objects wrap in an in-process :class:`EngineReplica`;
        objects already implementing the replica protocol (``host.py``'s
        subprocess-hosted :class:`~.host.HostedReplica`) join the set as
        themselves — a router may mix both."""
        if getattr(item, "replica_protocol", False):
            item.bind(replica_id)
            return item
        return EngineReplica(replica_id, item, self.config.serving)

    # ----------------------------------------------------- elastic replica set
    def add_replica(self, engine, warm: bool = True) -> EngineReplica:
        """Attach a new replica (autoscaler scale-up). Ids are monotonic and
        never reused — detached ids stay dead in the telemetry history.

        ``warm=True`` (the default, and what the autoscaler uses) admits the
        replica through the RECOVERING half-open probe path: it serves ONE
        probe request and only joins the dispatch pool once that succeeds —
        a replica that cannot serve (bad weights, wedged compile) never takes
        a batch of real traffic. ``warm=False`` trusts it LIVE immediately."""
        if self._draining:
            raise RouterDrainingError()
        rid = self._next_replica_id
        self._next_replica_id += 1
        replica = self._as_replica(engine, rid)
        self.replicas.append(replica)
        self._dispatched[rid] = []
        self.health[rid] = ReplicaHealth(
            state=ReplicaState.RECOVERING if warm else ReplicaState.LIVE)
        self.telemetry.dispatched.setdefault(rid, 0)
        logger.info(f"[router] replica {rid} attached "
                    f"({'warm-probe' if warm else 'live'}); "
                    f"{len(self.replicas)} replica(s)")
        return replica

    def begin_retire(self, replica_id: int, grace_s: Optional[float] = None,
                     now: Optional[float] = None) -> None:
        """Start a graceful scale-down of one replica: no new dispatches, its
        session affinities release, in-flight requests get ``grace_s`` to
        finish; whatever remains at the bound is evicted WITH generated
        prefixes and migrated to the other replicas — the same bit-exact
        continuation contract as death retry, minus the death."""
        now = time.monotonic() if now is None else now
        self._replica(replica_id)             # raises if not attached
        h = self.health[replica_id]
        if h.retiring:
            return
        # "serving" = attached, not retiring, not DEAD — a corpse is not
        # capacity, so retiring the last LIVE replica beside a corpse must
        # refuse too (detaching a DEAD replica itself is always allowed)
        serving = [r for r in self.replicas
                   if not self.health[r.id].retiring
                   and self.health[r.id].state != ReplicaState.DEAD]
        if any(r.id == replica_id for r in serving) and len(serving) <= 1:
            raise ValueError("cannot retire the last serving replica")
        h.retiring = True
        h.retiring_since = now
        h.retire_grace_s = float(self.config.retire_grace_s
                                 if grace_s is None else grace_s)
        if h.state != ReplicaState.DEAD:
            self._transition(replica_id, ReplicaState.RETIRING)
        for sess in [s for s, rid in self._affinity.items()
                     if rid == replica_id]:
            del self._affinity[sess]

    def _retire_sweep(self, now: float) -> None:
        """Advance retiring replicas: detach when empty (or dead — a kill
        mid-scale-down already migrated its work through ``_mark_dead``);
        at the grace bound, evict the stragglers with their prefixes and
        requeue them on the survivors."""
        for r in [r for r in list(self.replicas)
                  if self.health[r.id].retiring]:
            h = self.health[r.id]
            if h.state == ReplicaState.DEAD:
                self._detach(r)               # eviction already done
                continue
            if not self._dispatched[r.id]:
                self._detach(r)
                continue
            if now - h.retiring_since > h.retire_grace_s:
                logger.info(f"[router] retire grace expired on replica "
                            f"{r.id}: migrating "
                            f"{len(self._dispatched[r.id])} in-flight "
                            "request(s) with prefixes")
                r.scheduler.evict_all(reason="scale_down")
                for rr in self._dispatched[r.id]:
                    self._requeue(rr, r.id, now, breaker=False)
                self._dispatched[r.id].clear()
                self._detach(r)

    def _detach(self, replica: EngineReplica) -> None:
        self.replicas = [x for x in self.replicas if x.id != replica.id]
        self._dispatched.pop(replica.id, None)
        self.retired.append(replica.id)
        self._detached_tokens += replica.scheduler.telemetry.tokens_total
        self.health[replica.id].retiring = False
        if getattr(replica, "is_hosted", False):
            # a detached host's child must not outlive its membership — but
            # the stop ladder (drain → SIGTERM → SIGKILL) can legitimately
            # take seconds on a wedged child, and this sweep runs inside the
            # single-threaded serving loop: close on a reaper thread so the
            # survivors' dispatch/harvest never stalls behind it (the ladder
            # still guarantees the child is reaped)
            threading.Thread(target=replica.close, daemon=True,
                             name=f"host-close-{replica.id}").start()
        for sess in [s for s, rid in self._affinity.items()
                     if rid == replica.id]:
            del self._affinity[sess]
        logger.info(f"[router] replica {replica.id} detached; "
                    f"{len(self.replicas)} replica(s) remain")

    # ---------------------------------------------------------------- dispatch
    def _usable(self, replica: EngineReplica, rr: RouterRequest) -> bool:
        h = self.health[replica.id]
        if h.state == ReplicaState.LIVE:
            return replica.available > 0
        if h.state == ReplicaState.RECOVERING:
            return h.probe_request is None and replica.available > 0
        return False

    def _expected_saved(self, replica: EngineReplica,
                        prompt: np.ndarray) -> int:
        """Expected prefill-tokens-saved if ``prompt`` lands on ``replica``.

        In-process replicas answer exactly via a read-only trie probe
        (``PrefixCache.peek`` — device or host rung, either avoids the
        re-prefill). Hosted replicas answer approximately from the digest
        ladder gossiped on their last heartbeat; a stale or absent heartbeat
        degrades to 0 (routing quality, never correctness)."""
        pc = getattr(replica.scheduler, "prefix_cache", None)
        if pc is not None:
            try:
                return int(pc.peek(prompt)[0])
            except Exception:
                return 0
        hb = getattr(replica, "hb", None)
        if not isinstance(hb, dict):
            return 0
        cache = hb.get("cache")
        if not isinstance(cache, dict):
            return 0
        return match_from_digests(prompt, cache.get("digests"))

    def _pick(self, rr: RouterRequest) -> Optional[EngineReplica]:
        cands = [r for r in self.replicas if self._usable(r, rr)]
        if not cands:
            return None
        non_excluded = [r for r in cands if r.id not in rr.excluded]
        pool = non_excluded or cands       # all excluded → retry anywhere sane
        pinned = self._affinity.get(rr.session) if rr.session is not None \
            else None
        if not self.config.prefix_aware_routing:
            for r in pool:
                if r.id == pinned:
                    return r
            return min(pool, key=lambda r: (r.outstanding, r.id))
        # prefix-aware dispatch: score = expected prefill-tokens-saved minus
        # load penalty; session affinity is only a tiebreaker. Retries fold
        # accumulated tokens so the probe sees what prefill will see.
        prompt = np.concatenate(
            [rr.prompt, np.asarray(rr.tokens, np.int32)]) \
            if rr.tokens else rr.prompt
        w = self.config.prefix_route_load_weight
        best, best_key, best_saved = None, None, 0
        for r in pool:
            saved = self._expected_saved(r, prompt)
            key = (saved - w * r.outstanding,
                   1 if r.id == pinned else 0, -r.id)
            if best_key is None or key > best_key:
                best, best_key, best_saved = r, key, saved
        rr.expected_saved_tokens = best_saved
        return best

    def _dispatch(self, now: float) -> None:
        cfg = self.config
        for rr in list(self.queue):
            target = self._pick(rr)
            if target is None:
                continue                   # exclusions differ per request
            deadline = None
            if rr.deadline_s is not None:
                deadline = rr.deadline_s - (now - rr.arrival)
                if deadline <= 0:
                    self.queue.remove(rr)
                    self._finalize(rr, RouterRequestState.EXPIRED, "deadline",
                                   now)
                    continue
            prompt = np.concatenate(
                [rr.prompt, np.asarray(rr.tokens, np.int32)]) \
                if rr.tokens else rr.prompt
            # dispatch-attempt span: retries show as LINKED spans on the same
            # trace id — the retry replica id + the evicted attempt's span id
            # ride the attrs, so a killed request's original and retry lanes
            # join in one Perfetto query
            att = self._tracer.start_span(
                "attempt", parent=rr._root_span, cat=CAT_ROUTER,
                attrs={"replica": target.id, "attempt": rr.attempts + 1,
                       "prefix_tokens": len(rr.tokens),
                       **({"retry": True, "retry_replica_id": target.id,
                           "retry_of": rr._prev_attempt_id}
                          if rr.retried else {})})
            att_ctx = att.ctx if att is not None else None

            def attempt(t=target, p=prompt, r=rr, d=deadline, c=att_ctx):
                fault_point("serving.router.dispatch")
                return t.submit(p, max_new_tokens=r.remaining_budget,
                                eos_token_id=r.eos_token_id, deadline_s=d,
                                seed=r.seed, trace_ctx=c)

            try:
                inner = retry_with_backoff(attempt,
                                           retries=cfg.dispatch_retries,
                                           base_delay=cfg.retry_base_delay)
            except QueueFullError:
                self._tracer.end_span(att, attrs={"outcome": "queue_full"})
                continue                   # replica raced full; try next tick
            except Exception as e:
                logger.warning(f"[router] dispatch of request {rr.id} to "
                               f"replica {target.id} failed: "
                               f"{type(e).__name__}: {e}")
                self._tracer.end_span(att, attrs={"outcome": "dispatch_error",
                                                  "error": type(e).__name__})
                rr.excluded.add(target.id)
                self._health_failure(target.id, now)
                continue
            self.queue.remove(rr)
            rr.state = RouterRequestState.DISPATCHED
            rr.attempts += 1
            rr.replica_id = target.id
            rr.inner = inner
            rr._attempt_span = att
            if rr._cancel:                 # cancel landed between ticks
                inner.cancel()
            self._dispatched[target.id].append(rr)
            if rr.session is not None:
                self._affinity[rr.session] = target.id
            h = self.health[target.id]
            if h.state == ReplicaState.RECOVERING:
                h.probe_request = rr.id
            self.telemetry.on_dispatch(target.id)
            if self.config.prefix_aware_routing \
                    and rr.expected_saved_tokens > 0:
                self.telemetry.on_prefix_route(rr.expected_saved_tokens)

    # -------------------------------------------------------------------- pump
    def _pump(self, now: float) -> None:
        attempted = [r for r in self.replicas
                     if self.health[r.id].state != ReplicaState.DEAD]
        pumped = [r for r in attempted if r.step()]
        # one shared post-pump stamp: the pump is serial, so a co-replica's slow
        # step (first-dispatch compile, long chunk) must not age the heartbeats
        # of replicas that already responded this round
        t = time.monotonic()
        for r in attempted:
            r.last_pump_attempt = t
        for r in pumped:
            r.last_heartbeat = t

    # ----------------------------------------------------------------- harvest
    def _absorb_prefix(self, rr: RouterRequest) -> None:
        """Fold the current attempt's tokens into the cross-attempt stream."""
        if rr.inner is not None:
            rr.tokens.extend(int(t) for t in rr.inner.tokens)
            if rr.first_token_at is None and rr.inner.first_token_at is not None:
                rr.first_token_at = rr.inner.first_token_at
                rr.ttft = rr.first_token_at - rr.arrival
                rr.prefix_hit_tokens = getattr(rr.inner, "prefix_hit_tokens",
                                               0)
            inner_span = getattr(rr.inner, "_span", None)
            if inner_span is not None:
                # a killed replica never finalizes its handle: the scheduler-
                # side request span would stay open forever — close it here so
                # the original replica's lane is complete in the trace
                self._tracer.end_span(
                    inner_span, attrs={"state": "abandoned",
                                       "reason": "absorbed-by-router"})
                rr.inner._span = None
            rr.inner = None

    def _harvest(self, now: float) -> None:
        for r in self.replicas:
            if self.health[r.id].state == ReplicaState.DEAD:
                continue                   # handled by _mark_dead eviction
            still: List[RouterRequest] = []
            failures = 0
            h = self.health[r.id]
            for rr in self._dispatched[r.id]:
                inner = rr.inner
                if inner is None or not inner.done:
                    still.append(rr)
                    continue
                if inner.state == RequestState.FINISHED:
                    self._finalize(rr, RouterRequestState.FINISHED,
                                   inner.finish_reason, now)
                    self._health_success(r.id)
                elif inner.state == RequestState.EXPIRED:
                    self._finalize(rr, RouterRequestState.EXPIRED,
                                   "deadline", now)
                    if h.probe_request == rr.id:
                        h.probe_request = None
                elif inner.state == RequestState.CANCELLED \
                        and inner.finish_reason == "cancelled":
                    self._finalize(rr, RouterRequestState.CANCELLED,
                                   "cancelled", now)
                    if h.probe_request == rr.id:
                        h.probe_request = None
                else:
                    # replica-side failure (finish_reason "error") or eviction:
                    # checkpointless retry with the generated-so-far prefix.
                    # Breaker accounting is DEFERRED below — _mark_dead mutates
                    # the very list this loop walks.
                    failures += 1
                    self._requeue(rr, r.id, now, breaker=False)
            self._dispatched[r.id] = still
            for _ in range(failures):
                self._health_failure(r.id, now)

    def _requeue(self, rr: RouterRequest, replica_id: int, now: float,
                 breaker: bool) -> None:
        self._absorb_prefix(rr)
        if rr._attempt_span is not None:
            rr._prev_attempt_id = rr._attempt_span.span_id
            self._tracer.end_span(rr._attempt_span,
                                  attrs={"outcome": "evicted",
                                         "evicted_from_replica": replica_id})
            rr._attempt_span = None
        rr.evictions += 1
        rr.excluded.add(replica_id)
        self.telemetry.on_evicted()
        if breaker:
            self._health_failure(replica_id, now)
        if rr._cancel:
            self._finalize(rr, RouterRequestState.CANCELLED, "cancelled", now)
            return
        if self._expired(rr, now):
            self._finalize(rr, RouterRequestState.EXPIRED, "deadline", now)
            return
        if rr.remaining_budget < 1:
            # the dying replica delivered the full budget but never finalized;
            # everything generated is in hand — this is a completion
            self._finalize(rr, RouterRequestState.FINISHED, "length", now)
            return
        if rr.attempts >= self.config.max_attempts:
            logger.error(f"[router] request {rr.id}: retry budget exhausted "
                         f"after {rr.attempts} attempt(s)")
            self._finalize(rr, RouterRequestState.FAILED, "error", now)
            return
        if rr.prompt.size + len(rr.tokens) > self.max_prompt_len:
            # retry needs prompt+prefix to re-prefill; with the default
            # max_prompt_len (cap-1) this cannot trip, but a tighter configured
            # bound can — fail loudly rather than mis-serve
            logger.error(f"[router] request {rr.id}: prefix "
                         f"{len(rr.tokens)} tokens no longer fits "
                         f"max_prompt_len={self.max_prompt_len}; cannot retry")
            self._finalize(rr, RouterRequestState.FAILED, "error", now)
            return
        rr.state = RouterRequestState.QUEUED
        rr.replica_id = None
        rr.retried += 1
        self.telemetry.on_retried()
        self.queue.appendleft(rr)          # retries go to the head: oldest first

    # --------------------------------------------------------------- lifecycle
    def _finalize(self, rr: RouterRequest, state: RouterRequestState,
                  reason: Optional[str], now: float) -> None:
        self._absorb_prefix(rr)
        rr.state = state
        rr.finish_reason = reason
        rr.finished_at = now
        if (rr.first_token_at is not None and len(rr.tokens) > 1
                and now > rr.first_token_at):
            rr.tpot = (now - rr.first_token_at) / (len(rr.tokens) - 1)
        if rr._attempt_span is not None:
            self._tracer.end_span(rr._attempt_span, t1=now,
                                  attrs={"outcome": state.value})
            rr._attempt_span = None
        if rr._root_span is not None:
            self._tracer.end_span(
                rr._root_span, t1=now,
                attrs={"state": state.value, "reason": reason,
                       "tokens": len(rr.tokens), "attempts": rr.attempts,
                       "retried": rr.retried})
            rr._root_span = None
        if state == RouterRequestState.FINISHED:
            # completions feed the online service-time model behind SLO
            # admission and the adaptive retry_after hint
            self.estimator.observe(ttft_s=rr.ttft, tpot_s=rr.tpot,
                                   generated=len(rr.tokens),
                                   budget=rr.max_new_tokens, now=now)
        self.telemetry.on_finished(rr)
