"""TPU-native serving subsystem: continuous batching over a slot-recycled KV pool,
behind a health-supervised multi-replica router.

Layers (bottom-up):

- :mod:`kv_pool` — :class:`PagedKVPool` (default): one global pool of
  fixed-size KV pages behind static-shape per-slot page tables — page-count
  admission, refcounted zero-copy prefix sharing (copy-on-write boundary
  page), donated movers; :class:`SlotKVPool`: the legacy slot-indexed
  fixed-capacity buffers (scatter-in prefill, zero-fill on release);
- :mod:`executor` — :class:`ChunkedDecodeExecutor`: compiled fixed-shape decode
  chunks of K steps over the slot-batch (one compile per (slots, cap, chunk,
  sampling) key), per-slot prefill bucketed by prompt length, optional per-chunk
  watchdog deadline (:class:`ChunkTimeoutError`);
- :mod:`prefix_cache` — :class:`PrefixCache`: radix/trie index over token-ID
  prefixes whose entries hold gathered KV slabs (LRU under an HBM byte budget,
  exact match by token); a hit restores the slab into the slot and prefills
  only the suffix, so shared system prompts skip prefill;
- :mod:`scheduler` — :class:`ContinuousBatchingScheduler`: bounded request queue
  with admission control, backpressure (reject-with-retry-after), deadlines,
  cancellation, slot recycling between chunks, per-replica prefix-cache
  lookup/insert, and whole-replica eviction (``evict_all``) for the router's
  checkpointless retry;
- :mod:`router` — :class:`Router`: N engine replicas behind one admission queue
  with least-outstanding dispatch, session affinity, the
  LIVE→SUSPECT→DEAD→RECOVERING health state machine, checkpointless request
  retry and SIGTERM graceful drain;
- :mod:`host` — :class:`HostedReplica` + :class:`ReplicaSupervisor`:
  process-parallel replica hosts — the same stack in supervised child
  processes over the :mod:`subproc` JSONL pipe (async submit/harvest,
  child-stamped heartbeats, real-signal chaos, bounded-backoff respawn
  through the router's RECOVERING warm probe) so replica count finally buys
  machine parallelism;
- :mod:`net` — :class:`SocketHostedReplica` over a length-prefixed framed
  TCP transport carrying the same protocol v1 (per-frame CRC + quarantine/
  resync, versioned hello with session tokens, reconnect state machine with
  sever-evict-redial semantics, network chaos seam) — the fleet's recovery
  semantics made transport-independent;
- :mod:`autoscale` — :class:`Autoscaler` + :class:`ServiceTimeEstimator`: the
  elastic control plane — live metrics (queue depth, recent TTFT p95,
  occupancy) drive replica count with hysteresis + cooldown, and the online
  service-time estimator powers SLO-aware admission (shed infeasible
  deadlines at the front door) and the load-adaptive ``retry_after`` hint;
- :mod:`chaos` — scripted replica kills/stalls/surges for the chaos soak
  harness;
- :mod:`telemetry` — :class:`ServingTelemetry`: per-request TTFT/TPOT, queue
  depth, slot occupancy and tokens/sec through ``MonitorMaster``
  (:class:`~.router.RouterTelemetry` adds per-replica health/retry/eviction).
"""

from .autoscale import (Autoscaler, AutoscaleConfig, EstimatorConfig,
                        ServiceTimeEstimator)
from .chaos import ChaosEvent, ChaosSchedule, parse_chaos
from .host import (HostConfig, HostedReplica, ReplicaSupervisor,
                   SocketHostedReplica, SupervisorConfig)
from .net import FrameDecoder, NetConfig, SocketReplicaLink, encode_frame
from .executor import ChunkedDecodeExecutor, ChunkTimeoutError
from .kv_pool import PagedKVPool, SlotKVPool
from .prefix_cache import PrefixCache, PrefixCacheConfig
from .router import (AdmissionDeferredError, AdmissionShedError,
                     DegradationRung, EngineReplica, ReplicaDeadError,
                     ReplicaState, Router, RouterConfig, RouterDrainingError,
                     RouterRequest, RouterRequestState, RouterTelemetry)
from .scheduler import (ContinuousBatchingScheduler, QueueFullError,
                        RequestHandle, RequestState, ServingConfig)
from .telemetry import ServingTelemetry

__all__ = [
    "ChunkedDecodeExecutor", "ChunkTimeoutError", "SlotKVPool", "PagedKVPool",
    "PrefixCache", "PrefixCacheConfig",
    "ContinuousBatchingScheduler", "QueueFullError", "RequestHandle",
    "RequestState", "ServingConfig", "ServingTelemetry",
    "Router", "RouterConfig", "RouterRequest", "RouterRequestState",
    "RouterTelemetry", "EngineReplica", "ReplicaState", "ReplicaDeadError",
    "RouterDrainingError", "ChaosEvent", "ChaosSchedule", "parse_chaos",
    "Autoscaler", "AutoscaleConfig", "EstimatorConfig", "ServiceTimeEstimator",
    "AdmissionShedError", "AdmissionDeferredError", "DegradationRung",
    "HostConfig", "HostedReplica", "ReplicaSupervisor", "SupervisorConfig",
    "SocketHostedReplica", "SocketReplicaLink", "NetConfig", "FrameDecoder",
    "encode_frame",
]
