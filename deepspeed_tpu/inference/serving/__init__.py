"""TPU-native serving subsystem: continuous batching over a slot-recycled KV pool.

Layers (bottom-up):

- :mod:`kv_pool` — :class:`SlotKVPool`: slot-indexed fixed-capacity KV buffers
  built on ``init_cache``; scatter-in prefill, zero-fill on release, donated
  updates throughout;
- :mod:`executor` — :class:`ChunkedDecodeExecutor`: compiled fixed-shape decode
  chunks of K steps over the slot-batch (one compile per (slots, cap, chunk,
  sampling) key), per-slot prefill bucketed by prompt length;
- :mod:`scheduler` — :class:`ContinuousBatchingScheduler`: bounded request queue
  with admission control, backpressure (reject-with-retry-after), deadlines,
  cancellation, and slot recycling between chunks;
- :mod:`telemetry` — :class:`ServingTelemetry`: per-request TTFT/TPOT, queue
  depth, slot occupancy and tokens/sec through ``MonitorMaster``.
"""

from .executor import ChunkedDecodeExecutor
from .kv_pool import SlotKVPool
from .scheduler import (ContinuousBatchingScheduler, QueueFullError,
                        RequestHandle, RequestState, ServingConfig)
from .telemetry import ServingTelemetry

__all__ = [
    "ChunkedDecodeExecutor", "SlotKVPool", "ContinuousBatchingScheduler",
    "QueueFullError", "RequestHandle", "RequestState", "ServingConfig",
    "ServingTelemetry",
]
