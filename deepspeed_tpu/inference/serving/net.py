"""Socket-framed replica transport: protocol v1 over TCP.

PR 15's :mod:`.subproc` pipe proved the recovery model across a PROCESS
boundary; this module carries the same protocol v1 across a MACHINE boundary
— the ROADMAP's "TCP framing of protocol v1" item — without changing one
recovery semantic. Every JSONL line the pipe would carry rides inside a
length-prefixed frame::

    MAGIC(2) | length(4, big-endian) | crc32(4, big-endian) | payload

``MAGIC`` is ``0xD5`` + the wire version byte, so a drifted peer fails the
resync scan instead of mis-framing; ``length`` is bounded by
:data:`MAX_FRAME`; the CRC makes a corrupted frame a *detected* loss. The
quarantine contract is protocol v1's, verbatim: a bad frame (bad magic, bad
CRC, oversized header) is counted + sampled and the decoder RESYNCS to the
next magic — one bad frame loses one message, never the replica.

Both sides are nonblocking and deadline-driven (a ``select`` loop on the
parent, accept/dial threads on the child); neither ever blocks the serving
loop on the network.

**Hello + session tokens.** The parent opens every connection with
``{"hello": {"proto": 1, "resume": <token|null>}}``. The child mints one
session token per process (``os.urandom`` hex) and answers with the protocol
v1 ready line plus ``{"session": t, "resumed": bool}`` — ``resumed`` true iff
the parent's ``resume`` token matches, i.e. this is the SAME warm process
(engine built, caches hot) behind a redialed connection. A fresh token tells
the parent the process behind the endpoint was replaced: nothing it streamed
before survives. Either way the child cancels any orphaned in-flight work on
a new accept (the parent already evicted it — see below), so slots free
rather than leak.

**Sever semantics (the checkpointless-retry contract over TCP).** When the
connection severs — RST, FIN, or a chaos partition aging into DEAD — the
parent immediately evicts every in-flight request WITH its streamed token
prefix through the existing eviction path; the router's checkpointless retry
re-prefills ``prompt + prefix`` anywhere, bit-exact. The link then runs an
explicit reconnect state machine: CONNECTED -> SEVERED -> (bounded
exponential backoff redial) -> CONNECTED, resuming with the session token,
while the frozen heartbeat stamp ages the replica through the router's
LIVE->SUSPECT->DEAD machine. The supervisor's respawn arm stays process
scoped: a dead CHILD respawns, a dead CONNECTION redials — the
"respawn-or-redial" split.

**Write-side backpressure.** Outbound frames queue under a byte bound
(:attr:`NetConfig.write_buffer_max`); a submit that would exceed it raises
the scheduler's ``QueueFullError`` so the router's admission backpressure —
not an unbounded buffer — absorbs a slow link.

**Network chaos seam.** :meth:`SocketReplicaLink.net_fault` injects faults at
the transport seam (both directions, surviving redials until expiry):
``partition`` discards every byte both ways (silence -> SUSPECT -> DEAD ->
respawn-or-redial), ``delay`` sleeps the read path (heartbeat jitter that
must NOT false-kill below the SUSPECT threshold), ``drop`` corrupts inbound
bytes with seeded probability (CRC quarantine + resync exercised under
load). The chaos grammar (``net:replica=i,mode=...``) lives in :mod:`.chaos`.

``net/*`` telemetry (frames, reconnects, quarantined frames, RTT from
ping/pong frames, partition trips) is declared in ``observability.schema``
and emitted through a per-link :class:`~...observability.metrics.RegistryFeed`.
"""

import json
import os
import random
import select
import socket
import subprocess
import sys
import threading
import time
import zlib
from collections import deque
from dataclasses import dataclass
from typing import Dict, List, Optional

from ...observability.metrics import RegistryFeed
from ...utils.logging import logger
from .scheduler import QueueFullError
from .subproc import PROTO_VERSION, HostProtocolError, SubprocessReplica

#: frame sentinel: 0xD5 + wire version. Bumping the wire format bumps the
#: second byte, so an old peer's resync scan never mis-frames a new stream.
MAGIC = b"\xd5\x01"
_HEADER = 10                       # MAGIC(2) + length(4) + crc32(4)
#: hard bound on one frame's payload — a corrupted length field must never
#: stall the decoder waiting on gigabytes that are not coming
MAX_FRAME = 8 * 1024 * 1024


def encode_frame(payload: bytes) -> bytes:
    """One wire frame around ``payload`` (the JSONL line, encoded)."""
    if len(payload) > MAX_FRAME:
        raise ValueError(f"frame payload {len(payload)} exceeds MAX_FRAME "
                         f"{MAX_FRAME}")
    return (MAGIC + len(payload).to_bytes(4, "big")
            + (zlib.crc32(payload) & 0xFFFFFFFF).to_bytes(4, "big") + payload)


class FrameDecoder:
    """Streaming frame decoder with the v1 quarantine contract: garbage
    between frames, a corrupted CRC, or an insane length is counted +
    sampled, then the scan RESYNCS at the next magic — decoding never stops
    and never raises on wire bytes."""

    def __init__(self):
        self._buf = bytearray()
        self.frames = 0                  # well-formed frames decoded
        self.quarantined = 0             # resync events (bad magic/CRC/len)
        self.quarantined_sample: Optional[str] = None

    def _quarantine(self, sample: bytes) -> None:
        self.quarantined += 1
        self.quarantined_sample = repr(sample[:80])

    def feed(self, data: bytes) -> List[bytes]:
        """Absorb ``data``; return every complete payload now decodable."""
        self._buf += data
        out: List[bytes] = []
        while True:
            idx = self._buf.find(MAGIC)
            if idx < 0:
                # no magic in the buffer: quarantine all but the tail byte
                # (which may be the first byte of a magic split across reads)
                if len(self._buf) > 1:
                    self._quarantine(bytes(self._buf[:-1]))
                    del self._buf[:-1]
                break
            if idx > 0:                  # garbage before the frame: resync
                self._quarantine(bytes(self._buf[:idx]))
                del self._buf[:idx]
            if len(self._buf) < _HEADER:
                break                    # header still arriving
            length = int.from_bytes(self._buf[2:6], "big")
            if length > MAX_FRAME:
                # corrupted length: skip this magic, rescan inside
                self._quarantine(bytes(self._buf[:_HEADER]))
                del self._buf[:2]
                continue
            if len(self._buf) < _HEADER + length:
                break                    # truncated so far: wait for bytes
            payload = bytes(self._buf[_HEADER:_HEADER + length])
            crc = int.from_bytes(self._buf[6:10], "big")
            if (zlib.crc32(payload) & 0xFFFFFFFF) != crc:
                # detected corruption: drop the magic, rescan — the real next
                # frame's own magic survives inside the corrupted span
                self._quarantine(payload[:80])
                del self._buf[:2]
                continue
            del self._buf[:_HEADER + length]
            self.frames += 1
            out.append(payload)
        return out


@dataclass
class NetConfig:
    """Transport knobs for one :class:`SocketReplicaLink`."""
    connect_timeout_s: float = 30.0    # first dial / bootstrap deadline
    redial_backoff_base_s: float = 0.05  # reconnect machine: base * 2^attempt
    redial_backoff_max_s: float = 2.0
    redial_window_s: float = 120.0     # severed this long -> the endpoint is
    #   gone (the supervisor's respawn arm takes over)
    ping_interval_s: float = 0.5       # RTT probe cadence (net/rtt_ms)
    write_buffer_max: int = 8 * 1024 * 1024   # outbound byte bound: past it,
    #   submit raises QueueFullError (backpressure, not an unbounded buffer)
    emit_interval_s: float = 0.25      # net/* telemetry cadence


class _NetFault:
    """One active transport fault (the chaos seam's state). ``value`` is
    milliseconds for ``delay``, a probability for ``drop``."""

    def __init__(self, mode: str, value: float, duration_s: float):
        self.mode = mode
        self.value = float(value)
        self.until = time.monotonic() + float(duration_s)
        self._rng = random.Random(0xC0FFEE)

    def active(self, now: Optional[float] = None) -> bool:
        return (time.monotonic() if now is None else now) < self.until

    def corrupt(self, data: bytes) -> bytes:
        """``drop`` mode: flip one byte with probability ``value`` per read —
        the CRC turns the flip into a detected, quarantined frame loss."""
        if data and self._rng.random() < self.value:
            i = self._rng.randrange(len(data))
            b = bytearray(data)
            b[i] ^= 0xFF
            return bytes(b)
        return data


class _RemoteProc:
    """Duck-typed ``Popen`` stand-in for an endpoint-dialed child the parent
    did not spawn: "process death" is the reconnect machine giving up (the
    redial window closing), and signals have nowhere to go — the connection
    is the only lever, which is exactly the stop ladder's new rung."""

    def __init__(self, link: "SocketReplicaLink"):
        self._link = link
        self.pid: Optional[int] = None     # stamped from the child's hello
        self.returncode: Optional[int] = None

    def poll(self) -> Optional[int]:
        if self._link._gone:
            self.returncode = 1
        return self.returncode

    def wait(self, timeout: Optional[float] = None) -> Optional[int]:
        return self.poll()

    def send_signal(self, sig) -> None:    # no local process: sever instead
        self._link.force_sever("signal-on-remote")


class SocketReplicaLink(SubprocessReplica):
    """Parent-side link to a socket-served child: the exact
    :class:`~.subproc.SubprocessReplica` surface (ready/hb/progress/spans/
    quarantine/stop-ladder), carried over framed TCP with an explicit
    reconnect state machine instead of a pipe.

    Three wirings:

    - ``endpoint=None, child_dials=False`` — spawn the child with
      ``--serve-socket --listen 0``, read the ``{"listening": port}``
      bootstrap line off its stdout, dial it;
    - ``endpoint=None, child_dials=True`` — bind an ephemeral listener and
      spawn the child with ``--serve-socket --connect host:port`` (the child
      runs the dial/backoff loop, the parent accepts);
    - ``endpoint="host:port"`` — dial an externally started child
      (``deepspeed-serve --replica-endpoint``); the "process" is a
      :class:`_RemoteProc` whose death is the redial window closing.
    """

    def __init__(self, repo_root: str, env: Optional[Dict[str, str]] = None,
                 prefix_cache: bool = False, cmd: Optional[List[str]] = None,
                 endpoint: Optional[str] = None, child_dials: bool = False,
                 net: Optional[NetConfig] = None, **dims):
        # NOTE: deliberately does NOT chain to SubprocessReplica.__init__ —
        # that constructor spawns a pipe child and a pipe pump. This one
        # recreates the same state surface, then runs sockets. Everything
        # protocol-shaped (wait_ready, abandon_open_lanes, take_spans,
        # tokens/done/wait_tokens, alive) is inherited unchanged.
        self.net = net or NetConfig()
        self.ready: Optional[Dict] = None
        self.hb: Optional[Dict] = None
        self.last_line_at: Optional[float] = None
        self.progress: Dict[int, Dict] = {}
        self.quarantined = 0
        self.quarantined_sample: Optional[str] = None
        self.child_quarantined = 0
        self.escalations = 0
        self._trace_ctx: Dict[int, tuple] = {}
        self.spans: "deque" = deque(maxlen=200_000)
        self.spans_dropped = 0
        self.summary: Optional[Dict] = None
        self._lock = threading.Lock()
        # ---------------------------------------------- reconnect machine
        self.severed = False           # state: CONNECTED(False) | SEVERED(True)
        self.sever_count = 0
        self.reconnects = 0            # successful redials (CONNECTED again)
        self.session: Optional[str] = None   # child's token, from its hello
        self.resumed_last: Optional[bool] = None  # last hello's resume verdict
        self.frames_sent = 0
        self.rtt_last_ms: Optional[float] = None
        self._gone = False             # endpoint mode: redial window closed
        self._closed = False
        self._stopping = False
        self._fault: Optional[_NetFault] = None
        self._decoder = FrameDecoder()
        self._outq: "deque" = deque()  # encoded frames awaiting the socket
        self._out_bytes = 0
        self._sock: Optional[socket.socket] = None
        self._listener: Optional[socket.socket] = None
        self._endpoint = endpoint
        self._child_dials = bool(child_dials)
        self._feed = RegistryFeed()
        self._ticks = 0
        self._last_emit = 0.0
        self._rtts: List[float] = []
        self._severed_at: Optional[float] = None
        # self-pipe: submit() runs on the router thread but the socket is
        # owned by the IO thread — without a wakeup, an enqueued frame sits
        # out the select timeout (up to 50ms) before hitting the wire, which
        # serialises straight into TTFT on slot-starved replicas
        self._wake_r, self._wake_w = socket.socketpair()
        self._wake_r.setblocking(False)
        self._wake_w.setblocking(False)
        # the wire lock serialises sendall across the IO thread and the
        # inline-flush fast path (submit's thread) — without it two drains
        # could interleave partial frames on the wire
        self._wire_lock = threading.Lock()

        if endpoint is not None:
            self.proc = _RemoteProc(self)
        else:
            if child_dials:
                self._listener = socket.socket(socket.AF_INET,
                                               socket.SOCK_STREAM)
                self._listener.setsockopt(socket.SOL_SOCKET,
                                          socket.SO_REUSEADDR, 1)
                self._listener.bind(("127.0.0.1", 0))
                self._listener.listen(4)
            if cmd is None:
                cmd = [sys.executable, "-m",
                       "deepspeed_tpu.inference.serving.subproc",
                       "--serve-socket"]
                if child_dials:
                    port = self._listener.getsockname()[1]
                    cmd += ["--connect", f"127.0.0.1:{port}"]
                else:
                    cmd += ["--listen", "127.0.0.1:0"]
                for k, v in dims.items():
                    cmd += [f"--{k.replace('_', '-')}", str(v)]
                if prefix_cache:
                    cmd += ["--prefix-cache"]
            full_env = dict(os.environ)
            full_env.setdefault("JAX_PLATFORMS", "cpu")
            try:
                import jax
                full_env.setdefault(
                    "JAX_THREEFRY_PARTITIONABLE",
                    "1" if jax.config.jax_threefry_partitionable else "0")
            except Exception:
                pass
            if env:
                full_env.update(env)
            self.proc = subprocess.Popen(
                cmd, cwd=repo_root, env=full_env, text=True,
                stdin=subprocess.DEVNULL, stdout=subprocess.PIPE,
                stderr=subprocess.DEVNULL)
        self._io = threading.Thread(target=self._io_loop, daemon=True)
        self._io.start()

    # ----------------------------------------------------------- connection
    def _bootstrap_port(self) -> Optional[int]:
        """Spawn-listen mode: the child prints ``{"listening": port}`` on its
        REAL stdout before any heavy import — the one line stdio still
        carries."""
        deadline = time.monotonic() + self.net.connect_timeout_s
        while time.monotonic() < deadline and not self._closed:
            if self.proc.poll() is not None:
                return None
            line = self.proc.stdout.readline()
            if not line:
                return None
            try:
                obj = json.loads(line)
            except ValueError:
                continue               # stray interpreter noise: skip
            if "listening" in obj:
                return int(obj["listening"])
        return None

    def _connect_once(self) -> Optional[socket.socket]:
        """One CONNECTED attempt: dial (or accept), then open with the hello
        frame carrying the resume token."""
        try:
            if self._child_dials:
                self._listener.settimeout(1.0)
                try:
                    s, _ = self._listener.accept()
                except socket.timeout:
                    return None
            else:
                host, port = self._addr
                s = socket.create_connection((host, port), timeout=5.0)
        except OSError:
            return None
        s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        # the socket stays BLOCKING: reads are gated by select() and sends by
        # a bounded timeout — a nonblocking sendall can partial-write a frame
        # and desynchronize the stream, which the CRC would then quarantine
        # as a loss we inflicted on ourselves
        s.settimeout(5.0)
        try:
            hello = {"hello": {"proto": PROTO_VERSION, "resume": self.session}}
            s.sendall(encode_frame(json.dumps(hello).encode()))
        except OSError:
            try:
                s.close()
            except OSError:
                pass
            return None
        return s

    def _resolve_addr(self) -> bool:
        """Fill ``self._addr`` once (endpoint string or bootstrap port)."""
        if getattr(self, "_addr", None) is not None:
            return True
        if self._endpoint is not None:
            host, _, port = self._endpoint.rpartition(":")
            self._addr = (host or "127.0.0.1", int(port))
            return True
        if self._child_dials:
            self._addr = ("", 0)           # accept side: no dial target
            return True
        port = self._bootstrap_port()
        if port is None:
            return False
        # keep draining the child's stdout so a chatty interpreter can never
        # fill the pipe and wedge the child on a stray print
        threading.Thread(target=lambda: deque(self.proc.stdout, maxlen=0),
                         daemon=True).start()
        self._addr = ("127.0.0.1", port)
        return True

    # ------------------------------------------------------------- IO thread
    def _io_loop(self) -> None:
        self._addr = None
        if not self._resolve_addr():
            self._gone = True
            return
        attempt = 0
        first = True
        dial_started = time.monotonic()
        while not self._closed:
            if self.proc.poll() is not None and self._endpoint is None:
                return                 # child process died: supervisor's arm
            sock = self._connect_once()
            if sock is None:
                attempt += 1
                window = (self.net.connect_timeout_s if first
                          else self.net.redial_window_s)
                start = self._severed_at or dial_started
                if time.monotonic() - start > window:
                    self._gone = True  # reconnect machine gave up
                    return
                if not self._child_dials:
                    # bounded exponential backoff between dials
                    time.sleep(min(self.net.redial_backoff_max_s,
                                   self.net.redial_backoff_base_s
                                   * (2 ** min(attempt, 16))))
                continue
            attempt = 0
            with self._lock:
                self._sock = sock
                if not first:
                    self.reconnects += 1
                self.severed = False
                self._severed_at = None
            if not first:
                logger.warning("[net] link re-established "
                               f"(reconnect #{self.reconnects})")
            first = False
            self._serve_conn(sock)     # returns on sever
            if self._closed or self._stopping:
                return

    def _serve_conn(self, sock: socket.socket) -> None:
        last_ping = 0.0
        while not self._closed:
            now = time.monotonic()
            fault = self._fault
            if fault is not None and not fault.active(now):
                self._fault = fault = None
            # ---------------------------------------------------- write side
            if fault is not None and fault.mode == "partition":
                with self._lock:       # silence both ways: outbound discarded
                    self._outq.clear()
                    self._out_bytes = 0
            else:
                if now - last_ping >= self.net.ping_interval_s:
                    last_ping = now
                    self._enqueue({"ping": self._ticks, "t": now})
                try:
                    with self._wire_lock:
                        self._drain_outq(sock)
                except OSError:        # incl. a send timeout: the frame may
                    self._on_sever(sock, "send")   # be partial — sever, the
                    return             # peer's decoder resyncs on its CRC
            # ----------------------------------------------------- read side
            try:
                r, _, _ = select.select([sock, self._wake_r], [], [], 0.05)
            except (OSError, ValueError):
                self._on_sever(sock, "select")
                return
            if self._wake_r in r:
                try:
                    while self._wake_r.recv(4096):
                        pass
                except (OSError, BlockingIOError):
                    pass
                if sock not in r:
                    continue           # loop back to the write side at once
            if sock in r:
                try:
                    data = sock.recv(65536)
                except socket.timeout:
                    data = None
                except OSError:
                    self._on_sever(sock, "recv")
                    return
                if data == b"":
                    self._on_sever(sock, "eof")
                    return
                if data:
                    fault = self._fault
                    if fault is not None and fault.active():
                        if fault.mode == "partition":
                            data = b""       # inbound silence
                        elif fault.mode == "delay":
                            time.sleep(min(fault.value / 1e3,
                                           max(0.0, fault.until
                                               - time.monotonic())))
                        elif fault.mode == "drop":
                            data = fault.corrupt(data)
                    if data:
                        for payload in self._decoder.feed(data):
                            self._handle_payload(payload)
            self._maybe_emit()

    def _on_sever(self, sock: socket.socket, why: str) -> None:
        try:
            sock.close()
        except OSError:
            pass
        with self._lock:
            if self._sock is sock:
                self._sock = None
            already = self.severed
            self.severed = True
            # the last hello's resume verdict is stale the moment the
            # connection dies: readers polling for the NEXT hello's verdict
            # (sever-resume probes) must see "unknown", not the old answer
            self.resumed_last = None
            if self._severed_at is None:
                self._severed_at = time.monotonic()
            if not already:
                self.sever_count += 1
        if not self._closed and not self._stopping:
            logger.warning(f"[net] connection severed ({why}); "
                           "reconnect machine engaged")

    def _handle_payload(self, payload: bytes) -> None:
        try:
            obj = json.loads(payload)
            if not isinstance(obj, dict):
                raise ValueError("frame payload is not an object")
        except (ValueError, UnicodeDecodeError):
            with self._lock:
                self.quarantined += 1
                self.quarantined_sample = repr(payload[:200])
            return
        with self._lock:
            self.last_line_at = time.monotonic()
            if "pong" in obj:
                t = obj.get("t")
                if isinstance(t, (int, float)):
                    rtt = max(0.0, (time.monotonic() - float(t)) * 1e3)
                    self.rtt_last_ms = rtt
                    self._rtts.append(rtt)
                return
            if "ready" in obj:
                self.ready = obj
                if obj.get("session"):
                    self.session = str(obj["session"])
                self.resumed_last = bool(obj.get("resumed"))
                if isinstance(self.proc, _RemoteProc):
                    self.proc.pid = obj.get("pid")
            elif "hb" in obj:
                obj["_rx_t"] = time.time()
                self.hb = obj
            elif "badline" in obj:
                self.child_quarantined += 1
            elif "summary" in obj:
                self.summary = obj["summary"]
            elif "spans" in obj:
                overflow = (len(self.spans) + len(obj["spans"])
                            - self.spans.maxlen)
                if overflow > 0:
                    self.spans_dropped += overflow
                self.spans.extend(obj["spans"])
            elif "id" in obj:
                rid = int(obj["id"])
                self.progress[rid] = obj
                if obj.get("done"):
                    self._trace_ctx.pop(rid, None)

    # ------------------------------------------------------------- telemetry
    def _maybe_emit(self) -> None:
        now = time.monotonic()
        if now - self._last_emit < self.net.emit_interval_s:
            return
        self._last_emit = now
        self._ticks += 1
        with self._lock:
            rtts, self._rtts = self._rtts, []
        events = [
            ("net/frames_total",
             float(self._decoder.frames + self.frames_sent), self._ticks),
            ("net/reconnects_total", float(self.reconnects), self._ticks),
            ("net/quarantined_frames_total",
             float(self._decoder.quarantined), self._ticks),
            ("net/partition_trips_total", float(self.sever_count),
             self._ticks),
        ]
        events += [("net/rtt_ms", r, self._ticks) for r in rtts]
        self._feed.record_events(events)

    # ------------------------------------------------------------ chaos seam
    def net_fault(self, mode: str, value: float, duration_s: float) -> None:
        """Inject a transport fault (chaos ``net:`` grammar): ``partition``
        (silence both ways), ``delay`` (``value`` ms added to the read path),
        ``drop`` (``value`` probability of corrupting a read — CRC quarantine
        + resync). Persists across redials until the window expires."""
        if mode not in ("partition", "delay", "drop"):
            raise ValueError(f"unknown net fault mode {mode!r}")
        self._fault = _NetFault(mode, value, duration_s)
        logger.warning(f"[net] fault injected: mode={mode} value={value} "
                       f"for {duration_s}s")

    def force_sever(self, why: str = "forced") -> None:
        """Drop the connection NOW (evict-then-redial path — the endpoint
        analogue of a kill)."""
        with self._lock:
            sock = self._sock
        if sock is not None:
            self._on_sever(sock, why)

    # ------------------------------------------------------------ frame send
    def _enqueue(self, obj: Dict, enforce_bound: bool = False) -> None:
        frame = encode_frame(json.dumps(obj).encode())
        with self._lock:
            if enforce_bound and (self._out_bytes + len(frame)
                                  > self.net.write_buffer_max):
                raise QueueFullError(0.25)
            self._outq.append(frame)
            self._out_bytes += len(frame)
        self._try_flush_inline()
        try:
            self._wake_w.send(b"\x00")     # rouse the IO thread mid-select
        except (OSError, BlockingIOError):
            pass                           # full pipe already guarantees a wake

    def _drain_outq(self, sock: socket.socket) -> None:
        """Send every queued frame, FIFO. Caller holds ``_wire_lock``; an
        OSError propagates with the in-flight frame still queued (the frame
        may be partial on the wire — the peer's CRC resync absorbs that)."""
        while True:
            with self._lock:
                if not self._outq:
                    return
                frame = self._outq[0]
            sock.sendall(frame)
            self.frames_sent += 1
            with self._lock:
                self._outq.popleft()
                self._out_bytes -= len(frame)

    def _try_flush_inline(self) -> None:
        """Opportunistic same-thread flush: a submit lands on the wire for
        one syscall instead of a cross-thread GIL handoff (which costs up to
        the switch interval per frame — it serialises straight into TTFT on
        slot-starved replicas). Skipped whenever the IO thread owns the wire,
        a fault is staged (partition semantics live in the IO loop), or the
        link is down — the wake pipe covers those."""
        if not self._wire_lock.acquire(blocking=False):
            return
        try:
            with self._lock:
                sock = self._sock
            if sock is None or self._fault is not None:
                return
            try:
                self._drain_outq(sock)
            except OSError:
                self._on_sever(sock, "send")
        finally:
            self._wire_lock.release()

    # ----------------------------------------- SubprocessReplica overrides
    def submit(self, rid: int, prompt, max_new_tokens: int, seed: int = 0,
               eos_token_id: Optional[int] = None,
               deadline_s: Optional[float] = None,
               trace_id: Optional[str] = None,
               parent_span: Optional[str] = None) -> None:
        req = {"id": int(rid), "prompt": [int(t) for t in prompt],
               "max_new_tokens": int(max_new_tokens), "seed": int(seed),
               "eos_token_id": eos_token_id}
        if deadline_s is not None:
            req["deadline_s"] = float(deadline_s)
        if trace_id:
            req["trace_id"] = trace_id
            req["parent_span"] = parent_span
            self._trace_ctx[int(rid)] = (trace_id, parent_span,
                                         time.monotonic())
        self._enqueue(req, enforce_bound=True)

    def cancel(self, rid: int) -> None:
        try:
            self._enqueue({"cmd": "cancel", "id": int(rid)})
        except QueueFullError:
            pass                       # a severed/backed-up link is already
        #   the stronger cancellation (the child cancels on re-hello)

    def sigkill(self) -> None:
        if isinstance(self.proc, _RemoteProc):
            self.force_sever("sigkill-on-remote")
            return
        super().sigkill()

    def stop(self, drain_s: float = 10.0, term_s: float = 5.0) -> int:
        """Stop escalation ladder over TCP: stop frame + drain deadline ->
        **connection close** (the new rung: a wedged link cannot hang the
        drain) -> SIGTERM grace -> SIGKILL. Endpoint links stop at the
        connection-close rung — there is no process to signal."""
        self._stopping = True
        if self.proc.poll() is None:
            self._enqueue({"cmd": "stop"})
            deadline = time.monotonic() + drain_s
            while time.monotonic() < deadline and self.proc.poll() is None:
                time.sleep(0.02)
            if self.proc.poll() is None:
                self.escalations += 1      # rung: close the connection
                self.force_sever("stop-ladder")
                deadline = time.monotonic() + min(1.0, term_s)
                while (time.monotonic() < deadline
                       and self.proc.poll() is None):
                    time.sleep(0.02)
            if self.proc.poll() is None \
                    and not isinstance(self.proc, _RemoteProc):
                self.escalations += 1              # rung: SIGTERM grace
                try:
                    self.proc.send_signal(15)
                except ProcessLookupError:
                    pass
                try:
                    self.proc.wait(timeout=term_s)
                except subprocess.TimeoutExpired:
                    self.escalations += 1          # rung: SIGKILL backstop
                    try:
                        self.proc.send_signal(9)
                    except ProcessLookupError:
                        pass
                    self.proc.wait(timeout=30)
        self.close()
        return self.proc.returncode

    def close(self) -> None:
        """Tear the link down (no process action): sockets closed, IO thread
        released."""
        self._closed = True
        with self._lock:
            sock, self._sock = self._sock, None
        for s in (sock, self._listener, self._wake_r, self._wake_w):
            if s is not None:
                try:
                    s.close()
                except OSError:
                    pass

    @property
    def fault_active(self) -> bool:
        """Whether a chaos net fault currently governs this link — the
        breaker's evidence that an outage is transport-level, not a wedged
        child."""
        fault = self._fault
        return bool(fault is not None and fault.active())

    @property
    def alive(self) -> bool:
        return self.proc.poll() is None


# ============================================================= child side
class ChildSocketIO:
    """The child's transport: accept (``--listen``) or dial (``--connect``)
    one parent connection at a time, decode frames into the protocol v1
    ``lines`` the child main loop already consumes, and frame every ``emit``
    back out.

    Session contract: one token per process. Each new connection must open
    with the parent's hello (proto checked — a drifted parent is refused with
    an error frame, not mis-parsed); the child answers with the cached ready
    line + ``session``/``resumed`` and synthesizes a ``cancel_all`` so work
    orphaned by the dead connection frees its slots (the parent already
    evicted it with prefixes)."""

    def __init__(self, lines: List[str], term: threading.Event,
                 listen: Optional[str] = None, connect: Optional[str] = None):
        if (listen is None) == (connect is None):
            raise ValueError("--serve-socket needs exactly one of "
                             "--listen or --connect")
        self.lines = lines
        self.term = term
        self.session = os.urandom(8).hex()
        self.dropped = 0               # emits with no live connection
        self.quarantined = 0           # wire-level resync events (decoder)
        self._ready_obj: Optional[Dict] = None
        self._resumed = False
        self._conn: Optional[socket.socket] = None
        self._lock = threading.Lock()
        self._srv: Optional[socket.socket] = None
        self._connect = connect
        self.port: Optional[int] = None
        if listen is not None:
            host, _, port = str(listen).rpartition(":")
            self._srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
            self._srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
            self._srv.bind((host or "0.0.0.0", int(port or listen)))
            self._srv.listen(4)
            self.port = self._srv.getsockname()[1]
            # bootstrap line on the REAL stdout, before any heavy import:
            # the spawning parent learns the ephemeral port from it
            print(json.dumps({"listening": self.port}), flush=True)
        threading.Thread(target=self._run, daemon=True).start()

    # ------------------------------------------------------------------ emit
    def emit(self, obj: Dict) -> None:
        if "ready" in obj:
            self._ready_obj = dict(obj)
            obj = {**obj, "session": self.session, "resumed": self._resumed}
        frame = encode_frame(json.dumps(obj).encode())
        with self._lock:
            conn = self._conn
        if conn is None:
            self.dropped += 1
            return
        try:
            conn.sendall(frame)
        except OSError:
            self.dropped += 1

    # ------------------------------------------------------------- transport
    def _run(self) -> None:
        backoff = 0.05
        dial_deadline = time.monotonic() + 120.0
        while not self.term.is_set():
            sock = None
            if self._srv is not None:
                self._srv.settimeout(0.5)
                try:
                    sock, _ = self._srv.accept()
                except socket.timeout:
                    continue
                except OSError:
                    return
            else:
                host, _, port = self._connect.rpartition(":")
                try:
                    sock = socket.create_connection(
                        (host or "127.0.0.1", int(port)), timeout=2.0)
                    backoff = 0.05
                    dial_deadline = time.monotonic() + 120.0
                except OSError:
                    if time.monotonic() > dial_deadline:
                        self.term.set()    # parent gone for good: drain+exit
                        return
                    time.sleep(backoff)
                    backoff = min(2.0, backoff * 2)
                    continue
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            self._serve(sock)
            with self._lock:
                if self._conn is sock:
                    self._conn = None
            try:
                sock.close()
            except OSError:
                pass

    def _serve(self, sock: socket.socket) -> None:
        dec = FrameDecoder()
        hello_ok = False
        while not self.term.is_set():
            try:
                sock.settimeout(0.5)
                data = sock.recv(65536)
            except socket.timeout:
                continue
            except OSError:
                return
            if data == b"":
                return                 # parent hung up: await the next one
            q0 = dec.quarantined
            payloads = dec.feed(data)
            self.quarantined += dec.quarantined - q0
            for payload in payloads:
                try:
                    obj = json.loads(payload)
                    if not isinstance(obj, dict):
                        raise ValueError("not an object")
                except (ValueError, UnicodeDecodeError):
                    # protocol-level quarantine stays with the main loop:
                    # hand the raw line through as-is
                    self.lines.append(payload.decode("utf-8", "replace"))
                    continue
                if not hello_ok:
                    hello = obj.get("hello")
                    if not isinstance(hello, dict) \
                            or hello.get("proto") != PROTO_VERSION:
                        # a drifted (or absent) hello is refused, never
                        # mis-parsed — mirror of the parent's versioned check
                        try:
                            sock.sendall(encode_frame(json.dumps(
                                {"badline": "hello",
                                 "error": f"proto={hello.get('proto') if isinstance(hello, dict) else None!r}"
                                          f" != {PROTO_VERSION}"}).encode()))
                        except OSError:
                            pass
                        return
                    hello_ok = True
                    self._resumed = hello.get("resume") == self.session
                    with self._lock:
                        self._conn = sock
                    # free slots orphaned by the previous connection BEFORE
                    # the ready goes out: the parent has already evicted that
                    # work with prefixes, and a peer that has seen the ready
                    # may rely on the cancel having landed
                    self.lines.append(json.dumps({"cmd": "cancel_all"}))
                    if self._ready_obj is not None:
                        self.emit(self._ready_obj)   # re-adds session/resumed
                    continue
                if "ping" in obj:
                    self.emit({"pong": obj["ping"], "t": obj.get("t")})
                    continue
                self.lines.append(payload.decode("utf-8", "replace"))

    def close(self) -> None:
        with self._lock:
            conn, self._conn = self._conn, None
        for s in (conn, self._srv):
            if s is not None:
                try:
                    s.close()
                except OSError:
                    pass
