"""Stable-Diffusion serving engine: AOT-compiled txt2img on the chip.

The reference serves diffusers pipelines by injecting optimized UNet/VAE/CLIP
containers and replaying them under CUDA graphs (``model_implementations/
diffusers/unet.py:1`` — the UNet wrapper that enables cuda-graph capture; policy
routing ``module_inject/replace_module.py:213``). The TPU analogue: the whole
denoising loop — text encode → K DDIM steps of classifier-free-guided UNet →
VAE decode — is ONE jitted program (``lax.fori_loop`` over steps), so the chip
replays a fixed compiled graph with zero host round-trips, which is exactly what
cuda-graph capture buys the reference.

Scheduler: DDIM (eta=0) over the SD-1.x linear-beta schedule.
"""

from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np

from jax.sharding import NamedSharding, PartitionSpec as P

from ..models.diffusion import (CLIPTextConfig, CLIPTextEncoder, UNet2DCondition,
                                UNetConfig, VAEConfig, VAEDecoder)
from ..parallel.mesh import AXIS_TENSOR, MeshSpec, get_global_mesh, set_global_mesh
from ..utils.logging import log_dist, logger

# attention/ff projection names → Megatron column/row parallelism over the
# tensor axis (the sharding the reference's containers apply to UNet/CLIP
# attention, ``module_inject/containers/unet.py`` / ``clip.py``); convs and
# norms replicate (their FLOPs are spatial, not channel-bound)
_COL_NAMES = ("to_q", "to_k", "to_v", "net_0_proj",
              "q_proj", "k_proj", "v_proj", "fc1")
_ROW_NAMES = ("to_out_0", "net_2", "out_proj", "fc2")


def shard_diffusion_params(params, mesh: MeshSpec):
    """Place attention column/row kernels sharded over the tensor axis;
    everything else replicated."""
    tp = mesh.size(AXIS_TENSOR)

    def rec(node, mod_name):
        if isinstance(node, dict):
            return {k: rec(v, k if isinstance(v, dict) else mod_name)
                    for k, v in node.items()}
        spec = P(*([None] * node.ndim))
        if tp > 1 and node.ndim == 2:
            # suffix match: CLIP params are flat-named (layers_0_q_proj, ...)
            if any(mod_name.endswith(n) for n in _COL_NAMES) \
                    and node.shape[1] % tp == 0:
                spec = P(None, AXIS_TENSOR)
            elif any(mod_name.endswith(n) for n in _ROW_NAMES) \
                    and node.shape[0] % tp == 0:
                spec = P(AXIS_TENSOR, None)
        return jax.device_put(node, NamedSharding(mesh.mesh, spec))

    return rec(params, "")


def ddim_schedule(num_train_timesteps: int = 1000, beta_start: float = 0.00085,
                  beta_end: float = 0.012):
    """SD's scaled-linear beta schedule → cumulative alphas (fp32)."""
    betas = jnp.linspace(beta_start ** 0.5, beta_end ** 0.5,
                         num_train_timesteps, dtype=jnp.float32) ** 2
    return jnp.cumprod(1.0 - betas)


class DiffusionInferenceEngine:
    """txt2img over (CLIP text, UNet, VAE decoder) flax params.

    ``generate(prompt_ids, negative_ids, steps, guidance_scale)`` returns images
    in [0, 1], running the full loop as one compiled dispatch."""

    def __init__(self, unet_config: UNetConfig, unet_params: Any,
                 clip_config: CLIPTextConfig, clip_params: Any,
                 vae_config: VAEConfig, vae_params: Any,
                 num_train_timesteps: int = 1000,
                 mesh_spec: Optional[MeshSpec] = None,
                 set_alpha_to_one: bool = False):
        self.unet_config = unet_config
        self.clip_config = clip_config
        self.vae_config = vae_config
        self.unet = UNet2DCondition(unet_config)
        self.clip = CLIPTextEncoder(clip_config)
        self.vae = VAEDecoder(vae_config)
        self.params = {"unet": unet_params, "clip": clip_params,
                       "vae": vae_params}
        self.mesh_spec = mesh_spec
        if mesh_spec is not None:
            # the diffusion graph reads no global mesh — placement is explicit
            # NamedShardings on the params — so only install the global mesh
            # when the slot is free; NEVER clobber another engine's active mesh
            # (a training engine constructed earlier in the process would have
            # its sharding context silently swapped out from under it)
            existing = get_global_mesh()
            if existing is None:
                set_global_mesh(mesh_spec)
            elif existing is not mesh_spec:
                logger.warning(
                    "[diffusion] a different global mesh is already installed; "
                    "leaving it in place — this engine's shardings are "
                    "self-contained (explicit NamedShardings), but mixed-mesh "
                    "processes should scope engines to separate processes")
            self.params = shard_diffusion_params(self.params, mesh_spec)
        self.alphas_cumprod = ddim_schedule(num_train_timesteps)
        self.num_train_timesteps = num_train_timesteps
        # Final-step alpha when prev_t < 0: diffusers' DDIMScheduler knob.
        # SD-1.x ships ``set_alpha_to_one=false`` → final_alpha_cumprod =
        # alphas_cumprod[0] (the first schedule entry), NOT 1.0 — using 1.0
        # diverges from diffusers on the very last denoising step.
        self.final_alpha_cumprod = (jnp.float32(1.0) if set_alpha_to_one
                                    else self.alphas_cumprod[0])
        self._fns: Dict[Any, Any] = {}
        log_dist(
            f"diffusion engine ready: unet {unet_config.block_out_channels} "
            f"clip d{clip_config.hidden_size} vae {vae_config.block_out_channels}",
            ranks=[0])

    # ------------------------------------------------------------------ the loop
    def _build(self, steps: int):
        cfg = self.unet_config
        alphas = self.alphas_cumprod
        # DDIM timestep subsequence: LEADING spacing + steps_offset=1 — the
        # SD-1.x DDIMScheduler configuration ([981, 961, ..., 1] at 50 steps)
        step_idx = (jnp.arange(steps, dtype=jnp.int32)[::-1] *
                    (self.num_train_timesteps // steps)) + 1

        def run(params, prompt_ids, negative_ids, guidance, rng):
            text = self.clip.apply({"params": params["clip"]}, prompt_ids)
            uncond = self.clip.apply({"params": params["clip"]}, negative_ids)
            ctx = jnp.concatenate([uncond, text], axis=0)     # (2b, t, d)
            b = prompt_ids.shape[0]
            s = cfg.sample_size
            latents = jax.random.normal(rng, (b, s, s, cfg.in_channels),
                                        jnp.float32)

            def body(i, lat):
                t = step_idx[i]
                prev_t = t - self.num_train_timesteps // steps
                lat2 = jnp.concatenate([lat, lat], axis=0)
                eps = self.unet.apply(
                    {"params": params["unet"]}, lat2,
                    jnp.full((2 * b,), t, jnp.int32), ctx)
                eps_u, eps_c = jnp.split(eps, 2, axis=0)
                eps = eps_u + guidance * (eps_c - eps_u)
                a_t = alphas[t]
                a_prev = jnp.where(prev_t >= 0, alphas[jnp.maximum(prev_t, 0)],
                                   self.final_alpha_cumprod)
                x0 = (lat - jnp.sqrt(1.0 - a_t) * eps) / jnp.sqrt(a_t)
                return jnp.sqrt(a_prev) * x0 + jnp.sqrt(1.0 - a_prev) * eps

            latents = jax.lax.fori_loop(0, steps, body, latents)
            img = self.vae.apply({"params": params["vae"]},
                                 latents / self.vae_config.scaling_factor)
            return jnp.clip(img * 0.5 + 0.5, 0.0, 1.0)

        return jax.jit(run, static_argnums=())

    def generate(self, prompt_ids, negative_ids=None, steps: int = 50,
                 guidance_scale: float = 7.5,
                 seed: int = 0) -> np.ndarray:
        """(b, 77) int32 token ids → (b, H, W, 3) float images in [0, 1].

        For diffusers-equivalent classifier-free guidance, pass the TOKENIZED
        empty prompt (BOS + EOS + padding per your tokenizer) as
        ``negative_ids`` — token ids are tokenizer-specific, so this engine
        cannot synthesize them. The all-zeros default is a placeholder
        unconditional sequence, not the empty-prompt encoding."""
        prompt_ids = jnp.asarray(np.asarray(prompt_ids), jnp.int32)
        if negative_ids is None:
            negative_ids = jnp.zeros_like(prompt_ids)
        else:
            negative_ids = jnp.asarray(np.asarray(negative_ids), jnp.int32)
        if steps not in self._fns:
            self._fns[steps] = self._build(steps)
        out = self._fns[steps](self.params, prompt_ids, negative_ids,
                               jnp.float32(guidance_scale),
                               jax.random.PRNGKey(seed))
        return np.asarray(out)


def init_diffusion_inference(unet_sd: Dict[str, Any], clip_model,
                             vae_sd: Dict[str, Any],
                             unet_config: Optional[UNetConfig] = None,
                             vae_config: Optional[VAEConfig] = None,
                             mesh_spec: Optional[MeshSpec] = None,
                             set_alpha_to_one: bool = False
                             ) -> DiffusionInferenceEngine:
    """``generic_injection`` surface: torch state dicts (diffusers naming) + the
    HF CLIP text model → a fully converted, compiled TPU engine."""
    from ..module_inject.diffusers_policies import (convert_clip_text,
                                                   convert_unet_state_dict,
                                                   convert_vae_decoder_state_dict)
    unet_config = unet_config or UNetConfig()
    vae_config = vae_config or VAEConfig()
    unet_params = convert_unet_state_dict(unet_sd, unet_config)
    vae_params = convert_vae_decoder_state_dict(vae_sd, vae_config)
    clip_config, clip_params = convert_clip_text(clip_model)
    return DiffusionInferenceEngine(unet_config, unet_params, clip_config,
                                    clip_params, vae_config, vae_params,
                                    mesh_spec=mesh_spec,
                                    set_alpha_to_one=set_alpha_to_one)
