"""Speculative decoding: draft-propose / one-pass-verify on the decode path.

The r5 north-star measured 7B decode as weight-bandwidth-bound (14.1 GB of
weights stream per step; batching amortizes the stream across rows but
single-stream latency is stuck at one token per pass). Speculation is the
single-stream analogue of batching: a cheap *draft proposer* guesses k tokens,
ONE target forward over the (k+1)-token window scores all of them
(``prefix_fill`` masked attention — the PR 9 suffix-prefill mode), and the
accept rule keeps the longest valid prefix plus one bonus token. Same weight
bytes as a single decode step, up to k+1 tokens out.

Pieces:

- **proposers** — :class:`NgramProposer` (self-speculative: match the recent
  suffix of the prompt+generated stream against its own history; no second
  model, deterministic, CPU-testable) and :class:`DraftModelProposer` (a
  second tiny engine greedy-decodes the draft). Both are deterministic, so a
  draft is a point-mass proposal distribution — rejection sampling below
  stays exact for either.
- **accept rules** — :func:`greedy_accept` is exact longest-prefix-match
  against the verify argmax, which makes greedy speculative output
  bit-identical to non-speculative greedy decode *by construction*: every
  emitted token IS a target argmax. :func:`accept_tokens` adds the sampled
  path: per-slot-keyed rejection sampling (accept draft x with prob
  ``p_target(x)``, resample the rejection from the renormalized residual)
  which preserves the target distribution exactly.
- **rollback** — there is none to do on the KV side: the verify step writes
  the whole window's K/V at rows ``[cache_len, cache_len+valid)`` and the
  caller simply advances ``cache_len`` by the number of tokens actually
  committed. Rows beyond the new ``cache_len`` are attention-masked and get
  overwritten by later appends — a page-table/cache_len rewind, never a copy
  (the same structural argument that makes paged release O(pages)).

Key-stream contract (sampled path): position ``i`` of a request's stream uses
``fold_in(fold_in(base_key, seed), step0 + i)`` — the same per-slot ``(seed,
step)`` coordinates as ``decode_fns.make_slot_select_fn`` — so a request's
sampled tokens are a pure function of its own seed and token index,
independent of slot placement and co-batching. A fully-accepted round's bonus
draw bit-matches ``make_slot_select_fn``'s stream for that position; a
rejection consumes the residual stream instead, so an individual sampled
trajectory may diverge from the speculation-off one after a rejection — what
is preserved exactly is the per-position *distribution* (and greedy output,
which is bit-identical always). The accept test and the residual resample
each fold a distinct constant so the three draws per position never alias.
"""

from dataclasses import dataclass
from typing import List, Tuple

import numpy as np

#: per-position key folds for the sampled accept path (must differ so the
#: accept-uniform and the residual-resample never share a stream with the
#: bonus-token categorical, which uses the UNFOLDED per-step key to match
#: ``make_slot_select_fn`` exactly on a full acceptance)
_FOLD_ACCEPT = 1
_FOLD_RESAMPLE = 2


@dataclass
class SpeculativeConfig:
    """Knobs for the draft-propose / one-pass-verify loop."""
    k: int = 4                      # draft tokens per verify window
    proposer: str = "ngram"         # "ngram" | "draft_model"
    ngram_max: int = 4              # longest suffix-match tried, down to min
    ngram_min: int = 1
    draft_engine: object = None     # tiny InferenceEngine for "draft_model"

    def __post_init__(self):
        if self.k < 1:
            raise ValueError(f"spec k must be >= 1, got {self.k}")
        if self.proposer not in ("ngram", "draft_model"):
            raise ValueError(f"proposer must be 'ngram' or 'draft_model', "
                             f"got {self.proposer!r}")
        if not 1 <= self.ngram_min <= self.ngram_max:
            raise ValueError(f"need 1 <= ngram_min <= ngram_max, got "
                             f"({self.ngram_min}, {self.ngram_max})")


# ------------------------------------------------------------------ proposers
class NgramProposer:
    """Self-speculative n-gram draft: match the stream's recent suffix against
    its own history and propose the continuation of the most recent earlier
    occurrence.

    For ``n`` from ``ngram_max`` down to ``ngram_min``: find the latest
    position ``< len - n`` where the last ``n`` tokens of ``context`` occurred
    before, and propose the (up to) ``k`` tokens that followed. Longest match
    wins; no match proposes nothing (the verify step then degenerates to a
    plain single-token decode). Deterministic: the proposal is a pure function
    of the token stream, so checkpointless retry re-derives identical drafts
    wherever the request lands."""

    deterministic = True

    def __init__(self, ngram_max: int = 4, ngram_min: int = 1):
        if not 1 <= ngram_min <= ngram_max:
            raise ValueError(f"need 1 <= ngram_min <= ngram_max, got "
                             f"({ngram_min}, {ngram_max})")
        self.ngram_max = int(ngram_max)
        self.ngram_min = int(ngram_min)

    def propose(self, context: np.ndarray, k: int) -> np.ndarray:
        context = np.asarray(context, dtype=np.int32).reshape(-1)
        T = context.size
        if k < 1 or T < self.ngram_min + 1:
            return np.zeros(0, np.int32)
        for n in range(min(self.ngram_max, T - 1), self.ngram_min - 1, -1):
            suffix = context[T - n:]
            # latest earlier occurrence: scan right-to-left over start indices
            # whose match window ends strictly before the suffix itself
            for start in range(T - n - 1, -1, -1):
                if np.array_equal(context[start:start + n], suffix):
                    cont = context[start + n:start + n + k]
                    if cont.size:
                        return cont.astype(np.int32)
                    break           # suffix-adjacent match: try a shorter n
        return np.zeros(0, np.int32)


class DraftModelProposer:
    """Small-draft-model proposer: a second (tiny) ``InferenceEngine`` greedy-
    decodes ``k`` continuation tokens from the context tail. Greedy drafting
    keeps the proposal deterministic — a point-mass distribution — so the
    rejection-sampling accept rule stays exact without needing the draft's
    probabilities on the wire."""

    deterministic = True

    def __init__(self, engine):
        self.engine = engine
        self.cap = int(engine._config.max_out_tokens)

    def propose(self, context: np.ndarray, k: int) -> np.ndarray:
        context = np.asarray(context, dtype=np.int32).reshape(-1)
        if k < 1 or context.size < 1:
            return np.zeros(0, np.int32)
        # tail-slice so prompt + k fits the draft engine's own KV cap
        keep = max(self.cap - k, 1)
        tail = context[-keep:]
        out = self.engine.generate(tail[None, :], max_new_tokens=k,
                                   do_sample=False)
        return np.asarray(out[0, tail.size:], dtype=np.int32)


def make_proposer(cfg: SpeculativeConfig):
    if cfg.proposer == "draft_model":
        if cfg.draft_engine is None:
            raise ValueError("proposer='draft_model' needs a draft_engine")
        return DraftModelProposer(cfg.draft_engine)
    return NgramProposer(cfg.ngram_max, cfg.ngram_min)


# ---------------------------------------------------------------- accept rules
def greedy_accept(draft: np.ndarray, target_argmax: np.ndarray) -> int:
    """Longest prefix of ``draft`` matching the verify argmax at the same
    positions. Every accepted token equals the token greedy decode would have
    picked there — bit-identity with the non-speculative stream is structural,
    not numerical luck."""
    n = int(min(draft.size, target_argmax.size))
    a = 0
    while a < n and int(draft[a]) == int(target_argmax[a]):
        a += 1
    return a


def accept_tokens(draft: np.ndarray, logits: np.ndarray, *,
                  sampling: Tuple[bool, float, int, float],
                  base_key, seed: int, step0: int) -> Tuple[List[int], int]:
    """Accept/reject one slot's draft against its verify-window logits.

    ``draft``: (L,) proposed tokens; ``logits``: (L+1, V) target logits at
    window positions 0..L (position i scored the prefix through draft i-1).
    Returns ``(emitted, accepted)``: up to L+1 emitted tokens (accepted draft
    prefix + one bonus/correction token) and the accepted-draft count.

    Greedy: exact longest-prefix-match, bonus = argmax at the first mismatch
    (or at L on a full match) — the emitted sequence is exactly what
    step-by-step greedy decode would produce. Sampled: per-position rejection
    sampling against the point-mass draft — accept token x with probability
    ``p(x)``, on rejection emit a sample from the renormalized residual
    ``p`` minus the rejected mass and stop; a full acceptance draws the bonus
    with the plain per-step key, bit-matching ``make_slot_select_fn``'s
    stream for that position. Either way the emitted tokens are distributed
    exactly as the target distribution (q is a point mass: accept prob p(x)
    puts mass p(x) on x, and the residual path distributes 1-p(x) over y≠x
    as p(y)/(1-p(x)) — total mass p(y) for every y)."""
    import jax
    import jax.numpy as jnp
    from .decode_fns import logits_transform

    draft = np.asarray(draft, dtype=np.int32).reshape(-1)
    L = int(draft.size)
    do_sample = bool(sampling[0])
    if not do_sample:
        tgt = np.argmax(logits, axis=-1).astype(np.int32)
        a = greedy_accept(draft, tgt[:L])
        return [int(x) for x in draft[:a]] + [int(tgt[a])], a

    transform = logits_transform(*sampling)
    x = np.asarray(transform(jnp.asarray(logits, jnp.float32)))
    probs = np.asarray(jax.nn.softmax(jnp.asarray(x), axis=-1))
    emitted: List[int] = []
    for i in range(L):
        key = jax.random.fold_in(jax.random.fold_in(base_key, seed),
                                 step0 + i)
        u = float(jax.random.uniform(jax.random.fold_in(key, _FOLD_ACCEPT)))
        px = float(probs[i, draft[i]])
        if u < px:
            emitted.append(int(draft[i]))
            continue
        # rejection: resample from the renormalized residual (target minus
        # the rejected point mass) and stop — later drafts were conditioned
        # on the rejected token and carry no usable information
        resid = probs[i].astype(np.float64).copy()
        resid[draft[i]] = 0.0
        z = resid.sum()
        if z <= 0.0:                    # p was a point mass AT the draft:
            emitted.append(int(draft[i]))   # accept is the only outcome
            continue
        tok = int(jax.random.categorical(
            jax.random.fold_in(key, _FOLD_RESAMPLE),
            jnp.log(jnp.asarray(resid / z))))
        emitted.append(tok)
        return emitted, i
    # full acceptance: bonus token from position L with the plain per-step
    # key — exactly make_slot_select_fn's draw for that step index
    key = jax.random.fold_in(jax.random.fold_in(base_key, seed), step0 + L)
    bonus = int(jax.random.categorical(key, jnp.asarray(x[L])))
    emitted.append(bonus)
    return emitted, L


# ------------------------------------------------------------------- telemetry
@dataclass
class SpecStats:
    """Per-scheduler speculative-decoding counters (host-side, cumulative)."""
    rounds: int = 0          # verify dispatches (== target forward passes)
    proposed: int = 0        # draft tokens offered to the verifier
    accepted: int = 0        # draft tokens that survived accept/reject
    tokens: int = 0          # tokens emitted by spec rounds (incl. bonus)
    draft_s: float = 0.0     # cumulative proposer wall time
    verify_s: float = 0.0    # cumulative verify dispatch+fetch wall time

    @property
    def acceptance_rate(self) -> float:
        return self.accepted / self.proposed if self.proposed else 0.0

    @property
    def passes_per_token(self) -> float:
        """Target forward passes per emitted decode token (non-speculative
        decode is exactly 1.0 — the bench gate divides this)."""
        return self.rounds / self.tokens if self.tokens else 1.0

    def snapshot(self) -> dict:
        return {
            "spec_rounds": self.rounds,
            "spec_proposed": self.proposed,
            "spec_accepted": self.accepted,
            "spec_tokens": self.tokens,
            "spec_acceptance_rate": self.acceptance_rate,
            "spec_passes_per_token": self.passes_per_token,
            "spec_draft_s": self.draft_s,
            "spec_verify_s": self.verify_s,
        }


def emit_spec_events(telemetry, stats: SpecStats, round_draft_s: float,
                     tick: int) -> None:
    """Publish the ``serving/spec_*`` tags for one spec round through the
    owning :class:`~.serving.telemetry.ServingTelemetry` (registry feed +
    monitor backends). Lives here — not in telemetry.py — so the emission
    site sits in the subsystem that owns the semantics (this module is listed
    in ``observability.schema.EMITTER_MODULES`` and tag-linted)."""
    telemetry._write([
        ("serving/spec_acceptance_rate", stats.acceptance_rate, tick),
        ("serving/spec_proposed_total", float(stats.proposed), tick),
        ("serving/spec_accepted_total", float(stats.accepted), tick),
        ("serving/spec_draft_ms", round_draft_s * 1e3, tick),
    ])
