from .config import DeepSpeedInferenceConfig, DeepSpeedTPConfig
from .engine import InferenceEngine
