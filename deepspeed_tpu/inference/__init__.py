from .config import DeepSpeedInferenceConfig, DeepSpeedTPConfig
from .engine import InferenceEngine
from .diffusion_engine import DiffusionInferenceEngine, init_diffusion_inference
from .serving import (ChunkedDecodeExecutor, ContinuousBatchingScheduler,
                      QueueFullError, RequestHandle, RequestState, ServingConfig,
                      ServingTelemetry, SlotKVPool)
from .speculative import (DraftModelProposer, NgramProposer, SpeculativeConfig,
                          make_proposer)
