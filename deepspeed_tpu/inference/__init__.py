from .config import DeepSpeedInferenceConfig, DeepSpeedTPConfig
from .engine import InferenceEngine
from .diffusion_engine import DiffusionInferenceEngine, init_diffusion_inference
