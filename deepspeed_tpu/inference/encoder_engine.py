"""Encoder inference engine: TP-sharded whole-sequence serving.

The bidirectional counterpart of :class:`~.engine.InferenceEngine` (reference
``InferenceEngine`` serving injected BERT/DistilBERT containers,
``module_inject/containers/bert.py``). No KV cache or generation loop — one
jitted forward over params sharded per the Megatron encoder rules; ``forward``
returns HF-shaped ``(last_hidden_state, pooler_output)``.
"""

from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from ..models.encoder import EncoderConfig, EncoderLM, encoder_param_specs
from ..parallel.mesh import AXIS_DATA, AXIS_TENSOR, MeshSpec, set_global_mesh
from ..utils.logging import log_dist


class EncoderInferenceEngine:
    def __init__(self, model, config=None, params: Optional[Any] = None,
                 mesh_spec: Optional[MeshSpec] = None, seed: int = 0):
        from .config import DeepSpeedInferenceConfig
        self._config = config or DeepSpeedInferenceConfig()
        tp = self._config.resolved_tp()
        dp = max(1, int(self._config.data_parallel))
        self.mesh_spec = mesh_spec or MeshSpec(
            {AXIS_TENSOR: tp, AXIS_DATA: dp}, devices=jax.devices()[:tp * dp])
        set_global_mesh(self.mesh_spec)

        if isinstance(model, EncoderConfig):
            self.model_config = model
            if params is None:
                module = EncoderLM(model)
                params = jax.jit(lambda r: module.init(
                    {"params": r}, jnp.zeros((1, 8), jnp.int32))["params"])(
                        jax.random.PRNGKey(seed))
        else:
            from ..module_inject.encoder_policies import convert_hf_encoder
            self.model_config, params = convert_hf_encoder(model)
        self.dtype = self._config.jax_dtype()
        self.model_config.dtype = self.dtype
        self.module = EncoderLM(self.model_config)
        self.params = self._place_params(params)
        self._fns: Dict[str, Any] = {}
        log_dist(f"encoder inference engine ready: {self.model_config.name} "
                 f"params≈{self.model_config.num_params():,} tp={tp} dp={dp} "
                 f"dtype={self.dtype.__name__}", ranks=[0])

    # matmul weights eligible for int8 (same set/shape policy as the decoder's
    # GroupQuantizer analogue; embeddings, norms, pooler stay in fp)
    _QUANT_NAMES = ("q_proj", "k_proj", "v_proj", "o_proj", "fc_in", "fc_out")

    def _place_params(self, raw):
        from .engine import spec_fits
        specs = encoder_param_specs(raw, tensor_axis=AXIS_TENSOR)
        mesh = self.mesh_spec
        int8 = self._config.is_int8()
        if int8:
            from ..ops.quantizer import validate_quant_config
            validate_quant_config(self._config.quant)
        self._quantized = int8

        def put(arr, spec):
            if not spec_fits(mesh, arr.shape, spec):
                spec = P(*([None] * arr.ndim))
            return jax.device_put(arr, NamedSharding(mesh.mesh, spec))

        def walk(node, spec_node, path):
            if isinstance(node, dict):
                return {k: walk(v, spec_node[k], path + (k,))
                        for k, v in node.items()}
            arr = jnp.asarray(node)
            if arr.ndim >= 2 and arr.dtype in (jnp.float32, jnp.float16,
                                               jnp.bfloat16):
                arr = arr.astype(self.dtype)
            if int8 and arr.ndim == 2 and path[-1] == "kernel" \
                    and set(path) & set(self._QUANT_NAMES):
                from ..ops.quantizer import (INT8_Q, INT8_SCALE,
                                             quantize_grouped)
                q, scale = quantize_grouped(arr)
                spec_t = tuple(spec_node)
                return {INT8_Q: put(q, P(*spec_t)),
                        INT8_SCALE: put(scale.astype(jnp.float32),
                                        P(*spec_t))}
            return put(arr, spec_node)

        return walk(raw, specs, ())

    def _dequant(self, params):
        if not getattr(self, "_quantized", False):
            return params
        from ..ops.quantizer import dequantize_tree
        return dequantize_tree(params, self.dtype)

    def forward(self, input_ids, attention_mask=None, token_type_ids=None,
                **kwargs):
        """(last_hidden_state, pooler_output or None) — HF BertModel surface."""
        set_global_mesh(self.mesh_spec)
        if "fwd" not in self._fns:
            self._fns["fwd"] = jax.jit(
                lambda p, ids, am, tt: self.module.apply(
                    {"params": self._dequant(p)}, ids, attention_mask=am,
                    token_type_ids=tt))
        ids = jnp.asarray(np.asarray(input_ids))
        am = None if attention_mask is None else \
            jnp.asarray(np.asarray(attention_mask))
        tt = None if token_type_ids is None else \
            jnp.asarray(np.asarray(token_type_ids))
        return self._fns["fwd"](self.params, ids, am, tt)

    __call__ = forward
