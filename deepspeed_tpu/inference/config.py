"""Inference configuration.

Mirrors reference ``deepspeed/inference/config.py`` (``DeepSpeedInferenceConfig:125``,
``DeepSpeedTPConfig``): same knob names so reference configs port over; TPU-only knobs
(mesh data axis for throughput batching) added.
"""

from typing import Any, Dict, List, Optional

from pydantic import Field

from ..config.config_utils import ConfigModel


class DeepSpeedTPConfig(ConfigModel):
    """Reference ``DeepSpeedTPConfig``: tensor-parallel degree."""
    enabled: bool = True
    tp_size: int = 1


class QuantConfig(ConfigModel):
    enabled: bool = False
    bits: int = 8


class WeightQuantConfig(ConfigModel):
    """Weight-streaming quantized decode (``ops/quantizer/fused_matmul.py``).

    Projection weights (qkv/o_proj/fc_in/fc_out/gate/up + MoE expert FFNs) are
    stored grouped-quantized and dequantized INSIDE the fused matmul kernels,
    so int8/int4 bytes are what streams from HBM on the decode hot path.
    Embeddings, norms, biases and the lm_head stay fp.

    - ``bits``: 8 or 4 (int4 packs two nibbles per byte — 4x weight reads).
    - ``group``: elements per scale group along the contraction dim.
    - ``exclude``: parameter-path substrings to keep in bf16 (e.g.
      ``["layers_0/", "fc_out"]``).
    - ``outlier_threshold``: per-matrix relative-error audit bound — matrices
      whose quantize/dequantize relative Frobenius error exceeds it stay bf16
      (outlier-heavy matrices quantize badly under symmetric grouped scales).
      ``None`` picks a per-bits default (0.05 for int8, 0.30 for int4).
    """
    enabled: bool = False
    bits: int = 8
    group: int = 128
    exclude: List[str] = Field(default_factory=list)
    outlier_threshold: Optional[float] = None

    def resolved_threshold(self) -> float:
        if self.outlier_threshold is not None:
            return float(self.outlier_threshold)
        return 0.05 if self.bits == 8 else 0.30


class InferenceCheckpointConfig(ConfigModel):
    checkpoint_dir: Optional[str] = None
    tag: Optional[str] = None


class DeepSpeedInferenceConfig(ConfigModel):
    """Reference ``inference/config.py:125``. ``replace_with_kernel_inject`` is accepted and
    means "use the fused decode path" (always on here — it is the only path)."""
    dtype: str = "bfloat16"                       # reference default fp16; bf16 on TPU
    tensor_parallel: DeepSpeedTPConfig = Field(default_factory=DeepSpeedTPConfig)
    data_parallel: int = 1                        # extra throughput axis (TPU addition)
    max_out_tokens: int = 1024                    # reference max_out_tokens
    max_batch_size: int = 1
    replace_with_kernel_inject: bool = True
    quant: QuantConfig = Field(default_factory=QuantConfig)
    # weight-streaming quantized decode; supersedes the legacy ``quant`` block
    # (which resolves to weight_quant(bits=8) at engine construction)
    weight_quant: WeightQuantConfig = Field(default_factory=WeightQuantConfig)
    checkpoint: Optional[Any] = None
    replace_method: str = "auto"
    enable_cuda_graph: bool = False               # accepted; AOT decode is always compiled
    min_out_tokens: int = 1
    # MoE decode implementation override applied to the resolved model config at
    # engine construction ("pallas" | "xla"; None keeps the model's choice) —
    # the supported way to select the impl, instead of mutating
    # engine.model_config after the engine (and its compiled fns) exist
    moe_decode_impl: Optional[str] = None

    # comm-compute overlap block (chunked collective matmuls on the TP decode
    # hot path; same keys as the training config's "comm_overlap" — parsed by
    # parallel.overlap.resolve_overlap_config at engine construction)
    comm_overlap: Dict[str, Any] = Field(default_factory=dict)

    # convenience aliases the reference accepts at top level
    mp_size: Optional[int] = None                 # deprecated alias of tensor_parallel.tp_size

    def resolved_tp(self) -> int:
        if self.mp_size is not None:
            return int(self.mp_size)
        return int(self.tensor_parallel.tp_size)

    def is_int8(self) -> bool:
        """int8 serving = int8-quantized weights + bf16 compute (grouped dequant at use).

        The reference's int8 path is the same shape: ``GroupQuantizer`` quantizes weights at
        injection (``module_inject/replace_module.py:152``) and kernels dequantize into fp16
        compute (``csrc/transformer/inference/csrc/dequantize.cu``)."""
        return str(self.dtype).replace("torch.", "") == "int8" or self.quant.enabled

    def resolved_weight_quant(self) -> WeightQuantConfig:
        """One weight-quantization surface: the ``weight_quant`` block wins;
        the legacy ``quant`` block / ``dtype="int8"`` resolve to its 8-bit
        defaults so both spellings drive the same per-site kernel path."""
        if self.weight_quant.enabled:
            return self.weight_quant
        if self.is_int8():
            return WeightQuantConfig(enabled=True, bits=8)
        return self.weight_quant

    def jax_dtype(self):
        import jax.numpy as jnp
        key = str(self.dtype).replace("torch.", "")
        if key == "int8":
            return jnp.bfloat16                   # compute dtype; weights quantized separately
        return {"float32": jnp.float32, "fp32": jnp.float32,
                "float16": jnp.float16, "fp16": jnp.float16, "half": jnp.float16,
                "bfloat16": jnp.bfloat16, "bf16": jnp.bfloat16}[key]
