"""Inference configuration.

Mirrors reference ``deepspeed/inference/config.py`` (``DeepSpeedInferenceConfig:125``,
``DeepSpeedTPConfig``): same knob names so reference configs port over; TPU-only knobs
(mesh data axis for throughput batching) added.
"""

from typing import Any, Dict, Optional

from pydantic import Field

from ..config.config_utils import ConfigModel


class DeepSpeedTPConfig(ConfigModel):
    """Reference ``DeepSpeedTPConfig``: tensor-parallel degree."""
    enabled: bool = True
    tp_size: int = 1


class QuantConfig(ConfigModel):
    enabled: bool = False
    bits: int = 8


class InferenceCheckpointConfig(ConfigModel):
    checkpoint_dir: Optional[str] = None
    tag: Optional[str] = None


class DeepSpeedInferenceConfig(ConfigModel):
    """Reference ``inference/config.py:125``. ``replace_with_kernel_inject`` is accepted and
    means "use the fused decode path" (always on here — it is the only path)."""
    dtype: str = "bfloat16"                       # reference default fp16; bf16 on TPU
    tensor_parallel: DeepSpeedTPConfig = Field(default_factory=DeepSpeedTPConfig)
    data_parallel: int = 1                        # extra throughput axis (TPU addition)
    max_out_tokens: int = 1024                    # reference max_out_tokens
    max_batch_size: int = 1
    replace_with_kernel_inject: bool = True
    quant: QuantConfig = Field(default_factory=QuantConfig)
    checkpoint: Optional[Any] = None
    replace_method: str = "auto"
    enable_cuda_graph: bool = False               # accepted; AOT decode is always compiled
    min_out_tokens: int = 1
    # MoE decode implementation override applied to the resolved model config at
    # engine construction ("pallas" | "xla"; None keeps the model's choice) —
    # the supported way to select the impl, instead of mutating
    # engine.model_config after the engine (and its compiled fns) exist
    moe_decode_impl: Optional[str] = None

    # comm-compute overlap block (chunked collective matmuls on the TP decode
    # hot path; same keys as the training config's "comm_overlap" — parsed by
    # parallel.overlap.resolve_overlap_config at engine construction)
    comm_overlap: Dict[str, Any] = Field(default_factory=dict)

    # convenience aliases the reference accepts at top level
    mp_size: Optional[int] = None                 # deprecated alias of tensor_parallel.tp_size

    def resolved_tp(self) -> int:
        if self.mp_size is not None:
            return int(self.mp_size)
        return int(self.tensor_parallel.tp_size)

    def is_int8(self) -> bool:
        """int8 serving = int8-quantized weights + bf16 compute (grouped dequant at use).

        The reference's int8 path is the same shape: ``GroupQuantizer`` quantizes weights at
        injection (``module_inject/replace_module.py:152``) and kernels dequantize into fp16
        compute (``csrc/transformer/inference/csrc/dequantize.cu``)."""
        return str(self.dtype).replace("torch.", "") == "int8" or self.quant.enabled

    def jax_dtype(self):
        import jax.numpy as jnp
        key = str(self.dtype).replace("torch.", "")
        if key == "int8":
            return jnp.bfloat16                   # compute dtype; weights quantized separately
        return {"float32": jnp.float32, "fp32": jnp.float32,
                "float16": jnp.float16, "fp16": jnp.float16, "half": jnp.float16,
                "bfloat16": jnp.bfloat16, "bf16": jnp.bfloat16}[key]
