"""Public pipeline-parallelism namespace (reference ``deepspeed/pipe/__init__.py``)."""

from ..runtime.pipe import (FlaxPipeLayer, LambdaLayer, LayerSpec, PipeLayer,
                            PipelineModule, TiedLayerSpec)
from ..parallel.topology import (PipeDataParallelTopology, PipeModelDataParallelTopology,
                                 ProcessTopology)
