from .config import (
    DeepSpeedConfig,
    DeepSpeedConfigError,
    FP16Config,
    BF16Config,
    MeshConfig,
    MonitorConfig,
    FlopsProfilerConfig,
    ActivationCheckpointingConfig,
    CommsLoggerConfig,
    PipelineConfig,
    CheckpointConfig,
    AIOConfig,
)
from .config_utils import ConfigModel
