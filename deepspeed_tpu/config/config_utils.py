"""Config model base class.

TPU-native analogue of the reference's ``deepspeed/runtime/config_utils.py``
(``DeepSpeedConfigModel``): a pydantic model accepting the same JSON surface, with support for
deprecated fields that forward their value to a replacement field.
"""

from typing import Any, Dict

from pydantic import BaseModel, ConfigDict, model_validator


class ConfigModel(BaseModel):
    """Base for all subsystem configs.

    Supports ``Field(..., json_schema_extra={"deprecated": True, "new_param": "name"})`` the way
    the reference's ``DeepSpeedConfigModel`` does (``config_utils.py``): if the user sets the
    deprecated field, its value is forwarded to the replacement and a warning is logged.
    """

    model_config = ConfigDict(
        validate_assignment=True,
        populate_by_name=True,
        extra="allow",
        arbitrary_types_allowed=True,
        protected_namespaces=(),
    )

    def __init__(self, strict: bool = False, **data: Any):
        if not strict:  # drop None values so defaults apply, like the reference
            data = {k: v for k, v in data.items() if v is not None or k.endswith("_")}
        super().__init__(**data)

    @model_validator(mode="after")
    def _forward_deprecated_fields(self):
        from ..utils.logging import logger
        fields_set = self.model_fields_set
        for name, field in type(self).model_fields.items():
            extra = field.json_schema_extra or {}
            if not isinstance(extra, dict) or not extra.get("deprecated"):
                continue
            if name in fields_set:
                new_param = extra.get("new_param", "")
                logger.warning(f"Config parameter {name} is deprecated" +
                               (f", use {new_param} instead" if new_param else ""))
                if new_param and new_param not in fields_set:
                    object.__setattr__(self, new_param, getattr(self, name))
        return self

    def dict(self, **kwargs) -> Dict[str, Any]:  # pydantic-v1-style convenience
        return self.model_dump(**kwargs)


def get_scalar_param(param_dict: Dict, param_name: str, param_default_value):
    """Reference ``runtime/config_utils.py:get_scalar_param``."""
    return param_dict.get(param_name, param_default_value)


def get_dict_param(param_dict: Dict, param_name: str, param_default_value):
    return param_dict.get(param_name, param_default_value)
