"""Top-level training config.

TPU-native analogue of reference ``deepspeed/runtime/config.py`` (``DeepSpeedConfig`` at
``config.py:738``, ``_initialize_params:845``, batch-triple inference ``_configure_train_batch_size``).
Accepts the same JSON/dict surface (also a path to a ``.json`` file), resolves the
(train_batch_size, micro_batch_per_device, gradient_accumulation_steps) triple against the
data-parallel world size, and instantiates per-subsystem configs.

TPU-native addition: a ``"mesh"`` block naming the device-mesh axis sizes
(data/fsdp/tensor/pipe/expert/seq); -1 means "infer from device count".
"""

import base64
import json
import os
from typing import Any, Dict, Optional, Union

from pydantic import Field

from .. import constants as C
from ..utils.logging import logger
from .config_utils import ConfigModel
from ..runtime.zero.config import DeepSpeedZeroConfig


class FP16Config(ConfigModel):
    """Reference ``runtime/fp16/...`` config block (``runtime/config.py`` fp16 keys)."""
    enabled: bool = False
    auto_cast: bool = False
    loss_scale: float = Field(0.0, ge=0)     # 0 = dynamic
    initial_scale_power: int = Field(16, ge=0)
    loss_scale_window: int = Field(1000, gt=0)
    hysteresis: int = Field(2, ge=0)
    min_loss_scale: float = Field(1.0, ge=0)

    @property
    def dynamic(self) -> bool:
        return self.loss_scale == 0.0


class BF16Config(ConfigModel):
    enabled: bool = False


class MeshConfig(ConfigModel):
    """TPU-native parallelism spec: sizes of named mesh axes.

    ``data`` is the pure data-parallel axis; ``fsdp`` is the axis ZeRO shards over (when ZeRO
    stage > 0 and fsdp == 1 it absorbs the data axis — see ``parallel/mesh.py``); ``tensor`` is
    megatron-style TP; ``pipe`` pipeline stages; ``expert`` MoE expert parallelism; ``seq``
    sequence/context parallelism (ring attention), absent in the reference snapshot (SURVEY §2.3).
    """
    data: int = -1
    fsdp: int = 1
    tensor: int = 1
    pipe: int = 1
    expert: int = 1
    seq: int = 1


class ActivationCheckpointingConfig(ConfigModel):
    """Reference ``runtime/activation_checkpointing/config.py`` keys."""
    partition_activations: bool = False
    cpu_checkpointing: bool = False
    contiguous_memory_optimization: bool = False
    number_checkpoints: Optional[int] = None
    synchronize_checkpoint_boundary: bool = False
    profile: bool = False
    # TPU-native: jax.checkpoint policy name (see runtime/activation_checkpointing)
    policy: str = "nothing_saveable"


class CommsLoggerConfig(ConfigModel):
    """Reference ``comm/config.py:CommsLoggerConfig``."""
    enabled: bool = False
    verbose: bool = False
    prof_all: bool = True
    debug: bool = False
    prof_ops: list = Field(default_factory=list)


class TensorBoardConfig(ConfigModel):
    enabled: bool = False
    output_path: str = ""
    job_name: str = "DeepSpeedJobName"


class WandbConfig(ConfigModel):
    enabled: bool = False
    group: Optional[str] = None
    team: Optional[str] = None
    project: str = "deepspeed_tpu"


class CSVConfig(ConfigModel):
    enabled: bool = False
    output_path: str = ""
    job_name: str = "DeepSpeedJobName"


class JSONLConfig(ConfigModel):
    """Scrape-free metrics for serving runs: one JSON object per event, one file
    per job (TPU addition — no reference analogue)."""
    enabled: bool = False
    output_path: str = ""
    job_name: str = "DeepSpeedJobName"


class MonitorConfig(ConfigModel):
    """Reference ``monitor/config.py`` (+ TPU-native ``jsonl_monitor`` backend)."""
    tensorboard: TensorBoardConfig = Field(default_factory=TensorBoardConfig)
    wandb: WandbConfig = Field(default_factory=WandbConfig)
    csv_monitor: CSVConfig = Field(default_factory=CSVConfig)
    jsonl_monitor: JSONLConfig = Field(default_factory=JSONLConfig)

    @property
    def enabled(self) -> bool:
        return (self.tensorboard.enabled or self.wandb.enabled
                or self.csv_monitor.enabled or self.jsonl_monitor.enabled)


class FlopsProfilerConfig(ConfigModel):
    """Reference ``profiling/config.py`` (+ ``peak_tflops`` for the modeled
    ``Train/mfu`` registry event — defaults to the engine's device-kind table
    when unset; unknown kinds skip the event)."""
    enabled: bool = False
    profile_step: int = 1
    module_depth: int = -1
    top_modules: int = 1
    detailed: bool = True
    output_file: Optional[str] = None
    peak_tflops: Optional[float] = None


class PipelineConfig(ConfigModel):
    """Reference pipeline keys (``runtime/config.py`` "pipeline" block)."""
    stages: str = "auto"
    partition: str = "best"
    seed_layers: bool = False
    activation_checkpoint_interval: int = 0
    use_reentrant: bool = True


class CheckpointConfig(ConfigModel):
    tag_validation: str = "Warn"  # Ignore | Warn | Fail
    load_universal: bool = False
    use_node_local_storage: bool = False
    checkpoint_in_cpu: bool = False
    async_save: bool = False


class AIOConfig(ConfigModel):
    """Reference ``runtime/swap_tensor/aio_config.py`` keys."""
    block_size: int = 1048576
    queue_depth: int = 8
    thread_count: int = 1
    single_submit: bool = False
    overlap_events: bool = True


class DeepSpeedConfigError(Exception):
    pass


def _resolve_config_dict(config: Union[str, dict]) -> dict:
    if isinstance(config, dict):
        return dict(config)
    if isinstance(config, str):
        if os.path.exists(config):
            with open(config) as f:
                return json.load(f)
        # reference accepts base64-encoded JSON (runtime/config.py:745)
        try:
            return json.loads(base64.urlsafe_b64decode(config).decode())
        except Exception:
            raise DeepSpeedConfigError(
                f"Expected a file path, dict, or base64 JSON, got: {config!r}")
    raise DeepSpeedConfigError(f"Unsupported config type: {type(config)}")


class DeepSpeedConfig:
    """Parsed, validated, batch-resolved training configuration.

    Reference: ``runtime/config.py:738``. ``dp_world_size`` is the product of the data and fsdp
    mesh axes (the axes a batch is split over).
    """

    def __init__(self, config: Union[str, dict], dp_world_size: Optional[int] = None):
        self._param_dict = _resolve_config_dict(config)
        pd = self._param_dict

        for key in C.IGNORED_CUDA_ONLY_KEYS:
            if key in pd:
                logger.warning(f"Config key '{key}' is CUDA-specific and ignored on TPU")

        # --- subsystem blocks -------------------------------------------------
        self.mesh = MeshConfig(**pd.get(C.MESH, {}))
        self.zero_config = DeepSpeedZeroConfig(**pd.get(C.ZERO_OPTIMIZATION, {}))
        self.fp16 = FP16Config(**pd.get(C.FP16, {}))
        bf16_dict = pd.get(C.BFLOAT16, pd.get(C.BFLOAT16_OLD, {}))
        self.bf16 = BF16Config(**bf16_dict)
        self.activation_checkpointing = ActivationCheckpointingConfig(
            **pd.get(C.ACTIVATION_CHECKPOINTING, {}))
        self.comms_logger = CommsLoggerConfig(**pd.get(C.COMMS_LOGGER, {}))
        # "comm_overlap" (T3 arxiv 2401.16677 + EQuARX arxiv 2506.17615) stays
        # a raw dict here — parallel.overlap.OverlapConfig is the single
        # source of truth for keys/defaults, and resolve_overlap_config (which
        # rejects unknown keys) validates it; called now so bad keys still
        # fail at config parse, not first trace.
        from ..parallel.overlap import resolve_overlap_config
        self.comm_overlap = dict(pd.get(C.COMM_OVERLAP, {}))
        resolve_overlap_config(self.comm_overlap)
        self.monitor_config = MonitorConfig(
            tensorboard=pd.get(C.MONITOR_TENSORBOARD, {}),
            wandb=pd.get(C.MONITOR_WANDB, {}),
            csv_monitor=pd.get(C.MONITOR_CSV, {}),
            jsonl_monitor=pd.get(C.MONITOR_JSONL, {}),
        )
        self.flops_profiler = FlopsProfilerConfig(**pd.get(C.FLOPS_PROFILER, {}))
        self.pipeline = PipelineConfig(**pd.get(C.PIPELINE, {}))
        self.checkpoint_config = CheckpointConfig(**pd.get(C.CHECKPOINT, {}))
        self.aio_config = AIOConfig(**pd.get(C.AIO, {}))

        # --- scalars ----------------------------------------------------------
        self.optimizer_name: Optional[str] = None
        self.optimizer_params: Dict[str, Any] = {}
        if C.OPTIMIZER in pd:
            self.optimizer_name = pd[C.OPTIMIZER].get("type")
            if self.optimizer_name:
                self.optimizer_name = self.optimizer_name.lower()
            self.optimizer_params = pd[C.OPTIMIZER].get(C.OPTIMIZER_PARAMS, {})
        self.scheduler_name: Optional[str] = None
        self.scheduler_params: Dict[str, Any] = {}
        if C.SCHEDULER in pd:
            self.scheduler_name = pd[C.SCHEDULER].get("type")
            self.scheduler_params = pd[C.SCHEDULER].get(C.SCHEDULER_PARAMS, {})

        self.gradient_clipping: float = pd.get(C.GRADIENT_CLIPPING,
                                               C.GRADIENT_CLIPPING_DEFAULT)
        self.prescale_gradients: bool = pd.get(C.PRESCALE_GRADIENTS, False)
        self.gradient_predivide_factor: float = pd.get(C.GRADIENT_PREDIVIDE_FACTOR, 1.0)
        self.sparse_gradients_enabled: bool = pd.get(C.SPARSE_GRADIENTS, False)
        self.steps_per_print: int = pd.get(C.STEPS_PER_PRINT, C.STEPS_PER_PRINT_DEFAULT)
        self.wall_clock_breakdown: bool = pd.get(C.WALL_CLOCK_BREAKDOWN, False)
        self.memory_breakdown: bool = pd.get(C.MEMORY_BREAKDOWN, False)
        self.dump_state: bool = pd.get(C.DUMP_STATE, False)
        self.dataloader_drop_last: bool = pd.get(C.DATALOADER_DROP_LAST, False)
        self.progressive_layer_drop: Dict = pd.get(C.PROGRESSIVE_LAYER_DROP, {})
        self.eigenvalue: Dict = pd.get(C.EIGENVALUE, {})
        self.elasticity: Dict = pd.get(C.ELASTICITY, {})
        self.compression_config: Dict = pd.get(C.COMPRESSION_TRAINING, {})
        # MoQ (reference "quantize_training" block, runtime/quantize.py): expressed as
        # a weight-quantization compression schedule — one QAT mechanism serves both
        qt = pd.get(C.QUANTIZE_TRAINING, {})
        if qt.get("enabled", False):
            if "weight_quantization" in self.compression_config:
                raise DeepSpeedConfigError(
                    "Set either quantize_training or "
                    "compression_training.weight_quantization, not both")
            start_bits = qt.get("quantize_bits", {}).get("start_bits", 16)
            target_bits = qt.get("quantize_bits", {}).get("target_bits", 8)
            algo = qt.get("quantize_algo", {}) or {}
            self.compression_config = dict(self.compression_config)
            self.compression_config["weight_quantization"] = {
                "shared_parameters": {
                    "enabled": True,
                    "schedule_offset": qt.get("schedule_offset", 0),
                    "quantize_groups": qt.get("quantize_groups", 1),
                    "quantization_type": algo.get("q_type", "symmetric"),
                    "rounding": algo.get("rounding", "nearest"),
                },
                "different_groups": {"moq": {"params": {
                    "start_bits": start_bits, "target_bits": target_bits,
                    "quantization_period": qt.get("quantize_period", 1000),
                }}},
            }
        self.data_efficiency_config: Dict = pd.get(C.DATA_EFFICIENCY, {})
        self.curriculum_params_legacy: Dict = pd.get(C.CURRICULUM_LEARNING_LEGACY, {})
        self.curriculum_enabled_legacy: bool = bool(
            self.curriculum_params_legacy.get("enabled", False))

        if self.fp16.enabled and self.bf16.enabled:
            raise DeepSpeedConfigError("fp16 and bf16 cannot both be enabled")

        # --- batch triple -----------------------------------------------------
        self.train_batch_size: Optional[int] = pd.get(C.TRAIN_BATCH_SIZE)
        self.train_micro_batch_size_per_gpu: Optional[int] = pd.get(
            C.TRAIN_MICRO_BATCH_SIZE_PER_GPU)
        self.gradient_accumulation_steps: Optional[int] = pd.get(
            C.GRADIENT_ACCUMULATION_STEPS)
        if dp_world_size is not None:
            self.resolve_batch_config(dp_world_size)

    # Batch-triple inference: reference ``runtime/config.py`` _configure_train_batch_size.
    def resolve_batch_config(self, dp_world_size: int):
        if not (dp_world_size >= 1):
            raise AssertionError('dp_world_size >= 1')
        self.dp_world_size = dp_world_size
        tb, mb, gas = (self.train_batch_size, self.train_micro_batch_size_per_gpu,
                       self.gradient_accumulation_steps)
        if tb is not None and mb is not None and gas is not None:
            pass
        elif tb is not None and mb is not None:
            gas = tb // (mb * dp_world_size)
        elif tb is not None and gas is not None:
            mb = tb // (gas * dp_world_size)
        elif mb is not None and gas is not None:
            tb = mb * gas * dp_world_size
        elif tb is not None:
            gas = 1
            mb = tb // dp_world_size
        elif mb is not None:
            gas = 1
            tb = mb * dp_world_size
        else:
            raise DeepSpeedConfigError(
                "At least one of train_batch_size / train_micro_batch_size_per_gpu must be set")
        self.train_batch_size, self.train_micro_batch_size_per_gpu, \
            self.gradient_accumulation_steps = tb, mb, gas
        self._batch_assertion()

    def _batch_assertion(self):
        tb, mb, gas = (self.train_batch_size, self.train_micro_batch_size_per_gpu,
                       self.gradient_accumulation_steps)
        if tb <= 0 or mb <= 0 or gas <= 0:
            raise DeepSpeedConfigError(
                f"Batch sizes must be positive: train={tb} micro={mb} gas={gas}")
        if tb != mb * gas * self.dp_world_size:
            raise DeepSpeedConfigError(
                f"Check batch-related parameters: train_batch_size ({tb}) != "
                f"micro_batch_per_device ({mb}) * gradient_accumulation_steps ({gas}) * "
                f"dp_world_size ({self.dp_world_size})")

    @property
    def zero_enabled(self) -> bool:
        return self.zero_config.stage > 0

    @property
    def zero_optimization_stage(self) -> int:
        return self.zero_config.stage

    def print_user_config(self):
        logger.info(json.dumps(self._param_dict, sort_keys=True, indent=4,
                               default=lambda o: str(o)))
