"""Paged-attention decode: gather K/V blocks by page index inside the kernel.

The paged sibling of ``ops/attention/decode.py`` — vLLM's PagedAttention idiom
done TPU-style. The KV store is one global pool of fixed-size pages per layer,
``{"k": (P, h_kv, page, d), "v": ...}``; each decode slot owns a **static-shape
page table** row ``(max_pages,)`` of physical page indices (padded with the
null-page sentinel 0 — page 0 is reserved, never allocated, and every row it
could contribute is masked by ``cache_len``). All shapes are static: the page
count ``P``, the per-slot table width and the page size are compile-time
constants, so a slot serving an 8-token prompt and one serving a 500-token
prompt hit the SAME compiled chunk — page-count growth never mints a compile
key (pinned by the analysis sweep's paged lane).

Two implementations, PR-5 style:

- :func:`paged_attention_xla` — ground truth: gather the slot's pages into the
  dense head-major ``(b, h_kv, cap, d)`` view and run the EXACT same masked
  softmax as ``decode_attention_xla``. Because the gathered view is
  element-identical to what the slot-row pool holds (and sliced to exactly
  ``cap`` rows), greedy decode through this path is **bit-identical** to the
  slot-row pool — the property every serving parity lane leans on.
- :func:`paged_attention` — the fused Pallas kernel: grid over slots, K/V
  pages DMA'd HBM→VMEM double-buffered **by page index** (the gather happens
  inside the grid; the dense view is never materialised in HBM), online
  softmax across pages. Used on a real TPU backend;
  ``DS_TPU_PAGED_FORCE_FUSED=1`` routes CPU tests through interpret mode
  (kernel-vs-XLA parity is a test gate, same contract as
  ``DS_TPU_WQ_FORCE_FUSED``).
"""

import functools
import os

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .attention.decode import NEG_INF, decode_attention_xla

FORCE_FUSED_ENV = "DS_TPU_PAGED_FORCE_FUSED"


def _interpret() -> bool:
    return jax.default_backend() != "tpu"


def fused_paged_active() -> bool:
    """Fused kernel engaged: a real TPU backend, or the env override routing
    CPU tests through interpret mode."""
    if os.environ.get(FORCE_FUSED_ENV, "0") == "1":
        return True
    return jax.default_backend() == "tpu"


def fused_paged_for(head_dim: int) -> bool:
    """Would :func:`paged_attention` dispatch to the fused kernel for this
    head dim? The chunk builder keys its body shape off this — compiling the
    fused body while the per-step dispatcher falls back to XLA would gather
    the dense view EVERY step instead of once per chunk (the exact regression
    the fallback chunk exists to avoid)."""
    return fused_paged_active() and (head_dim % 128 == 0 or _interpret())


# ------------------------------------------------------------- dense gather
def gather_kv_dense(k_pages, v_pages, page_table, cap: int):
    """Reassemble the dense head-major cache view from pages.

    ``k_pages``/``v_pages``: ``(P, hk, page, d)``; ``page_table``:
    ``(b, max_pages)`` int32. Returns ``(b, hk, cap, d)`` ×2 — rows sliced to
    EXACTLY ``cap`` so downstream attention math (reduction shapes included)
    is identical to the slot-row pool's, keeping greedy bit-exact even when
    ``cap`` is not a page multiple (pages round it up internally)."""
    kp = k_pages[page_table]                       # (b, mp, hk, page, d)
    vp = v_pages[page_table]
    b, mp, hk, ps, d = kp.shape
    k = kp.transpose(0, 2, 1, 3, 4).reshape(b, hk, mp * ps, d)
    v = vp.transpose(0, 2, 1, 3, 4).reshape(b, hk, mp * ps, d)
    return k[:, :, :cap, :], v[:, :, :cap, :]


def paged_attention_xla(q, k_pages, v_pages, page_table, cache_len, cap: int,
                        softmax_scale=None):
    """Ground-truth paged decode attention: dense gather + the slot-row
    pool's exact masked-softmax math (``decode_attention_xla``)."""
    k, v = gather_kv_dense(k_pages, v_pages, page_table, cap)
    return decode_attention_xla(q, k, v, cache_len, softmax_scale)


# ----------------------------------------------------------- cache update
def paged_cache_update(k_pages, v_pages, k_new, v_new, page_table, cache_len):
    """Append one decode step's K/V at each slot's ``cache_len`` position.

    ``k_new``/``v_new``: ``(b, hk, 1, d)``; writes land at physical page
    ``page_table[s, cache_len[s] // page]`` row ``cache_len[s] % page``.
    Per-slot pages are disjoint (allocator invariant), so the batched scatter
    has unique indices."""
    ps = k_pages.shape[2]
    page_pos = cache_len // ps                      # (b,) slot-local page idx
    off = cache_len % ps
    pidx = jnp.take_along_axis(page_table, page_pos[:, None], axis=1)[:, 0]
    k_pages = k_pages.at[pidx, :, off, :].set(
        k_new[:, :, 0, :].astype(k_pages.dtype))
    v_pages = v_pages.at[pidx, :, off, :].set(
        v_new[:, :, 0, :].astype(v_pages.dtype))
    return k_pages, v_pages


# ------------------------------------------------------------ fused kernel
def _paged_decode_kernel(len_ref, table_ref, q_ref, k_hbm, v_hbm, o_ref, *,
                         page: int, max_pages: int, scale):
    """q_ref: (1, hk, g, d) VMEM; k/v_hbm: (P, hk, page, d) pages in HBM.
    len_ref (b,) and table_ref (b * max_pages,) are scalar-prefetch. The DMA
    source block is selected by PAGE INDEX — the gather lives inside the
    grid, double-buffered so page fetches overlap the online-softmax math
    (same pipeline shape as ``ops/attention/decode._decode_kernel``)."""
    i = pl.program_id(0)
    L = len_ref[i]
    q = q_ref[0].astype(jnp.float32)                # (hk, g, d)
    hk, g, d = q.shape
    npg = pl.cdiv(L, page)                          # only touch live pages

    def scoped(k_buf, v_buf, ksem, vsem):
        def k_dma(slot, p):
            pidx = table_ref[i * max_pages + p]
            return pltpu.make_async_copy(k_hbm.at[pidx], k_buf.at[slot],
                                         ksem.at[slot])

        def v_dma(slot, p):
            pidx = table_ref[i * max_pages + p]
            return pltpu.make_async_copy(v_hbm.at[pidx], v_buf.at[slot],
                                         vsem.at[slot])

        k_dma(0, 0).start()
        v_dma(0, 0).start()

        def body(p, carry):
            m, l, acc = carry
            slot = jax.lax.rem(p, 2)
            nxt = jax.lax.rem(p + 1, 2)

            @pl.when(p + 1 < npg)
            def _():
                k_dma(nxt, p + 1).start()
                v_dma(nxt, p + 1).start()

            k_dma(slot, p).wait()
            v_dma(slot, p).wait()
            k_blk = k_buf[slot].astype(jnp.float32)   # (hk, page, d)
            v_blk = v_buf[slot].astype(jnp.float32)
            s = jax.lax.dot_general(
                q, k_blk, dimension_numbers=(((2,), (2,)), ((0,), (0,))),
                preferred_element_type=jnp.float32) * scale
            cols = p * page + jax.lax.broadcasted_iota(
                jnp.int32, (hk, g, page), 2)
            s = jnp.where(cols < L, s, NEG_INF)
            m_new = jnp.maximum(m, jnp.max(s, axis=-1))
            alpha = jnp.exp(m - m_new)
            pr = jnp.exp(s - m_new[..., None])
            l_new = l * alpha + jnp.sum(pr, axis=-1)
            acc_new = acc * alpha[..., None] + jax.lax.dot_general(
                pr, v_blk, dimension_numbers=(((2,), (1,)), ((0,), (0,))),
                preferred_element_type=jnp.float32)
            return m_new, l_new, acc_new

        m0 = jnp.full((hk, g), NEG_INF, jnp.float32)
        l0 = jnp.zeros((hk, g), jnp.float32)
        acc0 = jnp.zeros((hk, g, d), jnp.float32)
        m, l, acc = jax.lax.fori_loop(0, npg, body, (m0, l0, acc0))
        l_safe = jnp.where(l > 0, l, 1.0)
        o_ref[0] = (acc / l_safe[..., None]).astype(o_ref.dtype)

    pl.run_scoped(
        scoped,
        k_buf=pltpu.VMEM((2, hk, page, d), k_hbm.dtype),
        v_buf=pltpu.VMEM((2, hk, page, d), v_hbm.dtype),
        ksem=pltpu.SemaphoreType.DMA((2,)),
        vsem=pltpu.SemaphoreType.DMA((2,)),
    )


def paged_attention_fused(q, k_pages, v_pages, page_table, cache_len,
                          softmax_scale=None):
    """One decode step of paged attention through the Pallas kernel.

    q: ``(b, h, d)``; k/v_pages: ``(P, hk, page, d)``; page_table:
    ``(b, max_pages)``; cache_len: ``(b,)``. Interpret mode off-TPU."""
    b, h, d = q.shape
    hk, ps = k_pages.shape[1], k_pages.shape[2]
    if h % hk != 0:
        raise AssertionError(f"query heads {h} must be a multiple of kv "
                             f"heads {hk}")
    g = h // hk
    mp = page_table.shape[1]
    scale = (softmax_scale if softmax_scale is not None
             else 1.0 / float(np.sqrt(d)))
    q4 = q.reshape(b, hk, g, d)
    lens = cache_len.astype(jnp.int32)
    table = page_table.astype(jnp.int32).reshape(-1)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(b,),
        in_specs=[
            pl.BlockSpec((1, hk, g, d), lambda i, lens_ref, table_ref:
                         (i, 0, 0, 0)),
            pl.BlockSpec(memory_space=pl.ANY),   # pages stay in HBM
            pl.BlockSpec(memory_space=pl.ANY),
        ],
        out_specs=pl.BlockSpec((1, hk, g, d), lambda i, lens_ref, table_ref:
                               (i, 0, 0, 0)),
    )
    out = pl.pallas_call(
        functools.partial(_paged_decode_kernel, page=ps, max_pages=mp,
                          scale=scale),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((b, hk, g, d), q.dtype),
        interpret=_interpret(),
    )(lens, table, q4, k_pages, v_pages)
    return out.reshape(b, h, d)


def paged_attention(q, k_pages, v_pages, page_table, cache_len, cap: int,
                    softmax_scale=None):
    """Dispatch: fused kernel on TPU (or under ``DS_TPU_PAGED_FORCE_FUSED=1``
    interpret mode), XLA dense-gather ground truth otherwise. The XLA path is
    the default on CPU hosts — it is bit-identical to the slot-row pool, which
    is what the serving parity lanes gate on; the kernel carries its own
    numerical parity test."""
    d = q.shape[-1]
    if fused_paged_for(d):
        return paged_attention_fused(q, k_pages, v_pages, page_table,
                                     cache_len, softmax_scale)
    return paged_attention_xla(q, k_pages, v_pages, page_table, cache_len,
                               cap, softmax_scale)
