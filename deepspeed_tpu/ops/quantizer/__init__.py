from .quant import DEFAULT_GROUP, dequantize_grouped, quantize_grouped
