from .quant import (DEFAULT_GROUP, INT8_Q, INT8_SCALE, dequantize_grouped,
                    dequantize_tree, quantize_grouped, validate_quant_config)
