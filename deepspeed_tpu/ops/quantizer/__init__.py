from .quant import (DEFAULT_GROUP, INT4_Q, INT4_SCALE, INT8_Q, INT8_SCALE,
                    dequantize_grouped, dequantize_node, dequantize_tree,
                    is_quant_node, make_quant_node, node_bits,
                    node_logical_shape, node_qs, pack_int4, quantize_grouped,
                    quantize_with_audit, unpack_int4, validate_quant_config)
from .fused_matmul import (dense_weight_bytes, force_fused,
                           fused_backend_active, node_weight_bytes,
                           quant_dense_apply, quantized_matmul,
                           quantized_matmul_xla)
