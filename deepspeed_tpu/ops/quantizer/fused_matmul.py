"""Fused dequant-matmul kernels — the weight-streaming quantized decode hot path.

The r5 north-star bench shows 7B decode is weight-bandwidth-bound (~14.1 GB of
HBM weight reads per step). Grouped int8/int4 storage only pays off if the
QUANTIZED bytes are what actually streams from HBM: dequantizing a whole weight
tree inside the compiled decode body re-materialises bf16 weights per step and
the hot-path read never shrinks. These Pallas kernels fuse dequantization into
the matmul instead (the TPU-native analogue of the reference's
``csrc/quantization/dequantize.cu`` + fused inference GEMMs): quantized weight
blocks are pipelined HBM→VMEM (the same double-buffered streaming idiom as
``ops/attention/decode.py`` — here via the grid pipeline, since weight blocks
are static-shaped), dequantized in-register against their per-group scales, and
accumulated in fp32.

Two block regimes behind one kernel:

- decode GEMV / skinny GEMM (``m <= SKINNY_M``): one row-block, wide ``n``
  blocks — every weight byte is read exactly once per step;
- prefill GEMM: ``m`` additionally blocked so activations tile VMEM.

int4 uses the per-group split-half packed layout of ``quant.pack_int4`` (two
nibbles per byte; unpack = shift + concat, no interleave), for a 4x weight-read
reduction vs bf16.

``quant_dense_apply`` is the model-facing entry: it takes a quant NODE
(``{__int8_q__|__int4_q__, *_scale__}``, the engine's parameter-tree leaf
format), handles (b, t, k) activations, TP sharding (column- or row-parallel
via shard_map — Pallas is opaque to GSPMD, same reason ``_sharded_decode``
wraps the decode-attention kernel), and falls back to an XLA dequant+matmul
whenever the fused path is ineligible (non-TPU backend unless forced, ragged
shapes, non-divisible shards).
"""

import functools
import os

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl

from .quant import (dequantize_node, is_quant_node, node_bits,
                    node_logical_shape, node_qs)

# below this row count the matmul is a GEMV/skinny GEMM: keep one m block and
# spend VMEM on wide n blocks (weight streaming dominates)
SKINNY_M = 256


def _interpret() -> bool:
    return jax.default_backend() != "tpu"


def force_fused() -> bool:
    """Test hook: route engine-level paths through the fused (interpret-mode)
    kernels on a non-TPU backend."""
    return os.environ.get("DS_TPU_WQ_FORCE_FUSED") == "1"


def fused_backend_active() -> bool:
    """Fused kernels stream on a real TPU; everywhere else they only run when
    forced (tests) — the XLA fallback with hoisted dequant is faster on CPU."""
    return jax.default_backend() == "tpu" or force_fused()


# ------------------------------------------------------------------- kernel
def _qmm_kernel(x_ref, q_ref, s_ref, o_ref, *, bits: int, group: int):
    """One (bm, bn) output block, accumulating over the k grid dim.

    x_ref: (bm, bk) activations; q_ref: (bk, bn) int8 or (bk//2, bn) packed
    int4; s_ref: (bk//group, bn) f32 scales; o_ref: (bm, bn) f32.
    """
    kb = pl.program_id(2)
    x = x_ref[...]
    s = s_ref[...]
    gb, bn = s.shape
    if bits == 8:
        qg = q_ref[...].reshape(gb, group, bn).astype(jnp.float32)
    else:
        # per-group split-half layout: low nibbles are the group's first half,
        # high nibbles the second — unpack is a concat, no interleave. Shifts
        # run in int32 (arithmetic >> sign-extends the nibbles).
        pg = q_ref[...].reshape(gb, group // 2, bn).astype(jnp.int32)
        lo = (pg << 28) >> 28
        hi = pg >> 4
        qg = jnp.concatenate([lo, hi], axis=1).astype(jnp.float32)
    w = (qg * s[:, None, :]).reshape(gb * group, bn).astype(x.dtype)
    acc = jnp.dot(x, w, preferred_element_type=jnp.float32)

    @pl.when(kb == 0)
    def _():
        o_ref[...] = acc

    @pl.when(kb > 0)
    def _():
        o_ref[...] += acc


def _pick_block(dim: int, candidates) -> int:
    for c in candidates:
        if dim % c == 0:
            return c
    return 0


def _block_config(m: int, k: int, n: int, bits: int, group: int, interpret: bool):
    """(bm, bk, bn) or None when the shape can't tile the compiled kernel.
    Interpret mode (tests/tiny models) runs whole-array blocks — no alignment
    constraints there."""
    if interpret:
        return m, k, n
    if k % group:
        return None
    bn = _pick_block(n, (512, 256, 128))
    # k blocks must cover whole scale groups; target ~512 rows so an int8
    # (bk, bn) block is <= 256 KB and the grid pipeline double-buffers cheaply
    bk = 0
    for c in (1024, 512, 256, 128):
        if c % group == 0 and k % c == 0:
            bk = c
            break
    if bk == 0 and k == group:
        bk = k
    if bits == 4 and bk % 2:
        return None
    if not bn or not bk:
        return None
    # m never gates eligibility: the wrapper zero-pads rows up to bm
    bm = m if m <= SKINNY_M else 256
    return bm, bk, bn


def quantized_matmul(x, q, scales, *, bits: int = 8, out_dtype=None,
                     interpret=None) -> jnp.ndarray:
    """``x (m, k) @ dequant(q, scales) -> (m, n)`` with in-register dequant.

    ``q``: int8 ``(k, n)`` (bits=8) or packed ``(k//2, n)`` (bits=4);
    ``scales``: f32 ``(k//g, n)``. Accumulates f32; returns ``out_dtype``
    (default: x.dtype). Falls back to the XLA dequant+matmul when the shape
    cannot tile the compiled kernel.
    """
    m, k = x.shape
    groups, n = scales.shape
    group = k // groups
    out_dtype = out_dtype or x.dtype
    interp = _interpret() if interpret is None else interpret
    cfg = _block_config(m, k, n, bits, group, interp)
    if cfg is None:
        return quantized_matmul_xla(x, q, scales, bits=bits, out_dtype=out_dtype)
    bm, bk, bn = cfg
    pad = (-m) % bm
    if pad:
        x = jnp.pad(x, ((0, pad), (0, 0)))
    grid = ((m + pad) // bm, n // bn, k // bk)
    kq = bk if bits == 8 else bk // 2
    out = pl.pallas_call(
        functools.partial(_qmm_kernel, bits=bits, group=group),
        grid=grid,
        in_specs=[
            pl.BlockSpec((bm, bk), lambda i, j, kb: (i, kb)),
            pl.BlockSpec((kq, bn), lambda i, j, kb: (kb, j)),
            pl.BlockSpec((bk // group, bn), lambda i, j, kb: (kb, j)),
        ],
        out_specs=pl.BlockSpec((bm, bn), lambda i, j, kb: (i, j)),
        out_shape=jax.ShapeDtypeStruct((m + pad, n), jnp.float32),
        interpret=interp,
    )(x, q, scales)
    return out[:m].astype(out_dtype)


def quantized_matmul_xla(x, q, scales, *, bits: int = 8, out_dtype=None):
    """XLA reference/fallback: dequantize (fused by XLA into the consumer's
    operand read) then matmul. Ground truth for the kernel parity tests."""
    from .quant import dequantize_grouped, unpack_int4
    out_dtype = out_dtype or x.dtype
    if bits == 4:
        q = unpack_int4(q, scales.shape[-2])
    w = dequantize_grouped(q, scales)
    return jnp.dot(x.astype(jnp.float32), w,
                   preferred_element_type=jnp.float32).astype(out_dtype)


# ------------------------------------------------------------ bytes accounting
def node_weight_bytes(node) -> int:
    """HBM bytes the fused kernel streams for one full pass over a quant node
    (each weight/scale block is read exactly once): quantized payload + scales.
    This is the kernel's own block accounting — ``bench.py --wq`` sums it into
    the modeled bytes-per-step figure."""
    q, s = node_qs(node)
    return int(np.prod(q.shape)) * q.dtype.itemsize + \
        int(np.prod(s.shape)) * s.dtype.itemsize


def dense_weight_bytes(shape, dtype) -> int:
    return int(np.prod(shape)) * jnp.dtype(dtype).itemsize


# --------------------------------------------------------------- model entry
def _tp_aligned(node, k: int, n: int, tp: int, parallel: str) -> bool:
    """Can the quant node shard-map cleanly over ``tp`` shards? Column splits
    n; row splits k — which for int4 must also split whole packed groups."""
    q, s = node_qs(node)
    if parallel == "column":
        return n % tp == 0 and s.shape[-1] % tp == 0
    groups = s.shape[-2]
    return k % tp == 0 and groups % tp == 0 and q.shape[-2] % tp == 0


def quant_dense_apply(x, node, bias, dtype, *, parallel: str = "column",
                      site: str = "wq.dense"):
    """Dense ``y = x @ W + b`` where ``W`` is a quant node.

    ``x``: (b, t, k_logical) activations ((m, k) also accepted); ``parallel``:
    "column" (qkv/fc_in — kernel sharded ``P(None, tensor)``) or "row"
    (o_proj/fc_out — kernel sharded ``P(tensor, None)``). Row-parallel with
    an active ``comm_overlap`` config routes through the fused quantized
    ring (``parallel/qring.py``): dequant-GEMM per ring step, intN wire
    payload — retiring the PR-5 "does NOT compose with the comm_overlap
    ring" carve-out. Overlap off (or ring-ineligible shapes) keeps the
    monolithic psum.

    Fused path: TPU backend (or forced), shapes tile, shards divide. Fallback:
    XLA dequant+matmul — GSPMD shards the dequant+matmul and inserts the psum,
    so numerics and sharding stay correct for any shape.
    """
    bits = node_bits(node)
    q, s = node_qs(node)
    squeeze = x.ndim == 2
    if squeeze:
        x = x[:, None]
    b, t, k = x.shape
    n = q.shape[-1]

    from ...parallel.mesh import AXIS_TENSOR, BATCH_AXES, get_global_mesh
    mesh = get_global_mesh()
    tp = mesh.size(AXIS_TENSOR) if mesh is not None else 1
    groups = s.shape[-2]
    interp = _interpret()
    use_fused = fused_backend_active()
    if use_fused and tp > 1:
        use_fused = _tp_aligned(node, k, n, tp, parallel)
    if use_fused:
        # eligibility is probed on the PER-SHARD shapes the shard_map body
        # will actually run — a shape that tiles globally but not per-shard
        # would otherwise pass here and then fall back to the XLA dequant
        # inside every compiled decode step
        k_loc = k // tp if (tp > 1 and parallel == "row") else k
        n_loc = n // tp if (tp > 1 and parallel == "column") else n
        use_fused = _block_config(
            b * t, k_loc, n_loc, bits, k // groups, interp) is not None

    # row-parallel + active overlap config: the fused quantized ring replaces
    # the monolithic psum. The ring wires fp accumulator CHUNKS (never the
    # packed payload), so its only alignment demands are the fp ring's own
    # (k and groups divide tp; rows pad) — it does not require the Pallas
    # kernel to tile (the ring hoists an XLA dequant once per trace instead).
    use_qring = False
    cfg_ov = None
    if parallel == "row" and tp > 1 and _tp_aligned(node, k, n, tp, "row"):
        from ...parallel.overlap import (_overlap_dense_eligible,
                                         get_overlap_config)
        cfg_ov = get_overlap_config()
        use_qring = _overlap_dense_eligible(mesh, b, t, k, cfg_ov)[0]

    if not use_fused and not use_qring:
        if fused_backend_active():
            # trace-time (once per compile): the audit said quantized, but
            # this site is streaming bf16 — say so instead of silently
            # regressing the hot path
            from ...utils.logging import log_dist
            log_dist(f"weight_quant[{site}]: fused kernel ineligible "
                     f"(m={b * t} k={k} n={n} bits={bits} tp={tp} "
                     f"parallel={parallel}) — XLA dequant fallback on this "
                     "projection", ranks=[0])
        if parallel == "row" and tp > 1:
            # GSPMD inserts the row-parallel allreduce around the fallback
            # matmul too — record it so bytes_on_wire doesn't undercount on
            # exactly the degraded-path topologies worth watching
            from ...utils.comms_logging import record_collective
            record_collective(site + ".monolithic", "all_reduce",
                              b * t * n * jnp.dtype(dtype).itemsize, tp,
                              overlapped=False)
        w = dequantize_node(node).astype(dtype)
        y = x.astype(dtype) @ w
        if squeeze:
            y = y[:, 0]
        return y if bias is None else y + bias.astype(dtype)

    x = x.astype(dtype)
    if mesh is not None:
        batch_axes = tuple(ax for ax in BATCH_AXES if mesh.size(ax) > 1)
        bsz = int(np.prod([mesh.size(ax) for ax in batch_axes])) \
            if batch_axes else 1
        if batch_axes and b % bsz:
            batch_axes, bsz = (), 1
    else:
        batch_axes = ()
    # the bare kernel call is only safe when NOTHING is sharded: Pallas is
    # opaque to GSPMD (the reason _sharded_decode wraps the decode-attention
    # kernel), so a dp>1/tp=1 mesh must still go through the shard_map below
    # (tensor axis of size 1 degenerates cleanly) or batch-sharded
    # activations get replicated around the opaque call
    if mesh is None or (tp <= 1 and not batch_axes):
        y = quantized_matmul(x.reshape(b * t, k), q, s, bits=bits,
                             out_dtype=dtype, interpret=interp).reshape(b, t, n)
        if squeeze:
            y = y[:, 0]
        return y if bias is None else y + bias.astype(dtype)

    from ...utils.comms_logging import record_collective
    from ...utils.jax_compat import shard_map
    from jax.sharding import PartitionSpec as P
    bspec = batch_axes or None

    if parallel == "column":
        def body(x_l, q_l, s_l):
            bl, tl, kl = x_l.shape
            return quantized_matmul(
                x_l.reshape(bl * tl, kl), q_l, s_l, bits=bits,
                out_dtype=dtype, interpret=interp).reshape(bl, tl, -1)

        y = shard_map(
            body, mesh=mesh.mesh, axis_names=set(batch_axes) | {AXIS_TENSOR},
            in_specs=(P(bspec, None, None), P(None, AXIS_TENSOR),
                      P(None, AXIS_TENSOR)),
            out_specs=P(bspec, None, AXIS_TENSOR), check_vma=False)(x, q, s)
    elif use_qring:
        # row-parallel + comm_overlap: fused quantized ring (dequant-GEMM per
        # ring step, intN + error-feedback wire payload) + tiled all-gather —
        # the quantized analogue of row_parallel_dense_apply's decomposed
        # allreduce, span-recorded under the same site names
        from ...parallel.qring import quant_row_parallel_apply
        y = quant_row_parallel_apply(
            x, q, s, bits=bits, dtype=dtype, mesh=mesh,
            batch_axes=batch_axes, cfg=cfg_ov, interpret=interp, site=site)
    else:
        # row-parallel: each shard multiplies its k slice of the quantized
        # kernel (fp32 accumulation inside the kernel), then ONE monolithic
        # psum of the serve-dtype partial — same wire dtype, numerics, and
        # bytes accounting as the fp RowParallelDense monolithic path
        if tp > 1:
            record_collective(site + ".monolithic", "all_reduce",
                              b * t * n * jnp.dtype(dtype).itemsize, tp,
                              overlapped=False)

        def body(x_l, q_l, s_l):
            bl, tl, kl = x_l.shape
            part = quantized_matmul(
                x_l.reshape(bl * tl, kl), q_l, s_l, bits=bits,
                out_dtype=dtype, interpret=interp)
            return jax.lax.psum(part, AXIS_TENSOR).reshape(bl, tl, -1)

        y = shard_map(
            body, mesh=mesh.mesh, axis_names=set(batch_axes) | {AXIS_TENSOR},
            in_specs=(P(bspec, None, AXIS_TENSOR), P(AXIS_TENSOR, None),
                      P(AXIS_TENSOR, None)),
            out_specs=P(bspec, None, None), check_vma=False)(x, q, s)
        y = y.astype(dtype)
    if squeeze:
        y = y[:, 0]
    return y if bias is None else y + bias.astype(dtype)
