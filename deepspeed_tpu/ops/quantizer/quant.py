"""Grouped symmetric int8 weight quantization.

TPU-native analogue of the reference's quantization kernels
(``csrc/quantization/quantize.cu`` / ``dequantize.cu``) and the injection-time
``GroupQuantizer`` (``module_inject/replace_module.py:152``): weights are quantized per
group along the contraction (input) dimension with one fp scale per group per output
column; dequantisation happens in the compiled graph where XLA fuses it into the
consumer. Storage and HBM reads of the weight halve (int8 vs bf16).
"""

from typing import Tuple

import jax.numpy as jnp

DEFAULT_GROUP = 128


def _group_size(k: int, group_size: int) -> int:
    g = min(group_size, k)
    while k % g:
        g //= 2
    return max(g, 1)


def quantize_grouped(w, group_size: int = DEFAULT_GROUP) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """w: (..., k, n) → (q int8 (..., k, n), scales f32 (..., k//g, n)).

    Groups run along the second-to-last (contraction) dim; symmetric, zero-point-free —
    the reference's symmetric mode (``quantize.cu`` Symmetric kernels).
    """
    w = jnp.asarray(w)
    k, n = w.shape[-2], w.shape[-1]
    g = _group_size(k, group_size)
    lead = w.shape[:-2]
    wg = w.reshape(*lead, k // g, g, n).astype(jnp.float32)
    amax = jnp.max(jnp.abs(wg), axis=-2, keepdims=True)          # (..., k//g, 1, n)
    scale = jnp.where(amax > 0, amax / 127.0, 1.0)
    q = jnp.clip(jnp.round(wg / scale), -127, 127).astype(jnp.int8)
    return q.reshape(*lead, k, n), scale[..., 0, :]


def dequantize_grouped(q, scales) -> jnp.ndarray:
    """Inverse of :func:`quantize_grouped`; returns f32 (cast at the consumer)."""
    k, n = q.shape[-2], q.shape[-1]
    groups = scales.shape[-2]
    g = k // groups
    lead = q.shape[:-2]
    wg = q.reshape(*lead, groups, g, n).astype(jnp.float32)
    return (wg * scales[..., :, None, :]).reshape(*lead, k, n)


# --------------------------------------------------------- engine tree helpers
INT8_Q = "__int8_q__"
INT8_SCALE = "__int8_scale__"


def validate_quant_config(quant_cfg) -> None:
    """Serving engines support 8-bit grouped quantization only — reject other
    widths loudly instead of silently serving 8-bit (``QuantConfig.bits``)."""
    bits = getattr(quant_cfg, "bits", 8)
    if getattr(quant_cfg, "enabled", False) and bits != 8:
        raise NotImplementedError(
            f"quant.bits={bits} requested but only 8-bit grouped weight "
            "quantization is wired (reference GroupQuantizer is 8-bit too)")


def dequantize_tree(params, dtype):
    """Collapse ``{__int8_q__, __int8_scale__}`` nodes to fp weights inside a
    traced computation (XLA fuses the dequant into the consuming matmul's
    operand read). Shared by the decoder and encoder inference engines so the
    int8 node contract cannot drift between them."""
    def walk(node):
        if isinstance(node, dict):
            if INT8_Q in node:
                return dequantize_grouped(
                    node[INT8_Q], node[INT8_SCALE]).astype(dtype)
            return {k: walk(v) for k, v in node.items()}
        return node

    return walk(params)
