"""Grouped symmetric int8/int4 weight quantization.

TPU-native analogue of the reference's quantization kernels
(``csrc/quantization/quantize.cu`` / ``dequantize.cu``) and the injection-time
``GroupQuantizer`` (``module_inject/replace_module.py:152``): weights are quantized per
group along the contraction (input) dimension with one fp scale per group per output
column; dequantisation happens either inside the fused Pallas matmul kernels
(``fused_matmul.py`` — int8/int4 bytes are what streams from HBM) or, on the XLA
fallback path, once per dispatch where XLA fuses it into the consumer. Storage and
HBM reads of the weight shrink 2x (int8) / 4x (int4) vs bf16, plus the per-group
scale overhead (4/group bytes per element).

int4 storage packs two nibbles per int8 byte (``pack_int4``/``unpack_int4``) with a
*per-group split-half* layout: within each scale group of ``g`` rows, byte row ``j``
(``j < g/2``) holds logical row ``j`` in its low nibble and row ``j + g/2`` in its
high nibble. Unpacking is then a concat along the (sublane) row axis — no interleave
— and a TP shard whose row range covers whole groups unpacks locally without
neighbour data.
"""

from collections.abc import Mapping
from typing import Optional, Tuple

import jax.numpy as jnp

from ...utils.logging import log_dist

DEFAULT_GROUP = 128


def _group_size(k: int, group_size: int, *, warn_for: Optional[str] = None) -> int:
    g = min(group_size, k)
    while k % g:
        g //= 2
    g = max(g, 1)
    if warn_for is not None and g < min(group_size, k):
        # silent degradation to tiny groups bloats the scale tensor (4/g bytes
        # per element) and, at g == 1, erases the storage win entirely — say so
        import logging
        log_dist(
            f"quantize[{warn_for}]: requested group {group_size} does not "
            f"divide k={k}; effective group degraded to {g} "
            f"(scale overhead {4.0 / g:.3f} B/elem)",
            ranks=[0], level=logging.WARNING)
    return g


def quantize_grouped(w, group_size: int = DEFAULT_GROUP, bits: int = 8,
                     warn_for: Optional[str] = None
                     ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """w: (..., k, n) → (q int8 (..., k, n), scales f32 (..., k//g, n)).

    Groups run along the second-to-last (contraction) dim; symmetric, zero-point-free —
    the reference's symmetric mode (``quantize.cu`` Symmetric kernels). ``bits=4``
    clips to [-7, 7] (values still land in an int8 carrier; see :func:`pack_int4`
    for the 2-nibbles-per-byte storage form).
    """
    if bits not in (8, 4):
        raise ValueError(f"quantize_grouped: bits={bits} not in (8, 4)")
    w = jnp.asarray(w)
    k, n = w.shape[-2], w.shape[-1]
    g = _group_size(k, group_size, warn_for=warn_for)
    lead = w.shape[:-2]
    qmax = 127.0 if bits == 8 else 7.0
    wg = w.reshape(*lead, k // g, g, n).astype(jnp.float32)
    amax = jnp.max(jnp.abs(wg), axis=-2, keepdims=True)          # (..., k//g, 1, n)
    scale = jnp.where(amax > 0, amax / qmax, 1.0)
    q = jnp.clip(jnp.round(wg / scale), -qmax, qmax).astype(jnp.int8)
    return q.reshape(*lead, k, n), scale[..., 0, :]


def dequantize_grouped(q, scales) -> jnp.ndarray:
    """Inverse of :func:`quantize_grouped` (unpacked int8 carrier); returns f32
    (cast at the consumer)."""
    k, n = q.shape[-2], q.shape[-1]
    groups = scales.shape[-2]
    g = k // groups
    lead = q.shape[:-2]
    wg = q.reshape(*lead, groups, g, n).astype(jnp.float32)
    return (wg * scales[..., :, None, :]).reshape(*lead, k, n)


# ------------------------------------------------------------------ int4 packing
def pack_int4(q, groups: int) -> jnp.ndarray:
    """Pack int4 values (int8 carrier in [-7, 7], shape (..., k, n)) two nibbles
    per byte → (..., k//2, n) int8, per-group split-half layout (see module
    docstring). ``groups`` is the scale-group count along k; the per-group size
    ``g = k // groups`` must be even."""
    q = jnp.asarray(q)
    k, n = q.shape[-2], q.shape[-1]
    g = k // groups
    if k % groups or g % 2:
        raise ValueError(
            f"pack_int4: group size k/groups = {k}/{groups} must be an even "
            "integer (two nibbles pack across each group's halves)")
    lead = q.shape[:-2]
    qg = q.reshape(*lead, groups, g, n)
    lo = qg[..., : g // 2, :]
    hi = qg[..., g // 2:, :]
    packed = ((hi << 4) | (lo & 0xF)).astype(jnp.int8)
    return packed.reshape(*lead, k // 2, n)


def unpack_int4(packed, groups: int) -> jnp.ndarray:
    """Inverse of :func:`pack_int4`: (..., k//2, n) int8 → (..., k, n) int8 in
    [-7, 7] (sign-extended nibbles)."""
    packed = jnp.asarray(packed)
    k2, n = packed.shape[-2], packed.shape[-1]
    k = 2 * k2
    g = k // groups
    lead = packed.shape[:-2]
    pg = packed.reshape(*lead, groups, g // 2, n)
    lo = ((pg << 4) >> 4).astype(jnp.int8)       # arithmetic: sign-extends low nibble
    hi = (pg >> 4).astype(jnp.int8)              # arithmetic: high nibble w/ sign
    return jnp.concatenate([lo, hi], axis=-2).reshape(*lead, k, n)


# --------------------------------------------------------- engine tree helpers
INT8_Q = "__int8_q__"
INT8_SCALE = "__int8_scale__"
INT4_Q = "__int4_q__"
INT4_SCALE = "__int4_scale__"


def make_quant_node(q, scales, bits: int) -> dict:
    if bits == 8:
        return {INT8_Q: q, INT8_SCALE: scales}
    return {INT4_Q: q, INT4_SCALE: scales}


def is_quant_node(node) -> bool:
    # Mapping, not dict: flax hands params back as FrozenDict views on some
    # paths, and the model-side projection modules must still recognise a node
    return isinstance(node, Mapping) and (INT8_Q in node or INT4_Q in node)


def node_bits(node) -> int:
    return 8 if INT8_Q in node else 4


def node_qs(node) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """(quantized payload, scales) of a quant node."""
    if INT8_Q in node:
        return node[INT8_Q], node[INT8_SCALE]
    return node[INT4_Q], node[INT4_SCALE]


def node_logical_shape(node) -> Tuple[int, ...]:
    """The (..., k, n) shape of the bf16 weight a quant node stands in for."""
    q, _ = node_qs(node)
    if node_bits(node) == 4:
        return q.shape[:-2] + (2 * q.shape[-2], q.shape[-1])
    return tuple(q.shape)


def dequantize_node(node) -> jnp.ndarray:
    """Collapse a quant node to the f32 weight (cast at the consumer)."""
    q, scales = node_qs(node)
    if node_bits(node) == 4:
        q = unpack_int4(q, scales.shape[-2])
    return dequantize_grouped(q, scales)


def quantize_with_audit(w, *, bits: int, group_size: int, threshold: float,
                        name: str):
    """Quantize one matrix with a relative-error audit.

    Returns ``(node_or_None, info)``. ``node`` is the engine-tree quant node
    (int4 payload packed) or ``None`` when the matrix must stay fp; ``info``
    records the decision for the engine's quantization audit log:
    ``{name, decision, reason, bits, group_requested, group_effective,
    rel_err}``. Decisions:

    - ``quantized``   — peak-masked relative Frobenius error under
      ``threshold``;
    - ``excluded``    — outlier-heavy (error over ``threshold``): symmetric
      grouped scales burn their whole grid on the outlier, so the matrix is
      kept in bf16 (the fp read costs 2 bytes/elem but the numerics survive);
    - ``excluded`` (odd group) — ``bits=4`` but the effective group is odd so
      the split-half nibble packing cannot apply; kept fp rather than
      silently serving an int8-sized int4 carrier.

    The error metric masks out each group's scale-setting peak from BOTH the
    error and the reference: an outlier quantizes near-exactly (it IS the
    scale) while zeroing everything else in its group, so the plain
    whole-matrix relative error goes to ~0 exactly when the damage is worst.
    The masked form measures what the grid does to the non-peak mass.
    """
    w = jnp.asarray(w).astype(jnp.float32)
    k = w.shape[-2]
    g = _group_size(k, group_size, warn_for=name)
    info = {"name": name, "bits": bits, "group_requested": group_size,
            "group_effective": g}
    if bits == 4 and g % 2:
        # decided before any quantize/norm work: the matrix stays fp
        # regardless of its error, so don't burn two host-synced Frobenius
        # norms per matrix on a 7B tree
        info.update(rel_err=None, decision="excluded",
                    reason=f"effective group {g} is odd — int4 split-half "
                    "packing needs an even group; kept fp")
        return None, info
    q, s = quantize_grouped(w, group_size, bits=bits)
    lead = w.shape[:-2]
    n = w.shape[-1]
    wg = w.reshape(*lead, k // g, g, n)
    eg = (dequantize_grouped(q, s) - w).reshape(*lead, k // g, g, n)
    keep = jnp.abs(wg) < jnp.max(jnp.abs(wg), axis=-2, keepdims=True)
    err = jnp.linalg.norm((eg * keep).reshape(-1))
    ref = jnp.linalg.norm((wg * keep).reshape(-1))
    rel = float(err) / max(float(ref), 1e-12)
    info["rel_err"] = rel
    if rel > threshold:
        info.update(decision="excluded", reason=f"rel_err {rel:.4f} > "
                    f"outlier_threshold {threshold:.4f}")
        return None, info
    if bits == 4:
        q = pack_int4(q, s.shape[-2])
    info.update(decision="quantized", reason="")
    return make_quant_node(q, s.astype(jnp.float32), bits), info


def validate_quant_config(quant_cfg) -> None:
    """The legacy ``quant`` block (and ``dtype="int8"``) selects 8-bit grouped
    quantization only — reject other widths loudly instead of silently serving
    8-bit (``QuantConfig.bits``). 4-bit lives behind the ``weight_quant`` block
    where group/exclude/outlier controls exist to keep it accurate."""
    bits = getattr(quant_cfg, "bits", 8)
    if getattr(quant_cfg, "enabled", False) and bits != 8:
        raise NotImplementedError(
            f"quant.bits={bits} requested but the legacy quant block is 8-bit "
            "grouped only (reference GroupQuantizer is 8-bit too) — use the "
            "weight_quant config block for int4")


def dequantize_tree(params, dtype):
    """Collapse every quant node (int8 and packed int4) to fp weights inside a
    traced computation (XLA fuses the dequant into the consuming matmul's
    operand read). Shared by the decoder and encoder inference engines so the
    quant node contract cannot drift between them. The serving decode path
    hoists this OUT of compiled loop bodies (``decode_fns`` builders call it
    once per dispatch) and keeps fused-kernel-eligible nodes quantized."""
    def walk(node):
        if isinstance(node, dict):
            if is_quant_node(node):
                return dequantize_node(node).astype(dtype)
            return {k: walk(v) for k, v in node.items()}
        return node

    return walk(params)
