"""JIT builder for host-side native (C++) ops.

The TPU analogue of the reference's ``op_builder/builder.py`` (``OpBuilder.jit_load:430``):
device kernels need no builder here (XLA/Pallas compile in-process), but the host tier —
SIMD optimizer steps for ZeRO-Offload, async file I/O for the NVMe swap — is C++ just like
the reference's ``csrc/``. Sources live in ``deepspeed_tpu/ops/csrc/`` and are compiled on
first use into a content-hashed shared library under ``~/.cache/deepspeed_tpu/ops`` (override
with ``DS_TPU_BUILD_DIR``), then loaded via ctypes.

Flag fallback chain mirrors the reference's CPU-arch probing (``builder.py:cpu_arch``):
``-march=native -fopenmp`` → ``-fopenmp`` → plain ``-O3``.
"""

import ctypes
import hashlib
import os
import subprocess
import threading
from typing import Dict, Optional, Sequence

from ..utils.logging import logger

CSRC_DIR = os.path.join(os.path.dirname(__file__), "csrc")

_loaded: Dict[str, ctypes.CDLL] = {}
_lock = threading.Lock()


class OpBuildError(RuntimeError):
    pass


def _build_dir() -> str:
    d = os.environ.get("DS_TPU_BUILD_DIR") or os.path.join(
        os.path.expanduser("~"), ".cache", "deepspeed_tpu", "ops")
    os.makedirs(d, exist_ok=True)
    return d


def _compile(sources: Sequence[str], out_path: str, extra_flags: Sequence[str]):
    flag_sets = (
        ["-O3", "-march=native", "-fopenmp"],
        ["-O3", "-fopenmp"],
        ["-O3"],
    )
    last_err = None
    tmp = f"{out_path}.{os.getpid()}.tmp"  # per-process: concurrent builds must not race
    for flags in flag_sets:
        cmd = (["g++", "-shared", "-fPIC", "-std=c++17"] + list(flags) +
               list(extra_flags) + list(sources) + ["-o", tmp])
        try:
            proc = subprocess.run(cmd, capture_output=True, text=True, timeout=300)
        except (OSError, subprocess.TimeoutExpired) as e:
            raise OpBuildError(f"g++ unavailable or timed out: {e}")
        if proc.returncode == 0:
            os.replace(tmp, out_path)
            logger.info(f"[op_builder] built {os.path.basename(out_path)} "
                        f"({' '.join(flags)})")
            return
        last_err = proc.stderr
    raise OpBuildError(f"native build failed for {sources}:\n{last_err}")


def load_op(name: str, sources: Sequence[str],
            extra_flags: Sequence[str] = ()) -> ctypes.CDLL:
    """Compile (cached) and dlopen a csrc op. ``sources`` are csrc-relative paths."""
    with _lock:
        if name in _loaded:
            return _loaded[name]
        paths = [os.path.join(CSRC_DIR, s) for s in sources]
        h = hashlib.sha256()
        for p in paths:
            with open(p, "rb") as f:
                h.update(f.read())
        h.update(" ".join(extra_flags).encode())
        so = os.path.join(_build_dir(), f"{name}-{h.hexdigest()[:12]}.so")
        if not os.path.exists(so):
            _compile(paths, so, extra_flags)
        try:
            lib = ctypes.CDLL(so)
        except OSError as e:
            raise OpBuildError(f"built {so} but dlopen failed: {e}")
        _loaded[name] = lib
        return lib


def op_available(name: str, sources: Sequence[str]) -> bool:
    """Probe-compile (the reference's ``is_compatible`` check)."""
    try:
        load_op(name, sources)
        return True
    except OpBuildError as e:
        logger.warning(f"[op_builder] {name} unavailable: {e}")
        return False
