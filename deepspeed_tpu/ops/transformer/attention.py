"""Attention ops with switchable implementations.

The training-side analogue of the reference's fused attention kernels
(``csrc/transformer/softmax_kernels.cu`` + strided-batch-gemm attention in
``csrc/transformer/ds_transformer_cuda.cpp``): on TPU the baseline is plain XLA einsum+softmax
(which the compiler fuses and tiles onto the MXU); the ``flash``/``ring`` implementations are
Pallas kernels (``ops/attention/``) selected by name so models stay implementation-agnostic.
"""

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp


def xla_attention(q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray,
                  causal: bool = True, mask: Optional[jnp.ndarray] = None,
                  softmax_scale: Optional[float] = None,
                  dropout_rate: float = 0.0,
                  dropout_rng=None) -> jnp.ndarray:
    """Reference multi-head attention.

    Shapes: q/k/v ``(batch, seq, heads, head_dim)`` → out ``(batch, seq, heads, head_dim)``.
    Softmax runs in fp32 regardless of input dtype (the reference's attn_softmax kernels do the
    same for fp16 inputs).
    """
    *_, t, h, d = q.shape
    s = k.shape[1]
    scale = softmax_scale if softmax_scale is not None else 1.0 / (d ** 0.5)
    logits = jnp.einsum("bthd,bshd->bhts", q, k).astype(jnp.float32) * scale
    if causal:
        causal_mask = jnp.tril(jnp.ones((t, s), dtype=bool), k=s - t)
        logits = jnp.where(causal_mask[None, None], logits, jnp.finfo(jnp.float32).min)
    if mask is not None:
        # mask: (batch, s) padding mask or (batch, 1, t, s) full mask
        if mask.ndim == 2:
            mask = mask[:, None, None, :]
        logits = jnp.where(mask.astype(bool), logits, jnp.finfo(jnp.float32).min)
    probs = jax.nn.softmax(logits, axis=-1)
    if dropout_rate > 0.0 and dropout_rng is not None:
        keep = jax.random.bernoulli(dropout_rng, 1.0 - dropout_rate, probs.shape)
        probs = jnp.where(keep, probs / (1.0 - dropout_rate), 0.0)
    probs = probs.astype(v.dtype)
    return jnp.einsum("bhts,bshd->bthd", probs, v)


# Minimum sequence length for the Pallas flash kernel under ``auto``. Since the
# grid-pipelined rewrite (K/V streamed through the grid's innermost dim, online-softmax
# carry in VMEM scratch) flash wins at EVERY measured length — v5e, GPT-2-shaped
# b*t=8192 h=12 d=64 bf16, fwd: 2.6x at 1024 / 8.6x at 4096; fwd+bwd: 2.8x at 1024 /
# 6.3x at 4096 (see tests/unit/ops/test_flash_crossover.py) — so the kernel floor only
# excludes degenerate tiny shapes where block padding dominates.
FLASH_MIN_SEQ = 256


def flash_eligible(t: int) -> bool:
    """Kernel-eligibility rule shared by every flash-vs-XLA dispatch site:
    t % 128 != 0 degrades ``_block_sizes`` to tiny MXU-starved blocks, and below
    ``FLASH_MIN_SEQ`` block padding dominates — those shapes stay on XLA."""
    return t >= FLASH_MIN_SEQ and t % 128 == 0


def _auto_attention(q, k, v, **kw):
    if flash_eligible(q.shape[1]):
        from ..attention.flash import flash_attention
        return flash_attention(q, k, v, **kw)
    return xla_attention(q, k, v, **kw)


def get_attention_impl(name: str = "xla"):
    """Resolve an attention implementation by name:
    ``auto`` | ``xla`` | ``flash`` | ``ring`` | ``ulysses`` (or a pre-bound callable).

    ``auto`` on a real TPU backend dispatches by sequence length — the Pallas flash
    kernel from ``FLASH_MIN_SEQ`` up (it beats XLA at all measured lengths), XLA below;
    elsewhere always XLA (on CPU the Pallas kernel runs in interpreter mode, which is
    orders of magnitude slower — fine for kernel unit tests, wrong as a default).
    """
    if callable(name):
        return name  # pre-bound impl (e.g. make_sparse_attention_impl(config))
    if name == "auto":
        if jax.default_backend() != "tpu":
            return xla_attention
        return _auto_attention
    if name == "xla":
        return xla_attention
    if name == "flash":
        from ..attention.flash import flash_attention
        return flash_attention
    if name == "ring":
        from ..attention.ring import ring_attention
        return ring_attention
    if name == "ulysses":
        from ..attention.ulysses import ulysses_attention
        return ulysses_attention
    raise ValueError(f"Unknown attention impl {name!r}")
