from .decode_ffn import moe_decode_ffn, moe_decode_ffn_quant, moe_decode_ffn_xla

__all__ = ["moe_decode_ffn", "moe_decode_ffn_quant", "moe_decode_ffn_xla"]
