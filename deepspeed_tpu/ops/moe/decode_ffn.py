"""Gather-fused MoE FFN for the decode hot loop.

TPU-native equivalent of the reference's dedicated MoE inference ops
(``deepspeed/ops/transformer/inference/moe_inference.py:463`` — gating + selected-expert
FFN in the per-token decode path). A decode step carries one token per sequence, so the
FFN touches exactly ``n = b*k`` expert slices of the stacked ``(e, d, f)`` weights. The
naive ``w1[idx]`` gather materialises an HBM copy of those slices (gather read + write +
matmul re-read = 3× weight traffic — measured 68% of dense decode tok/s at 125M/8e);
this kernel instead selects each token's expert block in the ``BlockSpec`` index maps
(scalar-prefetched indices), so the chosen expert's weights stream from HBM into the
matmul exactly once.

Grid ``(n, f_blocks)``: for token ``i`` and hidden block ``j``,
``h_j = act(x_i @ w1[idx_i, :, j] + b1[idx_i, j])`` then ``y_i += h_j @ w2[idx_i, j, :]``
— the second matmul folds the f-blocked partial sums into the output, so nothing of size
``f`` ever lands in HBM.
"""

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def _interpret() -> bool:
    return jax.default_backend() != "tpu"


def _kernel(idx_ref, x_ref, w1_ref, b1_ref, w2_ref, b2_ref, o_ref, *, act):
    # x/b1/b2/o ride a singleton middle axis so their (1, dim) tails satisfy the
    # TPU block-shape rule (last two dims divide (8, 128) or equal the array's)
    j = pl.program_id(1)
    x = x_ref[0]                                                 # (1, d)
    h = jnp.dot(x, w1_ref[0], preferred_element_type=jnp.float32)
    h = act(h + b1_ref[0].astype(jnp.float32))                   # (1, bf)
    part = jnp.dot(h.astype(w2_ref.dtype), w2_ref[0],
                   preferred_element_type=jnp.float32)           # (1, d)

    @pl.when(j == 0)
    def _():
        o_ref[0] = part + b2_ref[0].astype(jnp.float32)

    @pl.when(j > 0)
    def _():
        o_ref[0] += part


def _pick_block_f(d: int, f: int) -> int:
    # two weight blocks of (d, bf) resident + Pallas double-buffering; keep under ~8MB
    for bf in (512, 256, 128):
        if f % bf == 0 and 2 * 2 * d * bf * 2 <= 8 * 2 ** 20:
            return bf
    return 0


def moe_decode_ffn_xla(x, idx, w1, b1, w2, b2, act) -> jnp.ndarray:
    """Reference path: gather the selected experts' weights, then matmul.

    ``x``: (n, d) tokens; ``idx``: (n,) expert ids; stacked weights ``w1`` (e, d, f),
    ``b1`` (e, f), ``w2`` (e, f, d), ``b2`` (e, d). Returns (n, d) float32."""
    cdtype = x.dtype
    h = jnp.einsum("nm,nmf->nf", x, w1[idx].astype(cdtype)) + \
        b1[idx].astype(cdtype)
    out = jnp.einsum("nf,nfm->nm", act(h), w2[idx].astype(cdtype)) + \
        b2[idx].astype(cdtype)
    return out.astype(jnp.float32)


def _gather_expert(w, idx, cdtype):
    """Select per-token expert slices of a stacked weight that may be a quant
    node. Quantized: the HBM gather reads int8 (or packed int4) bytes — 2-4x
    less weight traffic than gathering bf16 — and the dequant runs on the
    small gathered ``(n, ...)`` slice, where XLA fuses it into the consuming
    einsum's operand read."""
    from ..quantizer.quant import (dequantize_grouped, is_quant_node,
                                   node_bits, node_qs, unpack_int4)
    if not is_quant_node(w):
        return w[idx].astype(cdtype)
    q, s = node_qs(w)
    qg, sg = q[idx], s[idx]
    if node_bits(w) == 4:
        qg = unpack_int4(qg, s.shape[-2])
    return dequantize_grouped(qg, sg).astype(cdtype)


def moe_decode_ffn_quant(x, idx, w1, b1, w2, b2, act) -> jnp.ndarray:
    """Selected-expert FFN over (possibly) quantized stacked expert weights.

    Same contract as :func:`moe_decode_ffn_xla` except ``w1``/``w2`` may be
    quant nodes (``ops/quantizer`` engine-tree leaves); ``b1``/``b2`` are
    always fp. Per-expert grouped scales ride the gather, so numerics equal
    dequantize-then-gather exactly."""
    cdtype = x.dtype
    h = jnp.einsum("nm,nmf->nf", x, _gather_expert(w1, idx, cdtype)) + \
        b1[idx].astype(cdtype)
    out = jnp.einsum("nf,nfm->nm", act(h), _gather_expert(w2, idx, cdtype)) + \
        b2[idx].astype(cdtype)
    return out.astype(jnp.float32)


def moe_decode_ffn(x, idx, w1, b1, w2, b2, act) -> jnp.ndarray:
    """Selected-expert FFN: (n, d) tokens → (n, d) float32 (combine weights applied by
    the caller). Falls back to the XLA gather path when shapes don't block cleanly."""
    n, d = x.shape
    e, _, f = w1.shape
    bf = _pick_block_f(d, f)
    if _interpret() and bf == 0:
        bf = f                    # interpret mode has no tiling constraints
    if bf == 0 or (d % 128 != 0 and not _interpret()):
        return moe_decode_ffn_xla(x, idx, w1, b1, w2, b2, act)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(n, f // bf),
        in_specs=[
            pl.BlockSpec((1, 1, d), lambda i, j, idx_ref: (i, 0, 0)),
            pl.BlockSpec((1, d, bf), lambda i, j, idx_ref: (idx_ref[i], 0, j)),
            pl.BlockSpec((1, 1, bf), lambda i, j, idx_ref: (idx_ref[i], 0, j)),
            pl.BlockSpec((1, bf, d), lambda i, j, idx_ref: (idx_ref[i], j, 0)),
            pl.BlockSpec((1, 1, d), lambda i, j, idx_ref: (idx_ref[i], 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, 1, d), lambda i, j, idx_ref: (i, 0, 0)),
    )
    out = pl.pallas_call(
        functools.partial(_kernel, act=act),
        out_shape=jax.ShapeDtypeStruct((n, 1, d), jnp.float32),
        grid_spec=grid_spec,
        interpret=_interpret(),
    )(idx.astype(jnp.int32), x[:, None, :], w1, b1[:, None, :], w2,
      b2[:, None, :])
    return out[:, 0, :]
