// Host-offload fused optimizers (ZeRO-Offload tier).
//
// Behavioural equivalent of reference csrc/adam/cpu_adam.cpp (Adam_Optimizer::Step_8) and
// csrc/adagrad/cpu_adagrad.cpp, whose AVX intrinsics (csrc/includes/simd.h:17) exist because
// eager loops can't vectorise. Here SIMD comes from the compiler: `#pragma omp parallel for
// simd` plus -O3 -march=native emits the same packed FMA sequence without hand-written
// intrinsics, and parallelises across cores for multi-GB optimizer states.
//
// All buffers are flat, contiguous fp32. Bias corrections (1 - beta^t) are computed by the
// Python caller and passed in (1.0 disables). The math matches
// deepspeed_tpu/ops/adam/fused_adam.py exactly so in-graph and offloaded training agree.

#include <cmath>
#include <cstdint>
#include <cstring>

extern "C" {

void ds_adam_step(float* __restrict p, float* __restrict m, float* __restrict v,
                  const float* __restrict g, int64_t n,
                  float lr, float beta1, float beta2, float eps,
                  float weight_decay, int adam_w_mode, float bc1, float bc2) {
  const float one_minus_b1 = 1.0f - beta1;
  const float one_minus_b2 = 1.0f - beta2;
  const float inv_bc1 = 1.0f / bc1;
  const float inv_sqrt_bc2 = 1.0f / sqrtf(bc2);
  const bool l2_decay = (weight_decay != 0.0f) && !adam_w_mode;
  const bool decoupled_decay = (weight_decay != 0.0f) && adam_w_mode;

#pragma omp parallel for simd schedule(static)
  for (int64_t i = 0; i < n; ++i) {
    float grad = g[i];
    if (l2_decay) grad += weight_decay * p[i];
    const float mi = beta1 * m[i] + one_minus_b1 * grad;
    const float vi = beta2 * v[i] + one_minus_b2 * grad * grad;
    m[i] = mi;
    v[i] = vi;
    // denom = sqrt(v/bc2) + eps, written as sqrt(v)*rsqrt(bc2) for one div per element
    const float denom = sqrtf(vi) * inv_sqrt_bc2 + eps;
    float delta = (mi * inv_bc1) / denom;
    if (decoupled_decay) delta += weight_decay * p[i];
    p[i] -= lr * delta;
  }
}

void ds_adagrad_step(float* __restrict p, float* __restrict s,
                     const float* __restrict g, int64_t n,
                     float lr, float eps, float weight_decay) {
  const bool decay = weight_decay != 0.0f;
#pragma omp parallel for simd schedule(static)
  for (int64_t i = 0; i < n; ++i) {
    float grad = g[i];
    if (decay) grad += weight_decay * p[i];
    const float si = s[i] + grad * grad;
    s[i] = si;
    p[i] -= lr * grad / (sqrtf(si) + eps);
  }
}

// fp32 -> bfloat16 (round to nearest even), for pushing updated masters back to the chip
// in compute dtype without a second full-precision pass in Python.
void ds_fp32_to_bf16(const float* __restrict in, uint16_t* __restrict out, int64_t n) {
#pragma omp parallel for schedule(static)
  for (int64_t i = 0; i < n; ++i) {
    uint32_t bits;
    std::memcpy(&bits, &in[i], sizeof(bits));
    if ((bits & 0x7FFFFFFFu) > 0x7F800000u) {
      out[i] = static_cast<uint16_t>((bits >> 16) | 0x0040u);  // quiet NaN
    } else {
      const uint32_t rounding = 0x7FFFu + ((bits >> 16) & 1u);
      out[i] = static_cast<uint16_t>((bits + rounding) >> 16);
    }
  }
}

}  // extern "C"
