// Asynchronous file I/O for the NVMe swap tier (ZeRO-Infinity equivalent).
//
// Behavioural equivalent of reference csrc/aio/py_lib/deepspeed_py_aio_handle.cpp
// (deepspeed_aio_handle_t: async_pread:294 / async_pwrite, wait, thread-pool backed) and
// csrc/aio/common/deepspeed_aio_utils.cpp. The reference drives libaio/io_submit; this
// implementation uses a pthread worker pool issuing pread/pwrite — on modern kernels with
// page-cached NVMe this saturates the device for the large sequential blocks the swapper
// moves, without the libaio dependency. The queue/completion semantics match: submit
// returns immediately with a ticket, wait() blocks until the submitted batch completes.

#include <cerrno>
#include <cstdint>
#include <cstring>
#include <fcntl.h>
#include <pthread.h>
#include <unistd.h>

#include <condition_variable>
#include <mutex>
#include <queue>
#include <thread>
#include <vector>

namespace {

struct Request {
  int fd;
  void* buf;
  int64_t nbytes;
  int64_t offset;
  bool write;
};

struct Handle {
  std::vector<std::thread> workers;
  std::queue<Request> pending;
  std::mutex mu;
  std::condition_variable cv_work;
  std::condition_variable cv_done;
  int64_t inflight = 0;
  int64_t errors = 0;
  bool shutdown = false;
  int64_t block_size;

  explicit Handle(int n_threads, int64_t block) : block_size(block) {
    for (int i = 0; i < n_threads; ++i) {
      workers.emplace_back([this] { this->run(); });
    }
  }

  ~Handle() {
    {
      std::lock_guard<std::mutex> lock(mu);
      shutdown = true;
    }
    cv_work.notify_all();
    for (auto& t : workers) t.join();
  }

  static bool do_io(const Request& r, int64_t block) {
    char* p = static_cast<char*>(r.buf);
    int64_t left = r.nbytes;
    int64_t off = r.offset;
    while (left > 0) {
      int64_t chunk = left < block ? left : block;
      ssize_t n = r.write ? pwrite(r.fd, p, chunk, off)
                          : pread(r.fd, p, chunk, off);
      if (n <= 0) return false;
      p += n;
      off += n;
      left -= n;
    }
    return true;
  }

  void run() {
    for (;;) {
      Request r;
      {
        std::unique_lock<std::mutex> lock(mu);
        cv_work.wait(lock, [this] { return shutdown || !pending.empty(); });
        if (shutdown && pending.empty()) return;
        r = pending.front();
        pending.pop();
      }
      bool ok = do_io(r, block_size);
      {
        std::lock_guard<std::mutex> lock(mu);
        if (!ok) ++errors;
        --inflight;
      }
      cv_done.notify_all();
    }
  }

  void submit(const Request& r) {
    {
      std::lock_guard<std::mutex> lock(mu);
      pending.push(r);
      ++inflight;
    }
    cv_work.notify_one();
  }

  int64_t wait() {
    std::unique_lock<std::mutex> lock(mu);
    cv_done.wait(lock, [this] { return inflight == 0; });
    int64_t e = errors;
    errors = 0;
    return e;  // 0 = all ok
  }
};

}  // namespace

extern "C" {

void* ds_aio_handle_new(int n_threads, int64_t block_size) {
  if (n_threads < 1) n_threads = 1;
  if (block_size < 4096) block_size = 1 << 20;
  return new Handle(n_threads, block_size);
}

void ds_aio_handle_free(void* h) { delete static_cast<Handle*>(h); }

int ds_aio_open(const char* path, int for_write) {
  // O_TRUNC: an overwrite must not leave stale tail bytes from a longer old file
  int flags = for_write ? (O_WRONLY | O_CREAT | O_TRUNC) : O_RDONLY;
  return open(path, flags, 0644);
}

int ds_aio_open_direct(const char* path, int for_write) {
  // O_DIRECT bypasses the page cache (reference deepspeed_aio_common.cpp:76-116
  // opens with O_DIRECT for its io_submit path): required for NVMe swap tiers whose
  // working set exceeds RAM, where buffered IO double-copies and evicts. Caller
  // guarantees 4096-aligned buffers/offsets/lengths. Returns -1 when the
  // filesystem refuses O_DIRECT (e.g. tmpfs) — caller falls back to buffered.
  // Returns the fd, or -errno so the caller can distinguish a genuine O_DIRECT
  // refusal (EINVAL/EOPNOTSUPP) from unrelated failures (ENOENT, EACCES).
#ifdef O_DIRECT
  int flags = for_write ? (O_WRONLY | O_CREAT | O_TRUNC | O_DIRECT) : (O_RDONLY | O_DIRECT);
  int fd = open(path, flags, 0644);
  return fd >= 0 ? fd : -errno;
#else
  (void)path; (void)for_write;
  return -EINVAL;
#endif
}

void ds_aio_close(int fd) { close(fd); }

void ds_aio_pread(void* h, int fd, void* buf, int64_t nbytes, int64_t offset) {
  static_cast<Handle*>(h)->submit({fd, buf, nbytes, offset, false});
}

void ds_aio_pwrite(void* h, int fd, void* buf, int64_t nbytes, int64_t offset) {
  static_cast<Handle*>(h)->submit({fd, buf, nbytes, offset, true});
}

// Blocks until every submitted op completes; returns the number of FAILED ops.
int64_t ds_aio_wait(void* h) { return static_cast<Handle*>(h)->wait(); }

}  // extern "C"
