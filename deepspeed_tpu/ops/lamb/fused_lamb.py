"""LAMB optimizer.

Behavioural equivalent of reference ``deepspeed/ops/lamb/fused_lamb.py`` (``FusedLamb``, CUDA
kernel ``csrc/lamb/fused_lamb_cuda_kernel.cu``): Adam update rescaled per tensor by the trust
ratio ||p|| / ||update||, with configurable min/max coefficient clamping.
"""

from typing import NamedTuple, Tuple

import jax
import jax.numpy as jnp

from ..optimizer import Optimizer


class LambState(NamedTuple):
    step: jnp.ndarray
    exp_avg: any
    exp_avg_sq: any


def fused_lamb(betas: Tuple[float, float] = (0.9, 0.999),
               eps: float = 1e-8,
               weight_decay: float = 0.0,
               bias_correction: bool = True,
               max_coeff: float = 10.0,
               min_coeff: float = 0.01) -> Optimizer:
    """Defaults follow ``ops/lamb/fused_lamb.py:FusedLamb.__init__`` (max_coeff/min_coeff)."""
    beta1, beta2 = betas

    def init(params):
        zeros = lambda p: jnp.zeros(p.shape, dtype=jnp.float32)
        return LambState(step=jnp.int32(0),
                         exp_avg=jax.tree_util.tree_map(zeros, params),
                         exp_avg_sq=jax.tree_util.tree_map(zeros, params))

    def update(grads, state: LambState, params, lr):
        step = state.step + 1
        if bias_correction:
            bc1 = 1.0 - beta1 ** step.astype(jnp.float32)
            bc2 = 1.0 - beta2 ** step.astype(jnp.float32)
        else:
            bc1 = bc2 = jnp.float32(1.0)

        def upd(p, g, m, v):
            g = g.astype(jnp.float32)
            m_new = beta1 * m + (1.0 - beta1) * g
            v_new = beta2 * v + (1.0 - beta2) * (g * g)
            u = (m_new / bc1) / (jnp.sqrt(v_new / bc2) + eps)
            if weight_decay != 0.0:
                u = u + weight_decay * p.astype(jnp.float32)
            p_norm = jnp.linalg.norm(p.astype(jnp.float32).reshape(-1))
            u_norm = jnp.linalg.norm(u.reshape(-1))
            trust = jnp.where(u_norm > 0, p_norm / u_norm, 1.0)
            trust = jnp.where(p_norm > 0, trust, 1.0)
            trust = jnp.clip(trust, min_coeff, max_coeff)
            return (p - lr * trust * u).astype(p.dtype), m_new, v_new

        out = jax.tree_util.tree_map(upd, params, grads, state.exp_avg, state.exp_avg_sq)
        leaf = lambda t: isinstance(t, tuple)
        return (jax.tree_util.tree_map(lambda t: t[0], out, is_leaf=leaf),
                LambState(step=step,
                          exp_avg=jax.tree_util.tree_map(lambda t: t[1], out, is_leaf=leaf),
                          exp_avg_sq=jax.tree_util.tree_map(lambda t: t[2], out, is_leaf=leaf)))

    return Optimizer(init=init, update=update, name="FusedLamb")
