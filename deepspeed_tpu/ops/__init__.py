from .optimizer import Optimizer, from_optax
from .adam.fused_adam import fused_adam, fused_adamw
from .lamb.fused_lamb import fused_lamb
from .adagrad.cpu_adagrad import adagrad
