"""Optimizer protocol.

The reference ships CUDA/AVX 'fused' optimizers (``ops/adam/fused_adam.py``,
``ops/adam/cpu_adam.py``, ``ops/lamb``) because eager PyTorch won't fuse the elementwise math.
Under XLA the math fuses automatically, so an optimizer here is a pair of pure functions over
pytrees. The protocol matches optax's GradientTransformation shape but threads the learning
rate as a traced argument so LR schedules never trigger recompilation.
"""

from typing import Any, Callable, NamedTuple

import jax


class Optimizer(NamedTuple):
    """``init(params) -> state``; ``update(grads, state, params, lr) -> (new_params, state)``."""
    init: Callable[[Any], Any]
    update: Callable[..., Any]
    name: str = "optimizer"


def from_optax(tx, name: str = "optax") -> Optimizer:
    """Wrap an optax GradientTransformation (ignores the ``lr`` argument — bake the schedule
    into the transform, or use ``optax.inject_hyperparams``)."""

    def init(params):
        return tx.init(params)

    def update(grads, state, params, lr=None):
        updates, new_state = tx.update(grads, state, params)
        new_params = jax.tree_util.tree_map(lambda p, u: p + u, params, updates)
        return new_params, new_state

    return Optimizer(init=init, update=update, name=name)
