"""Block-sparse attention (reference ``deepspeed/ops/sparse_attention``): pattern
configs here; the Pallas block-sparse kernel lives in ``ops/attention``."""
from .sparsity_config import (BigBirdSparsityConfig, BSLongformerSparsityConfig,
                              DenseSparsityConfig, FixedSparsityConfig,
                              LocalSlidingWindowSparsityConfig, SparsityConfig,
                              VariableSparsityConfig, layout_to_dense_mask)
