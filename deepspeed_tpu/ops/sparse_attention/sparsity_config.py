"""Block-sparse attention pattern library.

Behavioural equivalent of reference ``deepspeed/ops/sparse_attention/sparsity_config.py``
(``SparsityConfig:9``, ``Fixed:94``, ``Variable:243``, ``BigBird:421``, ``BSLongformer:559``,
``LocalSlidingWindow:686``): each config produces a layout tensor of shape
``(num_heads, num_blocks, num_blocks)`` with 1 where a ``block×block`` tile of the
attention matrix is computed. Layouts are numpy (host-side, built once per seq length);
the Pallas block-sparse kernel consumes them as a static block mask, and
``layout_to_dense_mask`` expands them for the XLA fallback / tests.

Patterns are built with vectorised index arithmetic instead of the reference's per-element
loops — same layouts, testable in O(1) numpy ops.
"""

import math
from typing import List, Optional

import numpy as np


class SparsityConfig:
    """Base: block size, head count, per-head layout policy (reference :9)."""

    def __init__(self, num_heads: int, block: int = 16,
                 different_layout_per_head: bool = False):
        self.num_heads = num_heads
        self.block = block
        self.different_layout_per_head = different_layout_per_head
        self.num_layout_heads = num_heads if different_layout_per_head else 1

    def setup_layout(self, seq_len: int) -> np.ndarray:
        if seq_len % self.block != 0:
            raise ValueError(f"Sequence length {seq_len} must be divisible by "
                             f"block size {self.block}")
        num_blocks = seq_len // self.block
        return np.zeros((self.num_heads, num_blocks, num_blocks), dtype=np.int64)

    def check_and_propagate_first_head_layout(self, layout: np.ndarray) -> np.ndarray:
        if not self.different_layout_per_head:
            layout[1:] = layout[0]
        return layout

    def make_layout(self, seq_len: int) -> np.ndarray:
        raise NotImplementedError


class DenseSparsityConfig(SparsityConfig):
    """All blocks on — for comparison/debug (reference :63)."""

    def make_layout(self, seq_len: int) -> np.ndarray:
        layout = self.setup_layout(seq_len)
        layout[:] = 1
        return layout


def _local_windows(layout: np.ndarray, h: int, window_starts: List[int],
                   window_ends: List[int], unidirectional: bool):
    """Dense (or causal) blocks within each [start, end) window."""
    n = layout.shape[1]
    row = np.arange(n)[:, None]
    col = np.arange(n)[None, :]
    for start, end in zip(window_starts, window_ends):
        end = min(end, n)
        inside = (row >= start) & (row < end) & (col >= start) & (col < end)
        if unidirectional:
            inside &= col <= row
        layout[h][inside] = 1


class FixedSparsityConfig(SparsityConfig):
    """Fixed local windows + periodic global blocks (reference :94)."""

    def __init__(self, num_heads: int, block: int = 16,
                 different_layout_per_head: bool = False,
                 num_local_blocks: int = 4, num_global_blocks: int = 1,
                 attention: str = "bidirectional",
                 horizontal_global_attention: bool = False,
                 num_different_global_patterns: int = 1):
        super().__init__(num_heads, block, different_layout_per_head)
        if num_local_blocks % num_global_blocks != 0:
            raise ValueError(
                f"num_local_blocks {num_local_blocks} must be divisible by "
                f"num_global_blocks {num_global_blocks}")
        if attention not in ("unidirectional", "bidirectional"):
            raise NotImplementedError(
                "only uni/bi-directional attention is supported")
        if attention != "bidirectional" and horizontal_global_attention:
            raise ValueError("horizontal global attention requires bidirectional")
        if num_different_global_patterns > 1 and not different_layout_per_head:
            raise ValueError("multiple global patterns require "
                             "different_layout_per_head=True")
        if num_different_global_patterns > num_local_blocks // num_global_blocks:
            raise ValueError(
                f"num_different_global_patterns {num_different_global_patterns} "
                f"cannot exceed {num_local_blocks // num_global_blocks}")
        self.num_local_blocks = num_local_blocks
        self.num_global_blocks = num_global_blocks
        self.attention = attention
        self.horizontal_global_attention = horizontal_global_attention
        self.num_different_global_patterns = num_different_global_patterns

    def set_local_layout(self, h: int, layout: np.ndarray) -> np.ndarray:
        n = layout.shape[1]
        starts = list(range(0, n, self.num_local_blocks))
        _local_windows(layout, h, starts,
                       [s + self.num_local_blocks for s in starts],
                       self.attention == "unidirectional")
        return layout

    def set_global_layout(self, h: int, layout: np.ndarray) -> np.ndarray:
        n = layout.shape[1]
        g = self.num_global_blocks
        first = self.num_local_blocks - \
            (1 + h % self.num_different_global_patterns) * g
        end = n - (n % self.num_local_blocks)
        starts = list(range(first, end, self.num_local_blocks))
        if end < n:  # short last window (reference :214)
            starts.append(min(end + first, n - g))
        for i in starts:
            first_row = 0 if self.attention == "bidirectional" else i
            layout[h, first_row:, i:i + g] = 1
            if self.horizontal_global_attention:
                layout[h, i:i + g, :] = 1
        return layout

    def make_layout(self, seq_len: int) -> np.ndarray:
        layout = self.setup_layout(seq_len)
        for h in range(self.num_layout_heads):
            self.set_local_layout(h, layout)
            self.set_global_layout(h, layout)
        return self.check_and_propagate_first_head_layout(layout)


class VariableSparsityConfig(SparsityConfig):
    """Random + variable-width local windows + listed global blocks (reference :243)."""

    def __init__(self, num_heads: int, block: int = 16,
                 different_layout_per_head: bool = False,
                 num_random_blocks: int = 0,
                 local_window_blocks: Optional[List[int]] = None,
                 global_block_indices: Optional[List[int]] = None,
                 global_block_end_indices: Optional[List[int]] = None,
                 attention: str = "bidirectional",
                 horizontal_global_attention: bool = False,
                 seed: int = 0):
        super().__init__(num_heads, block, different_layout_per_head)
        self.num_random_blocks = num_random_blocks
        if local_window_blocks is not None and not local_window_blocks:
            raise ValueError("local_window_blocks must be non-empty "
                             "(every row needs a local window size)")
        self.local_window_blocks = (local_window_blocks
                                    if local_window_blocks is not None else [4])
        self.global_block_indices = (global_block_indices
                                     if global_block_indices is not None else [0])
        if global_block_end_indices is not None:
            if len(self.global_block_indices) != len(global_block_end_indices):
                raise ValueError("global start/end index lists must match in length")
            for s, e in zip(self.global_block_indices, global_block_end_indices):
                if s >= e:
                    raise ValueError(f"global start {s} must be < end {e}")
        self.global_block_end_indices = global_block_end_indices
        if attention not in ("unidirectional", "bidirectional"):
            raise NotImplementedError(
                "only uni/bi-directional attention is supported")
        if attention != "bidirectional" and horizontal_global_attention:
            raise ValueError("horizontal global attention requires bidirectional")
        self.attention = attention
        self.horizontal_global_attention = horizontal_global_attention
        self._rng = np.random.default_rng(seed)

    def set_random_layout(self, h: int, layout: np.ndarray) -> np.ndarray:
        n = layout.shape[1]
        if n < self.num_random_blocks:
            raise ValueError(
                f"num_random_blocks {self.num_random_blocks} exceeds rows {n}")
        for row in range(n):
            cols = self._rng.choice(n, size=self.num_random_blocks, replace=False)
            layout[h, row, cols] = 1
        return layout

    def set_local_layout(self, h: int, layout: np.ndarray) -> np.ndarray:
        n = layout.shape[1]
        starts, ends = [], []
        pos = 0
        for size in self.local_window_blocks:
            starts.append(pos)
            ends.append(min(pos + size, n))
            pos += size
        while pos < n:  # repeat the last window size (reference :357)
            starts.append(pos)
            ends.append(min(pos + size, n))
            pos += size
        _local_windows(layout, h, starts, ends,
                       self.attention == "unidirectional")
        return layout

    def set_global_layout(self, h: int, layout: np.ndarray) -> np.ndarray:
        n = layout.shape[1]
        if self.global_block_end_indices is None:
            spans = [(i, i + 1) for i in self.global_block_indices]
        else:
            spans = list(zip(self.global_block_indices,
                             self.global_block_end_indices))
        for start, end in spans:
            if start >= n:
                continue
            end = min(end, n)
            if self.horizontal_global_attention:
                layout[h, start:end, :] = 1
            first_row = 0 if self.attention == "bidirectional" else start
            layout[h, first_row:, start:end] = 1
        return layout

    def make_layout(self, seq_len: int) -> np.ndarray:
        layout = self.setup_layout(seq_len)
        for h in range(self.num_layout_heads):
            self.set_random_layout(h, layout)
            self.set_local_layout(h, layout)
            self.set_global_layout(h, layout)
        return self.check_and_propagate_first_head_layout(layout)


class BigBirdSparsityConfig(SparsityConfig):
    """Random + sliding window + ITC global blocks (reference :421; the BigBird paper
    pattern, arXiv:2007.14062)."""

    def __init__(self, num_heads: int, block: int = 16,
                 different_layout_per_head: bool = False,
                 num_random_blocks: int = 1,
                 num_sliding_window_blocks: int = 3,
                 num_global_blocks: int = 1,
                 attention: str = "bidirectional",
                 seed: int = 0):
        super().__init__(num_heads, block, different_layout_per_head)
        self.num_random_blocks = num_random_blocks
        self.num_sliding_window_blocks = num_sliding_window_blocks
        self.num_global_blocks = num_global_blocks
        if attention not in ("unidirectional", "bidirectional"):
            raise NotImplementedError(
                "only uni/bi-directional attention is supported")
        self.attention = attention
        self._rng = np.random.default_rng(seed)

    def set_random_layout(self, h: int, layout: np.ndarray) -> np.ndarray:
        n = layout.shape[1]
        if n < self.num_random_blocks:
            raise ValueError(
                f"num_random_blocks {self.num_random_blocks} exceeds rows {n}")
        for row in range(n):
            hi = n if self.attention == "bidirectional" else row + 1
            k = min(self.num_random_blocks, hi)
            cols = self._rng.choice(hi, size=k, replace=False)
            layout[h, row, cols] = 1
        return layout

    def set_sliding_window_layout(self, h: int, layout: np.ndarray) -> np.ndarray:
        n = layout.shape[1]
        if n < self.num_sliding_window_blocks:
            raise ValueError(
                f"num_sliding_window_blocks {self.num_sliding_window_blocks} "
                f"exceeds rows {n}")
        w = self.num_sliding_window_blocks // 2
        row = np.arange(n)[:, None]
        col = np.arange(n)[None, :]
        layout[h][np.abs(row - col) <= w] = 1
        return layout

    def set_global_layout_itc(self, h: int, layout: np.ndarray) -> np.ndarray:
        n = layout.shape[1]
        if n < self.num_global_blocks:
            raise ValueError(
                f"num_global_blocks {self.num_global_blocks} exceeds rows {n}")
        g = self.num_global_blocks
        layout[h, :g, :] = 1
        layout[h, :, :g] = 1
        if self.attention == "unidirectional":
            layout[h] = np.tril(layout[h])
        return layout

    def make_layout(self, seq_len: int) -> np.ndarray:
        layout = self.setup_layout(seq_len)
        for h in range(self.num_layout_heads):
            self.set_random_layout(h, layout)
            self.set_sliding_window_layout(h, layout)
            self.set_global_layout_itc(h, layout)
        return self.check_and_propagate_first_head_layout(layout)


class BSLongformerSparsityConfig(SparsityConfig):
    """Block-sparse Longformer: sliding window + listed global rows/cols
    (reference :559; arXiv:2004.05150)."""

    def __init__(self, num_heads: int, block: int = 16,
                 different_layout_per_head: bool = False,
                 num_sliding_window_blocks: int = 3,
                 global_block_indices: Optional[List[int]] = None,
                 global_block_end_indices: Optional[List[int]] = None,
                 attention: str = "bidirectional"):
        super().__init__(num_heads, block, different_layout_per_head)
        self.num_sliding_window_blocks = num_sliding_window_blocks
        self.global_block_indices = (global_block_indices
                                     if global_block_indices is not None else [0])
        if global_block_end_indices is not None:
            if len(self.global_block_indices) != len(global_block_end_indices):
                raise ValueError("global start/end index lists must match in length")
            for s, e in zip(self.global_block_indices, global_block_end_indices):
                if s >= e:
                    raise ValueError(f"global start {s} must be < end {e}")
        self.global_block_end_indices = global_block_end_indices
        self.attention = attention

    def set_sliding_window_layout(self, h: int, layout: np.ndarray) -> np.ndarray:
        n = layout.shape[1]
        if n < self.num_sliding_window_blocks:
            raise ValueError(
                f"num_sliding_window_blocks {self.num_sliding_window_blocks} "
                f"exceeds rows {n}")
        w = self.num_sliding_window_blocks // 2
        row = np.arange(n)[:, None]
        col = np.arange(n)[None, :]
        layout[h][np.abs(row - col) <= w] = 1
        return layout

    def set_global_layout(self, h: int, layout: np.ndarray) -> np.ndarray:
        n = layout.shape[1]
        if self.global_block_end_indices is None:
            spans = [(i, i + 1) for i in self.global_block_indices]
        else:
            spans = list(zip(self.global_block_indices,
                             self.global_block_end_indices))
        for start, end in spans:
            if start >= n:
                continue
            end = min(end, n)
            layout[h, start:end, :] = 1
            layout[h, :, start:end] = 1
        if self.attention == "unidirectional":
            layout[h] = np.tril(layout[h])
        return layout

    def make_layout(self, seq_len: int) -> np.ndarray:
        layout = self.setup_layout(seq_len)
        for h in range(self.num_layout_heads):
            self.set_sliding_window_layout(h, layout)
            self.set_global_layout(h, layout)
        return self.check_and_propagate_first_head_layout(layout)


class LocalSlidingWindowSparsityConfig(SparsityConfig):
    """Pure sliding window (reference :686)."""

    def __init__(self, num_heads: int, block: int = 16,
                 num_sliding_window_blocks: int = 3,
                 attention: str = "unidirectional"):
        super().__init__(num_heads, block)
        self.num_sliding_window_blocks = num_sliding_window_blocks
        self.attention = attention

    def make_layout(self, seq_len: int) -> np.ndarray:
        layout = self.setup_layout(seq_len)
        n = layout.shape[1]
        if n < self.num_sliding_window_blocks:
            raise ValueError(
                f"num_sliding_window_blocks {self.num_sliding_window_blocks} "
                f"exceeds rows {n}")
        w = self.num_sliding_window_blocks // 2
        row = np.arange(n)[:, None]
        col = np.arange(n)[None, :]
        for h in range(self.num_layout_heads):
            if self.attention == "bidirectional":
                layout[h][np.abs(row - col) <= w] = 1
            else:
                layout[h][(col <= row) & (row - col <= w)] = 1
        return self.check_and_propagate_first_head_layout(layout)


def layout_to_dense_mask(layout: np.ndarray, block: int) -> np.ndarray:
    """Expand a (H, nb, nb) block layout into a (H, T, T) boolean attention mask —
    the XLA fallback path and the ground truth for kernel tests."""
    return np.kron(layout, np.ones((block, block), dtype=layout.dtype)).astype(bool)
