"""Async I/O (reference deepspeed/ops/aio)."""
from .aio_handle import AsyncIOHandle, aio_available
