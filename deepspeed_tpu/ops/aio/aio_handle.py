"""Async file I/O handle — Python surface of the native aio op.

Behavioural equivalent of reference ``deepspeed/ops/aio`` + ``csrc/aio/py_lib``
(``AsyncIOBuilder``, ``deepspeed_aio_handle_t``): submit reads/writes of numpy buffers
against files, overlap them with compute, ``wait()`` for batch completion. Backed by
the thread-pool C++ op (``csrc/aio/deepspeed_aio.cpp``) built through the same JIT
op-builder as the SIMD Adam.
"""

import ctypes
import os
from typing import Optional

import numpy as np

from ..op_builder import OpBuildError, load_op

_lib = None
_lib_checked = False


def _get_lib():
    global _lib, _lib_checked
    if not _lib_checked:
        _lib_checked = True
        try:
            lib = load_op("deepspeed_aio", ["aio/deepspeed_aio.cpp"],
                          extra_flags=("-lpthread",))
            lib.ds_aio_handle_new.argtypes = [ctypes.c_int, ctypes.c_int64]
            lib.ds_aio_handle_new.restype = ctypes.c_void_p
            lib.ds_aio_handle_free.argtypes = [ctypes.c_void_p]
            lib.ds_aio_open.argtypes = [ctypes.c_char_p, ctypes.c_int]
            lib.ds_aio_open.restype = ctypes.c_int
            lib.ds_aio_open_direct.argtypes = [ctypes.c_char_p, ctypes.c_int]
            lib.ds_aio_open_direct.restype = ctypes.c_int
            lib.ds_aio_close.argtypes = [ctypes.c_int]
            for fn in (lib.ds_aio_pread, lib.ds_aio_pwrite):
                fn.argtypes = [ctypes.c_void_p, ctypes.c_int, ctypes.c_void_p,
                               ctypes.c_int64, ctypes.c_int64]
            lib.ds_aio_wait.argtypes = [ctypes.c_void_p]
            lib.ds_aio_wait.restype = ctypes.c_int64
            _lib = lib
        except OpBuildError:
            _lib = None
    return _lib


def aio_available() -> bool:
    return _get_lib() is not None


O_DIRECT_ALIGN = 4096


def aligned_array(n_bytes: int, dtype=np.uint8, align: int = O_DIRECT_ALIGN
                  ) -> np.ndarray:
    """Contiguous array of ``ceil(n_bytes/align)*align`` bytes whose data pointer is
    ``align``-aligned — the buffer contract O_DIRECT imposes (reference allocates the
    same via posix_memalign in deepspeed_aio_utils.cpp)."""
    itemsize = np.dtype(dtype).itemsize
    padded = -(-n_bytes // align) * align
    raw = np.empty(padded + align, np.uint8)
    shift = (-raw.ctypes.data) % align
    # the returned view keeps ``raw`` alive through its .base chain
    return raw[shift:shift + padded].view(dtype)


def padded_len(n_elems: int, itemsize: int, align: int = O_DIRECT_ALIGN) -> int:
    """Element count whose byte length rounds ``n_elems*itemsize`` up to ``align``."""
    return (-(-(n_elems * itemsize) // align) * align) // itemsize


class AsyncIOHandle:
    """Reference ``deepspeed_aio_handle_t`` surface: async_pread/async_pwrite/wait +
    sync convenience wrappers. Buffers must be contiguous writable numpy arrays and
    stay alive until ``wait()`` returns.

    ``queue_depth``/``single_submit``/``overlap_events`` are accepted for reference
    aio-config compatibility but are NO-OPS here: they tune libaio's io_submit
    batching, which the thread-pool backend doesn't have — concurrency is
    ``thread_count``, chunking is ``block_size``.
    """

    def __init__(self, thread_count: int = 1, block_size: int = 1 << 20,
                 queue_depth: int = 8, single_submit: bool = False,
                 overlap_events: bool = True, o_direct: bool = False):
        lib = _get_lib()
        if lib is None:
            raise RuntimeError("native aio op unavailable (no C++ toolchain?)")
        self._lib = lib
        # O_DIRECT (reference deepspeed_aio_common.cpp O_DIRECT + io_submit): page-
        # cache bypass for swap tiers bigger than RAM. Requires 4096-aligned
        # buffers/offsets/lengths (see aligned_array/padded_len); downgrades to
        # buffered per-filesystem when open(O_DIRECT) is refused (tmpfs).
        self.o_direct = bool(o_direct)
        if self.o_direct:
            # chunk boundaries inherit block_size alignment — a non-4096-multiple
            # block would make every chunk after the first start unaligned (EINVAL)
            block_size = max(O_DIRECT_ALIGN,
                             (int(block_size) // O_DIRECT_ALIGN) * O_DIRECT_ALIGN)
        self._h = lib.ds_aio_handle_new(int(thread_count), int(block_size))
        self._fds = {}
        self._direct_warned = False

    # errnos meaning "this filesystem does not support O_DIRECT" (vs unrelated
    # open failures like ENOENT, which must surface through the buffered retry)
    _DIRECT_REFUSED = (22, 95)   # EINVAL, EOPNOTSUPP

    def _fd(self, path: str, write: bool) -> int:
        key = (path, write)
        if key not in self._fds:
            fd = -1
            if self.o_direct:
                fd = self._lib.ds_aio_open_direct(path.encode(), int(write))
                if fd < 0 and -fd in self._DIRECT_REFUSED \
                        and not self._direct_warned:
                    from ...utils.logging import logger
                    logger.warning(f"aio: filesystem refused O_DIRECT for {path}; "
                                   "falling back to buffered IO")
                    self._direct_warned = True
            if fd < 0:
                fd = self._lib.ds_aio_open(path.encode(), int(write))
            if fd < 0:
                raise OSError(f"aio: cannot open {path} (write={write})")
            self._fds[key] = fd
        return self._fds[key]

    @staticmethod
    def _buf(arr: np.ndarray):
        if not (arr.flags["C_CONTIGUOUS"]):
            raise AssertionError("aio buffers must be contiguous")
        return arr.ctypes.data_as(ctypes.c_void_p), arr.nbytes

    def async_pread(self, arr: np.ndarray, path: str, offset: int = 0):
        if not (arr.flags["WRITEABLE"]):
            raise AssertionError("read target must be writable")
        ptr, nbytes = self._buf(arr)
        self._lib.ds_aio_pread(self._h, self._fd(path, False), ptr, nbytes, offset)

    def async_pwrite(self, arr: np.ndarray, path: str, offset: int = 0):
        ptr, nbytes = self._buf(arr)
        self._lib.ds_aio_pwrite(self._h, self._fd(path, True), ptr, nbytes, offset)

    def wait(self) -> int:
        """Block until all submitted ops complete; raises on I/O errors."""
        errors = self._lib.ds_aio_wait(self._h)
        if errors:
            raise OSError(f"aio: {errors} I/O operations failed")
        return 0

    def sync_pread(self, arr: np.ndarray, path: str, offset: int = 0):
        self.async_pread(arr, path, offset)
        self.wait()

    def sync_pwrite(self, arr: np.ndarray, path: str, offset: int = 0):
        self.async_pwrite(arr, path, offset)
        self.wait()

    def close(self):
        if self._h is not None:
            self._lib.ds_aio_wait(self._h)
            for fd in self._fds.values():
                self._lib.ds_aio_close(fd)
            self._fds.clear()
            self._lib.ds_aio_handle_free(self._h)
            self._h = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass
