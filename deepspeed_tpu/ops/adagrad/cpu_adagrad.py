"""Adagrad.

Behavioural equivalent of reference ``deepspeed/ops/adagrad/cpu_adagrad.py``
(``DeepSpeedCPUAdagrad``, AVX kernel ``csrc/adagrad/cpu_adagrad.cpp``). The host-offloaded
variant lives with the ZeRO offload tier; this is the device-side math.
"""

from typing import NamedTuple

import jax
import jax.numpy as jnp

from ..optimizer import Optimizer


class AdagradState(NamedTuple):
    step: jnp.ndarray
    sum_sq: any


def adagrad(eps: float = 1e-10, weight_decay: float = 0.0) -> Optimizer:
    def init(params):
        return AdagradState(
            step=jnp.int32(0),
            sum_sq=jax.tree_util.tree_map(
                lambda p: jnp.zeros(p.shape, dtype=jnp.float32), params))

    def update(grads, state: AdagradState, params, lr):
        def upd(p, g, s):
            g = g.astype(jnp.float32)
            if weight_decay != 0.0:
                g = g + weight_decay * p.astype(jnp.float32)
            s_new = s + g * g
            return (p - lr * g / (jnp.sqrt(s_new) + eps)).astype(p.dtype), s_new

        out = jax.tree_util.tree_map(upd, params, grads, state.sum_sq)
        leaf = lambda t: isinstance(t, tuple)
        return (jax.tree_util.tree_map(lambda t: t[0], out, is_leaf=leaf),
                AdagradState(step=state.step + 1,
                             sum_sq=jax.tree_util.tree_map(lambda t: t[1], out, is_leaf=leaf)))

    return Optimizer(init=init, update=update, name="Adagrad")
