"""Host-offloaded SIMD Adam — the optimizer step of the ZeRO-Offload tier.

Behavioural equivalent of reference ``ops/adam/cpu_adam.py`` (``DeepSpeedCPUAdam:24``) backed
by ``csrc/adam/cpu_adam.cpp``: fp32 master params and both moments live in host RAM; each step
is one fused in-place pass per tensor through the native op (compiler-vectorised + OpenMP, the
analogue of the reference's AVX ``Step_8``). Falls back to a numpy implementation when no C++
toolchain exists — same math, no parallel SIMD.

The update rule matches ``ops/adam/fused_adam.py`` bit-for-bit in structure so in-graph and
offloaded training agree.
"""

import ctypes
from typing import Any, List, Optional, Tuple

import numpy as np

from ..op_builder import OpBuildError, load_op

_F32P = ctypes.POINTER(ctypes.c_float)
_lib = None
_lib_checked = False


def _get_lib():
    global _lib, _lib_checked
    if not _lib_checked:
        _lib_checked = True
        try:
            lib = load_op("cpu_adam", ["adam/cpu_adam.cpp"])
            lib.ds_adam_step.argtypes = [
                _F32P, _F32P, _F32P, _F32P, ctypes.c_int64,
                ctypes.c_float, ctypes.c_float, ctypes.c_float, ctypes.c_float,
                ctypes.c_float, ctypes.c_int, ctypes.c_float, ctypes.c_float]
            lib.ds_adam_step.restype = None
            lib.ds_adagrad_step.argtypes = [
                _F32P, _F32P, _F32P, ctypes.c_int64,
                ctypes.c_float, ctypes.c_float, ctypes.c_float]
            lib.ds_adagrad_step.restype = None
            lib.ds_fp32_to_bf16.argtypes = [
                _F32P, ctypes.POINTER(ctypes.c_uint16), ctypes.c_int64]
            lib.ds_fp32_to_bf16.restype = None
            _lib = lib
        except OpBuildError:
            _lib = None
    return _lib


def _as_flat_f32(a: np.ndarray) -> np.ndarray:
    if not (a.dtype == np.float32):
        raise AssertionError(f"host Adam buffers must be fp32, got {a.dtype}")
    return np.ascontiguousarray(a).reshape(-1)


def adam_step(p: np.ndarray, m: np.ndarray, v: np.ndarray, g: np.ndarray,
              lr: float, beta1: float, beta2: float, eps: float,
              weight_decay: float, adam_w_mode: bool, step: int,
              bias_correction: bool = True):
    """One fused Adam step, in place on fp32 numpy buffers."""
    bc1 = 1.0 - beta1 ** step if bias_correction else 1.0
    bc2 = 1.0 - beta2 ** step if bias_correction else 1.0
    pf, mf, vf = _as_flat_f32(p), _as_flat_f32(m), _as_flat_f32(v)
    gf = _as_flat_f32(np.asarray(g, dtype=np.float32))
    lib = _get_lib()
    if lib is not None:
        lib.ds_adam_step(
            pf.ctypes.data_as(_F32P), mf.ctypes.data_as(_F32P),
            vf.ctypes.data_as(_F32P), gf.ctypes.data_as(_F32P),
            ctypes.c_int64(pf.size), ctypes.c_float(lr), ctypes.c_float(beta1),
            ctypes.c_float(beta2), ctypes.c_float(eps), ctypes.c_float(weight_decay),
            ctypes.c_int(int(adam_w_mode)), ctypes.c_float(bc1), ctypes.c_float(bc2))
        return
    # numpy fallback (same math as csrc/adam/cpu_adam.cpp)
    grad = gf if not (weight_decay != 0.0 and not adam_w_mode) \
        else gf + np.float32(weight_decay) * pf
    mf *= beta1
    mf += (1.0 - beta1) * grad
    vf *= beta2
    vf += (1.0 - beta2) * grad * grad
    denom = np.sqrt(vf / bc2) + eps
    delta = (mf / bc1) / denom
    if weight_decay != 0.0 and adam_w_mode:
        delta += np.float32(weight_decay) * pf
    pf -= np.float32(lr) * delta


def adagrad_step(p: np.ndarray, s: np.ndarray, g: np.ndarray,
                 lr: float, eps: float, weight_decay: float):
    """One fused Adagrad step in place (reference ``csrc/adagrad/cpu_adagrad.cpp``)."""
    pf, sf = _as_flat_f32(p), _as_flat_f32(s)
    gf = _as_flat_f32(np.asarray(g, dtype=np.float32))
    lib = _get_lib()
    if lib is not None:
        lib.ds_adagrad_step(
            pf.ctypes.data_as(_F32P), sf.ctypes.data_as(_F32P),
            gf.ctypes.data_as(_F32P), ctypes.c_int64(pf.size),
            ctypes.c_float(lr), ctypes.c_float(eps), ctypes.c_float(weight_decay))
        return
    grad = gf if weight_decay == 0.0 else gf + np.float32(weight_decay) * pf
    sf += grad * grad
    pf -= np.float32(lr) * grad / (np.sqrt(sf) + eps)


def native_available() -> bool:
    return _get_lib() is not None


def fp32_to_bf16(a: np.ndarray) -> np.ndarray:
    """Round-to-nearest-even fp32→bf16 (native one-pass when built, ml_dtypes otherwise)."""
    import ml_dtypes
    flat = _as_flat_f32(np.asarray(a, dtype=np.float32))
    lib = _get_lib()
    if lib is not None:
        out = np.empty(flat.size, dtype=np.uint16)
        lib.ds_fp32_to_bf16(flat.ctypes.data_as(_F32P),
                            out.ctypes.data_as(ctypes.POINTER(ctypes.c_uint16)),
                            ctypes.c_int64(flat.size))
        return out.view(ml_dtypes.bfloat16).reshape(np.shape(a))
    return flat.astype(ml_dtypes.bfloat16).reshape(np.shape(a))


class DeepSpeedCPUAdam:
    """Flat-buffer host Adam over a list of fp32 leaves (reference ``DeepSpeedCPUAdam:24``).

    Buffers are updated IN PLACE; callers keep references to ``params`` and read the updated
    values after ``step``.
    """

    def __init__(self, params: List[np.ndarray],
                 lr: float = 1e-3,
                 betas: Tuple[float, float] = (0.9, 0.999),
                 eps: float = 1e-8,
                 weight_decay: float = 0.0,
                 adamw_mode: bool = True,
                 bias_correction: bool = True):
        self.params = [_as_flat_f32_view(p) for p in params]
        self.m = [np.zeros_like(p) for p in self.params]
        self.v = [np.zeros_like(p) for p in self.params]
        self.lr = lr
        self.betas = betas
        self.eps = eps
        self.weight_decay = weight_decay
        self.adamw_mode = adamw_mode
        self.bias_correction = bias_correction
        self.step_count = 0

    def step(self, grads: List[np.ndarray], lr: Optional[float] = None,
             on_leaf_done=None):
        """One optimizer step over every leaf. ``on_leaf_done(i)`` fires right after
        leaf ``i``'s in-place update — the offload tier uses it to dispatch that
        leaf's async H2D push while the NEXT leaf's SIMD Adam runs (reference
        cpu_adam.cpp:21-57 tiles copy/compute the same way)."""
        if not (len(grads) == len(self.params)):
            raise AssertionError('len(grads) == len(self.params)')
        self.step_count += 1
        lr = self.lr if lr is None else float(lr)
        for i, (p, m, v, g) in enumerate(zip(self.params, self.m, self.v, grads)):
            adam_step(p, m, v, np.asarray(g, dtype=np.float32).reshape(-1),
                      lr, self.betas[0], self.betas[1], self.eps,
                      self.weight_decay, self.adamw_mode, self.step_count,
                      self.bias_correction)
            if on_leaf_done is not None:
                on_leaf_done(i)

    def state_dict(self) -> dict:
        return {"step": self.step_count, "m": self.m, "v": self.v}

    def load_state_dict(self, sd: dict):
        self.step_count = int(sd["step"])
        for dst, src in zip(self.m, sd["m"]):
            np.copyto(dst, np.asarray(src, dtype=np.float32).reshape(dst.shape))
        for dst, src in zip(self.v, sd["v"]):
            np.copyto(dst, np.asarray(src, dtype=np.float32).reshape(dst.shape))


def _as_flat_f32_view(a: np.ndarray) -> np.ndarray:
    """Flat fp32 view sharing memory (in-place updates must propagate to the caller —
    a silent copy would make ``step`` a no-op on the caller's buffer, so reject inputs
    that would force one)."""
    a = np.asarray(a)
    if a.dtype != np.float32 or not a.flags["C_CONTIGUOUS"] or not a.flags["WRITEABLE"]:
        raise ValueError(
            "DeepSpeedCPUAdam params must be writable C-contiguous fp32 arrays "
            f"(got dtype={a.dtype}, contiguous={a.flags['C_CONTIGUOUS']}, "
            f"writeable={a.flags['WRITEABLE']}); updates are in place")
    return a.reshape(-1)
