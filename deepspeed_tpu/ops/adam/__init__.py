from .cpu_adam import DeepSpeedCPUAdam, adam_step, native_available
from .fused_adam import fused_adam, fused_adamw
