"""Adam / AdamW.

Behavioural equivalent of reference ``deepspeed/ops/adam/fused_adam.py`` (``FusedAdam``,
multi-tensor CUDA kernel ``csrc/adam/multi_tensor_adam.cu``): Adam with bias correction and
either decoupled (AdamW) or L2 weight decay. XLA fuses the elementwise update across the whole
pytree, which is what the multi-tensor-apply kernel buys on CUDA.
"""

from typing import NamedTuple, Tuple

import jax
import jax.numpy as jnp

from ..optimizer import Optimizer


class AdamState(NamedTuple):
    step: jnp.ndarray      # i32
    exp_avg: any           # pytree like params
    exp_avg_sq: any        # pytree like params


def fused_adam(betas: Tuple[float, float] = (0.9, 0.999),
               eps: float = 1e-8,
               weight_decay: float = 0.0,
               adam_w_mode: bool = True,
               bias_correction: bool = True,
               state_dtype=jnp.float32) -> Optimizer:
    """Reference defaults match ``ops/adam/fused_adam.py:FusedAdam.__init__``."""
    beta1, beta2 = betas

    def init(params):
        zeros = lambda p: jnp.zeros(p.shape, dtype=state_dtype)
        return AdamState(
            step=jnp.int32(0),
            exp_avg=jax.tree_util.tree_map(zeros, params),
            exp_avg_sq=jax.tree_util.tree_map(zeros, params),
        )

    def update(grads, state: AdamState, params, lr):
        step = state.step + 1
        if bias_correction:
            bc1 = 1.0 - beta1 ** step.astype(jnp.float32)
            bc2 = 1.0 - beta2 ** step.astype(jnp.float32)
        else:
            bc1 = bc2 = jnp.float32(1.0)

        def upd(p, g, m, v):
            g = g.astype(state_dtype)
            if weight_decay != 0.0 and not adam_w_mode:
                g = g + weight_decay * p
            m_new = beta1 * m + (1.0 - beta1) * g
            v_new = beta2 * v + (1.0 - beta2) * (g * g)
            denom = jnp.sqrt(v_new / bc2) + eps
            delta = (m_new / bc1) / denom
            if weight_decay != 0.0 and adam_w_mode:
                delta = delta + weight_decay * p
            return (p - lr * delta).astype(p.dtype), m_new, v_new

        out = jax.tree_util.tree_map(upd, params, grads, state.exp_avg, state.exp_avg_sq)
        new_params = jax.tree_util.tree_map(lambda t: t[0], out,
                                            is_leaf=lambda t: isinstance(t, tuple))
        new_m = jax.tree_util.tree_map(lambda t: t[1], out,
                                       is_leaf=lambda t: isinstance(t, tuple))
        new_v = jax.tree_util.tree_map(lambda t: t[2], out,
                                       is_leaf=lambda t: isinstance(t, tuple))
        return new_params, AdamState(step=step, exp_avg=new_m, exp_avg_sq=new_v)

    return Optimizer(init=init, update=update,
                     name="FusedAdam(adam_w)" if adam_w_mode else "FusedAdam")


def fused_adamw(betas=(0.9, 0.999), eps=1e-8, weight_decay=0.01, **kw) -> Optimizer:
    return fused_adam(betas=betas, eps=eps, weight_decay=weight_decay, adam_w_mode=True, **kw)
