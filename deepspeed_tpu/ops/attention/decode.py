"""Fused decode attention with KV cache — the inference hot loop.

TPU-native equivalent of the reference's ``softmax_context`` inference kernel
(``csrc/transformer/inference/csrc/softmax.cu`` + KV-cache layout in ``transform.cu``, bound as
``softmax_context`` in ``pt_binding.cpp``): one kernel computes a single decode step's
attention over the cache with online softmax, masked by the per-sequence cache length —
no (T,) score materialisation in HBM, no dynamic shapes (the cache is a fixed-capacity buffer).

The cache is stored HEAD-MAJOR ``(b, h_kv, T, d)`` — the same layout transformation the
reference performs in ``transform.cu`` — so each kv head's cache block is contiguous and the
per-head matmuls batch cleanly on the MXU. Supports grouped-query attention (``h_kv <= h``) by
batching the q heads of each kv group into one matmul.
"""

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30


def _interpret() -> bool:
    return jax.default_backend() != "tpu"


def _decode_kernel(len_ref, q_ref, k_hbm, v_hbm, o_ref, *, block_k, scale):
    """q_ref: (1, hk, g, d) VMEM; k/v_hbm: (b, hk, T, d) in HBM (DMA'd blockwise);
    len_ref: scalar-prefetch (b,). Double-buffered DMA overlaps cache reads with compute —
    the cache never fits VMEM (the reason the reference streams its KV cache too)."""
    i = pl.program_id(0)
    L = len_ref[i]
    q = q_ref[0].astype(jnp.float32)            # (hk, g, d)
    hk, g, d = q.shape
    nk = pl.cdiv(L, block_k)                    # dynamic: only touch valid cache blocks

    def scoped(k_buf, v_buf, ksem, vsem):
        def k_dma(slot, kb):
            return pltpu.make_async_copy(
                k_hbm.at[i, :, pl.ds(kb * block_k, block_k), :], k_buf.at[slot],
                ksem.at[slot])

        def v_dma(slot, kb):
            return pltpu.make_async_copy(
                v_hbm.at[i, :, pl.ds(kb * block_k, block_k), :], v_buf.at[slot],
                vsem.at[slot])

        k_dma(0, 0).start()
        v_dma(0, 0).start()

        def body(kb, carry):
            m, l, acc = carry
            slot = jax.lax.rem(kb, 2)
            nxt = jax.lax.rem(kb + 1, 2)

            @pl.when(kb + 1 < nk)
            def _():
                k_dma(nxt, kb + 1).start()
                v_dma(nxt, kb + 1).start()

            k_dma(slot, kb).wait()
            v_dma(slot, kb).wait()
            k_blk = k_buf[slot].astype(jnp.float32)   # (hk, bk, d)
            v_blk = v_buf[slot].astype(jnp.float32)
            # (hk, g, d) x (hk, bk, d) -> (hk, g, bk), batched over kv heads
            s = jax.lax.dot_general(
                q, k_blk,
                dimension_numbers=(((2,), (2,)), ((0,), (0,))),
                preferred_element_type=jnp.float32) * scale
            cols = kb * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (hk, g, block_k), 2)
            s = jnp.where(cols < L, s, NEG_INF)
            m_new = jnp.maximum(m, jnp.max(s, axis=-1))
            alpha = jnp.exp(m - m_new)
            p = jnp.exp(s - m_new[..., None])
            l_new = l * alpha + jnp.sum(p, axis=-1)
            # (hk, g, bk) x (hk, bk, d) -> (hk, g, d)
            acc_new = acc * alpha[..., None] + jax.lax.dot_general(
                p, v_blk,
                dimension_numbers=(((2,), (1,)), ((0,), (0,))),
                preferred_element_type=jnp.float32)
            return m_new, l_new, acc_new

        m0 = jnp.full((hk, g), NEG_INF, jnp.float32)
        l0 = jnp.zeros((hk, g), jnp.float32)
        acc0 = jnp.zeros((hk, g, d), jnp.float32)
        m, l, acc = jax.lax.fori_loop(0, nk, body, (m0, l0, acc0))
        l_safe = jnp.where(l > 0, l, 1.0)
        o_ref[0] = (acc / l_safe[..., None]).astype(o_ref.dtype)

    pl.run_scoped(
        scoped,
        k_buf=pltpu.VMEM((2, hk, block_k, d), k_hbm.dtype),
        v_buf=pltpu.VMEM((2, hk, block_k, d), v_hbm.dtype),
        ksem=pltpu.SemaphoreType.DMA((2,)),
        vsem=pltpu.SemaphoreType.DMA((2,)),
    )


def decode_attention(q: jnp.ndarray, k_cache: jnp.ndarray, v_cache: jnp.ndarray,
                     cache_len: jnp.ndarray, softmax_scale=None,
                     block_k: int = 128) -> jnp.ndarray:
    """One decode step of attention against the cache.

    q: ``(b, h, d)`` (current position); k_cache/v_cache: ``(b, h_kv, T, d)`` head-major
    fixed-capacity; cache_len: ``(b,)`` valid lengths (the current position is already
    written to the cache). Returns ``(b, h, d)``.
    """
    b, h, d = q.shape
    hk, T = k_cache.shape[1], k_cache.shape[2]
    if not (h % hk == 0):
        raise AssertionError(f"query heads {h} must be a multiple of kv heads {hk}")
    g = h // hk
    scale = softmax_scale if softmax_scale is not None else 1.0 / float(np.sqrt(d))
    if d % 128 != 0 and not _interpret():
        # Mosaic requires HBM DMA slices 128-aligned in the minor dim; head_dim 64 caches
        # take the XLA path (still fused/online-softmax'd by XLA, just not hand-scheduled)
        return decode_attention_xla(q, k_cache, v_cache, cache_len, softmax_scale)
    bk = min(block_k, T)
    while T % bk:
        bk //= 2
    q4 = q.reshape(b, hk, g, d)
    lens = cache_len.astype(jnp.int32)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(b,),
        in_specs=[
            pl.BlockSpec((1, hk, g, d), lambda i, lens_ref: (i, 0, 0, 0)),
            pl.BlockSpec(memory_space=pl.ANY),   # cache stays in HBM, DMA'd blockwise
            pl.BlockSpec(memory_space=pl.ANY),
        ],
        out_specs=pl.BlockSpec((1, hk, g, d), lambda i, lens_ref: (i, 0, 0, 0)),
    )
    out = pl.pallas_call(
        functools.partial(_decode_kernel, block_k=bk, scale=scale),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((b, hk, g, d), q.dtype),
        interpret=_interpret(),
    )(lens, q4, k_cache, v_cache)
    return out.reshape(b, h, d)


def decode_attention_xla(q, k_cache, v_cache, cache_len, softmax_scale=None):
    """jnp reference implementation (ground truth for kernel tests; fallback path).

    Same head-major cache layout ``(b, h_kv, T, d)`` as the kernel."""
    b, h, d = q.shape
    hk, T = k_cache.shape[1], k_cache.shape[2]
    g = h // hk
    scale = softmax_scale if softmax_scale is not None else 1.0 / float(np.sqrt(d))
    q4 = q.reshape(b, hk, g, d).astype(jnp.float32)
    k = k_cache.astype(jnp.float32)
    v = v_cache.astype(jnp.float32)
    s = jnp.einsum("bkgd,bktd->bkgt", q4, k) * scale
    mask = jnp.arange(T)[None, None, None, :] < cache_len[:, None, None, None]
    s = jnp.where(mask, s, NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    o = jnp.einsum("bkgt,bktd->bkgd", p, v)
    return o.reshape(b, h, d).astype(q.dtype)
