"""Block-sparse attention — Pallas TPU kernel driven by SparsityConfig layouts.

TPU-native replacement for the reference's Triton block-sparse attention
(``deepspeed/ops/sparse_attention/matmul.py`` SDD/DSD kernels + ``softmax.py``, consumed
by ``sparse_self_attention.py``): the pattern library
(``ops/sparse_attention/sparsity_config.py`` here) produces a ``(heads, nb, nb)`` block
layout; this kernel computes attention only over active blocks.

Design: the flash-attention structure (online softmax, per-q-block streaming) with the
k-block loop replaced by a walk over a per-(head, q-block) table of ACTIVE k-block
indices. The tables are host-precomputed from the (static) layout and enter the kernel
via scalar prefetch (SMEM), so each grid cell runs a data-dependent-length ``fori_loop``
over exactly its nonzero blocks — compute and HBM traffic scale with layout density,
not t². The backward walks the transposed table for dk/dv (which q-blocks attend to
this k-block), recomputing probabilities from the saved logsumexp like the flash
backward.

Within-block elementwise causality applies on top of the block mask when the pattern is
unidirectional (the layouts are block-granular; diagonal blocks need the elementwise
triangle).
"""

import functools
from typing import Dict, Optional, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ..sparse_attention.sparsity_config import SparsityConfig, layout_to_dense_mask

NEG_INF = -1e30


def _interpret() -> bool:
    return jax.default_backend() != "tpu"


# ------------------------------------------------------------------ layout tables
def build_tables(layout: np.ndarray) -> Dict[str, np.ndarray]:
    """Active-block index tables from a (H, nb, nb) 0/1 layout.

    ``fwd_idx[h, qi, n]`` = n-th active k-block for q-block ``qi`` (pad 0),
    ``fwd_cnt[h, qi]`` = number of active k-blocks; ``bwd_*`` the transpose
    (q-blocks per k-block).
    """
    layout = np.asarray(layout) != 0
    h, nb, _ = layout.shape
    fwd_cnt = layout.sum(axis=2).astype(np.int32)
    bwd_cnt = layout.sum(axis=1).astype(np.int32)
    max_f = max(1, int(fwd_cnt.max()))
    max_b = max(1, int(bwd_cnt.max()))
    fwd_idx = np.zeros((h, nb, max_f), np.int32)
    bwd_idx = np.zeros((h, nb, max_b), np.int32)
    for hi in range(h):
        for qi in range(nb):
            nz = np.nonzero(layout[hi, qi])[0]
            fwd_idx[hi, qi, :len(nz)] = nz
        for ki in range(nb):
            nz = np.nonzero(layout[hi, :, ki])[0]
            bwd_idx[hi, ki, :len(nz)] = nz
    return {"fwd_idx": fwd_idx, "fwd_cnt": fwd_cnt,
            "bwd_idx": bwd_idx, "bwd_cnt": bwd_cnt}


# ------------------------------------------------------------------ forward kernel
def _fwd_kernel(idx_ref, cnt_ref, q_ref, k_ref, v_ref, o_ref, lse_ref,
                *, scale, causal, block, n_heads):
    q = q_ref[0].astype(jnp.float32)                  # (block, d)
    bq, d = q.shape
    i = pl.program_id(0)
    j = pl.program_id(1)
    h_idx = jax.lax.rem(i, n_heads)
    rows = j * bq + jax.lax.broadcasted_iota(jnp.int32, (bq, block), 0)
    nnz = cnt_ref[h_idx, j]

    def body(n, carry):
        m, l, acc = carry
        kb = idx_ref[h_idx, j, n]
        k_blk = k_ref[0, pl.ds(kb * block, block), :].astype(jnp.float32)
        v_blk = v_ref[0, pl.ds(kb * block, block), :].astype(jnp.float32)
        s = jax.lax.dot_general(q, k_blk, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        if causal:
            cols = kb * block + jax.lax.broadcasted_iota(jnp.int32, (bq, block), 1)
            s = jnp.where(cols <= rows, s, NEG_INF)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1))
        alpha = jnp.exp(m - m_new)
        p = jnp.exp(s - m_new[:, None])
        l_new = l * alpha + jnp.sum(p, axis=-1)
        acc_new = acc * alpha[:, None] + jax.lax.dot_general(
            p, v_blk, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32)
        return m_new, l_new, acc_new

    m0 = jnp.full((bq,), NEG_INF, jnp.float32)
    l0 = jnp.zeros((bq,), jnp.float32)
    acc0 = jnp.zeros((bq, d), jnp.float32)
    m, l, acc = jax.lax.fori_loop(0, nnz, body, (m0, l0, acc0))
    l_safe = jnp.where(l > 0, l, 1.0)
    o_ref[0] = (acc / l_safe[:, None]).astype(o_ref.dtype)
    lse = jnp.where(l > 0, m + jnp.log(l_safe), NEG_INF)
    lse_ref[0, 0] = jnp.broadcast_to(lse[None, :], (8, bq))


def _bs_fwd(q3, k3, v3, fwd_idx, fwd_cnt, scale, causal, block, n_heads):
    bh, t, d = q3.shape
    nq = t // block
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(bh, nq),
        in_specs=[
            pl.BlockSpec((1, block, d), lambda i, j, *_: (i, j, 0)),
            pl.BlockSpec((1, t, d), lambda i, j, *_: (i, 0, 0)),
            pl.BlockSpec((1, t, d), lambda i, j, *_: (i, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block, d), lambda i, j, *_: (i, j, 0)),
            pl.BlockSpec((1, 1, 8, block), lambda i, j, *_: (i, j, 0, 0)),
        ],
    )
    o3, lse = pl.pallas_call(
        functools.partial(_fwd_kernel, scale=scale, causal=causal, block=block,
                          n_heads=n_heads),
        grid_spec=grid_spec,
        out_shape=[
            jax.ShapeDtypeStruct((bh, t, d), q3.dtype),
            jax.ShapeDtypeStruct((bh, nq, 8, block), jnp.float32),
        ],
        interpret=_interpret(),
    )(fwd_idx, fwd_cnt, q3, k3, v3)
    return o3, lse[:, :, 0, :].reshape(bh, t)


# ------------------------------------------------------------------ backward kernels
def _bwd_dq_kernel(idx_ref, cnt_ref, q_ref, k_ref, v_ref, do_ref, lse_ref,
                   delta_ref, dq_ref, *, scale, causal, block, n_heads):
    q = q_ref[0].astype(jnp.float32)
    do = do_ref[0].astype(jnp.float32)
    lse = lse_ref[0, 0, 0]
    delta = delta_ref[0, 0, 0]
    bq, d = q.shape
    i = pl.program_id(0)
    j = pl.program_id(1)
    h_idx = jax.lax.rem(i, n_heads)
    rows = j * bq + jax.lax.broadcasted_iota(jnp.int32, (bq, block), 0)
    nnz = cnt_ref[h_idx, j]

    def body(n, dq):
        kb = idx_ref[h_idx, j, n]
        k_blk = k_ref[0, pl.ds(kb * block, block), :].astype(jnp.float32)
        v_blk = v_ref[0, pl.ds(kb * block, block), :].astype(jnp.float32)
        s = jax.lax.dot_general(q, k_blk, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        if causal:
            cols = kb * block + jax.lax.broadcasted_iota(jnp.int32, (bq, block), 1)
            s = jnp.where(cols <= rows, s, NEG_INF)
        p = jnp.exp(s - lse[:, None])
        dp = jax.lax.dot_general(do, v_blk, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - delta[:, None]) * scale
        return dq + jax.lax.dot_general(ds, k_blk, (((1,), (0,)), ((), ())),
                                        preferred_element_type=jnp.float32)

    dq = jax.lax.fori_loop(0, nnz, body, jnp.zeros((bq, d), jnp.float32))
    dq_ref[0] = dq.astype(dq_ref.dtype)


def _bwd_dkv_kernel(idx_ref, cnt_ref, q_ref, k_ref, v_ref, do_ref, lse_ref,
                    delta_ref, dk_ref, dv_ref, *, scale, causal, block, n_heads):
    k_blk = k_ref[0].astype(jnp.float32)              # (block, d)
    v_blk = v_ref[0].astype(jnp.float32)
    bk, d = k_blk.shape
    i = pl.program_id(0)
    kb = pl.program_id(1)
    h_idx = jax.lax.rem(i, n_heads)
    cols = kb * bk + jax.lax.broadcasted_iota(jnp.int32, (block, bk), 1)
    nnz = cnt_ref[h_idx, kb]

    def body(n, carry):
        dk, dv = carry
        qb = idx_ref[h_idx, kb, n]
        q_blk = q_ref[0, pl.ds(qb * block, block), :].astype(jnp.float32)
        do_blk = do_ref[0, pl.ds(qb * block, block), :].astype(jnp.float32)
        lse_blk = lse_ref[0, qb, 0]
        delta_blk = delta_ref[0, qb, 0]
        s = jax.lax.dot_general(q_blk, k_blk, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        if causal:
            rows = qb * block + jax.lax.broadcasted_iota(jnp.int32, (block, bk), 0)
            s = jnp.where(cols <= rows, s, NEG_INF)
        p = jnp.exp(s - lse_blk[:, None])
        dv_new = dv + jax.lax.dot_general(p, do_blk, (((0,), (0,)), ((), ())),
                                          preferred_element_type=jnp.float32)
        dp = jax.lax.dot_general(do_blk, v_blk, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - delta_blk[:, None]) * scale
        dk_new = dk + jax.lax.dot_general(ds, q_blk, (((0,), (0,)), ((), ())),
                                          preferred_element_type=jnp.float32)
        return dk_new, dv_new

    dk0 = jnp.zeros((bk, d), jnp.float32)
    dv0 = jnp.zeros((bk, d), jnp.float32)
    dk, dv = jax.lax.fori_loop(0, nnz, body, (dk0, dv0))
    dk_ref[0] = dk.astype(dk_ref.dtype)
    dv_ref[0] = dv.astype(dv_ref.dtype)


def _bs_bwd(q3, k3, v3, o3, lse, do3, tables, scale, causal, block, n_heads):
    bh, t, d = q3.shape
    nq = t // block
    delta = jnp.sum(do3.astype(jnp.float32) * o3.astype(jnp.float32), axis=-1)
    lse_b = jnp.broadcast_to(lse.reshape(bh, nq, 1, block), (bh, nq, 8, block))
    delta_b = jnp.broadcast_to(delta.reshape(bh, nq, 1, block), (bh, nq, 8, block))

    dq_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(bh, nq),
        in_specs=[
            pl.BlockSpec((1, block, d), lambda i, j, *_: (i, j, 0)),
            pl.BlockSpec((1, t, d), lambda i, j, *_: (i, 0, 0)),
            pl.BlockSpec((1, t, d), lambda i, j, *_: (i, 0, 0)),
            pl.BlockSpec((1, block, d), lambda i, j, *_: (i, j, 0)),
            pl.BlockSpec((1, 1, 8, block), lambda i, j, *_: (i, j, 0, 0)),
            pl.BlockSpec((1, 1, 8, block), lambda i, j, *_: (i, j, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, block, d), lambda i, j, *_: (i, j, 0)),
    )
    dq = pl.pallas_call(
        functools.partial(_bwd_dq_kernel, scale=scale, causal=causal, block=block,
                          n_heads=n_heads),
        grid_spec=dq_spec,
        out_shape=jax.ShapeDtypeStruct((bh, t, d), q3.dtype),
        interpret=_interpret(),
    )(tables["fwd_idx"], tables["fwd_cnt"], q3, k3, v3, do3, lse_b, delta_b)

    dkv_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(bh, nq),
        in_specs=[
            pl.BlockSpec((1, t, d), lambda i, j, *_: (i, 0, 0)),
            pl.BlockSpec((1, block, d), lambda i, j, *_: (i, j, 0)),
            pl.BlockSpec((1, block, d), lambda i, j, *_: (i, j, 0)),
            pl.BlockSpec((1, t, d), lambda i, j, *_: (i, 0, 0)),
            pl.BlockSpec((1, nq, 8, block), lambda i, j, *_: (i, 0, 0, 0)),
            pl.BlockSpec((1, nq, 8, block), lambda i, j, *_: (i, 0, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block, d), lambda i, j, *_: (i, j, 0)),
            pl.BlockSpec((1, block, d), lambda i, j, *_: (i, j, 0)),
        ],
    )
    dk, dv = pl.pallas_call(
        functools.partial(_bwd_dkv_kernel, scale=scale, causal=causal, block=block,
                          n_heads=n_heads),
        grid_spec=dkv_spec,
        out_shape=[
            jax.ShapeDtypeStruct((bh, t, d), k3.dtype),
            jax.ShapeDtypeStruct((bh, t, d), v3.dtype),
        ],
        interpret=_interpret(),
    )(tables["bwd_idx"], tables["bwd_cnt"], q3, k3, v3, do3, lse_b, delta_b)
    return dq, dk, dv


# ------------------------------------------------------------------ custom vjp core
@functools.partial(jax.custom_vjp, nondiff_argnums=(7, 8, 9, 10))
def _bs_core(q3, k3, v3, fwd_idx, fwd_cnt, bwd_idx, bwd_cnt,
             scale, causal, block, n_heads):
    o3, _ = _bs_fwd(q3, k3, v3, fwd_idx, fwd_cnt, scale, causal, block, n_heads)
    return o3


def _bs_core_fwd(q3, k3, v3, fwd_idx, fwd_cnt, bwd_idx, bwd_cnt,
                 scale, causal, block, n_heads):
    o3, lse = _bs_fwd(q3, k3, v3, fwd_idx, fwd_cnt, scale, causal, block, n_heads)
    return o3, (q3, k3, v3, o3, lse, fwd_idx, fwd_cnt, bwd_idx, bwd_cnt)


def _bs_core_bwd(scale, causal, block, n_heads, res, do3):
    q3, k3, v3, o3, lse, fwd_idx, fwd_cnt, bwd_idx, bwd_cnt = res
    tables = {"fwd_idx": fwd_idx, "fwd_cnt": fwd_cnt,
              "bwd_idx": bwd_idx, "bwd_cnt": bwd_cnt}
    dq, dk, dv = _bs_bwd(q3, k3, v3, o3, lse, do3, tables, scale, causal, block,
                         n_heads)
    zeros = lambda x: jnp.zeros_like(x)
    return dq, dk, dv, zeros(fwd_idx), zeros(fwd_cnt), zeros(bwd_idx), zeros(bwd_cnt)


_bs_core.defvjp(_bs_core_fwd, _bs_core_bwd)


# ------------------------------------------------------------------ public ops
def block_sparse_attention(q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray,
                           layout: np.ndarray, block: int,
                           causal: bool = False,
                           softmax_scale: Optional[float] = None) -> jnp.ndarray:
    """q/k/v ``(b, t, h, d)`` with a (h, nb, nb) block ``layout`` → ``(b, t, h, d)``.

    ``causal`` applies the elementwise triangle on top of the block mask (use with
    unidirectional layouts). Rows whose layout is empty produce zeros.
    """
    b, t, h, d = q.shape
    if not (k.shape == q.shape and v.shape == q.shape):
        raise AssertionError("self-attention only")
    layout = np.asarray(layout)
    if not (layout.shape[0] == h):
        raise AssertionError((layout.shape, h))
    if not (layout.shape[1] * block == t):
        raise AssertionError(f"layout covers {layout.shape[1] * block} positions, inputs have {t}")
    scale = softmax_scale if softmax_scale is not None else 1.0 / float(np.sqrt(d))
    tables = build_tables(layout)

    def to3(x):
        return x.transpose(0, 2, 1, 3).reshape(b * h, t, d)

    o3 = _bs_core(to3(q), to3(k), to3(v),
                  jnp.asarray(tables["fwd_idx"]), jnp.asarray(tables["fwd_cnt"]),
                  jnp.asarray(tables["bwd_idx"]), jnp.asarray(tables["bwd_cnt"]),
                  scale, causal, block, h)
    return o3.reshape(b, h, t, d).transpose(0, 2, 1, 3)


def block_sparse_attention_reference(q, k, v, layout, block, causal=False,
                                     softmax_scale=None):
    """Dense-masked XLA ground truth (tests + feature fallback)."""
    from ..transformer.attention import xla_attention
    mask = layout_to_dense_mask(np.asarray(layout), block)      # (h, t, t)
    return xla_attention(q, k, v, causal=causal, mask=jnp.asarray(mask)[None],
                         softmax_scale=softmax_scale)


def make_sparse_attention_impl(config: SparsityConfig):
    """Bind a :class:`SparsityConfig` into a flash-signature attention impl
    (the ``SparseSelfAttention`` role, reference
    ``ops/sparse_attention/sparse_self_attention.py``): layouts are built and cached
    per sequence length."""
    layouts: Dict[int, np.ndarray] = {}

    def impl(q, k, v, causal=True, mask=None, softmax_scale=None,
             dropout_rate=0.0, dropout_rng=None):
        from ..transformer.attention import xla_attention
        uni = getattr(config, "attention", "bidirectional") == "unidirectional"
        if mask is not None or dropout_rate > 0.0 or q.shape[1] != k.shape[1]:
            # features the kernel doesn't cover: keep the SPARSITY PATTERN (dense
            # mask from the layout) and fall back to the masked XLA path — falling
            # back to dense attention would silently change the architecture
            t, s = q.shape[1], k.shape[1]
            if s not in layouts:
                layouts[s] = config.make_layout(s)
            lmask = jnp.asarray(layout_to_dense_mask(layouts[s],
                                                     config.block))[None]
            lmask = lmask[:, :, -t:, :]  # decode: q covers the cache tail
            if mask is not None:
                user = mask[:, None, None, :] if mask.ndim == 2 else mask
                lmask = jnp.logical_and(lmask, user.astype(bool))
            return xla_attention(q, k, v, causal=causal or uni, mask=lmask,
                                 softmax_scale=softmax_scale,
                                 dropout_rate=dropout_rate,
                                 dropout_rng=dropout_rng)
        t = q.shape[1]
        if t not in layouts:
            layouts[t] = config.make_layout(t)
        return block_sparse_attention(q, k, v, layouts[t], config.block,
                                      causal=causal or uni,
                                      softmax_scale=softmax_scale)

    return impl
