"""Flash attention — Pallas TPU kernel, forward + backward.

TPU-native replacement for the reference's fused attention kernels
(``csrc/transformer/softmax_kernels.cu`` ``attn_softmax``/``softmax_backward`` + the strided
batch gemms in ``csrc/transformer/ds_transformer_cuda.cpp``): one kernel computes the whole
attention block with online softmax, never materialising the (t × t) score matrix in HBM —
the memory behaviour the reference approximates with kernel fusion, taken to its fixed point.

Algorithm: standard flash attention v2 tiling. Forward keeps running (max, sum, acc) per
q-row-block while streaming k/v blocks through VMEM; saves per-row logsumexp for the backward.
Backward recomputes probabilities blockwise from the saved logsumexp (dq kernel gridded over
q blocks, dk/dv kernel gridded over k blocks) — no stored attention matrix, matching the
activation-memory profile that makes long sequences feasible.

On CPU (tests) kernels run in interpreter mode automatically.
"""

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu
from jax.sharding import PartitionSpec as P

NEG_INF = -1e30


def _interpret() -> bool:
    return jax.default_backend() != "tpu"


def _block_sizes(t: int, block_q: int, block_k: int):
    bq = min(block_q, t)
    bk = min(block_k, t)
    while t % bq:
        bq //= 2
    while t % bk:
        bk //= 2
    return max(bq, 1), max(bk, 1)


# ----------------------------------------------------------------------- forward kernel
def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, *, scale, causal, block_k, t_valid):
    q = q_ref[0].astype(jnp.float32)          # (bq, d)
    bq, d = q.shape
    t = k_ref.shape[1]
    j = pl.program_id(1)
    q_start = j * bq
    rows = q_start + jax.lax.broadcasted_iota(jnp.int32, (bq, block_k), 0)

    nk = t // block_k
    if causal:
        # process only blocks intersecting the causal triangle
        k_hi = jax.lax.div(q_start + bq + block_k - 1, block_k)
        k_hi = jnp.minimum(k_hi, nk)
    else:
        k_hi = nk

    def body(kb, carry):
        m, l, acc = carry
        k_blk = k_ref[0, pl.ds(kb * block_k, block_k), :].astype(jnp.float32)
        v_blk = v_ref[0, pl.ds(kb * block_k, block_k), :].astype(jnp.float32)
        s = jax.lax.dot_general(q, k_blk, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        cols = kb * block_k + jax.lax.broadcasted_iota(jnp.int32, (bq, block_k), 1)
        mask = cols < t_valid
        if causal:
            mask = jnp.logical_and(mask, cols <= rows)
        s = jnp.where(mask, s, NEG_INF)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1))
        alpha = jnp.exp(m - m_new)
        p = jnp.exp(s - m_new[:, None])
        l_new = l * alpha + jnp.sum(p, axis=-1)
        acc_new = acc * alpha[:, None] + jax.lax.dot_general(
            p, v_blk, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32)
        return m_new, l_new, acc_new

    m0 = jnp.full((bq,), NEG_INF, dtype=jnp.float32)
    l0 = jnp.zeros((bq,), dtype=jnp.float32)
    acc0 = jnp.zeros((bq, d), dtype=jnp.float32)
    m, l, acc = jax.lax.fori_loop(0, k_hi, body, (m0, l0, acc0))

    l_safe = jnp.where(l > 0, l, 1.0)
    o_ref[0] = (acc / l_safe[:, None]).astype(o_ref.dtype)
    # lse stored (bh, nq, 8, bq): TPU block tiling needs the last two dims (sublane, lane)
    # aligned to (8, 128); the 8 duplicate sublanes cost t*32B and keep the layout legal
    lse = (m + jnp.log(l_safe)).astype(jnp.float32)
    lse_ref[0, 0] = jnp.broadcast_to(lse[None, :], (8, lse.shape[0]))


def _flash_fwd(q3, k3, v3, scale, causal, block_q, block_k, t_valid):
    """q3/k3/v3: (bh, t, d) padded to block multiples. Returns (o3, lse (bh, t))."""
    bh, t, d = q3.shape
    bq, bk = _block_sizes(t, block_q, block_k)
    grid = (bh, t // bq)
    kernel = functools.partial(_fwd_kernel, scale=scale, causal=causal,
                               block_k=bk, t_valid=t_valid)
    o3, lse = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, bq, d), lambda i, j: (i, j, 0)),
            pl.BlockSpec((1, t, d), lambda i, j: (i, 0, 0)),
            pl.BlockSpec((1, t, d), lambda i, j: (i, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, bq, d), lambda i, j: (i, j, 0)),
            pl.BlockSpec((1, 1, 8, bq), lambda i, j: (i, j, 0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, t, d), q3.dtype),
            jax.ShapeDtypeStruct((bh, t // bq, 8, bq), jnp.float32),
        ],
        interpret=_interpret(),
    )(q3, k3, v3)
    return o3, lse[:, :, 0, :].reshape(bh, t)


# ---------------------------------------------------------------------- backward kernels
def _bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref,
                   *, scale, causal, block_k, t_valid):
    q = q_ref[0].astype(jnp.float32)
    do = do_ref[0].astype(jnp.float32)
    lse = lse_ref[0, 0, 0]
    delta = delta_ref[0, 0, 0]
    bq, d = q.shape
    t = k_ref.shape[1]
    j = pl.program_id(1)
    q_start = j * bq
    rows = q_start + jax.lax.broadcasted_iota(jnp.int32, (bq, block_k), 0)
    nk = t // block_k
    if causal:
        k_hi = jnp.minimum(jax.lax.div(q_start + bq + block_k - 1, block_k), nk)
    else:
        k_hi = nk

    def body(kb, dq):
        k_blk = k_ref[0, pl.ds(kb * block_k, block_k), :].astype(jnp.float32)
        v_blk = v_ref[0, pl.ds(kb * block_k, block_k), :].astype(jnp.float32)
        s = jax.lax.dot_general(q, k_blk, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        cols = kb * block_k + jax.lax.broadcasted_iota(jnp.int32, (bq, block_k), 1)
        mask = cols < t_valid
        if causal:
            mask = jnp.logical_and(mask, cols <= rows)
        s = jnp.where(mask, s, NEG_INF)
        p = jnp.exp(s - lse[:, None])                      # true probs
        dp = jax.lax.dot_general(do, v_blk, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - delta[:, None]) * scale
        return dq + jax.lax.dot_general(ds, k_blk, (((1,), (0,)), ((), ())),
                                        preferred_element_type=jnp.float32)

    dq = jax.lax.fori_loop(0, k_hi, body, jnp.zeros((bq, d), jnp.float32))
    dq_ref[0] = dq.astype(dq_ref.dtype)


def _bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dk_ref, dv_ref,
                    *, scale, causal, block_q, t_valid):
    k_blk = k_ref[0].astype(jnp.float32)      # (bk, d)
    v_blk = v_ref[0].astype(jnp.float32)
    bk, d = k_blk.shape
    t = q_ref.shape[1]
    kb = pl.program_id(1)
    k_start = kb * bk
    cols = k_start + jax.lax.broadcasted_iota(jnp.int32, (block_q, bk), 1)
    nq = t // block_q
    q_lo = jax.lax.div(k_start, block_q) if causal else 0

    def body(qb, carry):
        dk, dv = carry
        q_blk = q_ref[0, pl.ds(qb * block_q, block_q), :].astype(jnp.float32)
        do_blk = do_ref[0, pl.ds(qb * block_q, block_q), :].astype(jnp.float32)
        lse_blk = lse_ref[0, qb, 0]                           # (block_q,)
        delta_blk = delta_ref[0, qb, 0]
        s = jax.lax.dot_general(q_blk, k_blk, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        rows = qb * block_q + jax.lax.broadcasted_iota(jnp.int32, (block_q, bk), 0)
        mask = cols < t_valid
        if causal:
            mask = jnp.logical_and(mask, cols <= rows)
        s = jnp.where(mask, s, NEG_INF)
        p = jnp.exp(s - lse_blk[:, None])
        dv = dv + jax.lax.dot_general(p, do_blk, (((0,), (0,)), ((), ())),
                                      preferred_element_type=jnp.float32)
        dp = jax.lax.dot_general(do_blk, v_blk, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - delta_blk[:, None]) * scale
        dk = dk + jax.lax.dot_general(ds, q_blk, (((0,), (0,)), ((), ())),
                                      preferred_element_type=jnp.float32)
        return dk, dv

    dk0 = jnp.zeros((bk, d), jnp.float32)
    dv0 = jnp.zeros((bk, d), jnp.float32)
    dk, dv = jax.lax.fori_loop(q_lo, nq, body, (dk0, dv0))
    dk_ref[0] = dk.astype(dk_ref.dtype)
    dv_ref[0] = dv.astype(dv_ref.dtype)


def _flash_bwd(q3, k3, v3, o3, lse, do3, scale, causal, block_q, block_k, t_valid):
    bh, t, d = q3.shape
    bq, bk = _block_sizes(t, block_q, block_k)
    nq = t // bq
    delta = jnp.sum(do3.astype(jnp.float32) * o3.astype(jnp.float32), axis=-1)  # (bh, t)
    lse_b = jnp.broadcast_to(lse.reshape(bh, nq, 1, bq), (bh, nq, 8, bq))
    delta_b = jnp.broadcast_to(delta.reshape(bh, nq, 1, bq), (bh, nq, 8, bq))

    dq = pl.pallas_call(
        functools.partial(_bwd_dq_kernel, scale=scale, causal=causal, block_k=bk,
                          t_valid=t_valid),
        grid=(bh, nq),
        in_specs=[
            pl.BlockSpec((1, bq, d), lambda i, j: (i, j, 0)),
            pl.BlockSpec((1, t, d), lambda i, j: (i, 0, 0)),
            pl.BlockSpec((1, t, d), lambda i, j: (i, 0, 0)),
            pl.BlockSpec((1, bq, d), lambda i, j: (i, j, 0)),
            pl.BlockSpec((1, 1, 8, bq), lambda i, j: (i, j, 0, 0)),
            pl.BlockSpec((1, 1, 8, bq), lambda i, j: (i, j, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, bq, d), lambda i, j: (i, j, 0)),
        out_shape=jax.ShapeDtypeStruct((bh, t, d), q3.dtype),
        interpret=_interpret(),
    )(q3, k3, v3, do3, lse_b, delta_b)

    dk, dv = pl.pallas_call(
        functools.partial(_bwd_dkv_kernel, scale=scale, causal=causal, block_q=bq,
                          t_valid=t_valid),
        grid=(bh, t // bk),
        in_specs=[
            pl.BlockSpec((1, t, d), lambda i, j: (i, 0, 0)),
            pl.BlockSpec((1, bk, d), lambda i, j: (i, j, 0)),
            pl.BlockSpec((1, bk, d), lambda i, j: (i, j, 0)),
            pl.BlockSpec((1, t, d), lambda i, j: (i, 0, 0)),
            pl.BlockSpec((1, nq, 8, bq), lambda i, j: (i, 0, 0, 0)),
            pl.BlockSpec((1, nq, 8, bq), lambda i, j: (i, 0, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, bk, d), lambda i, j: (i, j, 0)),
            pl.BlockSpec((1, bk, d), lambda i, j: (i, j, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, t, d), k3.dtype),
            jax.ShapeDtypeStruct((bh, t, d), v3.dtype),
        ],
        interpret=_interpret(),
    )(q3, k3, v3, do3, lse_b, delta_b)
    return dq, dk, dv


# --------------------------------------------------------------------------- public op
@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def _flash_core(q3, k3, v3, scale, causal, block_q, block_k):
    t_valid = q3.shape[1]
    o3, _ = _flash_fwd(q3, k3, v3, scale, causal, block_q, block_k, t_valid)
    return o3


def _flash_core_fwd(q3, k3, v3, scale, causal, block_q, block_k):
    t_valid = q3.shape[1]
    o3, lse = _flash_fwd(q3, k3, v3, scale, causal, block_q, block_k, t_valid)
    return o3, (q3, k3, v3, o3, lse)


def _flash_core_bwd(scale, causal, block_q, block_k, res, do3):
    q3, k3, v3, o3, lse = res
    t_valid = q3.shape[1]
    dq, dk, dv = _flash_bwd(q3, k3, v3, o3, lse, do3, scale, causal,
                            block_q, block_k, t_valid)
    return dq, dk, dv


_flash_core.defvjp(_flash_core_fwd, _flash_core_bwd)


def flash_attention(q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray,
                    causal: bool = True, mask: Optional[jnp.ndarray] = None,
                    softmax_scale: Optional[float] = None,
                    dropout_rate: float = 0.0, dropout_rng=None,
                    block_q: int = 128, block_k: int = 128) -> jnp.ndarray:
    """Drop-in replacement for ``xla_attention``: q/k/v ``(b, t, h, d)`` → ``(b, t, h, d)``.

    Falls back to the XLA path for features the kernel does not cover (arbitrary masks,
    attention dropout, cross-attention with different kv length).
    """
    from ..transformer.attention import xla_attention
    if mask is not None or dropout_rate > 0.0 or q.shape[1] != k.shape[1]:
        return xla_attention(q, k, v, causal=causal, mask=mask,
                             softmax_scale=softmax_scale,
                             dropout_rate=dropout_rate, dropout_rng=dropout_rng)
    b, t, h, d = q.shape
    # VMEM guard: the fwd/dq kernels stage full-length K+V per batch-head (the dkv kernel
    # full Q+dO); with Pallas double-buffering that is ~4·t·d·itemsize bytes, which must fit
    # the ~16 MiB VMEM alongside block buffers. Beyond the budget, route to the XLA path —
    # very long sequences belong to ring_attention (seq-axis sharding) anyway. TODO: stream
    # K/V blocks from HBM via pltpu.make_async_copy (decode.py pattern) to lift this.
    vmem_budget = 8 * 1024 * 1024
    if 4 * t * d * q.dtype.itemsize > vmem_budget:
        return xla_attention(q, k, v, causal=causal, softmax_scale=softmax_scale)
    scale = softmax_scale if softmax_scale is not None else 1.0 / float(np.sqrt(d))

    def local(q4, k4, v4):
        lb, lt, lh, ld = q4.shape

        def to3(x):
            return x.transpose(0, 2, 1, 3).reshape(lb * lh, lt, ld)

        o3 = _flash_core(to3(q4), to3(k4), to3(v4), scale, causal, block_q, block_k)
        return o3.reshape(lb, lh, lt, ld).transpose(0, 2, 1, 3)

    # A pallas_call is opaque to the SPMD partitioner: under a sharded mesh it would force a
    # full rematerialisation. Run the kernel per-shard with shard_map over the batch (and TP
    # head) axes instead — sequence stays unsharded here (ring_attention owns the seq axis).
    from ...parallel.mesh import BATCH_AXES, AXIS_TENSOR, get_global_mesh
    mesh = get_global_mesh()
    if mesh is not None:
        batch_axes = tuple(ax for ax in BATCH_AXES if mesh.size(ax) > 1)
        bsz = int(np.prod([mesh.size(ax) for ax in batch_axes])) if batch_axes else 1
        tp = mesh.size(AXIS_TENSOR)
        use_tp = tp > 1 and h % tp == 0
        manual = set(batch_axes) | ({AXIS_TENSOR} if use_tp else set())
        if manual and b % max(bsz, 1) == 0:
            spec = P(batch_axes or None, None, AXIS_TENSOR if use_tp else None, None)
            mapped = jax.shard_map(local, mesh=mesh.mesh, axis_names=manual,
                                   in_specs=(spec,) * 3, out_specs=spec,
                                   check_vma=False)
            return mapped(q, k, v)
    return local(q, k, v)
