"""Flash attention — Pallas TPU kernel, forward + backward.

TPU-native replacement for the reference's fused attention kernels
(``csrc/transformer/softmax_kernels.cu`` ``attn_softmax``/``softmax_backward`` + the strided
batch gemms in ``csrc/transformer/ds_transformer_cuda.cpp``): one kernel computes the whole
attention block with online softmax, never materialising the (t × t) score matrix in HBM —
the memory behaviour the reference approximates with kernel fusion, taken to its fixed point.

Algorithm: flash attention v2 tiling with the K/V loop folded into the GRID's innermost
dimension: the Pallas TPU pipeline then streams K/V blocks HBM→VMEM with automatic
double-buffering (copy of block ``k+1`` overlaps compute on block ``k``), and the online
softmax carry (m, l, acc) lives in VMEM scratch across grid steps. VMEM holds only
one q block + two k/v blocks + carry — independent of sequence length, so there is NO
sequence-length guard: 128k tokens stream exactly like 1k.

Causality skips work at BLOCK granularity by index-map clamping: kv blocks entirely above
the diagonal map to the previous block index, which the pipeline recognises (no HBM
re-copy) while ``pl.when`` skips their compute — ~2× effective speedup for causal without
a second grid.

Backward recomputes probabilities blockwise from the saved logsumexp (dq kernel gridded
over q blocks × kv blocks, dk/dv kernel over kv blocks × q blocks) — no stored attention
matrix, matching the activation-memory profile that makes long sequences feasible.

On CPU (tests) kernels run in interpreter mode automatically.
"""

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu
from jax.sharding import PartitionSpec as P
from ...utils.jax_compat import shard_map

# jax >= 0.5 renames TPUCompilerParams -> CompilerParams; support both so the
# kernels load on either side of the rename
_CompilerParams = getattr(pltpu, "CompilerParams", None) or \
    getattr(pltpu, "TPUCompilerParams")

NEG_INF = -1e30


def _interpret() -> bool:
    return jax.default_backend() != "tpu"


def _block_sizes(t: int, block_q: int, block_k: int):
    bq = min(block_q, t)
    bk = min(block_k, t)
    while t % bq:
        bq //= 2
    while t % bk:
        bk //= 2
    return max(bq, 1), max(bk, 1)


def _causal_k_hi(q_idx, bq, bk):
    """Last kv-block index (inclusive) intersecting the causal triangle of q block."""
    return ((q_idx + 1) * bq - 1) // bk


def _causal_q_lo(k_idx, bq, bk):
    """First q-block index intersecting the causal triangle of kv block."""
    return (k_idx * bk) // bq


def _k_index_map(causal, bq, bk):
    """kv-block index map: under causality, blocks above the diagonal clamp to the
    last needed block — same index as the previous grid step, so the pipeline skips
    the copy while ``pl.when`` skips the compute. Shared by fwd and bwd-dq so the
    two cannot drift."""
    def k_index(i, j, kb):
        if causal:
            return (i, jnp.minimum(kb, _causal_k_hi(j, bq, bk)), 0)
        return (i, kb, 0)
    return k_index


def _q_index_map(causal, bq, bk, extra_dims=0):
    """q/lse-block index map for the dkv kernel: q blocks strictly above the causal
    diagonal clamp forward to the first contributing block (no copy, no compute)."""
    tail = (0,) * (1 + extra_dims)

    def q_index(i, kb, qb):
        if causal:
            return (i, jnp.maximum(qb, _causal_q_lo(kb, bq, bk))) + tail
        return (i, qb) + tail
    return q_index


# ----------------------------------------------------------------------- forward kernel
def _fwd_kernel(*refs, scale, causal, use_alibi, nk, bq, bk, t_valid):
    if use_alibi:
        q_ref, k_ref, v_ref, slopes_ref, o_ref, lse_ref, m_scr, l_scr, acc_scr = refs
    else:
        q_ref, k_ref, v_ref, o_ref, lse_ref, m_scr, l_scr, acc_scr = refs
        slopes_ref = None
    j = pl.program_id(1)
    kb = pl.program_id(2)

    @pl.when(kb == 0)
    def _init():
        m_scr[...] = jnp.full_like(m_scr, NEG_INF)
        l_scr[...] = jnp.zeros_like(l_scr)
        acc_scr[...] = jnp.zeros_like(acc_scr)

    active = kb * bk < t_valid
    if causal:
        active = jnp.logical_and(active, kb <= _causal_k_hi(j, bq, bk))

    @pl.when(active)
    def _compute():
        # matmuls take the INPUT dtype (bf16 inputs hit the MXU's native rate —
        # an f32 upcast here would halve matmul throughput) and accumulate f32
        q = q_ref[0]                                           # (bq, d)
        k_blk = k_ref[0]                                       # (bk, d)
        v_blk = v_ref[0]
        s = jax.lax.dot_general(q, k_blk, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        rows = j * bq + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 0)
        cols = kb * bk + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
        if use_alibi:
            # per-head additive bias slope*(col-row) — 0 on the diagonal, negative
            # below (alibi distance penalty; masked positions are overwritten next)
            s = s + slopes_ref[0, 0, 0] * (cols - rows).astype(jnp.float32)
        mask = cols < t_valid
        if causal:
            mask = jnp.logical_and(mask, cols <= rows)
        s = jnp.where(mask, s, NEG_INF)
        m_prev = m_scr[0]                                      # (8, bq) broadcast rows
        m_row = m_prev[0]                                      # (bq,)
        m_new = jnp.maximum(m_row, jnp.max(s, axis=-1))
        alpha = jnp.exp(m_row - m_new)
        p = jnp.exp(s - m_new[:, None])
        l_new = l_scr[0][0] * alpha + jnp.sum(p, axis=-1)
        acc_scr[...] = acc_scr[...] * alpha[None, :, None] + jax.lax.dot_general(
            p.astype(v_blk.dtype), v_blk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)[None]
        m_scr[...] = jnp.broadcast_to(m_new[None, None, :], m_scr.shape)
        l_scr[...] = jnp.broadcast_to(l_new[None, None, :], l_scr.shape)

    @pl.when(kb == nk - 1)
    def _finalize():
        l = l_scr[0][0]
        l_safe = jnp.where(l > 0, l, 1.0)
        o_ref[0] = (acc_scr[0] / l_safe[:, None]).astype(o_ref.dtype)
        # lse stored (bh, nq, 8, bq): TPU block tiling needs the last two dims
        # (sublane, lane) aligned; the 8 duplicate sublanes keep the layout legal
        lse = (m_scr[0][0] + jnp.log(l_safe)).astype(jnp.float32)
        lse_ref[0, 0] = jnp.broadcast_to(lse[None, :], (8, lse.shape[0]))


def _flash_fwd(q3, k3, v3, slopes3, scale, causal, block_q, block_k, t_valid):
    """q3/k3/v3: (bh, t, d) padded to block multiples; slopes3: per-(b·h) alibi
    slopes broadcast to (bh, 8, 128) for lane alignment, or None.
    Returns (o3, lse (bh, t))."""
    bh, t, d = q3.shape
    bq, bk = _block_sizes(t, block_q, block_k)
    nq, nk = t // bq, t // bk
    grid = (bh, nq, nk)
    use_alibi = slopes3 is not None

    k_index = _k_index_map(causal, bq, bk)
    kernel = functools.partial(_fwd_kernel, scale=scale, causal=causal,
                               use_alibi=use_alibi, nk=nk, bq=bq, bk=bk,
                               t_valid=t_valid)
    in_specs = [
        pl.BlockSpec((1, bq, d), lambda i, j, kb: (i, j, 0)),
        pl.BlockSpec((1, bk, d), k_index),
        pl.BlockSpec((1, bk, d), k_index),
    ]
    args = [q3, k3, v3]
    if use_alibi:
        in_specs.append(pl.BlockSpec((1, 8, 128), lambda i, j, kb: (i, 0, 0)))
        args.append(slopes3)
    o3, lse = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=in_specs,
        out_specs=[
            pl.BlockSpec((1, bq, d), lambda i, j, kb: (i, j, 0)),
            pl.BlockSpec((1, 1, 8, bq), lambda i, j, kb: (i, j, 0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, t, d), q3.dtype),
            jax.ShapeDtypeStruct((bh, nq, 8, bq), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((1, 8, bq), jnp.float32),      # m (rows dup'd over sublanes)
            pltpu.VMEM((1, 8, bq), jnp.float32),      # l
            pltpu.VMEM((1, bq, d), jnp.float32),      # acc
        ],
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "arbitrary", "arbitrary")),
        interpret=_interpret(),
    )(*args)
    return o3, lse[:, :, 0, :].reshape(bh, t)


# ---------------------------------------------------------------------- backward kernels
def _bwd_dq_kernel(*refs, scale, causal, use_alibi, nk, bq, bk, t_valid):
    if use_alibi:
        (q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, slopes_ref,
         dq_ref, dq_scr) = refs
    else:
        q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref, dq_scr = refs
        slopes_ref = None
    j = pl.program_id(1)
    kb = pl.program_id(2)

    @pl.when(kb == 0)
    def _init():
        dq_scr[...] = jnp.zeros_like(dq_scr)

    active = kb * bk < t_valid
    if causal:
        active = jnp.logical_and(active, kb <= _causal_k_hi(j, bq, bk))

    @pl.when(active)
    def _compute():
        # input-dtype matmuls, f32 accumulation (same policy as the forward —
        # bf16 inputs keep the MXU at its native rate AND make the recomputed s
        # bit-identical to the s the forward derived lse from)
        q = q_ref[0]
        do = do_ref[0]
        lse = lse_ref[0, 0, 0]
        delta = delta_ref[0, 0, 0]
        k_blk = k_ref[0]
        v_blk = v_ref[0]
        s = jax.lax.dot_general(q, k_blk, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        rows = j * bq + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 0)
        cols = kb * bk + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
        if use_alibi:
            s = s + slopes_ref[0, 0, 0] * (cols - rows).astype(jnp.float32)
        mask = cols < t_valid
        if causal:
            mask = jnp.logical_and(mask, cols <= rows)
        s = jnp.where(mask, s, NEG_INF)
        p = jnp.exp(s - lse[:, None])                      # true probs
        dp = jax.lax.dot_general(do, v_blk, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = (p * (dp - delta[:, None]) * scale).astype(k_blk.dtype)
        dq_scr[...] += jax.lax.dot_general(
            ds, k_blk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)[None]

    @pl.when(kb == nk - 1)
    def _finalize():
        dq_ref[0] = dq_scr[0].astype(dq_ref.dtype)


def _bwd_dkv_kernel(*refs, scale, causal, use_alibi, nq, bq, bk, t_valid):
    if use_alibi:
        (q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, slopes_ref,
         dk_ref, dv_ref, dk_scr, dv_scr) = refs
    else:
        (q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
         dk_ref, dv_ref, dk_scr, dv_scr) = refs
        slopes_ref = None
    kb = pl.program_id(1)
    qb = pl.program_id(2)

    @pl.when(qb == 0)
    def _init():
        dk_scr[...] = jnp.zeros_like(dk_scr)
        dv_scr[...] = jnp.zeros_like(dv_scr)

    active = kb * bk < t_valid
    if causal:
        active = jnp.logical_and(active, qb >= _causal_q_lo(kb, bq, bk))

    @pl.when(active)
    def _compute():
        # input-dtype matmuls, f32 accumulation (see _bwd_dq_kernel)
        k_blk = k_ref[0]                          # (bk, d)
        v_blk = v_ref[0]
        q_blk = q_ref[0]                          # (bq, d)
        do_blk = do_ref[0]
        lse_blk = lse_ref[0, 0, 0]                # (bq,)
        delta_blk = delta_ref[0, 0, 0]
        s = jax.lax.dot_general(q_blk, k_blk, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        rows = qb * bq + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 0)
        cols = kb * bk + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
        if use_alibi:
            s = s + slopes_ref[0, 0, 0] * (cols - rows).astype(jnp.float32)
        mask = cols < t_valid
        if causal:
            mask = jnp.logical_and(mask, cols <= rows)
        s = jnp.where(mask, s, NEG_INF)
        p = jnp.exp(s - lse_blk[:, None])
        dv_scr[...] += jax.lax.dot_general(
            p.astype(do_blk.dtype), do_blk, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)[None]
        dp = jax.lax.dot_general(do_blk, v_blk, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = (p * (dp - delta_blk[:, None]) * scale).astype(q_blk.dtype)
        dk_scr[...] += jax.lax.dot_general(
            ds, q_blk, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)[None]

    @pl.when(qb == nq - 1)
    def _finalize():
        dk_ref[0] = dk_scr[0].astype(dk_ref.dtype)
        dv_ref[0] = dv_scr[0].astype(dv_ref.dtype)


def _flash_bwd(q3, k3, v3, o3, lse, do3, slopes3, scale, causal, block_q, block_k,
               t_valid):
    bh, t, d = q3.shape
    bq, bk = _block_sizes(t, block_q, block_k)
    nq, nk = t // bq, t // bk
    use_alibi = slopes3 is not None
    delta = jnp.sum(do3.astype(jnp.float32) * o3.astype(jnp.float32), axis=-1)  # (bh, t)
    lse_b = jnp.broadcast_to(lse.reshape(bh, nq, 1, bq), (bh, nq, 8, bq))
    delta_b = jnp.broadcast_to(delta.reshape(bh, nq, 1, bq), (bh, nq, 8, bq))

    k_index = _k_index_map(causal, bq, bk)
    dq_in_specs = [
        pl.BlockSpec((1, bq, d), lambda i, j, kb: (i, j, 0)),
        pl.BlockSpec((1, bk, d), k_index),
        pl.BlockSpec((1, bk, d), k_index),
        pl.BlockSpec((1, bq, d), lambda i, j, kb: (i, j, 0)),
        pl.BlockSpec((1, 1, 8, bq), lambda i, j, kb: (i, j, 0, 0)),
        pl.BlockSpec((1, 1, 8, bq), lambda i, j, kb: (i, j, 0, 0)),
    ]
    dq_args = [q3, k3, v3, do3, lse_b, delta_b]
    if use_alibi:
        dq_in_specs.append(pl.BlockSpec((1, 8, 128), lambda i, j, kb: (i, 0, 0)))
        dq_args.append(slopes3)
    dq = pl.pallas_call(
        functools.partial(_bwd_dq_kernel, scale=scale, causal=causal,
                          use_alibi=use_alibi, nk=nk, bq=bq, bk=bk, t_valid=t_valid),
        grid=(bh, nq, nk),
        in_specs=dq_in_specs,
        out_specs=pl.BlockSpec((1, bq, d), lambda i, j, kb: (i, j, 0)),
        out_shape=jax.ShapeDtypeStruct((bh, t, d), q3.dtype),
        scratch_shapes=[pltpu.VMEM((1, bq, d), jnp.float32)],
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "arbitrary", "arbitrary")),
        interpret=_interpret(),
    )(*dq_args)

    q_index = _q_index_map(causal, bq, bk)
    lse_index = _q_index_map(causal, bq, bk, extra_dims=1)
    dkv_in_specs = [
        pl.BlockSpec((1, bq, d), q_index),
        pl.BlockSpec((1, bk, d), lambda i, kb, qb: (i, kb, 0)),
        pl.BlockSpec((1, bk, d), lambda i, kb, qb: (i, kb, 0)),
        pl.BlockSpec((1, bq, d), q_index),
        pl.BlockSpec((1, 1, 8, bq), lse_index),
        pl.BlockSpec((1, 1, 8, bq), lse_index),
    ]
    dkv_args = [q3, k3, v3, do3, lse_b, delta_b]
    if use_alibi:
        dkv_in_specs.append(pl.BlockSpec((1, 8, 128), lambda i, kb, qb: (i, 0, 0)))
        dkv_args.append(slopes3)
    dk, dv = pl.pallas_call(
        functools.partial(_bwd_dkv_kernel, scale=scale, causal=causal,
                          use_alibi=use_alibi, nq=nq, bq=bq, bk=bk, t_valid=t_valid),
        grid=(bh, nk, nq),
        in_specs=dkv_in_specs,
        out_specs=[
            pl.BlockSpec((1, bk, d), lambda i, kb, qb: (i, kb, 0)),
            pl.BlockSpec((1, bk, d), lambda i, kb, qb: (i, kb, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, t, d), k3.dtype),
            jax.ShapeDtypeStruct((bh, t, d), v3.dtype),
        ],
        scratch_shapes=[pltpu.VMEM((1, bk, d), jnp.float32),
                        pltpu.VMEM((1, bk, d), jnp.float32)],
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "arbitrary", "arbitrary")),
        interpret=_interpret(),
    )(*dkv_args)
    return dq, dk, dv


# --------------------------------------------------------------------------- public op
@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7, 8))
def _flash_core(q3, k3, v3, slopes3, scale, causal, use_alibi, block_q, block_k):
    t_valid = q3.shape[1]
    o3, _ = _flash_fwd(q3, k3, v3, slopes3 if use_alibi else None, scale, causal,
                       block_q, block_k, t_valid)
    return o3


def _flash_core_fwd(q3, k3, v3, slopes3, scale, causal, use_alibi, block_q, block_k):
    t_valid = q3.shape[1]
    o3, lse = _flash_fwd(q3, k3, v3, slopes3 if use_alibi else None, scale, causal,
                         block_q, block_k, t_valid)
    return o3, (q3, k3, v3, o3, lse, slopes3)


def _flash_core_bwd(scale, causal, use_alibi, block_q, block_k, res, do3):
    q3, k3, v3, o3, lse, slopes3 = res
    t_valid = q3.shape[1]
    dq, dk, dv = _flash_bwd(q3, k3, v3, o3, lse, do3,
                            slopes3 if use_alibi else None, scale, causal,
                            block_q, block_k, t_valid)
    # alibi slopes are a fixed schedule, not trained — zero cotangent
    return dq, dk, dv, jnp.zeros_like(slopes3)


_flash_core.defvjp(_flash_core_fwd, _flash_core_bwd)

_DUMMY_SLOPES = np.zeros((1, 8, 128), np.float32)


def _slopes3(alibi_slopes, b, h):
    """(h,) per-head slopes → (b*h, 8, 128) f32 (value duplicated for TPU lane
    alignment; the kernel reads element [0, 0, 0] of each head's block)."""
    s = jnp.tile(jnp.asarray(alibi_slopes, jnp.float32), b)       # bh = bi*h + hi
    return jnp.broadcast_to(s[:, None, None], (b * h, 8, 128))


def flash_attention_local(q4, k4, v4, causal: bool = True,
                          softmax_scale: Optional[float] = None,
                          alibi_slopes: Optional[jnp.ndarray] = None,
                          block_q: int = 1024, block_k: int = 1024):
    """Per-shard kernel invocation with NO mesh dispatch — for callers already inside a
    ``shard_map`` manual region (e.g. the TP pipeline stage_fn), where the public
    :func:`flash_attention`'s own shard_map wrapper would illegally nest."""
    lb, lt, lh, ld = q4.shape
    scale = softmax_scale if softmax_scale is not None else 1.0 / float(np.sqrt(ld))
    use_alibi = alibi_slopes is not None
    slopes3 = (_slopes3(alibi_slopes, lb, lh) if use_alibi
               else jnp.asarray(_DUMMY_SLOPES))

    def to3(x):
        return x.transpose(0, 2, 1, 3).reshape(lb * lh, lt, ld)

    o3 = _flash_core(to3(q4), to3(k4), to3(v4), slopes3, scale, causal, use_alibi,
                     block_q, block_k)
    return o3.reshape(lb, lh, lt, ld).transpose(0, 2, 1, 3)


def flash_attention(q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray,
                    causal: bool = True, mask: Optional[jnp.ndarray] = None,
                    softmax_scale: Optional[float] = None,
                    dropout_rate: float = 0.0, dropout_rng=None,
                    alibi_slopes: Optional[jnp.ndarray] = None,
                    block_q: int = 1024, block_k: int = 1024) -> jnp.ndarray:
    """Drop-in replacement for ``xla_attention``: q/k/v ``(b, t, h, d)`` → ``(b, t, h, d)``.

    ``alibi_slopes`` (h,) adds the per-head alibi distance bias ``slope*(col-row)``
    inside the kernel (BLOOM; reference fuses the same bias into its attn_softmax
    kernel, ``softmax_kernels.cu``) — no (h, t, s) bias tensor is ever materialised.

    Falls back to the XLA path for features the kernel does not cover (arbitrary masks,
    attention dropout, cross-attention with different kv length). There is no
    sequence-length guard: K/V blocks stream through the grid pipeline, so VMEM use is
    O(block) regardless of t.
    """
    from ..transformer.attention import xla_attention
    if mask is not None or dropout_rate > 0.0 or q.shape[1] != k.shape[1]:
        if alibi_slopes is not None:
            raise NotImplementedError(
                "alibi_slopes is kernel-only: combine it with mask/dropout/"
                "cross-attention via the model-level XLA bias path instead")
        return xla_attention(q, k, v, causal=causal, mask=mask,
                             softmax_scale=softmax_scale,
                             dropout_rate=dropout_rate, dropout_rng=dropout_rng)
    b, t, h, d = q.shape
    scale = softmax_scale if softmax_scale is not None else 1.0 / float(np.sqrt(d))
    use_alibi = alibi_slopes is not None

    def local(q4, k4, v4, slopes=None):
        return flash_attention_local(q4, k4, v4, causal=causal, softmax_scale=scale,
                                     alibi_slopes=slopes,
                                     block_q=block_q, block_k=block_k)

    # A pallas_call is opaque to the SPMD partitioner: under a sharded mesh it would force a
    # full rematerialisation. Run the kernel per-shard with shard_map over the batch (and TP
    # head) axes instead — sequence stays unsharded here (ring_attention owns the seq axis).
    from ...parallel.mesh import BATCH_AXES, AXIS_TENSOR, get_global_mesh
    mesh = get_global_mesh()
    if mesh is not None:
        batch_axes = tuple(ax for ax in BATCH_AXES if mesh.size(ax) > 1)
        bsz = int(np.prod([mesh.size(ax) for ax in batch_axes])) if batch_axes else 1
        tp = mesh.size(AXIS_TENSOR)
        use_tp = tp > 1 and h % tp == 0
        manual = set(batch_axes) | ({AXIS_TENSOR} if use_tp else set())
        if manual and b % max(bsz, 1) == 0:
            spec = P(batch_axes or None, None, AXIS_TENSOR if use_tp else None, None)
            if use_alibi:
                # slopes shard over the head (TP) axis: each shard sees its heads'
                sspec = P(AXIS_TENSOR if use_tp else None)
                mapped = shard_map(
                    lambda q4, k4, v4, s: local(q4, k4, v4, s),
                    mesh=mesh.mesh, axis_names=manual,
                    in_specs=(spec,) * 3 + (sspec,), out_specs=spec,
                    check_vma=False)
                return mapped(q, k, v, jnp.asarray(alibi_slopes, jnp.float32))
            mapped = shard_map(local, mesh=mesh.mesh, axis_names=manual,
                                   in_specs=(spec,) * 3, out_specs=spec,
                                   check_vma=False)
            return mapped(q, k, v)
    return local(q, k, v, jnp.asarray(alibi_slopes, jnp.float32) if use_alibi
                 else None)
