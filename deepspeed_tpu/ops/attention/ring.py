"""Ring attention — sequence/context parallelism over the ``seq`` mesh axis.

The reference snapshot has NO sequence parallelism (SURVEY §2.3: SP/CP/ring/Ulysses absent —
its long-sequence story is block-sparse attention + curriculum). Here it is first-class: the
sequence dim shards over the ``seq`` axis, K/V chunks rotate around the ring via
``jax.lax.ppermute`` (compiled onto the ICI torus) while each device accumulates attention for
its local Q chunk with online-softmax (log-sum-exp) merging — so attention memory per device is
O(t/S · t/S) per step and activations never materialise the full sequence anywhere.

The per-step chunk attention is XLA einsum+softmax (fused); each ring step is rematerialised
in the backward. Gradients flow through the transposed permutes automatically — the backward
ring runs in the reverse direction, which is exactly the ring-attention backward algorithm.
"""

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from ...parallel.mesh import AXIS_SEQ, get_global_mesh
from ...utils.jax_compat import shard_map

NEG_BIG = -1e30


def _chunk_attn(q, k, v, rows0, cols0, causal, scale):
    """Unnormalised blockwise attention: returns (acc, m, l) for LSE merging.

    q: (b, tl, h, d); k/v: (b, tc, h, d); rows0/cols0: global offsets of the chunks.
    """
    tl, tc = q.shape[1], k.shape[1]
    s = jnp.einsum("bthd,bshd->bhts", q.astype(jnp.float32),
                   k.astype(jnp.float32)) * scale
    if causal:
        rows = rows0 + jax.lax.broadcasted_iota(jnp.int32, (tl, tc), 0)
        cols = cols0 + jax.lax.broadcasted_iota(jnp.int32, (tl, tc), 1)
        s = jnp.where((cols <= rows)[None, None], s, NEG_BIG)
    m = jnp.max(s, axis=-1)                                   # (b, h, tl)
    p = jnp.exp(s - m[..., None])
    p = jnp.where(s <= NEG_BIG / 2, 0.0, p)                   # fully-masked rows stay 0
    l = jnp.sum(p, axis=-1)
    acc = jnp.einsum("bhts,bshd->bhtd", p, v.astype(jnp.float32))
    return acc, m, l


def ring_attention_local(q_l, k_l, v_l, causal: bool = True,
                         softmax_scale: Optional[float] = None,
                         axis_name: str = AXIS_SEQ,
                         seq_size: Optional[int] = None) -> jnp.ndarray:
    """Per-shard ring attention for callers ALREADY INSIDE a ``shard_map`` whose
    manual axes include ``axis_name`` (e.g. the 1F1B pipeline's seq-parallel body
    stage_fn, where :func:`ring_attention`'s own shard_map would illegally nest).

    q_l/k_l/v_l: this shard's ``(b, t/S, h, d)`` chunks; K/V rotate around the
    ring via ``ppermute`` with online-softmax (LSE) merging."""
    b, tl, h, d = q_l.shape
    S = seq_size if seq_size is not None else jax.lax.psum(1, axis_name)
    scale = softmax_scale if softmax_scale is not None else 1.0 / float(np.sqrt(d))
    perm = [(r, (r + 1) % S) for r in range(S)]
    s_idx = jax.lax.axis_index(axis_name)
    rows0 = s_idx * tl

    def step(carry, i):
        m_run, l_run, acc, k_c, v_c = carry
        owner = (s_idx - i) % S       # which global chunk this k/v is
        cols0 = owner * tl
        acc_c, m_c, l_c = _chunk_attn(q_l, k_c, v_c, rows0, cols0, causal, scale)
        m_new = jnp.maximum(m_run, m_c)
        a_run = jnp.exp(m_run - m_new)
        a_c = jnp.exp(m_c - m_new)
        acc = acc * a_run[..., None] + acc_c * a_c[..., None]
        l_new = l_run * a_run + l_c * a_c
        # rotate k/v to the next device (backward runs the reverse ring)
        k_n = jax.lax.ppermute(k_c, axis_name, perm)
        v_n = jax.lax.ppermute(v_c, axis_name, perm)
        return (m_new, l_new, acc, k_n, v_n), None

    m0 = jnp.full((b, h, tl), NEG_BIG, jnp.float32)
    l0 = jnp.zeros((b, h, tl), jnp.float32)
    acc0 = jnp.zeros((b, h, tl, d), jnp.float32)
    (m_f, l_f, acc_f, _, _), _ = jax.lax.scan(
        jax.checkpoint(step), (m0, l0, acc0, k_l, v_l), jnp.arange(S))
    l_safe = jnp.where(l_f > 0, l_f, 1.0)
    o = (acc_f / l_safe[..., None]).transpose(0, 2, 1, 3)  # (b, tl, h, d)
    return o.astype(q_l.dtype)


def allgather_attention_local(q_l, k_l, v_l, causal: bool = True,
                              softmax_scale: Optional[float] = None,
                              axis_name: str = AXIS_SEQ) -> jnp.ndarray:
    """Sequence-parallel attention via GROUPED all-gather of K/V — for manual
    regions where the ppermute ring cannot run.

    Inside the 1F1B pipeline, stage activity is staggered: at any tick only some
    pipe rows execute the attention. A ``ppermute`` (collective-permute) encodes
    every device's source→target pair in ONE instruction, so executing it under a
    pipe-non-uniform ``lax.cond`` is undefined (observed: XLA CPU thunk crash).
    GROUPED collectives (all-gather / psum with per-pipe-row replica groups)
    execute all-or-nothing per row and are safe there. The trade: K/V materialise
    fully (O(t)) inside attention — activations stay sequence-sharded, so stage
    memory and cross-stage traffic keep the /S win; K/V HBM is transient.
    """
    b, tl, h, d = q_l.shape
    scale = softmax_scale if softmax_scale is not None else 1.0 / float(np.sqrt(d))
    k = jax.lax.all_gather(k_l, axis_name, axis=1, tiled=True)   # (b, t, h, d)
    v = jax.lax.all_gather(v_l, axis_name, axis=1, tiled=True)
    s_idx = jax.lax.axis_index(axis_name)
    acc, m, l = _chunk_attn(q_l, k, v, s_idx * tl, 0, causal, scale)
    l_safe = jnp.where(l > 0, l, 1.0)
    o = (acc / l_safe[..., None]).transpose(0, 2, 1, 3)          # (b, tl, h, d)
    return o.astype(q_l.dtype)


def ring_attention(q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray,
                   causal: bool = True, mask: Optional[jnp.ndarray] = None,
                   softmax_scale: Optional[float] = None,
                   dropout_rate: float = 0.0, dropout_rng=None,
                   axis_name: str = AXIS_SEQ, mesh_spec=None) -> jnp.ndarray:
    """Drop-in attention: q/k/v ``(b, t, h, d)`` with ``t`` sharded over ``seq``.

    Falls back to flash attention when the mesh has no seq axis (or features the ring path
    does not cover are requested)."""
    from .flash import flash_attention
    mesh = mesh_spec or get_global_mesh()
    if (mesh is None or mesh.size(axis_name) <= 1 or mask is not None
            or dropout_rate > 0.0):
        return flash_attention(q, k, v, causal=causal, mask=mask,
                               softmax_scale=softmax_scale,
                               dropout_rate=dropout_rate, dropout_rng=dropout_rng)

    b, t, h, d = q.shape
    S = mesh.size(axis_name)
    if not (t % S == 0):
        raise AssertionError(f"seq len {t} must divide the seq axis {S}")
    scale = softmax_scale if softmax_scale is not None else 1.0 / float(np.sqrt(d))

    mapped = shard_map(
        lambda q_l, k_l, v_l: ring_attention_local(
            q_l, k_l, v_l, causal=causal, softmax_scale=scale,
            axis_name=axis_name, seq_size=S),
        mesh=mesh.mesh,
        axis_names={axis_name},
        in_specs=(P(None, axis_name, None, None),) * 3,
        out_specs=P(None, axis_name, None, None),
        check_vma=False,
    )
    return mapped(q, k, v)
