from .decode import decode_attention, decode_attention_xla
from .flash import flash_attention
from .ring import ring_attention
