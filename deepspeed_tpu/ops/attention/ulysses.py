"""Ulysses attention — all-to-all sequence parallelism over the ``seq`` mesh axis.

The second sequence-parallel strategy (DeepSpeed-Ulysses, Jacobs et al. 2023; absent
from the reference snapshot like ring — SURVEY §2.3): activations arrive sharded on the
SEQUENCE dim; an in-graph ``all_to_all`` re-shards them onto the HEAD dim, every device
then runs ordinary full-sequence attention for its ``h/P`` heads, and a second
``all_to_all`` restores sequence sharding. Communication is 2 all-to-alls of the qkv/o
activations (O(bt·h·d/P) per device, constant in P on a torus) versus ring's P
``ppermute`` steps of K/V — Ulysses wins when heads divide nicely and the per-device
full-sequence attention fits; ring wins for extreme lengths. Both ride ICI.

Requires ``n_heads % seq_axis == 0`` (the Ulysses constraint); falls back to ring
otherwise.
"""

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from ...parallel.mesh import AXIS_SEQ, get_global_mesh
from ...utils.jax_compat import shard_map


def ulysses_attention(q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray,
                      causal: bool = True, mask: Optional[jnp.ndarray] = None,
                      softmax_scale: Optional[float] = None,
                      dropout_rate: float = 0.0, dropout_rng=None,
                      axis_name: str = AXIS_SEQ, mesh_spec=None) -> jnp.ndarray:
    """Drop-in attention: q/k/v ``(b, t, h, d)`` with ``t`` sharded over ``seq``."""
    from .ring import ring_attention
    mesh = mesh_spec or get_global_mesh()
    if mesh is None or mesh.size(axis_name) <= 1 or mask is not None \
            or dropout_rate > 0.0:
        from .flash import flash_attention
        return flash_attention(q, k, v, causal=causal, mask=mask,
                               softmax_scale=softmax_scale,
                               dropout_rate=dropout_rate, dropout_rng=dropout_rng)
    b, t, h, d = q.shape
    S = mesh.size(axis_name)
    if h % S != 0:
        # Ulysses needs head divisibility; ring has no such constraint
        return ring_attention(q, k, v, causal=causal,
                              softmax_scale=softmax_scale, axis_name=axis_name,
                              mesh_spec=mesh)
    if not (t % S == 0):
        raise AssertionError(f"seq len {t} must divide the seq axis {S}")
    scale = softmax_scale if softmax_scale is not None else 1.0 / float(np.sqrt(d))

    def ulysses_fn(q_l, k_l, v_l):
        # local (b, t/S, h, d) → all_to_all → (b, t, h/S, d): scatter the head dim,
        # gather the sequence dim
        def seq_to_heads(x):
            return jax.lax.all_to_all(x, axis_name, split_axis=2, concat_axis=1,
                                      tiled=True)

        def heads_to_seq(x):
            return jax.lax.all_to_all(x, axis_name, split_axis=1, concat_axis=2,
                                      tiled=True)

        qh, kh, vh = seq_to_heads(q_l), seq_to_heads(k_l), seq_to_heads(v_l)
        # full-sequence attention over the local head group (fused by XLA; the MXU
        # sees the complete t×t problem for h/S heads)
        s = jnp.einsum("bthd,bshd->bhts", qh.astype(jnp.float32),
                       kh.astype(jnp.float32)) * scale
        if causal:
            tri = jnp.tril(jnp.ones((t, t), bool))
            s = jnp.where(tri[None, None], s, -1e30)
        p = jax.nn.softmax(s, axis=-1).astype(vh.dtype)
        o = jnp.einsum("bhts,bshd->bthd", p, vh)
        return heads_to_seq(o).astype(q_l.dtype)

    mapped = shard_map(
        ulysses_fn,
        mesh=mesh.mesh,
        axis_names={axis_name},
        in_specs=(P(None, axis_name, None, None),) * 3,
        out_specs=P(None, axis_name, None, None),
        check_vma=False,
    )
    return mapped(q, k, v)
