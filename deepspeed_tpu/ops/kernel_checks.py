"""Compiled-kernel parity checks — ONE source of shapes and tolerances.

Shared by the real-TPU test lane (``tests/unit/ops/test_kernels_tpu.py``) and the
bench's pre-run gate (``bench.py kernel_gate``), so the two cannot drift: a Mosaic
regression that fails the test suite fails the bench identically. Each check
compiles the Pallas kernel (no interpret mode) and compares against its XLA
reference; thresholds are per-check, matched to the check's dtype.
"""

from typing import Dict, Iterable, Optional, Tuple

import numpy as np


def _err(a, b) -> float:
    import jax.numpy as jnp
    return float(jnp.max(jnp.abs(a.astype(jnp.float32) - b.astype(jnp.float32))))


def check_flash_fwd() -> float:
    import jax
    import jax.numpy as jnp
    from .attention.flash import flash_attention
    from .transformer.attention import xla_attention
    rng = np.random.RandomState(0)
    q, k, v = (jnp.asarray(rng.standard_normal((2, 1024, 4, 64)), jnp.float32)
               for _ in range(3))
    o1 = jax.jit(lambda *a: flash_attention(*a, causal=True))(q, k, v)
    return _err(o1, xla_attention(q, k, v, causal=True))


def check_flash_bwd() -> float:
    import jax
    import jax.numpy as jnp
    from .attention.flash import flash_attention
    from .transformer.attention import xla_attention
    rng = np.random.RandomState(1)
    q, k, v = (jnp.asarray(rng.standard_normal((2, 512, 4, 64)), jnp.bfloat16)
               for _ in range(3))
    g1 = jax.jit(jax.grad(lambda *a: flash_attention(
        *a, causal=True).astype(jnp.float32).sum(), argnums=(0, 1, 2)))(q, k, v)
    g2 = jax.jit(jax.grad(lambda *a: xla_attention(
        *a, causal=True).astype(jnp.float32).sum(), argnums=(0, 1, 2)))(q, k, v)
    return max(_err(a, b) for a, b in zip(g1, g2))


def check_flash_alibi() -> float:
    import jax
    import jax.numpy as jnp
    from ..models.causal_lm import _alibi_attention_xla, alibi_slopes
    from .attention.flash import flash_attention
    rng = np.random.RandomState(2)
    q, k, v = (jnp.asarray(rng.standard_normal((2, 512, 4, 64)), jnp.bfloat16)
               for _ in range(3))
    slopes = jnp.asarray(alibi_slopes(4))
    o1 = jax.jit(lambda *a: flash_attention(*a, causal=True,
                                            alibi_slopes=slopes))(q, k, v)
    return _err(o1, _alibi_attention_xla(q, k, v, slopes))


def check_decode() -> float:
    import jax
    import jax.numpy as jnp
    from .attention.decode import decode_attention, decode_attention_xla
    rng = np.random.RandomState(0)
    b, h, hk, d, T = 4, 16, 4, 128, 2048
    q = jnp.asarray(rng.standard_normal((b, h, d)), jnp.bfloat16)
    kc = jnp.asarray(rng.standard_normal((b, hk, T, d)), jnp.bfloat16)
    vc = jnp.asarray(rng.standard_normal((b, hk, T, d)), jnp.bfloat16)
    lens = jnp.asarray(rng.randint(100, T, size=(b,)), jnp.int32)
    o1 = jax.jit(decode_attention)(q, kc, vc, lens)
    return _err(o1, decode_attention_xla(q, kc, vc, lens))


def check_block_sparse() -> float:
    import jax
    import jax.numpy as jnp
    from .attention.block_sparse import (block_sparse_attention,
                                         block_sparse_attention_reference)
    from .sparse_attention import FixedSparsityConfig
    rng = np.random.RandomState(0)
    cfg = FixedSparsityConfig(num_heads=4, block=128, num_local_blocks=2)
    layout = np.asarray(cfg.make_layout(1024))
    q, k, v = (jnp.asarray(rng.standard_normal((2, 1024, 4, 128)), jnp.bfloat16)
               for _ in range(3))
    o = jax.jit(lambda *a: block_sparse_attention(
        *a, layout=layout, block=128, causal=True))(q, k, v)
    return _err(o, block_sparse_attention_reference(q, k, v, layout, 128,
                                                    causal=True))


def check_moe_decode_ffn() -> float:
    import jax
    import jax.numpy as jnp
    from .moe.decode_ffn import moe_decode_ffn, moe_decode_ffn_xla
    rng = np.random.RandomState(3)
    e, d, f, n = 8, 768, 3072, 4
    x = jnp.asarray(rng.standard_normal((n, d)), jnp.bfloat16)
    w1 = jnp.asarray(rng.standard_normal((e, d, f)) * d ** -0.5, jnp.bfloat16)
    b1 = jnp.asarray(rng.standard_normal((e, f)) * 0.02, jnp.bfloat16)
    w2 = jnp.asarray(rng.standard_normal((e, f, d)) * f ** -0.5, jnp.bfloat16)
    b2 = jnp.asarray(rng.standard_normal((e, d)) * 0.02, jnp.bfloat16)
    idx = jnp.asarray(rng.randint(0, e, size=(n,)), jnp.int32)
    act = jax.nn.gelu
    o1 = jax.jit(lambda *a: moe_decode_ffn(*a, act=act))(x, idx, w1, b1, w2, b2)
    return _err(o1, moe_decode_ffn_xla(x, idx, w1, b1, w2, b2, act))


# name → (check fn, max-abs-err tolerance for the check's dtype/shape)
KERNEL_CHECKS: Dict[str, Tuple] = {
    "flash_fwd": (check_flash_fwd, 0.02),       # fp32
    "flash_bwd": (check_flash_bwd, 0.05),       # bf16 grads
    "flash_alibi": (check_flash_alibi, 0.05),   # bf16
    "decode": (check_decode, 0.03),             # bf16
    "block_sparse": (check_block_sparse, 0.03),  # bf16
    "moe_decode_ffn": (check_moe_decode_ffn, 0.03),  # bf16
}


def run_kernel_checks(names: Optional[Iterable[str]] = None) -> Dict[str, float]:
    """Run the named checks (all by default); returns {name: max_abs_err}.
    Raises RuntimeError listing every check whose error exceeds its tolerance."""
    errs, bad = {}, {}
    for name in (names or KERNEL_CHECKS):
        fn, tol = KERNEL_CHECKS[name]
        errs[name] = fn()
        if not (errs[name] < tol):      # NaN-safe
            bad[name] = (errs[name], tol)
    if bad:
        raise RuntimeError(f"kernel checks FAILED (err, tol): {bad}")
    return errs
